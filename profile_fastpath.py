#!/usr/bin/env python
"""Component profile of the prefix-commit engine (the tool behind
PROFILE.md).

Timing protocol: the tunneled single-chip runtime adds large, VARIABLE
per-call dispatch overhead (tens of ms), so naive per-call timing is
useless.  Every measurement here runs the component M_HI and M_LO times
inside one jitted ``lax.scan`` (data dependence threaded through the
carry) and reports ``(T(M_HI) - T(M_LO)) / (M_HI - M_LO)`` -- fixed
per-call costs cancel exactly.  All buffers are passed as real jit
arguments: device arrays captured as jit constants are re-uploaded
through the tunnel per call and would dominate.
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from __graft_entry__ import _preloaded_state
from dmclock_tpu.engine import fastpath
from profile_util import scalar_latency, state_digest

N = 100_000
K = 49152
M_LO, M_HI = 8, 32


def _time_call(f, *args, reps=3):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = f(*args)
        jax.device_get(state_digest(out.state) if hasattr(out, "state")
                       else out[1])
        best = min(best, time.perf_counter() - t0)
    return best


def measure_epoch(name, state, m_lo=M_LO, m_hi=M_HI, k=K, **ep_kw):
    """``ep_kw`` forwards to ``scan_prefix_epoch`` -- the
    ``select_impl`` / ``tag_width`` / ``window_m`` A/B rows below
    differ only here, so every variant shares one timing protocol."""
    f_lo = jax.jit(functools.partial(fastpath.scan_prefix_epoch,
                                     m=m_lo, k=k, anticipation_ns=0,
                                     **ep_kw))
    f_hi = jax.jit(functools.partial(fastpath.scan_prefix_epoch,
                                     m=m_hi, k=k, anticipation_ns=0,
                                     **ep_kw))
    now = jnp.int64(0)
    jax.device_get(state_digest(f_lo(state, now).state))
    jax.device_get(state_digest(f_hi(state, now).state))
    t_lo = _time_call(f_lo, state, now)
    t_hi = _time_call(f_hi, state, now)
    t = (t_hi - t_lo) / (m_hi - m_lo)
    print(f"{name:52s} {t*1e6:9.1f} us/batch  "
          f"({t/k*1e9:5.1f} ns/dec, {k/t/1e6:5.1f} M dec/s)")
    return t


def measure_scan(name, make_body, state, init):
    """make_body(state) -> (carry, _) -> carry scan body; differenced."""
    def mk(m):
        def fn(state, tick):
            body = make_body(state)
            c, vs = lax.scan(body, (tick, init), None, length=m)
            return state, c[0] + jnp.asarray(vs[0]).astype(jnp.int64).sum()
        return fn
    f_lo = jax.jit(mk(64))
    f_hi = jax.jit(mk(256))
    jax.device_get(f_lo(state, jnp.int64(0))[1])
    jax.device_get(f_hi(state, jnp.int64(0))[1])
    t_lo = _time_call(f_lo, state, jnp.int64(0))
    t_hi = _time_call(f_hi, state, jnp.int64(0))
    t = (t_hi - t_lo) / (256 - 64)
    print(f"{name:52s} {t*1e6:9.1f} us/iter")
    return t


def _zipf_state(n, ring, depth):
    """cfg4-like Zipf-64 skew over the preload: the calendar A/B's
    honest shape (uniform weights give minstop nothing to lose -- the
    min-stop IS everyone's stop; the ladder's gain is the skew)."""
    from dmclock_tpu.core.timebase import rate_to_inv_ns

    st = _preloaded_state(n, depth, ring=ring)
    w = 1.0 / np.arange(1, n + 1) ** 1.1
    w = np.clip(w / w[n // 2], 0.5, 64.0)
    rng = np.random.default_rng(7)
    rng.shuffle(w)
    winv = np.asarray([rate_to_inv_ns(x) for x in w], np.int64)
    c = np.arange(n)
    phase = ((c * 2654435761) & 0xFFFFF) / float(1 << 20)
    jitter = (phase * 2.0 * winv).astype(np.int64)
    return st._replace(weight_inv=jnp.asarray(winv),
                       head_prop=jnp.asarray(winv + jitter))


def measure_calendar(name, state, *, impl, levels, m_lo=4, m_hi=12,
                     steps=8, **cal_kw):
    """Calendar-epoch A/B row (minstop vs bucketed ladder vs wheel):
    marginal batch cost AND marginal decisions -- the impls commit
    different amounts per batch, so dec/s is the honest comparison,
    not us/batch alone.  ``cal_kw`` forwards to
    ``scan_calendar_epoch`` (the wheel_kernel xla/pallas A/B differs
    only there)."""
    mk = lambda m: jax.jit(functools.partial(       # noqa: E731
        fastpath.scan_calendar_epoch, m=m, steps=steps,
        anticipation_ns=0, calendar_impl=impl, ladder_levels=levels,
        **cal_kw))
    f_lo, f_hi = mk(m_lo), mk(m_hi)
    now = jnp.int64(0)
    jax.device_get(state_digest(f_lo(state, now).state))
    ep_hi = f_hi(state, now)
    jax.device_get(state_digest(ep_hi.state))
    t_lo = _time_call(f_lo, state, now)
    t_hi = _time_call(f_hi, state, now)
    t = (t_hi - t_lo) / (m_hi - m_lo)
    counts = np.asarray(jax.device_get(ep_hi.count))
    d = counts[m_lo:].sum() / (m_hi - m_lo)   # marginal batches only
    print(f"{name:52s} {t*1e6:9.1f} us/batch  "
          f"({d:7.0f} dec/batch, {d/max(t, 1e-12)/1e6:5.1f} M dec/s)")
    return t, d


def _high_rate_state(n, ring):
    """_preloaded_state with client rates x1000 (weights 1000..4000/s):
    per-serve tag advance ~1e6 ns, so a whole epoch's virtual-time
    drift fits the int32 rebase window and tag_width=32 never trips --
    the shape the rebase measurement is honest on (the default 1..4/s
    preload drifts ~1e9 ns/serve and falls back within one batch,
    which would measure the fallback, not the carry)."""
    st = _preloaded_state(n, 128, ring=ring)
    return st._replace(
        resv_inv=st.resv_inv // 1000,
        weight_inv=st.weight_inv // 1000,
        head_resv=st.head_resv // 1000,
        head_prop=st.head_prop // 1000)


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=N,
                    help="clients (smaller for cpu-box checks)")
    ap.add_argument("--k", type=int, default=K)
    args = ap.parse_args()
    n, k = args.n, args.k

    print(f"scalar round-trip latency: {scalar_latency()*1e3:.1f} ms\n")
    state = _preloaded_state(n, 128, ring=128)

    # -- whole epoch at bench shape, under both selection backends and
    # (on the window-fitting high-rate shape) both tag widths
    measure_epoch(f"scan_prefix_epoch (k={k}, ring=128)", state, k=k)
    measure_epoch(f"scan_prefix_epoch radix (k={k})", state, k=k,
                  select_impl="radix")
    hi = _high_rate_state(n, 128)
    measure_epoch(f"scan_prefix_epoch tag64 (high-rate, k={k})", hi,
                  k=k)
    measure_epoch(f"scan_prefix_epoch tag32 (high-rate, k={k})", hi,
                  k=k, tag_width=32)
    measure_epoch(f"scan_prefix_epoch m=64 window_m=8 (k={k})", state,
                  m_lo=16, m_hi=64, k=k, window_m=8)

    # -- calendar engine: minstop vs the bucketed stop-key ladder on a
    # Zipf-64-skewed backlog (the cfg4 cutter shape; docs/ENGINE.md).
    # The ladder fuses L measure+commit boundaries per launch, so its
    # batch costs ~L x more and must commit ~L x more to win -- the
    # dec/s column is the verdict.
    zs = _zipf_state(n, 128, 96)
    measure_calendar("scan_calendar_epoch minstop (steps=8)", zs,
                     impl="minstop", levels=1)
    measure_calendar("scan_calendar_epoch bucketed L=4 (steps=8)", zs,
                     impl="bucketed", levels=4)
    measure_calendar("scan_calendar_epoch bucketed L=8 (steps=8)", zs,
                     impl="bucketed", levels=8)
    # -- wheel: same ladder driven from the maintained bucket index
    # (O(1)-bucket re-slot per commit instead of an O(N) rebuild per
    # boundary), then the bucket kernel itself A/B'd xla vs pallas.
    # The pallas row prints the EFFECTIVE kernel: off-TPU (or on an
    # unsupported shape) the wheel falls back to the XLA kernel and
    # the two rows honestly measure the same program.
    measure_calendar("scan_calendar_epoch wheel L=8 (steps=8)", zs,
                     impl="wheel", levels=8)
    _, fb = fastpath._wheel_resolve("pallas", n)
    eff = "xla-fallback" if fb else "pallas"
    measure_calendar(
        f"scan_calendar_epoch wheel L=8 kernel={eff}", zs,
        impl="wheel", levels=8, wheel_kernel="pallas")

    # -- selection core of _prefix_select: the 5-array 2-key i32 sort
    # plus the cumulative-min prefix validation
    def sel_sort(state):
        iota = jnp.arange(n, dtype=jnp.int32)
        o32 = state.order.astype(jnp.int32)
        c32 = state.head_cost.astype(jnp.int32)

        def body(c, _):
            t, _x = c
            key = state.head_prop + state.prop_delta + t
            kmin = jnp.min(key)
            k32 = jnp.clip(key - kmin, 0, (1 << 31) - 2).astype(jnp.int32)
            r32 = k32 + jnp.int32(1)         # stand-in reentry payload
            ks, os_, idxs, cs, rs = lax.sort(
                (k32, o32, iota, c32, r32), num_keys=2)
            pk = (ks[:k].astype(jnp.int64) << 32) | \
                (os_[:k].astype(jnp.int64) & 0xFFFFFFFF)
            rpk = (rs[:k].astype(jnp.int64) << 32)
            cm = lax.associative_scan(jnp.minimum, rpk)
            count = jnp.argmax(~(cm > pk)).astype(jnp.int32)
            return (t + idxs[0].astype(jnp.int64) + 1, _x), count
        return body
    measure_scan("selection: 5-array 2-key i32 sort + cummin",
                 sel_sort, state, jnp.int32(0))

    # -- radix replacement for the same job: histogram k-th boundary +
    # dense membership + compaction + [k]-sized sort (``_select_radix``
    # verbatim, so the row is the shipped code's cost, not a model)
    def sel_radix(state):
        iota = jnp.arange(n, dtype=jnp.int32)
        c32 = state.head_cost.astype(jnp.int32)
        omask = (jnp.int64(1) << 28) - 1

        def body(c, _):
            t, _x = c
            key = state.head_prop + state.prop_delta + t
            kmin = jnp.min(key)
            krel = jnp.clip(key - kmin, 0, (1 << 31) - 2)
            pk = (krel << 28) | (state.order & omask)
            epk = pk + 1                     # stand-in reentry payload
            pks, idxs, rpk, costs, lens = fastpath._select_radix(
                pk, iota, epk, c32, None, k, min(k, n))
            cm = lax.associative_scan(jnp.minimum, rpk)
            count = jnp.argmax(~(cm > pks)).astype(jnp.int32)
            return (t + idxs[0].astype(jnp.int64) + 1, _x), count
        return body
    measure_scan("selection: radix histogram k-select + [k] sort",
                 sel_radix, state, jnp.int32(0))

    # -- serve: dense elementwise retag (no ring access)
    def serve(state):
        n = state.capacity
        cls = jnp.full((n,), fastpath.CLS_WEIGHT, jnp.int32)

        def body(c, _):
            t, _x = c
            st = state._replace(prev_prop=state.prev_prop + t)
            sv = fastpath._chain_serve(
                st, jnp.int64(1 << 60), [st.head_arrival],
                [st.head_cost], cls, False, 0)
            return (t + sv.head_prop[0] + 1, _x), sv.head_resv[0]
        return body
    measure_scan("serve: dense elementwise retag", serve, state,
                 jnp.int32(0))

    # -- ring window: prefetch (per epoch) and select (per batch)
    def prefetch(state):
        def body(c, _):
            t, _x = c
            st = state._replace(q_head=(state.q_head + jnp.int32(t)) % 128)
            win = fastpath.ring_window(st, 32)
            return (t + win.arr[0, 0] + 1, _x), win.cost[0, 0]
        return body
    measure_scan("ring_window prefetch (barrel shift, per EPOCH)",
                 prefetch, state, jnp.int32(0))

    win = jax.jit(lambda s: fastpath.ring_window(s, 32))(state)

    def select(state):
        def body(c, _):
            t, _x = c
            st = state._replace(q_head=(state.q_head + jnp.int32(t)) % 128)
            narr, ncost = fastpath._window_heads(st, win)
            return (t + narr[0] + 1, _x), ncost[0]
        return body
    measure_scan("window head select (one-hot, per batch)", select,
                 state, jnp.int32(0))


if __name__ == "__main__":
    main()
