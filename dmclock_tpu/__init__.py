"""dmclock-tpu: a TPU-native dmClock QoS scheduling framework.

Brand-new implementation of the capabilities of the reference C++
library (dmClock reservation/weight/limit tags, two-phase selection,
piggybacked rho/delta distributed tracking, pull/push queue surfaces,
QoS simulator) re-designed for TPUs: scheduler state as HBM-resident
arrays, tag recurrence as vmapped kernels, heap selection as fused
stable argmin, multi-server corrections as psum collectives.

Layers:
  core      -- canonical int64-ns tag algebra + pure-Python oracle
  engine    -- batched TPU scheduler: SoA client state, JAX device
               kernels (tag update, fused select, wave ingest),
               speculative fastpath, Tpu Pull/Push queues
  parallel  -- mesh sharding, multi-server cluster, psum trackers
               (Orig + Borrowing)
  sim       -- discrete-event QoS harness (INI-config compatible) +
               the device-resident batch simulator (device_sim)
  models    -- registered scheduler "models" (dmclock oracle, dmclock
               native C++, dmclock TPU engine, ssched FIFO)
  native    -- ctypes bindings to the C++ host runtime
  obs       -- metrics registry + scrape endpoint, on-device counters,
               decision traces
  robust    -- fault injection, degraded-mode cluster stepping,
               guarded commits (docs/ROBUSTNESS.md)
  utils     -- periodic tasks, profiling timers, crash-safe atomic
               checkpointing with digest sidecars + rotation
"""

__version__ = "0.2.0"

from . import core
from .core import (AtLimit, ClientInfo, Phase, PullPriorityQueue,
                   PushPriorityQueue, ReqParams, RequestTag, ServiceTracker)

__all__ = [
    "core", "AtLimit", "ClientInfo", "Phase", "PullPriorityQueue",
    "PushPriorityQueue", "ReqParams", "RequestTag", "ServiceTracker",
    "__version__",
]
