"""ctypes bindings to the native C++ dmClock runtime.

Loads ``libdmclock_c.so`` (built from ``native/src/capi.cc``) and wraps
it in the same Python API the oracle ``core.scheduler.PullPriorityQueue``
and ``core.tracker.ServiceTracker`` expose, so the sim harness and the
differential tests can drive all three backends interchangeably:

    Python oracle  <->  C++ native runtime  <->  JAX/TPU engine

All three implement the identical int64-ns tag algebra
(``core/timebase.py`` == ``native/include/dmclock/time.h``), so decision
streams are compared bit-for-bit (``tests/test_native_parity.py``).

The library is found via ``$DMCLOCK_NATIVE_LIB``, an existing
``native/build/libdmclock_c.so``, or built on demand with cmake (see
``ensure_built``).  ``load_library`` returns None when no compiler is
available; callers (tests, sim models) degrade gracefully.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
from collections import deque
from pathlib import Path
from typing import Any, Callable, Deque, Dict, Optional

from ..core.qos import ClientInfo
from ..core.recs import Phase, ReqParams
from ..core.scheduler import AtLimit, NextReqType, PullReq

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent
_NATIVE_DIR = _REPO_ROOT / "native"
_BUILD_DIR = _NATIVE_DIR / "build"

_lib: Optional[ctypes.CDLL] = None
_lib_err: Optional[str] = None


def _so_path() -> Path:
    return _BUILD_DIR / "libdmclock_c.so"


_CAPI_VERSION = 2


def _rebuild() -> Optional[Path]:
    """Force a cmake rebuild of the C library (stale-ABI path)."""
    if not shutil.which("cmake"):
        return None
    try:
        subprocess.run(["cmake", "-S", str(_NATIVE_DIR), "-B",
                        str(_BUILD_DIR)], check=True,
                       capture_output=True, timeout=300)
        subprocess.run(["cmake", "--build", str(_BUILD_DIR), "-j",
                        "--target", "dmclock_c"], check=True,
                       capture_output=True, timeout=600)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
        return None
    so = _so_path()
    return so if so.exists() else None


def ensure_built() -> Optional[Path]:
    """Build libdmclock_c.so with cmake if missing; None on failure."""
    env = os.environ.get("DMCLOCK_NATIVE_LIB")
    if env:
        if not Path(env).exists():
            raise FileNotFoundError(
                f"DMCLOCK_NATIVE_LIB={env!r} does not exist; refusing "
                "to silently fall back to a different library")
        return Path(env)
    so = _so_path()
    if so.exists():
        return so
    if not shutil.which("cmake"):
        return None
    try:
        subprocess.run(["cmake", "-S", str(_NATIVE_DIR), "-B",
                        str(_BUILD_DIR)], check=True,
                       capture_output=True, timeout=300)
        subprocess.run(["cmake", "--build", str(_BUILD_DIR), "-j",
                        "--target", "dmclock_c"], check=True,
                       capture_output=True, timeout=600)
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired):
        return None
    return so if so.exists() else None


def load_library() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the C ABI library; None if unavailable."""
    global _lib, _lib_err
    if _lib is not None:
        return _lib
    if _lib_err is not None:
        return None
    so = ensure_built()
    if so is None:
        _lib_err = "no compiler/cmake or build failed"
        return None
    lib = ctypes.CDLL(str(so))

    # ABI version gate: a stale prebuilt .so would silently ignore
    # newer trailing arguments (C calling convention), turning e.g.
    # use_prop_heap into a no-op.  Rebuild once on mismatch; refuse to
    # proceed if that does not converge.
    if not hasattr(lib, "dmc_capi_version") or \
            lib.dmc_capi_version() != _CAPI_VERSION:
        del lib
        so = _rebuild()
        if so is None:
            _lib_err = "stale native ABI and rebuild failed"
            raise RuntimeError(
                "libdmclock_c.so has a stale ABI and could not be "
                "rebuilt; remove native/build and rebuild")
        lib = ctypes.CDLL(str(so))
        if not hasattr(lib, "dmc_capi_version") or \
                lib.dmc_capi_version() != _CAPI_VERSION:
            _lib_err = "stale native ABI after rebuild"
            raise RuntimeError(
                "libdmclock_c.so ABI version mismatch persists after "
                "rebuild (DMCLOCK_NATIVE_LIB pointing at an old "
                "library?)")

    u64, i64, u32 = ctypes.c_uint64, ctypes.c_int64, ctypes.c_uint32
    p = ctypes.POINTER
    lib.dmc_queue_create.restype = ctypes.c_void_p
    lib.dmc_queue_create.argtypes = [ctypes.c_int, ctypes.c_int, i64,
                                     i64, ctypes.c_uint, ctypes.c_int,
                                     ctypes.c_int, ctypes.c_double,
                                     ctypes.c_double, ctypes.c_double,
                                     u64]
    lib.dmc_queue_destroy.argtypes = [ctypes.c_void_p]
    lib.dmc_queue_set_client_info.argtypes = [
        ctypes.c_void_p, u64, ctypes.c_double, ctypes.c_double,
        ctypes.c_double]
    lib.dmc_queue_update_client_info.argtypes = [ctypes.c_void_p, u64]
    lib.dmc_queue_add.restype = ctypes.c_int
    lib.dmc_queue_add.argtypes = [ctypes.c_void_p, u64, u64, u32, u32,
                                  i64, u32]
    lib.dmc_queue_pull.restype = ctypes.c_int
    lib.dmc_queue_pull.argtypes = [ctypes.c_void_p, i64, p(u64), p(u64),
                                   p(ctypes.c_int), p(u32), p(i64)]
    lib.dmc_queue_request_count.restype = u64
    lib.dmc_queue_request_count.argtypes = [ctypes.c_void_p]
    lib.dmc_queue_client_count.restype = u64
    lib.dmc_queue_client_count.argtypes = [ctypes.c_void_p]
    lib.dmc_queue_empty.restype = ctypes.c_int
    lib.dmc_queue_empty.argtypes = [ctypes.c_void_p]
    lib.dmc_queue_counters.argtypes = [ctypes.c_void_p, p(u64), p(u64),
                                       p(u64)]
    lib.dmc_queue_remove_by_client.restype = u64
    lib.dmc_queue_remove_by_client.argtypes = [
        ctypes.c_void_p, u64, ctypes.c_int, p(u64), u64]
    lib.dmc_queue_do_clean.argtypes = [ctypes.c_void_p]
    lib.dmc_queue_set_fake_clock.argtypes = [ctypes.c_void_p,
                                             ctypes.c_double]
    lib.dmc_queue_heap_branching.restype = ctypes.c_uint
    lib.dmc_queue_heap_branching.argtypes = [ctypes.c_void_p]

    lib.dmc_tracker_create.restype = ctypes.c_void_p
    lib.dmc_tracker_create.argtypes = [ctypes.c_int]
    lib.dmc_tracker_destroy.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.dmc_tracker_track_resp.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                           u64, ctypes.c_int, u32]
    lib.dmc_tracker_get_req_params.argtypes = [
        ctypes.c_void_p, ctypes.c_int, u64, p(u32), p(u32)]

    _lib = lib
    return _lib


class NativePullPriorityQueue:
    """The C++ Pull queue behind the oracle-queue Python API.

    Request payloads and client ids are arbitrary Python objects; the
    wrapper maps them to the uint64 handles the C ABI speaks and keeps
    per-client FIFOs of payloads mirroring the native queue order
    (cites: handle seam ``native/src/capi.cc``; API shape
    ``core/scheduler.py`` PullPriorityQueue).
    """

    def __init__(self, client_info_f: Callable[[Any], ClientInfo], *,
                 delayed_tag_calc: bool = True,
                 at_limit: AtLimit = AtLimit.WAIT,
                 reject_threshold_ns: int = 0,
                 anticipation_timeout_ns: int = 0,
                 heap_branching: int = 2,
                 dynamic_cli_info: bool = False,
                 use_prop_heap: bool = False,
                 idle_age_s: float = 0.0,
                 erase_age_s: float = 0.0,
                 check_time_s: float = 0.0,
                 erase_max: int = 0):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native dmclock library unavailable")
        self._lib = lib
        self.client_info_f = client_info_f
        # GC ages: 0 keeps the library default (reference constants)
        self._h = lib.dmc_queue_create(
            1 if delayed_tag_calc else 0, at_limit.value,
            int(reject_threshold_ns), int(anticipation_timeout_ns),
            int(heap_branching), 1 if dynamic_cli_info else 0,
            1 if use_prop_heap else 0, float(idle_age_s),
            float(erase_age_s), float(check_time_s), int(erase_max))
        self._dynamic = dynamic_cli_info
        self._cid: Dict[Any, int] = {}
        self._next_cid = 1
        self._payloads: Dict[int, Deque[Any]] = {}
        self._client_of: Dict[int, Any] = {}

    # -- client plumbing ------------------------------------------------
    def _client_handle(self, client_id: Any) -> int:
        cid = self._cid.get(client_id)
        if cid is None:
            cid = self._next_cid
            self._next_cid += 1
            self._cid[client_id] = cid
            self._client_of[cid] = client_id
            self._payloads[cid] = deque()
            info = self.client_info_f(client_id)
            self._lib.dmc_queue_set_client_info(
                self._h, cid, info.reservation, info.weight, info.limit)
        elif self._dynamic:
            info = self.client_info_f(client_id)
            self._lib.dmc_queue_set_client_info(
                self._h, cid, info.reservation, info.weight, info.limit)
        return cid

    # -- oracle-compatible API ------------------------------------------
    def add_request(self, request: Any, client_id: Any,
                    req_params: ReqParams = ReqParams(),
                    time_ns: Optional[int] = None, cost: int = 1) -> int:
        assert time_ns is not None, \
            "native parity surface requires explicit virtual times"
        cid = self._client_handle(client_id)
        q = self._payloads[cid]
        q.append(request)
        rc = self._lib.dmc_queue_add(self._h, cid, 0,
                                     req_params.delta, req_params.rho,
                                     int(time_ns), int(cost))
        if rc != 0:          # EAGAIN (AtLimit.REJECT): ownership returns
            q.pop()
        return rc

    def pull_request(self, now_ns: int) -> PullReq:
        client = ctypes.c_uint64()
        req_id = ctypes.c_uint64()
        phase = ctypes.c_int()
        cost = ctypes.c_uint32()
        when = ctypes.c_int64()
        t = self._lib.dmc_queue_pull(
            self._h, int(now_ns), ctypes.byref(client),
            ctypes.byref(req_id), ctypes.byref(phase), ctypes.byref(cost),
            ctypes.byref(when))
        if t == NextReqType.RETURNING.value:
            cid = client.value
            request = self._payloads[cid].popleft()
            return PullReq(NextReqType.RETURNING,
                           client=self._client_of[cid], request=request,
                           phase=Phase(phase.value), cost=cost.value)
        if t == NextReqType.FUTURE.value:
            return PullReq(NextReqType.FUTURE, when_ready=when.value)
        return PullReq(NextReqType.NONE)

    def update_client_info(self, client_id: Any) -> None:
        cid = self._cid.get(client_id)
        if cid is None:
            return
        info = self.client_info_f(client_id)
        self._lib.dmc_queue_set_client_info(
            self._h, cid, info.reservation, info.weight, info.limit)
        self._lib.dmc_queue_update_client_info(self._h, cid)

    def remove_by_client(self, client_id: Any, reverse: bool = False,
                         accum: Optional[Callable[[Any], None]] = None
                         ) -> None:
        cid = self._cid.get(client_id)
        if cid is None:
            return
        q = self._payloads[cid]
        cap = len(q)
        out = (ctypes.c_uint64 * max(cap, 1))()
        n = self._lib.dmc_queue_remove_by_client(
            self._h, cid, 1 if reverse else 0, out, cap)
        assert n == cap, "payload mirror out of sync with native queue"
        items = list(q)
        if reverse:
            items = list(reversed(items))
        if accum is not None:
            for r in items:
                accum(r)
        q.clear()

    def do_clean(self) -> None:
        self._lib.dmc_queue_do_clean(self._h)

    def set_fake_clock(self, now_s: float) -> None:
        """Deterministic GC clock (mirrors the oracle's injected
        monotonic_clock) -- march it forward, then do_clean()."""
        self._lib.dmc_queue_set_fake_clock(self._h, float(now_s))

    def request_count(self) -> int:
        return int(self._lib.dmc_queue_request_count(self._h))

    def client_count(self) -> int:
        return int(self._lib.dmc_queue_client_count(self._h))

    def empty(self) -> bool:
        return bool(self._lib.dmc_queue_empty(self._h))

    @property
    def _counters(self):
        r = ctypes.c_uint64()
        pr = ctypes.c_uint64()
        lb = ctypes.c_uint64()
        self._lib.dmc_queue_counters(self._h, ctypes.byref(r),
                                     ctypes.byref(pr), ctypes.byref(lb))
        return int(r.value), int(pr.value), int(lb.value)

    @property
    def reserv_sched_count(self) -> int:
        return self._counters[0]

    @property
    def prop_sched_count(self) -> int:
        return self._counters[1]

    @property
    def limit_break_sched_count(self) -> int:
        return self._counters[2]

    def heap_branching(self) -> int:
        return int(self._lib.dmc_queue_heap_branching(self._h))

    def shutdown(self) -> None:
        if getattr(self, "_h", None):
            self._lib.dmc_queue_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


class NativeServiceTracker:
    """The C++ ServiceTracker behind the oracle-tracker API
    (``core/tracker.py`` ServiceTracker; native ``tracker.h``)."""

    def __init__(self, borrowing: bool = False):
        lib = load_library()
        if lib is None:
            raise RuntimeError("native dmclock library unavailable")
        self._lib = lib
        self._b = 1 if borrowing else 0
        self._sid: Dict[Any, int] = {}
        self._next_sid = 1
        self._h = lib.dmc_tracker_create(self._b)

    def _server_handle(self, server: Any) -> int:
        sid = self._sid.get(server)
        if sid is None:
            sid = self._next_sid
            self._next_sid += 1
            self._sid[server] = sid
        return sid

    def get_req_params(self, server: Any) -> ReqParams:
        delta = ctypes.c_uint32()
        rho = ctypes.c_uint32()
        self._lib.dmc_tracker_get_req_params(
            self._h, self._b, self._server_handle(server),
            ctypes.byref(delta), ctypes.byref(rho))
        return ReqParams(delta.value, rho.value)

    def track_resp(self, server: Any, phase: Phase, cost: int = 1) -> None:
        self._lib.dmc_tracker_track_resp(
            self._h, self._b, self._server_handle(server),
            int(phase), int(cost))

    def shutdown(self) -> None:
        if getattr(self, "_h", None):
            self._lib.dmc_tracker_destroy(self._h, self._b)
            self._h = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass


__all__ = ["NativePullPriorityQueue", "NativeServiceTracker",
           "load_library", "ensure_built"]
