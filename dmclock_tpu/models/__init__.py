"""Scheduler "model" registry.

The model families of this framework are its schedulers.  Each entry
binds a queue factory + tracker factory pair for the sim harness
(playing the role of the reference's type-glue headers
``sim/src/test_dmclock.h:33-62`` and ``sim/src/test_ssched.h``):

  dmclock       -- oracle CPU dmClock queue + OrigTracker
  dmclock-delayed -- same with delayed tag calculation
  ssched        -- FIFO baseline + no-op tracker
  dmclock-tpu   -- JAX batch-engine-backed dmClock queue (engine/)
  dmclock-native -- C++ runtime via ctypes (native/), delayed tags
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

from ..core import AtLimit, PullPriorityQueue, ServiceTracker
from ..sim.ssched import NullServiceTracker, SimpleQueue

QueueFactory = Callable

_REGISTRY: Dict[str, Tuple[Callable, Callable]] = {}
_PUSH_REGISTRY: Dict[str, Callable] = {}


def register(name: str, queue_factory: Callable,
             tracker_factory: Callable) -> None:
    _REGISTRY[name] = (queue_factory, tracker_factory)


def register_push(name: str, queue_factory: Callable) -> None:
    """Push-mode factory: (server_id, info_f, anticipation_ns, soft, *,
    can_handle_f, handle_f, now_ns_f, sched_at_f) -> push queue."""
    _PUSH_REGISTRY[name] = queue_factory


def get(name: str) -> Tuple[Callable, Callable]:
    if name not in _REGISTRY:
        raise KeyError(f"unknown scheduler model {name!r}; "
                       f"have {sorted(_REGISTRY)}")
    return _REGISTRY[name]


def get_push(name: str) -> Callable:
    if name not in _PUSH_REGISTRY:
        raise KeyError(f"model {name!r} has no push-mode queue; "
                       f"have {sorted(_PUSH_REGISTRY)}")
    return _PUSH_REGISTRY[name]


def names():
    return sorted(_REGISTRY)


def push_names():
    return sorted(_PUSH_REGISTRY)


def _dmclock_queue(delayed: bool):
    def factory(server_id, client_info_f, anticipation_ns, soft_limit):
        # soft limit -> AtLimit.Allow, hard -> Wait (reference
        # test_dmclock_main.cc:190-198 create_queue_f)
        return PullPriorityQueue(
            client_info_f,
            delayed_tag_calc=delayed,
            at_limit=AtLimit.ALLOW if soft_limit else AtLimit.WAIT,
            anticipation_timeout_ns=anticipation_ns,
            run_gc_thread=False)
    return factory


def _dmclock_tracker():
    return ServiceTracker(run_gc_thread=False)


def _dmclock_tpu_queue(server_id, client_info_f, anticipation_ns,
                       soft_limit):
    # imported lazily so the CPU-only models don't pull in jax.
    # speculative_batch: the sim pulls one decision per service event,
    # so per-launch dispatch dominates; the buffer serves provably-
    # valid prefetched decisions launch-free (exactness is covered by
    # the oracle-vs-TPU trace parity suites, which run this factory)
    from ..engine import TpuPullPriorityQueue
    return TpuPullPriorityQueue(
        client_info_f,
        at_limit=AtLimit.ALLOW if soft_limit else AtLimit.WAIT,
        anticipation_timeout_ns=anticipation_ns,
        speculative_batch=4)


def _dmclock_native_queue(server_id, client_info_f, anticipation_ns,
                          soft_limit):
    # imported lazily; raises with a clear message if no toolchain
    from ..native import NativePullPriorityQueue
    return NativePullPriorityQueue(
        client_info_f,
        delayed_tag_calc=True,
        at_limit=AtLimit.ALLOW if soft_limit else AtLimit.WAIT,
        anticipation_timeout_ns=anticipation_ns,
        use_prop_heap=USE_PROP_HEAP)


# module-level switch for the native model's optional prop heap (the
# reference USE_PROP_HEAP build flag made runtime; behaviorally
# invisible -- pinned by tests/test_native_parity.py -- so sims only
# flip it for performance studies, via dmc_sim --use-prop-heap)
USE_PROP_HEAP = False


def _dmclock_push_queue(delayed: bool):
    def factory(server_id, client_info_f, anticipation_ns, soft_limit,
                *, can_handle_f, handle_f, now_ns_f, sched_at_f,
                capacity_f=None):
        # host queue consults can_handle before EVERY dispatch (the
        # reference's pacing); the free-slot count is unused
        from ..core import PushPriorityQueue
        return PushPriorityQueue(
            client_info_f, can_handle_f, handle_f,
            now_ns_f=now_ns_f, sched_at_f=sched_at_f,
            delayed_tag_calc=delayed,
            at_limit=AtLimit.ALLOW if soft_limit else AtLimit.WAIT,
            anticipation_timeout_ns=anticipation_ns,
            run_gc_thread=False)
    return factory


def _ssched_push_queue(server_id, client_info_f, anticipation_ns,
                       soft_limit, *, can_handle_f, handle_f, now_ns_f,
                       sched_at_f, capacity_f=None):
    return SimpleQueue(can_handle_f=can_handle_f, handle_f=handle_f)


def _dmclock_tpu_push_queue(server_id, client_info_f, anticipation_ns,
                            soft_limit, *, can_handle_f, handle_f,
                            now_ns_f, sched_at_f, capacity_f=None):
    # capacity_f (the sim server's free-slot count, reference
    # has_avail_thread sim_server.h:179) sizes each dispatch batch so
    # one device launch serves a whole burst of free threads; with
    # threads == 1 batches are size 1 and the decision stream is
    # identical to the host push queue's one-per-trigger pacing
    from ..engine import TpuPushPriorityQueue
    return TpuPushPriorityQueue(
        client_info_f, can_handle_f, handle_f,
        now_ns_f=now_ns_f, sched_at_f=sched_at_f,
        capacity_f=capacity_f,
        at_limit=AtLimit.ALLOW if soft_limit else AtLimit.WAIT,
        anticipation_timeout_ns=anticipation_ns)


register("dmclock", _dmclock_queue(delayed=False), _dmclock_tracker)
register("dmclock-delayed", _dmclock_queue(delayed=True), _dmclock_tracker)
register("dmclock-tpu", _dmclock_tpu_queue, _dmclock_tracker)
register("dmclock-native", _dmclock_native_queue, _dmclock_tracker)
register("ssched",
         lambda server_id, client_info_f, anticipation_ns, soft_limit:
         SimpleQueue(),
         NullServiceTracker)
register_push("dmclock", _dmclock_push_queue(delayed=False))
register_push("dmclock-delayed", _dmclock_push_queue(delayed=True))
register_push("dmclock-tpu", _dmclock_tpu_push_queue)
register_push("ssched", _ssched_push_queue)
