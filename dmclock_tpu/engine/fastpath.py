"""Prefix-commit speculative serving: thousands of decisions per O(N) pass.

The exact engine (`kernels.engine_step`) pays an O(N) masked-argmin per
decision -- semantically perfect, bandwidth-bound at scale.  This module
exploits a structural fact about dmClock's decision rule: at a fixed
``now`` the serial engine always serves the MINIMUM of one unified
lexicographic key space over clients,

    class 0  reservation-eligible  (head_resv <= now)     key = resv tag
    class 1  ready weight          (effective-ready,      key = prop tag
                                    prop < MAX)                 + delta
    class 2  limit-break           (AtLimit::Allow only)  key = prop tag
                                                                + delta

because the constraint phase takes absolute priority over the weight
phase (reference do_next_request :1124-1151), and the Allow fallback
only fires when both are empty (:1157-1165).  A full sort of the
per-client (class, key, creation-order) triples therefore yields the
ENTIRE candidate service order -- across regime boundaries -- in one
pass, and the engine commits the longest prefix of it that is provably
what the serial engine would have served, computed ON DEVICE.

Exactness argument (differentially tested against `engine_run`):
candidates are served in sorted (class, key, order) ascending order.
Serving candidate p re-enters its client at its EXIT key x_p -- the
unified key of its freshly-tagged next head (+inf if it empties or
leaves the candidate set).  The speculative order equals the serial
order up to position q iff ``min_{p<q} x_p > (class_q, key_q, order_q)``
at every position <= q -- the serial engine would have picked the
re-entered head first otherwise.  Since entry keys ascend and the
cumulative min only descends, the condition fails monotonically: the
first failing position ends the exact prefix.  Guaranteed progress:
whenever the serial engine would RETURN a request at ``now``, the
prefix is >= 1.

**Serve chains** (``chain_depth`` > 1) are what make interleaved-regime
workloads batch: a weight serve's reservation-debt reduction (reference
reduce_reservation_tags :1077-1111) often drags the served client's
next reservation tag back under ``now``.  At that serial moment the
client is the ONLY class-0 candidate (a weight serve happens only when
no reservation tag was eligible, and no other client's state changed),
so the serial engine provably serves THAT client's reservation
requests next, until its tag climbs past ``now`` again.  The chain
pre-computes this whole run -- one weight serve plus its induced
constraint serves, up to ``chain_depth`` total -- as ONE sort unit
whose exit key is back in weight space, so per-decision phase flips
(the reference's balanced mixed-QoS steady state) no longer cut the
committed prefix.  A chain that would exceed ``chain_depth`` exits at
its exact class-0 key, which stops the prefix right after the unit --
conservative, never inexact.

AtLimit::Allow (``allow_limit_break``) adds class 2: clients past their
limit, served lowest-proportion-first when classes 0/1 are empty, with
``limit_break`` flagged.  Restriction (checked by the caller): every
active client has weight > 0.  With a weight-0 (prop == MAX_TAG)
client that is ready, the reference's Allow fallback switches to
reservation order globally (the ready-heap top pins at MAX,
:1157-1165), which per-client classification cannot express.

Restrictions (checked by the caller): monotonic `now`, fixed `now`
within a batch.  The stored `ready` flags are superseded by the
computed `limit <= now` (equivalent under monotonic now, since a
promotion that serial processing would perform later in the batch is
performed here eagerly and verified sound).
"""

from __future__ import annotations

from typing import NamedTuple

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.pallas import tpu as pltpu

from ..core.timebase import MAX_TAG, MIN_TAG
from ..obs import device as obsdev
from ..obs import flight as obsflight
from ..obs import histograms as obshist
from ..obs import provenance as obsprov
from ..obs import slo as obsslo
from . import kernels
from . import kernels_pallas
from .kernels import (KEY_INF, NONE, RETURNING, Decision, _make_tag,
                      _fold_prev)
from .state import EngineState, TAG_I64_FIELDS


# Selection = ONE full sort on a packed int64 unified key: 2 class
# bits | 32-bit rebased tag | 28-bit rebased creation order.  A full
# sort yields the ENTIRE cross-regime service order, letting the batch
# size k grow to tens of thousands of decisions per O(N) pass.  Tags
# rebase per CLASS (reservation tags and proportion tags live in
# unrelated value spaces, so each class subtracts its own origin);
# rebase-window overflow (entry spread > ~3.2s above its class origin
# after the _EXIT_BIAS reservation) clamps to _KEY_CLAMP:
# harmless for candidates strictly beyond the selection boundary
# (never selectable), and the in-window check fails speculation
# otherwise, so exactness is never at risk (the serial engine takes
# the batch).  The creation-order spread guard is 2^28 live creations.
_KEY_CLAMP = (1 << 32) - 2   # in-window ceiling for real entry keys
_KEY_HI = (1 << 32) - 1      # above-window exit-key clamp (exact for
#                              every in-window boundary: see epk notes)
_EXIT_BIAS = jnp.int64(1) << 30   # window low end reserved for exits
#                                   below their class origin (~1.07s)
_ORDER_LIMIT = jnp.int64(1) << 28
_O_MASK = (jnp.int64(1) << 28) - 1

CLS_RESV = 0      # reservation-eligible: constraint phase
CLS_WEIGHT = 1    # effective-ready: weight phase
CLS_LB = 2        # AtLimit::Allow limit-break: weight phase + flag
CLS_NONE = 3      # non-candidate sentinel (sorts after every class)


def _ready_now(state: EngineState, now):
    """Effective readiness under monotonic now: stored flag OR limit
    passed (the promote loop marks exactly {limit <= now},
    reference :1135-1144)."""
    return state.head_ready | (state.head_limit <= now)


class RingWindow(NamedTuple):
    """Per-epoch prefetch of the tail rings.

    A speculative batch pops at most ``chain_depth`` requests per
    client, so a window of [w, N] ring positions ``q_head0 ..
    q_head0+w-1`` covers it.  Prefetching replaces the per-batch ring
    gather, which XLA lowers to a dense read of the ENTIRE [N, Q] ring
    pair (~200 MB/batch at bench shapes -- measured as 60x the
    window's traffic)."""

    arr: jnp.ndarray    # int64[w, N] arrivals at q_head0 + j
    cost: jnp.ndarray   # int64[w, N]
    q0: jnp.ndarray     # int32[N] q_head at prefetch time


# Pallas row-rotate: the barrel shift runs in VMEM (one HBM read +
# write per chunk) instead of log2(Q) full HBM passes -- measured 3x
# the XLA rolls at bench shapes.  Constraints of this TPU stack:
# gridded pallas_call does not legalize through the remote Mosaic
# compiler, so the kernel is gridless and the host slices VMEM-sized
# row chunks; int64 rings are bitcast to int32 lane pairs (a row
# rotation by 2*q0 on the pair plane is the int64 rotation by q0).
# The chunk scales inversely with ring width to stay inside the 16MB
# scoped-VMEM budget (2048 rows was tuned at Q=128 = 256 lanes).
_ROT_LANE_BUDGET = 2048 * 256


def _rot_chunk(q: int) -> int:
    return max(8, (_ROT_LANE_BUDGET // (2 * q)) // 8 * 8)


def _rotate_kernel(q_ref, x_ref, o_ref, *, q: int):
    x = x_ref[...]                       # [chunk, 2Q] int32
    shifts = q_ref[...]                  # [chunk, 2Q] int32, in [0, Q)
    one = jnp.int32(1)
    s = 0
    while (1 << s) < q:
        bit2 = ((shifts >> jnp.int32(s)) & one) == one
        d = jnp.int32((2 * q - 2 * (1 << s)) % (2 * q))
        x = jnp.where(bit2, pltpu.roll(x, shift=d, axis=1), x)
        s += 1
    o_ref[...] = x


def _rotate_rows_pallas(ring, q0, wsize: int, *, q0t=None,
                        interpret: bool = False):
    """out[w, i] = ring[i, (q0[i]+w) % Q] for w < wsize (int64 ring).
    ``q0t`` lets callers share the lane-tiled shift plane between the
    arrival and cost rotations."""
    from jax.experimental import pallas as pl

    n, q = ring.shape
    chunk = _rot_chunk(q)
    i32 = lax.bitcast_convert_type(ring, jnp.int32).reshape(n, 2 * q)
    pad = (-n) % chunk
    if pad:
        i32 = jnp.pad(i32, ((0, pad), (0, 0)))
    if q0t is None:
        q0t = _tile_shifts(q0, q, n + pad)
    call = pl.pallas_call(
        functools.partial(_rotate_kernel, q=q),
        out_shape=jax.ShapeDtypeStruct((chunk, 2 * q), jnp.int32),
        interpret=interpret)
    # slice each chunk to the window BEFORE concatenating: the full
    # rotated ring is never materialized in HBM
    outs = [call(q0t[c:c + chunk], i32[c:c + chunk])
            [:, :2 * wsize]
            for c in range(0, n + pad, chunk)]
    rot = jnp.concatenate(outs, axis=0)
    win = rot[:n].reshape(n, wsize, 2)
    return lax.bitcast_convert_type(win, jnp.int64).T


def _tile_shifts(q0, q: int, n_padded: int):
    q0 = jnp.pad(q0, (0, n_padded - q0.shape[0]))
    return jnp.broadcast_to(q0[:, None],
                            (n_padded, 2 * q)).astype(jnp.int32)


def _rotate_rows_xla(ring, q0, wsize: int):
    q = ring.shape[1]
    r = ring
    s = 0
    while (1 << s) < q:
        bit = ((q0 >> s) & 1).astype(bool)
        r = jnp.where(bit[:, None], jnp.roll(r, -(1 << s), axis=1), r)
        s += 1
    return r[:, :wsize].T


def ring_window(state: EngineState, m: int,
                use_pallas: bool | None = None) -> RingWindow:
    """Prefetch the next ``min(m, Q)`` ring elements of every client,
    transposed to [w, N] for cheap per-batch row selects.

    Built by barrel-shifting each client's ring left by its own
    ``q_head``: a Pallas VMEM kernel on TPU, log2(Q) masked dense XLA
    rolls elsewhere (TPU gathers with per-row indices serialize --
    measured 10x the rolls' cost for a 32-wide window; a vmapped
    dynamic-slice was 50x).  Window rows past a client's queued tail
    carry stale ring values -- reads of them only happen after the
    client drained, and are masked at commit.

    ``use_pallas`` overrides the backend auto-pick: callers that wrap
    this in ``vmap`` must pass False -- batching adds a grid dimension
    to the (deliberately gridless) kernel, and gridded pallas_calls do
    not legalize through this environment's remote Mosaic compiler."""
    q = state.ring_capacity
    q0 = state.q_head
    wsize = min(m, q)

    # the Pallas path needs a full lane tile (2q >= 128 int32 lanes)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and q >= 64
    if use_pallas:
        n = q0.shape[0]
        q0t = _tile_shifts(q0, q, n + ((-n) % _rot_chunk(q)))
        rot = functools.partial(_rotate_rows_pallas, q0=q0,
                                wsize=wsize, q0t=q0t)
    else:
        rot = functools.partial(_rotate_rows_xla, q0=q0, wsize=wsize)
    return RingWindow(arr=rot(state.q_arrival), cost=rot(state.q_cost),
                      q0=q0)


def _window_rows(state: EngineState, window: RingWindow, depth: int):
    """Rows ``off .. off+depth-1`` of the prefetched window for every
    client, where ``off = q_head - q0`` is how many rows the client
    consumed since the prefetch.  Unrolled one-hot selects -- a [w, N]
    take_along_axis lowers to a serializing gather (measured 20x
    slower)."""
    wsize = window.arr.shape[0]
    off = jnp.remainder(state.q_head - window.q0,
                        state.ring_capacity).astype(jnp.int32)
    arr_rows, cost_rows = [], []
    for d in range(depth):
        narr = window.arr[min(d, wsize - 1)]
        ncost = window.cost[min(d, wsize - 1)]
        for j in range(d + 1, wsize):
            pick = off == j - d
            narr = jnp.where(pick, window.arr[j], narr)
            ncost = jnp.where(pick, window.cost[j], ncost)
        arr_rows.append(narr)
        cost_rows.append(ncost)
    return arr_rows, cost_rows


def _window_heads(state: EngineState, window: RingWindow):
    """Every client's next tail element (new head after a pop)."""
    arr_rows, cost_rows = _window_rows(state, window, 1)
    return arr_rows[0], cost_rows[0]


def _heads_rows(heads, depth: int):
    """Normalize a ``heads`` argument to per-step row lists.

    Accepts the single-pop pair (narr[N], ncost[N]) for depth 1, or
    stacked [w, N] arrays (a ``ring_window``'s arr/cost with w >=
    depth) for chained pops."""
    arr, cost = heads
    if arr.ndim == 1:
        assert depth == 1
        return [arr], [cost]
    assert arr.shape[0] >= depth, \
        f"heads window {arr.shape[0]} rows < chain depth {depth}"
    return [arr[j] for j in range(depth)], [cost[j] for j in range(depth)]


# ----------------------------------------------------------------------
# unified candidate classification
# ----------------------------------------------------------------------

def _unified_class(now, has, resv, ready, prop, eff, allow: bool):
    """(class, key) in the unified candidate order the serial engine
    serves (reference do_next_request :1115-1186): constraint phase
    first (class 0, by reservation tag), then ready weight (class 1,
    by effective proportion), then -- Allow only -- limit-break
    (class 2, by effective proportion; :1157-1165, reachable because
    the caller guarantees weight > 0 for every active client, see
    module docstring).  Non-candidates get (CLS_NONE, KEY_INF).

    ONE definition shared by entry classification and the chain's
    exit classification -- they differ only in the readiness
    predicate (stored-flag-or-limit for current heads, limit-only for
    freshly popped ones)."""
    prop_ok = prop < MAX_TAG
    c0 = has & (resv <= now)
    c1 = has & ~c0 & ready & prop_ok
    cls = jnp.where(c0, CLS_RESV,
                    jnp.where(c1, CLS_WEIGHT, CLS_NONE))
    key = jnp.where(c0, resv, jnp.where(c1, eff, KEY_INF))
    if allow:
        c2 = has & ~c0 & ~c1 & prop_ok
        cls = jnp.where(c2, CLS_LB, cls)
        key = jnp.where(c2, eff, key)
    return cls.astype(jnp.int32), key


def _classify(state: EngineState, now, allow: bool):
    """Entry (class, key) per client (see ``_unified_class``)."""
    has_req = state.active & (state.depth > 0)
    return _unified_class(
        now, has_req, state.head_resv, _ready_now(state, now),
        state.head_prop, state.head_prop + state.prop_delta, allow)


# ----------------------------------------------------------------------
# dense serve chains
# ----------------------------------------------------------------------

class ChainServe(NamedTuple):
    """Elementwise ([N]) serve-chain computation: what every client's
    state would become after serving its full chain this batch.
    Scatter-free -- TPU scatters serialize badly, so the chain is
    computed densely for every client and committed with ``jnp.where``
    selects.  Rows outside the committed set are garbage and masked at
    commit."""

    depth: jnp.ndarray        # int32[N] after the chain
    qadv: jnp.ndarray         # int32[N] ring pops performed
    length: jnp.ndarray       # int32[N] serves in the chain (>=1 cand)
    head_resv: jnp.ndarray    # int64[N] final head tag
    head_prop: jnp.ndarray
    head_limit: jnp.ndarray
    head_arrival: jnp.ndarray
    head_cost: jnp.ndarray
    head_rho: jnp.ndarray
    prev_resv: jnp.ndarray
    prev_prop: jnp.ndarray
    prev_limit: jnp.ndarray
    prev_arrival: jnp.ndarray
    exit_cls: jnp.ndarray     # int32[N] unified class after the chain
    exit_key: jnp.ndarray     # int64[N] unified key after the chain
    cost_acc: jnp.ndarray     # int64[N] summed cost of the chain's
    #                           serves (garbage outside the committed
    #                           set, masked at commit like every other
    #                           dense chain field)


def _chain_serve(state: EngineState, now, arr_rows, cost_rows,
                 cls, allow: bool,
                 anticipation_ns: int) -> ChainServe:
    """The vectorized pop+retag (pop_process_request / update_next_tag /
    reduce_reservation_tags, reference :1021-1111) iterated
    ``len(arr_rows)`` times for EVERY client.

    Step 0 serves the entry head in the entry class's phase (weight
    phase pays the reservation debt, :1077-1111).  Steps >= 1 are the
    INDUCED constraint serves: they run only for weight/limit-break
    entries whose just-retagged reservation tag fell to ``now`` or
    below -- at that serial moment the client is the only class-0
    candidate, so the serial engine provably serves it next.  The
    chain stops when the tag climbs past ``now``, the queue drains, or
    the depth cap is hit; the exit (class, key) is the client's exact
    re-entry position in the unified order (KEY_INF when it leaves)."""
    depth_cap = len(arr_rows)
    is_cand = cls != CLS_NONE
    chains = (cls == CLS_WEIGHT) | (cls == CLS_LB)
    phase1 = chains                       # weight-phase entry serve

    h_resv, h_prop, h_limit = (state.head_resv, state.head_prop,
                               state.head_limit)
    h_arr, h_cost, h_rho = (state.head_arrival, state.head_cost,
                            state.head_rho)
    p_resv, p_prop, p_limit, p_arr = (state.prev_resv, state.prev_prop,
                                      state.prev_limit,
                                      state.prev_arrival)
    depth = state.depth
    qadv = jnp.zeros_like(state.q_head)
    length = jnp.zeros_like(state.q_head)
    cost_acc = jnp.zeros_like(h_resv)
    cont = is_cand

    for j in range(depth_cap):
        narr, ncost = arr_rows[j], cost_rows[j]
        nr, np_, nl = _make_tag(
            h_resv, h_prop, h_limit, h_arr,
            state.resv_inv, state.weight_inv, state.limit_inv,
            state.cur_delta, state.cur_rho, narr, ncost,
            anticipation_ns)
        if j == 0:
            off = jnp.where(phase1,
                            state.resv_inv * (h_cost + h_rho),
                            jnp.zeros_like(h_resv))
        else:
            off = jnp.zeros_like(h_resv)

        new_depth = depth - 1
        has_more = new_depth > 0
        upd = cont
        updh = cont & has_more
        # delivered-cost accumulation (the SLO window block's cost
        # column): the head served at this step is the CURRENT h_cost
        cost_acc = cost_acc + jnp.where(upd, h_cost, jnp.int64(0))

        new_h_resv = nr - off
        pr = jnp.where(has_more, _fold_prev(p_resv, nr), p_resv) - off
        pp = jnp.where(has_more, _fold_prev(p_prop, np_), p_prop)
        pl_ = jnp.where(has_more, _fold_prev(p_limit, nl), p_limit)

        h_resv = jnp.where(updh, new_h_resv, h_resv)
        h_prop = jnp.where(updh, np_, h_prop)
        h_limit = jnp.where(updh, nl, h_limit)
        h_arr = jnp.where(updh, narr, h_arr)
        h_cost = jnp.where(updh, ncost, h_cost)
        h_rho = jnp.where(updh, state.cur_rho, h_rho)
        p_resv = jnp.where(upd, pr, p_resv)
        p_prop = jnp.where(upd, pp, p_prop)
        p_limit = jnp.where(upd, pl_, p_limit)
        p_arr = jnp.where(updh, narr, p_arr)
        depth = jnp.where(upd, new_depth, depth).astype(jnp.int32)
        qadv = (qadv + updh).astype(jnp.int32)
        length = (length + upd).astype(jnp.int32)

        # continue only for weight/lb entries whose fresh reservation
        # tag is eligible: the induced-constraint-serve condition
        cont = cont & chains & has_more & (new_h_resv <= now)

    # exit classification on the final head (shared definition,
    # ``_unified_class``; a freshly popped head's stored ready flag is
    # False, so effective readiness is exactly limit <= now).  A chain
    # that hit the depth cap while still class-0-eligible exits at its
    # exact (0, resv) key: class 0 sorts before every remaining
    # class-1/2 entry, so the prefix stops right after the unit --
    # conservative (the serial engine would keep serving this client),
    # never inexact.
    has = state.active & (depth > 0)
    exit_cls, exit_key = _unified_class(
        now, has, h_resv, h_limit <= now, h_prop,
        h_prop + state.prop_delta, allow)

    return ChainServe(
        depth=depth, qadv=qadv, length=length,
        head_resv=h_resv, head_prop=h_prop, head_limit=h_limit,
        head_arrival=h_arr, head_cost=h_cost, head_rho=h_rho,
        prev_resv=p_resv, prev_prop=p_prop, prev_limit=p_limit,
        prev_arrival=p_arr,
        exit_cls=exit_cls.astype(jnp.int32), exit_key=exit_key,
        cost_acc=cost_acc)


def _commit_chains(state: EngineState, sel,
                   chain: ChainServe) -> EngineState:
    """Apply the dense chain result to the rows in ``sel``: pure
    elementwise selects, no scatters."""

    def pick(pred, new, old):
        return jnp.where(pred, new, old)

    popped = sel & (chain.qadv > 0)
    return state._replace(
        depth=pick(sel, chain.depth, state.depth),
        q_head=pick(popped,
                    (state.q_head + chain.qadv) % state.ring_capacity,
                    state.q_head).astype(jnp.int32),
        head_resv=pick(popped, chain.head_resv, state.head_resv),
        head_prop=pick(popped, chain.head_prop, state.head_prop),
        head_limit=pick(popped, chain.head_limit, state.head_limit),
        head_arrival=pick(popped, chain.head_arrival,
                          state.head_arrival),
        head_cost=pick(popped, chain.head_cost, state.head_cost),
        head_rho=pick(popped, chain.head_rho, state.head_rho),
        head_ready=state.head_ready & ~sel,
        prev_resv=pick(sel, chain.prev_resv, state.prev_resv),
        prev_prop=pick(sel, chain.prev_prop, state.prev_prop),
        prev_limit=pick(sel, chain.prev_limit, state.prev_limit),
        prev_arrival=pick(popped, chain.prev_arrival,
                          state.prev_arrival),
    )


# ----------------------------------------------------------------------
# unified prefix selection
# ----------------------------------------------------------------------

def _pack(cls, krel, o):
    """Lexicographic (class, key, order) as one int64: 2 class bits |
    32 key bits | 28 order bits.  ``o`` is masked against garbage
    orders on sentinel rows; all inputs int64."""
    return ((cls.astype(jnp.int64) << 60) | (krel << 28)
            | (o & _O_MASK))


# ----------------------------------------------------------------------
# selection backends: full sort vs histogram (radix) k-selection
# ----------------------------------------------------------------------
#
# The sort backend (the original engine) pays one O(N log N) lax.sort
# over 4-5 arrays to order ALL clients, then commits the first <= k.
# But selection only needs the k-th boundary plus membership; the
# ORDER is needed only for the k-sized decision emit.  The radix
# backend exploits that: a multi-pass dense histogram finds the exact
# k-th smallest packed key (no sorts, no gathers -- findings 4/8/10),
# dense elementwise ops compute membership, a prefix-sum compaction
# writes the <= k members into [k] arrays, and the expensive sort runs
# only over those k entries (honoring finding 8: cost/order/exit-key
# ride the small sort as payloads, never gathered).  Packed keys are
# unique among candidates (creation order breaks ties), so the small
# sort reproduces the big sort's first k positions BIT-EXACTLY; the
# only divergence is in masked padding lanes no caller reads
# (pinned by tests/test_radix.py).
#
# Digit width: dense one-hot histograms cost passes * 2^bits * N
# comparisons = (64/b) * 2^b * N, minimized at small b; 4-bit digits
# (16 passes of 16-bucket histograms) cost 8x less than 8-bit ones
# and keep every pass a pure vectorized compare+reduce.
#
# The histogram walk itself lives in ``kernels`` now (radix_kth_key):
# the calendar engine's bucketed stop-key ladder reuses it, so the
# machinery is shared instead of prefix-path-private.

_radix_kth_key = kernels.radix_kth_key


def _select_radix(pk_dense, iota, epk, cost32, lens, k: int, kk: int):
    """Histogram k-selection + small sort: the sorted first-kk columns
    of the big sort, built without ordering the other N-kk entries.

    Returns (pks, idxs, rpk, costs, lens_s) shaped [k], with sentinel
    padding (KEY_INF / -1 / KEY_INF / 0 / 0) past the member count --
    identical to the sort backend at every position a caller reads
    (every lane past the committed count is masked downstream).
    ``lens`` may be None (flat batches)."""
    t_kth = _radix_kth_key(pk_dense, kk)
    # membership: at most kk candidates (packed keys are unique among
    # candidates, so count == kk exactly when enough exist); the
    # KEY_INF exclusion drops sentinel rows when kk > live count
    member = (pk_dense <= t_kth) & (pk_dense < jnp.int64(KEY_INF))
    dest = jnp.cumsum(member.astype(jnp.int32)) - 1
    dest = jnp.where(member, dest, jnp.int32(k))   # k = dropped

    def compact(src, fill):
        out = jnp.full((k,), fill, dtype=src.dtype)
        return out.at[dest].set(src, mode="drop")

    ops = [compact(pk_dense, jnp.int64(KEY_INF)),
           compact(iota, jnp.int32(-1)),
           compact(epk, jnp.int64(KEY_INF)),
           compact(cost32, jnp.int32(0))]
    if lens is not None:
        ops.append(compact(lens, jnp.int32(0)))
        return lax.sort(tuple(ops), num_keys=1)
    pks, idxs, rpk, costs = lax.sort(tuple(ops), num_keys=1)
    return pks, idxs, rpk, costs, jnp.ones((k,), dtype=jnp.int32)


class _Selection(NamedTuple):
    """Everything a caller needs to commit + emit a unified prefix."""

    idxs: jnp.ndarray        # int32[k] sorted candidate slots
    cls_s: jnp.ndarray       # int32[k] sorted entry classes
    cost_s: jnp.ndarray      # int32[k] sorted entry (head) costs
    len_s: jnp.ndarray       # int32[k] sorted chain lengths
    count_units: jnp.ndarray  # int32 committed sort units
    count: jnp.ndarray       # int32 committed DECISIONS (sum of len)
    guards_ok: jnp.ndarray   # bool
    state: EngineState       # after the committed prefix
    last_client: jnp.ndarray  # int32 slot of the final committed unit
    cost_pc: jnp.ndarray     # int64[N] delivered cost per client over
    #                          the committed prefix (0 off-prefix)
    margin_s: jnp.ndarray    # int64[k] winner margin over the exact
    #                          runner-up per committed unit, ns
    #                          (-1 = no runner-up; obs.provenance)


def _unified_prefix(state: EngineState, now, k: int, *,
                    chain_depth: int, anticipation_ns: int,
                    allow: bool, heads, max_count,
                    select_impl: str = "sort") -> _Selection:
    """Classify, chain, select (full sort or histogram k-selection,
    ``select_impl``), and commit the longest exact prefix."""
    assert select_impl in ("sort", "radix"), select_impl
    if heads is None:
        heads = ring_window(state, chain_depth)
        heads = (heads.arr, heads.cost)
    arr_rows, cost_rows = _heads_rows(heads, chain_depth)

    cls, key = _classify(state, now, allow)
    chain = _chain_serve(state, now, arr_rows, cost_rows, cls, allow,
                         anticipation_ns)

    is_cand = cls != CLS_NONE
    # --- packed rebase over two key spaces: reservation tags
    # (class 0) and effective proportion tags (classes 1/2).
    # PER-CLASS rebase origins: each class's minimum entry rebases to
    # the bias, so position 0 of the sort is always in-window and a
    # nonempty candidate set always commits >= 1 unit (guaranteed
    # progress), whatever the spread between the classes' key spaces.
    # _EXIT_BIAS reserves the window's low end for exits that land
    # BELOW their class origin (e.g. a constraint serve re-entering
    # weight space under the ready minimum): within the bias they
    # rebase exactly; further below they clamp to 0, which only
    # shortens the prefix -- conservative, never inexact.
    def class_min(m):
        return jnp.min(jnp.where(m, key, KEY_INF))

    kresv = class_min(cls == CLS_RESV)
    kprop1 = class_min(cls == CLS_WEIGHT)
    kprop2 = class_min(cls == CLS_LB)

    def origin_of(c):
        return jnp.where(c == CLS_RESV, kresv,
                         jnp.where(c == CLS_WEIGHT, kprop1, kprop2))

    krel = jnp.clip(key - origin_of(cls) + _EXIT_BIAS, 0,
                    jnp.int64(_KEY_CLAMP))

    # order rebased like the keys: creation indices grow without bound,
    # so the 28-bit pack must be of the spread, not the absolute value
    omin = jnp.min(jnp.where(is_cand, state.order, jnp.int64(1) << 62))
    o64 = state.order - omin
    omax = jnp.max(jnp.where(is_cand, state.order, omin))
    # the cost guard masks to real candidates: an oversized cost on an
    # inactive/non-candidate row must not disable the fastpath forever
    cost_ok = jnp.max(jnp.where(is_cand, state.head_cost, 0)) \
        < (jnp.int64(1) << 31)
    guards_ok = (omax - omin < _ORDER_LIMIT) & cost_ok

    pk_dense = jnp.where(is_cand, _pack(cls, krel, o64),
                         jnp.int64(KEY_INF))

    # exit keys in the same packed space.  Clamping an exit low (past
    # the bias below its class origin) only shortens the prefix --
    # conservative, never inexact; clamping high (_KEY_HI, above the
    # entry clamp) preserves ``exit > boundary`` for every committable
    # boundary, which is strictly in-window.
    ekrel = jnp.clip(chain.exit_key - origin_of(chain.exit_cls)
                     + _EXIT_BIAS, 0, jnp.int64(_KEY_HI))
    epk = jnp.where(chain.exit_cls == CLS_NONE, jnp.int64(KEY_INF),
                    _pack(chain.exit_cls, ekrel, o64))

    iota = jnp.arange(key.shape[0], dtype=jnp.int32)
    kk = min(k, key.shape[0])

    def trim(a, fill):
        a = a[:kk]
        if kk < k:      # k beyond the population: sentinel padding
            a = jnp.concatenate(
                [a, jnp.full((k - kk,), fill, dtype=a.dtype)])
        return a

    if select_impl == "radix":
        pks, idxs, rpk, costs, lens = _select_radix(
            pk_dense, iota, epk, state.head_cost.astype(jnp.int32),
            chain.length if chain_depth > 1 else None, k, kk)
    elif chain_depth == 1:
        pks, idxs, rpk, costs = lax.sort(
            (pk_dense, iota, epk,
             state.head_cost.astype(jnp.int32)), num_keys=1)
        lens = jnp.ones((k,), dtype=jnp.int32)
        pks, idxs = trim(pks, KEY_INF), trim(idxs, -1)
        rpk, costs = trim(rpk, KEY_INF), trim(costs, 0)
    else:
        pks, idxs, rpk, costs, lens = lax.sort(
            (pk_dense, iota, epk,
             state.head_cost.astype(jnp.int32), chain.length),
            num_keys=1)
        lens = trim(lens, 0)
        pks, idxs = trim(pks, KEY_INF), trim(idxs, -1)
        rpk, costs = trim(rpk, KEY_INF), trim(costs, 0)

    # exclusive cumulative min of exit keys over the sorted order
    cm = lax.associative_scan(jnp.minimum, rpk)
    cm_excl = jnp.concatenate(
        [jnp.full((1,), jnp.int64(KEY_INF), dtype=jnp.int64), cm[:-1]])

    in_window = ((pks >> 60) < CLS_NONE) & \
        (((pks >> 28) & _KEY_HI) < _KEY_CLAMP)
    ok_q = in_window & (cm_excl > pks)
    count_units = jnp.where(jnp.all(ok_q), jnp.int32(k),
                            jnp.argmax(~ok_q).astype(jnp.int32))
    count_units = jnp.where(guards_ok, count_units, jnp.int32(0))
    if max_count is not None:
        assert chain_depth == 1, \
            "max_count caps decisions; only supported at chain_depth=1"
        count_units = jnp.minimum(count_units, jnp.int32(max_count))

    j = jnp.arange(k, dtype=jnp.int32)
    served = j < count_units
    cls_s = (pks >> 60).astype(jnp.int32)   # >= CLS_NONE on sentinels

    # provenance margins (obs.provenance): at the instant unit j
    # commits, the candidate set is {entries j+1..} plus the re-entry
    # exit keys of the already-served prefix p < j -- so the EXACT
    # runner-up is min(pks[j+1], cm_excl[j]), both already
    # materialized.  The >> 28 strips the order bits (packed key =
    # cls<<60 | rebased-ns<<28 | order): a same-class margin is the
    # tag distance in ns; a cross-class one carries the class step
    # (>= 2^32 ns -- "the phase ladder, not the tag, decided").  -1 =
    # no runner-up existed (sole candidate).  Dead code unless a
    # provenance/flight consumer reads it (XLA DCE).
    nxt = jnp.concatenate(
        [pks[1:], jnp.full((1,), jnp.int64(KEY_INF))])
    runner = jnp.minimum(nxt, cm_excl)
    margin_s = jnp.where(served & (runner < jnp.int64(KEY_INF)),
                         (runner - pks) >> 28, jnp.int64(-1))
    if chain_depth == 1:
        count = count_units
    else:
        count = jnp.sum(jnp.where(served, lens, 0)).astype(jnp.int32)

    # commit: dense membership is ``packed(key) <= packed boundary``
    # (packed keys are unique).  The boundary pk[count-1] is read as a
    # masked max over the sorted prefix, not a dynamic gather --
    # scalar gathers from vectors serialize on this stack (PROFILE.md
    # findings 4/8).
    boundary = jnp.max(jnp.where(served, pks, jnp.int64(-1)))
    sel = pk_dense <= boundary
    new_state = _commit_chains(state, sel, chain)

    # stored-flag parity (promote loop, reference :1135-1144): every
    # weight-phase (class >= 1 entry) decision promotes current heads
    # with limit <= now.  Classes sort ascending, so the LAST committed
    # unit has the batch's max class: if it is >= 1, its entry decision
    # ran the batch's final promote pass, and the only head that pass
    # never saw is the one its own chain popped into place.  With no
    # class >= 1 unit committed no promote pass ran, so the flags stay
    # untouched (pops still clear them via _commit_chains).
    sel_last = j == count_units - 1
    cls_last = jnp.max(jnp.where(sel_last, cls_s, -1))
    last_client = jnp.max(jnp.where(sel_last, idxs, -1))
    do_promote = (count_units > 0) & (cls_last >= CLS_WEIGHT)
    has_req_after = new_state.active & (new_state.depth > 0)
    promoted = new_state.head_ready | \
        (has_req_after & (new_state.head_limit <= now))
    promoted = promoted & (
        jnp.arange(state.capacity, dtype=jnp.int32) != last_client)
    new_state = new_state._replace(head_ready=jnp.where(
        do_promote, promoted, new_state.head_ready))

    return _Selection(idxs=idxs, cls_s=cls_s, cost_s=costs, len_s=lens,
                      count_units=count_units, count=count,
                      guards_ok=guards_ok, state=new_state,
                      last_client=last_client,
                      cost_pc=jnp.where(sel, chain.cost_acc,
                                        jnp.int64(0)),
                      margin_s=margin_s)


# ----------------------------------------------------------------------
# flat (chain_depth=1) batches: one decision per sort unit
# ----------------------------------------------------------------------

class PrefixBatch(NamedTuple):
    """Result of one prefix-commit attempt."""

    state: EngineState
    count: jnp.ndarray     # int32: decisions committed (exact serial
    #                        prefix; 0 = nothing eligible at `now`)
    guards_ok: jnp.ndarray  # bool: rebase-window guards held; when
    #                         False count is 0 and the caller must use
    #                         the serial engine for this batch
    decisions: Decision    # [k]; slots -1 / type NONE past `count`
    cost_pc: object = None  # int64[N] delivered cost per client (the
    #                         SLO window block's cost column feed)
    margins: object = None  # int64[k] per-decision winner margin, ns
    #                         (-1 = no runner-up; obs.provenance)


def speculate_prefix_batch(state: EngineState, now, k: int, *,
                           anticipation_ns: int,
                           heads=None,
                           max_count=None,
                           allow_limit_break: bool = False,
                           select_impl: str = "sort"
                           ) -> PrefixBatch:
    """One prefix-commit batch over the unified candidate order: the
    longest exact prefix of the sorted (class, key, order) triples
    commits, crossing constraint<->weight regime boundaries inside a
    single batch (reference do_next_request :1115-1186 makes a fresh
    phase choice per decision; the class field encodes it per unit).

    ``max_count`` (optional int32 scalar, may be traced) caps the
    committed prefix: a shorter prefix of an exact prefix is still
    exact, so callers can budget decisions (e.g. a simulator serving
    at most its remaining slice capacity) without losing parity."""
    s = _unified_prefix(state, now, k, chain_depth=1,
                        anticipation_ns=anticipation_ns,
                        allow=allow_limit_break, heads=heads,
                        max_count=max_count, select_impl=select_impl)
    j = jnp.arange(k, dtype=jnp.int32)
    served = j < s.count_units
    phase = jnp.where(s.cls_s >= CLS_WEIGHT, 1, 0).astype(jnp.int32)
    decisions = Decision(
        type=jnp.where(served, RETURNING, NONE).astype(jnp.int32),
        slot=jnp.where(served, s.idxs, -1).astype(jnp.int32),
        phase=jnp.where(served, phase, 0),
        cost=jnp.where(served, s.cost_s.astype(jnp.int64), 0),
        when=jnp.zeros((k,), dtype=jnp.int64),
        limit_break=served & (s.cls_s >= CLS_LB),
    )
    return PrefixBatch(state=s.state, count=s.count,
                       guards_ok=s.guards_ok, decisions=decisions,
                       cost_pc=s.cost_pc, margins=s.margin_s)


# ----------------------------------------------------------------------
# chained batches: one sort unit = up to chain_depth decisions
# ----------------------------------------------------------------------

class ChainBatch(NamedTuple):
    """Result of one chained prefix-commit attempt: compact unit form.

    The flat decision stream is ``slot[q]`` repeated ``length[q]``
    times for each committed unit q in order, phases = the unit's
    entry phase (class >= 1 -> weight) followed by length-1 constraint
    serves (see ``expand_units``)."""

    state: EngineState
    count: jnp.ndarray       # int32 committed DECISIONS
    unit_count: jnp.ndarray  # int32 committed sort units
    guards_ok: jnp.ndarray
    slot: jnp.ndarray        # int32[k] unit client (-1 pad)
    cls: jnp.ndarray         # int32[k] unit entry class
    length: jnp.ndarray      # int32[k] unit decisions
    cost_pc: object = None   # int64[N] delivered cost per client
    margins: object = None   # int64[k] per-unit winner margin, ns


def speculate_chain_batch(state: EngineState, now, k: int, *,
                          chain_depth: int, anticipation_ns: int,
                          heads=None,
                          allow_limit_break: bool = False,
                          select_impl: str = "sort"
                          ) -> ChainBatch:
    """One prefix-commit batch with serve chains (see module
    docstring): each sort unit serves a client up to ``chain_depth``
    times -- a weight serve plus the constraint serves its
    reservation-debt reduction induces -- so interleaved-regime
    streams commit in long prefixes."""
    s = _unified_prefix(state, now, k, chain_depth=chain_depth,
                        anticipation_ns=anticipation_ns,
                        allow=allow_limit_break, heads=heads,
                        max_count=None, select_impl=select_impl)
    j = jnp.arange(k, dtype=jnp.int32)
    served = j < s.count_units
    return ChainBatch(
        state=s.state, count=s.count, unit_count=s.count_units,
        guards_ok=s.guards_ok,
        slot=jnp.where(served, s.idxs, -1).astype(jnp.int32),
        cls=jnp.where(served, s.cls_s, CLS_NONE).astype(jnp.int32),
        length=jnp.where(served, s.len_s, 0).astype(jnp.int32),
        cost_pc=s.cost_pc, margins=s.margin_s)


def expand_units(slot, cls, length, pre_state, *,
                 limit_break: bool = False):
    """Host-side expansion of committed units into the flat serial
    decision stream (slots, phases, costs, limit_breaks) -- numpy, for
    differential tests and parity harnesses.  ``pre_state`` is the
    EngineState BEFORE the batch (its rings supply the induced serves'
    costs)."""
    import numpy as np

    slot = np.asarray(slot)
    cls = np.asarray(cls)
    length = np.asarray(length)
    head_cost = np.asarray(pre_state.head_cost)
    q_head = np.asarray(pre_state.q_head)
    q_cost = np.asarray(pre_state.q_cost)
    ring = q_cost.shape[1]
    slots, phases, costs, lbs = [], [], [], []
    for u in range(slot.shape[0]):
        c = int(slot[u])
        if c < 0 or length[u] == 0:
            continue
        for step in range(int(length[u])):
            slots.append(c)
            phases.append(1 if (step == 0 and cls[u] >= CLS_WEIGHT)
                          else 0)
            lbs.append(bool(limit_break and step == 0
                            and cls[u] >= CLS_LB))
            if step == 0:
                costs.append(int(head_cost[c]))
            else:
                costs.append(int(q_cost[c, (q_head[c] + step - 1)
                                        % ring]))
    return (np.asarray(slots, np.int32), np.asarray(phases, np.int32),
            np.asarray(costs, np.int64), np.asarray(lbs, bool))


# ----------------------------------------------------------------------
# epoch scans
# ----------------------------------------------------------------------

# state fields the speculative serve path never writes: rings are only
# popped via q_head, and QoS/identity/ingest-time fields are mutated by
# ingest alone, which cannot run mid-epoch.  Keeping them OUT of the
# scan carry stops XLA from shuffling ~100MB of loop-invariant buffers
# per iteration (the rings dominate).
_EPOCH_INVARIANT = ("active", "idle", "order", "resv_inv", "weight_inv",
                    "limit_inv", "prop_delta", "cur_rho", "cur_delta",
                    "q_arrival", "q_cost")
_EPOCH_MUTABLE = tuple(f for f in EngineState._fields
                       if f not in _EPOCH_INVARIANT)


# ----------------------------------------------------------------------
# int32 epoch tag carry (tag_width=32)
#
# The 10 int64 tag/arrival/cost fields in the scan carry
# (state.TAG_I64_FIELDS) are rebased to int32 offsets from per-field
# epoch origins (kernels.rebase32), halving the loop-carried HBM
# traffic of every epoch iteration.  Batches still compute in int64 --
# the widen/narrow converts fuse into the first/last elementwise pass
# of each batch -- so decisions are bit-identical to tag_width=64
# whenever the window holds (pinned by tests/test_radix.py).  A batch
# whose post-state no longer fits the +-2^31 ns window commits NOTHING
# (its carry is kept, its guards_ok output is False, and the
# rebase_fallbacks metric bumps once); the caller reruns the remaining
# batches on the int64 path from the returned state, exactly like the
# sort-key rebase-guard fallback.
# ----------------------------------------------------------------------

class _TagCarry32:
    """The int32 tag carry shared by the three epoch scans: per-field
    origins, entry/per-batch narrowing, widening, and the exit restore
    (one implementation so a fix lands once, not three times).

    Origins are the center of each field's organic (non-sentinel)
    value span at epoch entry, computed over the epoch's LIVE lanes
    only -- clients that are active with work queued.  Centering
    covers entry spreads up to the full 2^32 ns window (~4.3s) with
    symmetric headroom for in-epoch drift (tag climb above,
    weight-debt dips below).  Lanes that cannot serve this epoch
    (inactive or empty at entry; ingest cannot run mid-epoch, so they
    stay that way) are excluded from the window fit and carried as
    zero offsets: every read of their tag fields is masked by
    candidacy (`active & depth > 0`), and the exit restore puts their
    exact entry values back.  Without the live mask, ONE stale idle
    lane whose ancient tag sits outside the window would permanently
    disable the int32 carry on long-running states.

    Epochs whose live entry spread or serve advance exceeds the window
    trip the fit check and fall back exactly (see the section
    comment); low-rate workloads whose tags advance ~1e9 ns per serve
    are expected to live on tag_width=64 (docs/ENGINE.md)."""

    def __init__(self, state: EngineState):
        self.live0 = state.active & (state.depth > 0)

        def organic_center(v):
            fin = self.live0 & (v > MIN_TAG) & (v < MAX_TAG)
            lo = jnp.min(jnp.where(fin, v, MAX_TAG))
            hi = jnp.max(jnp.where(fin, v, MIN_TAG))
            return jnp.where(lo > hi, jnp.int64(0),
                             lo + (hi - lo) // 2)

        self.origins = {f: organic_center(getattr(state, f))
                        for f in TAG_I64_FIELDS}

    def narrow(self, mut: dict):
        """Rebase the int64 fields of a mutable-carry dict to int32;
        returns (narrowed dict, all-windows-held scalar).  Dead lanes
        rebase as zero offsets and never affect the fit."""
        ok = jnp.bool_(True)
        out = dict(mut)
        for f in TAG_I64_FIELDS:
            v = jnp.where(self.live0, mut[f], self.origins[f])
            v32, o = kernels.rebase32(v, self.origins[f])
            out[f] = v32
            ok = ok & o
        return out, ok

    def widen(self, mut32: dict) -> dict:
        """Inverse of :meth:`narrow` for live lanes; dead lanes widen
        to their origin -- garbage, but every consumer masks them by
        candidacy, and :meth:`restore` puts the real values back."""
        out = dict(mut32)
        for f in TAG_I64_FIELDS:
            out[f] = kernels.restore64(mut32[f], self.origins[f])
        return out

    def gate(self, dead, mut: dict, new_mut: dict, outs):
        """The per-batch fallback gate every epoch scan shares: narrow
        the post-batch state, and when it does not fit (or an earlier
        batch already tripped) zero this batch's outputs and keep the
        carry at the last good state.

        ``outs`` is a sequence of (value, fallback-fill) pairs in the
        scan's output order; returns ``(mut, dead, good, trip,
        gated_values)``."""
        new32, fit = self.narrow(new_mut)
        good = ~dead & fit
        trip = ~dead & ~fit
        vals = tuple(jnp.where(good, v, f) for v, f in outs)
        mut = {f: jnp.where(good, new32[f], mut[f]) for f in new32}
        return mut, dead | ~fit, good, trip, vals

    def restore(self, mut32: dict, mut0_64: dict, ok0) -> dict:
        """Exit state: widened live lanes, exact entry values for dead
        lanes (never written mid-epoch), and -- when the ENTRY state
        already failed to narrow -- the input state untouched."""
        out = self.widen(mut32)
        for f in out:
            keep = (self.live0 & ok0) if f in TAG_I64_FIELDS else ok0
            out[f] = jnp.where(keep, out[f], mut0_64[f])
        return out


class PrefixEpoch(NamedTuple):
    """M flat prefix batches' output, compact for one readback."""

    state: EngineState     # after ALL committed prefixes
    count: jnp.ndarray     # int32[M] decisions committed per batch
    guards_ok: jnp.ndarray  # bool[M]
    slot: jnp.ndarray      # int32[M, k] serial-order winners (-1 pad)
    phase: jnp.ndarray     # int8[M, k]  0 reservation / 1 weight
    cost: jnp.ndarray      # int32[M, k]
    lb: jnp.ndarray        # bool[M, k]  limit-break serves (Allow)
    metrics: jnp.ndarray   # int64[NUM_METRICS] (zeros unless
    #                        with_metrics; rides the same readback)
    # telemetry plane (None unless the caller passed an accumulator):
    hists: object = None   # int64[NUM_HISTS, NUM_BUCKETS+1]
    ledger: object = None  # int64[N, LED_COLS]
    flight: object = None  # obs.flight.FlightState
    slo: object = None     # int64[N, W_FIELDS] window block (obs.slo)
    prov: object = None    # obs.provenance.ProvBlock


def _batch_metrics(met, st: EngineState, *, count, resv, prop, lb,
                   guards_ok, rebase_fallback=False, live=True,
                   ladder_levels_used=0, ladder_base_decisions=0,
                   ladder_fallbacks=0, wheel_occ_hwm=0,
                   wheel_reslots=0, pallas_fallbacks=0):
    """Fold one batch's contribution into the epoch metrics vector --
    pure reductions over arrays the batch already materialized, so the
    decision stream cannot be perturbed.  A stall is a batch that
    committed nothing while work sat queued (every queued head capped
    by its limit/reservation tag).  ``rebase_fallback`` marks an int32
    tag-carry window trip (tag_width=32 epochs only); ``live`` is
    False for the DEAD batches after such a trip -- their forced-zero
    counts are not scheduler stalls, their speculative (discarded)
    state must not feed the ring high-water mark, and their guard
    outcomes would re-count one frozen speculation every remaining
    batch."""
    queued = jnp.any(st.active & (st.depth > 0))
    stall = (count == 0) & queued & live
    hwm = jnp.where(live, jnp.max(st.depth), 0)
    return obsdev.metrics_combine(met, obsdev.metrics_delta(
        decisions=count.astype(jnp.int64),
        resv=resv.astype(jnp.int64), prop=prop.astype(jnp.int64),
        limit_break=lb.astype(jnp.int64),
        stalls=stall.astype(jnp.int64),
        ring_hwm=hwm.astype(jnp.int64),
        guard_trips=(~guards_ok & live).astype(jnp.int64),
        rebase_fallbacks=jnp.asarray(rebase_fallback,
                                     jnp.int64),
        cal_ladder_levels_used=ladder_levels_used,
        cal_ladder_base_decisions=ladder_base_decisions,
        cal_ladder_fallbacks=ladder_fallbacks,
        wheel_occ_hwm=wheel_occ_hwm,
        wheel_reslots=wheel_reslots,
        pallas_fallbacks=pallas_fallbacks))


def _telemetry_delta(st_post: EngineState, now, cls, key, served_pc,
                     resv_pc, lb_pc, count, with_hists: bool,
                     with_ledger: bool, cost_pc=None,
                     with_slo: bool = False):
    """One batch/level's telemetry contribution (``obs.histograms`` /
    ``obs.slo``): pure reductions over the entry classification the
    batch already computed and the pre/post depth delta, so the
    decision stream cannot be perturbed.  Returns ``(hist_delta |
    None, ledger_delta | None, slo_delta | None)``; the caller folds
    them gated on batch liveness (the tag32 dead-batch rule, exactly
    like ``_batch_metrics``).

    Tardiness/latency are ENTRY-HEAD observations: ``max(now - key,
    0)`` against the committed unit's unified entry key -- the
    reservation deadline for class-0 entries, the effective proportion
    tag for class-1/2 entries (0 = served at/ahead of its virtual
    tag).  The stall observation is the time until the earliest queued
    head becomes eligible, read from the post-batch state.
    ``cost_pc`` (required with ``with_slo``) is the per-client
    delivered cost the batch committed -- the window block's cost
    column shares the ledger's entry-head tardiness semantics, so the
    windowed-vs-cumulative cross-check can hold exactly."""
    m = served_pc > 0
    tard = jnp.maximum(jnp.asarray(now, jnp.int64) - key, 0)
    resv_entry = m & (cls == CLS_RESV)
    w_entry = m & (cls >= CLS_WEIGHT) & (cls < CLS_NONE)
    hd = ld = sd = None
    if with_hists:
        hd = obshist.hist_zero()
        hd = obshist.hist_observe(hd, obshist.HIST_DECISION_LATENCY,
                                  tard, w_entry)
        hd = obshist.hist_observe(hd, obshist.HIST_RESV_TARDINESS,
                                  tard, resv_entry)
        queued = st_post.active & (st_post.depth > 0)
        stalled = (count == 0) & jnp.any(queued)
        next_elig = jnp.min(jnp.where(
            queued, jnp.minimum(st_post.head_resv, st_post.head_limit),
            MAX_TAG))
        hd = obshist.hist_observe_scalar(
            hd, obshist.HIST_LIMIT_STALL,
            jnp.maximum(next_elig - now, 0), stalled)
        hd = obshist.hist_observe_scalar(
            hd, obshist.HIST_COMMIT_SIZE, count.astype(jnp.int64), 1)
    if with_ledger or with_slo:
        t = jnp.where(resv_entry, tard, 0)
    if with_ledger:
        ld = jnp.stack([served_pc.astype(jnp.int64),
                        resv_pc.astype(jnp.int64),
                        lb_pc.astype(jnp.int64), t, t], axis=1)
    if with_slo:
        assert cost_pc is not None, \
            "the SLO window block needs the per-client delivered cost"
        tardy = (resv_entry & (tard > 0)).astype(jnp.int64)
        sd = obsslo.window_delta(served_pc, cost_pc, resv_pc, tardy,
                                 lb_pc, t)
    return hd, ld, sd


def _tele_init(state: EngineState, hists, ledger, flight,
               slo=None, prov=None) -> dict:
    """Normalize the optional telemetry accumulators into the tele
    carry dict (presence of a key IS the static on-flag)."""
    tele = {}
    if hists is not None:
        tele["h"] = jnp.asarray(hists, dtype=jnp.int64)
    if ledger is not None:
        ledger = jnp.asarray(ledger, dtype=jnp.int64)
        assert ledger.shape == (state.capacity, obshist.LED_COLS), \
            f"ledger shape {ledger.shape} != " \
            f"({state.capacity}, {obshist.LED_COLS})"
        tele["l"] = ledger
    if flight is not None:
        tele["f"] = flight
    if slo is not None:
        slo = jnp.asarray(slo, dtype=jnp.int64)
        assert slo.shape == (state.capacity, obsslo.W_FIELDS), \
            f"slo window shape {slo.shape} != " \
            f"({state.capacity}, {obsslo.W_FIELDS})"
        tele["s"] = slo
    if prov is not None:
        assert prov.last_served.shape == (state.capacity,), \
            f"prov last_served shape {prov.last_served.shape} != " \
            f"({state.capacity},)"
        tele["p"] = prov
    return tele


def _tele_fold(tele: dict, hd, ld, live, sd=None) -> dict:
    """Fold one batch's histogram/ledger/window deltas, gated on
    liveness."""
    out = dict(tele)
    if "h" in tele:
        out["h"] = obshist.hist_fold(tele["h"], hd, live)
    if "l" in tele:
        out["l"] = obshist.ledger_fold(tele["l"], ld, live)
    if "s" in tele:
        out["s"] = obsslo.window_fold(tele["s"], sd, live)
    return out


def _tele_entry_fold(tele: dict, st: EngineState, post_state,
                     now, allow: bool, count, live, cost_pc=None,
                     margins=None):
    """The shared prefix/chain telemetry fold: batch-entry
    classification, depth-delta served counts, the entry-head
    resv/limit-break derivation, and the gated histogram/ledger/window
    fold -- ONE implementation so the two sorted engines' entry-head
    semantics cannot drift.  ``margins`` is the batch's per-record
    winner-margin array (the provenance plane's histogram feed).
    Returns ``(tele, key_e, gate_n)`` -- the entry keys and the
    limit-gated client count feed each engine's own flight record."""
    cls_e, key_e = _classify(st, now, allow)
    served_pc = (st.depth - post_state.depth).astype(jnp.int32)
    srv = served_pc > 0
    w_entry = srv & (cls_e >= CLS_WEIGHT) & (cls_e < CLS_NONE)
    hd, ld, sd = _telemetry_delta(
        post_state, now, cls_e, key_e, served_pc,
        served_pc - w_entry.astype(jnp.int32),
        (srv & (cls_e == CLS_LB)).astype(jnp.int32),
        count, "h" in tele, "l" in tele,
        cost_pc=cost_pc, with_slo="s" in tele)
    has_req = st.active & (st.depth > 0)
    elig = cls_e != CLS_NONE
    gate_n = jnp.sum(has_req & ~elig).astype(jnp.int64)
    out = _tele_fold(tele, hd, ld, live, sd)
    if "p" in tele:
        newp = obsprov.prov_observe(
            tele["p"], now=now, elig=elig, gated=has_req & ~elig,
            win_cls=jnp.min(jnp.where(elig, cls_e, CLS_NONE)),
            served_pc=served_pc, margins=margins)
        out["p"] = obsprov.prov_select(live, newp, tele["p"])
    return out, key_e, gate_n


def _tele_flight(tele: dict, slot, cls, tag, cost, live,
                 margin=None, gate=None) -> dict:
    if "f" not in tele:
        return tele
    out = dict(tele)
    out["f"] = obsflight.flight_record(tele["f"], slot, cls, tag,
                                       cost, live=live,
                                       margin=margin, gate=gate)
    return out


def scan_prefix_epoch(state: EngineState, now, m: int, k: int, *,
                      anticipation_ns: int,
                      allow_limit_break: bool = False,
                      with_metrics: bool = False,
                      select_impl: str = "sort",
                      tag_width: int = 64,
                      window_m: int | None = None,
                      hists=None, ledger=None,
                      flight=None, slo=None,
                      prov=None) -> PrefixEpoch:
    """Run m flat prefix-commit batches of up to k decisions on device.

    EVERY batch commits its own exact prefix, so the concatenated
    per-batch prefixes are always the serial decision stream at
    ``now``.  Batches after the workload drains commit 0 and
    spin harmlessly.  Callers MUST check ``guards_ok``: a rare global
    rebase-guard failure (creation-order spread or served cost past
    2^31) zeroes that batch and every later one without committing --
    rerun from the returned state via ``make_prefix_runner``'s serial
    fallback in that case.

    ``with_metrics`` (STATIC) accumulates the ``obs.device`` vector in
    the same scan carry; the decision stream and final state are
    bit-identical with it on or off (tests/test_obs.py).

    ``select_impl`` (STATIC, "sort"|"radix") picks the selection
    backend -- both produce bit-identical decision streams
    (tests/test_radix.py); "radix" replaces the O(N log N) full sort
    with histogram k-selection + a [k]-sized sort.

    ``tag_width`` (STATIC, 64|32): with 32 the scan carries the int64
    tag fields as int32 epoch-rebased offsets (half the loop-carried
    HBM traffic); a window trip makes that batch and every later one
    commit 0 with guards_ok False (plus one ``rebase_fallbacks``
    metric bump) -- same caller contract as the sort-key guard.

    ``window_m`` (STATIC) chunks the ring-window prefetch: the epoch
    runs ``m / window_m`` prefetch chunks of ``window_m`` batches
    each, so wide epochs (m=64) amortize per-epoch dispatch without
    growing the unrolled window-select chain past ``window_m`` rows
    (the chain's cost scales with the window width -- PROFILE.md).
    Must divide m; None = one m-row window (the original layout).

    ``hists`` / ``ledger`` / ``flight`` / ``slo`` / ``prov`` (each
    None = off; presence is the static flag) are INITIAL telemetry
    accumulators (``obs.histograms.hist_zero()`` / ``ledger_zero(N)``
    / ``obs.flight.flight_init(R)`` / ``obs.slo.window_zero(N)`` /
    ``obs.provenance.prov_init(N)`` or the previous epoch's outputs,
    so chained epochs accumulate on device with one final fetch).
    They ride the scan carry next to the metrics vector and come back
    as the epoch result's ``hists``/``ledger``/``flight``/``slo``/
    ``prov`` fields; the decision stream and final state are
    bit-identical with telemetry on or off (tests/test_telemetry.py,
    tests/test_slo.py, tests/test_provenance.py).
    """
    assert tag_width in (32, 64), tag_width
    w = m if window_m is None else min(int(window_m), m)
    assert w > 0 and m % w == 0, "window_m must divide m"
    narrow32 = tag_width == 32
    invariant = {f: getattr(state, f) for f in _EPOCH_INVARIANT}
    mutable0_64 = {f: getattr(state, f) for f in _EPOCH_MUTABLE}
    met0 = obsdev.metrics_zero()
    tele0 = _tele_init(state, hists, ledger, flight, slo, prov)
    need_class = bool(tele0)
    if narrow32:
        tc = _TagCarry32(state)
        mutable0, ok0 = tc.narrow(mutable0_64)
        if with_metrics:
            met0 = obsdev.metrics_combine(met0, obsdev.metrics_delta(
                rebase_fallbacks=(~ok0).astype(jnp.int64)))
        carry0 = (mutable0, met0, tele0, ~ok0)
    else:
        carry0 = (mutable0_64, met0, tele0)

    def body(window, carry, _):
        if narrow32:
            mut, met, tele, dead = carry
            st = EngineState(**invariant, **tc.widen(mut))
        else:
            mut, met, tele = carry
            st = EngineState(**invariant, **mut)
        batch = speculate_prefix_batch(
            st, now, k, anticipation_ns=anticipation_ns,
            heads=_window_heads(st, window),
            allow_limit_break=allow_limit_break,
            select_impl=select_impl)
        count = batch.count
        guards = batch.guards_ok
        slot = batch.decisions.slot
        phase = batch.decisions.phase.astype(jnp.int8)
        cost = batch.decisions.cost.astype(jnp.int32)
        lb = batch.decisions.limit_break
        new_mut = {f: getattr(batch.state, f) for f in _EPOCH_MUTABLE}
        trip = jnp.bool_(False)
        good = jnp.bool_(True)
        if narrow32:
            mut, dead, good, trip, \
                (count, guards, slot, phase, cost, lb) = tc.gate(
                    dead, mut, new_mut,
                    [(count, 0), (guards, False), (slot, -1),
                     (phase, jnp.int8(0)), (cost, 0), (lb, False)])
        else:
            mut = new_mut
        out = (count, guards, slot, phase, cost, lb)
        if with_metrics:
            served = slot >= 0
            resv = jnp.sum(served & (phase == 0))
            met = _batch_metrics(
                met, batch.state, count=count, resv=resv,
                prop=count - resv, lb=jnp.sum(lb),
                guards_ok=batch.guards_ok, rebase_fallback=trip,
                live=good)
        if need_class:
            # entry classification recomputed for telemetry only (a
            # cheap dense pass; the decision stream is untouched)
            tele, key_e, gate_n = _tele_entry_fold(
                tele, st, batch.state, now, allow_limit_break,
                batch.count, good, cost_pc=batch.cost_pc,
                margins=batch.margins)
            tele = _tele_flight(
                tele, slot,
                phase.astype(jnp.int64) + lb.astype(jnp.int64),
                jnp.take(key_e, jnp.maximum(slot, 0)), cost, good,
                margin=batch.margins, gate=gate_n)
        carry = (mut, met, tele, dead) if narrow32 \
            else (mut, met, tele)
        return carry, out

    def run_chunk(carry, _):
        mut64 = tc.widen(carry[0]) if narrow32 else carry[0]
        st_c = EngineState(**invariant, **mut64)
        window = ring_window(st_c, w)
        return lax.scan(functools.partial(body, window), carry, None,
                        length=w)

    if w == m:
        carry, outs = run_chunk(carry0, None)
    else:
        carry, outs = lax.scan(run_chunk, carry0, None, length=m // w)
        outs = jax.tree_util.tree_map(
            lambda a: a.reshape((m,) + a.shape[2:]), outs)
    count, guards, slot, phase, cost, lb = outs
    mutable, metrics, tele = carry[0], carry[1], carry[2]
    if narrow32:
        state = EngineState(**invariant,
                            **tc.restore(mutable, mutable0_64, ok0))
    else:
        state = EngineState(**invariant, **mutable)
    return PrefixEpoch(state=state, count=count, guards_ok=guards,
                       slot=slot, phase=phase, cost=cost, lb=lb,
                       metrics=metrics, hists=tele.get("h"),
                       ledger=tele.get("l"), flight=tele.get("f"),
                       slo=tele.get("s"), prov=tele.get("p"))


class ChainEpoch(NamedTuple):
    """M chained prefix batches' output, compact for one readback."""

    state: EngineState
    count: jnp.ndarray       # int32[M] decisions committed per batch
    unit_count: jnp.ndarray  # int32[M]
    guards_ok: jnp.ndarray   # bool[M]
    slot: jnp.ndarray        # int32[M, k] unit clients (-1 pad)
    cls: jnp.ndarray         # int8[M, k]  unit entry class
    length: jnp.ndarray      # int8[M, k]  unit decisions
    metrics: jnp.ndarray     # int64[NUM_METRICS] (zeros unless
    #                          with_metrics)
    # telemetry plane (None unless the caller passed an accumulator)
    hists: object = None
    ledger: object = None
    flight: object = None
    slo: object = None
    prov: object = None


def scan_chain_epoch(state: EngineState, now, m: int, k: int, *,
                     chain_depth: int, anticipation_ns: int,
                     allow_limit_break: bool = False,
                     use_pallas: bool | None = None,
                     with_metrics: bool = False,
                     select_impl: str = "sort",
                     tag_width: int = 64,
                     hists=None, ledger=None,
                     flight=None, slo=None,
                     prov=None) -> ChainEpoch:
    """Run m chained prefix batches on device.  Each batch prefetches
    its own ``chain_depth``-row ring window (one barrel-shift ring
    pass per batch; a shared per-epoch window would need m *
    chain_depth rows of unrolled selects, which costs more than the
    rotate at chain depths > 1).  ``select_impl`` / ``tag_width`` /
    the ``hists``/``ledger``/``flight`` telemetry accumulators as in
    :func:`scan_prefix_epoch` (flight records here are per UNIT, the
    cost column carrying the unit's decision count)."""
    assert chain_depth <= state.ring_capacity
    assert tag_width in (32, 64), tag_width
    narrow32 = tag_width == 32
    invariant = {f: getattr(state, f) for f in _EPOCH_INVARIANT}
    mutable0_64 = {f: getattr(state, f) for f in _EPOCH_MUTABLE}
    met0 = obsdev.metrics_zero()
    tele0 = _tele_init(state, hists, ledger, flight, slo, prov)
    need_class = bool(tele0)
    if narrow32:
        tc = _TagCarry32(state)
        mutable0, ok0 = tc.narrow(mutable0_64)
        if with_metrics:
            met0 = obsdev.metrics_combine(met0, obsdev.metrics_delta(
                rebase_fallbacks=(~ok0).astype(jnp.int64)))
        carry0 = (mutable0, met0, tele0, ~ok0)
    else:
        carry0 = (mutable0_64, met0, tele0)

    def body(carry, _):
        if narrow32:
            mut, met, tele, dead = carry
            st = EngineState(**invariant, **tc.widen(mut))
        else:
            mut, met, tele = carry
            st = EngineState(**invariant, **mut)
        win = ring_window(st, chain_depth, use_pallas=use_pallas)
        batch = speculate_chain_batch(
            st, now, k, chain_depth=chain_depth,
            anticipation_ns=anticipation_ns,
            heads=(win.arr, win.cost),
            allow_limit_break=allow_limit_break,
            select_impl=select_impl)
        count, ucount = batch.count, batch.unit_count
        guards = batch.guards_ok
        slot = batch.slot
        cls = batch.cls.astype(jnp.int8)
        length = batch.length.astype(jnp.int8)
        new_mut = {f: getattr(batch.state, f) for f in _EPOCH_MUTABLE}
        trip = jnp.bool_(False)
        good = jnp.bool_(True)
        if narrow32:
            mut, dead, good, trip, \
                (count, ucount, guards, slot, cls, length) = tc.gate(
                    dead, mut, new_mut,
                    [(count, 0), (ucount, 0), (guards, False),
                     (slot, -1), (cls, jnp.int8(CLS_NONE)),
                     (length, jnp.int8(0))])
        else:
            mut = new_mut
        out = (count, ucount, guards, slot, cls, length)
        if with_metrics:
            units = slot >= 0
            # a unit's entry serve is weight-phase iff class >= 1; its
            # induced serves are all constraint-phase
            prop = jnp.sum(jnp.where(units, (cls >= CLS_WEIGHT)
                                     .astype(jnp.int64), 0))
            met = _batch_metrics(
                met, batch.state, count=count,
                resv=count.astype(jnp.int64) - prop, prop=prop,
                lb=jnp.sum(units & (cls >= CLS_LB)),
                guards_ok=batch.guards_ok, rebase_fallback=trip,
                live=good)
        if need_class:
            tele, key_e, gate_n = _tele_entry_fold(
                tele, st, batch.state, now, allow_limit_break,
                batch.count, good, cost_pc=batch.cost_pc,
                margins=batch.margins)
            tele = _tele_flight(
                tele, slot, cls.astype(jnp.int64),
                jnp.take(key_e, jnp.maximum(slot, 0)),
                length.astype(jnp.int64), good,
                margin=batch.margins, gate=gate_n)
        carry = (mut, met, tele, dead) if narrow32 \
            else (mut, met, tele)
        return carry, out

    carry, (count, units, guards, slot, cls, length) = \
        lax.scan(body, carry0, None, length=m)
    mutable, metrics, tele = carry[0], carry[1], carry[2]
    if narrow32:
        state = EngineState(**invariant,
                            **tc.restore(mutable, mutable0_64, ok0))
    else:
        state = EngineState(**invariant, **mutable)
    return ChainEpoch(state=state, count=count, unit_count=units,
                      guards_ok=guards, slot=slot, cls=cls,
                      length=length, metrics=metrics,
                      hists=tele.get("h"), ledger=tele.get("l"),
                      flight=tele.get("f"), slo=tele.get("s"),
                      prov=tele.get("p"))


# Module-level jit cache for the host-orchestrated prefix runner (the
# engine/queue.py convention, compile-plane-instrumented): repeated
# make_prefix_runner calls at one static config share one compiled
# attempt/exact pair instead of re-tracing per runner.
_RUNNER_JIT_CACHE: dict = {}


def _runner_jit(key: tuple, make):
    if key not in _RUNNER_JIT_CACHE:
        from ..obs import compile_plane as _cplane
        _RUNNER_JIT_CACHE[key] = _cplane.instrumented_jit(
            make(), cache="fastpath.runner", entry=key)
    return _RUNNER_JIT_CACHE[key]


def make_prefix_runner(k: int, *, anticipation_ns: int = 0,
                       allow_limit_break: bool = False,
                       select_impl: str = "sort"):
    """Host-orchestrated prefix runner: (state, now) -> (state,
    decisions, n_committed).  The serial engine is needed only when the
    global rebase guards fail (creation-order spread or a served cost
    past 2^31 -- never observed in practice); a zero count with guards
    intact means nothing is eligible at ``now`` (serial FUTURE/NONE).
    """
    attempt = _runner_jit(
        ("attempt", k, anticipation_ns, allow_limit_break,
         select_impl),
        lambda: functools.partial(
            speculate_prefix_batch, k=k,
            anticipation_ns=anticipation_ns,
            allow_limit_break=allow_limit_break,
            select_impl=select_impl))
    exact = _runner_jit(
        ("exact", k, anticipation_ns, allow_limit_break),
        lambda: lambda s, t: kernels.engine_run(
            s, t, k, allow_limit_break=allow_limit_break,
            anticipation_ns=anticipation_ns, advance_now=False))

    def run(state: EngineState, now):
        batch = attempt(state, now)
        if not bool(batch.guards_ok):
            st, _, decs = exact(state, now)
            d = jax.device_get(decs)
            return st, decs, int((d.type == RETURNING).sum())
        return batch.state, batch.decisions, int(batch.count)

    return run


# ----------------------------------------------------------------------
# calendar commit: sortless window batches
# ----------------------------------------------------------------------
#
# The sort-based prefix batch tops out when re-entries undercut the
# sorted tail: a Zipf weight-64 client re-enters every 2*winv_64 ns of
# proportion-tag space, so a single sort commits only the entries
# inside that window (~2.5k of 100k at the cfg4 steady state).  The
# calendar batch removes the sort entirely, from two structural facts:
#
#  1. The serial engine's SERVED unified keys are nondecreasing: it
#     always serves the global minimum, and a serve's re-entry key is
#     above its entry key (per-client tags are monotone under serves;
#     the one exception -- a weight serve's reservation-debt reduction
#     dropping the client into class 0 -- is absorbed into the serving
#     UNIT exactly as in the chained batches, and unit ENTRY keys are
#     nondecreasing per client, enforced below).
#  2. Therefore, for ANY boundary B, the set {serves whose unit entry
#     key < B} is exactly a prefix of the serial order -- computable
#     PER CLIENT by iterating its own tag recurrence, independent of
#     every other client.
#
# A client that cannot be followed past some point (serve-step budget
# exhausted, a unit's induced-serve chain cut mid-way, a non-monotone
# next entry) contributes its first unfollowable entry key as a STOP;
# B_eff = min over stops, and two dense passes (measure stops, then
# commit gated on B_eff) yield up to `steps` decisions per client per
# batch with no [k] cap and no 32-bit rebase guards (keys pack into a
# 58-bit per-class window that never clamps in practice; clamping is
# monotone and therefore only conservative).  The batch emits
# per-client counts, not an ordered stream: the committed SET plus the
# final state is exact (differentially pinned vs the serial engine);
# callers needing the ordered stream use the sort-based batches.

_CAL_BIAS = jnp.int64(1) << 57
_CAL_MASK = (jnp.int64(1) << 58) - 1


class CalendarBatch(NamedTuple):
    """Result of one calendar-commit batch."""

    state: EngineState
    count: jnp.ndarray        # int32 committed decisions
    resv_count: jnp.ndarray   # int32 constraint-phase decisions
    units: jnp.ndarray        # int32[N] committed units per client
    served: jnp.ndarray       # int32[N] committed decisions per client
    served_resv: jnp.ndarray  # int32[N] constraint decisions
    lb: jnp.ndarray           # int32[N] limit-break entries (Allow)
    progress_ok: jnp.ndarray  # bool: count>0 or no candidate existed
    served_cost: object = None  # int64[N] delivered cost per client
    margin: object = None     # int64[N] boundary-distance margin per
    #                           served client: B_eff minus the
    #                           client's LAST unit-entry pack, ns for
    #                           same-class keys (-1 = not served or
    #                           no finite boundary; obs.provenance)


def _cal_pack(cls, key, kresv, kprop1, kprop2):
    origin = jnp.where(cls == CLS_RESV, kresv,
                       jnp.where(cls == CLS_WEIGHT, kprop1, kprop2))
    rel = jnp.clip(key - origin + _CAL_BIAS, 0, _CAL_MASK)
    return jnp.where(cls == CLS_NONE, jnp.int64(KEY_INF),
                     (cls.astype(jnp.int64) << 58) | rel)


def _calendar_pass(state: EngineState, now, arr_rows, cost_rows,
                   allow: bool, anticipation_ns: int,
                   kresv, kprop1, kprop2, b_eff):
    """One dense pass of per-client serve iteration, as a lax.scan
    over the step axis (an unrolled step loop at steps=32 exploded
    compile time through the remote compiler).

    With ``b_eff`` None: measure mode -- serve everything followable
    and return the per-client STOP pack (KEY_INF when the client ran
    out of work).  With ``b_eff`` a scalar: commit mode -- serves gate
    on the unit entry pack being strictly below it; returns the final
    dense state fields and the per-client counters.

    Readiness is classified as ``limit <= now`` at every step: a
    stored ready flag implies it under the monotonic-now restriction
    (promotion happened at some now' <= now with limit <= now', and
    pops clear the flag), so the stored bit adds nothing here."""
    n = state.capacity

    carry0 = dict(
        h_resv=state.head_resv, h_prop=state.head_prop,
        h_limit=state.head_limit, h_arr=state.head_arrival,
        h_cost=state.head_cost, h_rho=state.head_rho,
        p_resv=state.prev_resv, p_prop=state.prev_prop,
        p_limit=state.prev_limit, p_arr=state.prev_arrival,
        depth=state.depth,
        qadv=jnp.zeros_like(state.q_head),
        cost=jnp.zeros_like(state.head_cost),
        alive=jnp.ones((n,), dtype=bool),
        in_unit=jnp.zeros((n,), dtype=bool),
        stop_pk=jnp.full((n,), jnp.int64(KEY_INF)),
        prev_pk=jnp.full((n,), jnp.int64(-1)),
        unit_cls=jnp.zeros((n,), dtype=jnp.int32),
        units=jnp.zeros((n,), dtype=jnp.int32),
        served=jnp.zeros((n,), dtype=jnp.int32),
        served_resv=jnp.zeros((n,), dtype=jnp.int32),
        lb=jnp.zeros((n,), dtype=jnp.int32),
    )

    def step(c, row):
        narr, ncost = row
        has = state.active & (c["depth"] > 0)
        cls, key = _unified_class(
            now, has, c["h_resv"], c["h_limit"] <= now, c["h_prop"],
            c["h_prop"] + state.prop_delta, allow)
        pk = _cal_pack(cls, key, kresv, kprop1, kprop2)

        at_boundary = ~c["in_unit"]
        cand = cls != CLS_NONE
        alive = c["alive"]
        nonmono = alive & at_boundary & cand & (pk < c["prev_pk"])
        stop_pk = c["stop_pk"]
        if b_eff is None:
            stop_pk = jnp.where(
                nonmono, jnp.minimum(stop_pk, c["prev_pk"]), stop_pk)
        alive = alive & ~(at_boundary & (~cand | nonmono))
        start = alive & at_boundary & cand
        if b_eff is not None:
            start = start & (pk < b_eff)
            alive = alive & ~(at_boundary & ~start)

        serve = start | (c["in_unit"] & alive)
        phase1 = start & (cls >= CLS_WEIGHT)

        nr, np_, nl = _make_tag(
            c["h_resv"], c["h_prop"], c["h_limit"], c["h_arr"],
            state.resv_inv, state.weight_inv, state.limit_inv,
            state.cur_delta, state.cur_rho, narr, ncost,
            anticipation_ns)
        off = jnp.where(phase1,
                        state.resv_inv * (c["h_cost"] + c["h_rho"]),
                        jnp.zeros_like(c["h_resv"]))
        new_depth = c["depth"] - 1
        has_more = new_depth > 0
        upd = serve
        updh = serve & has_more
        new_h_resv = nr - off
        pr = jnp.where(has_more, _fold_prev(c["p_resv"], nr),
                       c["p_resv"]) - off
        pp = jnp.where(has_more, _fold_prev(c["p_prop"], np_),
                       c["p_prop"])
        pl_ = jnp.where(has_more, _fold_prev(c["p_limit"], nl),
                        c["p_limit"])

        chains_cls = (cls == CLS_WEIGHT) | (cls == CLS_LB)
        unit_cls = jnp.where(start, cls, c["unit_cls"])
        cont_cls = (unit_cls == CLS_WEIGHT) | (unit_cls == CLS_LB)

        new = dict(
            h_resv=jnp.where(updh, new_h_resv, c["h_resv"]),
            h_prop=jnp.where(updh, np_, c["h_prop"]),
            h_limit=jnp.where(updh, nl, c["h_limit"]),
            h_arr=jnp.where(updh, narr, c["h_arr"]),
            h_cost=jnp.where(updh, ncost, c["h_cost"]),
            h_rho=jnp.where(updh, state.cur_rho, c["h_rho"]),
            p_resv=jnp.where(upd, pr, c["p_resv"]),
            p_prop=jnp.where(upd, pp, c["p_prop"]),
            p_limit=jnp.where(upd, pl_, c["p_limit"]),
            p_arr=jnp.where(updh, narr, c["p_arr"]),
            depth=jnp.where(upd, new_depth,
                            c["depth"]).astype(jnp.int32),
            qadv=(c["qadv"] + updh).astype(jnp.int32),
            # delivered cost: the head served at this step is the
            # CURRENT h_cost (the SLO window block's cost column)
            cost=c["cost"] + jnp.where(serve, c["h_cost"],
                                       jnp.int64(0)),
            alive=alive,
            in_unit=serve & cont_cls & has_more & (new_h_resv <= now),
            stop_pk=stop_pk,
            prev_pk=jnp.where(start, pk, c["prev_pk"]),
            unit_cls=unit_cls,
            units=c["units"] + start,
            served=c["served"] + serve,
            served_resv=c["served_resv"]
            + ((start & (cls == CLS_RESV)) | (serve & c["in_unit"])),
            lb=c["lb"] + (start & (cls >= CLS_LB)),
        )
        return new, None

    rows = (jnp.stack(arr_rows), jnp.stack(cost_rows))
    c, _ = lax.scan(step, carry0, rows)

    if b_eff is None:
        # post-loop stops: a chain still mid-unit cannot be followed
        # (exclude its whole unit); an alive client at a unit boundary
        # stops at its NEXT entry key.
        stop_pk = jnp.where(c["in_unit"],
                            jnp.minimum(c["stop_pk"], c["prev_pk"]),
                            c["stop_pk"])
        has = state.active & (c["depth"] > 0)
        cls, key = _unified_class(
            now, has, c["h_resv"], c["h_limit"] <= now, c["h_prop"],
            c["h_prop"] + state.prop_delta, allow)
        pk = _cal_pack(cls, key, kresv, kprop1, kprop2)
        boundary_stop = c["alive"] & ~c["in_unit"] & (cls != CLS_NONE)
        nonmono_next = boundary_stop & (pk < c["prev_pk"])
        stop_pk = jnp.where(
            boundary_stop,
            jnp.minimum(stop_pk,
                        jnp.where(nonmono_next, c["prev_pk"], pk)),
            stop_pk)
        return stop_pk

    fields = dict(head_resv=c["h_resv"], head_prop=c["h_prop"],
                  head_limit=c["h_limit"], head_arrival=c["h_arr"],
                  head_cost=c["h_cost"], head_rho=c["h_rho"],
                  prev_resv=c["p_resv"], prev_prop=c["p_prop"],
                  prev_limit=c["p_limit"], prev_arrival=c["p_arr"],
                  depth=c["depth"])
    return (fields, c["qadv"], c["units"], c["served"],
            c["served_resv"], c["lb"], c["prev_pk"], c["unit_cls"],
            c["cost"])


def _calendar_batch_core(state: EngineState, now, arr_rows, cost_rows,
                         *, anticipation_ns: int,
                         allow_limit_break: bool,
                         origins=None, stop_min=None):
    """The measure + boundary + commit + promote pipeline of one
    calendar batch, given the prefetched window rows.  Shared by
    :func:`calendar_batch` (one boundary per launch) and the bucketed
    ladder (L fused boundaries per launch).

    The boundary is the stop-key distribution's FIRST order statistic
    -- what ``kernels.radix_kth_key(stop_pk, 1)`` computes -- read as
    a plain ``jnp.min``: the same value for 16x fewer dense passes,
    and this stack's CPU backend miscompiles the histogram walk inside
    the sharded device sim (deterministic compiler SIGFPE, see
    tests/test_calendar_bucketed.py's device-sim note).  The histogram
    rounds proper serve where ranks beyond 1 are genuinely needed: the
    quantile planner (:func:`calendar_stop_ladder`).

    ``origins`` injects precomputed ``(kresv, kprop1, kprop2,
    any_cand)`` pack origins -- the wheel ladder reads them from its
    maintained bucket index in O(buckets) instead of the dense
    per-class mins here.  ``stop_min`` likewise replaces the dense
    ``jnp.min`` boundary with the wheel's occupancy-min-scan.  Both
    must be BIT-IDENTICAL to the dense reductions they replace (the
    wheel exactness argument, see the kernels wheel section).

    Returns ``(CalendarBatch, b_eff, stop_pk)``."""
    if origins is None:
        cls0, key0 = _classify(state, now, allow_limit_break)
        kresv = jnp.min(jnp.where(cls0 == CLS_RESV, key0, KEY_INF))
        kprop1 = jnp.min(jnp.where(cls0 == CLS_WEIGHT, key0, KEY_INF))
        kprop2 = jnp.min(jnp.where(cls0 == CLS_LB, key0, KEY_INF))
        any_cand = jnp.any(cls0 != CLS_NONE)
    else:
        kresv, kprop1, kprop2, any_cand = origins

    stop_pk = _calendar_pass(state, now, arr_rows, cost_rows,
                             allow_limit_break, anticipation_ns,
                             kresv, kprop1, kprop2, None)
    b_eff = jnp.min(stop_pk) if stop_min is None else stop_min(stop_pk)
    (fields, qadv, units, served, served_resv, lb, last_pk,
     last_cls, cost_pc) = _calendar_pass(
         state, now, arr_rows, cost_rows, allow_limit_break,
         anticipation_ns, kresv, kprop1, kprop2, b_eff)

    did = served > 0
    popped = did & (qadv > 0)

    def pick(pred, new, old):
        return jnp.where(pred, new, old)

    new_state = state._replace(
        depth=pick(did, fields["depth"], state.depth),
        q_head=pick(popped,
                    (state.q_head + qadv) % state.ring_capacity,
                    state.q_head).astype(jnp.int32),
        head_resv=pick(popped, fields["head_resv"], state.head_resv),
        head_prop=pick(popped, fields["head_prop"], state.head_prop),
        head_limit=pick(popped, fields["head_limit"],
                        state.head_limit),
        head_arrival=pick(popped, fields["head_arrival"],
                          state.head_arrival),
        head_cost=pick(popped, fields["head_cost"], state.head_cost),
        head_rho=pick(popped, fields["head_rho"], state.head_rho),
        head_ready=state.head_ready & ~did,
        prev_resv=pick(did, fields["prev_resv"], state.prev_resv),
        prev_prop=pick(did, fields["prev_prop"], state.prev_prop),
        prev_limit=pick(did, fields["prev_limit"], state.prev_limit),
        prev_arrival=pick(popped, fields["prev_arrival"],
                          state.prev_arrival),
    )

    # stored-flag parity (promote loop): the batch's LAST serial
    # decision is the unit with the max entry pack (ties by creation
    # order); if its class is >= 1, its entry ran the final promote
    # pass, whose only unseen head is the one that unit's own chain
    # popped into place.
    lp = jnp.where(did, last_pk, jnp.int64(-1))
    maxpk = jnp.max(lp)
    tied = did & (lp == maxpk)
    excl = jnp.argmax(jnp.where(tied, state.order,
                                jnp.int64(-1))).astype(jnp.int32)
    cls_last = jnp.max(jnp.where(tied, last_cls, -1))
    do_promote = jnp.any(did) & (cls_last >= CLS_WEIGHT)
    has_req_after = new_state.active & (new_state.depth > 0)
    promoted = new_state.head_ready | \
        (has_req_after & (new_state.head_limit <= now))
    promoted = promoted & (
        jnp.arange(state.capacity, dtype=jnp.int32) != excl)
    new_state = new_state._replace(head_ready=jnp.where(
        do_promote, promoted, new_state.head_ready))

    count = jnp.sum(served).astype(jnp.int32)
    # boundary-distance margin (obs.provenance): how much headroom
    # B_eff left each served client's LAST unit entry -- the calendar
    # analog of the sorted engines' runner-up margin (the boundary IS
    # the first unfollowable competitor).  Dead code unless a
    # provenance/flight consumer reads it (XLA DCE).
    margin = jnp.where((served > 0) & (b_eff < jnp.int64(KEY_INF)),
                       b_eff - last_pk, jnp.int64(-1))
    batch = CalendarBatch(
        state=new_state, count=count,
        resv_count=jnp.sum(served_resv).astype(jnp.int32),
        units=units, served=served, served_resv=served_resv, lb=lb,
        progress_ok=(count > 0) | ~any_cand,
        served_cost=jnp.where(served > 0, cost_pc, jnp.int64(0)),
        margin=margin)
    return batch, b_eff, stop_pk


def calendar_batch(state: EngineState, now, *, steps: int,
                   anticipation_ns: int = 0,
                   allow_limit_break: bool = False,
                   heads=None) -> CalendarBatch:
    """One calendar-commit batch: up to ``steps`` decisions PER CLIENT
    in two dense elementwise passes, no sort (see section comment).

    The committed set is exactly the serial engine's next ``count``
    decisions (differentially pinned by tests/test_prefix.py's
    calendar suite); the emission is per-client counts + final state.
    ``progress_ok`` False (count 0 with candidates present) happens
    only when the very first serial unit is unfollowable (its induced
    chain exceeds ``steps``): fall back to the serial engine."""
    assert steps <= state.ring_capacity, \
        "calendar steps exceed the ring window"
    if heads is None:
        win = ring_window(state, steps)
        heads = (win.arr, win.cost)
    arr_rows, cost_rows = _heads_rows(heads, steps)
    batch, _, _ = _calendar_batch_core(
        state, now, arr_rows, cost_rows,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break)
    return batch


# ----------------------------------------------------------------------
# bucketed calendar commits: the histogram stop-key ladder
# ----------------------------------------------------------------------
#
# The minstop boundary B_eff = min over per-client stop keys lets the
# single most conservative client truncate the whole batch: on a Zipf
# population the heavy client exhausts its `steps` budget at a low key
# while most clients could be followed far past it, so each launch
# commits one thin slab of the key space and pays a fresh dispatch for
# the next.  The bucketed ladder fuses L successive boundaries into
# ONE launch: a lax.scan over ladder levels where every level
# re-prefetches the ring window from the committed state (REFRESHED
# per-client step budgets -- the budget-stopped blocker continues from
# where it stood), measures fresh stop keys, takes the level boundary
# B_i = the stop distribution's first order statistic, and commits the
# exact serial prefix < B_i.  Level i therefore starts from exactly the
# serial state at B_{i-1}, so the concatenated committed sets are one
# serial prefix and the classical minstop exactness argument applies
# per level -- one device launch commits what previously took L full
# measure+commit batches.
#
# Why each level's boundary is its own refreshed min-stop and not a
# raw CDF quantile of the FIRST measure's stops: a stop key is a hard
# followability limit -- committing past a budget-stopped client's
# stop would emit other clients' serves the serial engine orders
# AFTER the blocker's unmeasured ones (not a prefix, not exact).
# Refreshing the budget is what discharges a stop, and only the
# level's own measure can prove it discharged.  The stop-key CDF
# ladder (``calendar_stop_ladder``, kernels.radix_quantile_ladder) is
# the PLANNER view of the same histogram: it predicts where the
# refreshed levels will land (on a skewed population the achieved
# boundaries track the stop quantiles) and prices a ladder depth L
# before running it; the commit path keeps the provable boundary.

_CAL_IMPLS = ("minstop", "bucketed", "wheel")


# ----------------------------------------------------------------------
# the timer-wheel calendar: a maintained bucket index over the tags
# ----------------------------------------------------------------------
#
# calendar_impl="wheel" keeps the bucketed ladder's commit structure
# (L refreshed-budget boundaries per launch) but replaces its dense
# O(N) reductions with O(buckets) reads of a MAINTAINED calendar
# wheel: three per-class bucket wheels (occupancy count + exact min
# key per bucket) built once per batch, then adjusted IN PLACE
# between ladder levels -- only the clients a commit actually moved
# re-slot; the rest of the population is never touched.  The level
# boundary B_i comes from a transient stop-key wheel: bucket-scatter
# the per-client stop packs and read the first occupied bucket's min
# (the occupancy-min-scan) -- the shape hand-written as the repo's
# first Pallas kernel (engine.kernels_pallas), behind the
# ``wheel_kernel`` switch with a counted XLA fallback.
#
# Exactness is inherited, not re-proven: every wheel read is
# bit-identical to the dense reduction it replaces (first occupied
# bucket's stored min == global masked min, because bucketing is
# monotone in the key -- kernels.py wheel section), so the committed
# stream, state, metrics, and telemetry equal bucketed-L and hence
# the serial engine exactly (ci.sh wheel digest gates).  The in-place
# adjust is exact because at FIXED now an unserved client's (class,
# key) cannot change across a commit: readiness is ``limit <= now``
# under monotone now (the stored head_ready bit adds nothing, see
# _calendar_pass), and the promote pass only flips stored bits that
# _ready_now already implied.  Re-slotting exactly the served clients
# therefore reproduces a full rebuild bit-for-bit (the adjust ==
# rebuild pin in tests/test_calendar_wheel.py).

_WHEEL_KERNELS = ("xla", "pallas")
_WHEEL_BUCKETS = 256
_WHEEL_SHIFT = 20        # 2^20 ns ~ 1ms fine buckets, ~268ms span
_WHEEL_STOP_SHIFT = 52   # stop packs live in [0, 2^60): 256 buckets


def _wheel_resolve(wheel_kernel: str, n: int):
    """STATIC resolution of the ``wheel_kernel`` switch: returns
    ``(scan_fn, fallback)`` with ``scan_fn(keys, slot, nb)`` matching
    :func:`kernels.wheel_scan`.  "pallas" resolves to the real kernel
    on TPU backends, to interpret mode anywhere when
    ``DMCLOCK_WHEEL_INTERPRET=1`` (the CI parity path), and otherwise
    falls back to the XLA reference with ``fallback=True`` -- counted
    per live batch in the pallas_fallbacks metric row, so a fleet
    silently running the fallback is visible in /metrics."""
    if wheel_kernel not in _WHEEL_KERNELS:
        raise ValueError(f"unknown wheel_kernel {wheel_kernel!r} "
                         f"(one of {_WHEEL_KERNELS})")
    if wheel_kernel == "pallas":
        interpret = os.environ.get("DMCLOCK_WHEEL_INTERPRET") == "1"
        if kernels_pallas.wheel_supported(n, 3 * _WHEEL_BUCKETS) and \
                (interpret or jax.default_backend() == "tpu"):
            return (functools.partial(kernels_pallas.wheel_scan_pallas,
                                      interpret=interpret), False)
        return kernels.wheel_scan, True
    return kernels.wheel_scan, False


class WheelIndex(NamedTuple):
    """The maintained calendar wheel: three class wheels of
    ``_WHEEL_BUCKETS`` buckets each, concatenated on one axis
    (slot = cls * B + bucket; 3B = unslotted), plus the per-client
    slot/key mirror that makes the in-place adjust self-contained."""

    origin: jnp.ndarray   # int64 bucket-0 left edge (all 3 wheels)
    cnt: jnp.ndarray      # int32[3B] occupancy per (class, bucket)
    bmin: jnp.ndarray     # int64[3B] exact min key per bucket
    slot: jnp.ndarray     # int32[N] current slot (3B = unslotted)
    key: jnp.ndarray      # int64[N] slotted key (where slot < 3B)
    reslots: jnp.ndarray  # int64 in-place re-slots since build
    hwm: jnp.ndarray      # int64 bucket-occupancy high-water mark


def _wheel_slots(cls, key, origin):
    """(class, key) -> wheel slot; non-candidates unslot (3B)."""
    b = kernels.wheel_slot(key, origin, _WHEEL_SHIFT, _WHEEL_BUCKETS)
    return jnp.where(cls == CLS_NONE,
                     jnp.int32(3 * _WHEEL_BUCKETS),
                     cls * _WHEEL_BUCKETS + b).astype(jnp.int32)


def wheel_build(state: EngineState, now, allow: bool, *,
                scan_fn=kernels.wheel_scan) -> WheelIndex:
    """Full O(N) bucket-scatter of the entry classification -- once
    per batch; levels and API events adjust in place from here."""
    cls, key = _classify(state, now, allow)
    origin = now - (jnp.int64(_WHEEL_BUCKETS // 2)
                    << _WHEEL_SHIFT)
    slot = _wheel_slots(cls, key, origin)
    cnt, bmin, _val, _found = scan_fn(key, slot, 3 * _WHEEL_BUCKETS)
    return WheelIndex(origin=origin, cnt=cnt, bmin=bmin, slot=slot,
                      key=key, reslots=jnp.int64(0),
                      hwm=jnp.max(cnt).astype(jnp.int64))


def wheel_origins(w: WheelIndex):
    """Batch-entry pack origins read from the wheel in O(buckets):
    per class, the first occupied bucket's stored min -- bit-equal to
    the dense masked min ``_calendar_batch_core`` would compute.
    Returns ``(kresv, kprop1, kprop2, any_cand)``."""
    b = _WHEEL_BUCKETS
    vals, founds = [], []
    for c in range(3):
        v, _b0, f = kernels.wheel_nearest(w.cnt[c * b:(c + 1) * b],
                                          w.bmin[c * b:(c + 1) * b])
        vals.append(v)
        founds.append(f)
    return vals[0], vals[1], vals[2], founds[0] | founds[1] | founds[2]


def wheel_adjust(w: WheelIndex, state: EngineState, now, allow: bool,
                 moved) -> WheelIndex:
    """In-place re-slot of exactly the ``moved`` clients: decrement
    their old buckets, increment the new ones, and recompute the min
    of ONLY the touched buckets from the stored keys.  Every
    untouched bucket keeps its count and min bit-identically, so the
    result equals :func:`wheel_build` of the new state whenever
    ``moved`` covers every client whose (class, key) changed -- the
    served set of a fixed-now commit, a live QoS update's target, an
    idle re-entry, a churn re-slot (section comment; pinned by
    tests/test_calendar_wheel.py's adjust == rebuild gates)."""
    nb = 3 * _WHEEL_BUCKETS
    cls, key = _classify(state, now, allow)
    new_slot = _wheel_slots(cls, key, w.origin)
    slot2 = jnp.where(moved, new_slot, w.slot)
    key2 = jnp.where(moved, key, w.key)
    out_s = jnp.where(moved, w.slot, jnp.int32(nb))
    in_s = jnp.where(moved, slot2, jnp.int32(nb))
    cnt2 = w.cnt.at[out_s].add(jnp.int32(-1), mode="drop") \
                .at[in_s].add(jnp.int32(1), mode="drop")
    touched = jnp.zeros((nb,), bool) \
        .at[out_s].set(True, mode="drop") \
        .at[in_s].set(True, mode="drop")
    fresh = jnp.full((nb,), jnp.int64(KEY_INF)) \
        .at[slot2].min(key2, mode="drop")
    bmin2 = jnp.where(touched, fresh, w.bmin)
    changed = moved & ((slot2 != w.slot) | (key2 != w.key))
    return WheelIndex(
        origin=w.origin, cnt=cnt2, bmin=bmin2, slot=slot2, key=key2,
        reslots=w.reslots + jnp.sum(changed, dtype=jnp.int64),
        hwm=jnp.maximum(w.hwm, jnp.max(cnt2).astype(jnp.int64)))


def _wheel_stop_min(stop_pk, scan_fn):
    """The level boundary B_eff as the stop wheel's fused
    bucket-scatter + occupancy-min-scan (the Pallas kernel's shape)
    -- bit-identical to ``jnp.min(stop_pk)``: stop packs are
    non-negative and below 2^60, so 256 buckets of 2^52 cover the
    space exactly and the first occupied bucket's min IS the global
    min; all-KEY_INF distributions return KEY_INF like the dense
    min."""
    finite = stop_pk < jnp.int64(KEY_INF)
    slot = jnp.where(
        finite,
        kernels.wheel_slot(stop_pk, jnp.int64(0), _WHEEL_STOP_SHIFT,
                           _WHEEL_BUCKETS),
        jnp.int32(_WHEEL_BUCKETS))
    _cnt, _bmin, val, _found = scan_fn(stop_pk, slot, _WHEEL_BUCKETS)
    return val


class CalendarLadderBatch(NamedTuple):
    """Result of one bucketed calendar batch (L fused ladder levels).

    Totals aggregate over every level; the committed set is one serial
    prefix of ``count`` decisions (level i starts from the committed
    state of level i-1), so the differential contract is exactly
    :class:`CalendarBatch`'s with more committed per launch."""

    state: EngineState
    count: jnp.ndarray        # int32 committed decisions (all levels)
    resv_count: jnp.ndarray   # int32 constraint-phase decisions
    units: jnp.ndarray        # int32[N] committed units per client
    served: jnp.ndarray       # int32[N] committed decisions per client
    served_resv: jnp.ndarray  # int32[N] constraint decisions
    lb: jnp.ndarray           # int32[N] limit-break entries (Allow)
    progress_ok: jnp.ndarray  # bool: level 0 committed or had no
    #                           candidate (same fallback contract as
    #                           CalendarBatch.progress_ok)
    level_count: jnp.ndarray  # int32[L] decisions per ladder level
    level_bound: jnp.ndarray  # int64[L] committed boundary per level
    level_stall: jnp.ndarray  # bool[L] committed 0 with candidates
    #                           present (a mid-ladder stall wastes the
    #                           remaining levels; metric row
    #                           calendar_ladder_fallbacks)
    served_cost: object = None  # int64[N] delivered cost (all levels)


def _calendar_ladder_scan(invariant: dict, mut: dict, now, *,
                          steps: int, levels: int,
                          anticipation_ns: int, allow: bool,
                          use_pallas, with_hists: bool = False,
                          with_ledger: bool = False,
                          with_slo: bool = False,
                          prov0=None, wheel_scan_fn=None):
    """The fused ladder: a lax.scan over L levels, each a full
    window-prefetch + measure + histogram boundary + commit from the
    previous level's committed state.  Carries only the mutable epoch
    fields (the ring pair and QoS identity stay loop-invariant,
    exactly like the epoch scans).  Returns ``(mut', acc, tele_delta,
    outs, wstats)`` with ``acc`` the [N] per-client counters summed over
    levels, ``tele_delta`` the zero-based histogram/ledger deltas
    accumulated per LEVEL (so a level equals one minstop batch and
    bucketed-L telemetry equals the L-batch composition exactly; the
    caller folds the deltas gated on batch liveness), and ``outs`` the
    per-level (count, resv_count, bound, stall) stacks.  ``prov0``
    (an ``obs.provenance.ProvBlock``) threads the provenance block
    through the levels as FULL STATE (not a delta): each level
    observes its own entry classification and boundary margins, and
    the caller selects the returned block against the entry block on
    batch liveness.

    ``wheel_scan_fn`` (static, a :func:`kernels.wheel_scan`-shaped
    callable) switches the ladder to the WHEEL calendar: one bucket
    index built at entry, per-level origins/boundary read from it in
    O(buckets), and only each level's served clients re-slotted in
    place (see the wheel section comment -- every read is bit-equal
    to the dense reduction it replaces, so the committed stream is
    unchanged).  ``wstats`` is then ``(reslots, occ_hwm)`` int64
    scalars for the metrics plane, else None."""
    n = invariant["active"].shape[-1]
    acc0 = dict(units=jnp.zeros((n,), jnp.int32),
                served=jnp.zeros((n,), jnp.int32),
                served_resv=jnp.zeros((n,), jnp.int32),
                lb=jnp.zeros((n,), jnp.int32),
                cost=jnp.zeros((n,), jnp.int64),
                # newest boundary-distance margin per client across
                # levels (-1 = never observed): the flight record's
                # margin column for the whole bucketed batch
                margin=jnp.full((n,), jnp.int64(-1)))
    tacc0 = {}
    if with_hists:
        tacc0["h"] = obshist.hist_zero()
    if with_ledger:
        tacc0["l"] = obshist.ledger_zero(n)
    if with_slo:
        tacc0["s"] = obsslo.window_zero(n)
    if prov0 is not None:
        tacc0["p"] = prov0

    wheel0 = None
    if wheel_scan_fn is not None:
        wheel0 = wheel_build(EngineState(**invariant, **mut), now,
                             allow, scan_fn=wheel_scan_fn)

    def level(carry, _):
        if wheel_scan_fn is not None:
            mut, acc, tacc, w = carry
        else:
            mut, acc, tacc = carry
            w = None
        st = EngineState(**invariant, **mut)
        win = ring_window(st, steps, use_pallas=use_pallas)
        arr_rows, cost_rows = _heads_rows((win.arr, win.cost), steps)
        batch, b_eff, _ = _calendar_batch_core(
            st, now, arr_rows, cost_rows,
            anticipation_ns=anticipation_ns, allow_limit_break=allow,
            origins=None if w is None else wheel_origins(w),
            stop_min=None if w is None else functools.partial(
                _wheel_stop_min, scan_fn=wheel_scan_fn))
        if w is not None:
            # fixed-now commit: exactly the served clients moved
            w = wheel_adjust(w, batch.state, now, allow,
                             batch.served > 0)
        new_mut = {f: getattr(batch.state, f) for f in _EPOCH_MUTABLE}
        acc = dict(units=acc["units"] + batch.units,
                   served=acc["served"] + batch.served,
                   served_resv=acc["served_resv"] + batch.served_resv,
                   lb=acc["lb"] + batch.lb,
                   cost=acc["cost"] + batch.served_cost,
                   margin=jnp.where(batch.margin >= 0, batch.margin,
                                    acc["margin"]))
        if with_hists or with_ledger or with_slo or prov0 is not None:
            # per-LEVEL entry classification: level i starts from the
            # exact serial state at boundary i-1, so these are the
            # same observations L sequential minstop batches would
            # record
            cls_e, key_e = _classify(st, now, allow)
            hd, ld, sd = _telemetry_delta(
                batch.state, now, cls_e, key_e, batch.served,
                batch.served_resv, batch.lb, batch.count,
                with_hists, with_ledger,
                cost_pc=batch.served_cost, with_slo=with_slo)
            tacc = dict(tacc)
            if with_hists:
                tacc["h"] = obshist.hist_combine(tacc["h"], hd)
            if with_ledger:
                tacc["l"] = obshist.ledger_combine(tacc["l"], ld)
            if with_slo:
                tacc["s"] = obsslo.window_combine(tacc["s"], sd)
            if prov0 is not None:
                has_req = st.active & (st.depth > 0)
                elig = cls_e != CLS_NONE
                tacc["p"] = obsprov.prov_observe(
                    tacc["p"], now=now, elig=elig,
                    gated=has_req & ~elig,
                    win_cls=jnp.min(jnp.where(elig, cls_e, CLS_NONE)),
                    served_pc=batch.served, margins=batch.margin)
        # a level that commits nothing WITH candidates present is a
        # ladder stall: progress_ok's per-level analog (later levels
        # deterministically repeat it -- same state, same boundary)
        stall = ~batch.progress_ok
        out = (batch.count, batch.resv_count, b_eff, stall)
        if wheel_scan_fn is not None:
            return (new_mut, acc, tacc, w), out
        return (new_mut, acc, tacc), out

    if wheel_scan_fn is not None:
        (mut, acc, tacc, wfin), outs = lax.scan(
            level, (mut, acc0, tacc0, wheel0), None, length=levels)
        return mut, acc, tacc, outs, (wfin.reslots, wfin.hwm)
    (mut, acc, tacc), outs = lax.scan(level, (mut, acc0, tacc0), None,
                                      length=levels)
    return mut, acc, tacc, outs, None


def calendar_batch_bucketed(state: EngineState, now, *, steps: int,
                            levels: int,
                            anticipation_ns: int = 0,
                            allow_limit_break: bool = False,
                            use_pallas: bool | None = None
                            ) -> CalendarLadderBatch:
    """One bucketed calendar batch: L fused ladder levels (see section
    comment), each committing the exact serial prefix below its own
    refreshed stop-key boundary with a fresh per-client ``steps``
    budget.  With ``levels=1`` the committed set, the final state, and
    every counter are bit-identical to :func:`calendar_batch` (the
    ci.sh digest gate)."""
    assert steps <= state.ring_capacity, \
        "calendar steps exceed the ring window"
    assert levels >= 1, "the ladder needs at least one level"
    invariant = {f: getattr(state, f) for f in _EPOCH_INVARIANT}
    mut0 = {f: getattr(state, f) for f in _EPOCH_MUTABLE}
    mut, acc, _tacc, (count, resv, bound, stall), _w = \
        _calendar_ladder_scan(
            invariant, mut0, now, steps=steps, levels=levels,
            anticipation_ns=anticipation_ns, allow=allow_limit_break,
            use_pallas=use_pallas)
    total = jnp.sum(count).astype(jnp.int32)
    return CalendarLadderBatch(
        state=EngineState(**invariant, **mut),
        count=total,
        resv_count=jnp.sum(resv).astype(jnp.int32),
        units=acc["units"], served=acc["served"],
        served_resv=acc["served_resv"], lb=acc["lb"],
        progress_ok=~stall[0],
        level_count=count, level_bound=bound, level_stall=stall,
        served_cost=acc["cost"])


def calendar_batch_wheel(state: EngineState, now, *, steps: int,
                         levels: int, anticipation_ns: int = 0,
                         allow_limit_break: bool = False,
                         use_pallas: bool | None = None,
                         wheel_kernel: str = "xla"
                         ) -> CalendarLadderBatch:
    """One WHEEL calendar batch: the bucketed ladder driven by the
    maintained bucket index (wheel section comment) -- same
    :class:`CalendarLadderBatch` contract, bit-identical committed
    set/state/counters to :func:`calendar_batch_bucketed` at the same
    ``levels`` (and to :func:`calendar_batch` at ``levels=1``); the
    ci.sh wheel digest gates pin both."""
    assert steps <= state.ring_capacity, \
        "calendar steps exceed the ring window"
    assert levels >= 1, "the ladder needs at least one level"
    scan_fn, _fb = _wheel_resolve(wheel_kernel, state.capacity)
    invariant = {f: getattr(state, f) for f in _EPOCH_INVARIANT}
    mut0 = {f: getattr(state, f) for f in _EPOCH_MUTABLE}
    mut, acc, _tacc, (count, resv, bound, stall), _w = \
        _calendar_ladder_scan(
            invariant, mut0, now, steps=steps, levels=levels,
            anticipation_ns=anticipation_ns, allow=allow_limit_break,
            use_pallas=use_pallas, wheel_scan_fn=scan_fn)
    total = jnp.sum(count).astype(jnp.int32)
    return CalendarLadderBatch(
        state=EngineState(**invariant, **mut),
        count=total,
        resv_count=jnp.sum(resv).astype(jnp.int32),
        units=acc["units"], served=acc["served"],
        served_resv=acc["served_resv"], lb=acc["lb"],
        progress_ok=~stall[0],
        level_count=count, level_bound=bound, level_stall=stall,
        served_cost=acc["cost"])


def calendar_stop_ladder(state: EngineState, now, *, steps: int,
                         levels: int, anticipation_ns: int = 0,
                         allow_limit_break: bool = False,
                         heads=None):
    """The histogram PLANNER view of the ladder: one measure pass,
    then the stop-key CDF quantiles B_1 <= ... <= B_levels via the
    shared dense-histogram rounds (kernels.radix_quantile_ladder).
    B_1 is exactly the minstop boundary; the higher quantiles predict
    where successive refreshed-budget commit levels land on a skewed
    stop distribution (diagnostic/sizing -- the commit path itself
    re-measures per level; see section comment).

    Returns ``(ladder int64[levels], stop_pk int64[N])``."""
    assert steps <= state.ring_capacity, \
        "calendar steps exceed the ring window"
    if heads is None:
        win = ring_window(state, steps)
        heads = (win.arr, win.cost)
    arr_rows, cost_rows = _heads_rows(heads, steps)
    cls0, key0 = _classify(state, now, allow_limit_break)
    kresv = jnp.min(jnp.where(cls0 == CLS_RESV, key0, KEY_INF))
    kprop1 = jnp.min(jnp.where(cls0 == CLS_WEIGHT, key0, KEY_INF))
    kprop2 = jnp.min(jnp.where(cls0 == CLS_LB, key0, KEY_INF))
    stop_pk = _calendar_pass(state, now, arr_rows, cost_rows,
                             allow_limit_break, anticipation_ns,
                             kresv, kprop1, kprop2, None)
    return kernels.radix_quantile_ladder(stop_pk, levels), stop_pk


class CalendarEpoch(NamedTuple):
    """M calendar batches' output, compact for one readback."""

    state: EngineState
    count: jnp.ndarray        # int32[M] decisions per batch
    resv_count: jnp.ndarray   # int32[M]
    progress_ok: jnp.ndarray  # bool[M]
    served: jnp.ndarray       # int32[N] per-client decisions (whole
    #                           epoch; calibration feed)
    metrics: jnp.ndarray      # int64[NUM_METRICS] (zeros unless
    #                           with_metrics)
    level_count: jnp.ndarray  # int32[M, L] decisions per ladder level
    #                           (L = ladder_levels for "bucketed", 1
    #                           for "minstop"; bench decisions-per-
    #                           level attribution)
    # telemetry plane (None unless the caller passed an accumulator)
    hists: object = None
    ledger: object = None
    flight: object = None
    slo: object = None
    prov: object = None


def scan_calendar_epoch(state: EngineState, now, m: int, *,
                        steps: int, anticipation_ns: int = 0,
                        allow_limit_break: bool = False,
                        use_pallas: bool | None = None,
                        with_metrics: bool = False,
                        tag_width: int = 64,
                        calendar_impl: str = "minstop",
                        ladder_levels: int = 8,
                        wheel_kernel: str = "xla",
                        hists=None, ledger=None,
                        flight=None, slo=None,
                        prov=None) -> CalendarEpoch:
    """Run m calendar batches on device (each prefetches its own
    ``steps``-row ring window).  ``tag_width`` as in
    :func:`scan_prefix_epoch` (a window trip reports
    ``progress_ok=False`` for that batch and every later one).

    ``calendar_impl`` (STATIC, "minstop"|"bucketed"|"wheel") picks the
    commit boundary scheme, mirroring the prefix engine's
    ``select_impl`` switch: "minstop" is one global min-stop boundary
    per batch; "bucketed" fuses ``ladder_levels`` refreshed-budget
    boundaries per batch (see the bucketed section comment), so one
    launch commits what took ``ladder_levels`` minstop batches;
    "wheel" is the bucketed ladder driven by the maintained bucket
    index (wheel section comment) with its boundary scan behind the
    ``wheel_kernel`` switch ("xla" reference or the "pallas" kernel
    with a counted fallback).  All produce exact serial prefixes;
    ``ladder_levels=1`` is bit-identical to "minstop" (ci.sh digest
    gates).

    ``hists`` / ``ledger`` / ``flight`` telemetry accumulators as in
    :func:`scan_prefix_epoch`.  Histogram/ledger observations are per
    LEVEL (a bucketed ladder level == one minstop batch, so bucketed-L
    telemetry equals the L-batch minstop composition exactly); flight
    records are per CLIENT per BATCH (the calendar engine emits
    per-client counts, not an ordered stream), the cost column
    carrying the client's committed decisions."""
    assert tag_width in (32, 64), tag_width
    assert calendar_impl in _CAL_IMPLS, calendar_impl
    wheel = calendar_impl == "wheel"
    bucketed = calendar_impl == "bucketed" or wheel
    levels = int(ladder_levels) if bucketed else 1
    assert levels >= 1, "the ladder needs at least one level"
    if wheel:
        wheel_fn, wheel_fb = _wheel_resolve(wheel_kernel,
                                            state.capacity)
    else:
        wheel_fn, wheel_fb = None, False
    narrow32 = tag_width == 32
    invariant = {f: getattr(state, f) for f in _EPOCH_INVARIANT}
    mutable0_64 = {f: getattr(state, f) for f in _EPOCH_MUTABLE}
    served0 = jnp.zeros((state.capacity,), dtype=jnp.int32)
    met0 = obsdev.metrics_zero()
    tele0 = _tele_init(state, hists, ledger, flight, slo, prov)
    need_tele = bool(tele0)
    if narrow32:
        tc = _TagCarry32(state)
        mutable0, ok0 = tc.narrow(mutable0_64)
        if with_metrics:
            met0 = obsdev.metrics_combine(met0, obsdev.metrics_delta(
                rebase_fallbacks=(~ok0).astype(jnp.int64)))
        carry0 = (mutable0, served0, met0, tele0, ~ok0)
    else:
        carry0 = (mutable0_64, served0, met0, tele0)

    def body(carry, _):
        if narrow32:
            mut, acc, met, tele, dead = carry
            st = EngineState(**invariant, **tc.widen(mut))
        else:
            mut, acc, met, tele = carry
            st = EngineState(**invariant, **mut)
        hd = ld = sd = p_new = margin_pc = None
        if need_tele:
            # batch-entry classification, shared by the minstop
            # telemetry delta and the flight records (ONE definition,
            # so the two cannot drift); the bucketed ladder computes
            # its own per-LEVEL classification internally, and XLA
            # drops this one when nothing reads it
            cls_e, key_e = _classify(st, now, allow_limit_break)
        w_reslots = jnp.int64(0)
        w_hwm = jnp.int64(0)
        if bucketed:
            mut_in = {f: getattr(st, f) for f in _EPOCH_MUTABLE}
            new_mut, lacc, tdelta, \
                (lvl_count, lvl_resv, _bound, lvl_stall), wstats = \
                _calendar_ladder_scan(
                    invariant, mut_in, now, steps=steps,
                    levels=levels, anticipation_ns=anticipation_ns,
                    allow=allow_limit_break, use_pallas=use_pallas,
                    with_hists="h" in tele, with_ledger="l" in tele,
                    with_slo="s" in tele, prov0=tele.get("p"),
                    wheel_scan_fn=wheel_fn)
            if wstats is not None:
                w_reslots, w_hwm = wstats
            hd, ld, sd = (tdelta.get("h"), tdelta.get("l"),
                          tdelta.get("s"))
            p_new = tdelta.get("p")
            margin_pc = lacc["margin"]
            batch_state = EngineState(**invariant, **new_mut)
            count = jnp.sum(lvl_count).astype(jnp.int32)
            resv_count = jnp.sum(lvl_resv).astype(jnp.int32)
            progress = ~lvl_stall[0]
            served = lacc["served"]
            lb_total = jnp.sum(lacc["lb"]).astype(jnp.int64)
            levels_used = jnp.sum((lvl_count > 0)
                                  .astype(jnp.int64))
            ladder_fb = jnp.any(lvl_stall).astype(jnp.int64)
            base_decs = lvl_count[0].astype(jnp.int64)
        else:
            win = ring_window(st, steps, use_pallas=use_pallas)
            batch = calendar_batch(
                st, now, steps=steps,
                anticipation_ns=anticipation_ns,
                allow_limit_break=allow_limit_break,
                heads=(win.arr, win.cost))
            batch_state = batch.state
            count, resv_count = batch.count, batch.resv_count
            progress = batch.progress_ok
            served = batch.served
            lb_total = jnp.sum(batch.lb).astype(jnp.int64)
            lvl_count = count[None]
            levels_used = (count > 0).astype(jnp.int64)
            ladder_fb = jnp.int64(0)
            base_decs = count.astype(jnp.int64)
            new_mut = {f: getattr(batch.state, f)
                       for f in _EPOCH_MUTABLE}
            margin_pc = batch.margin
            if "h" in tele or "l" in tele or "s" in tele:
                hd, ld, sd = _telemetry_delta(
                    batch.state, now, cls_e, key_e, batch.served,
                    batch.served_resv, batch.lb, batch.count,
                    "h" in tele, "l" in tele,
                    cost_pc=batch.served_cost, with_slo="s" in tele)
            if "p" in tele:
                has_req = st.active & (st.depth > 0)
                elig = cls_e != CLS_NONE
                p_new = obsprov.prov_observe(
                    tele["p"], now=now, elig=elig,
                    gated=has_req & ~elig,
                    win_cls=jnp.min(jnp.where(elig, cls_e,
                                              CLS_NONE)),
                    served_pc=batch.served, margins=batch.margin)
        trip = jnp.bool_(False)
        good = jnp.bool_(True)
        if narrow32:
            mut, dead, good, trip, \
                (count, resv_count, progress, served, lb_total,
                 lvl_count, levels_used, ladder_fb,
                 base_decs, w_reslots, w_hwm) = tc.gate(
                    dead, mut, new_mut,
                    [(count, 0), (resv_count, 0), (progress, False),
                     (served, 0), (lb_total, 0),
                     (lvl_count, jnp.zeros((levels,), jnp.int32)),
                     (levels_used, 0), (ladder_fb, 0),
                     (base_decs, 0), (w_reslots, 0), (w_hwm, 0)])
        else:
            mut = new_mut
        out = (count, resv_count, progress, lvl_count)
        if with_metrics:
            met = _batch_metrics(
                met, batch_state, count=count,
                resv=resv_count,
                prop=count - resv_count,
                lb=lb_total,
                # a calendar batch with candidates that cannot make
                # progress is the guard-trip analog (serial fallback)
                guards_ok=progress | ~good, rebase_fallback=trip,
                live=good,
                ladder_levels_used=levels_used,
                ladder_base_decisions=base_decs,
                ladder_fallbacks=ladder_fb,
                wheel_occ_hwm=w_hwm, wheel_reslots=w_reslots,
                # static per-trace: the requested Pallas kernel
                # resolved to the XLA reference for this program
                pallas_fallbacks=jnp.where(
                    good, jnp.int64(1 if wheel_fb else 0),
                    jnp.int64(0)))
        if need_tele:
            tele = _tele_fold(tele, hd, ld, good, sd)
            if "p" in tele:
                tele["p"] = obsprov.prov_select(good, p_new,
                                                tele["p"])
            if "f" in tele:
                # per-client-per-batch records (the calendar engine
                # emits counts, not a stream); GATED served, so a
                # dead batch records nothing
                has_req = st.active & (st.depth > 0)
                gate_n = jnp.sum(has_req & (cls_e == CLS_NONE)) \
                    .astype(jnp.int64)
                iota = jnp.arange(st.capacity, dtype=jnp.int32)
                tele = _tele_flight(
                    tele, jnp.where(served > 0, iota, -1),
                    cls_e.astype(jnp.int64), key_e,
                    served.astype(jnp.int64), good,
                    margin=margin_pc, gate=gate_n)
        carry = (mut, acc + served, met, tele, dead) if narrow32 \
            else (mut, acc + served, met, tele)
        return carry, out

    carry, (count, resv, ok, lvls) = lax.scan(body, carry0, None,
                                              length=m)
    mutable, served, metrics = carry[0], carry[1], carry[2]
    tele = carry[3]
    if narrow32:
        state = EngineState(**invariant,
                            **tc.restore(mutable, mutable0_64, ok0))
    else:
        state = EngineState(**invariant, **mutable)
    return CalendarEpoch(state=state, count=count, resv_count=resv,
                         progress_ok=ok, served=served,
                         metrics=metrics, level_count=lvls,
                         hists=tele.get("h"), ledger=tele.get("l"),
                         flight=tele.get("f"), slo=tele.get("s"),
                         prov=tele.get("p"))


# ----------------------------------------------------------------------
# epoch-engine dispatch: the one registry + kwargs normalization
# ----------------------------------------------------------------------
#
# Every epoch body doubles as a STREAM STEP: the guarded runner
# (robust.guarded), the streaming chunk program (engine.stream), and
# any future caller must resolve "engine name -> scan fn + the kwargs
# that engine actually takes" IDENTICALLY, or a knob silently applied
# to one loop and not the other would break the stream-vs-round
# digest gate.  One implementation here; callers never hand-build the
# kwarg dicts.

EPOCH_ENGINES = ("prefix", "chain", "calendar")

# Decision-stream field classification for the lifecycle plane's
# canonical client-id-space digest (lifecycle.plane.canon_results):
# SLOT fields hold client slot indices (-1 pads) that must translate
# through the slot map; CAPACITY fields are per-slot arrays over the
# full [capacity] axis that must scatter to client-id space.  Every
# other digest field is layout-invariant already -- the engines'
# selection reductions are permutation-invariant over slots (mins /
# sums / any) and their sorts tie-break on the per-client creation
# ``order``, which moves with its row.
DECISION_SLOT_FIELDS = {"prefix": ("slot",), "chain": ("slot",),
                        "calendar": ()}
DECISION_CAPACITY_FIELDS = {"prefix": (), "chain": (),
                            "calendar": ("served",)}


def epoch_scan_fn(engine: str):
    """The epoch-scan callable for ``engine`` (raises KeyError on an
    unknown name)."""
    return {"prefix": scan_prefix_epoch, "chain": scan_chain_epoch,
            "calendar": scan_calendar_epoch}[engine]


def epoch_scan_kwargs(engine: str, *, k: int = 0, chain_depth: int = 4,
                      select_impl: str = "sort", tag_width: int = 64,
                      window_m: int | None = None,
                      calendar_impl: str = "minstop",
                      ladder_levels: int = 8,
                      wheel_kernel: str = "xla",
                      anticipation_ns: int = 0,
                      allow_limit_break: bool = False,
                      with_metrics: bool = False) -> dict:
    """Normalize the shared knob set into the kwargs ``engine``'s scan
    accepts: prefix reads k/select_impl/window_m, chain reads
    k/select_impl/chain_depth, and the calendar engine has no [k] cap
    -- k doubles as its per-client serve-step budget (``steps``)."""
    if engine not in EPOCH_ENGINES:
        raise ValueError(f"unknown epoch engine {engine!r} "
                         f"(one of {EPOCH_ENGINES})")
    kw = dict(anticipation_ns=anticipation_ns,
              allow_limit_break=allow_limit_break,
              with_metrics=with_metrics, tag_width=tag_width)
    if engine == "prefix":
        kw.update(k=k, select_impl=select_impl, window_m=window_m)
    elif engine == "chain":
        kw.update(k=k, select_impl=select_impl,
                  chain_depth=chain_depth)
    else:
        kw.update(steps=max(k, 1), calendar_impl=calendar_impl,
                  ladder_levels=ladder_levels,
                  wheel_kernel=wheel_kernel)
    return kw
