"""Prefix-commit speculative serving: thousands of decisions per O(N) pass.

The exact engine (`kernels.engine_step`) pays an O(N) masked-argmin per
decision -- semantically perfect, bandwidth-bound at scale.  This module
exploits the structure of dmClock steady states: with a deep backlog,
consecutive decisions serve DISTINCT clients (each serve advances that
client's virtual time by ~inv, far past the tag spacing between
clients), and serves of distinct clients commute.  A full lexicographic
sort of the candidate (tag, creation-order) keys yields the ENTIRE
candidate service order in one pass, and the engine commits the longest
prefix of it that is provably what the serial engine would have served
-- computed ON DEVICE, so there is no fallback cliff.

Exactness argument (differentially tested against `engine_run`):
candidates are served in sorted (key, order) ascending order -- the
serial engine's total order.  Serving candidate p re-enters its client
at a new key r_p (its freshly-tagged next head; +inf if it empties or
leaves the candidate set).  The speculative order equals the serial
order up to position q iff ``min_{p<q} r_p > (key_q, order_q)`` at every
position <= q -- the serial engine would have picked the re-entered head
first otherwise.  Since keys ascend and the cumulative min only
descends, the condition fails monotonically: the first failing position
ends the exact prefix.  Regime-exit events (a weight-phase serve making
the client's reservation tag eligible, reference do_next_request
:1124-1128) are encoded as r_p = -inf, stopping the prefix right
after p.  Guaranteed progress: whenever the serial engine would RETURN
a request at ``now``, the prefix is >= 1; the serial engine is needed
only for the never-observed global rebase-guard failures (see
``make_prefix_runner``).

The regime of each batch is picked exactly as the serial engine's first
decision would (reservation phase iff the lowest reservation tag is
eligible, :1124-1128); weight-phase candidates are effectively-ready
clients ordered by (proportion + prop_delta, order), reservation-phase
candidates by (reservation tag, order).

Restrictions (checked by the caller): AtLimit::Wait, monotonic `now`,
fixed `now` within a batch.  The stored `ready` flags are superseded by
the computed `limit <= now` (equivalent under monotonic now, since a
promotion that serial processing would perform later in the batch is
performed here eagerly and verified sound).
"""

from __future__ import annotations

from typing import NamedTuple

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.pallas import tpu as pltpu

from ..core.timebase import MAX_TAG
from . import kernels
from .kernels import (KEY_INF, NONE, RETURNING, Decision, _make_tag,
                      _fold_prev)
from .state import EngineState


# Selection = ONE full lexicographic sort on 32-bit rebased keys.  TPUs
# emulate int64 as register pairs, so sorting (key-key_min) as int32 with
# a second int32 creation-order key is ~4x cheaper than a packed-int64
# top_k -- and a full sort yields the ENTIRE service order, letting the
# batch size k grow to tens of thousands of decisions per O(N) pass.
# Rebase-window overflow clamps to _CLAMP32: harmless for candidates
# strictly beyond the selection boundary (never selectable), and the
# boundary check ``vk < _CLAMP32`` fails speculation otherwise, so
# exactness is never at risk (the serial engine takes the batch).
_CLAMP32 = (1 << 31) - 2     # in-window ceiling for real candidates
_SENT32 = (1 << 31) - 1      # non-candidate sentinel (sorts last)
_ORDER32_LIMIT = jnp.int64(1) << 31


class _Rebase(NamedTuple):
    """Shared 32-bit rebase of (key, order) + the global exactness
    guards.  This is the overflow-sensitive core of prefix selection."""

    real: jnp.ndarray      # bool[N] key < KEY_INF
    kmin: jnp.ndarray      # int64 scalar: min real key (rebase origin)
    k32: jnp.ndarray       # int32[N] rebased key; _CLAMP32 = real but
    #                        out of window; _SENT32 = non-candidate
    o32: jnp.ndarray       # int32[N] rebased creation order
    guards_ok: jnp.ndarray  # bool: order spread + cost payload fit


def _rebase32(key, order, cost) -> _Rebase:
    real = key < KEY_INF
    kmin = jnp.min(jnp.where(real, key, KEY_INF))
    krel = key - kmin
    fits = real & (krel < _CLAMP32)
    k32 = jnp.where(fits, krel,
                    jnp.where(real, _CLAMP32, _SENT32)).astype(jnp.int32)
    # order rebased like the keys: creation indices grow without bound,
    # so the int32 cast must be of the spread, not the absolute value
    omin = jnp.min(jnp.where(real, order, jnp.int64(1) << 62))
    o32 = (order - omin).astype(jnp.int32)
    omax = jnp.max(jnp.where(real, order, omin))
    # the cost guard masks to real candidates: an oversized cost on an
    # inactive/non-candidate row must not disable the fastpath forever
    cost_ok = jnp.max(jnp.where(real, cost, 0)) < (jnp.int64(1) << 31)
    guards_ok = (omax - omin < _ORDER32_LIMIT) & cost_ok
    return _Rebase(real=real, kmin=kmin, k32=k32, o32=o32,
                   guards_ok=guards_ok)


def _ready_now(state: EngineState, now):
    """Effective readiness under monotonic now: stored flag OR limit
    passed (the promote loop marks exactly {limit <= now},
    reference :1135-1144)."""
    return state.head_ready | (state.head_limit <= now)


class RingWindow(NamedTuple):
    """Per-epoch prefetch of the tail rings.

    A speculative batch pops at most ONE request per client, so an
    m-batch epoch only ever reads ring positions ``q_head0 ..
    q_head0+m-1``.  Prefetching that [m, N] window once per epoch
    replaces the per-batch ring gather, which XLA lowers to a dense
    read of the ENTIRE [N, Q] ring pair (~200 MB/batch at bench shapes
    -- measured as 60x the window's traffic)."""

    arr: jnp.ndarray    # int64[m, N] arrivals at q_head0 + j
    cost: jnp.ndarray   # int64[m, N]
    q0: jnp.ndarray     # int32[N] q_head at prefetch time


# Pallas row-rotate: the barrel shift runs in VMEM (one HBM read +
# write per chunk) instead of log2(Q) full HBM passes -- measured 3x
# the XLA rolls at bench shapes.  Constraints of this TPU stack:
# gridded pallas_call does not legalize through the remote Mosaic
# compiler, so the kernel is gridless and the host slices VMEM-sized
# row chunks; int64 rings are bitcast to int32 lane pairs (a row
# rotation by 2*q0 on the pair plane is the int64 rotation by q0).
# The chunk scales inversely with ring width to stay inside the 16MB
# scoped-VMEM budget (2048 rows was tuned at Q=128 = 256 lanes).
_ROT_LANE_BUDGET = 2048 * 256


def _rot_chunk(q: int) -> int:
    return max(8, (_ROT_LANE_BUDGET // (2 * q)) // 8 * 8)


def _rotate_kernel(q_ref, x_ref, o_ref, *, q: int):
    x = x_ref[...]                       # [chunk, 2Q] int32
    shifts = q_ref[...]                  # [chunk, 2Q] int32, in [0, Q)
    one = jnp.int32(1)
    s = 0
    while (1 << s) < q:
        bit2 = ((shifts >> jnp.int32(s)) & one) == one
        d = jnp.int32((2 * q - 2 * (1 << s)) % (2 * q))
        x = jnp.where(bit2, pltpu.roll(x, shift=d, axis=1), x)
        s += 1
    o_ref[...] = x


def _rotate_rows_pallas(ring, q0, wsize: int, *, q0t=None,
                        interpret: bool = False):
    """out[w, i] = ring[i, (q0[i]+w) % Q] for w < wsize (int64 ring).
    ``q0t`` lets callers share the lane-tiled shift plane between the
    arrival and cost rotations."""
    from jax.experimental import pallas as pl

    n, q = ring.shape
    chunk = _rot_chunk(q)
    i32 = lax.bitcast_convert_type(ring, jnp.int32).reshape(n, 2 * q)
    pad = (-n) % chunk
    if pad:
        i32 = jnp.pad(i32, ((0, pad), (0, 0)))
    if q0t is None:
        q0t = _tile_shifts(q0, q, n + pad)
    call = pl.pallas_call(
        functools.partial(_rotate_kernel, q=q),
        out_shape=jax.ShapeDtypeStruct((chunk, 2 * q), jnp.int32),
        interpret=interpret)
    # slice each chunk to the window BEFORE concatenating: the full
    # rotated ring is never materialized in HBM
    outs = [call(q0t[c:c + chunk], i32[c:c + chunk])
            [:, :2 * wsize]
            for c in range(0, n + pad, chunk)]
    rot = jnp.concatenate(outs, axis=0)
    win = rot[:n].reshape(n, wsize, 2)
    return lax.bitcast_convert_type(win, jnp.int64).T


def _tile_shifts(q0, q: int, n_padded: int):
    q0 = jnp.pad(q0, (0, n_padded - q0.shape[0]))
    return jnp.broadcast_to(q0[:, None],
                            (n_padded, 2 * q)).astype(jnp.int32)


def _rotate_rows_xla(ring, q0, wsize: int):
    q = ring.shape[1]
    r = ring
    s = 0
    while (1 << s) < q:
        bit = ((q0 >> s) & 1).astype(bool)
        r = jnp.where(bit[:, None], jnp.roll(r, -(1 << s), axis=1), r)
        s += 1
    return r[:, :wsize].T


def ring_window(state: EngineState, m: int,
                use_pallas: bool | None = None) -> RingWindow:
    """Prefetch the next ``min(m, Q)`` ring elements of every client,
    transposed to [w, N] for cheap per-batch row selects.

    Built by barrel-shifting each client's ring left by its own
    ``q_head``: a Pallas VMEM kernel on TPU, log2(Q) masked dense XLA
    rolls elsewhere (TPU gathers with per-row indices serialize --
    measured 10x the rolls' cost for a 32-wide window; a vmapped
    dynamic-slice was 50x).  Window rows past a client's queued tail
    carry stale ring values -- reads of them only happen after the
    client drained, and are masked at commit.

    ``use_pallas`` overrides the backend auto-pick: callers that wrap
    this in ``vmap`` must pass False -- batching adds a grid dimension
    to the (deliberately gridless) kernel, and gridded pallas_calls do
    not legalize through this environment's remote Mosaic compiler."""
    q = state.ring_capacity
    q0 = state.q_head
    wsize = min(m, q)

    # the Pallas path needs a full lane tile (2q >= 128 int32 lanes)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu" and q >= 64
    if use_pallas:
        n = q0.shape[0]
        q0t = _tile_shifts(q0, q, n + ((-n) % _rot_chunk(q)))
        rot = functools.partial(_rotate_rows_pallas, q0=q0,
                                wsize=wsize, q0t=q0t)
    else:
        rot = functools.partial(_rotate_rows_xla, q0=q0, wsize=wsize)
    return RingWindow(arr=rot(state.q_arrival), cost=rot(state.q_cost),
                      q0=q0)


def _window_heads(state: EngineState, window: RingWindow):
    """Every client's next tail element (new head after a pop), read
    from the prefetched window: rows consumed so far = q_head - q0.
    Unrolled one-hot selects -- a [w, N] take_along_axis lowers to a
    serializing gather (measured 20x slower)."""
    wsize = window.arr.shape[0]
    off = jnp.remainder(state.q_head - window.q0,
                        state.ring_capacity).astype(jnp.int32)
    narr = window.arr[0]
    ncost = window.cost[0]
    for j in range(1, wsize):
        pick = off == j
        narr = jnp.where(pick, window.arr[j], narr)
        ncost = jnp.where(pick, window.cost[j], ncost)
    return narr, ncost


class DenseServe(NamedTuple):
    """Elementwise ([N]) serve computation: what every client's state
    would become if its head were popped this batch.  Scatter-free --
    TPU scatters serialize badly (measured ~6x the whole elementwise
    serve), so the serve is computed densely for every client (ring
    heads read with a per-row ``take_along_axis``) and committed with
    ``jnp.where`` selects; the only index ops per batch are the
    [k]-sized decision-emit gathers."""

    has_more: jnp.ndarray     # bool[N] client still has queued work
    new_depth: jnp.ndarray    # int32[N]
    narr: jnp.ndarray         # int64[N] next head arrival
    ncost: jnp.ndarray        # int64[N] next head cost
    head_resv: jnp.ndarray    # int64[N] new tag minus weight-debt offset
    head_prop: jnp.ndarray    # int64[N]
    head_limit: jnp.ndarray   # int64[N]
    prev_resv: jnp.ndarray    # int64[N]
    prev_prop: jnp.ndarray    # int64[N]
    prev_limit: jnp.ndarray   # int64[N]


def _dense_serve(state: EngineState, heads,
                 phase_is_ready,
                 anticipation_ns: int) -> DenseServe:
    """The vectorized pop+retag (pop_process_request / update_next_tag /
    reduce_reservation_tags, reference :1021-1111) computed for EVERY
    client; rows outside the served set are garbage and masked out at
    commit.

    ``heads`` = (narr, ncost): every client's next tail element (the
    new head after a pop), precomputed by the caller so the per-epoch
    ring-window prefetch is shared across batches instead of re-read
    per batch.  ``phase_is_ready`` is a python bool or traced scalar
    (the cond-free prefix batch passes the regime flag through)."""
    # rows with depth <= 1 carry stale ring values -- masked at commit
    narr, ncost = heads

    nr, np_, nl = _make_tag(
        state.head_resv, state.head_prop, state.head_limit,
        state.head_arrival, state.resv_inv, state.weight_inv,
        state.limit_inv, state.cur_delta, state.cur_rho, narr, ncost,
        anticipation_ns)

    # phase_is_ready may be a python bool or a traced scalar (the
    # cond-free prefix batch passes the regime flag through)
    offset = jnp.where(
        phase_is_ready,
        state.resv_inv * (state.head_cost + state.head_rho),
        jnp.zeros_like(state.head_resv))

    new_depth = state.depth - 1
    has_more = new_depth > 0

    prev_r = jnp.where(has_more, _fold_prev(state.prev_resv, nr),
                       state.prev_resv) - offset
    prev_p = jnp.where(has_more, _fold_prev(state.prev_prop, np_),
                       state.prev_prop)
    prev_l = jnp.where(has_more, _fold_prev(state.prev_limit, nl),
                       state.prev_limit)

    return DenseServe(
        has_more=has_more,
        new_depth=new_depth.astype(jnp.int32),
        narr=narr, ncost=ncost,
        head_resv=nr - offset,
        head_prop=np_, head_limit=nl,
        prev_resv=prev_r, prev_prop=prev_p, prev_limit=prev_l,
    )


def _commit_serves(state: EngineState, mask, serve: DenseServe,
                   gate) -> EngineState:
    """Apply the dense serve to the rows in ``mask``, gated on the
    scalar speculation-validity flag: pure elementwise selects, no
    scatters."""
    sel = mask & gate
    selm = sel & serve.has_more

    def pick(pred, new, old):
        return jnp.where(pred, new, old)

    return state._replace(
        depth=pick(sel, serve.new_depth, state.depth),
        q_head=pick(selm, (state.q_head + 1) % state.ring_capacity,
                    state.q_head).astype(jnp.int32),
        head_resv=pick(selm, serve.head_resv, state.head_resv),
        head_prop=pick(selm, serve.head_prop, state.head_prop),
        head_limit=pick(selm, serve.head_limit, state.head_limit),
        head_arrival=pick(selm, serve.narr, state.head_arrival),
        head_cost=pick(selm, serve.ncost, state.head_cost),
        head_rho=pick(selm, state.cur_rho, state.head_rho),
        head_ready=state.head_ready & ~sel,
        prev_resv=pick(sel, serve.prev_resv, state.prev_resv),
        prev_prop=pick(sel, serve.prev_prop, state.prev_prop),
        prev_limit=pick(sel, serve.prev_limit, state.prev_limit),
        prev_arrival=pick(selm, serve.narr, state.prev_arrival),
    )


def _default_heads(state: EngineState):
    """Single-batch ring-head read (the m=1 window)."""
    return _window_heads(state, ring_window(state, 1))


# state fields the speculative serve path never writes: rings are only
# popped via q_head, and QoS/identity/ingest-time fields are mutated by
# ingest alone, which cannot run mid-epoch.  Keeping them OUT of the
# scan carry stops XLA from shuffling ~100MB of loop-invariant buffers
# per iteration (the rings dominate).
_EPOCH_INVARIANT = ("active", "idle", "order", "resv_inv", "weight_inv",
                    "limit_inv", "prop_delta", "cur_rho", "cur_delta",
                    "q_arrival", "q_cost")
_EPOCH_MUTABLE = tuple(f for f in EngineState._fields
                       if f not in _EPOCH_INVARIANT)


_O32_MASK = jnp.int64(0xFFFFFFFF)


def _pack(k32, o32):
    """Lexicographic (key, order) as one int64: key in the high word,
    order (nonneg; masked against sign-extension for the garbage orders
    of sentinel rows) in the low word."""
    return (k32.astype(jnp.int64) << 32) | (o32.astype(jnp.int64)
                                            & _O32_MASK)


class PrefixBatch(NamedTuple):
    """Result of one prefix-commit attempt."""

    state: EngineState
    count: jnp.ndarray     # int32: decisions committed (exact serial
    #                        prefix; 0 = nothing eligible at `now`)
    guards_ok: jnp.ndarray  # bool: rebase-window guards held; when
    #                         False count is 0 and the caller must use
    #                         the serial engine for this batch
    decisions: Decision    # [k]; slots -1 / type NONE past `count`


def _prefix_select(key, order, k: int, cost, reentry):
    """Longest-exact-prefix selection over sorted (key, order).

    ``key``     int64[N], KEY_INF for non-candidates.
    ``reentry`` int64[N]: the key at which the client re-enters the
                candidate order after one serve; KEY_INF when it leaves
                the batch's candidate set; any negative value to force
                the prefix to stop right after serving this client
                (regime-exit blocker).
    ``cost``    int64[N] (>= 0), ridden through the sort as int32.

    Returns (idx, sel_cost, pk, pk_dense, elig_key, count_fn,
    guards_ok) where ``idx``/``sel_cost``/``pk`` are the [k] sorted
    candidate slots, costs and packed boundary keys, ``pk_dense`` is
    the [N] packed key per client (for the dense commit-mask compare),
    ``elig_key`` is the [k] absolute key per position (for eligibility
    gates like resv <= now), and ``count_fn(elig_ok)`` finishes the
    prefix computation given the per-position eligibility mask.
    """
    rb = _rebase32(key, order, cost)
    # re-entry key in the same rebased space: values past the window
    # clamp high (harmless: every committable boundary is < _CLAMP32,
    # and packed comparisons stay strict); blockers stay negative.  The
    # KEY_INF sentinel is mapped before the subtraction (which would
    # wrap for it); a genuine reentry below kmin cannot occur (tags are
    # monotone under a serve) but would clamp to 0, which only shortens
    # the committed prefix -- conservative, never inexact.
    rrel = jnp.clip(reentry - rb.kmin, 0, jnp.int64(_SENT32))
    r32 = jnp.where(reentry < 0, jnp.int32(-1),
                    jnp.where(reentry >= KEY_INF, jnp.int32(_SENT32),
                              rrel.astype(jnp.int32)))
    iota = jnp.arange(key.shape[0], dtype=jnp.int32)
    ks, os_, idxs, cs, rs = lax.sort(
        (rb.k32, rb.o32, iota, cost.astype(jnp.int32), r32), num_keys=2)
    ks, os_, idxs, cs, rs = ks[:k], os_[:k], idxs[:k], cs[:k], rs[:k]

    pk_dense = _pack(rb.k32, rb.o32)
    pk = _pack(ks, os_)
    rpk = jnp.where(rs < 0, jnp.int64(-1), _pack(rs, os_))
    # exclusive cumulative min of re-entry keys over the sorted order
    cm = lax.associative_scan(jnp.minimum, rpk)
    cm_excl = jnp.concatenate(
        [jnp.full((1,), (jnp.int64(1) << 62), dtype=jnp.int64), cm[:-1]])

    guards_ok = rb.guards_ok
    in_window = ks < _CLAMP32
    elig_key = rb.kmin + ks.astype(jnp.int64)

    def count_fn(elig_ok):
        ok_q = in_window & elig_ok & (cm_excl > pk)
        count = jnp.where(jnp.all(ok_q), jnp.int32(k),
                          jnp.argmax(~ok_q).astype(jnp.int32))
        return jnp.where(guards_ok, count, jnp.int32(0))

    return (idxs, cs.astype(jnp.int64), pk, pk_dense, elig_key,
            count_fn, guards_ok)


def _commit_prefix(state: EngineState, serve: DenseServe, pk_dense,
                   count, pk) -> tuple[EngineState, jnp.ndarray]:
    """Commit the first ``count`` sorted candidates: dense membership is
    ``packed(key) <= packed boundary`` (packed keys are unique).

    The boundary pk[count-1] is read as a masked max over the sorted
    prefix, not a dynamic gather -- scalar gathers from vectors
    serialize on this stack (PROFILE.md findings 4/8)."""
    j = jnp.arange(pk.shape[0], dtype=jnp.int32)
    boundary = jnp.max(jnp.where(j < count, pk, jnp.int64(-1)))
    mask = pk_dense <= boundary
    return _commit_serves(state, mask, serve, jnp.bool_(True)), mask


def speculate_prefix_batch(state: EngineState, now, k: int, *,
                           anticipation_ns: int,
                           heads=None,
                           max_count=None) -> PrefixBatch:
    """One prefix-commit batch: regime picked exactly as the serial
    engine's first decision would (reservation phase iff the lowest
    reservation tag is eligible, reference :1124-1128), then the
    longest exact prefix of that regime's sorted candidates commits.

    ``max_count`` (optional int32 scalar, may be traced) caps the
    committed prefix: a shorter prefix of an exact prefix is still
    exact, so callers can budget decisions (e.g. a simulator serving
    at most its remaining slice capacity) without losing parity."""
    if heads is None:
        heads = _default_heads(state)

    def capped(count):
        return count if max_count is None \
            else jnp.minimum(count, jnp.int32(max_count))
    has_req = state.active & (state.depth > 0)
    resv_key = jnp.where(has_req, state.head_resv, KEY_INF)
    resv_regime = jnp.min(resv_key) <= now      # traced scalar bool

    # COND-FREE regime dispatch: both regimes share one dense serve
    # and ONE sort; the regime flag where-selects keys, re-entries and
    # the eligibility gate.  A lax.cond here materialized the selected
    # branch's operand set per batch and walled off fusion -- removing
    # it measured 2576 -> 1494 us/batch at k=49152 (PROFILE.md r4
    # finding 9).
    ready = has_req & _ready_now(state, now)
    cand_w = ready & (state.head_prop < MAX_TAG)
    key_w = jnp.where(cand_w, state.head_prop + state.prop_delta,
                      KEY_INF)
    key = jnp.where(resv_regime, resv_key, key_w)

    serve = _dense_serve(state, heads, ~resv_regime, anticipation_ns)

    # re-entry per regime.  Weight regime: a serve whose reservation
    # tag (post weight-debt reduction) becomes eligible forces the
    # next serial decision into the constraint phase (blocker = -1).
    reentry_r = jnp.where(has_req & serve.has_more, serve.head_resv,
                          KEY_INF)
    new_eff = serve.head_prop + state.prop_delta
    new_ready = (serve.head_limit <= now) & (serve.head_prop < MAX_TAG)
    blocked = cand_w & serve.has_more & (serve.head_resv <= now)
    reentry_w = jnp.where(
        blocked, jnp.int64(-1),
        jnp.where(cand_w & serve.has_more & new_ready, new_eff,
                  KEY_INF))
    reentry = jnp.where(resv_regime, reentry_r, reentry_w)

    (idxs, sel_cost, pk, pk_dense, elig_key, count_fn,
     guards) = _prefix_select(key, state.order, k, state.head_cost,
                              reentry)
    # constraint phase serves only tags <= now; the weight phase has
    # no eligibility gate (readiness is already in the candidate set)
    elig_ok = jnp.where(resv_regime, elig_key <= now, True)
    count = capped(count_fn(elig_ok))
    new_state, _ = _commit_prefix(state, serve, pk_dense, count, pk)

    # stored-flag parity (promote loop, reference :1135-1144), weight
    # regime only: every weight decision promotes current heads with
    # limit <= now; the head popped by the LAST committed decision was
    # never seen by a later promote pass.  With count == 0 no serial
    # decision ran, so the flags stay untouched.
    has_req_after = new_state.active & (new_state.depth > 0)
    promoted = new_state.head_ready | \
        (has_req_after & (new_state.head_limit <= now))
    # idxs[count-1] as a masked reduction, not a dynamic scalar gather
    j = jnp.arange(k, dtype=jnp.int32)
    last_client = jnp.max(jnp.where(j == count - 1, idxs, -1))
    promoted = promoted & (
        jnp.arange(state.capacity, dtype=jnp.int32) != last_client)
    new_state = new_state._replace(head_ready=jnp.where(
        ~resv_regime & (count > 0), promoted, new_state.head_ready))

    phase = jnp.where(resv_regime, jnp.int32(0), jnp.int32(1))
    served = j < count
    decisions = Decision(
        type=jnp.where(served, RETURNING, NONE).astype(jnp.int32),
        slot=jnp.where(served, idxs, -1).astype(jnp.int32),
        phase=jnp.broadcast_to(phase, (k,)),
        cost=jnp.where(served, sel_cost, 0),
        when=jnp.zeros((k,), dtype=jnp.int64),
        limit_break=jnp.zeros((k,), dtype=bool),
    )
    return PrefixBatch(state=new_state, count=count, guards_ok=guards,
                       decisions=decisions)


class PrefixEpoch(NamedTuple):
    """M prefix-commit batches' output, compact for one readback."""

    state: EngineState     # after ALL committed prefixes
    count: jnp.ndarray     # int32[M] decisions committed per batch
    guards_ok: jnp.ndarray  # bool[M]
    slot: jnp.ndarray      # int32[M, k] serial-order winners (-1 pad)
    phase: jnp.ndarray     # int8[M]    regime of batch i
    cost: jnp.ndarray      # int32[M, k]


def scan_prefix_epoch(state: EngineState, now, m: int, k: int, *,
                      anticipation_ns: int) -> PrefixEpoch:
    """Run m prefix-commit batches of up to k decisions on device.

    EVERY batch commits its own exact prefix, so the concatenated
    per-batch prefixes are always the serial decision stream at
    ``now``.  Batches after the workload drains commit 0 and
    spin harmlessly.  Callers MUST check ``guards_ok``: a rare global
    rebase-guard failure (creation-order spread or served cost past
    2^31) zeroes that batch and every later one without committing --
    rerun from the returned state via ``make_prefix_runner``'s serial
    fallback in that case.
    """
    invariant = {f: getattr(state, f) for f in _EPOCH_INVARIANT}
    mutable0 = {f: getattr(state, f) for f in _EPOCH_MUTABLE}
    window = ring_window(state, m)

    def body(mut, _):
        st = EngineState(**invariant, **mut)
        batch = speculate_prefix_batch(
            st, now, k, anticipation_ns=anticipation_ns,
            heads=_window_heads(st, window))
        out = (batch.count, batch.guards_ok,
               batch.decisions.slot,
               batch.decisions.phase[0].astype(jnp.int8),
               batch.decisions.cost.astype(jnp.int32))
        new_mut = {f: getattr(batch.state, f) for f in _EPOCH_MUTABLE}
        return new_mut, out

    mutable, (count, guards, slot, phase, cost) = lax.scan(
        body, mutable0, None, length=m)
    state = EngineState(**invariant, **mutable)
    return PrefixEpoch(state=state, count=count, guards_ok=guards,
                       slot=slot, phase=phase, cost=cost)


def make_prefix_runner(k: int, *, anticipation_ns: int = 0):
    """Host-orchestrated prefix runner: (state, now) -> (state,
    decisions, n_committed).  The serial engine is needed only when the
    global rebase guards fail (creation-order spread or a served cost
    past 2^31 -- never observed in practice); a zero count with guards
    intact means nothing is eligible at ``now`` (serial FUTURE/NONE).
    """
    attempt = jax.jit(functools.partial(
        speculate_prefix_batch, k=k, anticipation_ns=anticipation_ns))
    exact = jax.jit(lambda s, t: kernels.engine_run(
        s, t, k, allow_limit_break=False,
        anticipation_ns=anticipation_ns, advance_now=False))

    def run(state: EngineState, now):
        batch = attempt(state, now)
        if not bool(batch.guards_ok):
            st, _, decs = exact(state, now)
            d = jax.device_get(decs)
            return st, decs, int((d.type == RETURNING).sum())
        return batch.state, batch.decisions, int(batch.count)

    return run
