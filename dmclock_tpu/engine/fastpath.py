"""Speculative batched serving: thousands of decisions per O(N) pass.

The exact engine (`kernels.engine_step`) pays an O(N) masked-argmin per
decision -- semantically perfect, bandwidth-bound at scale.  This module
exploits the structure of dmClock steady states: with a deep backlog,
consecutive decisions serve DISTINCT clients (each serve advances that
client's virtual time by ~inv, far past the tag spacing between
clients), and serves of distinct clients commute.  So a batch of k
decisions is just the k smallest candidate tags -- one `top_k` plus
O(k) vectorized serves -- *provided* the speculation is validated.

Two speculative regimes, each with an on-device validity check that
compares against what the serial engine would have done (`engine_run`):

- **weight regime** (reference weight phase, do_next_request :1146-1151):
  no reservation tag is eligible (resv_min > now) and stays so through
  the batch; candidates are effectively-ready clients by
  (proportion + prop_delta, order).
- **reservation regime** (constraint phase, :1124-1128): every served
  tag is <= now (deep reservation backlog); weight phase is never
  reached, so no promotion side-effects occur.

Checks performed AFTER the vectorized serve (cheap, [k]-sized):
one-serve-per-client (each new head tag must leave the served window),
phase stability (reservation tags stay ineligible in the weight regime /
served tags all eligible in the reservation regime), and strict key
separation at the batch boundary (tie safety).  On failure the caller
falls back to the exact serial engine for that batch -- results are
therefore always bit-identical to `engine_run` (differentially tested).

Restrictions (checked by the caller): AtLimit::Wait, monotonic `now`,
fixed `now` within a batch.  The stored `ready` flags are superseded by
the computed `limit <= now` (equivalent under monotonic now, since a
promotion that serial processing would perform later in the batch is
performed here eagerly and verified sound).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.timebase import MAX_TAG, TIME_MAX
from . import kernels
from .kernels import KEY_INF, Decision, _make_tag, _fold_prev
from .state import EngineState


class FastBatch(NamedTuple):
    """Result of one speculative attempt."""

    state: EngineState
    ok: jnp.ndarray        # bool: speculation valid; else state is the
    #                        INPUT state (caller reruns serially)
    decisions: Decision    # [k] arrays, valid where ok


# Creation indices stay far below this (2^45 ~ 35 trillion requests);
# used to rank strictly-below-boundary candidates ahead of every
# boundary tie in the order-aware second top_k pass.
ORDER_BIG = 1 << 45


def _lex_top_k(key, order, k: int):
    """Indices of the k lexicographically-smallest (key, order) pairs.

    Exact at tie boundaries: pass 1 finds the k-th smallest key V;
    pass 2 ranks candidates with key < V ahead of everything and
    resolves the key == V boundary group by creation order -- the
    serial engine's exact tie-break.  Returns (idx[k], V,
    max_tied_order, count_ok).
    """
    neg, _ = lax.top_k(-key, k)
    v = -neg[k - 1]
    # Sentinel (masked) entries carry key == KEY_INF; they must never
    # join the tie group, or an underfull candidate set would rank them
    # by creation order and "serve" requestless clients.
    real = key < KEY_INF
    below = key < v
    tied = real & (key == v)
    rank = jnp.where(below, order - ORDER_BIG,
                     jnp.where(tied, order, KEY_INF))
    _, idx = lax.top_k(-rank, k)
    count_ok = v < KEY_INF  # k real candidates exist
    order_k = order[idx]
    max_tied_order = jnp.max(jnp.where(key[idx] == v, order_k,
                                       -(jnp.int64(1) << 62)))
    return idx, v, max_tied_order, count_ok


def _ready_now(state: EngineState, now):
    """Effective readiness under monotonic now: stored flag OR limit
    passed (the promote loop marks exactly {limit <= now},
    reference :1135-1144)."""
    return state.head_ready | (state.head_limit <= now)


class ServePlan(NamedTuple):
    """Planned (not yet applied) vectorized pop+retag of k clients."""

    served_cost: jnp.ndarray
    new_depth: jnp.ndarray
    has_more: jnp.ndarray
    rq_next: jnp.ndarray
    head_resv: jnp.ndarray
    head_prop: jnp.ndarray
    head_limit: jnp.ndarray
    head_arrival: jnp.ndarray
    head_cost: jnp.ndarray
    head_rho: jnp.ndarray
    prev_resv: jnp.ndarray
    prev_prop: jnp.ndarray
    prev_limit: jnp.ndarray
    prev_arrival: jnp.ndarray


def _plan_serves(state: EngineState, idx, phase_is_ready,
                 anticipation_ns: int) -> ServePlan:
    """Compute the vectorized pop+retag of k distinct clients
    (pop_process_request / update_next_tag / reduce_reservation_tags,
    reference :1021-1111) without touching state -- valid only when idx
    are distinct, which the speculation guarantees (one head per
    client).  Application is deferred to `_apply_serves` so a failed
    speculation costs nothing and needs no state rollback."""
    served_r = state.head_resv[idx]
    served_p = state.head_prop[idx]
    served_l = state.head_limit[idx]
    served_arr = state.head_arrival[idx]
    served_cost = state.head_cost[idx]
    served_rho = state.head_rho[idx]

    new_depth = state.depth[idx] - 1
    has_more = new_depth > 0
    rq = state.q_head[idx]
    narr = state.q_arrival[idx, rq]
    ncost = state.q_cost[idx, rq]

    nr, np_, nl = _make_tag(
        served_r, served_p, served_l, served_arr,
        state.resv_inv[idx], state.weight_inv[idx], state.limit_inv[idx],
        state.cur_delta[idx], state.cur_rho[idx], narr, ncost,
        anticipation_ns)

    offset = jnp.where(phase_is_ready,
                       state.resv_inv[idx] * (served_cost + served_rho),
                       jnp.int64(0))

    prev_r = jnp.where(has_more, _fold_prev(state.prev_resv[idx], nr),
                       state.prev_resv[idx]) - offset
    prev_p = jnp.where(has_more, _fold_prev(state.prev_prop[idx], np_),
                       state.prev_prop[idx])
    prev_l = jnp.where(has_more, _fold_prev(state.prev_limit[idx], nl),
                       state.prev_limit[idx])
    prev_arr = jnp.where(has_more, narr, state.prev_arrival[idx])

    return ServePlan(
        served_cost=served_cost,
        new_depth=new_depth.astype(jnp.int32),
        has_more=has_more,
        rq_next=((rq + 1) % state.ring_capacity).astype(jnp.int32),
        head_resv=nr - offset, head_prop=np_, head_limit=nl,
        head_arrival=narr, head_cost=ncost,
        head_rho=state.cur_rho[idx],
        prev_resv=prev_r, prev_prop=prev_p, prev_limit=prev_l,
        prev_arrival=prev_arr)


def _apply_serves(state: EngineState, idx, plan: ServePlan,
                  gate) -> EngineState:
    """Scatter the plan at idx, gated on the scalar `gate` (speculation
    validity): only k rows are touched, so a gated-off apply is free --
    no whole-state select, which matters inside scanned epochs."""
    has_more = plan.has_more & gate

    def scat(arr, val, pred):
        return arr.at[idx].set(jnp.where(pred, val, arr[idx]))

    return state._replace(
        depth=scat(state.depth, plan.new_depth, gate),
        q_head=scat(state.q_head, plan.rq_next, has_more),
        head_resv=scat(state.head_resv, plan.head_resv, has_more),
        head_prop=scat(state.head_prop, plan.head_prop, has_more),
        head_limit=scat(state.head_limit, plan.head_limit, has_more),
        head_arrival=scat(state.head_arrival, plan.head_arrival,
                          has_more),
        head_cost=scat(state.head_cost, plan.head_cost, has_more),
        head_rho=scat(state.head_rho, plan.head_rho, has_more),
        head_ready=scat(state.head_ready, jnp.zeros_like(idx, bool),
                        gate),
        prev_resv=scat(state.prev_resv, plan.prev_resv, gate),
        prev_prop=scat(state.prev_prop, plan.prev_prop, gate),
        prev_limit=scat(state.prev_limit, plan.prev_limit, gate),
        prev_arrival=scat(state.prev_arrival, plan.prev_arrival, gate),
    )


def speculate_weight_batch(state: EngineState, now, k: int, *,
                           anticipation_ns: int,
                           enabled=True) -> FastBatch:
    """k weight-phase serves in one pass; state untouched when the
    speculation fails (ok=False) or `enabled` is False."""
    has_req = state.active & (state.depth > 0)
    ready = has_req & _ready_now(state, now)
    eff = state.head_prop + state.prop_delta
    key = jnp.where(ready & (state.head_prop < MAX_TAG), eff, KEY_INF)

    # entry condition: reservation phase must not fire (:1124-1128)
    resv_key = jnp.where(has_req, state.head_resv, KEY_INF)
    resv_min0 = jnp.min(resv_key)
    cond_entry = resv_min0 > now

    idx, kth, max_tied_order, cond_count = _lex_top_k(key, state.order, k)
    key_k = key[idx]

    plan = _plan_serves(state, idx, jnp.ones((k,), dtype=bool),
                        anticipation_ns)

    # one-serve-per-client: each served client must leave the window --
    # its new head either empty, not ready at `now`, keyed strictly past
    # the boundary V, or tied at V but ordered after every served tie
    # (so the serial engine would also leave it unserved)
    new_eff = plan.head_prop + state.prop_delta[idx]
    new_ready = (plan.head_limit <= now) & (plan.head_prop < MAX_TAG)
    beyond = (new_eff > kth) | \
        ((new_eff == kth) & (state.order[idx] > max_tied_order))
    cond_once = jnp.all((~plan.has_more) | (~new_ready) | beyond)
    # phase stability: no served client's new reservation tag becomes
    # eligible (unserved clients' tags didn't move; entry checked them)
    cond_resv = jnp.all(
        jnp.where(plan.has_more, plan.head_resv, TIME_MAX) > now)

    ok = cond_entry & cond_count & cond_once & cond_resv
    gate = ok & enabled

    new_state = _apply_serves(state, idx, plan, gate)

    # emit decisions in exact serial order: (key, order) ascending
    order_k = state.order[idx]
    perm = jnp.lexsort((order_k, key_k))

    # Stored-flag parity with the serial engine: every weight decision
    # runs the promote loop first (reference :1135-1144), so at batch
    # end every current head with limit <= now carries ready=True --
    # except the head popped by the LAST decision, which no later
    # promotion pass ever saw.
    has_req_after = new_state.active & (new_state.depth > 0)
    promoted = new_state.head_ready | \
        (has_req_after & (new_state.head_limit <= now))
    last_client = idx[perm[k - 1]]
    promoted = promoted.at[last_client].set(False)
    new_state = new_state._replace(head_ready=jnp.where(
        gate, promoted, new_state.head_ready))

    decisions = Decision(
        type=jnp.zeros((k,), dtype=jnp.int32),
        slot=idx[perm].astype(jnp.int32),
        phase=jnp.ones((k,), dtype=jnp.int32),
        cost=plan.served_cost[perm],
        when=jnp.zeros((k,), dtype=jnp.int64),
        limit_break=jnp.zeros((k,), dtype=bool),
    )
    return FastBatch(state=new_state, ok=ok, decisions=decisions)


def speculate_resv_batch(state: EngineState, now, k: int, *,
                         anticipation_ns: int,
                         enabled=True) -> FastBatch:
    """k reservation-phase serves in one pass; state untouched when the
    speculation fails or `enabled` is False.

    Valid when the k smallest reservation tags are all <= now (deep
    constraint backlog): phase 1 fires every time, so no promotion or
    weight-phase side effects occur (reference :1124-1128)."""
    has_req = state.active & (state.depth > 0)
    key = jnp.where(has_req, state.head_resv, KEY_INF)

    idx, kth, max_tied_order, cond_count = _lex_top_k(key, state.order, k)
    key_k = key[idx]
    cond_eligible = kth <= now            # all k fire the constraint phase

    plan = _plan_serves(state, idx, jnp.zeros((k,), dtype=bool),
                        anticipation_ns)

    # one-serve-per-client: the new head tag must leave the window
    beyond = (plan.head_resv > kth) | \
        ((plan.head_resv == kth) & (state.order[idx] > max_tied_order))
    cond_once = jnp.all((~plan.has_more) | beyond)

    ok = cond_eligible & cond_count & cond_once
    new_state = _apply_serves(state, idx, plan, ok & enabled)

    order_k = state.order[idx]
    perm = jnp.lexsort((order_k, key_k))
    decisions = Decision(
        type=jnp.zeros((k,), dtype=jnp.int32),
        slot=idx[perm].astype(jnp.int32),
        phase=jnp.zeros((k,), dtype=jnp.int32),
        cost=plan.served_cost[perm],
        when=jnp.zeros((k,), dtype=jnp.int64),
        limit_break=jnp.zeros((k,), dtype=bool),
    )
    return FastBatch(state=new_state, ok=ok, decisions=decisions)


def attempt_fast_batch(state: EngineState, now, k: int, *,
                       anticipation_ns: int,
                       enabled=True,
                       weight_first=False) -> FastBatch:
    """One speculative attempt: one regime, then the other on failure.

    Both speculations are cheap (top_k + O(k) serves), so the branch is
    a small device cond.  The caller checks ``ok`` on the host (or via
    the epoch scan's commit mask) and falls back to the exact serial
    engine when speculation fails -- keeping the expensive O(k*N)
    fallback OUT of this compiled program.  With `enabled` False the
    state passes through untouched.  ``weight_first`` orders the
    attempts -- steady states stay in one regime for long stretches, so
    trying last batch's regime first skips a wasted speculation.
    """

    def resv(_):
        return speculate_resv_batch(state, now, k,
                                    anticipation_ns=anticipation_ns,
                                    enabled=enabled)

    def weight(_):
        return speculate_weight_batch(state, now, k,
                                      anticipation_ns=anticipation_ns,
                                      enabled=enabled)

    def ordered(first, second):
        def go(_):
            fb = first(None)
            return lax.cond(fb.ok, lambda _: fb, second, operand=None)
        return go

    return lax.cond(weight_first, ordered(weight, resv),
                    ordered(resv, weight), operand=None)


class FastEpoch(NamedTuple):
    """M speculative batches' worth of output, compact for readback.

    The tunneled single-chip runtime pays ~100ms round-trip latency per
    host readback CALL regardless of size, so an epoch returns all M
    batches' decisions in one pytree: one device_get per epoch.
    """

    state: EngineState     # after the last COMMITTED batch
    ok: jnp.ndarray        # bool[M]: batch i committed
    slot: jnp.ndarray      # int32[M, k] serial-order winners
    phase: jnp.ndarray     # int8[M, k]
    cost: jnp.ndarray      # int32[M, k]


# state fields the speculative serve path never writes: rings are only
# popped via q_head, and QoS/identity/ingest-time fields are mutated by
# ingest alone, which cannot run mid-epoch.  Keeping them OUT of the
# scan carry stops XLA from shuffling ~100MB of loop-invariant buffers
# per iteration (the rings dominate).
_EPOCH_INVARIANT = ("active", "idle", "order", "resv_inv", "weight_inv",
                    "limit_inv", "prop_delta", "cur_rho", "cur_delta",
                    "q_arrival", "q_cost")
_EPOCH_MUTABLE = tuple(f for f in EngineState._fields
                       if f not in _EPOCH_INVARIANT)


def scan_fast_epoch(state: EngineState, now, m: int, k: int, *,
                    anticipation_ns: int) -> FastEpoch:
    """Run up to m speculative batches of k decisions, entirely on
    device.  Commit-prefix semantics: the first failed speculation
    stops the epoch (its state is NOT applied, and no later batch is),
    so the returned state is always an exact serial prefix -- the host
    reruns from it with the exact engine, then resumes epochs.
    """
    invariant = {f: getattr(state, f) for f in _EPOCH_INVARIANT}
    mutable0 = {f: getattr(state, f) for f in _EPOCH_MUTABLE}

    def body(carry, _):
        mut, dead, weight_hint = carry
        st = EngineState(**invariant, **mut)
        batch = attempt_fast_batch(st, now, k,
                                   anticipation_ns=anticipation_ns,
                                   enabled=~dead,
                                   weight_first=weight_hint)
        commit = batch.ok & ~dead
        # batch.state is bit-identical to st when not committed (the
        # serve scatters are gated), so no whole-state select is needed
        out = (commit,
               batch.decisions.slot,
               batch.decisions.phase.astype(jnp.int8),
               batch.decisions.cost.astype(jnp.int32))
        new_mut = {f: getattr(batch.state, f) for f in _EPOCH_MUTABLE}
        weight_hint = jnp.where(batch.ok, batch.decisions.phase[0] == 1,
                                weight_hint)
        return (new_mut, dead | ~batch.ok, weight_hint), out

    (mutable, _dead, _hint), (ok, slot, phase, cost) = lax.scan(
        body, (mutable0, jnp.bool_(False), jnp.bool_(False)), None,
        length=m)
    state = EngineState(**invariant, **mutable)
    return FastEpoch(state=state, ok=ok, slot=slot, phase=phase,
                     cost=cost)


def make_fast_runner(k: int, *, anticipation_ns: int = 0):
    """Host-orchestrated runner: (state, now) -> (state, decisions,
    used_fast).  Bit-identical to ``kernels.engine_run(...,
    advance_now=False)`` under AtLimit::Wait with monotonic now
    (differential tests pin this): speculation is validated on device,
    and on failure the exact serial engine reruns the batch from the
    untouched input state.

    The one-scalar ``ok`` sync per batch costs ~launch latency, far
    below the serial fallback it avoids compiling into the hot program.
    """
    import functools

    import jax

    attempt = jax.jit(functools.partial(
        attempt_fast_batch, k=k, anticipation_ns=anticipation_ns))
    exact = jax.jit(lambda s, t: kernels.engine_run(
        s, t, k, allow_limit_break=False,
        anticipation_ns=anticipation_ns, advance_now=False))

    def run(state: EngineState, now):
        batch = attempt(state, now)
        if bool(batch.ok):
            return batch.state, batch.decisions, True
        st, _, decs = exact(state, now)
        return st, decs, False

    return run
