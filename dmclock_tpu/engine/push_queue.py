"""Push-mode surface over the TPU batch engine.

Equivalent of the reference ``PushPriorityQueue``
(``dmclock_server.h:1504-1797``) redesigned for a batched device
engine: the queue drives the server by invoking ``handle_f(client,
request, phase, cost)`` whenever ``can_handle_f()`` is true and a
request is eligible, with timed wakeups for future-eligible requests on
a dedicated sched-ahead thread (reference ``run_sched_ahead``
:1760-1786).

Batch-boundary sched_ahead (the SURVEY §7 hard part): instead of one
``do_next_request`` per dispatch, a scheduling pass pulls a BATCH of
decisions in one device launch -- sized by the embedder's
``capacity_f()`` when provided (a server that knows its free service
slots), else one at a time so the ``can_handle_f`` gate is consulted
before every dispatch exactly like the reference.  The sched-ahead
timer is armed once per batch from the batch-terminal FUTURE decision,
not per decision.
"""

from __future__ import annotations

import threading
import time as _walltime
from typing import Any, Callable, Optional

from ..core.qos import ClientInfo
from ..core.recs import Phase, ReqParams
from ..core.timebase import NS_PER_SEC, TIME_ZERO, sec_to_ns
from .queue import TpuPullPriorityQueue

ClientInfoFunc = Callable[[Any], Optional[ClientInfo]]


class TpuPushPriorityQueue:
    """Queue-drives-server mode on the batched device engine."""

    def __init__(self, client_info_f: ClientInfoFunc,
                 can_handle_f: Callable[[], bool],
                 handle_f: Callable[[Any, Any, Phase, int], None],
                 *,
                 capacity_f: Optional[Callable[[], int]] = None,
                 # capacity_f CONTRACT: when provided, can_handle_f()
                 # must be equivalent to capacity_f() > 0.  A batch pops
                 # up to capacity_f() requests from device state before
                 # the handle_f calls run, re-consulting can_handle_f
                 # only between batches -- so a gate that can close
                 # mid-batch for reasons other than slot exhaustion
                 # would see dispatches it meant to refuse (the
                 # reference consults can_handle before every dispatch;
                 # omit capacity_f to get that per-dispatch behavior).
                 batch_max: int = 64,
                 now_ns_f: Optional[Callable[[], int]] = None,
                 sched_at_f: Optional[Callable[[int], None]] = None,
                 **pull_kwargs):
        self._q = TpuPullPriorityQueue(client_info_f, **pull_kwargs)
        self.can_handle_f = can_handle_f
        self.handle_f = handle_f
        self.capacity_f = capacity_f
        self.batch_max = batch_max
        # virtual-time embedding (see the host PushPriorityQueue): the
        # injected clock feeds scheduling decisions and default arrival
        # stamps; sched_at_f must arrange a sched_ahead_fire() call at
        # the given virtual time, and no sched-ahead thread is spawned
        self._now_ns_f = now_ns_f or (lambda: sec_to_ns(_walltime.time()))
        self._sched_at_f = sched_at_f
        self._finishing = False
        # serializes scheduling passes so handle_f invocations are
        # totally ordered (the oracle holds data_mtx across the whole
        # pass; here pull_batch only locks per launch)
        self._dispatch_mtx = threading.Lock()
        self._sched_cv = threading.Condition()
        self._sched_when = TIME_ZERO  # ns; 0 = unarmed
        self._sched_thd = None
        if sched_at_f is None:
            self._sched_thd = threading.Thread(
                target=self._run_sched_ahead, daemon=True,
                name="dmclock-tpu-sched-ahead")
            self._sched_thd.start()

    # ------------------------------------------------------------------
    # embedder API (mirrors oracle PushPriorityQueue)
    # ------------------------------------------------------------------
    def add_request(self, request: Any, client_id: Any,
                    req_params: ReqParams = ReqParams(),
                    time_ns: Optional[int] = None, cost: int = 1) -> int:
        if time_ns is None:
            time_ns = self._now_ns_f()
        r = self._q.add_request(request, client_id, req_params,
                                time_ns=time_ns, cost=cost)
        if r == 0:
            self._schedule_request()
        return r

    def request_completed(self) -> None:
        """Server signals a finished op (reference request_completed
        :1651-1660): capacity may have opened, so re-evaluate."""
        self._schedule_request()

    def shutdown(self) -> None:
        self._finishing = True
        with self._sched_cv:
            self._sched_cv.notify_all()
        if self._sched_thd is not None:
            self._sched_thd.join()
        self._q.shutdown()

    # pass-through inspection / maintenance surface
    def empty(self) -> bool:
        return self._q.empty()

    def client_count(self) -> int:
        return self._q.client_count()

    def request_count(self) -> int:
        return self._q.request_count()

    def update_client_info(self, client_id: Any) -> None:
        self._q.update_client_info(client_id)

    def do_clean(self) -> None:
        self._q.do_clean()

    @property
    def reserv_sched_count(self) -> int:
        return self._q.reserv_sched_count

    @property
    def prop_sched_count(self) -> int:
        return self._q.prop_sched_count

    @property
    def limit_break_sched_count(self) -> int:
        return self._q.limit_break_sched_count

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _schedule_request(self) -> None:
        """One scheduling pass (reference schedule_request :1741-1755 +
        next_request's can_handle gate :1729-1737), batched."""
        with self._dispatch_mtx:
            self._schedule_locked()

    def _schedule_locked(self) -> None:
        while True:
            if self._finishing or not self.can_handle_f():
                return
            if self.capacity_f is not None:
                n = min(self.capacity_f(), self.batch_max)
                if n <= 0:
                    return
            else:
                n = 1  # consult can_handle_f before every dispatch
            now_ns = self._now_ns_f()
            batch = self._q.pull_batch(now_ns, n)
            dispatched = 0
            for pr in batch:
                if pr.is_retn():
                    self.handle_f(pr.client, pr.request, pr.phase,
                                  pr.cost)
                    dispatched += 1
                elif pr.is_future():
                    self._sched_at(pr.when_ready)
                    return
                else:
                    return
            if dispatched < n:
                # fewer decisions than requested: queue went NONE/FUTURE
                # inside the launch; nothing more is eligible right now
                return
            # full batch served -- more may be eligible; loop re-checks
            # the can_handle gate before pulling again

    def _sched_at(self, when_ns: int) -> None:
        # reference sched_at (:1789-1796); the armed-deadline dedup
        # also gates the virtual sched_at_f path
        with self._sched_cv:
            if self._finishing:
                return
            if self._sched_when == TIME_ZERO or \
                    when_ns < self._sched_when:
                self._sched_when = when_ns
                if self._sched_at_f is not None:
                    self._sched_at_f(when_ns)
                else:
                    self._sched_cv.notify_all()

    def sched_ahead_fire(self) -> None:
        """Virtual-time embedding: the ``sched_at_f`` callback landed --
        disarm and re-evaluate scheduling at the (virtual) now."""
        with self._sched_cv:
            if self._finishing:
                return
            self._sched_when = TIME_ZERO
        self._schedule_request()

    def _run_sched_ahead(self) -> None:
        # reference run_sched_ahead (:1760-1786): the armed deadline is
        # only consumed once it has passed; early wakeups re-evaluate
        with self._sched_cv:
            while not self._finishing:
                if self._sched_when == TIME_ZERO:
                    self._sched_cv.wait()
                    continue
                delay_s = (self._sched_when
                           - self._now_ns_f()) / NS_PER_SEC
                if delay_s > 0:
                    self._sched_cv.wait(timeout=delay_s)
                    continue
                self._sched_when = TIME_ZERO
                if self._finishing:
                    return
                self._sched_cv.release()
                try:
                    self._schedule_request()
                finally:
                    self._sched_cv.acquire()
