"""Hand-written Pallas TPU kernels: the timer-wheel scatter/scan.

The repo's first real kernel (ROADMAP "hand-written kernel" item): the
calendar engine's bucket-scatter + occupancy-min-scan
(``kernels.wheel_scan``) fused into ONE gridless pallas_call -- one
read of the lane data from HBM, the whole bucket grid lives in VMEM,
and the nearest-deadline scan happens in-register before a single
store.  The XLA reference lowers the same computation to a scatter-add
+ scatter-min (serializing on TPU) followed by a separate reduction
pass; the kernel is the template for kernelizing the radix histogram
walk next.

Bit-exactness contract (ci.sh wheel smoke gate, interpret mode on
CPU): for any ``(keys, slot, nb)`` the kernel returns EXACTLY
``kernels.wheel_scan(keys, slot, nb)`` -- counts, per-bucket minima,
nearest value, and the found flag.  The int64 keys travel as int32
(hi, lo) lane pairs: ``hi = key >> 32`` keeps the sign, and the low
word is XOR-biased (``lo ^ 0x8000_0000`` wrapped to int32) so SIGNED
int32 comparison of the biased low words equals UNSIGNED comparison
of the raw ones -- the (hi signed, lo unsigned) lexicographic order
IS the int64 order, so per-bucket (min hi, min lo among hi-ties)
reconstructs the exact int64 minimum.

Environment constraints (same stack notes as fastpath's row-rotate
kernel): the remote Mosaic compiler does not legalize gridded
pallas_calls, so the kernel is gridless and loops the lane rows with
``lax.fori_loop``; iotas are 2-D; all temporaries are [sublane, lane]
shaped with the bucket axis on sublanes, which makes the per-row
one-hot compare a plain broadcast with no transposes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .kernels import KEY_INF, WHEEL_GROUPS

_LANES = 128
_I32_MAX = 0x7FFFFFFF
# padded-lane budget for the gridless call: inputs are 3 int32 planes
# (12 B/lane) and the one-hot temp is [nb, 128]; 2^19 lanes keeps the
# whole working set well under the 16MB scoped-VMEM budget
_MAX_LANES = 1 << 19


def wheel_supported(n: int, nb: int) -> bool:
    """Static feasibility of the gridless kernel at [n] lanes and
    ``nb`` buckets (the caller falls back to the XLA reference --
    counted in the pallas_fallbacks metric row -- when False)."""
    padded = -(-n // _LANES) * _LANES
    return padded <= _MAX_LANES and nb % 8 == 0


def _wheel_kernel(bidx_ref, khi_ref, klo_ref, cnt_ref, mhi_ref,
                  mlo_ref, near_ref, *, nb: int, rows: int):
    i32max = jnp.int32(_I32_MAX)
    bid = lax.broadcasted_iota(jnp.int32, (nb, 1), 0)

    # phase A: occupancy count + per-bucket min of the high words.
    # One fori_loop over the [rows, 128] lane grid; each row compares
    # its 128 lane bucket ids against the [nb, 1] bucket column --
    # a broadcast one-hot, reduced along lanes.
    def phase_a(r, c):
        cnt, mhi = c
        oh = bidx_ref[pl.dslice(r, 1), :] == bid        # [nb, 128]
        hi = khi_ref[pl.dslice(r, 1), :]
        cnt = cnt + jnp.sum(oh, axis=1, keepdims=True,
                            dtype=jnp.int32)
        mhi = jnp.minimum(mhi, jnp.min(
            jnp.where(oh, hi, i32max), axis=1, keepdims=True))
        return cnt, mhi

    cnt, mhi = lax.fori_loop(
        0, rows, phase_a,
        (jnp.zeros((nb, 1), jnp.int32),
         jnp.full((nb, 1), i32max, jnp.int32)))

    # phase B: per-bucket min of the biased low words among the lanes
    # that tie the bucket's min high word (lex completion of the
    # int64 min; see module docstring)
    def phase_b(r, mlo):
        oh = bidx_ref[pl.dslice(r, 1), :] == bid
        tie = oh & (khi_ref[pl.dslice(r, 1), :] == mhi)
        return jnp.minimum(mlo, jnp.min(
            jnp.where(tie, klo_ref[pl.dslice(r, 1), :], i32max),
            axis=1, keepdims=True))

    mlo = lax.fori_loop(0, rows, phase_b,
                        jnp.full((nb, 1), i32max, jnp.int32))

    # fused occupancy-min-scan: first occupied bucket and its stored
    # minimum, before anything leaves the kernel
    occ = cnt > 0
    b0 = jnp.min(jnp.where(occ, bid, jnp.int32(nb)))
    at0 = occ & (bid == b0)
    nh = jnp.min(jnp.where(at0, mhi, i32max))
    nl = jnp.min(jnp.where(at0, mlo, i32max))
    found = (b0 < nb).astype(jnp.int32)

    cnt_ref[...] = cnt
    mhi_ref[...] = mhi
    mlo_ref[...] = mlo
    lane = lax.broadcasted_iota(jnp.int32, (8, 1), 0)
    near_ref[...] = jnp.where(
        lane == 0, b0,
        jnp.where(lane == 1, nh,
                  jnp.where(lane == 2, nl,
                            jnp.where(lane == 3, found,
                                      jnp.int32(0)))))


def _recon64(hi, lo_biased):
    """Invert the (hi, biased lo) int32 split back to int64."""
    lo = lo_biased.astype(jnp.int64) + jnp.int64(1 << 31)
    return (hi.astype(jnp.int64) << 32) | lo


def wheel_scan_pallas(keys, slot, nb: int, *,
                      groups: int = WHEEL_GROUPS,
                      interpret: bool = False):
    """Pallas twin of :func:`kernels.wheel_scan`: scatter ``keys``
    into ``nb`` buckets by ``slot`` (``slot == nb`` masks a lane out)
    and scan for the first occupied bucket.  Returns ``(cnt int32[nb],
    bmin int64[nb], nearest int64, found bool)`` bit-identical to the
    XLA reference.  ``groups`` is accepted for signature parity (the
    in-kernel scan needs no grouping)."""
    del groups
    n = keys.shape[0]
    rows = -(-n // _LANES)
    pad = rows * _LANES - n
    if pad:
        keys = jnp.pad(keys, (0, pad))
        slot = jnp.pad(slot, (0, pad), constant_values=nb)
    khi = (keys >> 32).astype(jnp.int32)
    klo = ((keys & jnp.int64(0xFFFFFFFF))
           ^ jnp.int64(0x80000000)).astype(jnp.int32)
    shape = (rows, _LANES)
    out1 = jax.ShapeDtypeStruct((nb, 1), jnp.int32)
    cnt, mhi, mlo, near = pl.pallas_call(
        functools.partial(_wheel_kernel, nb=nb, rows=rows),
        out_shape=[out1, out1, out1,
                   jax.ShapeDtypeStruct((8, 1), jnp.int32)],
        interpret=interpret,
    )(slot.reshape(shape).astype(jnp.int32), khi.reshape(shape),
      klo.reshape(shape))
    cnt = cnt[:, 0]
    bmin = jnp.where(cnt > 0, _recon64(mhi[:, 0], mlo[:, 0]),
                     jnp.int64(KEY_INF))
    found = near[3, 0] > 0
    val = jnp.where(found, _recon64(near[1, 0], near[2, 0]),
                    jnp.int64(KEY_INF))
    return cnt, bmin, val, found
