"""Device-resident scheduler state: the ClientState SoA.

The reference keeps per-client state in heap-linked ``ClientRec`` objects
(``dmclock_server.h:355-499``); here the same information is a struct of
``[capacity]`` arrays living in device memory, so tag updates vectorize
and selection is a masked argmin.  DelayedTagCalc semantics
(``dmclock_server.h:878-893``) are what make a head-only tag
representation sufficient: only the queue-head request of each client
ever carries a real tag, so the device holds full tags for heads and
just (arrival, cost) for the queued tail in a fixed-capacity ring.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class EngineState(NamedTuple):
    """SoA over client slots.  ``capacity`` = len of every [N] array;
    ``ring_capacity`` = Q of the [N, Q] tail rings.

    Mirrors, per slot: ``ClientInfo`` cached inverses
    (``dmclock_server.h:95-132``), ``ClientRec`` bookkeeping (:355-499),
    and the head request's ``RequestTag`` (:135-274).
    """

    # slot bookkeeping
    active: jnp.ndarray       # bool[N]  slot holds a live client
    idle: jnp.ndarray         # bool[N]  ClientRec::idle
    order: jnp.ndarray        # int64[N] creation index = selection tie-break

    # QoS parameters (ClientInfo inverses, ns per unit cost)
    resv_inv: jnp.ndarray     # int64[N]
    weight_inv: jnp.ndarray   # int64[N]
    limit_inv: jnp.ndarray    # int64[N]

    # ClientRec scheduling state
    prop_delta: jnp.ndarray   # int64[N] idle-reactivation shift (:937-985)
    prev_resv: jnp.ndarray    # int64[N] prev_tag.reservation
    prev_prop: jnp.ndarray    # int64[N] prev_tag.proportion
    prev_limit: jnp.ndarray   # int64[N] prev_tag.limit
    prev_arrival: jnp.ndarray  # int64[N] prev_tag.arrival (anticipation)
    cur_rho: jnp.ndarray      # int64[N] latest ReqParams.rho (:378-379)
    cur_delta: jnp.ndarray    # int64[N] latest ReqParams.delta

    # head request tag (the only fully-tagged request per client)
    head_resv: jnp.ndarray    # int64[N]
    head_prop: jnp.ndarray    # int64[N]
    head_limit: jnp.ndarray   # int64[N]
    head_arrival: jnp.ndarray  # int64[N]
    head_cost: jnp.ndarray    # int64[N]
    head_rho: jnp.ndarray     # int64[N] rho the head was tagged with
    head_ready: jnp.ndarray   # bool[N]  RequestTag::ready

    # queued-tail ring (beyond the head): only (arrival, cost) is needed,
    # because delayed tagging reads cur_rho/cur_delta at pop time
    # (update_next_tag, dmclock_server.h:1021-1036)
    depth: jnp.ndarray        # int32[N] request count INCLUDING head
    q_head: jnp.ndarray       # int32[N] ring read index of oldest tail
    q_arrival: jnp.ndarray    # int64[N, Q]
    q_cost: jnp.ndarray       # int64[N, Q]

    @property
    def capacity(self) -> int:
        return self.active.shape[-1]

    @property
    def ring_capacity(self) -> int:
        return self.q_arrival.shape[-1]


# The int64 per-client fields the epoch scans mutate batch to batch:
# tag triples, arrival timestamps, and the served-cost bookkeeping.
# Within one epoch each field's organic values drift only a few ms of
# virtual time, so the scans can carry them as int32 offsets from a
# per-field epoch origin (``kernels.rebase32``/``restore64``) at half
# the loop-carried HBM traffic -- the ``tag_width=32`` knob of
# ``fastpath.scan_prefix_epoch`` and friends.  Everything else in the
# scan carry (depth, q_head, head_ready) is already narrow.
TAG_I64_FIELDS = (
    "head_resv", "head_prop", "head_limit", "head_arrival",
    "head_cost", "head_rho",
    "prev_resv", "prev_prop", "prev_limit", "prev_arrival",
)


# Per-field fill values for slots that do not hold a client yet: the
# exact values ``init_state`` writes.  Growth and slot recycling both
# depend on a fresh slot being INDISTINGUISHABLE from an init-time one
# (the lifecycle plane's dynamic-vs-static digest gate pins this), so
# the fills live here, next to init_state, instead of being re-listed
# by every grower.
_FRESH_FILLS = {
    "active": False, "idle": True, "order": 0,
    "resv_inv": 0, "weight_inv": 0, "limit_inv": 0,
    "prop_delta": 0,
    "prev_resv": 0, "prev_prop": 0, "prev_limit": 0, "prev_arrival": 0,
    "cur_rho": 1, "cur_delta": 1,
    "head_resv": 0, "head_prop": 0, "head_limit": 0, "head_arrival": 0,
    "head_cost": 1, "head_rho": 0, "head_ready": False,
    "depth": 0, "q_head": 0, "q_arrival": 0, "q_cost": 0,
}


def grow_state(state: EngineState, new_capacity: int) -> EngineState:
    """Exact pytree migration to a larger slot capacity: every [N,...]
    leaf is extended along axis 0 with the ``init_state`` fill for its
    field, so slots ``old_n..new_n-1`` are byte-identical to
    freshly-initialized ones and existing slots are untouched.  The
    grow-on-demand half of the lifecycle plane's geometric doubling
    (docs/LIFECYCLE.md); ``TpuPullPriorityQueue`` uses the same
    migration for its capacity doubling."""
    import jax.numpy as _jnp

    old_n = state.capacity
    if new_capacity < old_n:
        # ValueError, not assert: a stripped check would hand
        # jnp.full a negative pad length deep inside the migration
        raise ValueError(
            f"grow_state cannot shrink: {new_capacity} < {old_n}")
    if new_capacity == old_n:
        return state

    def pad(arr, fill):
        ext = _jnp.full((new_capacity - old_n,) + arr.shape[1:], fill,
                        dtype=arr.dtype)
        return _jnp.concatenate([arr, ext], axis=0)

    return EngineState(**{
        f: pad(getattr(state, f), _FRESH_FILLS[f])
        for f in EngineState._fields})


def init_state(capacity: int, ring_capacity: int = 64) -> EngineState:
    """Fresh state: every slot free."""
    n = capacity
    i64 = lambda shape=(n,): jnp.zeros(shape, dtype=jnp.int64)  # noqa: E731
    return EngineState(
        active=jnp.zeros((n,), dtype=bool),
        idle=jnp.ones((n,), dtype=bool),
        order=i64(),
        resv_inv=i64(), weight_inv=i64(), limit_inv=i64(),
        prop_delta=i64(),
        prev_resv=i64(), prev_prop=i64(), prev_limit=i64(),
        prev_arrival=i64(),
        cur_rho=jnp.ones((n,), dtype=jnp.int64),
        cur_delta=jnp.ones((n,), dtype=jnp.int64),
        head_resv=i64(), head_prop=i64(), head_limit=i64(),
        head_arrival=i64(),
        head_cost=jnp.ones((n,), dtype=jnp.int64),
        head_rho=i64(),
        head_ready=jnp.zeros((n,), dtype=bool),
        depth=jnp.zeros((n,), dtype=jnp.int32),
        q_head=jnp.zeros((n,), dtype=jnp.int32),
        q_arrival=i64((n, ring_capacity)),
        q_cost=i64((n, ring_capacity)),
    )
