"""TPU-batched dmClock scheduling engine.

The device-resident replacement for the reference's three intrusive
k-way heaps + mutex design (``/root/reference/src/dmclock_server.h``):
per-client scheduler state lives as ``[capacity]`` SoA arrays in HBM
(`state.py`), the RequestTag recurrence is a vectorized integer kernel,
the three heap min-selections collapse into masked lexicographic argmins
matching the oracle's total order exactly (`kernels.py`), and many
scheduling decisions run per kernel launch via ``lax.scan``
(`engine_run`).  `queue.py` wraps it all in the same Pull-queue API the
oracle scheduler exposes, so the sim harness drives either backend
interchangeably and request ordering can be compared bit-for-bit.

The tag algebra is int64 nanoseconds end to end (see
``dmclock_tpu.core.timebase``), hence the x64 requirement below.
"""

from jax import config as _config

# The canonical tag algebra is int64; without x64 JAX silently truncates
# to int32 and every tag comparison is wrong.
_config.update("jax_enable_x64", True)

from .state import EngineState, init_state  # noqa: E402
from .kernels import engine_step, engine_run, ingest  # noqa: E402
from .queue import TpuPullPriorityQueue  # noqa: E402
from .push_queue import TpuPushPriorityQueue  # noqa: E402

__all__ = [
    "EngineState", "init_state",
    "engine_step", "engine_run", "ingest",
    "TpuPullPriorityQueue", "TpuPushPriorityQueue",
]
