"""Host wrapper: the TPU engine behind the standard Pull-queue API.

``TpuPullPriorityQueue`` speaks the same interface as the oracle
``core.scheduler.PullPriorityQueue`` (itself mirroring the reference
``PullPriorityQueue``, ``dmclock_server.h:1279-1501``), so the sim
harness and tests drive either backend interchangeably.  The host side
owns what cannot live in a compiled graph: client-id <-> slot mapping,
request payload FIFOs, op batching/padding, capacity growth, and GC
bookkeeping.  Everything per-request-hot runs on device.

Restrictions vs the oracle (by design, documented):
- DelayedTagCalc only -- the head-only device representation *is* the
  delayed optimization (reference :277-280).
- AtLimit::Reject IS offered, as a hybrid the reference cannot express
  (it asserts Reject incompatible with delayed calc, :856-857, because
  a delayed queue has no limit tag at add time): the host keeps an
  IMMEDIATE-mode mirror of the limit axis -- prev_limit/prev_arrival
  evolve only on adds (accepted or rejected both advance them, the
  reference's pinned behavior, :989-993), never on serves, so the
  per-client scalar recurrence is exactly computable host-side with
  ``core.tags.tag_calc`` and EAGAIN returns synchronously with no
  device round-trip.  Admission decisions are bit-identical to the
  oracle's immediate-mode Reject queue; scheduling of admitted
  requests stays delayed-tagged on device.
"""

from __future__ import annotations

import errno
import functools
import threading
import time as _walltime
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.qos import ClientInfo
from ..core.recs import Phase, ReqParams
from ..core.scheduler import AtLimit, NextReqType, PullReq
from ..core.tags import tag_calc
from ..core.timebase import MAX_TAG, MIN_TAG, sec_to_ns
from ..obs import compile_plane as _cplane
from ..obs import spans as _spans
from ..robust.guarded import RECOVERABLE_ERRORS, retry_with_backoff
from . import kernels
from .kernels import (OP_ADD, OP_CREATE, OP_NOP, FUTURE, NONE, RETURNING,
                      IngestOps)
from .state import EngineState, grow_state, init_state

ClientInfoFunc = Callable[[Any], Optional[ClientInfo]]


# Module-level jit cache shared across queue instances: a 100-server
# sim builds 100 queues, and per-instance jits would re-TRACE the
# engine for every one of them (tracing a long engine_run scan costs
# seconds; XLA's compile cache only deduplicates after tracing).
# Entries are compile-plane-instrumented (obs.compile_plane): every
# lower+compile is timed and recorded per entry, and a re-trace is
# attributed to the arg-signature diff that caused it.
_JIT_CACHE: Dict[Tuple, Callable] = {}


def _jit_cached(key: Tuple, fn) -> Callable:
    if key not in _JIT_CACHE:
        _JIT_CACHE[key] = _cplane.instrumented_jit(
            fn, cache="queue", entry=key)
    return _JIT_CACHE[key]


def _unpack_ops(packed) -> IngestOps:
    """In-graph split of the packed [10, B] int64 op buffer.  The host
    uploads ONE array per flush instead of ten (each host->device
    transfer costs a device_put; at one flush per sim event the ten
    transfers dominated the TPU-model sim's wall time)."""
    return IngestOps(
        kind=packed[0].astype(jnp.int32),
        slot=packed[1].astype(jnp.int32),
        time=packed[2], cost=packed[3], rho=packed[4],
        delta=packed[5], resv_inv=packed[6], weight_inv=packed[7],
        limit_inv=packed[8], order=packed[9])


def _shared_jit_ingest(anticipation_ns: int):
    def ingest_packed(s, packed):
        return kernels.ingest(s, _unpack_ops(packed),
                              anticipation_ns=anticipation_ns)
    return _jit_cached(("ingest", anticipation_ns), ingest_packed)


def _pack_decisions(dec) -> jnp.ndarray:
    """One int64 [6, steps] array per launch instead of a 6-array
    pytree: each device->host array fetch pays fixed overhead, and the
    sims fetch decisions once per service event."""
    return jnp.stack([
        dec.type.astype(jnp.int64), dec.slot.astype(jnp.int64),
        dec.phase.astype(jnp.int64), dec.cost,
        dec.when, dec.limit_break.astype(jnp.int64)])


def _shared_jit_run(steps: int, advance_now: bool, allow: bool,
                    anticipation_ns: int):
    def run(s, t):
        s, _, dec = kernels.engine_run(
            s, t, steps, allow_limit_break=allow,
            anticipation_ns=anticipation_ns,
            advance_now=advance_now)
        return s, _pack_decisions(dec)
    return _jit_cached(("run", steps, advance_now, allow,
                        anticipation_ns), run)


def _shared_jit_run_horizon(steps: int, allow: bool,
                            anticipation_ns: int):
    def run(s, t):
        s, _, dec, hz = kernels.engine_run(
            s, t, steps, allow_limit_break=allow,
            anticipation_ns=anticipation_ns,
            advance_now=False, with_horizon=True)
        return s, _pack_decisions(dec), hz
    return _jit_cached(("run_h", steps, allow, anticipation_ns), run)


def _stream_windows(s, t0, dt, *, steps: int, chunks: int, allow: bool,
                    anticipation_ns: int):
    """``chunks`` consecutive engine_run windows in one scan: window
    ``c`` serves up to ``steps`` decisions at ``t0 + c * dt``, each on
    the committed state of the previous one -- exactly what ``chunks``
    sequential ``pull_batch`` launches compute.  The ONE window body
    shared by both streaming jit factories, so the schedule and
    decision packing cannot drift between them."""
    def body(st, i):
        st, _, dec = kernels.engine_run(
            st, t0 + i * dt, steps, allow_limit_break=allow,
            anticipation_ns=anticipation_ns, advance_now=False)
        return st, _pack_decisions(dec)

    return jax.lax.scan(body, s, jnp.arange(chunks, dtype=jnp.int64))


def _shared_jit_run_stream(steps: int, chunks: int, allow: bool,
                           anticipation_ns: int):
    """The pull queue's streaming dispatch (docs/ENGINE.md
    "engine_loop"): the :func:`_stream_windows` scan as ONE launch,
    all packed decision blocks stacking in HBM and draining once."""
    def run(s, t0, dt):
        return _stream_windows(
            s, t0, dt, steps=steps, chunks=chunks, allow=allow,
            anticipation_ns=anticipation_ns)
    return _jit_cached(("run_stream", steps, chunks, allow,
                        anticipation_ns), run)


def _shared_jit_ingest_run_stream(steps: int, chunks: int, allow: bool,
                                  anticipation_ns: int):
    """Fused flush + streaming serve: pending op rows ingest once at
    window 0, then the chunked serve scan -- one launch where the
    sequential form pays ``1 + chunks``."""
    ant = anticipation_ns

    def fused(s, packed, t0, dt):
        s = kernels.ingest(s, _unpack_ops(packed),
                           anticipation_ns=ant)
        return _stream_windows(
            s, t0, dt, steps=steps, chunks=chunks, allow=allow,
            anticipation_ns=ant)
    return _jit_cached(("ingest_run_stream", steps, chunks, allow,
                        anticipation_ns), fused)


def _shared_jit_ingest_run(steps: int, advance_now: bool, allow: bool,
                           anticipation_ns: int):
    ant = anticipation_ns

    def fused(s, packed, t):
        s = kernels.ingest(s, _unpack_ops(packed),
                           anticipation_ns=ant)
        s, _, dec = kernels.engine_run(
            s, t, steps, allow_limit_break=allow,
            anticipation_ns=ant, advance_now=advance_now)
        return s, _pack_decisions(dec)
    return _jit_cached(("ingest_run", steps, advance_now, allow,
                        anticipation_ns), fused)




class TpuPullPriorityQueue:
    """Pull-mode dmClock queue on the batched device engine."""

    def __init__(self,
                 client_info_f: ClientInfoFunc,
                 *,
                 at_limit=AtLimit.WAIT,
                 anticipation_timeout_ns: int = 0,
                 # initial sizes only -- both grow by doubling on
                 # demand.  Small defaults matter: every launch is a
                 # dense pass over [capacity] (+ rings), so a 100-client
                 # sim server at capacity 1024 pays 8x the compute of
                 # capacity 128 per decision
                 capacity: int = 128,
                 ring_capacity: int = 16,
                 delayed_tag_calc: bool = True,
                 idle_age_s: float = 300.0,
                 erase_age_s: float = 600.0,
                 erase_max: int = 2000,
                 # speculative decision buffer: pull_request() serves
                 # from a prefetched batch of this size while provably
                 # valid (see _pull_spec); 0 = one launch per pull.
                 # Compile-count coupling: the adaptive prefetch sizes
                 # (powers of two up to this value) and the settle
                 # replay chunks each compile one engine_run program,
                 # so the shared jit cache grows O(log2(batch)), not
                 # O(batch)
                 speculative_batch: int = 0,
                 # guarded-commit contract (docs/ROBUSTNESS.md):
                 # transient device failures are retried this many
                 # times with exponential backoff from retry_base_s
                 # before raising; state only rebinds on success
                 device_retries: int = 3,
                 retry_base_s: float = 0.05,
                 retry_sleep: Callable[[float], None] = None,
                 monotonic_clock: Callable[[], float] =
                 _walltime.monotonic,
                 # time-domain tracing (obs.spans.SpanTracer or None):
                 # host-side spans around every launch -- pack ->
                 # dispatch -> device wait -> fetch -> fold -- the
                 # per-launch dispatch-tax decomposition
                 # (docs/OBSERVABILITY.md tracing plane).  None (the
                 # default) is a single None-check per site; decisions
                 # are bit-identical either way
                 tracer=None):
        assert delayed_tag_calc, \
            "the TPU engine is DelayedTagCalc by construction"
        # a bare number passed for at_limit is a RejectThreshold and
        # implies AtLimit.Reject (reference AtLimitParam :89-93,
        # :829-846); admission runs on the host's immediate-mode limit
        # mirror (module docstring)
        if isinstance(at_limit, AtLimit):
            self.at_limit = at_limit
            self.reject_threshold_ns = 0
        else:
            self.at_limit = AtLimit.REJECT
            self.reject_threshold_ns = int(at_limit)
        self.client_info_f = client_info_f
        self.tracer = tracer
        self.anticipation_timeout_ns = int(anticipation_timeout_ns)
        # host immediate-mode limit mirror (REJECT admission):
        # slot -> (prev_limit, prev_arrival, limit_inv, info cache)
        self._lim_prev: Dict[int, int] = {}
        self._lim_prev_arr: Dict[int, int] = {}
        self._lim_inv: Dict[int, int] = {}

        self.data_mtx = threading.Lock()
        self.state: EngineState = init_state(capacity, ring_capacity)

        # host bookkeeping
        self._slot_of: Dict[Any, int] = {}
        self._client_of: Dict[int, Any] = {}
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._payloads: Dict[int, Deque[Tuple[Any, int, int]]] = {}
        #   slot -> deque of (request, arrival_ns, cost); mirrors the
        #   device queue so payload pops track device pops exactly
        self._next_order = 0
        self._pending: List[Tuple] = []  # buffered IngestOps rows
        self._last_tick: Dict[int, int] = {}
        self.tick = 0

        # GC bookkeeping (oracle do_clean; reference :1206-1255).  The
        # host owns the policy; the device just gets idle/deactivate
        # scatters.  No background thread: embedders call do_clean().
        self.idle_age_s = idle_age_s
        self.erase_age_s = erase_age_s
        self.erase_max = erase_max
        self._monotonic = monotonic_clock
        self._clean_mark_points: Deque[Tuple[float, int]] = deque()
        self._last_erase_point = 0

        # scheduling counters (reference :810-812)
        self.reserv_sched_count = 0
        self.prop_sched_count = 0
        self.limit_break_sched_count = 0

        # host-side per-slot conformance ledger mirroring the device
        # ledger schema (obs.histograms LED_* columns): the pull queue
        # serves through engine_run, which emits no per-decision tags,
        # so the tardiness columns stay 0 here -- ops/resv/lb are
        # exact, and the sims cross-check them against their own
        # host-recomputed conformance tables (docs/OBSERVABILITY.md)
        self._ledger = np.zeros((capacity, 5), dtype=np.int64)
        # host-side SLO window mirror (obs.slo W_* layout; docs/
        # OBSERVABILITY.md "SLO plane"): the push/pull queue's
        # windowed analog of the epoch engines' device block.  The
        # countable columns (ops / cost / resv / limit-break) are
        # exact; the tardiness columns stay 0 for the same reason the
        # ledger's do.  ``update_client_info`` and slot creation bump
        # the per-slot contract-epoch counter, so rolled windows
        # attribute to exactly one contract version; embedders roll
        # via roll_slo_windows() on whatever cadence they serve.
        from ..obs import slo as _obsslo
        self._W = _obsslo
        self._slo_win = np.zeros((capacity, _obsslo.W_FIELDS),
                                 dtype=np.int64)
        self._slo_cepoch = np.zeros(capacity, dtype=np.int64)
        self.slo_window_rolls = 0
        # last-applied QoS inverses per slot: the contract-epoch bump
        # must fire on a REAL ClientInfo change, not on every
        # update_client_infos() refresh sweep (an unchanged-triple
        # bump would fragment the (client, contract_version) series
        # the epoch counter exists to keep whole)
        self._qos_inv: Dict[int, Tuple[int, int, int]] = {}

        # guarded-commit telemetry (docs/ROBUSTNESS.md): launches
        # retried after a transient device error, and adds rejected
        # for an invalid cost (nothing committed either way)
        self.device_retries = int(device_retries)
        self.retry_base_s = float(retry_base_s)
        self._retry_sleep = retry_sleep or _walltime.sleep
        self.guard_retries = 0
        # launches whose bounded retries were EXHAUSTED (the error
        # surfaced to the caller; distinct from guard_retries, which
        # counts recovered attempts) -- the degradation ladder's
        # launch-failure escalation signal
        self.launch_failures = 0
        self.invalid_cost_rejects = 0
        # lifecycle accounting (docs/LIFECYCLE.md): erased clients free
        # their slot for a future tenant; the final conformance-ledger
        # row is folded into the departed-clients report BEFORE the
        # recycle zeroes it, so a client's QoS history is never lost
        # silently
        self.slot_recycles = 0
        self._departed: List[Tuple[Any, np.ndarray]] = []

        # speculative decision buffer (see _pull_spec)
        self._spec = int(speculative_batch)
        self._spec_size = 1 if self._spec else 0  # adaptive, <= _spec
        self.spec_hits = 0        # pulls served launch-free
        self.spec_refills = 0
        self.spec_settles = 0     # invalidations with unconsumed tail
        self.spec_replays = 0     # settle replays (incl. mixed-drain)
        self._buf: Deque[Tuple] = deque()
        self._buf_slots: Dict[int, int] = {}
        self._buf_horizon = 0
        self._spec_pre: Optional[EngineState] = None
        self._spec_t0 = 0
        self._spec_consumed = 0
        self._spec_exact = True   # post-batch state == handed-out state
        self._host_idle: set = set()


    # ------------------------------------------------------------------
    # jit plumbing
    # ------------------------------------------------------------------
    def _jit_ingest(self):
        return _shared_jit_ingest(self.anticipation_timeout_ns)

    def _jit_run(self, steps: int, advance_now: bool):
        return _shared_jit_run(steps, advance_now,
                               self.at_limit is AtLimit.ALLOW,
                               self.anticipation_timeout_ns)

    def _jit_ingest_run(self, steps: int, advance_now: bool):
        """Fused flush + decide: one device launch per pull instead of
        two (launch latency dominates the sim's TPU-backend cost)."""
        return _shared_jit_ingest_run(steps, advance_now,
                                      self.at_limit is AtLimit.ALLOW,
                                      self.anticipation_timeout_ns)

    def _launch(self, fn, *args):
        """Run one device launch under the guarded-commit contract:
        transient failures (a wedged tunnel, a runtime hiccup) retry
        with bounded exponential backoff instead of raising out of the
        serving layer.  Launches are pure jit calls, so a failed
        attempt commits nothing -- callers rebind state only from the
        returned value.  A launch that exhausts its retries bumps
        ``launch_failures`` -- the escalation signal the degradation
        ladder (``robust.guarded.DegradationLadder``) steps down on --
        before re-raising."""
        def on_retry(_attempt, _exc):
            self.guard_retries += 1
            _spans.instant(self.tracer, "queue.retry", "retry",
                           error=type(_exc).__name__)

        def one_attempt():
            # the dispatch span wraps ONE attempt's jit call (a jitted
            # launch returns once dispatched, so this IS the
            # per-launch dispatch tax) -- never the backoff sleeps
            # between failed attempts, which would inflate
            # dispatch_ms_per_launch by retry_base_s per retry (the
            # guarded runner scopes its spans the same way)
            with _spans.span(self.tracer, "queue.launch", "dispatch"):
                return fn(*args)

        try:
            return retry_with_backoff(
                one_attempt, retries=self.device_retries,
                base_s=self.retry_base_s, on_retry=on_retry,
                sleep=self._retry_sleep)
        except RECOVERABLE_ERRORS:
            self.launch_failures += 1
            raise

    def _drain_and_launch(self, fused_fn, plain_fn, *args):
        """The guarded commit-nothing form of every op-consuming
        launch: drain the pending op rows, run ``fused_fn(state, ops,
        *args)`` (or ``plain_fn(state, *args)`` when nothing is
        pending; None = skip the launch entirely), and restore the
        drained rows if the launch ultimately fails so a later attempt
        (or a recovered device) still applies them."""
        rows = self._pending
        with _spans.span(self.tracer, "queue.pack_ops", "host_prep"):
            ops = self._build_ops()
        if ops is None:
            if plain_fn is None:
                return None
            return self._launch(plain_fn, self.state, *args)
        try:
            return self._launch(fused_fn, self.state, ops, *args)
        except Exception:
            self._pending = rows + self._pending
            raise

    # ------------------------------------------------------------------
    # capacity management
    # ------------------------------------------------------------------
    def _grow_capacity(self) -> None:
        self._settle_spec()
        old_n = self.state.capacity
        new_n = old_n * 2
        # the exact pytree migration lives next to init_state
        # (state.grow_state): new slots are byte-identical to
        # freshly-initialized ones
        self.state = grow_state(self.state, new_n)
        self._ledger = np.vstack(
            [self._ledger,
             np.zeros((new_n - old_n, 5), dtype=np.int64)])
        self._slo_win = np.vstack(
            [self._slo_win,
             np.zeros((new_n - old_n, self._W.W_FIELDS),
                      dtype=np.int64)])
        self._slo_cepoch = np.concatenate(
            [self._slo_cepoch,
             np.zeros(new_n - old_n, dtype=np.int64)])
        self._free.extend(range(new_n - 1, old_n - 1, -1))

    def _grow_ring(self) -> None:
        """Double ring capacity, unrolling each row so q_head becomes 0
        (ring positions are modulo ring_capacity, which changes)."""
        self._settle_spec()
        self._flush()
        st = self.state
        q = st.ring_capacity

        def unroll(rows):
            return jax.vmap(lambda row, h: jnp.roll(row, -h))(
                rows, st.q_head)

        q_arrival = jnp.pad(unroll(st.q_arrival), ((0, 0), (0, q)))
        q_cost = jnp.pad(unroll(st.q_cost), ((0, 0), (0, q)))
        self.state = st._replace(
            q_head=jnp.zeros_like(st.q_head),
            q_arrival=q_arrival, q_cost=q_cost)

    # ------------------------------------------------------------------
    # op buffering
    # ------------------------------------------------------------------
    def _build_ops(self):
        """Drain buffered rows into ONE packed [10, padded] int64 array
        (None if empty); the jitted consumers split it in-graph
        (``_unpack_ops``).  A single host->device transfer per flush."""
        if not self._pending:
            return None
        rows = self._pending
        self._pending = []
        n = len(rows)
        # pad to a power of two to bound distinct jit shapes
        padded = 1
        while padded < n:
            padded *= 2
        packed = np.zeros((10, padded), dtype=np.int64)
        packed[:, :n] = np.asarray(rows, dtype=np.int64).T
        return jnp.asarray(packed)

    def _flush(self) -> None:
        res = self._drain_and_launch(self._jit_ingest(), None)
        if res is not None:
            self.state = res

    # ------------------------------------------------------------------
    # public API (mirrors core.scheduler.PullPriorityQueue)
    # ------------------------------------------------------------------
    def add_request(self, request: Any, client_id: Any,
                    req_params: ReqParams = ReqParams(),
                    time_ns: Optional[int] = None, cost: int = 1) -> int:
        # guarded commit: an invalid cost would poison the tag algebra
        # (a non-positive charge breaks monotonicity device-side), so
        # the trip commits NOTHING -- no tick, no create, no limit
        # mirror advance -- and reports EINVAL instead of raising
        try:
            cost = int(cost)
        except (TypeError, ValueError):
            cost = 0
        if cost < 1:
            with self.data_mtx:
                self.invalid_cost_rejects += 1
            return errno.EINVAL
        if time_ns is None:
            time_ns = sec_to_ns(_walltime.time())
        with _spans.span(self.tracer, "queue.add", "ingest"), \
                self.data_mtx:
            self.tick += 1
            slot = self._slot_of.get(client_id)
            created = slot is None
            if created:
                info = self.client_info_f(client_id)
                assert info is not None
                if not self._free:
                    self._grow_capacity()
                slot = self._free.pop()
                self._slot_of[client_id] = slot
                self._client_of[slot] = client_id
                self._payloads[slot] = deque()
                self._pending.append(
                    (OP_CREATE, slot, 0, 0, 0, 0,
                     info.reservation_inv_ns, info.weight_inv_ns,
                     info.limit_inv_ns, self._next_order))
                self._next_order += 1
                self._lim_inv[slot] = info.limit_inv_ns
                self._lim_prev[slot] = 0
                self._lim_prev_arr[slot] = 0
                # a fresh tenancy is a fresh contract version; the
                # per-slot counter is monotone across recycling so
                # versions never repeat (obs.slo discipline)
                self._slo_cepoch[slot] += 1
                self._slo_win[slot] = 0
                self._slo_win[slot, self._W.W_CEPOCH] = \
                    self._slo_cepoch[slot]
                self._qos_inv[slot] = (info.reservation_inv_ns,
                                       info.weight_inv_ns,
                                       info.limit_inv_ns)
            if self.at_limit is AtLimit.REJECT:
                # host immediate-mode limit mirror (module docstring):
                # the axis recurrence depends only on add-time inputs,
                # and a rejected add still advances it (the reference
                # computes the tag -- mutating prev -- before the
                # reject check, pinned by test_reject_at_limit).
                # Known divergence: the reference un-idles a client on
                # a REJECTED add (its reactivation runs before the
                # check, :937-985 vs :989-993); here the device sees
                # no op, so reactivation waits for the next accepted
                # add.
                ant = self.anticipation_timeout_ns
                pa = self._lim_prev_arr[slot]
                t_eff = time_ns - ant if ant and (time_ns - ant) < pa \
                    else time_ns
                lim = tag_calc(t_eff, self._lim_prev[slot],
                               self._lim_inv[slot], req_params.delta,
                               False, cost)
                if lim != MAX_TAG and lim != MIN_TAG:
                    self._lim_prev[slot] = lim
                self._lim_prev_arr[slot] = time_ns
                self._last_tick[slot] = self.tick
                if lim > time_ns + self.reject_threshold_ns:
                    return errno.EAGAIN
            if len(self._payloads[slot]) >= self.state.ring_capacity:
                self._grow_ring()
            self._payloads[slot].append((request, time_ns, cost))
            self._last_tick[slot] = self.tick
            self._pending.append(
                (OP_ADD, slot, time_ns, cost, req_params.rho,
                 req_params.delta, 0, 0, 0, 0))
            if self._buf:
                # interference check (see the speculative-buffer notes):
                # only a pure tail append to a non-idle client with no
                # remaining buffered serve keeps the buffer valid
                fresh = created or len(self._payloads[slot]) == 1
                if fresh or slot in self._buf_slots or \
                        slot in self._host_idle:
                    self._settle_spec()
            self._host_idle.discard(slot)
            return 0

    def _decision_to_pullreq(self, dtype: int, dslot: int, dphase: int,
                             dcost: int, dwhen: int,
                             dlimit_break: bool) -> PullReq:
        if dtype == RETURNING:
            client = self._client_of[dslot]
            request, _arr, _cost = self._payloads[dslot].popleft()
            led = self._ledger[dslot]
            win = self._slo_win[dslot]
            led[0] += 1                      # LED_OPS
            win[self._W.W_OPS] += 1
            win[self._W.W_COST] += int(dcost)
            if dphase == 0:
                self.reserv_sched_count += 1
                led[1] += 1                  # LED_RESV_OPS
                win[self._W.W_RESV_OPS] += 1
                phase = Phase.RESERVATION
            else:
                self.prop_sched_count += 1
                phase = Phase.PRIORITY
            if dlimit_break:
                self.limit_break_sched_count += 1
                led[2] += 1                  # LED_LIMIT_BREAKS
                win[self._W.W_LB_OPS] += 1
            self._last_tick[dslot] = self.tick
            return PullReq(NextReqType.RETURNING, client=client,
                           request=request, phase=phase, cost=int(dcost))
        if dtype == FUTURE:
            return PullReq(NextReqType.FUTURE, when_ready=int(dwhen))
        return PullReq(NextReqType.NONE)

    def pull_request(self, now_ns: Optional[int] = None) -> PullReq:
        if now_ns is None:
            now_ns = sec_to_ns(_walltime.time())
        with self.data_mtx:
            if self._spec:
                return self._pull_spec(now_ns)
            self.state, dec = self._drain_and_launch(
                self._jit_ingest_run(1, False),
                self._jit_run(1, False), now_ns)
            d = self._traced_fetch(dec)
            with _spans.span(self.tracer, "queue.fold", "drain"):
                return self._decision_to_pullreq(
                    int(d[0, 0]), int(d[1, 0]), int(d[2, 0]),
                    int(d[3, 0]), int(d[4, 0]), bool(d[5, 0]))

    def _traced_fetch(self, dec):
        """Fetch a decision array, decomposed for the tracing plane:
        with a tracer attached the device wait (``block_until_ready``)
        and the host transfer (``device_get``) are separate spans, so
        per-launch wall time splits into dispatch / device_compute /
        fetch instead of lumping into one blocking fetch.  Without a
        tracer this is exactly the old single ``device_get`` (no extra
        sync)."""
        if self.tracer is None:
            return jax.device_get(dec)
        with self.tracer.span("queue.device_wait", "device_compute"):
            jax.block_until_ready(dec)
        with self.tracer.span("queue.fetch", "fetch"):
            return jax.device_get(dec)

    # ------------------------------------------------------------------
    # speculative decision buffer
    #
    # One device launch computes a BATCH of decisions at time t0 plus a
    # validity horizon: the earliest reservation/limit tag strictly past
    # t0 present in any intermediate state (engine_run with_horizon).
    # Decisions depend on `now` only through `tag <= now` threshold
    # tests, so for any later pull at t in [t0, horizon) the buffered
    # decision IS the decision a fresh launch would return -- zero
    # launches for buffer hits.  Everything else falls back to exact
    # recomputation:
    #
    # - `self.state` holds the POST-batch device state; `_spec_pre` the
    #   pre-batch state (immutable arrays -- keeping it is free).  When
    #   the buffer is dropped with unconsumed entries -- or drained
    #   after a MIXED batch whose trailing FUTURE/NONE steps performed
    #   never-handed-out promotions (`_spec_exact`) -- _settle_spec
    #   replays exactly the consumed prefix from _spec_pre (same t0,
    #   serial engine), so the logical state never includes an effect
    #   that was not handed to the caller.
    # - adds invalidate the buffer UNLESS provably non-interfering: a
    #   tail append (client already queued) for a client with no
    #   remaining buffered serve and not idle-marked commutes with
    #   every buffered serve (it touches only that client's ring tail /
    #   cur rho-delta, which no remaining buffered decision reads).
    # - every other mutator / state reader settles first.
    # ------------------------------------------------------------------
    def _consume_buf_entry(self) -> PullReq:
        """Pop one buffered decision: consumed-prefix and per-slot
        bookkeeping (the interference check and settle replay both
        depend on these counts staying exact)."""
        self.spec_hits += 1
        d = self._buf.popleft()
        self._spec_consumed += 1
        slot = d[1]
        left = self._buf_slots.get(slot, 0) - 1
        if left <= 0:
            self._buf_slots.pop(slot, None)
        else:
            self._buf_slots[slot] = left
        return self._decision_to_pullreq(*d)

    def _pull_spec(self, now_ns: int) -> PullReq:
        if self._buf and self._spec_t0 <= now_ns < self._buf_horizon:
            return self._consume_buf_entry()
        self.spec_refills += 1
        # adaptive sizing: a fully-drained buffer doubles the next
        # prefetch (up to speculative_batch); an early invalidation
        # resets to 1 (see _settle_spec) so workloads whose every add
        # interferes degrade to exactly the launch-per-pull path with
        # no settle-replay cost
        if self._spec_pre is not None and not self._buf:
            self._spec_size = min(self._spec_size * 2, self._spec)
        self._settle_spec()
        self._flush()
        pre = self.state
        st, dec, hz = self._launch(_shared_jit_run_horizon(
            self._spec_size, self.at_limit is AtLimit.ALLOW,
            self.anticipation_timeout_ns), pre, now_ns)
        self.state = st
        if self.tracer is not None:
            with self.tracer.span("queue.device_wait",
                                  "device_compute"):
                jax.block_until_ready((dec, hz))
        with _spans.span(self.tracer, "queue.fetch", "fetch"):
            d, horizon = jax.device_get((dec, hz))
        first = (int(d[0, 0]), int(d[1, 0]), int(d[2, 0]),
                 int(d[3, 0]), int(d[4, 0]), bool(d[5, 0]))
        self._spec_pre = pre
        self._spec_t0 = now_ns
        self._spec_consumed = 1 if first[0] == RETURNING else 0
        self._buf_horizon = int(horizon)
        n_ret = 0
        while n_ret < d.shape[1] and int(d[0, n_ret]) == RETURNING:
            n_ret += 1
        # the post-batch device state equals the handed-out state only
        # when the batch has no RETURNING/non-RETURNING boundary inside
        # it: all RETURNING (a full drain hands everything out), or
        # non-RETURNING from step 0 (the first FUTURE/NONE is handed
        # out and the later steps are idempotent repeats at fixed t0).
        # A MIXED batch's trailing FUTURE/NONE steps perform head_ready
        # promotions that are never handed to the caller -- _settle_spec
        # must then replay the consumed prefix even after a full drain.
        self._spec_exact = n_ret in (0, d.shape[1])
        for i in range(1, n_ret):
            slot = int(d[1, i])
            self._buf.append((RETURNING, slot, int(d[2, i]),
                              int(d[3, i]), int(d[4, i]),
                              bool(d[5, i])))
            self._buf_slots[slot] = self._buf_slots.get(slot, 0) + 1
        return self._decision_to_pullreq(*first)

    def _settle_spec(self) -> None:
        """Restore `self.state` to the logical state: the pre-batch
        state advanced by exactly the handed-out decisions.

        Replay is needed when buffered entries remain unconsumed, and
        also when a MIXED batch drained fully (see ``_spec_exact``):
        there the post-batch state carries promotions from trailing
        never-handed-out FUTURE/NONE steps.  The replay runs in
        power-of-two chunks (engine_run at fixed t0 composes exactly),
        bounding the jit cache to log2(speculative_batch) replay
        programs instead of one per distinct consumed length."""
        if self._spec_pre is not None:
            if self._buf:
                # early invalidation with an unconsumed tail: reset the
                # adaptive prefetch size
                self.spec_settles += 1
                self._spec_size = 1
            if self._buf or not self._spec_exact:
                # counted separately from spec_settles: a mixed batch
                # that drained fully (empty buffer, inexact) replays
                # too, and the adaptive-size telemetry needs to see
                # that cost (round-4 advisor finding)
                self.spec_replays += 1
                st = self._spec_pre
                n = self._spec_consumed
                while n:
                    p = 1 << (n.bit_length() - 1)
                    st, _ = self._launch(self._jit_run(p, False), st,
                                         self._spec_t0)
                    n -= p
                self.state = st
        self._spec_pre = None
        self._spec_consumed = 0
        self._spec_exact = True
        self._buf.clear()
        self._buf_slots.clear()
        self._buf_horizon = 0

    def settle(self) -> None:
        """Public: make `self.state` exactly reflect every decision
        handed out so far (drops any speculative prefetch).  Call
        before reading `state` externally (checkpointing does)."""
        with self.data_mtx:
            self._settle_spec()

    def pull_batch(self, now_ns: int, max_decisions: int,
                   advance_now: bool = False) -> List[PullReq]:
        """Up to ``max_decisions`` pulls in ONE device launch.

        Returns the decision stream: RETURNING entries in service order;
        the first non-RETURNING entry (FUTURE/NONE) terminates the list
        (with ``advance_now`` the clock jumps over FUTUREs instead, so
        only a trailing NONE terminates)."""
        with self.data_mtx:
            out: List[PullReq] = []
            if self._spec and not advance_now:
                # drain the still-valid speculative prefix first: these
                # are exactly the pulls a launch at this now would
                # return, and a fully-drained buffer makes the settle
                # below free (no replay)
                while (len(out) < max_decisions and self._buf and
                       self._spec_t0 <= now_ns < self._buf_horizon):
                    out.append(self._consume_buf_entry())
                if len(out) == max_decisions:
                    return out
            max_decisions -= len(out)
            self._settle_spec()
            self.state, dec = self._drain_and_launch(
                self._jit_ingest_run(max_decisions, advance_now),
                self._jit_run(max_decisions, advance_now), now_ns)
            d = self._traced_fetch(dec)
            for i in range(d.shape[1]):
                pr = self._decision_to_pullreq(
                    int(d[0, i]), int(d[1, i]), int(d[2, i]),
                    int(d[3, i]), int(d[4, i]), bool(d[5, i]))
                if pr.is_retn():
                    out.append(pr)
                elif advance_now and pr.is_future():
                    continue
                else:
                    out.append(pr)
                    break
            return out

    def pull_batch_stream(self, t0_ns: int, dt_ns: int, chunks: int,
                          max_decisions: int) -> List[List[PullReq]]:
        """``chunks`` consecutive ``pull_batch`` windows in ONE device
        launch -- the streaming serve loop at the pull-queue layer
        (docs/ENGINE.md "engine_loop"): window ``c`` serves at ``t0 +
        c * dt`` on the committed state of window ``c - 1``, the
        decision blocks accumulate in HBM, and the host drains them
        once per chunk instead of once per window.  Pending adds flush
        fused into window 0, and the launch runs under the same
        guarded-commit retry contract as every other launch (state
        rebinds only on success) -- dispatch and retry both at
        stream-chunk granularity.

        Bit-identical to ``chunks`` sequential ``pull_batch(t0 + c *
        dt, max_decisions)`` calls with no adds interleaved (pinned in
        tests/test_stream.py).  Returns one decision list per window,
        each terminated like ``pull_batch``'s."""
        assert chunks >= 1 and max_decisions >= 1
        with self.data_mtx:
            out: List[List[PullReq]] = []
            self._settle_spec()
            self.state, packs = self._drain_and_launch(
                _shared_jit_ingest_run_stream(
                    max_decisions, chunks,
                    self.at_limit is AtLimit.ALLOW,
                    self.anticipation_timeout_ns),
                _shared_jit_run_stream(
                    max_decisions, chunks,
                    self.at_limit is AtLimit.ALLOW,
                    self.anticipation_timeout_ns),
                t0_ns, dt_ns)
            d_all = self._traced_fetch(packs)   # [chunks, 6, steps]
            for c in range(chunks):
                d = d_all[c]
                rows: List[PullReq] = []
                for i in range(d.shape[1]):
                    pr = self._decision_to_pullreq(
                        int(d[0, i]), int(d[1, i]), int(d[2, i]),
                        int(d[3, i]), int(d[4, i]), bool(d[5, i]))
                    rows.append(pr)
                    if not pr.is_retn():
                        break
                out.append(rows)
            return out

    # ------------------------------------------------------------------
    # observability (obs.registry wiring)
    # ------------------------------------------------------------------
    def register_metrics(self, registry, labels=None) -> None:
        """Expose the scheduling counters and the speculative-buffer
        telemetry as callback gauges (zero hot-path cost; same metric
        names as the oracle queue so dashboards don't care which
        backend served)."""
        rows = (
            ("dmclock_sched_reservation_total", "reserv_sched_count",
             "scheduling decisions by phase"),
            ("dmclock_sched_priority_total", "prop_sched_count",
             "scheduling decisions by phase"),
            ("dmclock_sched_limit_break_total",
             "limit_break_sched_count", "scheduling decisions by phase"),
            ("dmclock_spec_hits_total", "spec_hits",
             "pulls served launch-free from the speculative buffer"),
            ("dmclock_spec_refills_total", "spec_refills",
             "speculative buffer refill launches"),
            ("dmclock_spec_settles_total", "spec_settles",
             "speculative invalidations with an unconsumed tail"),
            ("dmclock_spec_replays_total", "spec_replays",
             "settle replays (incl. mixed-drain)"),
            ("dmclock_guard_retries_total", "guard_retries",
             "device launches retried after a transient failure "
             "(guarded-commit contract, docs/ROBUSTNESS.md)"),
            ("dmclock_launch_failures_total", "launch_failures",
             "device launches that exhausted their bounded retries "
             "(degradation-ladder escalation signal)"),
            ("dmclock_invalid_cost_rejects_total",
             "invalid_cost_rejects",
             "adds rejected for a non-positive cost (EINVAL, "
             "nothing committed)"),
            ("dmclock_slot_recycles_total", "slot_recycles",
             "client slots erased and freed for a future tenant "
             "(do_clean erase; the final ledger row folds into the "
             "departed-clients report before it is zeroed)"),
        )
        for name, attr, help_text in rows:
            registry.gauge(name, help_text, labels=labels).set_function(
                lambda a=attr: getattr(self, a))
        registry.gauge("dmclock_clients", "tracked client records",
                       labels=labels).set_function(
            lambda: len(self._slot_of))
        # ledger column totals as callback gauges (per-client series
        # would explode the scrape; the table drains via ledger_rows)
        for col, cname in ((0, "ops"), (1, "resv_ops"),
                           (2, "limit_breaks")):
            registry.gauge(f"dmclock_ledger_{cname}",
                           "host conformance-ledger column total "
                           "(pull-queue mirror of the device ledger "
                           "schema; docs/OBSERVABILITY.md)",
                           labels=labels).set_function(
                lambda c=col: self._ledger_total(c))

    def _ledger_total(self, col: int) -> int:
        """Scrape-thread read of a ledger column under the data lock:
        the serve path mutates rows (and _grow_capacity swaps the
        whole array) under ``data_mtx``, and an unlocked sum could
        report mutually inconsistent column totals mid-serve."""
        with self.data_mtx:
            return int(self._ledger[:, col].sum())

    def departed_report(self, drain: bool = True
                        ) -> List[Tuple[Any, np.ndarray]]:
        """The departed-clients report: ``(client id, int64[5] final
        ledger row)`` for every client erased since the last drain, in
        eviction order (LED_* column layout, ``obs.histograms``).
        ``drain=False`` peeks without clearing.  This is where the
        conformance history of a recycled slot goes instead of being
        zeroed silently (docs/LIFECYCLE.md)."""
        with self.data_mtx:
            out = list(self._departed)
            if drain:
                self._departed.clear()
            return out

    def ledger_rows(self) -> Dict[Any, np.ndarray]:
        """Per-client conformance-ledger rows (client id -> int64[5]
        in the ``obs.histograms`` LED_* column order).  The pull
        queue's host mirror of the device ledger: ops/resv/lb exact,
        tardiness columns 0 (engine_run emits no per-decision tags).
        Sims cross-check their host-recomputed conformance tables
        against this (``SimReport.ledger_check``)."""
        with self.data_mtx:
            return {cid: self._ledger[slot].copy()
                    for cid, slot in self._slot_of.items()}

    def slo_window_rows(self) -> Dict[Any, np.ndarray]:
        """The OPEN window per live client (client id -> int64
        ``obs.slo`` W_* row): the push/pull queue's host mirror of the
        device window block -- countable columns exact, tardiness
        columns 0 (the ledger_rows caveat applies)."""
        with self.data_mtx:
            return {cid: self._slo_win[slot].copy()
                    for cid, slot in self._slot_of.items()}

    def roll_slo_windows(self) -> List[dict]:
        """Close the open window of every live client with activity:
        returns ``[{client, contract_epoch, ops, cost, resv_ops,
        lb_ops}]`` rows and zeroes the counters (the contract-epoch
        stamp survives).  Embedders call this on their own serving
        cadence; a client updated mid-window reports its whole window
        against the version live at close (the epoch engines avoid
        even that by pinning rolls to the lifecycle boundary grid)."""
        W = self._W
        with self.data_mtx:
            out = []
            for cid, slot in sorted(self._slot_of.items(),
                                    key=lambda kv: kv[1]):
                row = self._slo_win[slot]
                if not row[:W.W_CEPOCH].any():
                    continue
                out.append({"client": cid,
                            "contract_epoch": int(row[W.W_CEPOCH]),
                            "ops": int(row[W.W_OPS]),
                            "cost": int(row[W.W_COST]),
                            "resv_ops": int(row[W.W_RESV_OPS]),
                            "lb_ops": int(row[W.W_LB_OPS])})
                row[:W.W_CEPOCH] = 0
            self.slo_window_rolls += 1
            return out

    # ------------------------------------------------------------------
    # inspection (host mirrors; reference :545-564)
    # ------------------------------------------------------------------
    def empty(self) -> bool:
        with self.data_mtx:
            return all(not q for q in self._payloads.values()) \
                and not any(op[0] == OP_ADD for op in self._pending)

    def client_count(self) -> int:
        with self.data_mtx:
            return len(self._slot_of)

    def request_count(self) -> int:
        with self.data_mtx:
            return sum(len(q) for q in self._payloads.values())

    def display_queues(self) -> str:
        """Debug dump of the three selection orders from device state
        (oracle display_queues / reference :676-697): one line per
        'heap', clients sorted by that heap's total order, showing the
        head tag as R/P/L/ready."""
        with self.data_mtx:
            self._settle_spec()
            self._flush()
            st = jax.device_get(self.state)
            rows = []
            for cid, slot in self._slot_of.items():
                has_req = bool(st.active[slot]) and int(st.depth[slot]) > 0
                # rows carry BOTH the raw proportion tag (displayed, so
                # dumps diff cleanly against the oracle/native dumps,
                # which print the raw tag) and the effective tag
                # (raw + prop_delta, the actual ready-heap sort key)
                raw_p = int(st.head_prop[slot])
                rows.append((
                    cid, int(st.order[slot]), has_req,
                    int(st.head_resv[slot]),
                    raw_p + int(st.prop_delta[slot]),
                    int(st.head_limit[slot]),
                    bool(st.head_ready[slot]), raw_p))

            def fmt(r):
                cid, _o, has_req, rt, _eff, lt, ready, pt = r
                return f"{cid}:" + (
                    f"R{rt}/P{pt}/L{lt}/{'ready' if ready else 'wait'}"
                    if has_req else "noreq")

            def section(name, key):
                order = sorted(rows, key=key)
                return name + ": " + " | ".join(fmt(r) for r in order)

            # requestless clients sort last BY CREATION ORDER (their
            # head_* fields hold stale last-served tags; the oracle
            # keys requestless clients on order alone)
            return "\n".join([
                section("RESER",
                        lambda r: (not r[2], r[3] if r[2] else 0, r[1])),
                section("LIMIT",
                        lambda r: (not r[2], r[6] if r[2] else False,
                                   r[5] if r[2] else 0, r[1])),
                section("READY",
                        lambda r: (not r[2],
                                   (not r[6]) if r[2] else False,
                                   r[4] if r[2] else 0, r[1])),
            ])

    # ------------------------------------------------------------------
    # removal / info updates (reference :567-648)
    # ------------------------------------------------------------------
    def update_client_info(self, client_id: Any) -> None:
        with self.data_mtx:
            slot = self._slot_of.get(client_id)
            if slot is None:
                return
            # flush first: a buffered OP_CREATE for this slot would
            # otherwise replay stale inverses over the update
            self._settle_spec()
            self._flush()
            info = self.client_info_f(client_id)
            st = self.state
            self.state = st._replace(
                resv_inv=st.resv_inv.at[slot].set(info.reservation_inv_ns),
                weight_inv=st.weight_inv.at[slot].set(info.weight_inv_ns),
                limit_inv=st.limit_inv.at[slot].set(info.limit_inv_ns))
            # a live ClientInfo replacement is a new contract version
            # -- but only a REAL one: refresh sweeps
            # (update_client_infos) re-apply unchanged triples, and
            # bumping on those would fragment the version series.
            # The open window keeps accumulating (it spans the
            # update; the NEXT roll attributes it to the stamped
            # epoch, which is the version live at close -- embedders
            # that need clean attribution roll right before updating)
            triple = (info.reservation_inv_ns, info.weight_inv_ns,
                      info.limit_inv_ns)
            if self._qos_inv.get(slot) != triple:
                self._qos_inv[slot] = triple
                self._slo_cepoch[slot] += 1
                self._slo_win[slot, self._W.W_CEPOCH] = \
                    self._slo_cepoch[slot]

    def update_client_infos(self) -> None:
        for client_id in list(self._slot_of):
            self.update_client_info(client_id)

    def remove_by_client(self, client: Any, reverse: bool = False,
                         accum: Optional[Callable[[Any], None]] = None
                         ) -> None:
        with self.data_mtx:
            slot = self._slot_of.get(client)
            if slot is None:
                return
            self._settle_spec()
            self._flush()
            q = self._payloads[slot]
            items = list(reversed(q)) if reverse else list(q)
            if accum is not None:
                for request, _a, _c in items:
                    accum(request)
            q.clear()
            self.state = self.state._replace(
                depth=self.state.depth.at[slot].set(0))

    def remove_by_req_filter(self, filter_accum: Callable[[Any], bool],
                             visit_backwards: bool = False) -> bool:
        """Filtered removal (reference :567-605).  Rare/administrative,
        so it syncs the affected clients' queues host<->device."""
        with self.data_mtx:
            self._settle_spec()
            self._flush()
            any_removed = False
            for slot, q in self._payloads.items():
                if not q:
                    continue
                entries = list(q)
                idxs = range(len(entries) - 1, -1, -1) if visit_backwards \
                    else range(len(entries))
                removed = [False] * len(entries)
                for i in idxs:
                    if filter_accum(entries[i][0]):
                        removed[i] = True
                        any_removed = True
                if not any(removed):
                    continue
                kept = [e for e, r in zip(entries, removed) if not r]
                self._payloads[slot] = deque(kept)
                self._resync_client(slot, head_removed=removed[0],
                                    kept=kept)
            return any_removed

    def _resync_client(self, slot: int, head_removed: bool,
                       kept: List[Tuple[Any, int, int]]) -> None:
        """Rewrite one client's device queue after host-side removal.

        Matches oracle semantics: surviving requests keep their current
        tags -- the old head keeps its real tag; a promoted former-tail
        request carries the delayed-calc zero tag until it is tagged at
        pop time (oracle ClientRec.remove_by_req_filter + _initial_tag)."""
        st = self.state
        n = len(kept)
        ring = st.ring_capacity
        arrs = np.zeros(ring, dtype=np.int64)
        costs = np.zeros(ring, dtype=np.int64)
        for i, (_req, a, c) in enumerate(kept[1:]):
            arrs[i], costs[i] = a, c
        updates = dict(
            depth=st.depth.at[slot].set(n),
            q_head=st.q_head.at[slot].set(0),
            q_arrival=st.q_arrival.at[slot].set(jnp.asarray(arrs)),
            q_cost=st.q_cost.at[slot].set(jnp.asarray(costs)),
        )
        if head_removed and n > 0:
            _req, a, c = kept[0]
            updates.update(
                head_resv=st.head_resv.at[slot].set(0),
                head_prop=st.head_prop.at[slot].set(0),
                head_limit=st.head_limit.at[slot].set(0),
                head_arrival=st.head_arrival.at[slot].set(a),
                head_cost=st.head_cost.at[slot].set(c),
                head_rho=st.head_rho.at[slot].set(0),
                head_ready=st.head_ready.at[slot].set(False),
            )
        self.state = st._replace(**updates)

    def do_clean(self) -> None:
        """Idle-mark / erase long-inactive clients (oracle do_clean;
        reference :1206-1255), freeing slots for reuse."""
        now = self._monotonic()
        with self.data_mtx:
            self._settle_spec()
            self._flush()
            self._clean_mark_points.append((now, self.tick))

            erase_point = self._last_erase_point
            while self._clean_mark_points and \
                    self._clean_mark_points[0][0] <= now - self.erase_age_s:
                self._last_erase_point = self._clean_mark_points[0][1]
                erase_point = self._last_erase_point
                self._clean_mark_points.popleft()

            idle_point = 0
            for t, tick in self._clean_mark_points:
                if t <= now - self.idle_age_s:
                    idle_point = tick
                else:
                    break

            if not (erase_point or idle_point):
                return
            erase_slots: List[int] = []
            idle_slots: List[int] = []
            for slot, last in list(self._last_tick.items()):
                if erase_point and len(erase_slots) < self.erase_max \
                        and last <= erase_point:
                    erase_slots.append(slot)
                elif idle_point and last <= idle_point:
                    idle_slots.append(slot)
            if idle_slots:
                self.state = kernels.mark_idle(
                    self.state, jnp.asarray(idle_slots, dtype=jnp.int32))
                # a later add to an idle client reactivates (prop_delta
                # shift) -- the speculative buffer must not survive it
                self._host_idle.update(idle_slots)
            if erase_slots:
                self.state = kernels.deactivate(
                    self.state, jnp.asarray(erase_slots, dtype=jnp.int32))
                for slot in erase_slots:
                    client = self._client_of.pop(slot)
                    del self._slot_of[client]
                    del self._payloads[slot]
                    del self._last_tick[slot]
                    self._host_idle.discard(slot)
                    # recycled slots start with a fresh ledger row --
                    # a new tenant must not inherit the old one's
                    # conformance history.  The evicted client's FINAL
                    # row folds into the departed-clients report
                    # before the zero (drained via departed_report),
                    # and the recycle is counted -- a silently zeroed
                    # row would erase QoS history with no trace
                    self.slot_recycles += 1
                    self._departed.append((client,
                                           self._ledger[slot].copy()))
                    self._ledger[slot] = 0
                    # the open SLO window goes with the tenancy (its
                    # cumulative history is the ledger row above); the
                    # contract-epoch counter stays monotone so the
                    # next tenant gets a fresh version
                    self._slo_win[slot] = 0
                    self._free.append(slot)
            if len(erase_slots) < self.erase_max:
                self._last_erase_point = 0

    def shutdown(self) -> None:
        pass
