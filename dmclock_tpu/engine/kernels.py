"""Jitted dmClock kernels: tag recurrence, fused selection, batched run.

Device-side equivalents of the reference's hot path, semantics pinned by
the Python oracle (``dmclock_tpu.core.scheduler``) which is itself a
re-implementation of ``/root/reference/src/dmclock_server.h``:

- ``_make_tag``     = RequestTag recurrence / ``tag_calc`` (:145-183, :246-259)
- ``engine_step``   = ``do_next_request`` (:1115-1186) +
                      ``pop_process_request``/``update_next_tag`` (:1021-1073) +
                      ``reduce_reservation_tags`` (:1077-1111),
                      fused into one launch.  The three heap tops become
                      masked lexicographic argmins over the same total
                      order the oracle sorts by (tag, then creation
                      order), which is what makes cross-backend request
                      ordering bit-exact.
- ``engine_run``    = ``lax.scan`` of engine_step: many scheduling
                      decisions per launch (the batching that buys TPU
                      throughput).
- ``ingest``        = ``do_add_request`` (:913-1018) over a scanned op
                      batch, including idle-reactivation prop_delta
                      (:937-985) as a free masked min instead of the
                      reference's O(n) scan.

All arithmetic is int64 ns (see ``core.timebase``).  Everything here is
pure and jittable; config axes (AtLimit, anticipation) are static args
closed over by the queue wrapper's jit instances.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..core.timebase import (MAX_CHARGE_UNITS, MAX_TAG, MIN_TAG,
                             LOWEST_PROP_TAG_TRIGGER, ORGANIC_TAG_CAP,
                             TIME_MAX)
from .state import EngineState

# Masking sentinel for argmin keys: strictly above every legal key
# (tags are <= MAX_TAG = 2^62; effective proportions reach ~1.5*2^62).
KEY_INF = (1 << 63) - 1

# Decision type codes (== core.scheduler.NextReqType values)
RETURNING = 0
FUTURE = 1
NONE = 2


class Decision(NamedTuple):
    """One scheduling decision, device-side (oracle: NextReq/PullReq)."""

    type: jnp.ndarray         # int32: RETURNING/FUTURE/NONE
    slot: jnp.ndarray         # int32: winning client slot (-1 if none)
    phase: jnp.ndarray        # int32: 0 reservation, 1 priority
    cost: jnp.ndarray         # int64: served request cost
    when: jnp.ndarray         # int64: FUTURE wake-up time (ns)
    limit_break: jnp.ndarray  # bool: served via AtLimit::Allow fallback


# ----------------------------------------------------------------------
# tag algebra (vector form of core.tags)
# ----------------------------------------------------------------------

def _tag_axis(time_ns, prev, inv, dist, extreme_is_high: bool, cost):
    """One tag axis (reference tag_calc, dmclock_server.h:246-259)."""
    units = jnp.minimum(dist + cost, MAX_CHARGE_UNITS)
    organic = jnp.minimum(jnp.maximum(time_ns, prev + inv * units),
                          ORGANIC_TAG_CAP)
    sentinel = MAX_TAG if extreme_is_high else MIN_TAG
    return jnp.where(inv == 0, sentinel, organic)


def _make_tag(prev_r, prev_p, prev_l, prev_arrival,
              r_inv, w_inv, l_inv, delta, rho, time_ns, cost,
              anticipation_ns: int):
    """The RequestTag recurrence (reference :145-183): reservation uses
    rho, proportion/limit use delta; anticipation backdates arrivals
    within the window of the previous arrival (:159-161)."""
    backdate = (time_ns - anticipation_ns) < prev_arrival
    max_time = jnp.where(backdate, time_ns - anticipation_ns, time_ns)
    r = _tag_axis(max_time, prev_r, r_inv, rho, True, cost)
    p = _tag_axis(max_time, prev_p, w_inv, delta, True, cost)
    l = _tag_axis(max_time, prev_l, l_inv, delta, False, cost)
    return r, p, l


def _fold_prev(prev, tag):
    """prev_tag update skips pinned sentinels
    (oracle ClientRec.update_req_tag; reference :399-412)."""
    pinned = (tag == MAX_TAG) | (tag == MIN_TAG)
    return jnp.where(pinned, prev, tag)


def _min_not_0(current, possible):
    """min where 0 means "no time" (reference min_not_0_time :1192-1195)."""
    return jnp.where(possible == 0, current,
                     jnp.minimum(current, possible))


# ----------------------------------------------------------------------
# int32 epoch tag rebase
# ----------------------------------------------------------------------
#
# The epoch scans carry ~10 int64 [N] tag/arrival/cost arrays through
# every iteration; at bench shapes that loop-carried traffic is the
# bulk of the remaining elementwise cost (PROFILE.md headroom item).
# Within one epoch the organic values of each field move only a few ms
# of virtual time, so they fit an int32 offset from a per-field origin
# with ~2.1s (+-2^31 ns) to spare.  ``rebase32``/``restore64`` are the
# exact (window-checked) conversion pair: sentinels (MAX_TAG/MIN_TAG,
# which pin tags of disabled QoS axes) map to reserved int32 codes, and
# any organic value outside the window fails the check -- the caller
# must then stay on (fall back to) the int64 path.  Round-trip
# bit-exactness inside the window is pinned by tests/test_radix.py.

I32_MAX_TAG = (1 << 31) - 1     # reserved code for MAX_TAG
I32_MIN_TAG = -(1 << 31)        # reserved code for MIN_TAG
# organic window: strictly inside the reserved codes, with a small
# margin so clamped garbage can never alias a sentinel
_I32_WINDOW = (1 << 31) - 8


def rebase32(vals, origin):
    """Rebase int64 tags to int32 around ``origin``.

    Returns ``(vals32, ok)``: exact sentinel mapping for MAX_TAG /
    MIN_TAG, exact offset for organic values within +-(2^31 - 8) of
    ``origin``; ``ok`` is False when any organic value falls outside
    the window (the conversion result must then be discarded)."""
    is_max = vals == MAX_TAG
    is_min = vals == MIN_TAG
    rel = vals - origin
    in_win = (rel > -_I32_WINDOW) & (rel < _I32_WINDOW)
    ok = jnp.all(is_max | is_min | in_win)
    v32 = jnp.where(
        is_max, jnp.int64(I32_MAX_TAG),
        jnp.where(is_min, jnp.int64(I32_MIN_TAG),
                  jnp.clip(rel, -_I32_WINDOW, _I32_WINDOW)))
    return v32.astype(jnp.int32), ok


def restore64(vals32, origin):
    """Exact inverse of :func:`rebase32` for in-window conversions."""
    v = vals32.astype(jnp.int64)
    return jnp.where(vals32 == I32_MAX_TAG, jnp.int64(MAX_TAG),
                     jnp.where(vals32 == I32_MIN_TAG, jnp.int64(MIN_TAG),
                               v + origin))


# ----------------------------------------------------------------------
# dense-histogram (radix) key selection
# ----------------------------------------------------------------------
#
# Shared machinery: the prefix engine's k-selection
# (``fastpath._select_radix``) and the calendar engine's bucketed
# stop-key ladder both need order statistics of an int64 key vector
# without sorting it.  Multi-pass dense histograms walk the key space
# top-down to the exact kk-th smallest element -- O(N) work per round,
# no sort, no scatter, no scalar gathers (masked reductions only,
# PROFILE.md finding 10).  Digit width: dense one-hot histograms cost
# rounds * 2^bits * N compares = (64/b) * 2^b * N, minimized at small
# b; 4-bit digits (16 rounds of 16-bucket histograms) cost 8x less
# than 8-bit ones and keep every round a pure vectorized
# compare+reduce.

RADIX_BITS = 4
RADIX_SPAN = 1 << RADIX_BITS


def radix_kth_key(pk, kk):
    """Exact value of the ``kk``-th smallest element of ``pk``
    (1-indexed, duplicates counted) via 16 rounds of 4-bit dense
    histograms over the int64 key space.  ``kk`` may be a static int
    or a traced int32 scalar (the calendar quantile ladder passes
    traced CDF ranks).  ``pk`` must be non-negative (packed keys and
    the KEY_INF sentinel both are)."""
    buckets = jnp.arange(RADIX_SPAN, dtype=jnp.int64)
    lanes = jnp.arange(RADIX_SPAN, dtype=jnp.int32)
    prefix = jnp.int64(0)
    remaining = jnp.asarray(kk, dtype=jnp.int32)
    active = jnp.ones(pk.shape, dtype=bool)
    for shift in range(64 - RADIX_BITS, -1, -RADIX_BITS):
        digit = (pk >> shift) & (RADIX_SPAN - 1)
        hist = jnp.sum(active[None, :] & (digit[None, :]
                                          == buckets[:, None]),
                       axis=1, dtype=jnp.int32)
        cum = jnp.cumsum(hist)
        sel = jnp.argmax(cum >= remaining).astype(jnp.int32)
        below = jnp.sum(jnp.where(lanes < sel, hist, 0))
        remaining = remaining - below
        prefix = prefix | (sel.astype(jnp.int64) << shift)
        active = active & (digit == sel.astype(jnp.int64))
    return prefix


def radix_quantile_ladder(pk, levels: int):
    """CDF quantile ladder of the FINITE entries of ``pk``: boundary i
    (1-indexed) is the ``ceil(i * C / levels)``-th smallest key, where
    C counts entries strictly below KEY_INF.  Returns nondecreasing
    int64[levels] (all KEY_INF when nothing is finite).

    This is the calendar engine's bucketed-commit planner
    (docs/ENGINE.md): the per-client stop keys of a measure pass
    histogram into the ladder B_1 <= ... <= B_levels that predicts
    where successive refreshed-budget commit levels will land on a
    skewed stop distribution."""
    fin = jnp.sum((pk < jnp.int64(KEY_INF)).astype(jnp.int32))
    lv = jnp.arange(1, levels + 1, dtype=jnp.int32)
    ranks = jnp.maximum((lv * fin + levels - 1) // levels,
                        jnp.int32(1))
    return jax.vmap(lambda r: radix_kth_key(pk, r))(ranks)


# ----------------------------------------------------------------------
# timer-wheel scatter/scan: bucketed calendars over the key space
# ----------------------------------------------------------------------
#
# The timer-wheel primitives behind the calendar engine's
# calendar_impl="wheel": keys scatter into a fixed grid of buckets
# (count + exact per-bucket minimum), and nearest-deadline is an
# O(buckets) hierarchical occupancy scan (coarse group any-reduction,
# then first-set fine bucket) instead of a dense min over N lanes.
# The exactness argument is one line: ``wheel_slot`` is monotone
# NONDECREASING in the key for ANY origin/shift (out-of-span keys
# clamp to the edge buckets, which preserves monotonicity), so the
# first occupied bucket contains the global masked minimum and its
# stored ``bmin`` -- a scatter-min of the ACTUAL keys, not a bucket
# edge -- IS that minimum, bit for bit.  Geometry therefore only
# affects discrimination (how many keys share the clamp buckets),
# never the result.

WHEEL_GROUPS = 8


def wheel_slot(key, origin, shift: int, nb: int):
    """Bucket index of ``key`` on a wheel of ``nb`` buckets of width
    ``2**shift`` ns starting at ``origin``.  Out-of-span keys clamp to
    the edge buckets (monotone, hence exact -- see section comment)."""
    rel = (key - origin) >> shift
    return jnp.clip(rel, 0, nb - 1).astype(jnp.int32)


def wheel_scatter(keys, slot, nb: int):
    """Scatter ``keys`` into ``nb`` buckets: per-bucket occupancy
    count and exact minimum key.  ``slot == nb`` masks a lane out
    (dropped by the scatter).  Returns ``(cnt int32[nb],
    bmin int64[nb])`` with KEY_INF in empty buckets."""
    cnt = jnp.zeros((nb,), jnp.int32).at[slot].add(
        jnp.int32(1), mode="drop")
    bmin = jnp.full((nb,), jnp.int64(KEY_INF)).at[slot].min(
        keys, mode="drop")
    return cnt, bmin


def wheel_nearest(cnt, bmin, groups: int = WHEEL_GROUPS):
    """O(buckets) nearest-deadline: hierarchical occupancy ffs --
    coarse any-reduction over ``groups`` bucket groups, argmax picks
    the first occupied group, a dynamic slice finds its first occupied
    fine bucket -- then the bucket's stored min.  Returns
    ``(val, b0, found)`` with ``val = KEY_INF`` and ``b0 = nb`` when
    every bucket is empty."""
    nb = cnt.shape[0]
    gw = nb // groups
    occ = cnt > 0
    gany = jnp.any(occ.reshape(groups, gw), axis=1)
    g = jnp.argmax(gany).astype(jnp.int32)
    fine = lax.dynamic_slice(occ, (g * gw,), (gw,))
    b0 = g * gw + jnp.argmax(fine).astype(jnp.int32)
    found = jnp.any(gany)
    val = jnp.where(found, bmin[b0], jnp.int64(KEY_INF))
    return val, jnp.where(found, b0, nb).astype(jnp.int32), found


def wheel_scan(keys, slot, nb: int, *, groups: int = WHEEL_GROUPS):
    """Fused bucket-scatter + occupancy-min-scan: one pass from lanes
    to ``(cnt, bmin, nearest, found)``.  This is the XLA reference of
    the Pallas kernel in :mod:`engine.kernels_pallas`; the two are
    bit-identical (ci.sh wheel smoke, interpret mode on CPU)."""
    cnt, bmin = wheel_scatter(keys, slot, nb)
    val, _b0, found = wheel_nearest(cnt, bmin, groups)
    return cnt, bmin, val, found


# ----------------------------------------------------------------------
# selection: masked lexicographic argmin = a heap top
# ----------------------------------------------------------------------

def _masked_argmin(mask, key, order):
    """Top of a 'heap' ordered by (mask desc, key asc, order asc).

    Returns (valid, index, min_key).  Two-stage: min key among mask,
    then min creation order among key-ties -- the oracle's exact
    tie-break, so selection is deterministic and backend-independent.
    """
    k = jnp.where(mask, key, KEY_INF)
    min_key = jnp.min(k)
    tie = k == min_key
    idx = jnp.argmin(jnp.where(tie, order, KEY_INF)).astype(jnp.int32)
    return jnp.any(mask), idx, min_key


# ----------------------------------------------------------------------
# one scheduling decision (fused select + pop + retag)
# ----------------------------------------------------------------------

def engine_step(state: EngineState, now: jnp.ndarray, *,
                allow_limit_break: bool,
                anticipation_ns: int):
    """One ``do_next_request`` + serve, fully on device.

    Mirrors the oracle's decision order exactly: reservation phase,
    ready promotion, weight phase, optional Allow limit-break, else
    future/none (reference :1115-1186).
    """
    has_req = state.active & (state.depth > 0)
    eff_prop = state.head_prop + state.prop_delta

    # --- reservation heap top; constraint phase (:1124-1128)
    resv_valid, resv_idx, resv_min = _masked_argmin(
        has_req, state.head_resv, state.order)
    serve_resv = resv_valid & (resv_min <= now)

    # --- promote newly within-limit heads to ready (:1135-1144);
    # the oracle's promote loop marks exactly {head.limit <= now}, which
    # here is one mask op.  Gated on the reservation phase NOT serving:
    # the oracle returns before the promote loop in that case, and the
    # ready flags are persistent state, so promoting early would diverge
    # under non-monotonic injected pull times.
    head_ready = jnp.where(
        serve_resv, state.head_ready,
        state.head_ready | (has_req & ~state.head_ready &
                            (state.head_limit <= now)))

    # --- ready heap top; weight phase (:1146-1151)
    ready_mask = has_req & head_ready
    rdy_valid, rdy_idx, _ = _masked_argmin(ready_mask, eff_prop,
                                           state.order)
    serve_ready = (~serve_resv) & rdy_valid & \
        (state.head_prop[rdy_idx] < MAX_TAG)

    # --- overall ready-heap top (ready clients sort before non-ready:
    # oracle _ready_key) -- needed for the Allow fallback (:1157-1165)
    nonready_mask = has_req & ~head_ready
    nr_valid, nr_idx, _ = _masked_argmin(nonready_mask, eff_prop,
                                         state.order)
    overall_idx = jnp.where(rdy_valid, rdy_idx, nr_idx)
    overall_valid = rdy_valid | nr_valid
    if allow_limit_break:
        undecided = ~serve_resv & ~serve_ready
        lb_ready_ok = overall_valid & \
            (state.head_prop[overall_idx] < MAX_TAG)
        lb_serve_ready = undecided & lb_ready_ok
        lb_serve_resv = undecided & ~lb_ready_ok & resv_valid & \
            (resv_min < MAX_TAG)
    else:
        lb_serve_ready = jnp.bool_(False)
        lb_serve_resv = jnp.bool_(False)

    # --- nothing eligible: earliest future time (:1170-1185).  The
    # limit-heap top orders non-ready before ready (oracle _limit_key).
    l_nr_valid, l_nr_idx, _ = _masked_argmin(
        nonready_mask, state.head_limit, state.order)
    l_r_valid, l_r_idx, _ = _masked_argmin(
        ready_mask, state.head_limit, state.order)
    lim_idx = jnp.where(l_nr_valid, l_nr_idx, l_r_idx)
    lim_valid = l_nr_valid | l_r_valid
    next_call = jnp.int64(TIME_MAX)
    next_call = jnp.where(resv_valid, _min_not_0(next_call, resv_min),
                          next_call)
    next_call = jnp.where(lim_valid,
                          _min_not_0(next_call, state.head_limit[lim_idx]),
                          next_call)

    serving = serve_resv | serve_ready | lb_serve_ready | lb_serve_resv
    phase_is_ready = serve_ready | lb_serve_ready
    w = jnp.where(serve_resv | lb_serve_resv, resv_idx, overall_idx)
    limit_break = lb_serve_ready | lb_serve_resv

    # ------------------------------------------------------------------
    # serve winner w (pop_process_request :1046-1073 + update_next_tag
    # :1021-1036 + reduce_reservation_tags :1077-1111)
    # ------------------------------------------------------------------
    served_r = state.head_resv[w]
    served_p = state.head_prop[w]
    served_l = state.head_limit[w]
    served_arr = state.head_arrival[w]
    served_cost = state.head_cost[w]
    served_rho = state.head_rho[w]

    new_depth = state.depth[w] - 1
    has_more = new_depth > 0

    # pop the oldest tail element as the new head
    rq = state.q_head[w]
    narr = state.q_arrival[w, rq]
    ncost = state.q_cost[w, rq]

    # delayed tagging of the new head: recurrence predecessor is the
    # just-served tag, with the client's latest rho/delta (:1021-1036)
    nr_tag, np_tag, nl_tag = _make_tag(
        served_r, served_p, served_l, served_arr,
        state.resv_inv[w], state.weight_inv[w], state.limit_inv[w],
        state.cur_delta[w], state.cur_rho[w], narr, ncost,
        anticipation_ns)

    # weight-phase service pays reservation debt (:1077-1111); under
    # delayed calc only the head (here: the freshly-tagged new head)
    # and prev_tag are adjusted
    offset = jnp.where(phase_is_ready,
                       state.resv_inv[w] * (served_cost + served_rho),
                       jnp.int64(0))

    # prev_tag folds in the new head tag (update_req_tag), then the
    # reservation offset -- matching the oracle's operation order
    new_prev_r = jnp.where(has_more,
                           _fold_prev(state.prev_resv[w], nr_tag),
                           state.prev_resv[w]) - offset
    new_prev_p = jnp.where(has_more,
                           _fold_prev(state.prev_prop[w], np_tag),
                           state.prev_prop[w])
    new_prev_l = jnp.where(has_more,
                           _fold_prev(state.prev_limit[w], nl_tag),
                           state.prev_limit[w])
    new_prev_arr = jnp.where(has_more, narr, state.prev_arrival[w])

    def upd(arr, value, pred):
        return arr.at[w].set(jnp.where(serving & pred, value, arr[w]))

    true1 = jnp.bool_(True)
    state = state._replace(
        depth=upd(state.depth, new_depth.astype(jnp.int32), true1),
        q_head=upd(state.q_head,
                   ((rq + 1) % state.ring_capacity).astype(jnp.int32),
                   has_more),
        head_resv=upd(state.head_resv, nr_tag - offset, has_more),
        head_prop=upd(state.head_prop, np_tag, has_more),
        head_limit=upd(state.head_limit, nl_tag, has_more),
        head_arrival=upd(state.head_arrival, narr, has_more),
        head_cost=upd(state.head_cost, ncost, has_more),
        head_rho=upd(state.head_rho, state.cur_rho[w], has_more),
        head_ready=head_ready.at[w].set(
            jnp.where(serving, False, head_ready[w])),
        prev_resv=upd(state.prev_resv, new_prev_r, true1),
        prev_prop=upd(state.prev_prop, new_prev_p, true1),
        prev_limit=upd(state.prev_limit, new_prev_l, true1),
        prev_arrival=upd(state.prev_arrival, new_prev_arr, true1),
    )

    decision = Decision(
        type=jnp.where(serving, RETURNING,
                       jnp.where(next_call < TIME_MAX, FUTURE,
                                 NONE)).astype(jnp.int32),
        slot=jnp.where(serving, w, -1).astype(jnp.int32),
        phase=phase_is_ready.astype(jnp.int32),
        cost=jnp.where(serving, served_cost, 0),
        when=next_call,
        limit_break=jnp.asarray(limit_break, dtype=bool),
    )
    return state, decision


def engine_run(state: EngineState, now: jnp.ndarray, steps: int, *,
               allow_limit_break: bool, anticipation_ns: int,
               advance_now: bool = False, with_horizon: bool = False,
               with_metrics: bool = False):
    """``steps`` scheduling decisions in one launch via lax.scan.

    With a fixed ``now`` this equals ``steps`` successive pulls at the
    same instant (once a FUTURE/NONE occurs, state is unchanged and all
    later decisions repeat it).  With ``advance_now`` the virtual clock
    jumps to each FUTURE's wake-up time -- an infinitely-fast server,
    which is the decisions/sec benchmark mode.

    With ``with_horizon`` a 4th value is returned: the earliest
    reservation or (non-ready) limit tag STRICTLY past ``now`` present
    in any intermediate state of the run.  Decisions depend on ``now``
    only through the threshold tests ``resv <= now`` and ``limit <=
    now`` (reference do_next_request :1115-1186), so for any t in
    [now, horizon) this exact decision sequence is what pulls at t
    would also have produced -- the validity window for speculative
    decision buffers.  Conservative: tags replaced mid-run count via
    the initial-state minimum, created tags via per-step minima.

    With ``with_metrics`` an ``obs.device`` metrics vector is appended
    to the return tuple, accumulated in the same scan (phase counts,
    limit-capped FUTURE stalls, ring-occupancy high-water mark) and
    drained with the same fetch as the decisions -- no extra launch.
    The flag is STATIC and touches only the metrics carry: the decision
    stream and final state are bit-identical either way (pinned by
    tests/test_obs.py).
    """
    from ..obs import device as _obsdev

    def tag_horizon(st, t):
        has_req = st.active & (st.depth > 0)
        hr = jnp.min(jnp.where(has_req & (st.head_resv > t),
                               st.head_resv, TIME_MAX))
        nonready = has_req & ~st.head_ready & (st.head_limit > t)
        hl = jnp.min(jnp.where(nonready, st.head_limit, TIME_MAX))
        return jnp.minimum(hr, hl)

    def body(carry, _):
        st, t, h, met = carry
        st, dec = engine_step(st, t,
                              allow_limit_break=allow_limit_break,
                              anticipation_ns=anticipation_ns)
        if with_horizon:
            # the served client's freshly-created head tags are the only
            # tags not present in the PREVIOUS state; fold them in
            w = jnp.maximum(dec.slot, 0)
            nr = st.head_resv[w]
            nl = st.head_limit[w]
            served = dec.slot >= 0
            h = jnp.where(served & (nr > t), jnp.minimum(h, nr), h)
            h = jnp.where(served & ~st.head_ready[w] & (nl > t),
                          jnp.minimum(h, nl), h)
        if with_metrics:
            served1 = (dec.type == RETURNING).astype(jnp.int64)
            is_resv = served1 * (dec.phase == 0)
            met = _obsdev.metrics_combine(met, _obsdev.metrics_delta(
                decisions=served1, resv=is_resv,
                prop=served1 - is_resv,
                limit_break=dec.limit_break.astype(jnp.int64),
                stalls=(dec.type == FUTURE).astype(jnp.int64),
                ring_hwm=jnp.max(st.depth).astype(jnp.int64)))
        if advance_now:
            t = jnp.where(dec.type == FUTURE, dec.when, t)
        return (st, t, h, met), dec

    h0 = tag_horizon(state, now) if with_horizon \
        else jnp.int64(TIME_MAX)
    (state, now, horizon, metrics), decisions = lax.scan(
        body, (state, now, h0, _obsdev.metrics_zero()), None,
        length=steps)
    out = (state, now, decisions)
    if with_horizon:
        out = out + (horizon,)
    if with_metrics:
        out = out + (metrics,)
    return out


# ----------------------------------------------------------------------
# ingest: batched do_add_request (+ client creation)
# ----------------------------------------------------------------------

OP_NOP = 0
OP_ADD = 1
OP_CREATE = 2


class IngestOps(NamedTuple):
    """A scanned batch of queue mutations (host-built, padded with NOPs
    so batch shapes hit a few jit cache entries)."""

    kind: jnp.ndarray     # int32[B]: OP_NOP/OP_ADD/OP_CREATE
    slot: jnp.ndarray     # int32[B]
    time: jnp.ndarray     # int64[B] arrival ns (ADD)
    cost: jnp.ndarray     # int64[B]
    rho: jnp.ndarray      # int64[B]
    delta: jnp.ndarray    # int64[B]
    resv_inv: jnp.ndarray   # int64[B] (CREATE)
    weight_inv: jnp.ndarray  # int64[B]
    limit_inv: jnp.ndarray   # int64[B]
    order: jnp.ndarray    # int64[B] creation index (CREATE)


def ingest(state: EngineState, ops: IngestOps, *,
           anticipation_ns: int) -> EngineState:
    """Apply a batch of creates/adds in order (scan), equivalent to the
    oracle's per-call ``_do_add_request`` (reference :913-1018).

    Sequencing matters: a batch may hold several ops for one client, and
    idle-reactivation reads all other clients' state at its moment.
    """

    def body(st: EngineState, op):
        s = op.slot
        is_add = op.kind == OP_ADD
        is_create = op.kind == OP_CREATE

        # ---- CREATE: install a fresh ClientRec (reference :920-932)
        def cset(arr, value):
            return arr.at[s].set(jnp.where(is_create, value, arr[s]))

        st = st._replace(
            active=cset(st.active, True),
            idle=cset(st.idle, True),
            order=cset(st.order, op.order),
            resv_inv=cset(st.resv_inv, op.resv_inv),
            weight_inv=cset(st.weight_inv, op.weight_inv),
            limit_inv=cset(st.limit_inv, op.limit_inv),
            prop_delta=cset(st.prop_delta, 0),
            prev_resv=cset(st.prev_resv, 0),
            prev_prop=cset(st.prev_prop, 0),
            prev_limit=cset(st.prev_limit, 0),
            prev_arrival=cset(st.prev_arrival, 0),
            cur_rho=cset(st.cur_rho, 1),
            cur_delta=cset(st.cur_delta, 1),
            depth=cset(st.depth, 0),
            q_head=cset(st.q_head, 0),
            head_ready=cset(st.head_ready, False),
        )

        # ---- ADD (reference do_add_request :913-1018)
        # idle reactivation (:937-985): lowest effective proportion tag
        # among other non-idle clients, as a masked min (the adding
        # client is still marked idle here, excluding itself -- same as
        # the oracle's scan)
        others = st.active & ~st.idle
        eff = jnp.where(st.depth > 0, st.head_prop, st.prev_prop) \
            + st.prop_delta
        lowest = jnp.min(jnp.where(others, eff, KEY_INF))
        do_shift = is_add & st.idle[s] & jnp.any(others) & \
            (lowest < LOWEST_PROP_TAG_TRIGGER)
        st = st._replace(
            prop_delta=st.prop_delta.at[s].set(
                jnp.where(do_shift, lowest - op.time, st.prop_delta[s])),
            idle=st.idle.at[s].set(jnp.where(is_add, False, st.idle[s])),
        )

        # delayed tagging (:878-893): a real tag only if the request
        # lands at the queue head
        empty = st.depth[s] == 0
        tag_it = is_add & empty
        r, p, l = _make_tag(
            st.prev_resv[s], st.prev_prop[s], st.prev_limit[s],
            st.prev_arrival[s],
            st.resv_inv[s], st.weight_inv[s], st.limit_inv[s],
            op.delta, op.rho, op.time, op.cost, anticipation_ns)

        def hset(arr, value, pred=tag_it):
            return arr.at[s].set(jnp.where(pred, value, arr[s]))

        # tail ring write position (depth includes head; tail count is
        # depth-1, so the new element lands at q_head + depth - 1)
        wpos = (st.q_head[s] + st.depth[s] - 1) % st.ring_capacity
        push_it = is_add & ~empty

        st = st._replace(
            head_resv=hset(st.head_resv, r),
            head_prop=hset(st.head_prop, p),
            head_limit=hset(st.head_limit, l),
            head_arrival=hset(st.head_arrival, op.time),
            head_cost=hset(st.head_cost, op.cost),
            head_rho=hset(st.head_rho, op.rho),
            head_ready=hset(st.head_ready, False),
            prev_resv=hset(st.prev_resv, _fold_prev(st.prev_resv[s], r)),
            prev_prop=hset(st.prev_prop, _fold_prev(st.prev_prop[s], p)),
            prev_limit=hset(st.prev_limit,
                            _fold_prev(st.prev_limit[s], l)),
            prev_arrival=hset(st.prev_arrival, op.time),
            q_arrival=st.q_arrival.at[s, wpos].set(
                jnp.where(push_it, op.time, st.q_arrival[s, wpos])),
            q_cost=st.q_cost.at[s, wpos].set(
                jnp.where(push_it, op.cost, st.q_cost[s, wpos])),
            depth=st.depth.at[s].set(
                jnp.where(is_add, st.depth[s] + 1, st.depth[s])),
            cur_rho=hset(st.cur_rho, op.rho, is_add),
            cur_delta=hset(st.cur_delta, op.delta, is_add),
        )
        return st, None

    state, _ = lax.scan(body, state, ops)
    return state


def ingest_wave(state: EngineState, requesting: jnp.ndarray,
                time_ns, cost: jnp.ndarray, rho: jnp.ndarray,
                delta: jnp.ndarray, *,
                anticipation_ns: int) -> EngineState:
    """Vectorized do_add_request for a WAVE: at most one new request per
    client, all slots distinct, applied in parallel.

    Semantics differ from the sequential ``ingest`` scan in exactly one
    place, by design: idle-reactivation's lowest-proportion-tag scan
    (reference :960-983) reads the PRE-wave state, so a reactivating
    client misses EVERY earlier same-wave op's effect on the scanned
    tags -- other reactivations AND plain adds that retag a drained
    lower-slot client's head.  (Bit-for-bit parity with the scan holds
    when each wave's reactivator, if any, is the wave's lowest slot --
    pinned by tests/test_tpu_engine.py.)  This is the batch-synchronous
    model of ``sim.device_sim``: same-instant arrivals are unordered.
    Everything else -- delayed tagging, ring append, cur rho/delta --
    matches the scan bit for bit.

    ``requesting`` bool[N]; time_ns scalar; cost/rho/delta int64[N].
    """
    st = state
    n = st.capacity

    # --- idle reactivation vs pre-wave state
    others = st.active & ~st.idle
    eff = jnp.where(st.depth > 0, st.head_prop, st.prev_prop) \
        + st.prop_delta
    lowest = jnp.min(jnp.where(others, eff, KEY_INF))
    do_shift = requesting & st.idle & jnp.any(others) & \
        (lowest < LOWEST_PROP_TAG_TRIGGER)
    prop_delta = jnp.where(do_shift, lowest - time_ns, st.prop_delta)
    idle = st.idle & ~requesting

    # --- delayed tagging: a real tag only when the queue is empty
    empty = st.depth == 0
    tag_it = requesting & empty
    t_arr = jnp.full((n,), time_ns, dtype=jnp.int64) \
        if jnp.ndim(time_ns) == 0 else time_ns
    r, p, l = _make_tag(
        st.prev_resv, st.prev_prop, st.prev_limit, st.prev_arrival,
        st.resv_inv, st.weight_inv, st.limit_inv,
        delta, rho, t_arr, cost, anticipation_ns)

    def hset(new, old, pred=tag_it):
        return jnp.where(pred, new, old)

    # --- ring append for non-empty queues: dense one-hot write along
    # the ring axis (per-row scatters serialize on TPU)
    push_it = requesting & ~empty
    wpos = (st.q_head + st.depth - 1) % st.ring_capacity
    onehot = jnp.arange(st.ring_capacity,
                        dtype=jnp.int32)[None, :] == wpos[:, None]
    write = push_it[:, None] & onehot
    q_arrival = jnp.where(write, t_arr[:, None], st.q_arrival)
    q_cost = jnp.where(write, cost[:, None], st.q_cost)

    return st._replace(
        idle=idle,
        prop_delta=prop_delta,
        head_resv=hset(r, st.head_resv),
        head_prop=hset(p, st.head_prop),
        head_limit=hset(l, st.head_limit),
        head_arrival=hset(t_arr, st.head_arrival),
        head_cost=hset(cost, st.head_cost),
        head_rho=hset(rho, st.head_rho),
        head_ready=st.head_ready & ~tag_it,
        prev_resv=hset(_fold_prev(st.prev_resv, r), st.prev_resv),
        prev_prop=hset(_fold_prev(st.prev_prop, p), st.prev_prop),
        prev_limit=hset(_fold_prev(st.prev_limit, l), st.prev_limit),
        prev_arrival=hset(t_arr, st.prev_arrival),
        q_arrival=q_arrival,
        q_cost=q_cost,
        depth=jnp.where(requesting, st.depth + 1,
                        st.depth).astype(jnp.int32),
        cur_rho=hset(rho, st.cur_rho, requesting),
        cur_delta=hset(delta, st.cur_delta, requesting),
    )


def ingest_superwave(state: EngineState, counts: jnp.ndarray,
                     wave_times: jnp.ndarray, cost: jnp.ndarray,
                     rho: jnp.ndarray, delta: jnp.ndarray, *,
                     anticipation_ns: int) -> EngineState:
    """W consecutive ingest waves fused into ONE ring pass.

    Client ``i`` receives ``counts[i]`` arrivals (0 <= counts <= W) at
    times ``wave_times[0..counts[i]-1]``, each with the client's
    ``cost``/``rho``/``delta`` (constant across the superwave).
    Bit-equivalent to W sequential ``ingest_wave`` calls with
    ``requesting_w = counts > w`` (pinned by tests) -- the reactivation
    scan only ever fires at wave 0 (a client with counts > w was
    already non-idle by wave w >= 1), and with no serves in between the
    w-th arrival's ring slot is just ``base + w``.  The point: the
    [N, Q] ring pair is read+written ONCE for the whole superwave
    instead of once per wave, which is what makes sustained
    ingest+serve loops affordable (the reference pays its `add_request`
    cost per call under one mutex, `dmclock_server.h:913-1018`).

    Caller contract: ``depth + counts <= ring capacity`` (same
    no-overflow contract as the other ingest paths) and
    ``wave_times`` ascending with ``len(wave_times) = W`` static.
    """
    st = state
    n = st.capacity
    q = st.ring_capacity
    w_waves = wave_times.shape[0]
    requesting = counts > 0
    t0 = jnp.broadcast_to(wave_times[0], (n,))

    # --- idle reactivation at wave 0, vs pre-superwave state (the
    # ingest_wave batch-synchronous semantics, kernels.ingest_wave)
    others = st.active & ~st.idle
    eff = jnp.where(st.depth > 0, st.head_prop, st.prev_prop) \
        + st.prop_delta
    lowest = jnp.min(jnp.where(others, eff, KEY_INF))
    do_shift = requesting & st.idle & jnp.any(others) & \
        (lowest < LOWEST_PROP_TAG_TRIGGER)
    prop_delta = jnp.where(do_shift, lowest - t0, st.prop_delta)
    idle = st.idle & ~requesting

    # --- wave-0 arrival becomes the head of an empty queue
    empty = st.depth == 0
    tag_it = requesting & empty
    r, p, l = _make_tag(
        st.prev_resv, st.prev_prop, st.prev_limit, st.prev_arrival,
        st.resv_inv, st.weight_inv, st.limit_inv,
        delta, rho, t0, cost, anticipation_ns)

    def hset(new, old, pred=tag_it):
        return jnp.where(pred, new, old)

    # --- ring multi-append: arrivals h..counts-1 land at consecutive
    # ring positions starting at base (h = 1 when the head consumed
    # wave 0).  Dense: for ring column c, the wave index is
    # (c - base) mod Q + h, written when < counts.
    h = tag_it.astype(jnp.int32)
    ring_count = jnp.maximum(counts.astype(jnp.int32) - h, 0)
    base = jnp.remainder(st.q_head + st.depth + h - 1, q)
    col = jnp.arange(q, dtype=jnp.int32)
    jrel = jnp.remainder(col[None, :] - base[:, None], q)
    writem = jrel < ring_count[:, None]
    widx = jrel + h[:, None]
    # wave_times select: W is small and static, so unrolled selects
    # fuse into the single ring pass (a gather would serialize)
    val = jnp.broadcast_to(wave_times[0], (n, q))
    for wv in range(1, w_waves):
        val = jnp.where(widx == wv, wave_times[wv], val)
    q_arrival = jnp.where(writem, val, st.q_arrival)
    q_cost = jnp.where(writem, cost[:, None], st.q_cost)

    return st._replace(
        idle=idle,
        prop_delta=prop_delta,
        head_resv=hset(r, st.head_resv),
        head_prop=hset(p, st.head_prop),
        head_limit=hset(l, st.head_limit),
        head_arrival=hset(t0, st.head_arrival),
        head_cost=hset(cost, st.head_cost),
        head_rho=hset(rho, st.head_rho),
        head_ready=st.head_ready & ~tag_it,
        prev_resv=hset(_fold_prev(st.prev_resv, r), st.prev_resv),
        prev_prop=hset(_fold_prev(st.prev_prop, p), st.prev_prop),
        prev_limit=hset(_fold_prev(st.prev_limit, l), st.prev_limit),
        prev_arrival=hset(t0, st.prev_arrival),
        q_arrival=q_arrival,
        q_cost=q_cost,
        depth=(st.depth + counts.astype(jnp.int32)),
        cur_rho=hset(rho, st.cur_rho, requesting),
        cur_delta=hset(delta, st.cur_delta, requesting),
    )


# ----------------------------------------------------------------------
# small host-facing helpers
# ----------------------------------------------------------------------

def mark_idle(state: EngineState, slots: jnp.ndarray) -> EngineState:
    """GC support: mark the given slots idle (oracle do_clean's idle
    branch; reference :1206-1255)."""
    return state._replace(idle=state.idle.at[slots].set(True))


def deactivate(state: EngineState, slots: jnp.ndarray) -> EngineState:
    """GC support: erase clients (slots are recycled by the host)."""
    return state._replace(
        active=state.active.at[slots].set(False),
        depth=state.depth.at[slots].set(0),
    )
