"""Always-on streaming serve loop: fused ingest+serve+commit chunks.

The round-based engine loop pays the tunnel dispatch tax per epoch
THREE times over: a ``device_get(state.depth)`` for the host-side
admission clamp, an ``ingest_superwave`` launch, and the epoch-scan
launch -- ~17 ms each through the tunneled runtime (PROFILE.md
findings 17-18, priced continuously by ``bench.py --spans``).  This
module is the RackSched microsecond-dispatch thesis (PAPERS.md)
applied to that structure: ONE device launch runs a whole **stream
chunk** of epochs -- a ``lax.scan`` over epochs whose body fuses

1. the admission clamp (``min(raw_counts, min(ring - depth, waves))``
   computed ON DEVICE from the carried state, the same integer math
   the host clamp does, so the ingested counts are bit-identical),
2. ``kernels.ingest_superwave`` (the superwave ring pass), and
3. one full epoch of any of the three epoch engines
   (``fastpath.scan_prefix_epoch`` / ``scan_chain_epoch`` /
   ``scan_calendar_epoch``, all fast paths included),

with the decision stream, the per-epoch metric vectors, and the PR-6
telemetry accumulators (histograms / ledger / flight ring) stacking
up in HBM as scan outputs.  The host only uploads the PRE-GENERATED
raw Poisson draws (state-independent, so they can be drawn for chunk
T+1 while the device runs chunk T -- the double buffer) and drains
the stacked outputs at chunk boundaries, which the supervisor aligns
with its PR-5 checkpoint boundaries so crash equivalence survives the
refactor unchanged.

Everything in the decision path is integer (int64/int32/bool) ops, so
running the SAME epoch scans inside a bigger jit cannot perturb a
decision: the stream loop is digest-pinned bit-identical to the
round-based engine (tests/test_stream.py, ci.sh streaming smoke).

Layering: this module owns the pure device program + the host-side
epoch views that reconstruct per-epoch results for the chain digest;
``robust.guarded.run_stream_chunk_guarded`` adds retry + the
guard-trip fallback (a tripped chunk is discarded and re-run on the
proven round path); ``robust.supervisor`` drives chunks between
checkpoint boundaries; ``bench.py --engine-loop stream`` chunks its
own sustained rounds the same way.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from . import kernels
from . import fastpath
from .state import EngineState


class StreamChunk(NamedTuple):
    """One fused chunk's device outputs.

    ``outs`` is a dict of per-epoch arrays stacked on a leading
    ``[epochs]`` axis -- exactly the fields the matching epoch-result
    class carries (see :data:`STREAM_OUT_FIELDS`), plus ``"metrics"``
    (``int64[epochs, NUM_METRICS]``; zeros when ``with_metrics`` is
    off).  Slicing epoch ``i`` out of every field reconstructs that
    epoch's result bit-for-bit (:func:`epoch_view`)."""

    state: EngineState
    outs: dict
    hists: object = None
    ledger: object = None
    flight: object = None
    slo: object = None
    prov: object = None


# per-engine stacked output fields, in the epoch-result class's field
# order (minus state/metrics/telemetry, which ride separately)
STREAM_OUT_FIELDS = {
    "prefix": ("count", "guards_ok", "slot", "phase", "cost", "lb"),
    "chain": ("count", "unit_count", "guards_ok", "slot", "cls",
              "length"),
    "calendar": ("count", "resv_count", "progress_ok", "served",
                 "level_count"),
}

# the guard vector each engine exposes (run_epoch_guarded's contract:
# any False means the epoch needs the host fallback path)
STREAM_GUARD_FIELD = {"prefix": "guards_ok", "chain": "guards_ok",
                      "calendar": "progress_ok"}


def clamped_ingest(state: EngineState, counts, t_base, *, waves: int,
                   dt_wave: int) -> EngineState:
    """The admission clamp + superwave ingest, ON DEVICE: the host
    clamp's integer math ``min(raw, min(ring - depth, waves))`` over
    the carried depth, then :func:`kernels.ingest_superwave` at wave
    times ``t_base + j * dt_wave``.  The ONE implementation shared by
    the fused chunk body and the guarded runner's standalone fallback
    leg (:func:`jit_ingest_step`) -- their bit-identity contract is
    that both ingest exactly what the round loop's host clamp would
    have, so the clamp must not be able to drift between them."""
    n = state.capacity
    cost1 = jnp.ones((n,), dtype=jnp.int64)
    headroom = jnp.minimum(
        jnp.int32(state.ring_capacity) - state.depth,
        jnp.int32(waves))
    c = jnp.minimum(counts, headroom)
    wave_times = jnp.asarray(t_base, jnp.int64) + jnp.arange(
        waves, dtype=jnp.int64) * dt_wave
    return kernels.ingest_superwave(
        state, c, wave_times, cost1, cost1, cost1, anticipation_ns=0)


def make_epoch_step(*, engine: str, m: int, kw: dict, dt_epoch_ns: int,
                    waves: int, ingest: bool,
                    with_pressure: bool = False):
    """The ONE fused per-epoch step shared by the stream chunk body
    and the mesh serving plane's per-shard chunk
    (``parallel.mesh.build_mesh_chunk``): clamped superwave ingest at
    ``t_base`` + one full epoch of ``engine`` serving at ``t_base +
    dt`` with the telemetry accumulators riding the carry.  Factoring
    it here is what makes the S=1 mesh == stream bit-identity a
    construction, not a test-only coincidence -- the two loops cannot
    drift because they trace the same step.

    ``with_pressure`` adds a MID-EPOCH pressure probe
    (``obs.provenance.pressure_vec`` on the post-ingest pre-serve
    state, at the epoch's serve time): the one instant where arrivals
    are queued but not yet drained, which is what makes the probe a
    real backlog signal on the calendar engines too -- their deadline
    commits drain ``state.depth`` within the epoch, so any
    boundary-time depth read is structurally zero there.  The probe is
    a pure integer read (no state change, no collective); it rides
    ``outs["pressure"]`` (``int64[PRESS_FIELDS]``) and is ignored by
    the digest's epoch views.

    Returns ``step(state, t_base, counts_e, hists, ledger, flight,
    slo, prov) -> ((state', hists', ledger', flight', slo', prov'),
    outs)`` with ``outs`` the engine's :data:`STREAM_OUT_FIELDS` plus
    ``"metrics"``."""
    fn = fastpath.epoch_scan_fn(engine)
    fields = STREAM_OUT_FIELDS[engine]
    dt = int(dt_epoch_ns)
    dt_wave = dt // int(waves)
    if with_pressure:
        from ..obs import provenance as _prov

    def step(st, t_base, counts_e, h, l, f, s, p):
        if ingest:
            st = clamped_ingest(st, counts_e, t_base,
                                waves=waves, dt_wave=dt_wave)
        if with_pressure:
            press = _prov.pressure_vec(st, t_base + dt)
        ep = fn(st, t_base + dt, m=m, **kw,
                hists=h, ledger=l, flight=f, slo=s, prov=p)
        outs = {name: getattr(ep, name) for name in fields}
        outs["metrics"] = ep.metrics
        if with_pressure:
            outs["pressure"] = press
        return (ep.state, ep.hists, ep.ledger, ep.flight,
                ep.slo, ep.prov), outs

    return step


def build_stream_chunk(*, engine: str, epochs: int, m: int, k: int = 0,
                       chain_depth: int = 4, dt_epoch_ns: int,
                       waves: int, anticipation_ns: int = 0,
                       allow_limit_break: bool = False,
                       with_metrics: bool = True,
                       select_impl: str = "sort", tag_width: int = 64,
                       window_m: Optional[int] = None,
                       calendar_impl: str = "minstop",
                       ladder_levels: int = 8,
                       wheel_kernel: str = "xla",
                       ingest: bool = True):
    """Build the pure chunk program ``(state, epoch0, counts, hists,
    ledger, flight) -> StreamChunk`` for one static configuration.

    ``epoch0`` is a TRACED int64 scalar (the chunk's first epoch
    index), so one compiled program serves every chunk of the same
    length; ``counts`` is ``int32[epochs, N]`` of RAW Poisson draws
    (``None`` and ``ingest=False`` for serve-only streams).  Epoch
    ``i`` ingests at ``t_base = (epoch0 + i) * dt_epoch_ns`` (wave
    times ``t_base + j * (dt_epoch_ns // waves)``) and serves at
    ``t_base + dt_epoch_ns`` -- the exact round-loop schedule
    (``robust.supervisor._job_loop``)."""
    assert engine in fastpath.EPOCH_ENGINES, engine
    epochs = int(epochs)
    assert epochs >= 1, "a stream chunk needs at least one epoch"
    kw = fastpath.epoch_scan_kwargs(
        engine, k=k, chain_depth=chain_depth, select_impl=select_impl,
        tag_width=tag_width, window_m=window_m,
        calendar_impl=calendar_impl, ladder_levels=ladder_levels,
        wheel_kernel=wheel_kernel,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break,
        with_metrics=with_metrics)
    dt = int(dt_epoch_ns)
    epoch_step = make_epoch_step(engine=engine, m=m, kw=kw,
                                 dt_epoch_ns=dt, waves=waves,
                                 ingest=ingest)

    def chunk(state: EngineState, epoch0, counts, hists=None,
              ledger=None, flight=None, slo=None,
              prov=None) -> StreamChunk:
        epoch0 = jnp.asarray(epoch0, dtype=jnp.int64)

        def body(carry, xs):
            st, h, l, f, s, p = carry
            counts_e, i = xs
            t_base = (epoch0 + i) * dt
            return epoch_step(st, t_base, counts_e, h, l, f, s, p)

        idx = jnp.arange(epochs, dtype=jnp.int64)
        if ingest:
            assert counts is not None, "ingest=True needs raw counts"
            xs = (counts, idx)
        else:
            xs = (jnp.zeros((epochs, 0), dtype=jnp.int32), idx)
        (state, hists, ledger, flight, slo, prov), outs = lax.scan(
            body, (state, hists, ledger, flight, slo, prov), xs)
        return StreamChunk(state=state, outs=outs, hists=hists,
                           ledger=ledger, flight=flight, slo=slo,
                           prov=prov)

    return chunk


# module-level jit cache keyed by the full static configuration (the
# engine/queue.py convention): a fresh jax.jit per chunk would
# recompile the whole fused program on every launch.  Entries are
# compile-plane-instrumented (obs.compile_plane): the fused chunk is
# the most expensive program in the repo to compile, so its
# lower+compile wall and retraces are exactly what the capacity plane
# must see.
_STREAM_JIT_CACHE: dict = {}


def jit_stream_chunk(*, donate: bool = False, **cfg):
    """Jitted :func:`build_stream_chunk` for ``cfg``.  ``donate=True``
    donates the state + telemetry accumulators (carried HBM state, the
    bench discipline); the guarded runner keeps them alive instead so
    a tripped chunk can be discarded and re-run from its entry state."""
    from ..obs import compile_plane as _cplane

    key = (donate,) + tuple(sorted(cfg.items()))
    if key not in _STREAM_JIT_CACHE:
        fn = build_stream_chunk(**cfg)
        donate_argnums = (0, 3, 4, 5, 6, 7) if donate else ()
        _STREAM_JIT_CACHE[key] = _cplane.instrumented_jit(
            fn, cache="stream.chunk", entry=key,
            donate_argnums=donate_argnums)
    return _STREAM_JIT_CACHE[key]


_INGEST_STEP_CACHE: dict = {}


def jit_ingest_step(*, dt_epoch_ns: int, waves: int):
    """One fused clamp+superwave ingest launch ``(state, raw_counts,
    t_base) -> state`` -- the stream chunk's ingest leg standing
    alone, for the guarded runner's round-path fallback (identical
    clamp math, so the fallback ingests exactly what the chunk would
    have)."""
    from ..obs import compile_plane as _cplane

    key = (int(dt_epoch_ns), int(waves))
    if key not in _INGEST_STEP_CACHE:
        dt_wave = int(dt_epoch_ns) // int(waves)

        def step(state: EngineState, counts, t_base):
            return clamped_ingest(state, counts, t_base,
                                  waves=waves, dt_wave=dt_wave)

        _INGEST_STEP_CACHE[key] = _cplane.instrumented_jit(
            step, cache="stream.ingest", entry=key)
    return _INGEST_STEP_CACHE[key]


def epoch_view(engine: str, outs: dict, i: int):
    """Reconstruct epoch ``i``'s result object from the fetched
    stacked chunk outputs -- the SAME result class the round-based
    epoch scan returns (``state=None``; nobody hashes or folds it), so
    the supervisor's chain digest (``_digest_update``'s
    ``hasattr``-driven field walk) sees byte-identical arrays in the
    identical field layout."""
    fields = {name: outs[name][i] for name in STREAM_OUT_FIELDS[engine]}
    metrics = outs["metrics"][i]
    if engine == "prefix":
        return fastpath.PrefixEpoch(state=None, metrics=metrics,
                                    **fields)
    if engine == "chain":
        return fastpath.ChainEpoch(state=None, metrics=metrics,
                                   **fields)
    return fastpath.CalendarEpoch(state=None, metrics=metrics,
                                  **fields)


def chunk_bounds(start: int, epochs: int, every: int):
    """Yield ``(e0, e1)`` stream-chunk windows from ``start`` to
    ``epochs``, each ending at the next PR-5 checkpoint boundary
    (``(e + 1) % every == 0`` or the final epoch) -- so a chunk drain
    IS a checkpoint drain and crash equivalence needs no new
    machinery.  Handles any ``start`` (a resume lands on a snapshot's
    epoch, always a boundary of this same layout)."""
    every = max(int(every), 1)
    e = int(start)
    while e < epochs:
        b = min((e // every + 1) * every, epochs)
        yield e, b
        e = b


def epoch_decisions(engine: str, outs: dict, i: int) -> int:
    """Decisions epoch ``i`` committed (the ``GuardedEpoch.count``
    mirror): the sum of the per-batch commit counts."""
    import numpy as np

    return int(np.asarray(outs["count"][i]).sum())
