"""The ingest server: a stdlib ``selectors`` event loop coalescing
tagged requests from many concurrent client processes into the
superwave count matrix the fused stream chunk admits (docs/RPC.md).

Design stance: the network plane owns EVERYTHING nondeterministic --
socket interleaving, retries, backpressure, injected chaos -- and
compresses it into one deterministic artifact per chunk boundary:
the ``int32[epochs, n]`` admitted-counts matrix the arrival journal
makes durable.  Downstream of ``take_chunk`` the run is a pure
function of that trace, which is what makes ``--mode rpc``
digest-comparable to a self-generated replay and SIGKILL-resumable.

Robustness plane, in one place:

- **backpressure**: total queued ops at or past ``high_watermark``
  answers ``ST_BUSY`` with a ``retry_after_ms`` hint instead of
  admitting; a device-side admission-clamp signal
  (:meth:`IngestServer.note_device_drops`, fed from the
  ``MET_INGEST_DROPS`` delta) halves the watermark and doubles the
  hint until the clamp drains -- the 429 path is DERIVED from the
  engine's own ``ingest_drops`` / ``bounded_by`` counters, not a
  second opinion.
- **exactly-once admission**: per-client ``(mark, extras)`` seq
  watermarks dedup retries and injected duplicates even under
  reordering (``extras`` holds out-of-order admits until the mark
  catches up); the watermarks ride every journal record, so a
  resumed server keeps refusing what a dead incarnation admitted.
- **bounded connections**: per-connection idle timeouts reap stalled
  peers; oversized/malformed frames close only the offending
  connection.
- **chaos**: the seeded :mod:`.faults` plane runs at frame ingress
  with exact counter accounting (the ci gate compares them to the
  host oracle).
"""

from __future__ import annotations

import json
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from . import faults as faults_mod
from . import framing

_RECV = 1 << 16


class _Conn:
    __slots__ = ("sock", "framer", "out", "last", "sub", "addr")

    def __init__(self, sock, addr) -> None:
        self.sock = sock
        self.addr = addr
        self.framer = framing.Framer()
        self.out = bytearray()
        self.last = time.monotonic()
        self.sub = False


class TakeResult(tuple):
    """``(counts, marks, events, arrivals_ns, carry)`` from one
    coalesce take -- counts is the journal/device matrix, marks the
    dedup watermarks after it, events the cumulative counter
    snapshot, arrivals_ns the admission timestamps the latency plane
    prices, carry the leftover queued ops (admitted but beyond this
    chunk's ``epochs * waves`` capacity -- journaled so a crash
    cannot lose them).  ``carry`` is snapshotted under the SAME lock
    hold as ``counts``: an op is in exactly one of the two."""

    __slots__ = ()

    def __new__(cls, counts, marks, events, arrivals_ns, carry):
        return tuple.__new__(cls, (counts, marks, events,
                                   arrivals_ns, carry))

    counts = property(lambda s: s[0])
    marks = property(lambda s: s[1])
    events = property(lambda s: s[2])
    arrivals_ns = property(lambda s: s[3])
    carry = property(lambda s: s[4])


class IngestServer:
    """Threaded ingest front-end for one serving loop.

    ``route`` maps a client id to its coalesce slot (default
    ``cid % n_slots`` -- the closed-population identity);
    ``shard_of`` (e.g. ``PlacementMap.shard_of``) attributes per-
    shard received-ops counters for the routing/observability plane
    without touching admission math.
    """

    COUNTERS = ("requests", "admitted_ops", "admitted_reqs",
                "deduped", "busy", "drops_injected", "dup_frames",
                "reordered", "proto_errors", "conns_opened",
                "conns_timed_out", "notify_batches",
                "device_drop_signals", "datagrams")

    def __init__(self, n_slots: int, *, waves: int,
                 host: str = "127.0.0.1", port: int = 0,
                 high_watermark: Optional[int] = None,
                 retry_after_ms: int = 25,
                 fault_spec=None,
                 route: Optional[Callable[[int], int]] = None,
                 shard_of: Optional[Callable[[int], int]] = None,
                 idle_timeout_s: float = 30.0,
                 datagram: bool = True) -> None:
        self.n = int(n_slots)
        self.waves = int(waves)
        self.spec = faults_mod.parse_net_fault_spec(fault_spec)
        self.route = route or (lambda cid: int(cid) % self.n)
        self.shard_of = shard_of
        self.hwm = int(high_watermark) if high_watermark \
            else self.n * self.waves * 4
        self.retry_after_ms = int(retry_after_ms)
        self.idle_timeout_s = float(idle_timeout_s)

        self._lock = threading.Lock()
        self.pending = np.zeros(self.n, dtype=np.int64)
        self._held: List[Tuple[int, int]] = []   # reordered (slot, n)
        # cid -> [mark, set(extras)]: mark = highest seq with every
        # seq <= mark admitted; extras = admitted seqs above the mark
        # (out-of-order arrivals awaiting contiguity)
        self._marks: Dict[int, list] = {}
        self._arrivals: List[int] = []
        self.counters: Dict[str, int] = {k: 0 for k in self.COUNTERS}
        self.shard_rx: Dict[int, int] = {}
        self._device_pressure = False

        self._sel = selectors.DefaultSelector()
        self._lsock = socket.socket(socket.AF_INET,
                                    socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET,
                               socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(128)
        self._lsock.setblocking(False)
        self.host, self.port = self._lsock.getsockname()[:2]
        self._sel.register(self._lsock, selectors.EVENT_READ,
                           ("accept", None))
        self._dsock = None
        if datagram:
            self._dsock = socket.socket(socket.AF_INET,
                                        socket.SOCK_DGRAM)
            self._dsock.bind((self.host, self.port))
            self._dsock.setblocking(False)
            self._sel.register(self._dsock, selectors.EVENT_READ,
                               ("datagram", None))
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        self._sel.register(self._wake_r, selectors.EVENT_READ,
                           ("wake", None))
        self._notify_q: deque = deque()
        self._conns: Dict[int, _Conn] = {}
        self._stop = False
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "IngestServer":
        self._thread = threading.Thread(target=self._loop,
                                        name="rpc-ingest",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop = True
        self._wake()
        if self._thread is not None:
            self._thread.join(timeout=10)
        for conn in list(self._conns.values()):
            self._close(conn)
        for s in (self._lsock, self._dsock):
            if s is not None:
                try:
                    self._sel.unregister(s)
                except (KeyError, ValueError):
                    pass
                s.close()
        for fd in (self._wake_r, self._wake_w):
            try:
                os.close(fd)
            except OSError:
                pass
        self._sel.close()

    def __enter__(self) -> "IngestServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _wake(self) -> None:
        try:
            os.write(self._wake_w, b"x")
        except OSError:
            pass

    # -- admission (any thread; lock-guarded) --------------------------
    def _seen(self, cid: int, seq: int) -> bool:
        ent = self._marks.get(cid)
        return ent is not None and (seq <= ent[0] or seq in ent[1])

    def _mark(self, cid: int, seq: int) -> None:
        ent = self._marks.setdefault(cid, [-1, set()])
        ent[1].add(seq)
        while ent[0] + 1 in ent[1]:
            ent[0] += 1
            ent[1].discard(ent[0])

    def _admit_once(self, cid: int, seq: int, nops: int,
                    reorder: bool) -> Tuple[int, int]:
        if self._seen(cid, seq):
            self.counters["deduped"] += 1
            return framing.ST_DUP, 0
        hwm = max(1, self.hwm // 2) if self._device_pressure \
            else self.hwm
        held = sum(n for _, n in self._held)
        if int(self.pending.sum()) + held >= hwm:
            self.counters["busy"] += 1
            hint = self.retry_after_ms * \
                (2 if self._device_pressure else 1)
            return framing.ST_BUSY, hint
        self._mark(cid, seq)
        slot = int(self.route(cid)) % self.n
        if reorder:
            self._held.append((slot, int(nops)))
            self.counters["reordered"] += 1
        else:
            self.pending[slot] += int(nops)
        self.counters["admitted_ops"] += int(nops)
        self.counters["admitted_reqs"] += 1
        self._arrivals.append(time.monotonic_ns())
        if self.shard_of is not None:
            sh = int(self.shard_of(cid))
            self.shard_rx[sh] = self.shard_rx.get(sh, 0) + int(nops)
        return framing.ST_OK, 0

    def admit_frame(self, cid: int, seq: int, nops: int,
                    attempt: int) -> Optional[Tuple[int, int]]:
        """Run one REQ through chaos ingress + dedup + backpressure;
        returns ``(status, retry_after_ms)`` for the ACK, or None
        when the chaos plane dropped the frame (no ACK at all -- the
        client's timeout is the signal)."""
        with self._lock:
            self.counters["requests"] += 1
            drop, dup, reorder = faults_mod.decide(
                self.spec, cid, seq, attempt)
            if drop:
                self.counters["drops_injected"] += 1
                return None
            st = self._admit_once(cid, seq, nops, reorder)
            if dup and st[0] != framing.ST_BUSY:
                # the network delivered a second copy; it must hit
                # the watermark (BUSY admits nothing, so there is no
                # watermark for a copy to hit -- the client retries
                # the whole frame)
                self.counters["dup_frames"] += 1
                self._admit_once(cid, seq, nops, reorder)
            return st

    # -- the coalesce take (serve-loop thread) -------------------------
    def take_chunk(self, epochs: int) -> TakeResult:
        """Drain the coalesce buffer into an ``int32[epochs, n]``
        superwave matrix (per-slot, per-epoch rows capped at
        ``waves`` -- the device clamp's own wave geometry, so the
        host never fabricates an epoch the device would refuse).
        Ops beyond ``epochs * waves`` per slot stay pending for the
        next take; held (reordered) admissions pour into the buffer
        AFTER the matrix is built, landing one boundary late by
        construction."""
        epochs = int(epochs)
        counts = np.zeros((epochs, self.n), dtype=np.int32)
        with self._lock:
            for e in range(epochs):
                take = np.minimum(self.pending, self.waves)
                counts[e] = take.astype(np.int32)
                self.pending -= take
            for slot, nops in self._held:
                self.pending[slot] += nops
            self._held.clear()
            marks = {str(c): [int(m[0]), sorted(m[1])]
                     for c, m in self._marks.items()}
            events = dict(self.counters)
            arrivals = self._arrivals
            self._arrivals = []
            carry = [int(x) for x in self.pending]
        return TakeResult(counts, marks, events, arrivals, carry)

    def restore_marks(self, marks: Optional[dict]) -> None:
        """Rehydrate dedup watermarks from a journal record (resume):
        what a dead incarnation durably admitted stays admitted."""
        if not marks:
            return
        with self._lock:
            for cid, (mark, extras) in marks.items():
                self._marks[int(cid)] = [int(mark),
                                         set(int(x) for x in extras)]

    def note_device_drops(self, delta: int) -> None:
        """Feed the device admission clamp's ``ingest_drops`` delta:
        any clamping this chunk tightens backpressure (halved
        watermark, doubled retry hint) until a clean chunk clears
        it."""
        with self._lock:
            if int(delta) > 0:
                self.counters["device_drop_signals"] += 1
                self._device_pressure = True
            else:
                self._device_pressure = False

    # -- notifications -------------------------------------------------
    def publish(self, obj) -> None:
        """Queue one completion NOTIFY batch for every subscriber
        (best-effort: subscribers are telemetry, never admission)."""
        payload = framing.pack_notify(obj)
        with self._lock:
            self.counters["notify_batches"] += 1
        self._notify_q.append(payload)
        self._wake()

    # -- status / metrics ----------------------------------------------
    def queue_depth(self) -> int:
        with self._lock:
            return int(self.pending.sum()) \
                + sum(n for _, n in self._held)

    def status(self) -> dict:
        with self._lock:
            return {
                "port": self.port,
                "queue_depth": int(self.pending.sum())
                + sum(n for _, n in self._held),
                "high_watermark": self.hwm,
                "device_pressure": bool(self._device_pressure),
                "connections": len(self._conns),
                "clients_seen": len(self._marks),
                "fault_spec": faults_mod.describe(self.spec),
                "shard_rx": {str(k): v
                             for k, v in sorted(self.shard_rx.items())},
                "counters": dict(self.counters),
            }

    def http_handler(self, method: str, path: str, body):
        """``GET /rpc/status`` handler for
        :meth:`obs.registry.MetricsHTTPServer.mount` -- the admin API
        and the ingest plane share one endpoint (docs/RPC.md)."""
        if method != "GET":
            return 405, "text/plain", b"method not allowed"
        return 200, "application/json", json.dumps(
            self.status(), sort_keys=True).encode("utf-8")

    # -- event loop ----------------------------------------------------
    def _loop(self) -> None:
        last_sweep = time.monotonic()
        while not self._stop:
            for key, mask in self._sel.select(timeout=0.2):
                kind, conn = key.data
                if kind == "accept":
                    self._accept()
                elif kind == "datagram":
                    self._datagram()
                elif kind == "wake":
                    try:
                        while os.read(self._wake_r, 4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                else:
                    if mask & selectors.EVENT_READ:
                        self._readable(conn)
                    if conn.sock.fileno() >= 0 and \
                            mask & selectors.EVENT_WRITE:
                        self._flush(conn)
            self._drain_notify()
            now = time.monotonic()
            if now - last_sweep >= 1.0:
                self._sweep_idle(now)
                last_sweep = now

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._lsock.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock, addr)
            self._conns[sock.fileno()] = conn
            self.counters["conns_opened"] += 1
            self._sel.register(sock, selectors.EVENT_READ,
                               ("conn", conn))

    def _close(self, conn: _Conn) -> None:
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        self._conns.pop(conn.sock.fileno(), None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _on_payload(self, conn: _Conn, payload: bytes) -> None:
        t, fields = framing.unpack(payload)
        if t == framing.T_REQ:
            cid, seq, nops, attempt = fields
            verdict = self.admit_frame(cid, seq, nops, attempt)
            if verdict is not None:
                conn.out += framing.frame(
                    framing.pack_ack(cid, seq, *verdict))
        elif t == framing.T_SUB:
            conn.sub = True
        else:
            raise framing.ProtocolError(
                f"unexpected frame type {t} from client")

    def _readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(_RECV)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close(conn)
            return
        if not data:
            self._close(conn)
            return
        conn.last = time.monotonic()
        try:
            for payload in conn.framer.feed(data):
                self._on_payload(conn, payload)
        except framing.ProtocolError:
            self.counters["proto_errors"] += 1
            self._close(conn)
            return
        self._flush(conn)

    def _flush(self, conn: _Conn) -> None:
        if conn.out:
            try:
                sent = conn.sock.send(conn.out)
                del conn.out[:sent]
            except (BlockingIOError, InterruptedError):
                pass
            except OSError:
                self._close(conn)
                return
        want = selectors.EVENT_READ | \
            (selectors.EVENT_WRITE if conn.out else 0)
        try:
            self._sel.modify(conn.sock, want, ("conn", conn))
        except (KeyError, ValueError):
            pass

    def _datagram(self) -> None:
        assert self._dsock is not None
        while True:
            try:
                payload, addr = self._dsock.recvfrom(_RECV)
            except (BlockingIOError, OSError):
                return
            self.counters["datagrams"] += 1
            try:
                t, fields = framing.unpack(payload)
            except framing.ProtocolError:
                self.counters["proto_errors"] += 1
                continue
            if t != framing.T_REQ:
                self.counters["proto_errors"] += 1
                continue
            cid, seq, nops, attempt = fields
            verdict = self.admit_frame(cid, seq, nops, attempt)
            if verdict is not None:
                try:
                    self._dsock.sendto(
                        framing.pack_ack(cid, seq, *verdict), addr)
                except OSError:
                    pass

    def _drain_notify(self) -> None:
        while self._notify_q:
            payload = self._notify_q.popleft()
            framed = framing.frame(payload)
            for conn in list(self._conns.values()):
                if conn.sub:
                    conn.out += framed
                    self._flush(conn)

    def _sweep_idle(self, now: float) -> None:
        for conn in list(self._conns.values()):
            if now - conn.last > self.idle_timeout_s:
                self.counters["conns_timed_out"] += 1
                self._close(conn)
