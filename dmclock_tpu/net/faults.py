"""The deterministic network fault plane (docs/RPC.md "Chaos").

Same design contract as the PR-3 device fault plane
(:mod:`dmclock_tpu.robust.faults`): a seeded spec parsed from a
compact ``key=value`` grammar, a PURE decision function of the frame
identity, and an EXACT host oracle -- the chaos gate asserts the
server's fault counters equal the oracle's plan, not "roughly
behaved".

Decisions hash ``(seed, cid, seq, attempt)`` through splitmix64, so
they are independent of arrival order, socket interleaving, and
retry timing: the same logical request draws the same fate in every
run, which is what makes exact accounting possible across N worker
processes racing over real sockets.

Fault semantics (applied at server frame ingress, simulating the
network; docs/RPC.md for the full contract):

- ``drop``: the frame vanishes -- no ACK; the client times out and
  retries with ``attempt + 1`` (a fresh fate draw).
- ``dup``: the frame is delivered twice back-to-back; the second
  copy hits the dedup watermark (counted ``deduped``).  Evaluated
  only on the attempt that actually admits.
- ``reorder``: delivery is delayed past the current coalesce window
  -- the request is ACKed normally but admits at the NEXT chunk
  boundary.  Evaluated only on the admitting attempt.
- ``stall_ms``: client-side -- the loadgen worker sleeps this long
  before sending the affected frame (slow-client robustness; the
  server's idle-timeout plane is what it exercises).  Drawn with the
  same hash, salt 3.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

_KEYS = ("seed", "p_drop", "p_dup", "p_reorder", "p_stall",
         "stall_ms")
_FLOAT_KEYS = frozenset(("p_drop", "p_dup", "p_reorder", "p_stall"))

# fate salts (distinct streams per fault kind)
_S_DROP, _S_DUP, _S_REORDER, _S_STALL = 1, 2, 3, 4

_SCALE = 1 << 64          # float probabilities -> integer thresholds
#                           (the fate draw is a full u64)


def parse_net_fault_spec(spec: Union[str, dict, None]
                         ) -> Optional[dict]:
    """Parse ``"seed=7,p_drop=0.1,p_dup=0.05"`` (or a dict) into a
    normalized spec dict; None/empty -> None (fault plane off).
    Unknown keys are an error -- a typo'd chaos spec must not
    silently run a clean leg."""
    if spec is None:
        return None
    if isinstance(spec, str):
        spec = spec.strip()
        if not spec:
            return None
        out: Dict[str, float] = {}
        for part in spec.split(","):
            k, _, v = part.partition("=")
            k = k.strip()
            if k not in _KEYS:
                raise ValueError(f"unknown net fault key {k!r} "
                                 f"(have {', '.join(_KEYS)})")
            out[k] = float(v) if k in _FLOAT_KEYS else int(v)
        spec = out
    else:
        bad = set(spec) - set(_KEYS)
        if bad:
            raise ValueError(f"unknown net fault keys {sorted(bad)}")
        spec = dict(spec)
    spec.setdefault("seed", 0)
    for k in _FLOAT_KEYS:
        p = float(spec.setdefault(k, 0.0))
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"{k}={p} outside [0, 1]")
    spec.setdefault("stall_ms", 0)
    if not any(spec[k] > 0 for k in _FLOAT_KEYS):
        return None
    return spec


def describe(spec: Optional[dict]) -> str:
    """Compact spec tag for logs / bench JSON (PR-3 style)."""
    if spec is None:
        return "none"
    parts = [f"seed={int(spec['seed'])}"]
    parts += [f"{k}={spec[k]:g}" for k in sorted(_FLOAT_KEYS)
              if spec.get(k, 0.0) > 0]
    if spec.get("stall_ms", 0):
        parts.append(f"stall_ms={int(spec['stall_ms'])}")
    return ",".join(parts)


def _mix(seed: int, cid: int, seq: int, attempt: int,
         salt: int) -> int:
    """splitmix64 over the frame identity -- one u64 fate draw."""
    x = (seed * 0x9E3779B97F4A7C15 + cid * 0xBF58476D1CE4E5B9
         + seq * 0x94D049BB133111EB + attempt * 0xD6E8FEB86659FD93
         + salt * 0xA24BAED4963EE407) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


def _hit(spec: dict, key: str, cid: int, seq: int, attempt: int,
         salt: int) -> bool:
    p = float(spec.get(key, 0.0))
    if p <= 0.0:
        return False
    return _mix(int(spec["seed"]), cid, seq, attempt, salt) \
        < int(p * _SCALE)


def decide(spec: Optional[dict], cid: int, seq: int,
           attempt: int) -> Tuple[bool, bool, bool]:
    """The pure fate function: ``(drop, dup, reorder)`` for one frame
    identity.  Same triple in the server, the oracle, and any test."""
    if spec is None:
        return False, False, False
    cid, seq, attempt = int(cid), int(seq), int(attempt)
    return (_hit(spec, "p_drop", cid, seq, attempt, _S_DROP),
            _hit(spec, "p_dup", cid, seq, attempt, _S_DUP),
            _hit(spec, "p_reorder", cid, seq, attempt, _S_REORDER))


def stall_ms(spec: Optional[dict], cid: int, seq: int,
             attempt: int) -> int:
    """Client-side slow-sender stall for this frame (0 = none)."""
    if spec is None or spec.get("stall_ms", 0) <= 0:
        return 0
    if _hit(spec, "p_stall", int(cid), int(seq), int(attempt),
            _S_STALL):
        return int(spec["stall_ms"])
    return 0


def plan_events(spec: Optional[dict],
                schedule: Sequence[Tuple[int, int]],
                max_attempts: int = 8) -> Dict[str, int]:
    """The exact host oracle: walk every ``(cid, seq)`` in
    ``schedule`` through the fate function exactly like a retrying
    client would, and return the event totals a faithful server run
    MUST report (the ci chaos gate's equality check).

    ``drops`` counts dropped attempt-frames; ``dups``/``reorders``
    are per admitted request (evaluated at the admitting attempt --
    BUSY retries re-send the same attempt, so backpressure cannot
    skew the accounting); ``lost`` counts requests whose every
    attempt up to ``max_attempts`` dropped (the loadgen reports these
    as failures, the server never saw them admit)."""
    out = {"drops": 0, "dups": 0, "reorders": 0, "lost": 0,
           "admitted": 0}
    for cid, seq in schedule:
        admitted_at = None
        for a in range(int(max_attempts)):
            drop, _, _ = decide(spec, cid, seq, a)
            if drop:
                out["drops"] += 1
            else:
                admitted_at = a
                break
        if admitted_at is None:
            out["lost"] += 1
            continue
        out["admitted"] += 1
        _, dup, reorder = decide(spec, cid, seq, admitted_at)
        if dup:
            out["dups"] += 1
        if reorder:
            out["reorders"] += 1
    return out


def plan_schedule_events(spec: Optional[dict],
                         schedules: Sequence[Sequence[Tuple[int, int]]],
                         max_attempts: int = 8) -> Dict[str, int]:
    """Oracle over several workers' schedules (order-independent by
    construction -- the fate hash never sees arrival order)."""
    flat: List[Tuple[int, int]] = [rq for sched in schedules
                                   for rq in sched]
    return plan_events(spec, flat, max_attempts=max_attempts)
