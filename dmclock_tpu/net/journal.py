"""The fsync'd arrival journal (docs/RPC.md "Crash equivalence").

Write-ahead admission on the checkpoint-boundary grid, the same WAL
discipline as the controller's :class:`control.journal
.DecisionJournal`: one JSON line per chunk boundary, appended --
``write`` + ``flush`` + ``fsync`` -- BEFORE the chunk is applied to
the device.  A SIGKILL between the fsync and the apply therefore
leaves a journaled-but-unapplied record, and resume REPLAYS it
instead of re-taking from the (gone) socket buffers: the admitted-
counts trace of the resumed run is byte-identical to an
uninterrupted one, which is the whole crash-equivalence contract of
``--mode rpc``.

Each record carries everything admission needs to be exactly-once:

- ``counts``: the coalesced ``int32[epochs, n]`` superwave matrix
  this boundary admits (the device sees nothing else);
- ``marks``: the per-client dedup watermarks AFTER this take (a
  resumed server rehydrates them, so a client retrying an already-
  journaled seq gets ST_DUP, not a double admission);
- ``events``: the cumulative fault/backpressure counter snapshot
  (the chaos gate's exact-accounting read).

Torn tails (a crash mid-append) are truncated away on load, exactly
like the decision journal: a record is either durable and complete
or it never happened.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

_FILENAME = "arrivals.jsonl"


class ArrivalJournal:
    """Append-only, strictly sequential boundary records.

    ``workdir=None`` keeps the journal in memory (unit tests and the
    self-generated replay twin, where durability is meaningless)."""

    def __init__(self, workdir: Optional[str] = None) -> None:
        self.path = None if workdir is None else os.path.join(
            os.fspath(workdir), _FILENAME)
        self.entries: List[dict] = []
        if self.path is not None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._load()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        keep = 0
        with open(self.path, "rb") as f:
            raw = f.read()
        for line in raw.split(b"\n"):
            if not line:
                continue
            end = raw.find(b"\n", keep)
            if end < 0:
                break          # torn tail: no newline -> not durable
            try:
                ent = json.loads(raw[keep:end].decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                break          # torn/corrupt line: truncate from here
            if int(ent.get("seq", -1)) != len(self.entries):
                break          # sequence gap: refuse the suffix
            self.entries.append(ent)
            keep = end + 1
        if keep < len(raw):
            # drop the torn suffix ON DISK too, so the next append
            # starts at a clean line boundary
            with open(self.path, "r+b") as f:
                f.truncate(keep)
                f.flush()
                os.fsync(f.fileno())

    def __len__(self) -> int:
        return len(self.entries)

    def entry_at(self, seq: int) -> Optional[dict]:
        seq = int(seq)
        return self.entries[seq] if 0 <= seq < len(self.entries) \
            else None

    def append(self, entry: dict) -> dict:
        """Durably append the next boundary record; returns it.  The
        fsync completes BEFORE this returns -- callers apply the
        chunk only after."""
        entry = dict(entry)
        entry["seq"] = len(self.entries)
        if self.path is not None:
            line = json.dumps(entry, sort_keys=True,
                              separators=(",", ":")) + "\n"
            with open(self.path, "ab") as f:
                f.write(line.encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
        self.entries.append(entry)
        return entry

    def counts_trace(self) -> List[list]:
        """The admitted-counts trace, one matrix per boundary -- what
        the self-generated replay twin is fed (the digest gate)."""
        return [ent["counts"] for ent in self.entries]

    def last_marks(self) -> Optional[dict]:
        """The newest record's dedup watermarks (server rehydration
        on resume); None when the journal is empty."""
        return self.entries[-1]["marks"] if self.entries else None
