"""The RPC ingest front-end (docs/RPC.md).

A fault-tolerant network serving plane in front of the PR-8 fused
stream chunks: many concurrent client processes push tagged requests
over real sockets, the host coalesces them into the superwave count
matrix, and the existing device-side admission clamp prices them --
the network plane adds EXACTLY ZERO new device math, which is what
keeps ``--mode rpc`` digest-comparable to a self-generated run fed
the same admitted-counts trace.

Layering (each module stands alone and is unit-tested alone):

- :mod:`.framing`  -- wire format: length-prefixed stream frames and
  single-datagram frames sharing one payload encoding.
- :mod:`.faults`   -- the deterministic network fault plane (seeded
  drops / duplicates / reorders), PR-3 spec-grammar style, with an
  exact host oracle for the chaos gates.
- :mod:`.journal`  -- the fsync'd arrival journal on the checkpoint-
  boundary grid; the crash-equivalence contract's durable half.
- :mod:`.server`   -- the selectors event loop: backpressure,
  dedup watermarks, per-shard routing accounting, completion
  notifications, counters.
- :mod:`.client`   -- the blocking client with bounded exponential
  backoff (what scripts/loadgen.py workers drive).
- :mod:`.serve`    -- the serving loop: journal -> fused chunk ->
  checkpoint, double-buffered, SIGKILL-resumable, replayable.
"""

from . import framing, faults, journal  # noqa: F401

__all__ = ["framing", "faults", "journal"]
