"""Wire format for the RPC ingest plane (docs/RPC.md "Framing").

One payload encoding, two transports:

- **stream** (TCP): each frame is a 4-byte big-endian length prefix
  followed by that many payload bytes.  :class:`Framer` decodes the
  byte stream incrementally (partial frames across ``recv`` calls are
  the normal case, not an error).
- **datagram** (UDP): one payload per datagram, no length prefix --
  the datagram boundary IS the frame boundary.

Payload = 1 type byte + fixed ``struct`` body (JSON body for NOTIFY,
whose schema is host-side telemetry, not admission state).  All
integers are network byte order.  The format is versionless on
purpose: the client and server ship in the same tree, and an unknown
type byte is a protocol error, not a negotiation.
"""

from __future__ import annotations

import json
import struct
from typing import List, Optional, Tuple

# frame types
T_REQ = 1        # client -> server: admit `nops` ops for client `cid`
T_ACK = 2        # server -> client: per-REQ verdict
T_NOTIFY = 3     # server -> subscribers: batched completion report
T_SUB = 4        # client -> server: subscribe this conn to NOTIFYs

# ACK statuses
ST_OK = 0        # accepted into the coalesce buffer
ST_DUP = 1       # (cid, seq) already admitted -- idempotent resend
ST_BUSY = 2      # backpressure: retry after `retry_after_ms`

_LEN = struct.Struct("!I")
_REQ = struct.Struct("!IQIH")     # cid, seq, nops, attempt
_ACK = struct.Struct("!IQBI")     # cid, seq, status, retry_after_ms

#: refuse frames bigger than this (a corrupt length prefix must not
#: make the server buffer gigabytes)
MAX_FRAME = 1 << 20


def pack_req(cid: int, seq: int, nops: int, attempt: int = 0) -> bytes:
    return bytes([T_REQ]) + _REQ.pack(int(cid), int(seq), int(nops),
                                      int(attempt))


def pack_ack(cid: int, seq: int, status: int,
             retry_after_ms: int = 0) -> bytes:
    return bytes([T_ACK]) + _ACK.pack(int(cid), int(seq), int(status),
                                      int(retry_after_ms))


def pack_notify(obj) -> bytes:
    return bytes([T_NOTIFY]) + json.dumps(
        obj, sort_keys=True, separators=(",", ":")).encode("utf-8")


def pack_sub() -> bytes:
    return bytes([T_SUB])


def unpack(payload: bytes) -> Tuple[int, tuple]:
    """Decode one payload to ``(type, fields)``.

    - REQ -> ``(cid, seq, nops, attempt)``
    - ACK -> ``(cid, seq, status, retry_after_ms)``
    - NOTIFY -> ``(obj,)`` (decoded JSON)
    - SUB -> ``()``
    """
    if not payload:
        raise ProtocolError("empty payload")
    t = payload[0]
    body = payload[1:]
    try:
        if t == T_REQ:
            return t, _REQ.unpack(body)
        if t == T_ACK:
            return t, _ACK.unpack(body)
        if t == T_NOTIFY:
            return t, (json.loads(body.decode("utf-8")),)
        if t == T_SUB:
            if body:
                raise ProtocolError("SUB carries no body")
            return t, ()
    except (struct.error, ValueError) as e:
        raise ProtocolError(f"bad frame body (type {t}): {e}") from e
    raise ProtocolError(f"unknown frame type {t}")


def frame(payload: bytes) -> bytes:
    """Length-prefix a payload for the stream transport."""
    if len(payload) > MAX_FRAME:
        raise ProtocolError(f"frame too large ({len(payload)} bytes)")
    return _LEN.pack(len(payload)) + payload


class ProtocolError(ValueError):
    """Malformed frame: the connection that produced it is closed
    (one bad peer must not take the accept loop down)."""


class Framer:
    """Incremental stream decoder: feed received bytes, harvest
    complete payloads.  Tolerates arbitrary fragmentation; rejects
    oversized length prefixes immediately (before buffering)."""

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf.extend(data)
        out: List[bytes] = []
        while True:
            if len(self._buf) < _LEN.size:
                return out
            (n,) = _LEN.unpack_from(self._buf, 0)
            if n > MAX_FRAME:
                raise ProtocolError(f"frame length {n} > {MAX_FRAME}")
            if len(self._buf) < _LEN.size + n:
                return out
            out.append(bytes(self._buf[_LEN.size:_LEN.size + n]))
            del self._buf[:_LEN.size + n]

    def pending(self) -> int:
        """Bytes buffered awaiting a complete frame (0 at a clean
        frame boundary -- what the tests assert after a drain)."""
        return len(self._buf)


def read_frame(sock, timeout: Optional[float] = None) -> bytes:
    """Blocking single-frame read off a stream socket (the simple
    client path; the server never blocks like this).  Raises
    ``ConnectionError`` on EOF mid-frame."""
    if timeout is not None:
        sock.settimeout(timeout)
    need = _LEN.size
    head = _recv_exact(sock, need)
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        raise ProtocolError(f"frame length {n} > {MAX_FRAME}")
    return _recv_exact(sock, n)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)
