"""The blocking RPC client (docs/RPC.md "Client contract").

What a well-behaved tenant of the ingest plane does, in one class:

- **timeout retry**: an unACKed frame (chaos drop, dead server) is
  re-sent with ``attempt + 1`` after a full exponential backoff step
  -- the attempt number is part of the frame identity, so the fault
  plane draws a fresh fate and the server's accounting stays exact.
- **backpressure honor**: ``ST_BUSY`` sleeps the server's
  ``retry_after_ms`` hint (plus the current backoff) and re-sends
  the SAME attempt -- backpressure is not a network fault, and
  keeping the attempt stable is what lets the chaos oracle price
  dup/reorder fates independently of queue depth.
- **idempotent resends**: ``ST_DUP`` is success (the earlier copy
  admitted; the ACK just got lost or raced a retry).
- **reconnect**: a torn connection rebuilds the socket and re-sends
  the in-flight frame (same attempt -- the transport died, not the
  admission).

Every worker in ``scripts/loadgen.py`` drives exactly this class
over a real socket; nothing here is test scaffolding.
"""

from __future__ import annotations

import socket
import time
from typing import Optional

from . import framing


class RpcError(RuntimeError):
    """Request abandoned after ``max_attempts`` unACKed sends."""


class RpcClient:
    """One connection, one in-flight request at a time (the loadgen
    runs N processes for concurrency -- real multi-tenant pressure,
    not asyncio simulation)."""

    def __init__(self, host: str, port: int, *,
                 timeout_s: float = 0.5, max_attempts: int = 8,
                 backoff_base_s: float = 0.02,
                 backoff_cap_s: float = 1.0,
                 sleep=time.sleep) -> None:
        self.host, self.port = host, int(port)
        self.timeout_s = float(timeout_s)
        self.max_attempts = int(max_attempts)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self._sleep = sleep
        self._sock: Optional[socket.socket] = None
        self.stats = {"sent": 0, "ok": 0, "dup": 0, "busy": 0,
                      "timeouts": 0, "reconnects": 0, "failed": 0}

    # -- transport -----------------------------------------------------
    def _ensure(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _teardown(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        self._teardown()

    def __enter__(self) -> "RpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _backoff(self, step: int) -> float:
        return min(self.backoff_base_s * (2 ** step),
                   self.backoff_cap_s)

    # -- the request path ----------------------------------------------
    def request(self, cid: int, seq: int, nops: int) -> int:
        """Admit ``nops`` ops for ``(cid, seq)``; returns the final
        ACK status (``ST_OK`` or ``ST_DUP``).  Raises
        :class:`RpcError` when every attempt times out."""
        attempt = 0
        step = 0
        while attempt < self.max_attempts:
            try:
                sock = self._ensure()
                sock.settimeout(self.timeout_s)
                sock.sendall(framing.frame(
                    framing.pack_req(cid, seq, nops, attempt)))
                self.stats["sent"] += 1
                payload = self._read_ack(sock, cid, seq)
            except socket.timeout:
                # dropped (chaos or loss): fresh attempt, fresh fate
                self.stats["timeouts"] += 1
                self._sleep(self._backoff(step))
                attempt += 1
                step += 1
                continue
            except (ConnectionError, OSError):
                self.stats["reconnects"] += 1
                self._teardown()
                self._sleep(self._backoff(step))
                step += 1
                continue          # transport died: SAME attempt
            status, retry_ms = payload
            if status == framing.ST_OK:
                self.stats["ok"] += 1
                return status
            if status == framing.ST_DUP:
                self.stats["dup"] += 1
                return status
            # ST_BUSY: honor the hint, re-send the SAME attempt
            self.stats["busy"] += 1
            self._sleep(retry_ms / 1000.0 + self._backoff(step))
            step += 1
        self.stats["failed"] += 1
        raise RpcError(f"cid={cid} seq={seq}: no ACK after "
                       f"{self.max_attempts} attempts")

    def _read_ack(self, sock, cid: int, seq: int):
        """Read frames until THIS request's ACK arrives (NOTIFYs and
        stale ACKs from abandoned attempts are skipped)."""
        while True:
            t, fields = framing.unpack(framing.read_frame(
                sock, timeout=self.timeout_s))
            if t != framing.T_ACK:
                continue
            a_cid, a_seq, status, retry_ms = fields
            if a_cid == cid and a_seq == seq:
                return status, retry_ms


def drain_notifies(host: str, port: int, *, timeout_s: float = 1.0,
                   max_batches: int = 10):
    """Subscribe and collect NOTIFY batches until the socket goes
    quiet (a test/debug helper; loadgen workers do not subscribe)."""
    out = []
    with socket.create_connection((host, int(port)),
                                  timeout=timeout_s) as sock:
        sock.sendall(framing.frame(framing.pack_sub()))
        try:
            while len(out) < max_batches:
                t, fields = framing.unpack(
                    framing.read_frame(sock, timeout=timeout_s))
                if t == framing.T_NOTIFY:
                    out.append(fields[0])
        except (socket.timeout, ConnectionError):
            pass
    return out
