"""The RPC serving loop (docs/RPC.md): journal -> fused chunk ->
checkpoint, double-buffered, SIGKILL-resumable, replayable.

The loop is the supervisor's stream loop with the Poisson pregen
swapped for the network coalesce: at every checkpoint boundary the
:class:`net.server.IngestServer` drains its coalesce buffer into an
``int32[epochs, n]`` superwave matrix, the :class:`net.journal
.ArrivalJournal` makes that matrix durable (fsync BEFORE apply), and
:func:`robust.guarded.run_stream_chunk_guarded` admits it through
the EXISTING device-side clamp -- no new device math, no new RNG.
Consequences, each load-bearing:

- **digest gate**: a run fed the journaled trace through the same
  loop (``trace=journal.counts_trace()``, no sockets) produces the
  IDENTICAL chain digest -- the ``--mode rpc`` acceptance gate.
- **crash equivalence**: SIGKILL anywhere -- including between the
  journal fsync and the chunk apply -- resumes from the newest
  rotation checkpoint, REPLAYS any journaled-but-unapplied record,
  rehydrates the dedup watermarks and the carry vector from the
  journal, and lands on the uninterrupted run's digest and
  admitted-counts trace.  Nothing admits twice, nothing journaled
  drops.
- **double buffering**: the ``overlap()`` seam takes + journals
  boundary T+1's arrivals while the device runs chunk T, so network
  receive and the fsync both hide under device compute.

Run it as a module for the subprocess legs (ci smoke, SIGKILL
tests)::

    python -m dmclock_tpu.net.serve --config cfg.json --out out.json
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import signal
import time
from typing import List, Optional

import numpy as np

from .journal import ArrivalJournal
from .server import IngestServer


@dataclasses.dataclass(frozen=True)
class RpcServeConfig:
    """Plain-data serving config (JSON-round-trips into the
    subprocess legs, the EpochJob discipline)."""

    engine: str = "prefix"
    n: int = 16                  # clients == coalesce slots
    depth: int = 4               # preloaded queue depth
    ring: int = 10
    epochs: int = 8
    m: int = 2
    k: int = 16
    chain_depth: int = 4
    select_impl: str = "sort"
    tag_width: int = 64
    calendar_impl: str = "minstop"
    ladder_levels: int = 8
    wheel_kernel: str = "xla"
    seed: int = 11
    waves: int = 4
    dt_epoch_ns: int = 10 ** 8
    ckpt_every: int = 2
    keep: int = 4
    n_shards: int = 1            # PlacementMap routing attribution
    with_slo: bool = True        # conformance verdicts in NOTIFYs
    # network knobs
    host: str = "127.0.0.1"
    port: int = 0
    high_watermark: int = 0      # 0 = auto (n * waves * 4)
    retry_after_ms: int = 25
    idle_timeout_s: float = 30.0
    fault_spec: Optional[str] = None
    # pacing: hold the FIRST boundary take until this many ops
    # admitted (ci smoke fills the buffer before serving starts)
    wait_ops: int = 0
    wait_timeout_s: float = 60.0
    # durable state (None = memory-only journal, no checkpoints --
    # the replay twin's shape)
    workdir: Optional[str] = None
    metrics_port: Optional[int] = None


def _cfg_from_json(d: dict) -> RpcServeConfig:
    fields = {f.name for f in dataclasses.fields(RpcServeConfig)}
    return RpcServeConfig(**{k: v for k, v in d.items()
                             if k in fields})


def _serve_job(cfg: RpcServeConfig):
    """The EpochJob twin of this config -- what lets the serving
    loop reuse the supervisor's deterministic preload verbatim (the
    digest gate's replay twin builds the same state the same way)."""
    from ..robust.supervisor import EpochJob

    return EpochJob(engine=cfg.engine, n=cfg.n, depth=cfg.depth,
                    ring=cfg.ring, epochs=cfg.epochs, m=cfg.m,
                    k=cfg.k, chain_depth=cfg.chain_depth,
                    select_impl=cfg.select_impl,
                    tag_width=cfg.tag_width,
                    calendar_impl=cfg.calendar_impl,
                    ladder_levels=cfg.ladder_levels,
                    wheel_kernel=cfg.wheel_kernel, seed=cfg.seed,
                    waves=cfg.waves, dt_epoch_ns=cfg.dt_epoch_ns,
                    ckpt_every=cfg.ckpt_every, keep=cfg.keep)


def make_server(cfg: RpcServeConfig) -> IngestServer:
    """Build (not start) the ingest server for a config, with
    PlacementMap ownership wired in as the per-shard routing
    attribution (``dmclock_rpc_shard_routed_ops_total``)."""
    shard_of = None
    if cfg.n_shards > 1:
        from ..lifecycle.placement import PlacementMap

        pm = PlacementMap(cfg.n_shards, cfg.n, mode="p2c",
                          seed=cfg.seed)
        pm.place_batch(list(range(cfg.n)),
                       backlog=np.zeros(cfg.n_shards,
                                        dtype=np.int64))
        shard_of = pm.shard_of
    return IngestServer(
        cfg.n, waves=cfg.waves, host=cfg.host, port=cfg.port,
        high_watermark=cfg.high_watermark or None,
        retry_after_ms=cfg.retry_after_ms,
        fault_spec=cfg.fault_spec, shard_of=shard_of,
        idle_timeout_s=cfg.idle_timeout_s)


def _ckpt_payload(state, digest: bytes, epoch: int, decisions: int,
                  met: np.ndarray) -> dict:
    return {"rpc_state": state,
            "rpc_digest": np.frombuffer(
                digest.ljust(32, b"\x00"), dtype=np.uint8).copy(),
            "rpc_epoch": np.int64(epoch),
            "rpc_decisions": np.int64(decisions),
            "rpc_met": np.asarray(met, dtype=np.int64)}


def trace_sha(trace: List[list]) -> str:
    """Canonical hash of an admitted-counts trace -- what the crash
    and chaos gates compare across incarnations."""
    blob = json.dumps(trace, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


def run_serve(cfg: RpcServeConfig, *,
              server: Optional[IngestServer] = None,
              trace: Optional[List[list]] = None,
              crash_after_fsync: Optional[int] = None) -> dict:
    """Run the serving loop to completion (or resume it) and return
    the result record.

    Exactly one arrivals source per boundary, in priority order: an
    existing journal record (resume/replay), the live ``server``
    coalesce, or the ``trace`` matrix (the self-generated twin).
    ``crash_after_fsync=k`` SIGKILLs the process immediately after
    boundary ``k``'s journal record is durable and before its chunk
    applies -- the exact window the crash-equivalence tests pin.
    """
    import jax

    from ..engine.stream import chunk_bounds
    from ..obs import device as obsdev
    from ..robust.guarded import run_stream_chunk_guarded
    from ..robust.supervisor import _digest_update, _job_state
    from ..utils import checkpoint as ckpt_mod

    job = _serve_job(cfg)
    state = _job_state(job)
    digest = b""
    decisions = 0
    met = np.zeros(obsdev.NUM_METRICS, dtype=np.int64)
    start = 0
    resumed = False

    ckpt_dir = None
    journal = ArrivalJournal(cfg.workdir)
    if cfg.workdir is not None:
        ckpt_dir = os.path.join(cfg.workdir, "ckpt")
        if ckpt_mod.rotation_paths(ckpt_dir):
            like = _ckpt_payload(state, b"\x00" * 32, 0, 0, met)
            tree, _ = ckpt_mod.restore_pytree_rotating(ckpt_dir, like)
            state = tree["rpc_state"]
            digest = bytes(np.asarray(tree["rpc_digest"],
                                      dtype=np.uint8).tobytes())
            start = int(tree["rpc_epoch"])
            decisions = int(tree["rpc_decisions"])
            met = np.asarray(tree["rpc_met"], dtype=np.int64).copy()
            resumed = True
    if server is not None:
        last = journal.last_marks()
        if last is not None:
            server.restore_marks(last)
        carry = journal.entries[-1].get("carry") \
            if journal.entries else None
        if carry:
            with server._lock:
                server.pending += np.asarray(carry, dtype=np.int64)

    # the SLO plane: conformance verdicts for the completion
    # notifications -- same contract layout as the preload (rate
    # floor 100 ops/s, weights 1 + i % 4), re-registered identically
    # on resume (deterministic counters; docs/RPC.md)
    slo_plane = slo_block = None
    slo_w0 = start
    if cfg.with_slo:
        from ..obs import slo as slo_mod

        slo_plane = slo_mod.SloPlane(cfg.n,
                                     dt_epoch_ns=cfg.dt_epoch_ns)
        for c in range(cfg.n):
            slo_plane.register(c, 100.0, 1.0 + (c % 4), 0.0)
        slo_block = slo_plane.stamp(slo_mod.window_zero(cfg.n))

    scrape = None
    if cfg.metrics_port is not None:
        from ..obs.registry import start_http_server

        scrape = start_http_server(port=cfg.metrics_port,
                                   host=cfg.host, fail_soft=True)
        if scrape is not None and server is not None:
            scrape.mount("/rpc", server.http_handler)

    if server is not None and cfg.wait_ops > 0 and start == 0 \
            and len(journal) == 0:
        deadline = time.monotonic() + cfg.wait_timeout_s
        while time.monotonic() < deadline:
            if server.counters["admitted_ops"] >= cfg.wait_ops:
                break
            time.sleep(0.01)

    lats: List[int] = []
    drops_seen = int(met[obsdev.MET_INGEST_DROPS])
    nxt: dict = {}

    def record_for(k: int, epochs_k: int) -> dict:
        """Take + durably journal boundary ``k`` (live mode)."""
        t = server.take_chunk(epochs_k)
        ent = journal.append({
            "seq": k, "counts": t.counts.tolist(),
            "carry": t.carry, "marks": t.marks,
            "events": t.events})
        nxt.setdefault("arrivals", {})[k] = t.arrivals_ns
        return ent

    bounds = list(chunk_bounds(start, cfg.epochs, cfg.ckpt_every))
    for e0, b in bounds:
        kb = e0 // cfg.ckpt_every
        ent = journal.entry_at(kb)
        if ent is None:
            if server is not None:
                ent = record_for(kb, b - e0)
            elif trace is not None:
                if kb >= len(trace):
                    raise ValueError(
                        f"replay trace ends at boundary {len(trace)}"
                        f", need {kb}")
                ent = journal.append({
                    "seq": kb, "counts": list(trace[kb]),
                    "carry": [], "marks": {}, "events": {}})
            else:
                raise ValueError("no arrivals source: need a live "
                                 "server, a trace, or a journal")
        if crash_after_fsync is not None and kb == crash_after_fsync:
            # the crash-equivalence window: the record is durable,
            # the chunk has NOT applied
            os.kill(os.getpid(), signal.SIGKILL)
        counts = np.asarray(ent["counts"], dtype=np.int32)

        overlap = None
        if server is not None and b < cfg.epochs:
            k_next, e_next = kb + 1, min(
                b + cfg.ckpt_every, cfg.epochs) - b

            def overlap(k_next=k_next, e_next=e_next):
                if journal.entry_at(k_next) is None:
                    record_for(k_next, e_next)

        g = run_stream_chunk_guarded(
            state, e0, counts, engine=cfg.engine, epochs=b - e0,
            m=cfg.m, k=cfg.k, chain_depth=cfg.chain_depth,
            dt_epoch_ns=cfg.dt_epoch_ns, waves=cfg.waves,
            with_metrics=True, select_impl=cfg.select_impl,
            tag_width=cfg.tag_width,
            calendar_impl=cfg.calendar_impl,
            ladder_levels=cfg.ladder_levels,
            wheel_kernel=cfg.wheel_kernel, slo=slo_block,
            overlap=overlap)
        state = g.state
        slo_block = g.slo
        for i in range(b - e0):
            decisions += g.counts[i]
            digest = _digest_update(digest, g.epochs[i])
            for r in g.epochs[i]:
                if getattr(r, "metrics", None) is not None:
                    met = obsdev.metrics_combine_np(
                        met, jax.device_get(r.metrics))

        verdicts = []
        if slo_plane is not None:
            slo_block, closed = slo_plane.roll(
                slo_block, slo_w0, b, depth=state.depth)
            slo_w0 = b
            verdicts = slo_plane.conformance_rows(closed)

        commit_ns = time.monotonic_ns()
        for t_arr in nxt.get("arrivals", {}).pop(kb, []):
            lats.append(commit_ns - t_arr)
        if server is not None:
            drops_now = int(met[obsdev.MET_INGEST_DROPS])
            server.note_device_drops(drops_now - drops_seen)
            drops_seen = drops_now
            server.publish({"b": b, "boundary": kb,
                            "decisions": int(sum(g.counts)),
                            "verdicts": verdicts})
            if scrape is not None:
                try:
                    from ..obs import rpc as obsrpc

                    obsrpc.publish_rpc(scrape.registry,
                                       server.status())
                    obsrpc.publish_rpc_latency(
                        scrape.registry,
                        obsrpc.latency_summary(lats))
                except Exception:
                    pass

        if ckpt_dir is not None:
            ckpt_mod.save_pytree_rotating(
                ckpt_dir, _ckpt_payload(state, digest, b, decisions,
                                        met), keep=cfg.keep)

    from ..obs import rpc as obsrpc

    events = journal.entries[-1].get("events", {}) \
        if journal.entries else {}
    if server is not None:
        events = dict(server.counters)
    out = {
        "mode": "rpc-serve" if server is not None else "rpc-replay",
        "resumed": resumed,
        "digest": digest.hex(),
        "decisions": int(decisions),
        "boundaries": len(journal),
        "trace_sha": trace_sha(journal.counts_trace()),
        "admitted_ops_traced": int(sum(
            int(np.asarray(ent["counts"]).sum())
            for ent in journal.entries)),
        "carry_ops": int(np.asarray(
            journal.entries[-1].get("carry") or [0]).sum())
        if journal.entries else 0,
        "ingest_drops": int(met[obsdev.MET_INGEST_DROPS]),
        "events": events,
        "latency": obsrpc.latency_summary(lats),
    }
    if scrape is not None:
        scrape.close()
    return out


def main(argv=None) -> int:
    """Subprocess entry for the ci smoke and the SIGKILL tests: runs
    a live serving leg (or a journal resume of one) and writes the
    result record as JSON."""
    ap = argparse.ArgumentParser(prog="dmclock-rpc-serve")
    ap.add_argument("--config", required=True,
                    help="RpcServeConfig as JSON")
    ap.add_argument("--out", required=True,
                    help="result record path (written atomically)")
    ap.add_argument("--port-file", default=None,
                    help="write the bound port here once listening")
    ap.add_argument("--crash-after-fsync", type=int, default=None)
    ap.add_argument("--resume-replay", action="store_true",
                    help="resume WITHOUT a live server: finish from "
                    "the journal alone (post-SIGKILL incarnation)")
    args = ap.parse_args(argv)

    with open(args.config, "r", encoding="utf-8") as f:
        cfg = _cfg_from_json(json.load(f))

    server = None
    if not args.resume_replay:
        server = make_server(cfg).start()
        if args.port_file:
            tmp = args.port_file + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(str(server.port))
            os.replace(tmp, args.port_file)
    try:
        out = run_serve(cfg, server=server,
                        crash_after_fsync=args.crash_after_fsync)
    finally:
        if server is not None:
            server.stop()
    tmp = args.out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(out, f, sort_keys=True)
    os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
