"""Mesh serving plane: shard_map'd full per-device epoch engines.

The paper's distributed story -- many servers each running a complete
mClock queue, coordinated only by piggybacked per-client delta/rho
counters -- as one TPU mesh program.  Each shard owns a full
client-state pytree + rings (the ``parallel.cluster`` stacked layout)
and runs the COMPLETE fused epoch program (the PR-8 stream-chunk body:
on-device admission clamp + superwave ingest + one full epoch of any
of the three engines, telemetry riding the carry) for a whole chunk of
epochs inside ONE mesh launch.  The only cross-shard traffic is the
[C]-sized counter-view psum -- the paper's per-request four-scalar
piggyback contract, batched to epoch boundaries -- refreshed on epochs
where ``epoch % counter_sync_every == 0`` (the staleness knob: the
protocol tolerates stale views by construction, which is what makes
K>1 safe; ``parallel.cluster.run_mesh_rounds`` pins the same knob
decision-exact against the host-loop ``delay_counters`` fault).

Model: each shard is one SERVER owning a DISTINCT ``n``-client
partition of the deployment's population -- ``S * n`` client
contracts live across the mesh, each with its own queue state and
arrival stream (what makes ``obs.capacity.plan_capacity``'s per-shard
HBM inversion the shard-count planner: more clients -> more shards).
The partitions share one contract LAYOUT (slot i carries the same QoS
triple on every shard), so the initial per-shard states are
numerically identical and only the independent arrival streams
diverge them.  Aggregate throughput is the sum of per-shard decision
streams.  The counter plane exchanges the [n]-sized per-slot
delta/rho psum at epoch boundaries: the piggyback protocol's wire
shape and cadence, measured for real; under partitioning the psum'd
view aggregates the S like-contracted clients sharing a slot index
(at S=1 it degenerates to the exact single-server counters, and the
REPLICATED-population model -- where the view IS client i's global
counter feeding its ReqParams -- is the ``parallel.cluster``
``run_mesh_rounds`` program, digest-pinned against the host loop).
Counters count unit-cost completions (the job's superwave is
unit-cost), folded per epoch from the SLO window block's exact
per-client delivered columns -- threaded scatter-free through all
three engines since PR-10 -- so the fold cannot perturb a decision.

Layering (the ``engine.stream`` convention): this module owns the pure
device program + host helpers; ``robust.guarded.run_mesh_chunk_guarded``
adds retry + the guard-trip fallback; ``robust.supervisor`` drives
chunks between checkpoint boundaries as ``EpochJob(engine_loop="mesh",
n_shards=S)``; ``bench.py --mode mesh`` runs the aggregate-throughput
trajectory.  S=1 is bit-identical to the single-engine stream loop BY
CONSTRUCTION: both trace ``engine.stream.make_epoch_step``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine import fastpath
from ..engine import stream as stream_mod
from ..obs import slo as obsslo
from ..utils.compat import shard_map
from .cluster import SERVER_AXIS, make_mesh  # noqa: F401 (re-export)
from .tracker import global_counters_from


class MeshChunk(NamedTuple):
    """One fused mesh chunk's device outputs.

    ``outs`` holds the engine's stacked per-epoch fields with a
    leading ``[S, E]`` (shard, epoch) axis pair; ``cd``/``cr`` are the
    per-shard per-client completion counters (``int64[S, N]``, the
    psum source), ``view_d``/``view_r`` the held counter views after
    the chunk.  ``slo_merged`` is the cluster-wide window block merged
    IN-GRAPH across the mesh via ``obs.slo.window_mesh_reduce``
    (replicated; ``int64[N, W_FIELDS]``) -- the one conformance table
    the SLO plane rolls.  ``flight`` is the stacked per-shard HBM
    flight-ring state (``with_flight`` chunks; each shard records its
    own commits, the host merges rings in shard order at drain)."""

    state: object             # stacked EngineState, [S, ...] leaves
    outs: dict                # [S, E, ...] stacked engine fields
    cd: jnp.ndarray           # int64[S, N] completions (delta source)
    cr: jnp.ndarray           # int64[S, N] resv-phase completions
    view_d: jnp.ndarray       # int64[S, N] held global-delta views
    view_r: jnp.ndarray       # int64[S, N]
    hists: object = None      # stacked telemetry accumulators
    ledger: object = None
    slo: object = None        # int64[S, N, W_FIELDS] per-shard blocks
    prov: object = None
    slo_merged: object = None  # int64[N, W_FIELDS] (window_mesh_reduce)
    flight: object = None     # stacked obs.flight.FlightState [S, ...]


def stack_shards(tree, n_shards: int, mesh: Optional[Mesh] = None):
    """Broadcast a single-engine pytree to the stacked ``[S, ...]``
    per-shard layout: every shard's DISTINCT client partition starts
    from the identical contract layout/state (independent arrival
    streams supply the divergence), optionally placing each leaf
    split over the ``servers`` mesh axis."""
    stacked = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (n_shards,) + jnp.shape(a)),
        tree)
    if mesh is not None:
        sharding = NamedSharding(mesh, P(SERVER_AXIS))
        stacked = jax.tree.map(
            lambda a: jax.device_put(a, sharding), stacked)
    return stacked


def unstack_shard(tree, s: int = 0):
    """Slice shard ``s`` back out of a stacked pytree (the S=1
    canonicalization: a 1-shard mesh IS a single engine, and the
    identity gate compares it against the round/stream loops)."""
    return jax.tree.map(lambda a: a[s], tree)


def counter_init(n_shards: int, n: int):
    """Fresh counter plane: zero per-shard completions, views at the
    protocol's counters-start-at-1 origin (``dmclock_client.h``)."""
    z = jnp.zeros((n_shards, n), dtype=jnp.int64)
    one = jnp.ones((n_shards, n), dtype=jnp.int64)
    return z, z, one, one


def mask_epoch_outs(outs: dict, up, fault_vec):
    """Mask one DOWN epoch's engine outputs to their committed-nothing
    neutrals (the ``robust.cluster`` decision-slots-read-NONE
    semantics, field-typed for the stream-chunk layout): guard vectors
    read True (nothing ran, nothing tripped), slots read -1, every
    count/cost/class reads 0.  ``metrics`` is zeroed and replaced by
    the epoch's fault-event delta (``fault_vec``; also added on LIVE
    epochs, where the engine metrics are kept).  The host chaos
    replay (``robust.guarded``) builds byte-identical rows from the
    same table -- one implementation would need shapes the host does
    not have, so the NAME table here is the shared contract."""
    masked = {}
    for name, arr in outs.items():
        if name == "metrics":
            masked[name] = jnp.where(up, arr, 0) + fault_vec
        elif name in ("guards_ok", "progress_ok"):
            masked[name] = jnp.where(up, arr, jnp.ones_like(arr))
        elif name == "slot":
            masked[name] = jnp.where(up, arr, jnp.full_like(arr, -1))
        else:
            masked[name] = jnp.where(up, arr, jnp.zeros_like(arr))
    return masked


def build_mesh_chunk(mesh: Mesh, *, engine: str, epochs: int, m: int,
                     k: int = 0, chain_depth: int = 4,
                     dt_epoch_ns: int, waves: int,
                     anticipation_ns: int = 0,
                     allow_limit_break: bool = False,
                     with_metrics: bool = True,
                     select_impl: str = "sort", tag_width: int = 64,
                     window_m: Optional[int] = None,
                     calendar_impl: str = "minstop",
                     ladder_levels: int = 8,
                     wheel_kernel: str = "xla",
                     counter_sync_every: int = 1,
                     collective_skipping: Optional[bool] = None,
                     ingest: bool = True,
                     with_faults: bool = False,
                     with_flight: bool = False,
                     with_pressure: bool = False):
    """Build the pure mesh chunk program ``(state, cd, cr, view_d,
    view_r, epoch0, counts, hists, ledger, slo, prov, flight, faults)
    -> MeshChunk`` for one static configuration.

    ``counts`` is ``int32[S, E, N]`` of RAW per-shard Poisson draws
    (shard axis leading so ``P(servers)`` splits it); ``epoch0`` is a
    TRACED int64 scalar, and the counter-sync mask is computed
    IN-GRAPH from the global epoch index ``(epoch0 + i) %
    counter_sync_every == 0``, so one compiled program serves every
    chunk position and the sync grid is global, not per-chunk.  ``slo``
    must always be a window block (``int64[S, N, W_FIELDS]``): the
    counter plane diffs its delivered columns per epoch -- when the
    job runs with the SLO plane off the caller passes a throwaway
    zero block.

    ``with_faults`` compiles the PR-3 fault model INTO the chunk:
    ``faults`` is a ``robust.faults.FaultChunk``-shaped 5-tuple of
    traced per-shard arrays (``up``/``skew_ns``/``delay_counters``/
    ``dup_completions`` [S, E] + ``up_prev`` [S]) precomputed on the
    host from the plan oracle.  Per epoch, per shard:

    - a DOWN shard commits nothing -- engine state, telemetry
      accumulators, and the SLO window block all keep their entry
      values, its decision outputs read the neutral masks
      (:func:`mask_epoch_outs`), and its frozen ``cd``/``cr``
      contribution keeps the counter psum MONOTONE (exactly the
      ``robust.cluster`` degraded-path semantics);
    - a live shard's view refreshes from the psum only on the global
      sync grid AND when its piggyback updates are not delayed; a
      RESTART (down -> up transition) always re-syncs -- the in-graph
      twin of ``resync_tracker``'s re-marking;
    - ``dup_completions`` folds the epoch's completion delta into the
      counters TWICE (the at-least-once response-network failure);
    - ``skew_ns`` lenses the shard's epoch clock (ingest + serve see
      ``t + skew``; the index-derived clock makes it per-epoch, not
      cumulative);
    - every injected event lands in the epoch's metrics vector rows
      (``server_dropouts``/``tracker_resyncs``/``faults_injected``),
      summing to the ``plan_events`` oracle exactly.

    An all-benign fault tuple (``zero_plan`` sliced) is value-
    identical to ``with_faults=False`` -- the zero-fault gate in
    ``scripts/ci.sh``.

    ``collective_skipping`` (STATIC) restructures the epoch scan into
    ``epochs // counter_sync_every``-sized SYNC GROUPS: the delta/rho
    psum executes ONCE at each group head and the non-sync epochs run
    COLLECTIVE-FREE -- zero all-reduces in the compiled HLO (the
    tests/test_mesh.py cost-analysis gate), where the flat scan
    executed the psum every epoch and K only gated the view refresh.
    Bit-identical to the flat scan when ``epoch0`` lands on the sync
    grid (``epoch0 % counter_sync_every == 0``): the group head IS
    the one on-grid epoch of its group, and its psum reads the same
    entry counters the flat program read there.  Off-grid chunks keep
    the flat program (the guarded runner picks per chunk).  Default
    ``None`` auto-enables for fault-free chunks with ``epochs``
    divisible by K > 1; faulty chunks always run flat -- a mid-group
    restart must re-sync from a FRESH psum, which is exactly the
    collective the skipping removes.

    ``with_pressure`` threads the mid-epoch pressure probe
    (``engine.stream.make_epoch_step``) through the chunk:
    ``outs["pressure"]`` stacks to ``int64[S, E, PRESS_FIELDS]``, a
    down epoch's row masks to zeros (a nonneg no-op under the peak
    max), and the probe is shard-local -- no collective, so the
    collective-skipping cost gates are unaffected."""
    from ..obs import device as obsdev

    assert engine in fastpath.EPOCH_ENGINES, engine
    epochs = int(epochs)
    assert epochs >= 1, "a mesh chunk needs at least one epoch"
    kw = fastpath.epoch_scan_kwargs(
        engine, k=k, chain_depth=chain_depth, select_impl=select_impl,
        tag_width=tag_width, window_m=window_m,
        calendar_impl=calendar_impl, ladder_levels=ladder_levels,
        wheel_kernel=wheel_kernel,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break,
        with_metrics=with_metrics)
    dt = int(dt_epoch_ns)
    every = max(int(counter_sync_every), 1)
    if collective_skipping is None:
        collective_skipping = (not with_faults and every > 1
                               and epochs % every == 0)
    if collective_skipping:
        assert not with_faults, \
            "collective skipping needs the fault-free chunk (a " \
            "mid-group restart must re-sync from a fresh psum)"
        assert epochs % every == 0, \
            f"collective skipping needs epochs ({epochs}) divisible " \
            f"by counter_sync_every ({every})"
    epoch_step = stream_mod.make_epoch_step(
        engine=engine, m=m, kw=kw, dt_epoch_ns=dt, waves=waves,
        ingest=ingest, with_pressure=with_pressure)

    def per_server(st, cd, cr, vd, vr, epoch0, counts_s, h, l, s, p,
                   f, flt):
        def body(carry, xs, counters=None):
            st, cd, cr, vd, vr, h, l, s, p, f, up_prev = carry
            if with_faults:
                counts_e, i, up, skew, delay, dup = xs
            else:
                counts_e, i = xs
                up = up_prev        # the all-up constant
                skew = jnp.int64(0)
            # batched delta/rho exchange at the epoch boundary: the
            # views refresh from the mesh psum only on the global
            # sync grid; between syncs every shard serves from its
            # held (stale) view -- the paper's tolerance, as data.
            # The collective runs on EVERY shard (SPMD); a down
            # shard's counters are frozen, so the psum stays monotone.
            # Under collective skipping the GROUP-HEAD psum arrives in
            # ``counters`` instead -- on an aligned chunk the head is
            # the only epoch that reads it, and it read the same
            # values here
            if counters is None:
                g_d, g_r = global_counters_from(
                    cd, cr, lambda x: lax.psum(x, SERVER_AXIS))
            else:
                g_d, g_r = counters
            sync = ((epoch0 + i) % every) == 0
            if with_faults:
                restart = up & ~up_prev
                dropout = ~up & up_prev
                # live non-delayed shards refresh on the grid; a
                # restart always re-syncs (resync_tracker's twin); a
                # down shard holds its frozen view
                refresh = (sync & up & ~delay) | restart
            else:
                refresh = sync
            vd = jnp.where(refresh, g_d, vd)
            vr = jnp.where(refresh, g_r, vr)
            t_base = (epoch0 + i) * dt + skew
            (st2, h2, l2, f2, s2, p2), outs = epoch_step(
                st, t_base, counts_e, h, l, f, s, p)
            if with_faults:
                # commit gate: a down shard keeps last-good state --
                # engine, telemetry, flight ring, SLO block alike --
                # and its outputs read the neutral masks
                def keep(new, old):
                    return None if new is None else jax.tree.map(
                        lambda a, b: jnp.where(up, a, b), new, old)

                st2, h2, l2, f2, p2 = (keep(st2, st), keep(h2, h),
                                       keep(l2, l), keep(f2, f),
                                       keep(p2, p))
                s2 = jnp.where(up, s2, s)
                perturb = ((dup & up).astype(jnp.int64)
                           + (delay & up).astype(jnp.int64)
                           + ((skew != 0) & up).astype(jnp.int64))
                events = (dropout.astype(jnp.int64)
                          + restart.astype(jnp.int64))
                outs = mask_epoch_outs(outs, up, obsdev.metrics_delta(
                    server_dropouts=dropout.astype(jnp.int64),
                    tracker_resyncs=restart.astype(jnp.int64),
                    faults_injected=events + perturb))
            # completions -> counters: the window block's delivered
            # columns are exact per-client counts (PR-10), so the
            # per-epoch diff IS this epoch's completion fold -- no
            # scatter, no second accumulator, no decision perturbed
            d_ops = s2[:, obsslo.W_OPS] - s[:, obsslo.W_OPS]
            d_resv = (s2[:, obsslo.W_RESV_OPS]
                      - s[:, obsslo.W_RESV_OPS])
            if with_faults:
                # duplicated completions: this epoch's batch folds
                # into the counters twice (masked; +0 is exact)
                mult = 1 + (dup & up).astype(jnp.int64)
                d_ops = d_ops * mult
                d_resv = d_resv * mult
            cd = cd + d_ops
            cr = cr + d_resv
            return (st2, cd, cr, vd, vr, h2, l2, s2, p2, f2,
                    up if with_faults else up_prev), outs

        idx = jnp.arange(epochs, dtype=jnp.int64)
        if not ingest:
            counts_s = jnp.zeros((epochs, 0), dtype=jnp.int32)
        if with_faults:
            up_s, skew_s, delay_s, dup_s, up0 = flt
            xs = (counts_s, idx, up_s, skew_s, delay_s, dup_s)
        else:
            up0 = jnp.asarray(True)
            xs = (counts_s, idx)
        carry0 = (st, cd, cr, vd, vr, h, l, s, p, f, up0)
        if collective_skipping:
            # sync groups: ONE psum per group of ``every`` epochs,
            # computed at the group head from the carried counters,
            # and the inner scan runs collective-free.  On an aligned
            # chunk the head is the group's only on-grid epoch, so
            # the refresh mask inside ``body`` consumes exactly the
            # values the flat program's per-epoch psum produced there
            # (off-grid epochs never read ``g_d``/``g_r`` at all)
            groups = epochs // every
            gxs = jax.tree.map(
                lambda a: a.reshape((groups, every) + a.shape[1:]),
                xs)

            def group(carry, xs_g):
                counters = global_counters_from(
                    carry[1], carry[2],
                    lambda x: lax.psum(x, SERVER_AXIS))
                return lax.scan(
                    lambda c, x: body(c, x, counters=counters),
                    carry, xs_g)

            carry, outs = lax.scan(group, carry0, gxs)
            outs = jax.tree.map(
                lambda a: a.reshape((epochs,) + a.shape[2:]), outs)
        else:
            carry, outs = lax.scan(body, carry0, xs)
        st, cd, cr, vd, vr, h, l, s, p, f = carry[:10]
        return st, cd, cr, vd, vr, h, l, f, s, p, outs

    def shard_fn(state, cd, cr, vd, vr, epoch0, counts,
                 hists, ledger, slo, prov, flight, faults):
        out = jax.vmap(
            per_server,
            in_axes=(0, 0, 0, 0, 0, None, 0, 0, 0, 0, 0, 0, 0),
        )(state, cd, cr, vd, vr, epoch0, counts, hists, ledger, slo,
          prov, flight, faults)
        # cluster-wide conformance: local combine over this shard's
        # vmapped servers, then ONE collective across the mesh --
        # counter columns psum, the contract-epoch column pmax
        # (obs.slo.window_mesh_reduce); replicated out-spec
        merged = obsslo.window_mesh_reduce(
            obsslo.window_combine_axis(out[8]), SERVER_AXIS)
        return out + (merged,)

    spec = P(SERVER_AXIS)
    in_specs = (spec,) * 5 + (P(),) + (spec,) * 7
    out_specs = (spec,) * 11 + (P(),)

    def chunk(state, cd, cr, vd, vr, epoch0, counts, hists=None,
              ledger=None, slo=None, prov=None, flight=None,
              faults=None) -> MeshChunk:
        epoch0 = jnp.asarray(epoch0, dtype=jnp.int64)
        if with_faults:
            assert faults is not None, \
                "with_faults=True needs the FaultChunk arrays"
            faults = (jnp.asarray(faults[0], dtype=bool),
                      jnp.asarray(faults[1], dtype=jnp.int64),
                      jnp.asarray(faults[2], dtype=bool),
                      jnp.asarray(faults[3], dtype=bool),
                      jnp.asarray(faults[4], dtype=bool))
        else:
            faults = None
        fn = shard_map(shard_fn, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
        (state, cd, cr, vd, vr, hists, ledger, flight, slo, prov,
         outs, merged) = fn(state, cd, cr, vd, vr, epoch0, counts,
                            hists, ledger, slo, prov, flight, faults)
        return MeshChunk(state=state, outs=outs, cd=cd, cr=cr,
                         view_d=vd, view_r=vr, hists=hists,
                         ledger=ledger, slo=slo, prov=prov,
                         slo_merged=merged, flight=flight)

    return chunk


# module-level jit cache keyed by the full static configuration + the
# mesh SHAPE (the mesh_step_jit convention: the object id is
# meaningless across runs, but distinct meshes at one cfg are distinct
# programs and colliding them would record phantom retraces)
_MESH_CHUNK_JIT_CACHE: dict = {}


def jit_mesh_chunk(mesh: Mesh, **cfg):
    from ..obs import compile_plane as _cplane

    from .cluster import mesh_cache_key

    mesh_shape = tuple(np.shape(getattr(mesh, "devices", ())))
    key = (mesh_shape,) + tuple(sorted(cfg.items()))
    full_key = mesh_cache_key(mesh, key)
    if full_key not in _MESH_CHUNK_JIT_CACHE:
        fn = build_mesh_chunk(mesh, **cfg)
        _MESH_CHUNK_JIT_CACHE[full_key] = _cplane.instrumented_jit(
            fn, cache="mesh.chunk", entry=key)
    return _MESH_CHUNK_JIT_CACHE[full_key]


def shard_epoch_view(engine: str, outs: dict, s: int, i: int):
    """Reconstruct shard ``s``'s epoch ``i`` result object from the
    fetched ``[S, E, ...]`` stacked outputs -- the stream loop's
    ``epoch_view`` over one shard's slice, so the supervisor's chain
    digest sees byte-identical arrays at S=1."""
    return stream_mod.epoch_view(
        engine, {name: arr[s] for name, arr in outs.items()}, i)


def mesh_epoch_results(engine: str, outs: dict, i: int) -> tuple:
    """Epoch ``i``'s digest-ready result rows: one PER-SHARD tuple of
    result views in shard order (flatten for the chain digest -- the
    flat order is unchanged from before the grouping; the per-shard
    structure is what lets a churn job canonicalize each shard's
    results through that shard's own slot map).  At S=1 the flattened
    row is exactly the stream loop's tuple."""
    n_shards = next(iter(outs.values())).shape[0]
    return tuple((shard_epoch_view(engine, outs, s, i),)
                 for s in range(n_shards))


def mesh_epoch_decisions(engine: str, outs: dict, i: int) -> int:
    """Decisions epoch ``i`` committed across ALL shards (the
    aggregate-throughput numerator)."""
    return int(np.asarray(outs["count"][:, i]).sum())
