"""Mesh-sharded multi-server dmClock cluster.

The TPU-native replacement for the reference's multi-server simulation
(N ``SimulatedServer`` thread pools + callback "network",
``sim/src/test_dmclock_main.cc:146-188``): every server's scheduler
state is one shard of a stacked ``EngineState`` on the ``servers`` mesh
axis, the per-(server, client) completion counters live next to it, and
one ``cluster_step`` advances EVERY server by k scheduling decisions in
a single program -- with the dmClock wire protocol's global counters
computed as a ``psum`` over ICI (DCN across hosts, transparently, via
the same collective).

Layout notes (scaling-book recipe): pick the mesh, annotate shardings,
let XLA insert the collectives.  All arrays are sharded on the leading
``servers`` axis; the only cross-shard traffic is the [C]-sized psum of
completion counters -- exactly the four-scalar-per-request piggyback
contract, batched.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..engine import kernels
from ..obs import device as obsdev
from ..utils.compat import shard_map
from ..engine.state import EngineState, init_state
from .tracker import (BorrowTrackerState, TrackerState,
                      borrow_tracker_prepare, borrow_tracker_track,
                      global_counters, init_borrow_tracker,
                      init_tracker, tracker_prepare, tracker_track)

SERVER_AXIS = "servers"


class ClusterState(NamedTuple):
    """Stacked per-server state; every leaf's leading axis is servers."""

    engine: EngineState       # [S, ...] scheduler state per server
    tracker: TrackerState     # [S, C] distributed-protocol counters
    #                           (TrackerState or BorrowTrackerState --
    #                           the accounting policy plug, reference
    #                           dmclock_client.h:39-154)
    now: jnp.ndarray          # int64[S] per-server virtual clock


def make_mesh(n_devices: int | None = None) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (SERVER_AXIS,))


def init_cluster(n_servers: int, n_clients: int,
                 ring_capacity: int = 64,
                 tracker_kind: str = "orig") -> ClusterState:
    """Host-side construction: capacity ``n_clients`` slots per server
    (slot i == client i cluster-wide, which is what lets completion
    counters psum by position).  ``tracker_kind``: "orig" or
    "borrowing" (the reference's two accounting policies)."""
    one = init_state(n_clients, ring_capacity)
    engine = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (n_servers,) + a.shape), one)
    base = {"orig": init_tracker,
            "borrowing": init_borrow_tracker}[tracker_kind](n_clients)
    tracker = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (n_servers,) + a.shape), base)
    return ClusterState(engine=engine, tracker=tracker,
                        now=jnp.zeros((n_servers,), dtype=jnp.int64))


def shard_cluster(cluster: ClusterState, mesh: Mesh) -> ClusterState:
    """Place every leaf with its leading axis split over the servers
    mesh axis."""
    sharding = NamedSharding(mesh, P(SERVER_AXIS))
    return jax.tree.map(lambda a: jax.device_put(a, sharding), cluster)


def install_clients(cluster: ClusterState, resv_inv, weight_inv,
                    limit_inv, active_mask=None) -> ClusterState:
    """Register the same client population on every server (QoS inverses
    are [C] int64 arrays).  Creation order = client index, making the
    cross-backend tie-break deterministic.  ``active_mask`` bool[C]
    restricts the initial population (slots left inactive join later
    via ``create_clients``); default: all C slots."""
    n_servers = cluster.now.shape[0]
    c = resv_inv.shape[0]
    if active_mask is None:
        active_mask = jnp.ones((c,), dtype=bool)

    def bcast(a):
        return jnp.broadcast_to(a, (n_servers, c))

    eng = cluster.engine._replace(
        active=bcast(active_mask),
        order=bcast(jnp.arange(c, dtype=jnp.int64)),
        resv_inv=bcast(resv_inv), weight_inv=bcast(weight_inv),
        limit_inv=bcast(limit_inv),
    )
    return cluster._replace(engine=eng)


def server_round(engine: EngineState, tracker: TrackerState,
                 now: jnp.ndarray, arrivals_per_client: jnp.ndarray,
                 cost: jnp.ndarray, g_delta: jnp.ndarray,
                 g_rho: jnp.ndarray, decisions_per_step: int,
                 anticipation_ns: int, allow_limit_break: bool,
                 max_arrivals: int, with_metrics: bool = False):
    """One server's round against a CALLER-SUPPLIED view of the global
    counters (``g_delta``/``g_rho``, [C] int64).  The healthy cluster
    passes the fresh psum (``_one_server_step``); the fault-injection
    layer (``robust.cluster``) passes a possibly stale held view -- the
    dmClock protocol tolerates stale counters by construction, which is
    exactly what makes delayed/lost piggyback updates injectable here
    without touching the tag algebra.

    Phase A: client c sends ``min(arrivals_per_client[c],
    max_arrivals)`` requests, each carrying view-derived ReqParams;
    arrivals interleave wave-major (every client's j-th request before
    any client's j+1-th, clients in slot order within a wave) -- the
    order the host-sim parity test replicates.
    Phase B: the engine makes ``decisions_per_step`` decisions.
    Phase C: completions fold into the tracker counters.
    """
    # the tracker STATE type picks the accounting policy
    borrowing = isinstance(tracker, BorrowTrackerState)
    prepare = borrow_tracker_prepare if borrowing else tracker_prepare

    c = arrivals_per_client.shape[0]
    slots = jnp.arange(c, dtype=jnp.int32)
    cost_c = jnp.broadcast_to(cost, (c,))   # per-client costs ([C] or
    #                                         scalar; heterogeneous
    #                                         multi-tenant rounds)
    for wave in range(max_arrivals):
        requesting = arrivals_per_client > wave
        # waves after a client's first request this round re-mark an
        # unchanged global counter, so their params are (0, 0) for
        # Orig / floor at (1, 1) for Borrowing -- the same streams the
        # host trackers emit for back-to-back requests with no
        # interleaved completions
        tracker, delta_out, rho_out = prepare(
            tracker, requesting, g_delta, g_rho)
        ops = kernels.IngestOps(
            kind=jnp.where(requesting, kernels.OP_ADD,
                           kernels.OP_NOP).astype(jnp.int32),
            slot=slots,
            time=jnp.broadcast_to(now, (c,)),
            cost=cost_c,
            rho=jnp.where(requesting, rho_out, 1),
            delta=jnp.where(requesting, delta_out, 1),
            resv_inv=jnp.zeros((c,), dtype=jnp.int64),
            weight_inv=jnp.zeros((c,), dtype=jnp.int64),
            limit_inv=jnp.zeros((c,), dtype=jnp.int64),
            order=jnp.zeros((c,), dtype=jnp.int64),
        )
        engine = kernels.ingest(engine, ops,
                                anticipation_ns=anticipation_ns)

    # --- scheduling decisions.  ``with_metrics`` (STATIC) rides the
    # obs vector in the same scan carry -- decisions bit-identical
    # either way (tests/test_obs.py) -- so the healthy path can merge
    # cluster totals in-graph (metrics_mesh_reduce) with no host-side
    # gather.
    if with_metrics:
        engine, now, decs, met = kernels.engine_run(
            engine, now, decisions_per_step,
            allow_limit_break=allow_limit_break,
            anticipation_ns=anticipation_ns, advance_now=True,
            with_metrics=True)
    else:
        engine, now, decs = kernels.engine_run(
            engine, now, decisions_per_step,
            allow_limit_break=allow_limit_break,
            anticipation_ns=anticipation_ns, advance_now=True)

    # --- completions -> counters (the response half of the protocol;
    # both policies fold completions identically)
    served = decs.type == kernels.RETURNING
    track = borrow_tracker_track if borrowing else tracker_track
    tracker = track(tracker, decs.slot, decs.cost, decs.phase, served)
    if with_metrics:
        return engine, tracker, now, decs, met
    return engine, tracker, now, decs


def _one_server_step(engine: EngineState, tracker: TrackerState,
                     now: jnp.ndarray, arrivals_per_client: jnp.ndarray,
                     cost: jnp.ndarray, decisions_per_step: int,
                     anticipation_ns: int, allow_limit_break: bool,
                     max_arrivals: int, with_metrics: bool = False):
    """One server's slice of a healthy cluster step (runs inside
    shard_map with a [1, ...]-shaped shard; vmapped over that unit
    axis): the distributed ReqParams come from the FRESH psum'd global
    counters, then the round runs via :func:`server_round`."""
    g_delta, g_rho = global_counters(
        tracker, lambda x: lax.psum(x, SERVER_AXIS))
    return server_round(
        engine, tracker, now, arrivals_per_client, cost, g_delta,
        g_rho, decisions_per_step=decisions_per_step,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break, max_arrivals=max_arrivals,
        with_metrics=with_metrics)


def cluster_step(cluster: ClusterState, arrivals: jnp.ndarray,
                 cost, mesh: Mesh, *,
                 decisions_per_step: int,
                 max_arrivals: int = 1,
                 anticipation_ns: int = 0,
                 allow_limit_break: bool = False,
                 advance_ns: int = 0,
                 with_metrics: bool = False,
                 with_pressure: bool = False):
    """Advance the whole cluster: ``arrivals`` is int32[S, C] request
    counts (honored up to the static ``max_arrivals`` per client per
    round, wave-major order -- see _one_server_step), sharded over
    servers.  ``cost`` is a scalar or an int64[C] per-client cost
    vector (heterogeneous multi-tenant rounds; reference requests carry
    per-request Cost, sim_recs.h:84).  Returns (cluster, decisions)
    with decisions' leaves [S, k]-shaped.

    Jit this (it is pure); under jit XLA turns the psum into one ICI
    all-reduce per step.

    ``advance_ns`` moves every server's virtual clock forward at round
    start (the real time elapsing between arrival waves; without it a
    weight-dominated cluster never advances past its reservation tags
    and the constraint phase never engages).

    ``with_metrics`` (STATIC) additionally returns ``(per_shard
    int64[S, NUM_METRICS], merged int64[NUM_METRICS])``: each server's
    obs vector from the same scan carry as its decisions, and the
    cluster total merged IN-GRAPH across the mesh (counter rows psum,
    hwm rows pmax -- ``obs.device.metrics_mesh_reduce``), so cluster
    totals need no host-side gather.  Decisions are bit-identical with
    the flag on or off (tests/test_obs.py pins the engine; the merged
    == host-summed pin lives in tests/test_cluster_realism.py).

    ``with_pressure`` (STATIC) additionally returns ``(per_shard
    int64[S, PRESS_FIELDS], merged int64[PRESS_FIELDS])``: each
    server's post-round scheduling-pressure vector (live eligible-set
    depth, backlog, peak, head-wait watermark --
    ``obs.provenance.pressure_vec``) and the cluster total through the
    same psum/pmax collective (``pressure_mesh_reduce``) -- the
    placement signal the ROADMAP rack-scheduling item routes on,
    published as ``dmclock_shard_pressure_*``
    (``obs.provenance.publish_shard_pressure``)."""
    from ..obs import provenance as obsprov

    cost = jnp.asarray(cost, dtype=jnp.int64)

    def shard_fn(engine, tracker, now, arr):
        step = functools.partial(
            _one_server_step,
            decisions_per_step=decisions_per_step,
            anticipation_ns=anticipation_ns,
            allow_limit_break=allow_limit_break,
            max_arrivals=max_arrivals, with_metrics=with_metrics)
        # shards carry a leading [1] server axis; vmap it away
        out = jax.vmap(
            lambda e, t, n, a: step(e, t, n, a, cost=cost),
        )(engine, tracker, now, arr)
        if with_metrics:
            engine, tracker, now, decs, met = out
            # local servers reduce with the vector's own merge
            # semantics, then one collective crosses the mesh; the
            # merged vector is replicated (P() out-spec)
            merged = obsdev.metrics_mesh_reduce(
                obsdev.metrics_combine_axis(met), SERVER_AXIS)
            out = (engine, tracker, now, decs, met, merged)
        if with_pressure:
            engine, tracker, now = out[0], out[1], out[2]
            press = jax.vmap(obsprov.pressure_vec)(engine, now)
            press_merged = obsprov.pressure_mesh_reduce(
                obsprov.pressure_combine_axis(press), SERVER_AXIS)
            out = out + (press, press_merged)
        return out

    spec = P(SERVER_AXIS)
    out_specs = (spec,) * 4
    if with_metrics:
        out_specs += (spec, P())
    if with_pressure:
        out_specs += (spec, P())
    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=out_specs,
        check_vma=False)
    now0 = cluster.now + jnp.int64(advance_ns)
    out = fn(cluster.engine, cluster.tracker, now0, arrivals)
    engine, tracker, now, decs = out[:4]
    return (ClusterState(engine=engine, tracker=tracker, now=now),
            decs) + tuple(out[4:])


# Module-level jit cache for the healthy-path round driver (the
# engine/queue.py _JIT_CACHE convention): one compiled cluster_step
# program per (mesh, static-config) pair.
_ROUNDS_JIT_CACHE: dict = {}


def mesh_cache_key(mesh: Mesh, cfg: tuple) -> tuple:
    """THE cache key for every mesh-program module-jit cache
    (``mesh_step_jit``, :func:`jit_mesh_rounds`,
    ``parallel.mesh.jit_mesh_chunk``): (mesh, cfg) with the
    unhashable-mesh ``id()`` fallback some jax versions need.  One
    implementation so a jax-version fix lands in one place."""
    try:
        key = (mesh,) + cfg
        hash(key)
        return key
    except TypeError:            # unhashable mesh on some jax versions
        return (id(mesh),) + cfg


def mesh_step_jit(cache: dict, step_fn, mesh: Mesh, cfg: tuple):
    """Shared module-jit-cache helper for mesh step drivers (this
    module's healthy rounds and ``robust.cluster``'s faulty steps):
    one compiled ``jax.jit(partial(step_fn, mesh=mesh, <cfg>))`` per
    (mesh, static-config) pair.  ``cfg`` is the five-tuple
    (decisions_per_step, max_arrivals, anticipation_ns,
    allow_limit_break, advance_ns)."""
    from ..obs import compile_plane as _cplane

    key = mesh_cache_key(mesh, cfg)
    if key not in cache:
        (decisions_per_step, max_arrivals, anticipation_ns,
         allow_limit_break, advance_ns) = cfg
        # compile-plane-instrumented (obs.compile_plane): the mesh
        # step is the program the multichip item compiles per (mesh,
        # config) pair; entry is keyed WITHOUT the mesh repr (the
        # object id is meaningless across runs), but WITH the mesh
        # shape -- distinct meshes at one cfg are distinct programs,
        # and colliding them would record phantom retraces
        mesh_shape = tuple(np.shape(getattr(mesh, "devices", ())))
        cache[key] = _cplane.instrumented_jit(
            functools.partial(
                step_fn, mesh=mesh,
                decisions_per_step=decisions_per_step,
                max_arrivals=max_arrivals,
                anticipation_ns=anticipation_ns,
                allow_limit_break=allow_limit_break,
                advance_ns=advance_ns),
            cache=f"cluster.{getattr(step_fn, '__name__', 'step')}",
            entry=cfg + (mesh_shape,))
    return cache[key]


def run_cluster_rounds(cluster: ClusterState, arrivals_seq, cost,
                       mesh: Mesh, *, decisions_per_step: int,
                       max_arrivals: int = 1, anticipation_ns: int = 0,
                       allow_limit_break: bool = False,
                       advance_ns: int = 0, tracer=None):
    """Drive ``arrivals_seq.shape[0]`` healthy cluster steps from the
    host -- the happy-path twin of ``robust.cluster.run_with_plan``,
    so the tracing plane prices the mesh round-trip structure the same
    way on both paths.  ``tracer`` (``obs.spans.SpanTracer`` or None)
    records one ``cluster.round`` dispatch span per step (the whole
    shard_map launch) and a ``cluster.fetch`` span per decision
    readback; decisions are bit-identical with or without it.
    Returns ``(cluster, decs_seq)`` with per-step decisions fetched to
    host numpy."""
    from ..obs import spans as _spans

    step = mesh_step_jit(_ROUNDS_JIT_CACHE, cluster_step, mesh,
                         (decisions_per_step, max_arrivals,
                          anticipation_ns, allow_limit_break,
                          advance_ns))
    arrivals_seq = np.asarray(arrivals_seq)
    n_servers = cluster.now.shape[0]
    decs_seq = []
    for t in range(arrivals_seq.shape[0]):
        with _spans.span(tracer, "cluster.round", "dispatch",
                         step=t, servers=n_servers):
            cluster, decs = step(cluster,
                                 jnp.asarray(arrivals_seq[t]), cost)
        with _spans.span(tracer, "cluster.fetch", "fetch", step=t):
            decs_seq.append(jax.device_get(decs))
    return cluster, decs_seq


# ----------------------------------------------------------------------
# mesh serving plane: fused multi-round programs with batched
# delta/rho exchange (docs/ENGINE.md "Mesh serving")
# ----------------------------------------------------------------------

class MeshRounds(NamedTuple):
    """One fused mesh launch's outputs (``run_mesh_rounds``).

    ``decs`` leaves are ``[S, E, k]`` (server, round, decision slot);
    slice round ``t`` with :func:`mesh_decs_seq` to recover the
    per-step ``[S, k]`` stream the host-loop drivers emit.  ``metrics``
    is the per-shard ``int64[S, NUM_METRICS]`` vector accumulated
    across all E rounds with the robust path's delta accounting, so a
    zero-fault host loop and a mesh launch produce the same totals."""

    cluster: ClusterState
    view_delta: jnp.ndarray   # int64[S, C] held counter views
    view_rho: jnp.ndarray     # int64[S, C]
    metrics: jnp.ndarray      # int64[S, NUM_METRICS]
    decs: object              # kernels.Decision, [S, E, k] leaves
    merged: object = None     # int64[NUM_METRICS] (with_merged)
    pressure: object = None   # int64[S, PRESS_FIELDS] (with_pressure)
    pressure_merged: object = None


def round_sync_mask(epochs: int, counter_sync_every: int,
                    round0: int = 0) -> np.ndarray:
    """The GLOBAL counter-sync grid as a host bool mask over one
    launch's rounds: round ``round0 + t`` syncs iff it lies on the
    ``counter_sync_every`` grid.  One implementation shared by the
    healthy fused rounds (:func:`run_mesh_rounds`) and the chaos
    fused rounds (``robust.cluster.run_mesh_rounds_with_plan``), so
    the two programs cannot disagree about where a chunked launch
    sits on the grid."""
    every = max(int(counter_sync_every), 1)
    return (int(round0) + np.arange(int(epochs))) % every == 0


def init_mesh_views(n_servers: int, n_clients: int):
    """Held counter views at the protocol origin (counters start at 1,
    ``dmclock_client.h:191-198``) -- the same origin ``robust.cluster.
    init_robust`` gives its view arrays, so a mesh launch and the
    host-loop degraded path start from identical state."""
    return (jnp.ones((n_servers, n_clients), dtype=jnp.int64),
            jnp.ones((n_servers, n_clients), dtype=jnp.int64))


def _mesh_round_body(engine, tracker, now, arr, vd, vr, met, sync, *,
                     cost, decisions_per_step, anticipation_ns,
                     allow_limit_break, max_arrivals, advance_ns):
    """One fused round (inside the per-server scan): refresh the held
    counter view from the mesh psum on sync rounds only (the
    ``counter_sync_every`` staleness knob -- the paper's piggybacked
    views are naturally stale, and ``server_round`` takes the view as
    an argument precisely so a stale one is protocol-safe), then run
    the round and fold the completion metrics with the degraded path's
    delta accounting (``robust.cluster._one_server_step_faulty``'s
    zero-fault arm), so mesh and host-loop totals are comparable."""
    g_d, g_r = global_counters(
        tracker, lambda x: lax.psum(x, SERVER_AXIS))
    vd = jnp.where(sync, g_d, vd)
    vr = jnp.where(sync, g_r, vr)
    engine, tracker, now, decs = server_round(
        engine, tracker, now + advance_ns, arr, cost, vd, vr,
        decisions_per_step=decisions_per_step,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break,
        max_arrivals=max_arrivals)
    served = decs.type == kernels.RETURNING
    n_served = jnp.sum(served).astype(jnp.int64)
    n_resv = jnp.sum(served & (decs.phase == 0)).astype(jnp.int64)
    met = obsdev.metrics_combine(met, obsdev.metrics_delta(
        decisions=n_served, resv=n_resv, prop=n_served - n_resv,
        limit_break=jnp.sum(decs.limit_break).astype(jnp.int64),
        ring_hwm=jnp.max(engine.depth).astype(jnp.int64)))
    return engine, tracker, now, vd, vr, met, decs


def run_mesh_rounds(cluster: ClusterState, arrivals_seq, cost,
                    mesh: Mesh, *, decisions_per_step: int,
                    max_arrivals: int = 1, anticipation_ns: int = 0,
                    allow_limit_break: bool = False,
                    advance_ns: int = 0,
                    counter_sync_every: int = 1, round0: int = 0,
                    view_delta=None, view_rho=None, metrics=None,
                    with_merged: bool = False,
                    with_pressure: bool = False) -> MeshRounds:
    """The mesh serving plane's cluster program: ONE ``shard_map``
    launch advances every server by ``E = arrivals_seq.shape[0]``
    whole rounds (a ``lax.scan`` over rounds inside each shard), with
    the [C]-sized delta/rho counter psum -- the paper's piggyback
    protocol, batched -- exchanged once per round boundary instead of
    once per decision batch, and only on rounds where
    ``t % counter_sync_every == 0`` (round 0 always syncs; between
    syncs every server serves from its HELD view, exactly the
    stale-counter tolerance ``robust.cluster`` injects as the
    ``delay_counters`` fault -- the K>1 digest gate in
    ``tests/test_cluster_realism.py`` pins the two paths equal).

    ``arrivals_seq`` is int32[E, S, C] in round order.  With K=1 the
    launch is decision-for-decision AND counter-view-for-counter-view
    identical to ``E`` host-driven ``robust_cluster_step``s under a
    zero-fault plan; the only difference is launches: 1 vs 3E host
    round-trips.  ``view_delta``/``view_rho``/``metrics`` resume held
    state across launches (``None`` = the protocol origin / zeros)
    and ``round0`` anchors this launch on the GLOBAL round grid --
    the sync mask is ``(round0 + t) % K == 0`` -- so chunked mesh
    launches compose exactly like the host loop at ANY K (pass the
    previous launch's end round; the composition test pins K=2).

    ``with_merged`` additionally mesh-reduces the per-shard metric
    vectors in-graph (psum counters / pmax hwm); ``with_pressure``
    returns the post-run per-shard pressure gauges + their merged
    total (``obs.provenance``), replicated."""
    from ..obs import provenance as obsprov

    arrivals_seq = jnp.asarray(arrivals_seq, dtype=jnp.int32)
    epochs = int(arrivals_seq.shape[0])
    n_servers = cluster.now.shape[0]
    n_clients = arrivals_seq.shape[2]
    cost = jnp.asarray(cost, dtype=jnp.int64)
    sync_mask = jnp.asarray(
        round_sync_mask(epochs, counter_sync_every, round0))
    if view_delta is None or view_rho is None:
        view_delta, view_rho = init_mesh_views(n_servers, n_clients)
    if metrics is None:
        metrics = jnp.zeros((n_servers, obsdev.NUM_METRICS),
                            dtype=jnp.int64)
    # [E, S, C] -> [S, E, C]: the shard axis must lead for P(servers)
    arr_s = jnp.swapaxes(arrivals_seq, 0, 1)

    def per_server(engine, tracker, now, arrs, vd, vr, met):
        def body(carry, xs):
            engine, tracker, now, vd, vr, met = carry
            arr, sync = xs
            engine, tracker, now, vd, vr, met, decs = \
                _mesh_round_body(
                    engine, tracker, now, arr, vd, vr, met, sync,
                    cost=cost, decisions_per_step=decisions_per_step,
                    anticipation_ns=anticipation_ns,
                    allow_limit_break=allow_limit_break,
                    max_arrivals=max_arrivals, advance_ns=advance_ns)
            return (engine, tracker, now, vd, vr, met), decs

        (engine, tracker, now, vd, vr, met), decs = lax.scan(
            body, (engine, tracker, now, vd, vr, met),
            (arrs, sync_mask))
        return engine, tracker, now, vd, vr, met, decs

    def shard_fn(engine, tracker, now, arrs, vd, vr, met):
        out = jax.vmap(per_server)(engine, tracker, now, arrs, vd,
                                   vr, met)
        if with_merged:
            out = out + (obsdev.metrics_mesh_reduce(
                obsdev.metrics_combine_axis(out[5]), SERVER_AXIS),)
        if with_pressure:
            press = jax.vmap(obsprov.pressure_vec)(out[0], out[2])
            out = out + (press, obsprov.pressure_mesh_reduce(
                obsprov.pressure_combine_axis(press), SERVER_AXIS))
        return out

    spec = P(SERVER_AXIS)
    out_specs = (spec,) * 7
    if with_merged:
        out_specs += (P(),)
    if with_pressure:
        out_specs += (spec, P())
    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(spec,) * 7, out_specs=out_specs,
                   check_vma=False)
    outs = fn(cluster.engine, cluster.tracker, cluster.now, arr_s,
              view_delta, view_rho, metrics)
    engine, tracker, now, vd, vr, met, decs = outs[:7]
    rest = list(outs[7:])
    merged = rest.pop(0) if with_merged else None
    press, press_merged = (rest if with_pressure else (None, None))
    return MeshRounds(
        cluster=ClusterState(engine=engine, tracker=tracker, now=now),
        view_delta=vd, view_rho=vr, metrics=met, decs=decs,
        merged=merged, pressure=press, pressure_merged=press_merged)


_MESH_ROUNDS_JIT_CACHE: dict = {}


def jit_mesh_rounds(mesh: Mesh, *, epochs: int,
                    decisions_per_step: int, max_arrivals: int = 1,
                    anticipation_ns: int = 0,
                    allow_limit_break: bool = False,
                    advance_ns: int = 0, counter_sync_every: int = 1,
                    round0: int = 0, with_merged: bool = False,
                    with_pressure: bool = False):
    """Module-cached jit of :func:`run_mesh_rounds` for one (mesh,
    static-config) pair -- ``(cluster, arrivals_seq, cost, view_d,
    view_r, metrics) -> MeshRounds``.  The fused multi-round program
    is the mesh plane's expensive compile; the entry is keyed with the
    mesh SHAPE (not its repr) like ``mesh_step_jit``.  ``round0``
    anchors the sync grid (static; distinct chunk positions at K>1
    are distinct programs -- at K=1 every position shares one)."""
    from ..obs import compile_plane as _cplane

    cfg = (epochs, decisions_per_step, max_arrivals, anticipation_ns,
           allow_limit_break, advance_ns, counter_sync_every,
           int(round0) % max(int(counter_sync_every), 1),
           with_merged, with_pressure)
    key = mesh_cache_key(mesh, cfg)
    if key not in _MESH_ROUNDS_JIT_CACHE:
        def run(cluster, arrivals_seq, cost, view_d, view_r, met):
            return run_mesh_rounds(
                cluster, arrivals_seq, cost, mesh,
                decisions_per_step=decisions_per_step,
                max_arrivals=max_arrivals,
                anticipation_ns=anticipation_ns,
                allow_limit_break=allow_limit_break,
                advance_ns=advance_ns,
                counter_sync_every=counter_sync_every,
                round0=round0,
                view_delta=view_d, view_rho=view_r, metrics=met,
                with_merged=with_merged, with_pressure=with_pressure)

        mesh_shape = tuple(np.shape(getattr(mesh, "devices", ())))
        _MESH_ROUNDS_JIT_CACHE[key] = _cplane.instrumented_jit(
            run, cache="cluster.mesh_rounds",
            entry=cfg + (mesh_shape,))
    return _MESH_ROUNDS_JIT_CACHE[key]


def mesh_decs_seq(decs) -> list:
    """Re-slice a fused launch's ``[S, E, k]`` decision leaves into
    the per-round ``[S, k]`` stream the host-loop drivers produce
    (``robust.cluster.run_with_plan``), so ``decision_digest`` applies
    to both unchanged."""
    epochs = int(np.asarray(decs.type).shape[1])
    host = jax.tree.map(lambda a: np.asarray(jax.device_get(a)), decs)
    return [jax.tree.map(lambda a: a[:, t], host)
            for t in range(epochs)]


def create_clients(cluster: ClusterState, new_mask: jnp.ndarray,
                   resv_inv: jnp.ndarray, weight_inv: jnp.ndarray,
                   limit_inv: jnp.ndarray, mesh: Mesh) -> ClusterState:
    """Mid-run client creation, cluster-wide (the reference admits new
    clients at their first request, dmclock_server.h:920-932; here
    creation is an explicit sharded OP_CREATE ingest so slot==client
    stays a cluster invariant).

    ``new_mask`` bool[C] picks the slots to install; the QoS inverse
    arrays are [C] (only masked entries are read).  Creation order =
    slot index, preserving the cluster-wide deterministic tie-break.
    New clients join every server; their tracker counters start fresh.
    """
    c = new_mask.shape[0]
    slots = jnp.arange(c, dtype=jnp.int32)
    ops = kernels.IngestOps(
        kind=jnp.where(new_mask, kernels.OP_CREATE,
                       kernels.OP_NOP).astype(jnp.int32),
        slot=slots,
        time=jnp.zeros((c,), dtype=jnp.int64),
        cost=jnp.ones((c,), dtype=jnp.int64),
        rho=jnp.ones((c,), dtype=jnp.int64),
        delta=jnp.ones((c,), dtype=jnp.int64),
        resv_inv=resv_inv, weight_inv=weight_inv, limit_inv=limit_inv,
        order=slots.astype(jnp.int64),
    )

    def shard_fn(engine):
        return jax.vmap(lambda e: kernels.ingest(
            e, ops, anticipation_ns=0))(engine)

    spec = P(SERVER_AXIS)
    engine = shard_map(
        shard_fn, mesh=mesh, in_specs=(spec,), out_specs=spec,
        check_vma=False)(cluster.engine)
    return cluster._replace(engine=engine)
