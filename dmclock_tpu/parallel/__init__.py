"""Distributed (multi-server / multi-chip) dmClock.

The reference's entire inter-node mechanism is four piggybacked scalars:
``ReqParams{delta, rho}`` client->server and ``PhaseType`` + cost back
(``/root/reference/src/dmclock_recs.h:40-72``), with the client-side
``ServiceTracker`` (``dmclock_client.h:157-287``) diffing global
completion counters per server.  Here the same contract rides a JAX
device mesh: each server's scheduler state is a shard on the ``servers``
axis, per-(server, client) completion counters live sharded next to it,
and the tracker's "global counters" are a ``psum`` over ICI -- so one
pod simulates an N-server storage cluster in a single program (SURVEY.md
section 2, parallelism table).
"""

from .cluster import (ClusterState, create_clients, init_cluster,
                      cluster_step, install_clients, make_mesh,
                      shard_cluster)
from .tracker import (BorrowTrackerState, TrackerState,
                      borrow_tracker_prepare, borrow_tracker_track,
                      init_borrow_tracker, init_tracker,
                      tracker_prepare, tracker_track)

__all__ = [
    "ClusterState", "init_cluster", "cluster_step", "make_mesh",
    "shard_cluster", "create_clients", "install_clients",
    "TrackerState", "init_tracker", "tracker_prepare", "tracker_track",
    "BorrowTrackerState", "init_borrow_tracker",
    "borrow_tracker_prepare", "borrow_tracker_track",
]
