"""Device-side distributed ServiceTracker (the psum delta/rho protocol).

Vectorized equivalent of the host ``core.tracker.ServiceTracker`` with
``OrigTracker`` accounting (reference ``dmclock_client.h:39-84``,
``:157-287``), laid out for a mesh: state is per-(server, client), and
the client's *global* completion counters -- which the host tracker
keeps as plain ints -- become a ``psum`` of per-server counters over the
``servers`` mesh axis.

Per (server s, client c), mirroring OrigTracker's fields:
  ``last_mark``  = global counter value at c's previous request to s
                   (``delta_prev_req``/``rho_prev_req``)
  ``own_since``  = c's completions AT s since that request
                   (``my_delta``/``my_rho``)
so a request from c to s carries
  ``delta_out = global_delta[c] - last_mark[s,c] - own_since[s,c]``
(reference ``prepare_req``, dmclock_client.h:59-67).

Counters start at 1, matching ``GlobalCounters`` (dmclock_client.h:191-198).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp


class TrackerState(NamedTuple):
    """Per-server shard of the distributed tracker ([C] arrays local to
    one server; stack/shard a leading ``servers`` axis for a cluster)."""

    completed_delta: jnp.ndarray  # int64[C] completions served here, by client
    completed_rho: jnp.ndarray    # int64[C] reservation-phase subset
    last_mark_delta: jnp.ndarray  # int64[C] global delta at last request here
    last_mark_rho: jnp.ndarray    # int64[C]
    seen: jnp.ndarray             # bool[C] client has contacted this server


def init_tracker(n_clients: int) -> TrackerState:
    z = jnp.zeros((n_clients,), dtype=jnp.int64)
    return TrackerState(
        completed_delta=z, completed_rho=z,
        last_mark_delta=z, last_mark_rho=z,
        seen=jnp.zeros((n_clients,), dtype=bool),
    )


def global_counters(tracker: TrackerState, psum):
    """The client-global counters: psum of per-server completions over
    the mesh, plus the reference's start-at-1 offset.

    ``psum`` is the collective to use -- ``lambda x: lax.psum(x,
    'servers')`` inside shard_map, or a plain sum for unsharded use.
    """
    return 1 + psum(tracker.completed_delta), \
        1 + psum(tracker.completed_rho)


def tracker_track(tracker: TrackerState, slots: jnp.ndarray,
                  costs: jnp.ndarray, phases: jnp.ndarray,
                  served: jnp.ndarray) -> TrackerState:
    """Fold a batch of completions at THIS server into the counters
    (reference resp_update, dmclock_client.h:69-79): delta always, rho
    only for reservation-phase service.

    slots/costs/phases/served are the decision-stream arrays from
    ``engine_run`` (phase 0 = reservation).
    """
    idx = jnp.where(served, slots, 0)
    add = jnp.where(served, costs, 0)
    add_rho = jnp.where(served & (phases == 0), costs, 0)
    return tracker._replace(
        completed_delta=tracker.completed_delta.at[idx].add(add),
        completed_rho=tracker.completed_rho.at[idx].add(add_rho),
    )


def tracker_track_counts(tracker: TrackerState, served: jnp.ndarray,
                         served_resv: jnp.ndarray,
                         cost: jnp.ndarray) -> TrackerState:
    """Counts form of :func:`tracker_track` for engines that emit
    per-client completion totals instead of an ordered decision stream
    (the calendar engine's ``served``/``served_resv`` vectors):
    ``delta += served * cost``, ``rho += served_resv * cost`` -- the
    exact sums the per-decision fold computes when every request of a
    client carries the same cost (the device sim's model; ``cost`` is
    the per-client [C] request cost).  Dense adds, no scatter."""
    return tracker._replace(
        completed_delta=tracker.completed_delta
        + served.astype(jnp.int64) * cost,
        completed_rho=tracker.completed_rho
        + served_resv.astype(jnp.int64) * cost,
    )


def tracker_prepare(tracker: TrackerState, requesting: jnp.ndarray,
                    global_delta: jnp.ndarray, global_rho: jnp.ndarray):
    """ReqParams for every client in ``requesting`` (bool[C]) sending its
    next request to THIS server (reference prepare_req + the first-
    contact ReqParams(1,1) case, dmclock_client.h:241-251).

    Returns (new_tracker, delta_out[C], rho_out[C]) with outputs valid
    where ``requesting``.
    """
    # OrigTracker's algebra: delta_out = (global movement since the
    # previous request here) - (own completions here since then), i.e.
    #   delta_out = (global - global_mark) - (own - own_mark).
    # One stored field suffices: last_mark_delta keeps
    # ``global_mark - own_mark``, so
    #   delta_out = global - completed - last_mark_delta
    # and re-marking stores ``global - completed`` again.
    mark = tracker.last_mark_delta
    mark_rho = tracker.last_mark_rho
    delta_out = jnp.where(
        tracker.seen,
        global_delta - tracker.completed_delta - mark,
        1)
    rho_out = jnp.where(
        tracker.seen,
        global_rho - tracker.completed_rho - mark_rho,
        1)
    new_mark = jnp.where(requesting,
                         global_delta - tracker.completed_delta, mark)
    new_mark_rho = jnp.where(requesting,
                             global_rho - tracker.completed_rho, mark_rho)
    tracker = tracker._replace(
        last_mark_delta=new_mark,
        last_mark_rho=new_mark_rho,
        seen=tracker.seen | requesting,
    )
    return tracker, delta_out, rho_out


def global_counters_from(completed_delta: jnp.ndarray,
                         completed_rho: jnp.ndarray, psum):
    """:func:`global_counters` over RAW per-client completion-count
    arrays (the mesh serving plane's counter plane keeps ``int64[C]``
    arrays instead of a full ``TrackerState`` -- its per-shard engines
    ingest unit-rate superwaves, so only the completions half of the
    protocol is live).  Same start-at-1 origin, same collective."""
    return 1 + psum(completed_delta), 1 + psum(completed_rho)


def counter_view_bytes(n_clients: int) -> int:
    """Wire bytes of ONE counter-view exchange: the [C]-sized
    delta + rho int64 psum -- the paper's per-request four-scalar
    piggyback contract, batched into one collective.  This is the
    number the mesh bench records as ``counter_bytes_per_sync``."""
    return 2 * 8 * int(n_clients)


def exchange_schedule(epochs: int, counter_sync_every: int,
                      start: int = 0) -> dict:
    """Host-side accounting of the mesh plane's batched counter
    exchange over the ``epochs`` boundaries starting at GLOBAL epoch
    ``start`` with the ``counter_sync_every`` staleness knob (the
    device grid is ``epoch % K == 0``, so epoch 0 always syncs):
    sync count and cadence -- multiply by :func:`counter_view_bytes`
    for the wire totals in the MULTICHIP v2 record.  ``start``
    matters whenever a measured window begins off-grid (the bench's
    timed window starts after warmup; ``run_mesh_rounds``'s
    ``round0`` is the same anchor)."""
    every = max(int(counter_sync_every), 1)
    e0 = int(start)
    n = max(int(epochs), 0)
    first = -(-e0 // every) * every       # first sync epoch >= e0
    syncs = len(range(first, e0 + n, every))
    return {"epochs": n, "counter_sync_every": every,
            "start": e0, "syncs": syncs,
            "sync_frac": syncs / max(n, 1)}


# ----------------------------------------------------------------------
# observability (obs.registry wiring)
# ----------------------------------------------------------------------

def tracker_snapshot(tracker) -> dict:
    """Aggregate hot-path stats of one tracker shard as host scalars
    (works for both ``TrackerState`` and ``BorrowTrackerState``).  One
    device fetch per call -- drain-time only, never per request."""
    import numpy as np

    out = {
        "completed_delta_total": int(np.asarray(
            tracker.completed_delta).sum()),
        "completed_rho_total": int(np.asarray(
            tracker.completed_rho).sum()),
        "clients_seen": int(np.asarray(tracker.seen).sum()),
    }
    if hasattr(tracker, "borrow_delta"):
        out["borrow_delta_outstanding"] = int(np.asarray(
            tracker.borrow_delta).sum())
        out["borrow_rho_outstanding"] = int(np.asarray(
            tracker.borrow_rho).sum())
    return out


def register_tracker_metrics(registry, get_tracker, labels=None) -> None:
    """Register callback gauges over a tracker shard.  ``get_tracker``
    returns the CURRENT state (tracker states are immutable NamedTuples
    that callers rebind, so a getter is the only stable handle)."""
    def gauge_fn(key):
        return lambda: tracker_snapshot(get_tracker()).get(key, 0)

    for key in ("completed_delta_total", "completed_rho_total",
                "clients_seen"):
        registry.gauge(f"dmclock_tracker_{key}",
                       "distributed ServiceTracker shard stat",
                       labels=labels).set_function(gauge_fn(key))


# ----------------------------------------------------------------------
# BorrowingTracker variant (reference dmclock_client.h:90-154)
# ----------------------------------------------------------------------

class BorrowTrackerState(NamedTuple):
    """Per-server shard of the distributed BorrowingTracker: guarantees
    delta/rho >= 1 by borrowing future replies (reference
    calc_with_borrow, dmclock_client.h:110-129)."""

    completed_delta: jnp.ndarray  # int64[C] completions served here
    completed_rho: jnp.ndarray    # int64[C] reservation-phase subset
    prev_delta: jnp.ndarray       # int64[C] global delta at last request here
    prev_rho: jnp.ndarray         # int64[C]
    borrow_delta: jnp.ndarray     # int64[C] outstanding borrow
    borrow_rho: jnp.ndarray       # int64[C]
    seen: jnp.ndarray             # bool[C]


def init_borrow_tracker(n_clients: int) -> BorrowTrackerState:
    z = jnp.zeros((n_clients,), dtype=jnp.int64)
    return BorrowTrackerState(
        completed_delta=z, completed_rho=z,
        prev_delta=z, prev_rho=z,
        borrow_delta=z, borrow_rho=z,
        seen=jnp.zeros((n_clients,), dtype=bool),
    )


def borrow_tracker_track(tracker: BorrowTrackerState, slots, costs,
                         phases, served) -> BorrowTrackerState:
    """Fold a batch of completions at THIS server (reference
    BorrowingTracker::resp_update, dmclock_client.h:131-141: only the
    global counters move -- the psum source here).  The fold is the
    same completed_delta/completed_rho scatter-add as OrigTracker's."""
    return tracker_track(tracker, slots, costs, phases, served)


def _calc_with_borrow(global_c, prev, borrow):
    """Vector form of calc_with_borrow (dmclock_client.h:110-129)."""
    result = global_c - prev
    out = jnp.where(result == 0, 1,
                    jnp.where(result > borrow, result - borrow, 1))
    new_borrow = jnp.where(result == 0, borrow + 1,
                           jnp.where(result > borrow, 0,
                                     borrow - result + 1))
    return out, new_borrow


def borrow_tracker_prepare(tracker: BorrowTrackerState, requesting,
                           global_delta, global_rho):
    """ReqParams for every client in ``requesting`` sending its next
    request to THIS server (reference prepare_req,
    dmclock_client.h:131-137; first contact returns ReqParams(1,1) and
    installs the marks, :241-251)."""
    d_out, nbd = _calc_with_borrow(global_delta, tracker.prev_delta,
                                   tracker.borrow_delta)
    r_out, nbr = _calc_with_borrow(global_rho, tracker.prev_rho,
                                   tracker.borrow_rho)
    d_out = jnp.where(tracker.seen, d_out, 1)
    r_out = jnp.where(tracker.seen, r_out, 1)
    upd = requesting
    first = upd & ~tracker.seen
    tracker = tracker._replace(
        prev_delta=jnp.where(upd, global_delta, tracker.prev_delta),
        prev_rho=jnp.where(upd, global_rho, tracker.prev_rho),
        borrow_delta=jnp.where(first, 0,
                               jnp.where(upd, nbd,
                                         tracker.borrow_delta)),
        borrow_rho=jnp.where(first, 0,
                             jnp.where(upd, nbr, tracker.borrow_rho)),
        seen=tracker.seen | requesting,
    )
    return tracker, d_out, r_out
