"""Deterministic, seeded fault plans for chaos runs.

A :class:`FaultPlan` is a pytree of per-step, per-server mask/value
arrays describing every fault the robustness layer can inject into a
cluster run (``robust.cluster.robust_cluster_step``):

- **server dropout / restart** (``up``): a down server commits nothing
  (engine and tracker keep last-good state; wall time still passes
  for its virtual clock, it just gains no serve-side advancement) and
  its decision slots read NONE; a restarted server re-syncs its
  ``TrackerState`` marks from the monotone global counters before
  serving again.
- **delayed / lost piggyback counter updates** (``delay_counters``):
  the server serves this step from its *held* view of the global
  delta/rho counters (last synced step) instead of the fresh psum --
  the stale-counter tolerance the reference protocol is built around
  (``dmclock_client.h:39-84``).
- **clock skew** (``skew_ns``): the server's virtual clock reads
  ``now + skew_ns`` for this step's tag threshold tests (a per-step
  lens, not cumulative drift).
- **duplicated completions** (``dup_completions``): this step's
  completion batch folds into the tracker counters twice -- the
  at-least-once delivery failure mode of a real response network.

Plans are **host data** (numpy-backed), sampled once from a seed;
slicing a step (:func:`plan_step`) yields the small [S] arrays a jitted
cluster step consumes.  ``plan=None`` everywhere means *no fault
plumbing at all*; an all-benign plan (:func:`zero_plan`) runs the fault
plumbing with every mask off and is pinned bit-identical to ``None``
(the chaos differential gate, ``tests/test_robust.py`` +
``scripts/ci.sh``).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np


class FaultPlan(NamedTuple):
    """Per-step fault schedule; every leaf is [T, S] (steps, servers)."""

    up: np.ndarray                # bool[T, S] server is live this step
    skew_ns: np.ndarray           # int64[T, S] clock skew for the step
    delay_counters: np.ndarray    # bool[T, S] hold the stale counter view
    dup_completions: np.ndarray   # bool[T, S] fold completions twice

    @property
    def steps(self) -> int:
        return self.up.shape[0]

    @property
    def n_servers(self) -> int:
        return self.up.shape[1]


class FaultStep(NamedTuple):
    """One time-slice of a plan ([S] leaves) plus the previous step's
    liveness -- what the jitted cluster step actually consumes."""

    up: np.ndarray
    skew_ns: np.ndarray
    delay_counters: np.ndarray
    dup_completions: np.ndarray


def zero_plan(steps: int, n_servers: int) -> FaultPlan:
    """The all-benign plan: every server up, zero skew, no delays, no
    duplicates.  Running it must be bit-identical to ``plan=None``."""
    return FaultPlan(
        up=np.ones((steps, n_servers), dtype=bool),
        skew_ns=np.zeros((steps, n_servers), dtype=np.int64),
        delay_counters=np.zeros((steps, n_servers), dtype=bool),
        dup_completions=np.zeros((steps, n_servers), dtype=bool),
    )


def sample_plan(seed: int, steps: int, n_servers: int, *,
                p_dropout: float = 0.0, mean_outage_steps: float = 2.0,
                p_delay: float = 0.0, p_dup: float = 0.0,
                max_skew_ns: int = 0) -> FaultPlan:
    """Sample a deterministic plan from ``seed`` (PCG64; stable across
    runs and platforms).

    Liveness is a per-server Markov chain: an up server goes down with
    ``p_dropout`` per step; a down server restarts with probability
    ``1/mean_outage_steps``.  Every server starts up.  ``delay`` /
    ``dup`` masks and skew draw i.i.d. per (step, server); faults other
    than dropout only apply to live steps (the runner masks them)."""
    rng = np.random.Generator(np.random.PCG64(seed))
    up = np.ones((steps, n_servers), dtype=bool)
    alive = np.ones((n_servers,), dtype=bool)
    p_restart = 1.0 / max(mean_outage_steps, 1.0)
    for t in range(steps):
        u = rng.random(n_servers)
        alive = np.where(alive, u >= p_dropout, u < p_restart)
        up[t] = alive
    skew = rng.integers(-max_skew_ns, max_skew_ns + 1,
                        size=(steps, n_servers), dtype=np.int64) \
        if max_skew_ns else np.zeros((steps, n_servers), np.int64)
    return FaultPlan(
        up=up,
        skew_ns=skew,
        delay_counters=rng.random((steps, n_servers)) < p_delay,
        dup_completions=rng.random((steps, n_servers)) < p_dup,
    )


def single_outage_plan(steps: int, n_servers: int, *, server: int,
                       down_from: int, down_until: int) -> FaultPlan:
    """One server down for ``[down_from, down_until)`` -- the minimal
    dropout + restart scenario the CI chaos smoke and the degraded-mode
    test drive."""
    plan = zero_plan(steps, n_servers)
    plan.up[down_from:down_until, server] = False
    return plan


def plan_step(plan: FaultPlan, t: int) -> FaultStep:
    """Slice step ``t`` for the jitted cluster step."""
    return FaultStep(up=plan.up[t], skew_ns=plan.skew_ns[t],
                     delay_counters=plan.delay_counters[t],
                     dup_completions=plan.dup_completions[t])


class FaultChunk(NamedTuple):
    """A chunk-window slice of a plan for the FUSED mesh chunk
    (``parallel.mesh.build_mesh_chunk``): shard-axis-leading ``[S, E]``
    mask/value arrays (so ``P(servers)`` splits them) plus the
    liveness entering the window (``up_prev``, [S] -- derived from the
    plan's previous step, so dropout/restart transitions land on the
    same epochs the host loop sees).  Host numpy data; the chunk
    traces them as inputs."""

    up: np.ndarray               # bool[S, E]
    skew_ns: np.ndarray          # int64[S, E]
    delay_counters: np.ndarray   # bool[S, E]
    dup_completions: np.ndarray  # bool[S, E]
    up_prev: np.ndarray          # bool[S] liveness entering the chunk


def plan_chunk(plan: FaultPlan, e0: int, e1: int) -> FaultChunk:
    """Slice epochs ``[e0, e1)`` of a plan into the fused-chunk layout.
    ``up_prev`` comes from step ``e0 - 1`` (all-up at the origin), so
    chunked chaos launches compose exactly like the per-step host
    loop."""
    e0, e1 = int(e0), int(e1)
    assert 0 <= e0 < e1 <= plan.steps, (e0, e1, plan.steps)
    prev = plan.up[e0 - 1] if e0 > 0 \
        else np.ones((plan.n_servers,), dtype=bool)
    return FaultChunk(
        up=np.ascontiguousarray(plan.up[e0:e1].T),
        skew_ns=np.ascontiguousarray(plan.skew_ns[e0:e1].T),
        delay_counters=np.ascontiguousarray(
            plan.delay_counters[e0:e1].T),
        dup_completions=np.ascontiguousarray(
            plan.dup_completions[e0:e1].T),
        up_prev=prev.copy())


def plan_events(plan: FaultPlan) -> dict:
    """Host-side ground truth of the fault events a run of this plan
    must surface in the device metrics vector -- the exact-match oracle
    for ``server_dropouts`` / ``tracker_resyncs`` / ``faults_injected``
    (the visibility half of the chaos differential suite)."""
    prev = np.vstack([np.ones((1, plan.n_servers), dtype=bool),
                      plan.up[:-1]])
    dropouts = int((prev & ~plan.up).sum())
    resyncs = int((~prev & plan.up).sum())
    live = plan.up
    perturbations = int((plan.delay_counters & live).sum()
                        + (plan.dup_completions & live).sum()
                        + ((plan.skew_ns != 0) & live).sum())
    return {
        "server_dropouts": dropouts,
        "tracker_resyncs": resyncs,
        "faults_injected": dropouts + resyncs + perturbations,
    }


def plan_shard_events(plan: FaultPlan) -> dict:
    """Per-shard form of :func:`plan_events` (``int64[S]`` arrays):
    the exact-match oracle for the ``shard``-labelled
    ``dmclock_fault_*`` families and the bench's per-shard
    dropout/resync record rows.  Summing each array reproduces the
    cluster totals of :func:`plan_events` by construction."""
    prev = np.vstack([np.ones((1, plan.n_servers), dtype=bool),
                      plan.up[:-1]])
    dropouts = (prev & ~plan.up).sum(axis=0).astype(np.int64)
    resyncs = (~prev & plan.up).sum(axis=0).astype(np.int64)
    live = plan.up
    perturb = ((plan.delay_counters & live).sum(axis=0)
               + (plan.dup_completions & live).sum(axis=0)
               + ((plan.skew_ns != 0) & live).sum(axis=0)
               ).astype(np.int64)
    return {"server_dropouts": dropouts,
            "tracker_resyncs": resyncs,
            "faults_injected": dropouts + resyncs + perturb}


# keys parse_fault_spec accepts (everything sample_plan takes except
# the run-derived steps/n_servers); "seed" rides separately
_SPEC_KEYS = ("p_dropout", "mean_outage_steps", "p_delay", "p_dup",
              "max_skew_ns")


def parse_fault_spec(spec) -> Optional[dict]:
    """Parse a ``--fault-plan`` value into :func:`sample_plan` kwargs
    (plus ``seed``), or None when the value is a plain LABEL (the
    PR-3 semantics: ``--fault-plan`` tagged a session without running
    anything).  A spec is a comma-separated ``key=value`` string --
    e.g. ``"seed=7,p_dropout=0.05,mean_outage_steps=2,p_dup=0.1"`` --
    or an already-parsed dict; ``"none"``/empty parses to None."""
    if spec is None:
        return None
    if isinstance(spec, dict):
        out = dict(spec)
    else:
        s = str(spec).strip()
        if not s or s.lower() == "none" or "=" not in s:
            return None
        out = {}
        for part in s.split(","):
            k, _, v = part.partition("=")
            k = k.strip()
            if k not in _SPEC_KEYS + ("seed",):
                raise ValueError(
                    f"unknown fault-plan spec key {k!r} (one of "
                    f"{('seed',) + _SPEC_KEYS})")
            out[k] = float(v) if "." in v or "e" in v.lower() \
                else int(v)
    out.setdefault("seed", 0)
    unknown = set(out) - set(_SPEC_KEYS) - {"seed"}
    if unknown:
        raise ValueError(f"unknown fault-plan spec keys "
                         f"{sorted(unknown)}")
    out["seed"] = int(out["seed"])
    out["max_skew_ns"] = int(out.get("max_skew_ns", 0))
    return out


def plan_from_spec(spec: dict, steps: int, n_servers: int) -> FaultPlan:
    """Sample the plan a parsed spec describes for a ``steps`` x
    ``n_servers`` run -- the one deterministic construction shared by
    ``EpochJob(fault_plan=...)`` and ``bench.py --fault-plan``, so a
    bench session and its supervised twin inject the identical
    schedule."""
    kw = dict(spec)
    seed = int(kw.pop("seed", 0))
    return sample_plan(seed, int(steps), int(n_servers), **kw)


def describe(plan: FaultPlan | None) -> str:
    """Compact history tag for bench/JSON records: ``"none"`` for no
    plan or an all-benign plan, else a summary naming the fault mix --
    chaos runs must never pollute the clean-run regression series
    (scripts/bench_guard.py keys on this)."""
    if plan is None:
        return "none"
    ev = plan_events(plan)
    if ev["faults_injected"] == 0:
        return "none"
    return (f"T{plan.steps}xS{plan.n_servers}:"
            f"drop{ev['server_dropouts']}"
            f"+resync{ev['tracker_resyncs']}"
            f"+inject{ev['faults_injected']}")
