"""Degraded-mode cluster stepping under an injected fault plan.

Wraps ``parallel.cluster`` with the graceful-degradation semantics the
reference protocol promises but the happy-path port never exercised:

- a **down** server commits nothing (engine and tracker counters keep
  last-good state; wall time still passes -- its virtual clock keeps
  tracking ``advance_ns`` but gains no serve-side advancement) and its
  decision slots read NONE; the psum still runs on every shard (SPMD),
  but a down shard's contribution is frozen at its last committed
  counters -- the global counters stay **monotone**, which is what
  makes the whole fault model protocol-safe;
- surviving servers keep serving their reservation contracts from
  whatever counter view they hold (``server_round`` takes the view as
  an argument -- the stale-counter tolerance of ``dmclock_client.h``);
- a **restarted** server re-syncs its ``TrackerState`` marks from the
  monotone global counters (:func:`resync_tracker`) before serving
  again, exactly like a real client re-contacting a returned server;
- every injected fault is counted into the on-device metrics vector
  (``server_dropouts`` / ``tracker_resyncs`` / ``faults_injected``
  rows) and the per-(server, client) conformance table
  (:func:`cluster_conformance`) mirrors the PR-1 sim table.

``fault=None`` takes the exact pre-existing ``cluster_step`` path --
zero cost when no faults are configured -- and an all-benign plan
(``faults.zero_plan``) is pinned bit-identical to ``None`` by the
chaos differential gate (``tests/test_robust.py``, ``scripts/ci.sh``).
"""

from __future__ import annotations

import functools
import hashlib
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..engine import kernels
from ..obs import device as obsdev
from ..parallel import cluster as CL
from ..parallel.cluster import SERVER_AXIS, ClusterState, server_round
from ..parallel.tracker import (BorrowTrackerState, borrow_tracker_track,
                                global_counters, tracker_track)
from ..utils.compat import shard_map
from .faults import FaultPlan, FaultStep, plan_step


class RobustClusterState(NamedTuple):
    """ClusterState plus the degradation bookkeeping.

    ``view_delta``/``view_rho`` are each server's *held* view of the
    global counters ([S, C] int64, re-synced on live non-delayed
    steps); ``up_prev`` tracks liveness transitions; ``metrics`` is a
    per-shard ``obs.device`` vector ([S, NUM_METRICS]; counters add,
    hwm rows max -- merge shards with ``obs.device.metrics_combine_np``
    or :func:`metrics_totals`)."""

    cluster: ClusterState
    view_delta: jnp.ndarray
    view_rho: jnp.ndarray
    up_prev: jnp.ndarray
    metrics: jnp.ndarray


def init_robust(cluster: ClusterState) -> RobustClusterState:
    """Wrap a freshly built cluster: views start at the protocol's
    counters-start-at-1 origin, every server up, metrics zero."""
    s, c = cluster.tracker.completed_delta.shape
    ones = jnp.ones((s, c), dtype=jnp.int64)
    return RobustClusterState(
        cluster=cluster, view_delta=ones, view_rho=ones,
        up_prev=jnp.ones((s,), dtype=bool),
        metrics=jnp.zeros((s, obsdev.NUM_METRICS), dtype=jnp.int64))


def shard_robust(rc: RobustClusterState, mesh) -> RobustClusterState:
    sharding = NamedSharding(mesh, P(SERVER_AXIS))
    return jax.tree.map(lambda a: jax.device_put(a, sharding), rc)


def resync_tracker(tracker, g_delta: jnp.ndarray, g_rho: jnp.ndarray):
    """Re-mark a restarted shard's tracker state against the monotone
    global counters: the next request from each seen client carries
    delta/rho = (global movement since the resync) - (own completions
    here since the resync) -- the same forgiveness the reference's
    re-marking ``prepare_req`` applies, so nothing missed during the
    outage is double-charged.  Unseen clients are untouched (their
    first contact already gets ReqParams(1, 1))."""
    seen = tracker.seen
    if isinstance(tracker, BorrowTrackerState):
        return tracker._replace(
            prev_delta=jnp.where(seen, g_delta, tracker.prev_delta),
            prev_rho=jnp.where(seen, g_rho, tracker.prev_rho),
            borrow_delta=jnp.where(seen, 0, tracker.borrow_delta),
            borrow_rho=jnp.where(seen, 0, tracker.borrow_rho))
    return tracker._replace(
        last_mark_delta=jnp.where(
            seen, g_delta - tracker.completed_delta,
            tracker.last_mark_delta),
        last_mark_rho=jnp.where(
            seen, g_rho - tracker.completed_rho,
            tracker.last_mark_rho))


def _one_server_step_faulty(engine, tracker, now, arr, view_d, view_r,
                            up_prev, met, up, skew, delay, dup, *,
                            cost, decisions_per_step, anticipation_ns,
                            allow_limit_break, max_arrivals):
    """One server's degraded-mode round (inside shard_map, vmapped over
    the [1] shard axis; ``up``/``skew``/``delay``/``dup`` are this
    server's FaultStep scalars)."""
    # the collective runs on EVERY shard (SPMD); a down shard's
    # counters are frozen, so the psum stays monotone
    g_d, g_r = global_counters(
        tracker, lambda x: lax.psum(x, SERVER_AXIS))

    restart = up & ~up_prev
    dropout = ~up & up_prev

    # counter-view sync: live servers pull the fresh psum unless the
    # plan delays their piggyback updates; a restart always re-syncs
    sync = (up & ~delay) | restart
    view_d = jnp.where(sync, g_d, view_d)
    view_r = jnp.where(sync, g_r, view_r)

    # restarted shard re-marks its tracker against the global counters
    resynced = resync_tracker(tracker, view_d, view_r)
    tracker = jax.tree.map(
        lambda a, b: jnp.where(restart, a, b), resynced, tracker)

    # the round itself, against the held view and the skewed clock
    new_engine, new_tracker, new_now, decs = server_round(
        engine, tracker, now + skew, arr, cost, view_d, view_r,
        decisions_per_step=decisions_per_step,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break,
        max_arrivals=max_arrivals)

    # duplicated completions: fold this step's completion batch a
    # second time (masked; an int scatter-add of 0 is exact)
    served = decs.type == kernels.RETURNING
    track = borrow_tracker_track \
        if isinstance(tracker, BorrowTrackerState) else tracker_track
    new_tracker = track(new_tracker, decs.slot, decs.cost, decs.phase,
                        served & dup)

    # commit gate: a down server keeps last-good state; its decision
    # slots read NONE (nothing was handed out)
    keep = lambda new, old: jnp.where(up, new, old)  # noqa: E731
    engine = jax.tree.map(keep, new_engine, engine)
    tracker = jax.tree.map(keep, new_tracker, tracker)
    now = jnp.where(up, new_now - skew, now)
    decs = kernels.Decision(
        type=jnp.where(up, decs.type, jnp.int32(kernels.NONE)),
        slot=jnp.where(up, decs.slot, jnp.int32(-1)),
        phase=jnp.where(up, decs.phase, jnp.int32(0)),
        cost=jnp.where(up, decs.cost, jnp.int64(0)),
        when=jnp.where(up, decs.when, jnp.int64(0)),
        limit_break=decs.limit_break & up)

    served = decs.type == kernels.RETURNING
    n_served = jnp.sum(served).astype(jnp.int64)
    n_resv = jnp.sum(served & (decs.phase == 0)).astype(jnp.int64)
    perturb = ((dup & up).astype(jnp.int64)
               + (delay & up).astype(jnp.int64)
               + ((skew != 0) & up).astype(jnp.int64))
    events = dropout.astype(jnp.int64) + restart.astype(jnp.int64)
    met = obsdev.metrics_combine(met, obsdev.metrics_delta(
        decisions=n_served, resv=n_resv, prop=n_served - n_resv,
        limit_break=jnp.sum(decs.limit_break).astype(jnp.int64),
        ring_hwm=jnp.max(engine.depth).astype(jnp.int64),
        server_dropouts=dropout.astype(jnp.int64),
        tracker_resyncs=restart.astype(jnp.int64),
        faults_injected=events + perturb))
    return engine, tracker, now, view_d, view_r, up, met, decs


def _merge_held_metrics(metrics: jnp.ndarray, mesh) -> jnp.ndarray:
    """Mesh-merge the [S, NUM_METRICS] held-view vectors in-graph
    (counters psum, hwm pmax); the result is replicated, one vector."""
    spec = P(SERVER_AXIS)
    fn = shard_map(
        lambda m: obsdev.metrics_mesh_reduce(
            obsdev.metrics_combine_axis(m), SERVER_AXIS),
        mesh=mesh, in_specs=(spec,), out_specs=P(),
        check_vma=False)
    return fn(metrics)


def robust_cluster_step(rc: RobustClusterState, arrivals: jnp.ndarray,
                        cost, mesh, *,
                        fault: Optional[FaultStep] = None,
                        decisions_per_step: int,
                        max_arrivals: int = 1,
                        anticipation_ns: int = 0,
                        allow_limit_break: bool = False,
                        advance_ns: int = 0,
                        with_merged: bool = False,
                        with_pressure: bool = False):
    """One cluster step under an optional :class:`FaultStep`.

    ``fault=None`` (STATIC) delegates to the plain ``cluster_step`` --
    the fault plumbing costs nothing when unused, and the views /
    transition bookkeeping are untouched (they re-sync on the next
    faulty step).  Pure; jit with ``mesh``/config bound via partial.

    ``with_merged`` (STATIC) additionally returns the mesh-merged
    total of the per-shard held-view metric vectors -- counters psum,
    hwm rows pmax via ``obs.device.metrics_mesh_reduce``, the same
    in-graph collective the healthy path's
    ``cluster_step(with_metrics=True)`` got in PR-4 -- replicated
    across the mesh, so cluster fault totals need no host gather even
    mid-chaos.  Pinned merged == host-summed under a nonzero plan in
    ``tests/test_cluster_realism.py``.

    ``with_pressure`` (STATIC) additionally returns ``(per_shard
    int64[S, PRESS_FIELDS], merged int64[PRESS_FIELDS])`` post-round
    scheduling-pressure vectors (``obs.provenance.pressure_vec`` --
    eligible depth / backlog / peak / head-wait watermark) through the
    same psum/pmax collective: the degraded-mode twin of the healthy
    path's gauges, so the rack-scheduling placement signal stays
    published even mid-chaos (a down shard reports its FROZEN state:
    its backlog keeps aging, which is exactly what a router must see).
    """
    if fault is None:
        out = CL.cluster_step(
            rc.cluster, arrivals, cost, mesh,
            decisions_per_step=decisions_per_step,
            max_arrivals=max_arrivals, anticipation_ns=anticipation_ns,
            allow_limit_break=allow_limit_break, advance_ns=advance_ns,
            with_pressure=with_pressure)
        cluster, decs = out[0], out[1]
        rc = rc._replace(cluster=cluster)
        res = (rc, decs)
        if with_merged:
            # no fault plumbing ran, but the caller still wants the
            # merged view of the HELD metrics (frozen this step)
            res = res + (_merge_held_metrics(rc.metrics, mesh),)
        if with_pressure:
            res = res + tuple(out[2:])
        return res

    cost = jnp.asarray(cost, dtype=jnp.int64)
    f_up = jnp.asarray(fault.up, dtype=bool)
    f_skew = jnp.asarray(fault.skew_ns, dtype=jnp.int64)
    f_delay = jnp.asarray(fault.delay_counters, dtype=bool)
    f_dup = jnp.asarray(fault.dup_completions, dtype=bool)

    def shard_fn(engine, tracker, now, arr, view_d, view_r, up_prev,
                 met, up, skew, delay, dup):
        step = functools.partial(
            _one_server_step_faulty, cost=cost,
            decisions_per_step=decisions_per_step,
            anticipation_ns=anticipation_ns,
            allow_limit_break=allow_limit_break,
            max_arrivals=max_arrivals)
        out = jax.vmap(step)(engine, tracker, now, arr, view_d,
                             view_r, up_prev, met, up, skew, delay,
                             dup)
        if with_merged:
            # local reduce over this shard's servers, then the mesh
            # collective: counters psum, hwm pmax (associative +
            # commutative, so mesh order cannot matter)
            merged = obsdev.metrics_mesh_reduce(
                obsdev.metrics_combine_axis(out[6]), SERVER_AXIS)
            out = out + (merged,)
        if with_pressure:
            from ..obs import provenance as obsprov
            # post-round engine state at the UNSKEWED clock (out[2]):
            # a down shard's frozen backlog keeps aging against the
            # cluster clock, exactly what a router must see
            press = jax.vmap(obsprov.pressure_vec)(out[0], out[2])
            out = out + (press, obsprov.pressure_mesh_reduce(
                obsprov.pressure_combine_axis(press), SERVER_AXIS))
        return out

    spec = P(SERVER_AXIS)
    out_specs = (spec,) * 8 + ((P(),) if with_merged else ())
    if with_pressure:
        out_specs += (spec, P())
    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(spec,) * 12, out_specs=out_specs,
        check_vma=False)
    now0 = rc.cluster.now + jnp.int64(advance_ns)
    outs = fn(
        rc.cluster.engine, rc.cluster.tracker, now0, arrivals,
        rc.view_delta, rc.view_rho, rc.up_prev, rc.metrics,
        f_up, f_skew, f_delay, f_dup)
    engine, tracker, now, view_d, view_r, up_prev, met, decs = \
        outs[:8]
    rc = RobustClusterState(
        cluster=ClusterState(engine=engine, tracker=tracker, now=now),
        view_delta=view_d, view_rho=view_r, up_prev=up_prev,
        metrics=met)
    return (rc, decs) + tuple(outs[8:])


# Module-level jit cache (the engine/queue.py _JIT_CACHE convention):
# a fresh jax.jit(partial(...)) per run_with_plan call would recompile
# the whole shard_map cluster program for every run of identical
# static config -- the CI chaos smoke alone runs three.  The cache
# keying (incl. the unhashable-mesh fallback) is shared with the
# healthy-path driver: parallel.cluster.mesh_step_jit.
_STEP_JIT_CACHE: dict = {}


def _jit_step(mesh, cfg: tuple):
    return CL.mesh_step_jit(_STEP_JIT_CACHE, robust_cluster_step,
                            mesh, cfg)


def run_with_plan(rc: RobustClusterState, arrivals, cost, mesh,
                  plan: Optional[FaultPlan] = None, *,
                  decisions_per_step: int, max_arrivals: int = 1,
                  anticipation_ns: int = 0,
                  allow_limit_break: bool = False,
                  advance_ns: int = 0, tracer=None):
    """Drive ``arrivals.shape[0]`` cluster steps under ``plan`` (None =
    no fault plumbing at all).  Returns ``(rc, decs_seq)`` with the
    per-step decisions fetched to host numpy -- the stream the chaos
    digest and the conformance table are computed from.

    ``tracer`` (``obs.spans.SpanTracer`` or None) records one
    ``cluster.round`` dispatch span per step (the whole-mesh launch;
    args carry the step index and whether a fault was applied) and a
    ``cluster.fetch`` span for the decision readback -- host-side
    only, the decision stream is bit-identical either way."""
    from ..obs import spans as _spans

    step = _jit_step(mesh, (decisions_per_step, max_arrivals,
                            anticipation_ns, allow_limit_break,
                            advance_ns))
    decs_seq = []
    for t in range(np.asarray(arrivals).shape[0]):
        fault = plan_step(plan, t) if plan is not None else None
        with _spans.span(tracer, "cluster.round", "dispatch",
                         step=t, faulty=fault is not None):
            rc, decs = step(rc, jnp.asarray(arrivals[t]), cost,
                            fault=fault)
        with _spans.span(tracer, "cluster.fetch", "fetch", step=t):
            decs_seq.append(jax.device_get(decs))
    return rc, decs_seq


def effective_plan(plan: FaultPlan, counter_sync_every: int = 1,
                   round0: int = 0) -> FaultPlan:
    """Fold the ``counter_sync_every`` staleness grid into a plan's
    ``delay_counters`` mask: a non-sync round IS the delay fault (the
    PR-13 equivalence -- the knob is the stale-view tolerance turned
    into a cadence), so the host loop under the effective plan is the
    exact reference for a fused K-grid launch under the raw plan.  At
    K=1 the plan is returned unchanged."""
    sync = CL.round_sync_mask(plan.steps, counter_sync_every, round0)
    if sync.all():
        return plan
    return plan._replace(
        delay_counters=plan.delay_counters | ~sync[:, None])


def run_mesh_rounds_with_plan(rc: RobustClusterState, arrivals_seq,
                              cost, mesh, plan: FaultPlan, *,
                              decisions_per_step: int,
                              max_arrivals: int = 1,
                              anticipation_ns: int = 0,
                              allow_limit_break: bool = False,
                              advance_ns: int = 0,
                              counter_sync_every: int = 1,
                              round0: int = 0):
    """The CHAOS twin of ``parallel.cluster.run_mesh_rounds``: ONE
    ``shard_map`` launch advances every server by ``E`` whole degraded
    rounds -- a ``lax.scan`` over :func:`_one_server_step_faulty`, the
    SAME per-round program the host loop (:func:`run_with_plan`) jits
    per step -- with the seeded :class:`FaultPlan` riding the scan as
    traced per-round mask slices and the ``counter_sync_every``
    staleness grid folded into the delay mask
    (:func:`effective_plan`).  Dropout/restart/skew/dup semantics,
    tracker re-sync, the frozen-contribution monotone psum, and the
    per-shard fault metric rows are all byte-the-same construction as
    the host loop's, so the digest gate

    ``run_mesh_rounds_with_plan(plan, K) == run_with_plan(
    effective_plan(plan, K))``

    (decisions + held views + tracker state + metric vectors) is an
    identity of launch structure only: E round-trips collapse to one.
    Returns ``(rc, decs)`` with ``decs`` leaves ``[S, E, k]``
    (re-slice with ``parallel.cluster.mesh_decs_seq``)."""
    import functools

    arrivals_seq = jnp.asarray(arrivals_seq, dtype=jnp.int32)
    epochs = int(arrivals_seq.shape[0])
    cost = jnp.asarray(cost, dtype=jnp.int64)
    eff = effective_plan(plan, counter_sync_every, round0)
    assert eff.steps == epochs, (eff.steps, epochs)
    # [T, S] plan leaves -> [S, T] so P(servers) splits them
    f_up = jnp.asarray(np.ascontiguousarray(eff.up.T))
    f_skew = jnp.asarray(np.ascontiguousarray(eff.skew_ns.T))
    f_delay = jnp.asarray(np.ascontiguousarray(eff.delay_counters.T))
    f_dup = jnp.asarray(np.ascontiguousarray(eff.dup_completions.T))
    arr_s = jnp.swapaxes(arrivals_seq, 0, 1)
    adv = jnp.int64(advance_ns)

    step = functools.partial(
        _one_server_step_faulty, cost=cost,
        decisions_per_step=decisions_per_step,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break,
        max_arrivals=max_arrivals)

    def per_server(engine, tracker, now, arrs, vd, vr, up_prev, met,
                   ups, skews, delays, dups):
        def body(carry, xs):
            engine, tracker, now, vd, vr, up_prev, met = carry
            arr, up, skew, delay, dup = xs
            engine, tracker, now, vd, vr, up_now, met, decs = step(
                engine, tracker, now + adv, arr, vd, vr, up_prev,
                met, up, skew, delay, dup)
            return (engine, tracker, now, vd, vr, up_now, met), decs

        carry, decs = lax.scan(
            body, (engine, tracker, now, vd, vr, up_prev, met),
            (arrs, ups, skews, delays, dups))
        engine, tracker, now, vd, vr, up_prev, met = carry
        return engine, tracker, now, vd, vr, up_prev, met, decs

    def shard_fn(engine, tracker, now, arrs, vd, vr, up_prev, met,
                 ups, skews, delays, dups):
        return jax.vmap(per_server)(engine, tracker, now, arrs, vd,
                                    vr, up_prev, met, ups, skews,
                                    delays, dups)

    spec = P(SERVER_AXIS)
    fn = shard_map(shard_fn, mesh=mesh, in_specs=(spec,) * 12,
                   out_specs=(spec,) * 8, check_vma=False)
    engine, tracker, now, vd, vr, up_prev, met, decs = fn(
        rc.cluster.engine, rc.cluster.tracker, rc.cluster.now, arr_s,
        rc.view_delta, rc.view_rho, rc.up_prev, rc.metrics,
        f_up, f_skew, f_delay, f_dup)
    rc = RobustClusterState(
        cluster=ClusterState(engine=engine, tracker=tracker, now=now),
        view_delta=vd, view_rho=vr, up_prev=up_prev, metrics=met)
    return rc, decs


def decision_digest(decs_seq) -> str:
    """sha256 over the decision stream (type/slot/phase/cost per step)
    -- the bit-identity currency of the chaos differential gate."""
    h = hashlib.sha256()
    for d in decs_seq:
        for arr in (d.type, d.slot, d.phase, d.cost):
            h.update(np.ascontiguousarray(np.asarray(arr)).tobytes())
    return h.hexdigest()


def metrics_totals(rc: RobustClusterState) -> dict:
    """Merge the per-shard metric vectors (counters add, hwm max) and
    name the rows -- one device fetch."""
    vecs = np.asarray(jax.device_get(rc.metrics))
    acc = np.zeros((obsdev.NUM_METRICS,), dtype=np.int64)
    acc = obsdev.metrics_combine_np(acc, *vecs)
    return obsdev.metrics_dict(acc)


# ----------------------------------------------------------------------
# per-(server, client) conformance -- the PR-1 table at cluster scale
# ----------------------------------------------------------------------

def cluster_conformance(decs_seq, arrivals, plan, qos_triples,
                        advance_ns: int, tol: float = 0.05
                        ) -> List[dict]:
    """Per-(server, client) QoS conformance over each server's LIVE
    window: delivered rate vs min(reservation, demand) and the limit
    cap -- the same verdict semantics as ``SimReport.conformance``
    (arrivals posted to a down server are lost, so they leave its
    demand).  ``qos_triples`` is [(reservation, weight, limit)] per
    client; each step spans ``advance_ns`` of virtual time."""
    arrivals = np.asarray(arrivals)
    t_steps, n_servers, n_clients = arrivals.shape
    live = np.asarray(plan.up) if plan is not None else \
        np.ones((t_steps, n_servers), dtype=bool)
    served = np.zeros((n_servers, n_clients), dtype=np.int64)
    for t, d in enumerate(decs_seq):
        dtype = np.asarray(d.type)
        dslot = np.asarray(d.slot)
        for s in range(n_servers):
            sel = dslot[s][dtype[s] == kernels.RETURNING]
            np.add.at(served[s], sel, 1)
    demand = (arrivals * live[:, :, None]).sum(axis=0)
    rows = []
    for s in range(n_servers):
        window_s = max(live[:, s].sum() * advance_ns / 1e9, 1e-9)
        for c in range(n_clients):
            resv, weight, limit = qos_triples[c]
            rate = served[s, c] / window_s
            demand_rate = demand[s, c] / window_s
            resv_floor = min(resv, demand_rate)
            rows.append({
                "server": s, "client": c,
                "live_steps": int(live[:, s].sum()),
                "reservation": resv, "weight": weight, "limit": limit,
                "ops": int(served[s, c]), "rate": rate,
                "demand_rate": demand_rate,
                "resv_met": (rate >= resv_floor * (1.0 - tol))
                if resv > 0 else True,
                "limit_ok": (rate <= limit * (1.0 + tol))
                if limit > 0 else True,
            })
    return rows


def format_cluster_conformance(rows: List[dict]) -> str:
    """Text table over :func:`cluster_conformance` rows (the PR-1
    conformance table with a server column and live-window rates)."""
    lines = ["-- per-(server, client) QoS conformance "
             "(live window) --",
             f"{'srv':>4} {'client':>6} {'live':>5} {'resv':>8} "
             f"{'limit':>8} {'ops':>8} {'rate':>9} {'demand':>9} "
             f"{'verdict':>12}"]
    for r in rows:
        verdict = ("ok" if r["resv_met"] else "RESV-MISS") + \
            ("" if r["limit_ok"] else "+LIMIT-EXCESS")
        lines.append(
            f"{r['server']:>4} {r['client']:>6} {r['live_steps']:>5} "
            f"{r['reservation']:>8.1f} {r['limit']:>8.1f} "
            f"{r['ops']:>8} {r['rate']:>9.2f} "
            f"{r['demand_rate']:>9.2f} {verdict:>12}")
    misses = sum(1 for r in rows if not r["resv_met"])
    excess = sum(1 for r in rows if not r["limit_ok"])
    lines.append(f"rows {len(rows)} | reservation misses {misses} "
                 f"| limit excesses {excess}")
    return "\n".join(lines)
