"""Crash-equivalent supervised epoch runs (docs/ROBUSTNESS.md).

PR-3 made device-level faults injectable; the host process stayed a
single point of failure.  This module closes that gap the way
RackSched survives per-server failures through stateless re-dispatch
(PAPERS.md): the epoch loop becomes a **resumable job** under a
supervisor --

- the job runs epochs of any of the three epoch engines through the
  guarded-commit contract (``robust.guarded.run_epoch_guarded``),
  ingesting Poisson arrivals drawn from a checkpointed host RNG;
- at epoch boundaries it writes **rotating crash-safe checkpoints**
  (``utils.checkpoint.save_pytree_rotating``) of the FULL run state:
  engine pytree, obs metrics vector, RNG bit-generator state, the
  decision-stream chain digest, the epoch/decision counters, and the
  degradation-ladder position;
- the supervisor (child process via spawn, or an in-process
  trampoline for tests) restarts a killed job with bounded
  exponential backoff; resume lands on the **newest intact** rotation
  snapshot (``restore_pytree_rotating``'s fallback walk) and replays
  forward deterministically.

The headline invariant is the **crash-equivalence digest gate**: a
run SIGKILLed at ANY :class:`~.host_faults.HostFaultPlan` point and
resumed produces the same decision-stream digest, the same final
engine state, and the same metric totals -- modulo the ``resume_*``
rows (``obs.device.RESUME_ROWS``) -- as the uninterrupted run.
Exactly-once is by construction: the digest is a sha256 **chain**
carried inside the checkpoint, so decisions committed before the last
snapshot are hashed exactly once, and decisions after it are replayed
bit-identically from the restored state + RNG.

On top sits the **degradation ladder**
(``robust.guarded.DegradationLadder``): repeated guard trips or
exhausted launch retries step the job down ``bucketed -> minstop``,
``radix -> sort``, ``tag32 -> int64`` -- every rung an already-proven
exact path, so a degraded run is slower, never divergent.  Ladder
position rides in the checkpoint and in obs row
``degradation_ladder_steps``.

``EpochJob(engine_loop="stream")`` swaps the per-epoch launch
structure for the always-on streaming serve loop (``engine.stream``;
docs/ENGINE.md "engine_loop"): one fused ingest+serve+commit device
launch per checkpoint-boundary chunk, double-buffered superwave
pregen, drains only at the boundaries -- decisions digest-pinned
bit-identical to the round loop, and every invariant above (crash
equivalence, telemetry, the ladder) carries over unchanged
(``_stream_epochs``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import subprocess
import sys
import time as _time
from typing import Callable, NamedTuple, Optional

import numpy as np

from ..utils import checkpoint as ckpt_mod
from .guarded import (RECOVERABLE_ERRORS, DegradationLadder,
                      run_epoch_guarded)
from .host_faults import (HostFaultInjector, HostFaultPlan, HostKill,
                          describe_host, plan_from_json, plan_to_json,
                          zero_host_plan)


class SupervisorGaveUp(RuntimeError):
    """The job died more times than ``max_restarts`` allows."""


@dataclasses.dataclass(frozen=True)
class EpochJob:
    """A deterministic, resumable epoch-loop workload -- the sim/bench
    inner loop distilled to what the supervisor needs: everything
    below is plain data, so a job JSON-round-trips into a spawned
    child process and two runs of the same job are bit-identical."""

    engine: str = "prefix"          # prefix | chain | calendar
    n: int = 512                    # clients
    depth: int = 12                 # preloaded queue depth
    ring: int = 16
    epochs: int = 8
    m: int = 4                      # batches per epoch
    k: int = 64                     # per-batch cap / calendar steps
    chain_depth: int = 4
    select_impl: str = "sort"
    tag_width: int = 64
    calendar_impl: str = "minstop"
    ladder_levels: int = 4
    wheel_kernel: str = "xla"       # wheel bucket kernel: xla | pallas
    seed: int = 11                  # arrival RNG seed
    arrival_lam: float = 2.0        # Poisson mean arrivals/client/epoch
    waves: int = 4
    dt_epoch_ns: int = 10 ** 8
    ckpt_every: int = 2             # checkpoint every N epochs
    keep: int = 4                   # rotation depth
    ladder: bool = False            # degradation ladder enabled
    ladder_threshold: int = 2
    metrics_port: Optional[int] = None   # scrape endpoint (fail-soft)
    # offset client 0's head proportion tag (ns): past +-2^31 it
    # deterministically trips the tag32 rebase window every epoch --
    # the in-repo way to exercise guard trips / ladder engagement
    tag_spread_ns: int = 0
    # device telemetry plane (obs.histograms / obs.flight): the
    # accumulators ride the rotation checkpoints, so crash equivalence
    # extends to telemetry (histograms + ledger + flight ring of a
    # killed-and-resumed run == the uninterrupted run, bit-identical)
    with_hists: bool = False        # log2 QoS histograms
    with_ledger: bool = False       # per-client conformance ledger
    flight_records: int = 0         # HBM flight-recorder rows (0=off)
    flight_dump: Optional[str] = None  # JSONL path the flight ring is
    #                                    dumped to when an incarnation
    #                                    crashes (--flight-dump)
    # time-domain tracing plane (obs.spans): span JSONL path, APPENDED
    # to at every checkpoint boundary -- and ONLY there: a resume
    # replays from the last snapshot, so flushing past it would
    # double-count the replayed epochs' spans.  The stream survives a
    # SIGKILL restart with exactly the rotation checkpoints'
    # durability window.  Spans are host-side wall time --
    # per-incarnation timestamps, deliberately OUTSIDE the
    # checkpointed state (crash equivalence is about decisions, not
    # about how long the host took)
    span_log: Optional[str] = None
    # client lifecycle plane (docs/LIFECYCLE.md): a churn spec dict
    # (lifecycle.churn.make_spec) turns the job into an OPEN-population
    # run -- the engine state starts EMPTY at the spec's capacity0 and
    # a lifecycle.LifecyclePlane drives registration / live QoS
    # updates / idle eviction / compaction at the ckpt_every boundary
    # grid (= the stream loop's chunk grid, so lifecycle ops compose
    # with the fused chunk by construction).  Arrivals come from the
    # spec's per-epoch lam vectors, drawn in CLIENT-ID space (identical
    # RNG consumption in a dynamic run and its static_variant -- the
    # digest gate's meaningfulness) and mapped onto the current slot
    # layout at each boundary.  The chain digest hashes the CANONICAL
    # client-id-space views (plane.canon_results), so registration
    # timing, slot recycling, growth, and compaction are digest-
    # neutral; the plane's state (slot map, pending-update journal,
    # WAL cursor, counters) rides the rotation checkpoints as lc_*
    # leaves, so churned runs stay crash-equivalent.  None = the
    # closed-population job the PRs 1-8 gates pin.
    churn: Optional[dict] = None
    # SLO plane (obs.slo / obs.alerts; docs/OBSERVABILITY.md "SLO
    # plane"): a per-client windowed-conformance block rides the epoch
    # scans like the PR-6 telemetry, with window rolls pinned to the
    # ckpt_every boundary grid (= the stream loop's chunk grid, so
    # both loops roll identically).  The closed-window ring, the
    # contract-epoch counters, and the burn-rate evaluator state ride
    # the rotation checkpoints as slo_* leaves -- crash equivalence
    # extends to all of them (a killed-and-resumed run's windows,
    # attribution, and fired episodes == the uninterrupted run's).
    with_slo: bool = False
    slo_ring: int = 64              # closed-window ring depth/client
    # judged closed windows as JSONL (scripts/slo_report.py's feed),
    # APPENDED right after each checkpoint commits -- the span_log
    # durability discipline: what is flushed is exactly what a resume
    # will never re-close
    slo_log: Optional[str] = None
    # decision provenance plane (obs.provenance;
    # docs/OBSERVABILITY.md "Provenance plane"): the per-batch "why"
    # block -- winner margins, limit-gate state, eligible-set depth,
    # winning phase, per-client last_served watermark + starvation
    # high-watermark -- rides the epoch scans like the PR-6
    # telemetry.  The block's leaves ride the rotation checkpoints
    # (prov_*), so crash equivalence extends to it bit-for-bit.
    # Composes with ``churn``: the per-slot last_served watermark
    # rides the lifecycle boundary as an extras rider with fill 0
    # (= never served), so a recycled slot's new tenant starts with
    # no inherited serve history and the dynamic==static digest gate
    # extends to the provenance plane.
    with_prov: bool = False
    # engine loop structure (docs/ENGINE.md "engine_loop"): "round"
    # launches the admission readback + ingest + epoch separately per
    # epoch (the PR-5 shape, ~3 tunnel round-trips/epoch); "stream"
    # fuses ingest+serve+commit for EVERY epoch between two checkpoint
    # boundaries into ONE device launch (engine.stream), with the
    # decision stream / metrics / telemetry accumulating in HBM, the
    # host pre-generating chunk T+1's superwave draws while the device
    # runs chunk T (double buffer), and drains only at the PR-5
    # checkpoint boundaries.  Decisions are digest-pinned
    # bit-identical to "round" (ci.sh streaming smoke); a guard trip
    # inside a chunk falls back to the round path for that chunk
    # (robust.guarded.run_stream_chunk_guarded), so crash equivalence
    # and the degradation ladder survive unchanged.  "mesh" shards the
    # stream loop over a device mesh (parallel.mesh; docs/ENGINE.md
    # "Mesh serving"): ``n_shards`` full per-device engines each run
    # the complete fused chunk inside ONE shard_map launch, with the
    # paper's delta/rho counter views exchanged through a [C]-sized
    # psum at epoch boundaries on the ``counter_sync_every`` grid.
    # S=1 mesh is bit-identical to "stream" (and so to "round") by
    # construction -- both trace engine.stream.make_epoch_step -- and
    # the counter plane + per-shard telemetry ride the rotation
    # checkpoints, so crash equivalence extends to the mesh loop
    # unchanged.  ``churn`` composes via PER-SHARD lifecycle planes
    # (client ids routed by the placement map -- ``placement`` below;
    # the default static map IS ``cid % n_shards``; docs/LIFECYCLE.md
    # "Per-shard routing") and ``flight_records`` via per-shard HBM
    # rings merged in shard order at drain; mesh churn does not yet
    # compose with ``with_slo`` (the merged window table would need
    # an id-space merge across per-shard slot layouts -- rejected up
    # front) and composes with ``fault_plan`` only under
    # ``placement="p2c"``, where a registration routed to a DOWN
    # shard deterministically re-routes to its live sampled choice
    # (or defers one boundary when both are down); static placement
    # has no re-route path, so churn + fault_plan + static stays a
    # loud up-front ValueError.
    engine_loop: str = "round"
    # mesh serving plane knobs (engine_loop="mesh" only): shard count
    # (devices used; obs.capacity.plan_capacity sizes it from the
    # client target) and the counter-exchange staleness knob -- views
    # refresh from the mesh psum only on epochs where
    # ``epoch % counter_sync_every == 0`` (epoch 0 always syncs; the
    # paper's piggybacked views are naturally stale, so K>1 keeps the
    # QoS invariants -- parallel.cluster.run_mesh_rounds pins the
    # same knob decision-exact against the host loop's
    # delay_counters fault)
    n_shards: int = 1
    counter_sync_every: int = 1
    # shard placement plane (lifecycle/placement.py; docs/LIFECYCLE.md
    # "Placement and migration"; engine_loop="mesh" + churn only):
    # "static" keeps the historical ``cid % n_shards`` ownership
    # BIT-IDENTICALLY (no PlacementMap is even built); "p2c" routes
    # new registrations by power-of-two-choices over the per-shard
    # pressure backlog from a checkpointed placement RNG (scenario
    # pins keep shard_skew's scripted ownership), enables the
    # controller's ``migrate`` actuation (live digest-neutral
    # EVICT/REGISTER handoffs between shards), and lifts the
    # churn-with-fault_plan rejection (DOWN-shard registrations
    # re-route/defer deterministically).  A ``{"mode": "p2c",
    # "overrides": {cid: shard}}`` dict pins specific clients to
    # specific shards -- the digest gate's placed-from-start twin.
    placement: object = "static"
    # degraded-mode mesh serving (docs/ROBUSTNESS.md "Degraded-mode
    # mesh"; engine_loop="mesh" only): a JSON-able fault-plan SPEC
    # (dict, or the bench's "seed=..,p_dropout=.." string form) --
    # ``robust.faults.parse_fault_spec`` keys: seed, p_dropout,
    # mean_outage_steps, p_delay, p_dup, max_skew_ns -- sampled
    # deterministically at job start into a ``FaultPlan`` over
    # (epochs, n_shards) and COMPILED INTO every fused mesh chunk as
    # traced per-epoch masks (parallel.mesh).  The plan is pure host
    # data recomputed per incarnation from this spec, so crash
    # equivalence needs no new checkpoint state; a guard trip during
    # a chaos chunk replays the identical schedule on the host robust
    # loop (counted as a mesh_chaos_fallback).  None = no fault
    # plumbing (byte-identical to the pre-chaos chunk program).
    fault_plan: object = None   # dict spec or
    #                             "seed=..,p_dropout=.." string
    # closed-loop serving controller (control/; docs/CONTROLLER.md):
    # a host control plane evaluated at the checkpoint-boundary grid
    # -- one typed ControlSignals snapshot per boundary (SLO burn,
    # backlog, capacity occupancy, starvation watermarks), a
    # deterministic guarded-transition policy with per-rule
    # hysteresis/cooldown, and a WAL-journaled knob vector (staleness,
    # ladder overlay, admission clamp, compaction trigger).  Every
    # decision is fsynced to the journal BEFORE it applies; a resumed
    # run REPLAYS journaled decisions instead of re-deciding, so
    # crash equivalence extends to the controller (kill at any
    # actuation stage == the uninterrupted twin, bit-identical).
    # Actuation routes only through exact-twin switches (ladder
    # rungs, device admission clamp, boundary compaction), so
    # ``controller=None`` (off) stays bit-identical to the bare
    # runner.  Accepts None/False (off), True (defaults), a
    # control.ControllerConfig, or its asdict() (JSON round-trip).
    controller: object = None

    def to_json(self) -> dict:
        # asdict recurses into a ControllerConfig, so a controller
        # job JSON round-trips into the spawn-mode child unchanged
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: dict) -> "EpochJob":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in obj.items() if k in fields})


class SupervisedResult(NamedTuple):
    """What a completed (bare or supervised) run reports."""

    digest: str         # hex decision-stream chain digest
    state_digest: str   # sha256 over the final engine state leaves
    decisions: int
    epochs: int
    metrics: np.ndarray  # int64[NUM_METRICS], resume row included
    restarts: int
    ladder_steps: list   # DegradationLadder.describe() rows
    # scrape-port rebinds observed by the FINAL incarnation (host
    # telemetry, deliberately outside the checkpointed state --
    # rebinds in killed incarnations die with them)
    scrape_rebinds: int
    # rotation path the FINAL incarnation resumed from (None when it
    # started fresh) -- the newest-intact-fallback observability hook
    resumed_from: Optional[str] = None
    # telemetry plane (None when the job ran with it off); numpy
    # arrays, compared bit-for-bit by the crash-equivalence gate
    hists: Optional[np.ndarray] = None      # [NUM_HISTS, BUCKETS+1]
    ledger: Optional[np.ndarray] = None     # [N, LED_COLS]
    flight_buf: Optional[np.ndarray] = None  # [R, FLIGHT_COLS]
    flight_seq: int = 0                      # records ever written
    # stream chunks that tripped a guard and re-ran on the round path
    # (engine_loop="stream" only; deterministic, so it replays to the
    # same value across a crash+resume)
    stream_fallbacks: int = 0
    # lifecycle-plane summary (plane.snapshot(): live/peak clients,
    # capacity, registration/eviction/compaction/qos-update counters)
    # for churn jobs; None for closed-population jobs.  Deterministic,
    # so the crash-equivalence gate compares it too.
    lifecycle: Optional[dict] = None
    # SLO plane outputs (None when the job ran with it off): the final
    # open window block, the closed-window ring (flat RING_COLS rows in
    # close order), the contract-epoch counters ([K, 2] cid/epoch
    # pairs), and the burn-rate evaluator summary -- all deterministic,
    # all compared by the crash-equivalence gate
    slo_window: Optional[np.ndarray] = None
    slo_ring: Optional[np.ndarray] = None
    slo_cepoch: Optional[np.ndarray] = None
    slo: Optional[dict] = None
    # provenance plane outputs (None when the job ran with it off):
    # the margin histogram row, the scalar aggregates, and the
    # per-client last_served watermark -- all deterministic, all
    # compared by the crash-equivalence gate
    prov_margin_hist: Optional[np.ndarray] = None
    prov_scal: Optional[np.ndarray] = None
    prov_last_served: Optional[np.ndarray] = None
    # mesh serving plane outputs (engine_loop="mesh" only; None
    # otherwise): the per-shard completion counters ([2, S, N]:
    # delta, rho) and the held counter views ([2, S, N]) -- both
    # deterministic, both compared by the crash-equivalence gate --
    # plus the chunk-fallback count (the stream_fallbacks analog)
    mesh_counters: Optional[np.ndarray] = None
    mesh_views: Optional[np.ndarray] = None
    mesh_fallbacks: int = 0
    # chaos chunks (fault_plan set) that tripped a guard and replayed
    # on the host robust loop -- the degraded-mode mesh's
    # slow-but-on-plan path (a subset of mesh_fallbacks)
    mesh_chaos_fallbacks: int = 0
    # closed-loop controller outputs (job.controller set; zeros/None
    # otherwise): applied decision count, final knob vector, and the
    # journaled decision trajectory [[seq, epoch, rule, knobs...]] --
    # all deterministic, all compared by the crash-equivalence gate.
    # controller_replays counts journal REPLAYS by the final
    # incarnation (legitimately nonzero only after a crash, like the
    # resume rows -- excluded from the gate).
    controller_decisions: int = 0
    controller_replays: int = 0
    controller_knobs: Optional[list] = None
    controller_trajectory: Optional[list] = None
    # shard placement / migration plane outputs (mesh churn with
    # placement != "static"; None/0 otherwise): the placement mode,
    # the migration count, the move log [[boundary, cid, src, dst]]
    # in move order (the digest gate's overrides source), and the
    # PlacementMap counter snapshot -- all deterministic (the
    # placement RNG rides the rotation checkpoints), all compared by
    # the crash-equivalence gate
    placement: Optional[str] = None
    migrations: int = 0
    migration_log: Optional[list] = None
    placement_counters: Optional[dict] = None


def assert_crash_equivalent(interrupted: SupervisedResult,
                            reference: SupervisedResult) -> None:
    """The digest gate: decision stream, final state, and metric
    totals must match bit-for-bit, modulo the resume rows an
    interrupted run legitimately grows."""
    from ..obs import device as obsdev

    assert interrupted.digest == reference.digest, \
        (f"decision digest diverged: {interrupted.digest[:16]} vs "
         f"{reference.digest[:16]}")
    assert interrupted.state_digest == reference.state_digest, \
        "final engine state diverged"
    assert interrupted.decisions == reference.decisions
    a = np.asarray(interrupted.metrics, dtype=np.int64).copy()
    b = np.asarray(reference.metrics, dtype=np.int64).copy()
    for row in obsdev.RESUME_ROWS:
        a[row] = b[row] = 0
    assert np.array_equal(a, b), \
        (f"metric totals diverged outside the resume rows: "
         f"{a.tolist()} vs {b.tolist()}")
    # crash equivalence extends to the telemetry plane: the
    # accumulators ride the rotation checkpoints and the replayed
    # decisions are bit-identical, so histograms, ledger, AND the
    # flight ring must match exactly (no resume-row exception -- the
    # telemetry plane has no host-restart counters)
    for field in ("hists", "ledger", "flight_buf"):
        x = getattr(interrupted, field)
        y = getattr(reference, field)
        assert (x is None) == (y is None), \
            f"telemetry field {field} enabled on only one side"
        if x is not None:
            assert np.array_equal(np.asarray(x), np.asarray(y)), \
                f"telemetry field {field} diverged across the crash"
    assert interrupted.flight_seq == reference.flight_seq
    # lifecycle state replays deterministically from the checkpointed
    # slot map + WAL cursor, so the full plane summary (population,
    # capacity, every counter) must match too
    assert interrupted.lifecycle == reference.lifecycle, \
        (f"lifecycle plane diverged across the crash: "
         f"{interrupted.lifecycle} vs {reference.lifecycle}")
    # the SLO plane's window block, closed-window ring, and
    # contract-epoch counters ride the rotation checkpoints and the
    # rolls are pinned to the checkpoint grid, so all three -- and the
    # burn-rate evaluator's episode accounting -- must be bit-identical
    for field in ("slo_window", "slo_ring", "slo_cepoch"):
        x = getattr(interrupted, field)
        y = getattr(reference, field)
        assert (x is None) == (y is None), \
            f"SLO field {field} enabled on only one side"
        if x is not None:
            assert np.array_equal(np.asarray(x), np.asarray(y)), \
                f"SLO field {field} diverged across the crash"
    assert interrupted.slo == reference.slo, \
        (f"SLO evaluator diverged across the crash: "
         f"{interrupted.slo} vs {reference.slo}")
    # the provenance block rides the rotation checkpoints and its
    # observations are pure functions of the replayed decisions, so
    # margin histogram, scalar aggregates, and the last_served
    # watermark must all be bit-identical too
    for field in ("prov_margin_hist", "prov_scal",
                  "prov_last_served"):
        x = getattr(interrupted, field)
        y = getattr(reference, field)
        assert (x is None) == (y is None), \
            f"provenance field {field} enabled on only one side"
        if x is not None:
            assert np.array_equal(np.asarray(x), np.asarray(y)), \
                f"provenance field {field} diverged across the crash"
    # the mesh counter plane (per-shard delta/rho completions + held
    # views) rides the rotation checkpoints and replays
    # deterministically, so both arrays must match bit-for-bit too
    for field in ("mesh_counters", "mesh_views"):
        x = getattr(interrupted, field)
        y = getattr(reference, field)
        assert (x is None) == (y is None), \
            f"mesh field {field} enabled on only one side"
        if x is not None:
            assert np.array_equal(np.asarray(x), np.asarray(y)), \
                f"mesh field {field} diverged across the crash"
    # the controller journals every decision BEFORE applying it and a
    # resumed run replays the journal instead of re-deciding, so the
    # applied count, the final knob vector, and the full decision
    # trajectory must be bit-identical (controller_replays is the one
    # legitimately-different field: it counts how many of those
    # decisions the final incarnation REPLAYED rather than made)
    assert interrupted.controller_decisions == \
        reference.controller_decisions, \
        (f"controller decision count diverged: "
         f"{interrupted.controller_decisions} vs "
         f"{reference.controller_decisions}")
    assert interrupted.controller_knobs == reference.controller_knobs, \
        (f"controller knob vector diverged: "
         f"{interrupted.controller_knobs} vs "
         f"{reference.controller_knobs}")
    assert interrupted.controller_trajectory == \
        reference.controller_trajectory, \
        (f"controller decision trajectory diverged: "
         f"{interrupted.controller_trajectory} vs "
         f"{reference.controller_trajectory}")
    # the placement map's RNG/assignment/move-log ride the rotation
    # checkpoints and migrations replay deterministically from the
    # journaled trigger + checkpointed RNG, so the whole plane -- the
    # move log included, in order -- must be bit-identical
    assert interrupted.placement == reference.placement, \
        "placement mode diverged across the crash"
    assert interrupted.migrations == reference.migrations, \
        (f"migration count diverged: {interrupted.migrations} vs "
         f"{reference.migrations}")
    assert interrupted.migration_log == reference.migration_log, \
        (f"migration log diverged: {interrupted.migration_log} vs "
         f"{reference.migration_log}")
    assert interrupted.placement_counters == \
        reference.placement_counters, \
        (f"placement counters diverged: "
         f"{interrupted.placement_counters} vs "
         f"{reference.placement_counters}")



# ----------------------------------------------------------------------
# the job loop
# ----------------------------------------------------------------------

def _job_state(job: EpochJob):
    """Deterministic preloaded engine state (the bench serve-only
    preload shape: staggered proportion tags, ``depth`` queued ops per
    client).  A churn job starts EMPTY at the spec's initial capacity
    instead -- its population arrives through the lifecycle plane.  A
    mesh job (``engine_loop="mesh"``) returns the STACKED ``[S, ...]``
    layout: every shard is one server owning a DISTINCT ``n``-client
    partition that shares this same contract layout (S * n client
    contracts across the mesh; independent per-shard arrival streams
    supply the divergence -- parallel.mesh module doc)."""
    import jax.numpy as jnp

    from ..core.timebase import rate_to_inv_ns
    from ..engine import init_state

    if job.engine_loop == "mesh":
        from ..parallel import mesh as mesh_mod

        single = dataclasses.replace(job, engine_loop="stream")
        return mesh_mod.stack_shards(_job_state(single), job.n_shards)
    if job.churn is not None:
        # open population: EMPTY at the spec's initial capacity (a
        # mesh churn job stacks S of these -- every shard starts at
        # the same capacity0, its partition arriving through its own
        # per-shard plane)
        return init_state(int(job.churn["capacity0"]), job.ring)
    st = init_state(job.n, job.ring)
    c = np.arange(job.n)
    rinv = np.full(job.n, rate_to_inv_ns(100.0), dtype=np.int64)
    winv = np.asarray([rate_to_inv_ns(1.0 + (i % 4)) for i in c],
                      dtype=np.int64)
    phase = ((c * 2654435761) & 0xFFFFF) / float(1 << 20)
    jitter = (phase * 2.0 * winv).astype(np.int64)
    if job.tag_spread_ns:
        jitter[0] += np.int64(job.tag_spread_ns)
    q_arr = np.zeros((job.n, job.ring), dtype=np.int64)
    q_arr[:, :job.depth - 1] = np.tile(np.arange(1, job.depth),
                                       (job.n, 1))
    return st._replace(
        active=jnp.ones(job.n, dtype=bool),
        idle=jnp.zeros(job.n, dtype=bool),
        order=jnp.arange(job.n, dtype=jnp.int64),
        resv_inv=jnp.asarray(rinv),
        weight_inv=jnp.asarray(winv),
        head_resv=jnp.asarray(rinv),
        head_prop=jnp.asarray(winv + jitter),
        head_limit=jnp.full(job.n, -(1 << 62), dtype=jnp.int64),
        depth=jnp.full(job.n, job.depth, dtype=jnp.int32),
        q_arrival=jnp.asarray(q_arr),
        q_cost=jnp.ones((job.n, job.ring), dtype=jnp.int64),
    )


def _rng_state_array(rng: np.random.Generator) -> np.ndarray:
    """PCG64 bit-generator state as uint64[6] (128-bit state and inc
    split lo/hi, plus the uint32 spill) -- checkpointable host RNG."""
    s = rng.bit_generator.state
    mask = (1 << 64) - 1
    st, inc = s["state"]["state"], s["state"]["inc"]
    return np.asarray([st & mask, (st >> 64) & mask,
                       inc & mask, (inc >> 64) & mask,
                       int(s["has_uint32"]), int(s["uinteger"])],
                      dtype=np.uint64)


def _rng_from_array(a) -> np.random.Generator:
    a = np.asarray(a, dtype=np.uint64)
    rng = np.random.Generator(np.random.PCG64(0))
    rng.bit_generator.state = {
        "bit_generator": "PCG64",
        "state": {"state": int(a[0]) | (int(a[1]) << 64),
                  "inc": int(a[2]) | (int(a[3]) << 64)},
        "has_uint32": int(a[4]), "uinteger": int(a[5])}
    return rng


_DIGEST_FIELDS = ("count", "unit_count", "resv_count", "slot", "cls",
                  "length", "phase", "cost", "lb", "served", "type")


def _digest_update(digest: bytes, results) -> bytes:
    """One chain-digest step: sha256(previous digest || this epoch's
    decision arrays).  Resumable where a single running sha256 is not:
    the 32-byte chain value rides in the checkpoint, decisions before
    the snapshot are hashed exactly once, decisions after it replay
    into the same chain."""
    import jax

    h = hashlib.sha256(digest)
    for r in results:
        for name in _DIGEST_FIELDS:
            if hasattr(r, name):
                a = np.asarray(jax.device_get(getattr(r, name)))
                h.update(str(a.dtype).encode())
                h.update(str(a.shape).encode())
                h.update(np.ascontiguousarray(a).tobytes())
    return h.digest()


def _tree_digest(tree) -> str:
    import jax

    return ckpt_mod._leaf_digest(
        [np.asarray(x) for x in jax.device_get(jax.tree.leaves(tree))])


def _payload(job: EpochJob, state, rng, met, digest: bytes,
             epoch: int, decisions: int, ladder_vec,
             hists=None, ledger=None, flight=None,
             plane=None, slo=None, prov=None, mesh=None,
             ctl=None, pm=None) -> dict:
    import jax

    from ..control import Controller
    from ..lifecycle.placement import PlacementMap
    from ..lifecycle.plane import LifecyclePlane
    from ..obs import flight as obsflight
    from ..obs import slo as obsslo
    from ..obs.alerts import SloEvaluator

    # telemetry leaves are ALWAYS present (zero-size when the job runs
    # with that accumulator off) so the restore template's structure
    # depends only on the job config, never on runtime state
    z = np.zeros((0,), dtype=np.int64)
    # rng may be the live Generator (round loop) or a state array
    # snapshot (stream loop: the double buffer draws chunk T+1 BEFORE
    # boundary T's save, so the live generator is ahead of the
    # boundary -- the snapshot taken after chunk T's own draws is what
    # must persist, or a resume would re-draw a different stream)
    rng_arr = np.asarray(rng, dtype=np.uint64) \
        if isinstance(rng, np.ndarray) else _rng_state_array(rng)
    # lifecycle leaves are ALWAYS present too (empty for closed-
    # population jobs) -- same structure-from-config convention; their
    # capacities vary at runtime, so churn jobs restore with
    # strict_shapes=False (utils.checkpoint).  A mesh churn job
    # carries a LIST of per-shard planes: each encodes under
    # lc_s{s}_* (S is job config, so the payload structure still
    # depends only on the config), the base lc_* leaves stay empty.
    if isinstance(plane, (list, tuple)):
        lc = dict(LifecyclePlane.empty_leaves())
        for s, pl in enumerate(plane):
            lc.update({f"lc_s{s}{k[2:]}": v
                       for k, v in pl.encode().items()})
    elif plane is not None:
        lc = plane.encode()
    else:
        lc = LifecyclePlane.empty_leaves()
    # SLO leaves follow the same always-present convention: the block,
    # the plane's ring/contract-epoch state, and the evaluator's
    # episode accounting (slo = (block, SloPlane, SloEvaluator) or
    # None); rolls are pinned to the checkpoint grid, so the saved
    # block is always a freshly-opened window
    if slo is not None:
        sl = {"slo_window": np.asarray(jax.device_get(slo[0]),
                                       dtype=np.int64),
              **slo[1].encode(), **slo[2].encode()}
    else:
        sl = {"slo_window": np.zeros((0, obsslo.W_FIELDS),
                                     dtype=np.int64),
              **obsslo.SloPlane.empty_leaves(),
              **SloEvaluator.empty_leaves()}
    # mesh counter-plane leaves (engine_loop="mesh"): per-shard
    # delta/rho completion counters + held views ([S, N] each) --
    # always present (zero-size otherwise), the structure-from-config
    # convention
    if mesh is not None:
        mz = {k: np.asarray(jax.device_get(v), dtype=np.int64)
              for k, v in zip(("mesh_cd", "mesh_cr", "mesh_vd",
                               "mesh_vr"), mesh)}
    else:
        mz = {k: np.zeros((0,), dtype=np.int64)
              for k in ("mesh_cd", "mesh_cr", "mesh_vd", "mesh_vr")}
    # controller leaves follow the same always-present convention:
    # the applied-decision cursor, the knob vector, and the policy
    # hysteresis/cooldown state (fixed shapes from the rule table, so
    # even the zero template matches exactly)
    ct = ctl.encode() if ctl is not None else Controller.empty_leaves()
    # placement-map leaves (mesh churn with placement != "static"):
    # assignment, placement RNG, counters, move log, deferred list --
    # always present (zero-size otherwise), the structure-from-config
    # convention; move-log/deferred axis 0 is runtime state, so such
    # jobs already restore with strict_shapes=False (churn)
    pmz = pm.encode() if pm is not None else PlacementMap.empty_leaves()
    return {**lc, **sl, **mz, **ct, **pmz,
            "digest": np.frombuffer(digest, dtype=np.uint8).copy(),
            "decisions": np.int64(decisions),
            "engine": state,
            "epoch": np.int64(epoch),
            "ladder": np.asarray(ladder_vec, dtype=np.int64),
            "metrics": np.asarray(met, dtype=np.int64),
            "rng": rng_arr,
            "tele_hists": z if hists is None
            else np.asarray(jax.device_get(hists), dtype=np.int64),
            "tele_ledger": z if ledger is None
            else np.asarray(jax.device_get(ledger), dtype=np.int64),
            "tele_flight_buf":
                np.zeros((0, obsflight.FLIGHT_COLS), dtype=np.int64)
                if flight is None
                else np.asarray(jax.device_get(flight.buf),
                                dtype=np.int64),
            # seq/batch are scalars on single-engine loops and [S]
            # arrays for the mesh's stacked per-shard rings
            "tele_flight_seq": np.int64(0) if flight is None
            else np.asarray(jax.device_get(flight.seq),
                            dtype=np.int64),
            "tele_flight_batch": np.int64(0) if flight is None
            else np.asarray(jax.device_get(flight.batch),
                            dtype=np.int64),
            "prov_margin_hist": z if prov is None
            else np.asarray(jax.device_get(prov.margin_hist),
                            dtype=np.int64),
            "prov_scal": z if prov is None
            else np.asarray(jax.device_get(prov.scal),
                            dtype=np.int64),
            "prov_last_served": z if prov is None
            else np.asarray(jax.device_get(prov.last_served),
                            dtype=np.int64)}


def _tele_init(job: EpochJob):
    """Fresh telemetry accumulators per the job's static flags.  A
    churn job's per-client ledger is sized to the spec's initial
    capacity (it grows with the state arrays at boundaries)."""
    from ..obs import flight as obsflight
    from ..obs import histograms as obshist
    from ..obs import provenance as obsprov

    n = int(job.churn["capacity0"]) if job.churn is not None else job.n
    hists = obshist.hist_zero() if job.with_hists else None
    ledger = obshist.ledger_zero(n) if job.with_ledger else None
    flight = obsflight.flight_init(job.flight_records) \
        if job.flight_records else None
    prov = obsprov.prov_init(n) if job.with_prov else None
    if job.engine_loop == "mesh":
        # per-shard accumulator stacks (each shard's epoch program
        # carries its own; hists/ledger/prov merge through their
        # mesh-reduce algebra on the way out, the flight rings merge
        # in shard order at drain)
        from ..parallel import mesh as mesh_mod

        def stk(acc):
            return None if acc is None \
                else mesh_mod.stack_shards(acc, job.n_shards)

        hists, ledger, prov, flight = (stk(hists), stk(ledger),
                                       stk(prov), stk(flight))
    return hists, ledger, flight, prov


def _placement_map(job: EpochJob, *, payload=None):
    """The shared :class:`~dmclock_tpu.lifecycle.placement
    .PlacementMap` of a mesh churn job with ``placement != "static"``
    -- None otherwise (the static path must stay byte-identical to
    the pre-placement mesh, so no map is even built).  Pins and
    overrides re-derive from the job config; the assignment array,
    placement RNG, counters, move log, and deferred list restore from
    the ``pm_*`` checkpoint leaves when a payload is given."""
    from ..lifecycle import placement as placement_mod

    mode, overrides = placement_mod.parse_placement(job.placement)
    if mode == "static" or job.churn is None \
            or job.engine_loop != "mesh":
        return None
    pm = placement_mod.PlacementMap(
        job.n_shards, int(job.churn["total_ids"]), mode=mode,
        seed=job.seed,
        pins=placement_mod.placement_pins(job.churn, job.n_shards),
        overrides=overrides)
    if payload is not None:
        pm.load(payload)
    return pm


def _mesh_planes(job: EpochJob, *, tracer=None, payload=None,
                 pm=None):
    """The per-shard lifecycle planes of a mesh churn job (client ids
    routed by the shared placement map ``pm`` when one exists, else
    by ``cid % n_shards`` -- ``lifecycle.slots.owner_shard``), fresh
    or restored from the namespaced ``lc_s{s}_*`` checkpoint leaves.
    Planes run WITHOUT a workdir: the admin WAL/API surface is
    single-shard, mesh churn is scripted-events-only (routing live
    control ops per shard is the remaining rack-scheduling item)."""
    from ..lifecycle.plane import LifecyclePlane

    planes = []
    for s in range(job.n_shards):
        if payload is not None:
            pre = f"lc_s{s}_"
            sub = {"lc_" + k[len(pre):]: v
                   for k, v in payload.items() if k.startswith(pre)}
            planes.append(LifecyclePlane.load(
                sub, job.churn, tracer=tracer,
                shard=(s, job.n_shards)))
        else:
            planes.append(LifecyclePlane(
                job.churn, tracer=tracer, shard=(s, job.n_shards)))
        if pm is not None:
            planes[-1].attach_placement(pm)
    return planes


def _payload_like(job: EpochJob) -> dict:
    from ..lifecycle.plane import LifecyclePlane
    from ..obs import device as obsdev

    hists, ledger, flight, prov = _tele_init(job)
    mesh = None
    if job.engine_loop == "mesh":
        from ..parallel import mesh as mesh_mod

        n0 = int(job.churn["capacity0"]) \
            if job.churn is not None else job.n
        mesh = mesh_mod.counter_init(job.n_shards, n0)
    # the SLO leaves' template stays the empty-leaf shape even for
    # with_slo jobs: their axis-0 sizes are runtime state (ring fill,
    # contract count), so such jobs restore with the axis-0-only
    # relaxation (trailing dims still gate) -- see _job_loop
    plane = None
    pm = _placement_map(job)
    if job.churn is not None:
        plane = _mesh_planes(job, pm=pm) \
            if job.engine_loop == "mesh" \
            else LifecyclePlane(job.churn)
    tmpl = _payload(job, _job_state(job),
                    np.random.Generator(np.random.PCG64(job.seed)),
                    np.zeros(obsdev.NUM_METRICS, dtype=np.int64),
                    b"\x00" * 32, 0, 0,
                    DegradationLadder().encode(),
                    hists=hists, ledger=ledger, flight=flight,
                    prov=prov, mesh=mesh, plane=plane, pm=pm)
    if job.engine_loop == "mesh" and job.with_slo:
        # a mesh job's saved window block is the STACKED per-shard
        # [S, N, W_FIELDS] layout -- the template must carry the rank
        # and trailing dims (axis 0 stays relaxed like every slo leaf)
        from ..obs import slo as obsslo

        tmpl["slo_window"] = np.zeros((0, job.n, obsslo.W_FIELDS),
                                      dtype=np.int64)
    return tmpl


def _slo_log_flush(slo_plane, slo_log, closed) -> None:
    """Append one roll's judged closed windows to the slo_log JSONL
    (fail-soft: telemetry must never kill the run) -- the ONE
    implementation both the round and the stream loop call right
    after their checkpoint commits, so the two durability
    disciplines cannot drift."""
    if not closed or not slo_log or slo_plane is None:
        return
    try:
        slo_plane.export_jsonl(slo_log, closed)
    except OSError as e:
        print(f"# supervisor: slo_log write failed: {e}",
              file=sys.stderr)


class _ScrapeCtl:
    """Scrape-endpoint lifecycle shared by the round and the stream
    loop: (re)bind at the loop's natural host points (every epoch for
    the round loop, every drained epoch for the stream loop), pin
    ephemeral ports, poll ``/healthz`` after a rebind, and honor the
    injector's port-loss points.  Host telemetry only -- deliberately
    outside the checkpointed state."""

    def __init__(self, port, start_epoch: int, on_bind=None):
        self.port = port
        self.start_epoch = start_epoch
        self.scrape = None
        self.rebinds = 0
        # called with the server after EVERY successful (re)bind --
        # how a churn job's admin control API (lifecycle.api) rides
        # the endpoint across port-loss faults: mounts are per-server,
        # so a rebind must re-mount
        self.on_bind = on_bind

    def tick(self, epoch: int, injector) -> None:
        from ..obs.registry import start_http_server

        if self.port is not None and self.scrape is None:
            self.scrape = start_http_server(port=self.port)
            if self.scrape is not None:
                self.port = self.scrape.port   # pin ephemeral binds
                if self.on_bind is not None:
                    self.on_bind(self.scrape)
                if epoch > self.start_epoch:
                    self.rebinds += 1
                    # a rebind is only a recovery if the new endpoint
                    # actually serves: poll /healthz (best-effort --
                    # telemetry must never kill the run it observes)
                    if not _healthz_ok(self.scrape):
                        print("# supervisor: scrape rebind on "
                              f"port {self.scrape.port} failed its "
                              "healthz probe", file=sys.stderr)
        if injector is not None and injector.drop_scrape(epoch) \
                and self.scrape is not None:
            self.scrape.close()      # the plan yanks the port; the
            self.scrape = None       # loop rebinds next tick

    def close(self) -> None:
        if self.scrape is not None:
            self.scrape.close()
            self.scrape = None


def _draw_counts(rng: np.random.Generator, job: EpochJob,
                 epochs: int) -> np.ndarray:
    """RAW per-epoch Poisson draws ``int32[epochs, N]`` in epoch order
    -- the identical ``rng.poisson(lam, n)`` consumption sequence the
    round loop makes, so pre-generating a chunk ahead (the double
    buffer) advances the generator exactly as per-epoch draws would."""
    return np.stack([rng.poisson(job.arrival_lam, job.n)
                     .astype(np.int32) for _ in range(epochs)])


_INGEST_JIT_CACHE: dict = {}


def _jit_ingest(job: EpochJob):
    """Jitted superwave ingest for this job's static shape (the
    engine/queue.py module-cache convention)."""
    key = (job.n, job.ring, job.waves, job.dt_epoch_ns)
    if key not in _INGEST_JIT_CACHE:
        import jax.numpy as jnp

        from ..engine import kernels
        from ..obs import compile_plane as _cplane

        waves, dt_wave = job.waves, job.dt_epoch_ns // job.waves
        cost = jnp.ones((job.n,), dtype=jnp.int64)

        def ingest(st, counts, t_base):
            wave_times = t_base + jnp.arange(waves,
                                             dtype=jnp.int64) * dt_wave
            return kernels.ingest_superwave(st, counts, wave_times,
                                            cost, cost, cost,
                                            anticipation_ns=0)

        _INGEST_JIT_CACHE[key] = _cplane.instrumented_jit(
            ingest, cache="supervisor.ingest", entry=key)
    return _INGEST_JIT_CACHE[key]


def _prov_extras(prov):
    """The provenance plane's lifecycle-boundary riders: the per-slot
    last_served watermark rides grow/evict/compact with fill 0 (=
    never served), so a recycled slot's new tenant inherits no serve
    history.  margin_hist and scal are population aggregates, not
    per-slot arrays -- they pass through boundaries untouched."""
    return None if prov is None else [(prov.last_served, 0)]


def _prov_restamp(prov, extras):
    if prov is None:
        return None
    from ..obs import provenance as obsprov

    return obsprov.prov_from_arrays(prov.margin_hist, prov.scal,
                                    extras[0][0])


def _boundary_with_prov(plane, state, b, every, ledger, slo_block,
                        prov):
    """One lifecycle boundary with every rider the supervisor carries
    (ledger, SLO block, provenance watermark) -- the single unpack
    point the round and stream loops share, so the extras discipline
    cannot drift between them."""
    extras = _prov_extras(prov)
    out = plane.boundary(state, b, every, ledger=ledger,
                         slo_block=slo_block, extras=extras)
    state, ledger = out[0], out[1]
    i = 2
    if slo_block is not None:
        slo_block = out[i]
        i += 1
    if extras is not None:
        prov = _prov_restamp(prov, out[i])
    return state, ledger, slo_block, prov


def _ctl_compact(plane, state, ledger, slo_block, prov, b: int):
    """The controller's ``compact`` actuation: an out-of-band
    compaction through the lifecycle plane's own boundary transform
    (digest-neutral by the PR-11 gate -- the chain digest hashes
    canonical client-id views).  Runs BEFORE the boundary's
    checkpoint save, so the snapshot holds the compacted layout and
    a replayed decision re-compacts the replayed layout
    deterministically."""
    extras = _prov_extras(prov)
    out = plane.force_compact(state, ledger=ledger,
                              slo_block=slo_block, extras=extras, b=b)
    state, ledger = out[0], out[1]
    i = 2
    if slo_block is not None:
        slo_block = out[i]
        i += 1
    if extras is not None:
        prov = _prov_restamp(prov, out[i])
    return state, ledger, slo_block, prov


def _job_loop(job: EpochJob, workdir: Optional[str],
              injector: Optional[HostFaultInjector]
              ) -> SupervisedResult:
    """Run the job to completion once (restore -> epochs -> return).
    ``workdir=None`` is the BARE runner: no restore, no checkpoints,
    no injector -- the uninterrupted reference the digest gate
    compares against."""
    import jax
    import jax.numpy as jnp

    from ..obs import device as obsdev
    from ..obs import spans as _spans

    from ..obs import flight as obsflight

    from ..lifecycle.placement import parse_placement
    _pl_mode, _ = parse_placement(job.placement)   # validates the spec
    if _pl_mode != "static" and (job.engine_loop != "mesh"
                                 or job.churn is None):
        raise ValueError(
            "EpochJob(placement='p2c') is the mesh churn placement "
            "plane (engine_loop='mesh' + churn=...): power-of-two-"
            "choices needs per-shard pressure to choose between and "
            "an open population to place")
    if job.engine_loop == "mesh":
        if job.churn is not None and job.with_slo:
            raise ValueError(
                "EpochJob(engine_loop='mesh', churn=...) does not "
                "compose with with_slo yet: the cluster-wide "
                "window_mesh_reduce table is slot-indexed, and "
                "per-shard slot layouts diverge under churn -- the "
                "merge needs an id-space scatter first")
        if job.churn is not None and job.fault_plan is not None \
                and _pl_mode == "static":
            raise ValueError(
                "EpochJob(engine_loop='mesh') does not compose churn "
                "with fault_plan under placement='static': a static "
                "map has no answer for a registration routed to a "
                "DOWN shard.  placement='p2c' does (re-route to the "
                "live sampled choice, defer one boundary when both "
                "are down) -- pass placement='p2c'")
        if job.n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, "
                             f"got {job.n_shards}")
        if job.churn is not None and \
                job.churn.get("scenario") == "shard_skew" and \
                int(job.churn.get("n_shards", 0)) != job.n_shards:
            # the spec's hot-shard mask is cid % spec.n_shards; a
            # mismatched job would silently smear the melt across
            # shards instead of concentrating it on one
            raise ValueError(
                f"shard_skew spec was built for "
                f"n_shards={job.churn.get('n_shards')} but the job "
                f"runs {job.n_shards} shards -- pass "
                f"make_spec('shard_skew', n_shards={job.n_shards})")
    if job.fault_plan is not None:
        if job.engine_loop != "mesh":
            raise ValueError(
                "EpochJob(fault_plan=...) is the in-chunk mesh fault "
                "model (engine_loop='mesh'); the round/stream loops "
                "inject faults through robust.cluster.run_with_plan")
        from .faults import parse_fault_spec
        # parse_fault_spec accepts dicts AND "seed=7,p_dropout=.."
        # strings (the bench --fault-plan form); a plain LABEL parses
        # to None and is rejected here -- a label cannot seed a plan
        if parse_fault_spec(job.fault_plan) is None:
            raise ValueError(f"fault_plan spec did not parse: "
                             f"{job.fault_plan!r} (expected keys like "
                             f"seed=.., p_dropout=..)")
    state = _job_state(job)
    rng = np.random.Generator(np.random.PCG64(job.seed))
    met = np.zeros(obsdev.NUM_METRICS, dtype=np.int64)
    digest = b"\x00" * 32
    start_epoch = 0
    decisions = 0
    tracer = _spans.SpanTracer() if job.span_log else None
    if tracer is not None:
        # compile records ride the SAME per-incarnation span stream
        # (category "compile"), so they flush with the span_log at
        # checkpoint boundaries -- the rotation checkpoints'
        # durability window (docs/OBSERVABILITY.md capacity plane).
        # Compile walls are host-side per-incarnation facts, like
        # every other span: deliberately outside the checkpointed
        # state and the crash-equivalence comparison.
        from ..obs import compile_plane as _cplane
        _cplane.plane().set_tracer(tracer)
    ladder = DegradationLadder(enabled=job.ladder,
                               threshold=job.ladder_threshold,
                               tracer=tracer)
    hists, ledger, flight, prov = _tele_init(job)
    ckpt_dir = os.path.join(workdir, "ckpt") if workdir else None

    # the closed-loop controller (control/; docs/CONTROLLER.md):
    # built before the restore so ctl.load can pick up the applied
    # cursor/knobs/policy state from the checkpoint while the journal
    # (loaded from the workdir in the constructor) supplies the
    # decisions to replay
    from ..control import Controller, as_spec as _ctl_as_spec
    ctl = None
    _ctl_spec = _ctl_as_spec(job.controller)
    if _ctl_spec is not None:
        ctl = Controller(
            _ctl_spec, n=job.n, ring=job.ring,
            counter_sync_every=job.counter_sync_every,
            capacity0=int(job.churn["capacity0"])
            if job.churn is not None else 0,
            n_shards=job.n_shards,
            workdir=workdir)

    payload = None
    resumed_from = None
    if ckpt_dir is not None and ckpt_mod.rotation_paths(ckpt_dir):
        # a non-empty rotation means a previous incarnation died:
        # resume from the newest INTACT snapshot (walks past any
        # torn/corrupted-by-plan entries).  EVERY entry torn is the
        # worst case, not a dead end: replay from scratch is
        # deterministic, so the run stays crash-equivalent -- it just
        # pays the full recompute.
        try:
            with _spans.span(tracer, "supervisor.resume",
                             "checkpoint"):
                # churn payloads hold grow-on-demand arrays (engine
                # state, ledger, slot map, journals) whose capacities
                # the fresh template cannot predict -- dtype+rank
                # checked, shapes from the file (utils.checkpoint).
                # SLO payloads relax the same way: the ring fill and
                # contract count are runtime state (axis 0 only;
                # trailing dims -- RING_COLS, W_FIELDS -- still gate)
                payload, resumed_from = \
                    ckpt_mod.restore_pytree_rotating(
                        ckpt_dir, _payload_like(job),
                        strict_shapes=job.churn is None
                        and not job.with_slo)
        except ckpt_mod.CheckpointCorruptError:
            payload = None
    if payload is not None:
        # durable resume journal: MET_SUPERVISOR_RESUMES counts
        # restarts that actually restored a snapshot -- a
        # replay-from-scratch restart (all snapshots torn) is a
        # RESTART but not a RESUME, and the metric exists to tell the
        # two apart
        with open(os.path.join(workdir, RESUME_LOG), "a") as fh:
            fh.write(f"{resumed_from}\n")
        state = payload["engine"]
        rng = _rng_from_array(payload["rng"])
        met = np.asarray(jax.device_get(payload["metrics"]),
                         dtype=np.int64).copy()
        digest = np.asarray(payload["digest"],
                            dtype=np.uint8).tobytes()
        start_epoch = int(payload["epoch"])
        decisions = int(payload["decisions"])
        ladder.load(jax.device_get(payload["ladder"]))
        # telemetry resumes from the snapshot too -- that is what
        # makes crash equivalence extend to the telemetry plane
        if job.with_hists:
            hists = jnp.asarray(payload["tele_hists"])
        if job.with_ledger:
            ledger = jnp.asarray(payload["tele_ledger"])
        if job.flight_records:
            flight = obsflight.flight_from_arrays(
                payload["tele_flight_buf"],
                payload["tele_flight_seq"],
                payload["tele_flight_batch"])
        if job.with_prov:
            from ..obs import provenance as obsprov
            # works for the stacked per-shard mesh blocks too --
            # jnp.asarray keeps the [S, ...] leading axis
            prov = obsprov.prov_from_arrays(
                payload["prov_margin_hist"], payload["prov_scal"],
                payload["prov_last_served"])
        if ctl is not None:
            # the applied cursor can only TRAIL the journal (fsync-
            # before-apply), so loading both re-arms the replay path
            # for every journaled-but-unapplied decision
            ctl.load(payload)

    mesh_ctrs = None
    if job.engine_loop == "mesh":
        from ..parallel import mesh as mesh_mod
        if payload is not None:
            mesh_ctrs = tuple(
                jnp.asarray(payload[k])
                for k in ("mesh_cd", "mesh_cr", "mesh_vd", "mesh_vr"))
        else:
            # per-slot counters follow the SLOT layout: a churn job's
            # slots start at the spec's capacity0 and grow/permute
            # with each shard's boundary (the extras discipline)
            n0 = int(job.churn["capacity0"]) \
                if job.churn is not None else job.n
            mesh_ctrs = mesh_mod.counter_init(job.n_shards, n0)

    plane = None
    mesh_planes = None
    pm = None
    if job.churn is not None and job.engine_loop == "mesh":
        pm = _placement_map(job, payload=payload)
        mesh_planes = _mesh_planes(job, tracer=tracer,
                                   payload=payload, pm=pm)
    elif job.churn is not None:
        from ..lifecycle.plane import LifecyclePlane
        if payload is not None:
            plane = LifecyclePlane.load(payload, job.churn,
                                        workdir=workdir, tracer=tracer)
        else:
            plane = LifecyclePlane(job.churn, workdir=workdir,
                                   tracer=tracer)

    # the SLO plane (obs.slo): window block + contract-epoch/ring host
    # state + burn-rate evaluator.  Window rolls happen ONLY at the
    # ckpt_every boundary grid below, in bare and supervised runs
    # alike -- the zero-host-fault gate compares their rings.
    slo_block = slo_plane = slo_eval = None
    slo_w0 = start_epoch
    if job.with_slo:
        import jax.numpy as _jnp

        from ..obs import slo as obsslo
        from ..obs.alerts import SloEvaluator

        if payload is not None:
            slo_block = _jnp.asarray(payload["slo_window"])
            # shape[-2] not [0]: a mesh job's block is the stacked
            # per-shard [S, N, W_FIELDS] layout
            slo_plane = obsslo.SloPlane.load(
                payload, capacity=int(slo_block.shape[-2]),
                dt_epoch_ns=job.dt_epoch_ns,
                ring_depth=max(job.slo_ring, 1))
            slo_eval = SloEvaluator(slo_plane)
            slo_eval.load(payload)
        else:
            n0 = int(job.churn["capacity0"]) if job.churn is not None \
                else job.n
            slo_plane = obsslo.SloPlane(n0,
                                        dt_epoch_ns=job.dt_epoch_ns,
                                        ring_depth=job.slo_ring)
            slo_block = obsslo.window_zero(n0)
            if job.churn is None:
                # closed population: every slot is a client with a
                # fixed contract, registered once from the device
                # truth (the inverse-rate arrays; a mesh job reads
                # shard 0 -- every partition shares one contract
                # layout, and the rolled table aggregates the S
                # like-contracted clients per slot)
                inv = state
                if job.engine_loop == "mesh":
                    from ..parallel import mesh as mesh_mod
                    inv = mesh_mod.unstack_shard(state)
                slo_plane.register_from_inv(
                    inv.resv_inv, inv.weight_inv, inv.limit_inv)
                slo_block = slo_plane.stamp(slo_block)
            if job.engine_loop == "mesh":
                # every shard carries its own block; the plane rolls
                # the window_mesh_reduce merge (cluster-wide table)
                from ..parallel import mesh as mesh_mod
                slo_block = mesh_mod.stack_shards(slo_block,
                                                  job.n_shards)
            slo_eval = SloEvaluator(slo_plane)
        if plane is not None:
            # lifecycle REGISTER/UPDATE/EVICT bump contract epochs
            # through the plane's boundary (docs/LIFECYCLE.md)
            plane.attach_slo(slo_plane)

    def _slo_roll(state_now, e1: int):
        """Close the window ending at boundary ``e1`` and judge it;
        returns the rows to flush AFTER the checkpoint commits."""
        nonlocal slo_block, slo_w0
        cid_of_slot = plane.slots.cid_of_slot if plane is not None \
            else None
        slo_block, closed = slo_plane.roll(
            slo_block, slo_w0, e1, cid_of_slot=cid_of_slot,
            depth=state_now.depth)
        slo_w0 = e1
        slo_eval.observe_roll(closed)
        return closed

    if ctl is not None:
        # pin the delta baselines to the RESTORED accumulators: the
        # previous boundary's snapshot is exactly what the killed
        # incarnation's controller last observed, so replayed
        # boundaries recollect identical signal deltas
        ctl.observe_baseline(met=met, slo_eval=slo_eval)
        from ..control import publish_controller
        from ..obs.registry import default_registry
        publish_controller(default_registry(), ctl)

    on_bind = None
    if plane is not None or slo_eval is not None:
        def on_bind(server, _plane=plane):
            # live control surface: the admin API (POST/PUT/DELETE
            # /clients...) + lifecycle counters ride the supervised
            # run's own scrape endpoint, re-mounted on every rebind.
            # Ops accepted here are WAL-fsynced (the plane has the
            # workdir), so a SIGKILL between accept and the epoch
            # boundary still applies them exactly once on resume.
            if _plane is not None:
                from ..lifecycle.api import mount_admin_api
                mount_admin_api(server, _plane, slo=slo_plane)
            if slo_eval is not None:
                from ..obs.alerts import mount_slo_api
                mount_slo_api(server, slo_eval)
    scr = _ScrapeCtl(job.metrics_port, start_epoch, on_bind)
    base_cfg = {"select_impl": job.select_impl,
                "tag_width": job.tag_width,
                "calendar_impl": job.calendar_impl}
    stream_fallbacks = 0

    if job.engine_loop == "stream":
        return _stream_epochs(job, injector, ckpt_dir, scr,
                              base_cfg, state, rng, met, digest,
                              start_epoch, decisions, ladder, tracer,
                              hists, ledger, flight, prov,
                              resumed_from, plane, slo_block,
                              slo_plane, slo_eval, ctl)
    if job.engine_loop == "mesh":
        return _mesh_epochs(job, injector, ckpt_dir, scr, base_cfg,
                            state, rng, met, digest, start_epoch,
                            decisions, ladder, tracer, hists, ledger,
                            flight, prov, resumed_from, slo_block,
                            slo_plane, slo_eval, mesh_ctrs,
                            mesh_planes, ctl, pm)
    assert job.engine_loop == "round", job.engine_loop
    ingest = _jit_ingest(job) \
        if job.arrival_lam > 0 and plane is None else None
    if plane is not None:
        from ..engine import stream as stream_mod
        from ..lifecycle import churn as churn_mod
        # the stream chunk's standalone ingest leg: the admission
        # clamp runs ON DEVICE with the identical integer math, so a
        # churn job's round loop is bit-identical to its stream loop
        churn_ingest = stream_mod.jit_ingest_step(
            dt_epoch_ns=job.dt_epoch_ns, waves=job.waves)

    try:
        for epoch in range(start_epoch, job.epochs):
            # epoch span entered/exited explicitly: the loop body
            # stays flat, and a crash mid-epoch simply leaves the span
            # open -- the tracer dies with the incarnation and the
            # flushed stream keeps every COMPLETED epoch (the same
            # at-most-one-epoch-lost window as the checkpoints)
            _ep_span = _spans.span(tracer, "supervisor.epoch",
                                   "host_prep", epoch=epoch)
            _ep_span.__enter__()
            scr.tick(epoch, injector)

            # lifecycle boundary: registration / QoS updates / idle
            # eviction / compaction apply BEFORE the window they
            # precede, on the ckpt_every grid (= the stream loop's
            # chunk grid), so a resume replaying this epoch re-applies
            # the identical ops from the checkpointed plane state
            if plane is not None and epoch % job.ckpt_every == 0:
                with _spans.span(tracer, "lifecycle.boundary",
                                 "host_prep", epoch=epoch):
                    state, ledger, slo_block, prov = \
                        _boundary_with_prov(plane, state, epoch,
                                            job.ckpt_every, ledger,
                                            slo_block, prov)

            t_base = jnp.int64(epoch * job.dt_epoch_ns)
            if plane is not None:
                with _spans.span(tracer, "supervisor.ingest",
                                 "ingest"):
                    raw = rng.poisson(churn_mod.lam_vector(
                        job.churn, epoch)).astype(np.int32)
                    counts = plane.map_counts(raw)
                    if ctl is not None:
                        # admission clamp AFTER the draws: the RNG
                        # consumption never depends on the knob, so
                        # controller on/off replays one arrival stream
                        counts = ctl.clamp_counts(counts, job.waves)
                    state = churn_ingest(
                        state, jnp.asarray(counts), t_base)
            elif ingest is not None:
                with _spans.span(tracer, "supervisor.ingest",
                                 "ingest"):
                    headroom = job.ring - np.asarray(
                        jax.device_get(state.depth), dtype=np.int64)
                    counts = np.minimum(
                        rng.poisson(job.arrival_lam, job.n),
                        np.minimum(headroom, job.waves)
                    ).astype(np.int32)
                    if ctl is not None:
                        counts = ctl.clamp_counts(counts, job.waves)
                    state = ingest(state, jnp.asarray(counts), t_base)
            while True:
                cfg = ladder.apply(ctl.overlay(base_cfg)
                                   if ctl is not None else base_cfg)
                try:
                    ep = run_epoch_guarded(
                        state,
                        epoch * job.dt_epoch_ns + job.dt_epoch_ns,
                        engine=job.engine, m=job.m, k=job.k,
                        chain_depth=job.chain_depth, with_metrics=True,
                        select_impl=cfg["select_impl"],
                        tag_width=cfg["tag_width"],
                        calendar_impl=cfg["calendar_impl"],
                        ladder_levels=job.ladder_levels,
                        wheel_kernel=job.wheel_kernel,
                        hists=hists, ledger=ledger, flight=flight,
                        slo=slo_block, prov=prov, tracer=tracer)
                    break
                except RECOVERABLE_ERRORS:
                    # bounded retries EXHAUSTED inside the guarded
                    # runner -- the ladder's launch-failure signal
                    # (recovered retries, ep.retries > 0, are NOT an
                    # escalation: the launch succeeded).  Each failed
                    # ATTEMPT counts toward the threshold, so the
                    # escalation is reachable at any threshold:
                    # below it the same path is re-attempted, at it a
                    # rung steps down, and with nothing left to
                    # concede (or the ladder off) the error surfaces
                    # to the supervisor's restart loop -- at most
                    # threshold * rungs attempts per epoch.
                    if not ladder.can_step(cfg):
                        raise
                    met[obsdev.MET_LADDER_STEPS] += \
                        ladder.note_epoch(cfg, launch_failures=1)
            state = ep.state
            decisions += ep.count
            if job.with_hists:
                hists = ep.hists
            if job.with_ledger:
                ledger = ep.ledger
            if job.flight_records:
                flight = ep.flight
            if job.with_prov:
                prov = ep.prov
            if job.with_slo:
                slo_block = ep.slo
            with _spans.span(tracer, "supervisor.digest", "drain"):
                # churn digests hash the CANONICAL client-id-space
                # views: slot layout (registration timing, recycling,
                # growth, compaction) must be digest-neutral
                digest = _digest_update(
                    digest, plane.canon_results(ep.results)
                    if plane is not None else ep.results)
                for r in ep.results:
                    if hasattr(r, "metrics"):
                        met = obsdev.metrics_combine_np(
                            met, jax.device_get(r.metrics))
            stepped = ladder.note_epoch(
                cfg,
                guard_trips=ep.rebase_fallbacks + ep.serial_fallbacks)
            met[obsdev.MET_LADDER_STEPS] += stepped

            if injector is not None:
                injector.after_decisions(decisions)
            at_boundary = ((epoch + 1) % job.ckpt_every == 0
                           or epoch + 1 == job.epochs)
            closed = None
            if slo_plane is not None and at_boundary:
                # the window roll happens in BARE and supervised runs
                # alike (same grid), BEFORE the snapshot: the saved
                # block is a freshly-opened window and the ring
                # already holds what this boundary closed
                closed = _slo_roll(state, epoch + 1)
            if ctl is not None and at_boundary:
                # the controller boundary: collect one typed signal
                # snapshot, run the guarded-transition policy (journal
                # fsyncs before every apply; a resumed run replays),
                # then actuate -- all BEFORE the snapshot, so the
                # checkpoint holds the post-actuation knobs/state
                sig = ctl.collect(
                    epoch + 1, state=state, met=met,
                    slo_eval=slo_eval, prov=prov,
                    planes=None if plane is None else [plane])
                fired = ctl.step(
                    epoch + 1, sig,
                    fault=None if injector is None
                    else injector.controller_point)
                if "compact" in fired and plane is not None:
                    state, ledger, slo_block, prov = _ctl_compact(
                        plane, state, ledger, slo_block, prov,
                        epoch + 1)
            if ckpt_dir is not None and at_boundary:
                with _spans.span(tracer, "supervisor.checkpoint_save",
                                 "checkpoint", epoch=epoch + 1):
                    payload = _payload(job, state, rng, met, digest,
                                       epoch + 1, decisions,
                                       ladder.encode(), hists=hists,
                                       ledger=ledger, flight=flight,
                                       prov=prov, plane=plane,
                                       slo=None if slo_plane is None
                                       else (slo_block, slo_plane,
                                             slo_eval), ctl=ctl)

                    def save(payload=payload):
                        return ckpt_mod.save_pytree_rotating(
                            ckpt_dir, payload, keep=job.keep)

                    if injector is not None:
                        injector.around_save(epoch, save)
                    else:
                        save()
                _ep_span.__exit__(None, None, None)
                # flush spans ONLY at checkpoint boundaries, right
                # after the snapshot commits: a resume replays from
                # the last checkpoint, so any span flushed PAST it
                # would appear twice in the stream after a
                # crash+resume (replayed epochs re-record).  Spans and
                # checkpoints share one durability window by
                # construction: what is flushed is exactly what will
                # never be replayed.  The slo_log flush follows the
                # same discipline: windows flushed after the save are
                # exactly the ones a resume will never re-close.
                if tracer is not None:
                    tracer.drain_jsonl(job.span_log)
                _slo_log_flush(slo_plane, job.slo_log, closed)
            else:
                _ep_span.__exit__(None, None, None)
                if ckpt_dir is None:
                    _slo_log_flush(slo_plane, job.slo_log, closed)
                if tracer is not None and ckpt_dir is None:
                    # bare/unsupervised runner: nothing ever replays,
                    # per-epoch flushes are safe
                    tracer.drain_jsonl(job.span_log)
    except BaseException:
        # the crash hook: dump the flight ring's last R commit
        # records before the incarnation dies (--flight-dump).  Best
        # effort -- the dump must never mask the original error.
        if job.flight_dump and flight is not None:
            try:
                n = obsflight.flight_dump(flight, job.flight_dump)
                print(f"# supervisor: dumped {n} flight records to "
                      f"{job.flight_dump}", file=sys.stderr)
            except Exception:
                pass
        # deliberately NO span flush here: rows recorded since the
        # last checkpoint boundary describe epochs a resume will
        # REPLAY, and flushing them would double-count those epochs
        # in the stream.  Un-flushed spans die with the incarnation --
        # exactly the checkpoint durability window the span_log
        # contract documents.
        raise
    finally:
        scr.close()

    if tracer is not None:   # e.g. a resume landing past the last
        tracer.drain_jsonl(job.span_log)  # epoch records only the
    #                                       resume span
    return _build_result(job, state, digest, decisions, met, ladder,
                         scr.rebinds, resumed_from, hists, ledger,
                         flight, stream_fallbacks, plane,
                         slo_block, slo_plane, slo_eval, prov,
                         ctl=ctl)


def _build_result(job, state, digest, decisions, met, ladder,
                  scrape_rebinds, resumed_from, hists, ledger, flight,
                  stream_fallbacks: int, plane=None,
                  slo_block=None, slo_plane=None,
                  slo_eval=None, prov=None, mesh=None,
                  mesh_fallbacks: int = 0,
                  mesh_chaos_fallbacks: int = 0,
                  ctl=None, pm=None) -> SupervisedResult:
    import jax

    slo_kw = {}
    if ctl is not None:
        slo_kw.update(
            controller_decisions=int(ctl.applied),
            controller_replays=int(ctl.replays),
            controller_knobs=[int(k) for k in ctl.knobs],
            controller_trajectory=ctl.trajectory())
    if pm is not None:
        slo_kw.update(
            placement=pm.mode,
            migrations=int(pm.counters["migrations"]),
            migration_log=pm.move_log(),
            placement_counters={k: int(v)
                                for k, v in pm.counters.items()})
    if mesh is not None and job.n_shards == 1:
        # S=1 canonicalization: a 1-shard mesh IS a single engine, so
        # the result (state digest, telemetry blocks, window block,
        # flight ring) drops the unit shard axis and the bit-identity
        # gate against the round/stream loops compares like for like
        from ..parallel import mesh as mesh_mod

        state = mesh_mod.unstack_shard(state)
        hists = None if hists is None else hists[0]
        ledger = None if ledger is None else ledger[0]
        prov = None if prov is None else mesh_mod.unstack_shard(prov)
        flight = None if flight is None \
            else mesh_mod.unstack_shard(flight)
        if slo_block is not None:
            slo_block = slo_block[0]
    elif mesh is not None and flight is not None:
        # S>1: merge the per-shard rings in DETERMINISTIC shard order
        # at drain -- each shard's valid rows in seq order, shards
        # concatenated 0..S-1 (obs.flight.flight_merge_stacked); the
        # crash-equivalence gate compares the merged rows, seq is the
        # cluster total
        from ..obs import flight as obsflight

        buf, seq = obsflight.flight_merge_stacked(flight)
        flight = obsflight.FlightState(
            buf=buf, seq=seq, batch=np.asarray(
                jax.device_get(flight.batch)).sum())
    if mesh is not None:
        cd, cr, vd, vr = [np.asarray(jax.device_get(x),
                                     dtype=np.int64) for x in mesh]
        slo_kw.update(mesh_counters=np.stack([cd, cr]),
                      mesh_views=np.stack([vd, vr]),
                      mesh_fallbacks=mesh_fallbacks,
                      mesh_chaos_fallbacks=mesh_chaos_fallbacks)
    if prov is not None:
        slo_kw.update(
            prov_margin_hist=np.asarray(
                jax.device_get(prov.margin_hist), dtype=np.int64),
            prov_scal=np.asarray(jax.device_get(prov.scal),
                                 dtype=np.int64),
            prov_last_served=np.asarray(
                jax.device_get(prov.last_served), dtype=np.int64))
    if slo_plane is not None:
        enc = slo_plane.encode()
        # update, never rebind: the provenance entries added above
        # must survive a job that runs BOTH planes
        slo_kw.update(
            slo_window=np.asarray(jax.device_get(slo_block),
                                  dtype=np.int64),
            slo_ring=enc["slo_ring"],
            slo_cepoch=enc["slo_cepoch"],
            slo=slo_eval.summary())
    if isinstance(plane, (list, tuple)):
        # mesh churn: one snapshot per shard (deterministic, so the
        # crash-equivalence dict compare still bites) + the cluster
        # rollup the bench/result consumers read
        shots = [p.snapshot() for p in plane]
        lifecycle = {
            "live_clients": sum(s["live_clients"] for s in shots),
            "peak_clients": sum(s["peak_clients"] for s in shots),
            "capacity": sum(s["capacity"] for s in shots),
            **{key: sum(s[key] for s in shots)
               for key in shots[0]
               if key not in ("live_clients", "peak_clients",
                              "capacity", "pending_ops")},
            "pending_ops": sum(s["pending_ops"] for s in shots),
            "shards": shots,
        }
    else:
        lifecycle = plane.snapshot() if plane is not None else None
    return SupervisedResult(
        **slo_kw,
        lifecycle=lifecycle,
        digest=hashlib.sha256(digest).hexdigest(),
        state_digest=_tree_digest(state),
        decisions=decisions, epochs=job.epochs,
        metrics=met, restarts=0,
        ladder_steps=ladder.describe(),
        scrape_rebinds=scrape_rebinds,
        resumed_from=resumed_from,
        hists=None if hists is None
        else np.asarray(jax.device_get(hists), dtype=np.int64),
        ledger=None if ledger is None
        else np.asarray(jax.device_get(ledger), dtype=np.int64),
        flight_buf=None if flight is None
        else np.asarray(jax.device_get(flight.buf), dtype=np.int64),
        flight_seq=0 if flight is None else int(flight.seq),
        stream_fallbacks=stream_fallbacks)


def _draw_counts_churn(rng: np.random.Generator, spec: dict,
                       e0: int, e1: int) -> np.ndarray:
    """RAW per-epoch Poisson draws for a churn spec,
    ``int32[e1 - e0, total_ids]`` in CLIENT-ID space and epoch order
    -- the identical consumption sequence in a dynamic run, its
    static variant, and both engine loops (the draw stays in id
    space; the slot mapping happens at the boundary, after the plane
    has applied it)."""
    from ..lifecycle import churn as churn_mod

    return np.stack([rng.poisson(churn_mod.lam_vector(spec, e))
                     .astype(np.int32) for e in range(e0, e1)])


def _stream_epochs(job: EpochJob, injector, ckpt_dir,
                   scr: _ScrapeCtl, base_cfg: dict, state, rng, met,
                   digest: bytes, start_epoch: int, decisions: int,
                   ladder, tracer, hists, ledger, flight, prov,
                   resumed_from, plane=None, slo_block=None,
                   slo_plane=None, slo_eval=None,
                   ctl=None) -> SupervisedResult:
    """The always-on streaming serve loop (docs/ENGINE.md
    "engine_loop"): one fused device launch per stream chunk (= the
    epochs between two PR-5 checkpoint boundaries), with the host
    pre-generating chunk T+1's superwave draws while the device runs
    chunk T and draining the HBM-accumulated decision stream /
    metrics / telemetry only at the boundary.

    Crash-equivalence discipline: the RNG state that rides each
    boundary's checkpoint is the snapshot taken right after THAT
    chunk's draws -- the double buffer's lookahead draws stay out of
    the persisted state, so a resumed incarnation re-draws them
    bit-identically.  The per-epoch drain bookkeeping (chain digest,
    metric fold, ladder notes, injector kill points) is the round
    loop's, run over the drained per-epoch rows in epoch order."""
    import jax

    from ..engine import stream as stream_mod
    from ..obs import device as obsdev
    from ..obs import flight as obsflight
    from ..obs import spans as _spans
    from .guarded import run_stream_chunk_guarded

    stream_fallbacks = 0
    do_ingest = job.arrival_lam > 0 or plane is not None
    slo_w0 = start_epoch
    try:
        counts = None
        rng_ckpt = _rng_state_array(rng)
        if do_ingest and start_epoch < job.epochs:
            with _spans.span(tracer, "stream.pregen", "host_prep"):
                e1 = next(stream_mod.chunk_bounds(
                    start_epoch, job.epochs, job.ckpt_every))[1]
                counts = _draw_counts_churn(
                    rng, job.churn, start_epoch, e1) \
                    if plane is not None \
                    else _draw_counts(rng, job, e1 - start_epoch)
            rng_ckpt = _rng_state_array(rng)
        for e0, b in stream_mod.chunk_bounds(start_epoch, job.epochs,
                                             job.ckpt_every):
            # bind/maintain the scrape endpoint BEFORE the fused
            # launch: the round loop serves /metrics from epoch 0, and
            # a first chunk can run for seconds -- the drain-time
            # per-epoch ticks below only honor the plan's port-loss
            # points (drop_scrape fires exactly once, so this pre-tick
            # cannot double-fire them)
            scr.tick(e0, injector)
            # lifecycle boundary at the chunk start: e0 is on the
            # ckpt_every grid by construction (chunk_bounds), so
            # lifecycle ops compose with the fused chunk by applying
            # only between launches -- the chunk itself never changes.
            # Slot mapping of the pre-generated ID-SPACE draws happens
            # HERE, after the boundary's registrations/evictions/
            # growth/compaction settled the layout for the chunk.
            if plane is not None:
                with _spans.span(tracer, "lifecycle.boundary",
                                 "host_prep", epoch=e0):
                    state, ledger, slo_block, prov = \
                        _boundary_with_prov(plane, state, e0,
                                            job.ckpt_every, ledger,
                                            slo_block, prov)
                counts_dev = plane.map_counts(counts)
            else:
                counts_dev = counts
            if ctl is not None and counts_dev is not None:
                # the whole chunk admits under the knob set at ITS
                # starting boundary -- exactly the per-epoch clamp the
                # round loop applies, because the knob only moves at
                # the controller boundaries (= the chunk grid)
                counts_dev = ctl.clamp_counts(counts_dev, job.waves)
            # the double buffer: chunk T+1's draws happen between the
            # chunk launch's dispatch and its device wait (the overlap
            # seam run_stream_chunk_guarded exposes).  Idempotent: a
            # retried launch must not re-advance the generator.
            nxt: dict = {}

            def overlap(b=b):
                if "rng" in nxt:
                    return
                if do_ingest and b < job.epochs:
                    with _spans.span(tracer, "stream.pregen",
                                     "host_prep"):
                        b1 = next(stream_mod.chunk_bounds(
                            b, job.epochs, job.ckpt_every))[1]
                        nxt["counts"] = _draw_counts_churn(
                            rng, job.churn, b, b1) \
                            if plane is not None \
                            else _draw_counts(rng, job, b1 - b)
                nxt["rng"] = _rng_state_array(rng)

            while True:
                cfg = ladder.apply(ctl.overlay(base_cfg)
                                   if ctl is not None else base_cfg)
                try:
                    g = run_stream_chunk_guarded(
                        state, e0, counts_dev, engine=job.engine,
                        epochs=b - e0, m=job.m, k=job.k,
                        chain_depth=job.chain_depth,
                        dt_epoch_ns=job.dt_epoch_ns, waves=job.waves,
                        with_metrics=True,
                        select_impl=cfg["select_impl"],
                        tag_width=cfg["tag_width"],
                        calendar_impl=cfg["calendar_impl"],
                        ladder_levels=job.ladder_levels,
                        wheel_kernel=job.wheel_kernel,
                        hists=hists, ledger=ledger, flight=flight,
                        slo=slo_block, prov=prov, tracer=tracer,
                        overlap=overlap)
                    break
                except RECOVERABLE_ERRORS:
                    # retries exhausted at stream-chunk granularity:
                    # the same ladder escalation as the round loop,
                    # re-attempting the chunk on the stepped-down
                    # config (overlap is idempotent, so the retry
                    # cannot re-advance the RNG)
                    if not ladder.can_step(cfg):
                        raise
                    met[obsdev.MET_LADDER_STEPS] += \
                        ladder.note_epoch(cfg, launch_failures=1)
            if "rng" not in nxt:
                overlap()     # e.g. every dispatch attempt failed
                #               fast; draw synchronously
            state = g.state
            if job.with_hists:
                hists = g.hists
            if job.with_ledger:
                ledger = g.ledger
            if job.flight_records:
                flight = g.flight
            if job.with_prov:
                prov = g.prov
            if job.with_slo:
                slo_block = g.slo
            stream_fallbacks += g.stream_fallback
            # the drain: per-epoch bookkeeping in epoch order, exactly
            # the round loop's sequence (digest -> metric fold ->
            # ladder note -> injector kill points), over the rows the
            # chunk accumulated in HBM
            with _spans.span(tracer, "stream.drain", "drain",
                             chunk=b - e0):
                for i in range(b - e0):
                    epoch = e0 + i
                    scr.tick(epoch, injector)
                    decisions += g.counts[i]
                    digest = _digest_update(
                        digest, plane.canon_results(g.epochs[i])
                        if plane is not None else g.epochs[i])
                    for r in g.epochs[i]:
                        if hasattr(r, "metrics") and \
                                r.metrics is not None:
                            met = obsdev.metrics_combine_np(
                                met, jax.device_get(r.metrics))
                    met[obsdev.MET_LADDER_STEPS] += ladder.note_epoch(
                        cfg, guard_trips=g.guard_trips[i])
                    if injector is not None:
                        injector.after_decisions(decisions)
            # the stream heartbeat: a drain-point instant the watchdog
            # reads as launch-cadence liveness (a fused chunk
            # legitimately runs for seconds with no dispatch span
            # completing -- docs/OBSERVABILITY.md)
            _spans.instant(tracer, "stream.heartbeat", "drain",
                           epoch=b)
            closed = None
            if slo_plane is not None:
                # b is a window boundary by construction: every chunk
                # ends on the ckpt_every grid (chunk_bounds), so the
                # stream loop rolls at exactly the round loop's points
                cid_of_slot = plane.slots.cid_of_slot \
                    if plane is not None else None
                slo_block, closed = slo_plane.roll(
                    slo_block, slo_w0, b, cid_of_slot=cid_of_slot,
                    depth=state.depth)
                slo_w0 = b
                slo_eval.observe_roll(closed)
            if ctl is not None:
                # b is a controller boundary by construction (= the
                # round loop's at_boundary grid): same collect ->
                # decide -> actuate sequence, before the save
                sig = ctl.collect(
                    b, state=state, met=met, slo_eval=slo_eval,
                    prov=prov,
                    planes=None if plane is None else [plane])
                fired = ctl.step(
                    b, sig, fault=None if injector is None
                    else injector.controller_point)
                if "compact" in fired and plane is not None:
                    state, ledger, slo_block, prov = _ctl_compact(
                        plane, state, ledger, slo_block, prov, b)
            if ckpt_dir is not None:
                # b is a checkpoint boundary by construction
                # (chunk_bounds); the persisted RNG state is rng_ckpt
                # -- the snapshot covering draws for epochs < b only
                with _spans.span(tracer, "supervisor.checkpoint_save",
                                 "checkpoint", epoch=b):
                    payload = _payload(job, state, rng_ckpt, met,
                                       digest, b, decisions,
                                       ladder.encode(), hists=hists,
                                       ledger=ledger, flight=flight,
                                       prov=prov, plane=plane,
                                       slo=None if slo_plane is None
                                       else (slo_block, slo_plane,
                                             slo_eval), ctl=ctl)

                    def save(payload=payload):
                        return ckpt_mod.save_pytree_rotating(
                            ckpt_dir, payload, keep=job.keep)

                    if injector is not None:
                        injector.around_save(b - 1, save)
                    else:
                        save()
                if tracer is not None:
                    tracer.drain_jsonl(job.span_log)
                _slo_log_flush(slo_plane, job.slo_log, closed)
            else:
                # bare/unsupervised runner: nothing ever replays,
                # per-chunk flushes are safe
                _slo_log_flush(slo_plane, job.slo_log, closed)
                if tracer is not None:
                    tracer.drain_jsonl(job.span_log)
            counts = nxt.get("counts")
            rng_ckpt = nxt["rng"]
    except BaseException:
        # the crash hook, as in the round loop: best-effort flight
        # dump, NO span flush (un-flushed spans describe epochs a
        # resume will replay)
        if job.flight_dump and flight is not None:
            try:
                n = obsflight.flight_dump(flight, job.flight_dump)
                print(f"# supervisor: dumped {n} flight records to "
                      f"{job.flight_dump}", file=sys.stderr)
            except Exception:
                pass
        raise
    finally:
        scr.close()

    if tracer is not None:
        tracer.drain_jsonl(job.span_log)
    return _build_result(job, state, digest, decisions, met, ladder,
                         scr.rebinds, resumed_from, hists, ledger,
                         flight, stream_fallbacks, plane,
                         slo_block, slo_plane, slo_eval, prov,
                         ctl=ctl)


def _draw_counts_mesh(rng: np.random.Generator, job: EpochJob,
                      epochs: int) -> np.ndarray:
    """RAW per-epoch per-shard Poisson draws ``int32[S, epochs, N]``
    (shard axis leading for the mesh launch).  Epoch-major draw order
    with ``(S, N)`` per epoch: at S=1 the generator consumes the
    IDENTICAL variate sequence as the stream loop's ``_draw_counts``
    (numpy fills C-order), which is what makes the S=1 mesh digest
    equal the stream digest including the arrival stream."""
    draws = np.stack([rng.poisson(job.arrival_lam,
                                  (job.n_shards, job.n))
                      .astype(np.int32) for _ in range(epochs)])
    return np.swapaxes(draws, 0, 1)


def _mesh_boundary(job: EpochJob, planes, state, ledger,
                   cd, cr, vd, vr, b: int, prov=None, pm=None,
                   up=None):
    """One mesh churn job's lifecycle boundary: every shard's plane
    applies its own due ops to its own slice (registrations routed by
    the placement map when one exists, else ``cid % n_shards``;
    per-shard SlotMaps), the counter plane's cd/cr (fill 0), held
    views (fill 1), and the provenance last_served watermark (fill 0
    = never served) ride each shard's grow/evict/compact transforms
    as boundary extras, and the stacked layout is forced back
    RECTANGULAR: one shard's grow-on-demand doubling grows every
    sibling to the max capacity before the restack.

    ``pm`` (placement != "static") runs the p2c ROUTING PASS first:
    every registration due at this boundary -- last boundary's
    deferrals first, then this cohort in ascending-cid order -- gets
    its shard assigned against the current per-shard backlog and the
    boundary's liveness row ``up`` BEFORE any plane filters its due
    ops.  A deferral finally placed re-enters as a pending op on its
    destination plane (its scripted event already fired), so nothing
    is lost across a both-choices-down boundary."""
    import jax
    import jax.numpy as jnp

    from ..parallel import mesh as mesh_mod

    S = job.n_shards
    if pm is not None:
        from ..lifecycle import churn as churn_mod

        deferred = pm.take_deferred()
        if job.churn.get("static") and b == 0:
            due = list(range(int(job.churn["total_ids"])))
        else:
            due = [int(e["cid"])
                   for e in churn_mod.events(job.churn, b,
                                             job.ckpt_every)
                   if e["op"] == "register"]
        cohort = [cid for cid in due if pm.shard_of(cid) < 0]
        if deferred or cohort:
            backlog = np.asarray(jax.device_get(state.depth),
                                 dtype=np.int64).sum(axis=-1)
            placed = pm.place_batch(deferred + cohort,
                                    backlog=backlog, up=up)
            for cid in placed:
                if cid in deferred:
                    # its scripted event fired at the earlier
                    # boundary; re-enter through the pending journal
                    r, w, l = churn_mod.init_qos(job.churn, cid)
                    planes[pm.shard_of(cid)].pending.append(
                        {"op": "register", "cid": cid, "r": r,
                         "w": w, "l": l, "apply_at": b})
    sts, leds, ctrs = [], [], []
    for s in range(S):
        st_s = mesh_mod.unstack_shard(state, s)
        led_s = None if ledger is None else ledger[s]
        extras = [(jnp.asarray(cd[s]), 0), (jnp.asarray(cr[s]), 0),
                  (jnp.asarray(vd[s]), 1), (jnp.asarray(vr[s]), 1)]
        if prov is not None:
            extras.append((prov.last_served[s], 0))
        st_s, led_s, extras = planes[s].boundary(
            st_s, b, job.ckpt_every, ledger=led_s, extras=extras)
        sts.append(st_s)
        leds.append(led_s)
        ctrs.append(extras)
    cap = max(int(st.capacity) for st in sts)
    for s in range(S):
        out = planes[s].ensure_capacity(cap, sts[s], ledger=leds[s],
                                        extras=ctrs[s])
        sts[s], leds[s] = out[0], out[1]
        ctrs[s] = out[-1]

    def restack(parts):
        return jax.tree.map(lambda *xs: jnp.stack(xs), *parts)

    state = restack(sts)
    ledger = None if ledger is None else jnp.stack(leds)
    cd, cr, vd, vr = (jnp.stack([ctrs[s][j][0] for s in range(S)])
                      for j in range(4))
    if prov is not None:
        from ..obs import provenance as obsprov

        prov = obsprov.prov_from_arrays(
            prov.margin_hist, prov.scal,
            jnp.stack([ctrs[s][4][0] for s in range(S)]))
    return state, ledger, cd, cr, vd, vr, prov


def _mesh_migrate(job: EpochJob, pm, ctl, planes, state, ledger,
                  cd, cr, vd, vr, b: int, prov=None, up=None,
                  press=None):
    """The controller's ``migrate`` actuation (docs/LIFECYCLE.md
    "Placement and migration"): move up to ``migrate_max`` drained
    clients off the hottest live shard as the EXISTING digest-neutral
    lifecycle ops -- EVICT on the source (final ledger row folded
    into the departed report first), REGISTER on the destination with
    the carried counter views (cd/cr completions, vd/vr held views --
    the paper's delta/rho piggyback as handoff) and the provenance
    last_served watermark installed at the destination slot.

    Determinism/crash story: the trigger is journaled (a resumed run
    REPLAYS it), the destination draws come from the checkpointed
    placement RNG, and the candidate order is a pure function of the
    replayed boundary state -- so a SIGKILL at ANY stage of
    evict -> handoff -> register (the ``placement._migrate_hook``
    seam) replays the identical move list from the previous
    checkpoint.  Runs AFTER the controller boundary and BEFORE the
    boundary's checkpoint save, like every other actuation."""
    import jax
    import jax.numpy as jnp

    from ..lifecycle import placement as placement_mod
    from ..lifecycle.plane import (LC_EVICT, LC_NOP, _pad_len,
                                   apply_op_vector)
    from ..parallel import mesh as mesh_mod

    S = job.n_shards
    depth = np.asarray(jax.device_get(state.depth), dtype=np.int64)
    backlog = depth.sum(axis=-1)
    # source = hottest LIVE shard (a down shard has no pressure to
    # shed -- its in-chunk commits are masked -- and its host-side
    # rows stay put until it returns)
    eligible = np.asarray(
        [int(backlog[s]) if (up is None or bool(up[s])) else -1
         for s in range(S)], dtype=np.int64)
    src = int(np.argmax(eligible))
    if eligible[src] <= 0:
        # boundary-time depth is structurally zero on calendar
        # engines (deadline commits drain within the epoch): fall
        # back to the chunk's mid-epoch pressure peaks -- the same
        # replay-deterministic signal that armed the rule
        if press is None:
            return state, ledger, cd, cr, vd, vr, prov
        from ..obs import provenance as obsprov
        peaks = np.asarray(press, dtype=np.int64)[
            :, obsprov.PRESS_BACKLOG]
        eligible = np.asarray(
            [int(peaks[s]) if (up is None or bool(up[s])) else -1
             for s in range(S)], dtype=np.int64)
        src = int(np.argmax(eligible))
        if eligible[src] <= 0:
            return state, ledger, cd, cr, vd, vr, prov
    plane_src = planes[src]
    cd_src = np.asarray(jax.device_get(cd[src]), dtype=np.int64)
    pick = ctl.migrate_pick()
    keyed = []
    for cid in sorted(plane_src.slots.slot_of):
        slot = plane_src.slots.slot_of[cid]
        # only DRAINED clients move: there are no queued ops to
        # teleport, so the whole handoff is counter state + contract
        if depth[src, slot] != 0:
            continue
        served = int(cd_src[slot])
        if pick == "cold" and served == 0:
            # quiet-since-start movers -- the digest gate's provably
            # placement-equivalent class (ascending cid)
            keyed.append((0, cid))
        elif pick != "cold" and served > 0:
            # largest served demand first (cid breaks ties): the
            # clients whose future arrivals the move actually sheds
            keyed.append((-served, cid))
    moves = pm.plan_moves(b, src=src,
                          candidates=[cid for _k, cid in sorted(keyed)],
                          backlog=backlog, up=up,
                          max_moves=ctl.migrate_batch())
    if not moves:
        return state, ledger, cd, cr, vd, vr, prov

    sts = [mesh_mod.unstack_shard(state, s) for s in range(S)]
    leds = [None if ledger is None else ledger[s] for s in range(S)]
    ctrs = [[(jnp.asarray(cd[s]), 0), (jnp.asarray(cr[s]), 0),
             (jnp.asarray(vd[s]), 1), (jnp.asarray(vr[s]), 1)]
            + ([(prov.last_served[s], 0)] if prov is not None else [])
            for s in range(S)]

    # source half: read the carried riders BEFORE the rows reset,
    # fold the final ledger rows, release the slots, EVICT on device
    carried = {}
    evict_rows = []
    handoff = []
    for cid, dst in moves:
        out = plane_src.migrate_out(cid, leds[src])
        if out is None:
            continue
        slot, qos = out
        carried[cid] = [arr[slot] for arr, _fill in ctrs[src]]
        evict_rows.append((LC_EVICT, slot, 0, 0, 0, 0))
        handoff.append((cid, dst, qos))
    if evict_rows:
        pad = _pad_len(len(evict_rows))
        rows = evict_rows + [(LC_NOP, 0, 0, 0, 0, 0)] \
            * (pad - len(evict_rows))
        arr = np.asarray(rows, dtype=np.int64)
        sts[src] = apply_op_vector(sts[src], arr[:, 0], arr[:, 1],
                                   arr[:, 2], arr[:, 3], arr[:, 4],
                                   arr[:, 5])
        idx = jnp.asarray([r[1] for r in evict_rows])
        if leds[src] is not None:
            leds[src] = leds[src].at[idx].set(0)
        ctrs[src] = [(a.at[idx].set(f), f) for a, f in ctrs[src]]
    if placement_mod._migrate_hook is not None:
        placement_mod._migrate_hook("evicted")

    # destination half: REGISTER with the carried QoS contract
    reg_rows: dict = {s: [] for s in range(S)}
    for cid, dst, qos in handoff:
        reg_rows[dst] += planes[dst].migrate_in(cid, qos)
    if placement_mod._migrate_hook is not None:
        placement_mod._migrate_hook("handoff")

    # one rectangle: a destination's grow-on-demand forces every
    # sibling to the same capacity before the restack
    cap = max(max(int(p.slots.capacity) for p in planes),
              max(int(st.capacity) for st in sts))
    for s in range(S):
        out = planes[s].ensure_capacity(cap, sts[s], ledger=leds[s],
                                        extras=ctrs[s])
        sts[s], leds[s] = out[0], out[1]
        ctrs[s] = out[-1]
    for s in range(S):
        if not reg_rows[s]:
            continue
        rows = list(reg_rows[s])
        pad = _pad_len(len(rows))
        rows += [(LC_NOP, 0, 0, 0, 0, 0)] * (pad - len(rows))
        arr = np.asarray(rows, dtype=np.int64)
        sts[s] = apply_op_vector(sts[s], arr[:, 0], arr[:, 1],
                                 arr[:, 2], arr[:, 3], arr[:, 4],
                                 arr[:, 5])
    # install the carried riders at the destination slots: the
    # delta/rho completions and held views arrive WITH the client
    # (the piggyback-as-handoff), the last_served watermark keeps its
    # starvation clock honest across the move
    for cid, dst, _qos in handoff:
        slot_d = planes[dst].slots.slot_of[cid]
        ctrs[dst] = [(a.at[slot_d].set(v), f)
                     for (a, f), v in zip(ctrs[dst], carried[cid])]
    if placement_mod._migrate_hook is not None:
        placement_mod._migrate_hook("registered")

    state = jax.tree.map(lambda *xs: jnp.stack(xs), *sts)
    ledger = None if ledger is None else jnp.stack(leds)
    cd, cr, vd, vr = (jnp.stack([ctrs[s][j][0] for s in range(S)])
                      for j in range(4))
    if prov is not None:
        from ..obs import provenance as obsprov

        prov = obsprov.prov_from_arrays(
            prov.margin_hist, prov.scal,
            jnp.stack([ctrs[s][4][0] for s in range(S)]))
    return state, ledger, cd, cr, vd, vr, prov


def _mesh_epochs(job: EpochJob, injector, ckpt_dir,
                 scr: _ScrapeCtl, base_cfg: dict, state, rng, met,
                 digest: bytes, start_epoch: int, decisions: int,
                 ladder, tracer, hists, ledger, flight, prov,
                 resumed_from, slo_block=None, slo_plane=None,
                 slo_eval=None, mesh_ctrs=None,
                 planes=None, ctl=None, pm=None) -> SupervisedResult:
    """The mesh serving loop (docs/ENGINE.md "Mesh serving"):
    ``n_shards`` full per-device engines advance a whole
    checkpoint-boundary chunk of epochs inside ONE ``shard_map``
    launch (``parallel.mesh.build_mesh_chunk`` -- the stream chunk's
    own epoch step, sharded), with the paper's delta/rho counter
    views exchanged through the [C]-sized psum on the global
    ``counter_sync_every`` epoch grid and the per-shard SLO window
    blocks merged in-graph through ``window_mesh_reduce`` into the
    ONE cluster-wide conformance table the SLO plane rolls.

    ``job.fault_plan`` (docs/ROBUSTNESS.md "Degraded-mode mesh")
    samples a deterministic ``FaultPlan`` over (epochs, n_shards) and
    compiles each chunk's slice INTO the fused launch as traced fault
    masks; a guard trip during a chaos chunk replays the same
    schedule on the host robust loop (``mesh_chaos_fallbacks``).
    ``planes`` (mesh churn) drives per-shard lifecycle boundaries at
    the chunk grid with the counter plane riding each shard's
    slot transforms; the chain digest hashes each shard's results
    through that shard's canonical slot->cid view, so the S>1
    dynamic==static gate holds.

    Crash-equivalence discipline: the chunk's raw draws are taken
    synchronously right before the launch and the checkpointed RNG
    state is the post-draw snapshot, so a resumed incarnation
    re-draws epochs >= the boundary bit-identically; the counter
    plane (per-shard completions + held views) rides the rotation
    checkpoints as ``mesh_*`` leaves, the fault plan is recomputed
    from its spec (pure host data).  The per-epoch drain bookkeeping
    (chain digest over the per-shard decision streams in shard order,
    metric fold, ladder notes, injector kill points) is the stream
    loop's, so at S=1 the two loops are bit-identical end to end."""
    import jax
    import jax.numpy as jnp

    from ..engine import stream as stream_mod
    from ..obs import device as obsdev
    from ..obs import spans as _spans
    from ..parallel import mesh as mesh_mod
    from .faults import parse_fault_spec, plan_chunk, plan_from_spec
    from .guarded import run_mesh_chunk_guarded

    n_dev = len(jax.devices())
    if job.n_shards > n_dev:
        raise ValueError(
            f"EpochJob(n_shards={job.n_shards}) needs that many "
            f"devices; this backend has {n_dev} (force a host mesh "
            f"with jax_num_cpu_devices / "
            f"--xla_force_host_platform_device_count)")
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_mod.make_mesh(job.n_shards)
    sharding = NamedSharding(mesh, P(mesh_mod.SERVER_AXIS))
    # the stacked [S, ...] state (built by _job_state or restored from
    # a checkpoint) gets its leaves split over the servers mesh axis
    state = jax.tree.map(lambda a: jax.device_put(a, sharding), state)
    cd, cr, vd, vr = mesh_ctrs
    plan = None
    if job.fault_plan is not None:
        plan = plan_from_spec(parse_fault_spec(job.fault_plan),
                              job.epochs, job.n_shards)
    mesh_fallbacks = 0
    mesh_chaos_fallbacks = 0
    do_ingest = job.arrival_lam > 0 or planes is not None
    slo_w0 = start_epoch
    # when the job's SLO plane is off, slo_block stays None and the
    # guarded runner builds its own throwaway window block per chunk
    # (the counter plane needs one; never checkpointed -- the diffs
    # are chunk-local, cd/cr are what persist)
    wblock = slo_block
    try:
        for e0, b in stream_mod.chunk_bounds(start_epoch, job.epochs,
                                             job.ckpt_every):
            scr.tick(e0, injector)
            # mesh churn: every shard's lifecycle boundary applies
            # BEFORE the chunk, on the chunk grid (the stream loop's
            # discipline); the counter plane follows each shard's
            # slot transforms as boundary extras
            up_row = None if plan is None \
                else plan.up[min(e0, plan.up.shape[0] - 1)]
            if planes is not None:
                with _spans.span(tracer, "lifecycle.boundary",
                                 "host_prep", epoch=e0):
                    state, ledger, cd, cr, vd, vr, prov = \
                        _mesh_boundary(job, planes, state, ledger,
                                       cd, cr, vd, vr, e0, prov,
                                       pm=pm, up=up_row)
            counts = None
            if do_ingest:
                with _spans.span(tracer, "mesh.pregen", "host_prep"):
                    if planes is not None:
                        # ONE id-space draw per epoch for the whole
                        # cluster (identical RNG consumption in the
                        # dynamic run and its static variant), mapped
                        # onto each shard's POST-boundary slot layout
                        raw = _draw_counts_churn(rng, job.churn,
                                                 e0, b)
                        counts = np.stack(
                            [planes[s].map_counts(raw)
                             for s in range(job.n_shards)])
                    else:
                        counts = _draw_counts_mesh(rng, job, b - e0)
                    if ctl is not None:
                        # whole-chunk clamp under the chunk-start knob
                        # (the stream loop's discipline) -- applied
                        # AFTER the draws, so RNG consumption never
                        # depends on the controller
                        counts = ctl.clamp_counts(counts, job.waves)
            rng_ckpt = _rng_state_array(rng)
            faults = plan_chunk(plan, e0, b) \
                if plan is not None else None
            while True:
                cfg = ladder.apply(ctl.overlay(base_cfg)
                                   if ctl is not None else base_cfg)
                try:
                    g = run_mesh_chunk_guarded(
                        state, cd, cr, vd, vr, e0, counts, mesh=mesh,
                        engine=job.engine, epochs=b - e0, m=job.m,
                        k=job.k, chain_depth=job.chain_depth,
                        dt_epoch_ns=job.dt_epoch_ns, waves=job.waves,
                        with_metrics=True,
                        select_impl=cfg["select_impl"],
                        tag_width=cfg["tag_width"],
                        calendar_impl=cfg["calendar_impl"],
                        ladder_levels=job.ladder_levels,
                        wheel_kernel=job.wheel_kernel,
                        counter_sync_every=ctl.knob_sync()
                        if ctl is not None
                        else job.counter_sync_every,
                        with_pressure=ctl is not None,
                        hists=hists, ledger=ledger, slo=wblock,
                        prov=prov, flight=flight, faults=faults,
                        tracer=tracer)
                    break
                except RECOVERABLE_ERRORS:
                    if not ladder.can_step(cfg):
                        raise
                    met[obsdev.MET_LADDER_STEPS] += \
                        ladder.note_epoch(cfg, launch_failures=1)
            state, cd, cr, vd, vr = g.state, g.cd, g.cr, \
                g.view_d, g.view_r
            if job.with_hists:
                hists = g.hists
            if job.with_ledger:
                ledger = g.ledger
            if job.with_prov:
                prov = g.prov
            if job.flight_records:
                flight = g.flight
            if job.with_slo:
                slo_block = g.slo
                wblock = g.slo
            mesh_fallbacks += g.mesh_fallback
            if plan is not None:
                # a chaos chunk that degraded to the host robust loop
                # -- the fallback carried the identical fault
                # schedule, so the run stays on-plan, just slower
                mesh_chaos_fallbacks += g.mesh_fallback
            # the drain: per-epoch bookkeeping in epoch order, the
            # stream loop's exact sequence; the chain digest hashes
            # every shard's decision stream in shard order per epoch
            # (a churn job hashes each shard's CANONICAL slot->cid
            # view through that shard's own plane)
            with _spans.span(tracer, "mesh.drain", "drain",
                             chunk=b - e0, shards=job.n_shards):
                for i in range(b - e0):
                    epoch = e0 + i
                    scr.tick(epoch, injector)
                    decisions += g.counts[i]
                    if planes is not None:
                        flat = tuple(
                            r for s, grp in enumerate(g.epochs[i])
                            for r in planes[s].canon_results(grp))
                    else:
                        flat = tuple(r for grp in g.epochs[i]
                                     for r in grp)
                    digest = _digest_update(digest, flat)
                    for r in flat:
                        if hasattr(r, "metrics") and \
                                r.metrics is not None:
                            met = obsdev.metrics_combine_np(
                                met, jax.device_get(r.metrics))
                    met[obsdev.MET_LADDER_STEPS] += ladder.note_epoch(
                        cfg, guard_trips=g.guard_trips[i])
                    if injector is not None:
                        injector.after_decisions(decisions)
            _spans.instant(tracer, "mesh.heartbeat", "drain",
                           epoch=b)
            closed = None
            if slo_plane is not None:
                # roll the CLUSTER-WIDE merged table (the in-graph
                # window_mesh_reduce output); the fresh stamped block
                # re-broadcasts to every shard.  Backlog for the
                # starvation predicate is the cluster total (at S=1:
                # exactly the stream loop's per-shard depth).
                depth_sum = jnp.sum(state.depth.astype(jnp.int64),
                                    axis=0)
                merged, closed = slo_plane.roll(
                    jnp.asarray(g.slo_merged), slo_w0, b,
                    depth=depth_sum)
                slo_w0 = b
                slo_eval.observe_roll(closed)
                slo_block = mesh_mod.stack_shards(merged,
                                                  job.n_shards)
                wblock = slo_block
            if ctl is not None:
                # cluster-level controller boundary: signals aggregate
                # over every shard (backlog = cluster depth total,
                # press_backlog = hottest shard's total).  A fired
                # ``compact`` journals + counts as migration-eligible
                # only; a fired ``migrate`` (placement != "static")
                # ACTUATES -- _mesh_migrate moves drained clients off
                # the hottest shard as digest-neutral EVICT/REGISTER
                # handoffs, BEFORE this boundary's checkpoint save so
                # a replayed trigger re-moves the replayed state
                # deterministically (staleness / ladder / clamp knobs
                # actuate exactly as on the other loops).
                if g.press is not None and scr.scrape is not None:
                    # live placement signal: the chunk's per-shard
                    # mid-epoch peaks on the dmclock_shard_pressure_*
                    # gauges (best-effort host telemetry)
                    try:
                        from ..obs import provenance as obsprov
                        obsprov.publish_shard_pressure(
                            scr.scrape.registry, g.press)
                    except Exception:
                        pass
                sig = ctl.collect(b, state=state, met=met,
                                  slo_eval=slo_eval, prov=prov,
                                  planes=planes, press=g.press)
                fired = ctl.step(b, sig,
                                 fault=None if injector is None
                                 else injector.controller_point)
                if "migrate" in fired and pm is not None:
                    with _spans.span(tracer, "lifecycle.migrate",
                                     "host_prep", epoch=b):
                        up_b = None if plan is None \
                            else plan.up[min(b, plan.up.shape[0] - 1)]
                        state, ledger, cd, cr, vd, vr, prov = \
                            _mesh_migrate(job, pm, ctl, planes,
                                          state, ledger, cd, cr,
                                          vd, vr, b, prov=prov,
                                          up=up_b, press=g.press)
            if ckpt_dir is not None:
                with _spans.span(tracer, "supervisor.checkpoint_save",
                                 "checkpoint", epoch=b):
                    payload = _payload(job, state, rng_ckpt, met,
                                       digest, b, decisions,
                                       ladder.encode(), hists=hists,
                                       ledger=ledger, prov=prov,
                                       flight=flight, plane=planes,
                                       mesh=(cd, cr, vd, vr),
                                       slo=None if slo_plane is None
                                       else (slo_block, slo_plane,
                                             slo_eval), ctl=ctl,
                                       pm=pm)

                    def save(payload=payload):
                        return ckpt_mod.save_pytree_rotating(
                            ckpt_dir, payload, keep=job.keep)

                    if injector is not None:
                        injector.around_save(b - 1, save)
                    else:
                        save()
                if tracer is not None:
                    tracer.drain_jsonl(job.span_log)
                _slo_log_flush(slo_plane, job.slo_log, closed)
            else:
                _slo_log_flush(slo_plane, job.slo_log, closed)
                if tracer is not None:
                    tracer.drain_jsonl(job.span_log)
    except BaseException:
        # the crash hook, as in the round/stream loops: best-effort
        # per-shard flight dump (shard column added), NO span flush
        if job.flight_dump and flight is not None:
            try:
                from ..obs import flight as obsflight
                n = obsflight.flight_dump_any(flight, job.flight_dump)
                print(f"# supervisor: dumped {n} flight records to "
                      f"{job.flight_dump}", file=sys.stderr)
            except Exception:
                pass
        raise
    finally:
        scr.close()

    if tracer is not None:
        tracer.drain_jsonl(job.span_log)
    return _build_result(job, state, digest, decisions, met, ladder,
                         scr.rebinds, resumed_from, hists, ledger,
                         flight, 0, planes, slo_block, slo_plane,
                         slo_eval, prov,
                         mesh=(cd, cr, vd, vr),
                         mesh_fallbacks=mesh_fallbacks,
                         mesh_chaos_fallbacks=mesh_chaos_fallbacks,
                         ctl=ctl, pm=pm)


def _healthz_ok(scrape, timeout_s: float = 2.0) -> bool:
    """One-shot liveness probe of a scrape endpoint's ``/healthz``
    (obs.registry.MetricsHTTPServer) -- what a restarted incarnation
    polls after rebinding its port to confirm the endpoint actually
    serves again."""
    import urllib.request

    try:
        with urllib.request.urlopen(scrape.healthz_url,
                                    timeout=timeout_s) as resp:
            return resp.status == 200 \
                and b"ok" in resp.read()
    except Exception:
        return False


def run_job(job: EpochJob) -> SupervisedResult:
    """The bare runner: the uninterrupted, unsupervised reference.
    The zero-host-fault gate pins ``run_supervised(job, wd,
    zero_host_plan())`` bit-identical to this."""
    return _job_loop(job, None, None)


# ----------------------------------------------------------------------
# the supervisor
# ----------------------------------------------------------------------

JOB_FILE = "job.json"
RESULT_FILE = "result.json"
RESUME_LOG = "resume.log"


class _ChildKilled(RuntimeError):
    """Spawn-mode child died (signal or nonzero exit) before writing
    its result."""


# what the restart loop treats as "the runner died": plan kills
# (trampoline HostKill, spawn child death) AND a recoverable device/
# transport error that survived the guarded runner's bounded retries
# and the ladder -- in both modes that run is gone, but an intact
# rotation checkpoint remains to resume from.  Genuine caller bugs
# (ValueError, plain RuntimeError) still surface immediately in
# trampoline mode.
_RESTART_ERRORS = (HostKill, _ChildKilled) + RECOVERABLE_ERRORS


def run_supervised(job: EpochJob, workdir,
                   plan: Optional[HostFaultPlan] = None, *,
                   mode: str = "trampoline", max_restarts: int = 8,
                   backoff_base_s: float = 0.01, backoff_max_s: float = 1.0,
                   sleep: Callable[[float], None] = _time.sleep
                   ) -> SupervisedResult:
    """Run ``job`` to completion under the supervisor, injecting
    ``plan`` (None/empty = no host faults), restarting a killed job
    with bounded exponential backoff until it completes or
    ``max_restarts`` is exhausted (:class:`SupervisorGaveUp`).

    ``mode="trampoline"`` restarts in-process (plan kills raise
    :class:`HostKill`; fast, what the test matrix uses);
    ``mode="spawn"`` runs each incarnation as a child interpreter and
    plan kills are REAL ``SIGKILL`` -- the CI crash smoke's mode.
    ``workdir`` must be fresh per logical run (it holds the rotation
    checkpoints, the fired-points journal, and -- in spawn mode --
    the job/result files)."""
    assert mode in ("trampoline", "spawn"), mode
    workdir = os.fspath(workdir)
    os.makedirs(workdir, exist_ok=True)
    restarts = 0
    while True:
        try:
            if mode == "trampoline":
                injector = HostFaultInjector(plan, workdir,
                                             kill_mode="raise")
                result = _job_loop(job, workdir, injector)
            else:
                result = _spawn_once(job, workdir, plan)
            break
        except _RESTART_ERRORS as e:
            restarts += 1
            if restarts > max_restarts:
                raise SupervisorGaveUp(
                    f"{restarts - 1} restarts exhausted "
                    f"(last kill: {e})") from e
            sleep(min(backoff_base_s * (2.0 ** (restarts - 1)),
                      backoff_max_s))
    from ..obs import device as obsdev

    met = np.asarray(result.metrics, dtype=np.int64).copy()
    # the resume row counts restarts that restored a snapshot (the
    # durable journal every incarnation appends to), NOT raw restart
    # attempts: a replay-from-scratch restart pays a full recompute
    # and must read as zero resumes
    resumes = 0
    resume_log = os.path.join(workdir, RESUME_LOG)
    if os.path.exists(resume_log):
        with open(resume_log) as fh:
            resumes = sum(1 for ln in fh if ln.strip())
    met[obsdev.MET_SUPERVISOR_RESUMES] = resumes
    return result._replace(metrics=met, restarts=restarts)


def _spawn_once(job: EpochJob, workdir: str,
                plan: Optional[HostFaultPlan]) -> SupervisedResult:
    """One child-process incarnation: write the job file, run
    ``python -m dmclock_tpu.robust.supervisor <workdir>``, read the
    result back.  A SIGKILLed child leaves no result file and raises
    :class:`_ChildKilled` for the restart loop."""
    job_path = os.path.join(workdir, JOB_FILE)
    res_path = os.path.join(workdir, RESULT_FILE)
    if os.path.exists(res_path):
        os.unlink(res_path)
    with open(job_path, "w") as fh:
        json.dump({"job": job.to_json(),
                   "plan": plan_to_json(plan)}, fh)
    proc = subprocess.run(
        [sys.executable, "-m", "dmclock_tpu.robust.supervisor",
         workdir], cwd=os.getcwd(), env=os.environ.copy())
    if proc.returncode != 0 or not os.path.exists(res_path):
        raise _ChildKilled(f"child exited {proc.returncode} "
                           f"({describe_host(plan)})")
    with open(res_path) as fh:
        obj = json.load(fh)

    def arr(key):
        v = obj.get(key)
        return None if v is None else np.asarray(v, dtype=np.int64)

    def arr2(key, cols):
        v = obj.get(key)
        if v is None:
            return None
        a = np.asarray(v, dtype=np.int64)
        # an empty list round-trips as shape (0,): restore the column
        # layout.  A non-empty block keeps its own rank -- a mesh
        # job's slo_window is the STACKED [S, N, cols] layout and a
        # forced reshape would flatten the shard axis.
        return a.reshape(-1, cols) if a.size == 0 or a.ndim < 2 else a

    from ..obs import slo as obsslo

    return SupervisedResult(
        digest=obj["digest"], state_digest=obj["state_digest"],
        decisions=int(obj["decisions"]), epochs=int(obj["epochs"]),
        metrics=np.asarray(obj["metrics"], dtype=np.int64),
        restarts=0, ladder_steps=obj["ladder_steps"],
        scrape_rebinds=int(obj["scrape_rebinds"]),
        resumed_from=obj.get("resumed_from"),
        hists=arr("hists"), ledger=arr("ledger"),
        flight_buf=arr("flight_buf"),
        flight_seq=int(obj.get("flight_seq", 0)),
        stream_fallbacks=int(obj.get("stream_fallbacks", 0)),
        lifecycle=obj.get("lifecycle"),
        slo_window=arr2("slo_window", obsslo.W_FIELDS),
        slo_ring=arr2("slo_ring", obsslo.RING_COLS),
        slo_cepoch=arr2("slo_cepoch", 2),
        slo=obj.get("slo"),
        prov_margin_hist=arr("prov_margin_hist"),
        prov_scal=arr("prov_scal"),
        prov_last_served=arr("prov_last_served"),
        mesh_counters=arr("mesh_counters"),
        mesh_views=arr("mesh_views"),
        mesh_fallbacks=int(obj.get("mesh_fallbacks", 0)),
        mesh_chaos_fallbacks=int(obj.get("mesh_chaos_fallbacks", 0)),
        controller_decisions=int(obj.get("controller_decisions", 0)),
        controller_replays=int(obj.get("controller_replays", 0)),
        controller_knobs=obj.get("controller_knobs"),
        controller_trajectory=obj.get("controller_trajectory"))


def _child_main(workdir: str) -> int:
    """Spawn-mode child entry: run one incarnation of the job in
    ``<workdir>/job.json`` with REAL SIGKILL plan points, then write
    the result atomically.  Platform comes from ``JAX_PLATFORMS`` set
    by the parent's environment (the image's boot shim ignores plain
    env vars, so apply it via jax.config before any backend use)."""
    plat = os.environ.get("JAX_PLATFORMS")
    import jax

    if plat:
        jax.config.update("jax_platforms", plat)
    jax.config.update("jax_enable_x64", True)

    with open(os.path.join(workdir, JOB_FILE)) as fh:
        obj = json.load(fh)
    job = EpochJob.from_json(obj["job"])
    plan = plan_from_json(obj.get("plan", {}))
    injector = HostFaultInjector(plan, workdir, kill_mode="sigkill")
    result = _job_loop(job, workdir, injector)
    res_path = os.path.join(workdir, RESULT_FILE)
    tmp = res_path + f".tmp.{os.getpid()}"
    def lst(v):
        return None if v is None else np.asarray(v).tolist()

    with open(tmp, "w") as fh:
        json.dump({"digest": result.digest,
                   "state_digest": result.state_digest,
                   "decisions": result.decisions,
                   "epochs": result.epochs,
                   "metrics": np.asarray(result.metrics).tolist(),
                   "ladder_steps": result.ladder_steps,
                   "scrape_rebinds": result.scrape_rebinds,
                   "resumed_from": result.resumed_from,
                   "hists": lst(result.hists),
                   "ledger": lst(result.ledger),
                   "flight_buf": lst(result.flight_buf),
                   "flight_seq": result.flight_seq,
                   "stream_fallbacks": result.stream_fallbacks,
                   "lifecycle": result.lifecycle,
                   "slo_window": lst(result.slo_window),
                   "slo_ring": lst(result.slo_ring),
                   "slo_cepoch": lst(result.slo_cepoch),
                   "slo": result.slo,
                   "prov_margin_hist": lst(result.prov_margin_hist),
                   "prov_scal": lst(result.prov_scal),
                   "prov_last_served":
                       lst(result.prov_last_served),
                   "mesh_counters": lst(result.mesh_counters),
                   "mesh_views": lst(result.mesh_views),
                   "mesh_fallbacks": result.mesh_fallbacks,
                   "mesh_chaos_fallbacks":
                       result.mesh_chaos_fallbacks,
                   "controller_decisions":
                       result.controller_decisions,
                   "controller_replays": result.controller_replays,
                   "controller_knobs": result.controller_knobs,
                   "controller_trajectory":
                       result.controller_trajectory}, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, res_path)
    return 0


if __name__ == "__main__":
    sys.exit(_child_main(sys.argv[1]))
