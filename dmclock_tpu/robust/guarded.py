"""The guarded-commit contract: trip -> commit nothing -> retry.

Generalizes the tag32 ``rebase_fallbacks`` pattern (docs/ENGINE.md)
into one repo-wide contract, documented in docs/ROBUSTNESS.md:

1. **Device side** -- an engine step that trips a guard (int32
   tag-window overflow, creation-order/cost rebase guard, calendar
   no-progress) commits *nothing* from that trip: the scan carry keeps
   the last good state, ``guards_ok``/``progress_ok`` reads False, and
   a fault counter bumps.  This is already built into the epoch scans;
   :func:`run_epoch_guarded` is the host half that resumes the
   remaining batches on the always-exact path.
2. **Host side** -- transient device failures (the shared tunnel
   wedging, a runtime OOM-and-recover) are retried with **bounded
   exponential backoff** instead of raising out of the serving layer:
   :func:`retry_with_backoff`, used by ``engine.queue
   .TpuPullPriorityQueue`` around every device launch.  State is only
   rebound on success (jax programs are pure), so a failed launch
   never half-commits.

This module must stay import-light: ``engine.queue`` imports it, so
anything from ``engine`` is imported lazily inside functions.
"""

from __future__ import annotations

import time as _time
from typing import Callable, NamedTuple, Optional

# Exception classes worth retrying: jax DEVICE errors (XlaRuntimeError
# -- the wedged-tunnel failure mode) and tunnel/transport failures
# (OSError covers ConnectionError; TimeoutError).  Plain RuntimeError
# is deliberately NOT in the set: a generic host-side RuntimeError is
# a caller bug, and retrying it would just re-raise the same error
# after three backoff sleeps under the queue lock.


def _recoverable_classes():
    classes = [OSError, TimeoutError]
    try:
        from jax.errors import JaxRuntimeError
        classes.append(JaxRuntimeError)
    except ImportError:     # pragma: no cover - older jax
        try:
            from jaxlib.xla_extension import XlaRuntimeError
            classes.append(XlaRuntimeError)
        except ImportError:
            # no importable device-error class: transport errors only
            # -- adding bare RuntimeError would break the
            # never-retry-caller-bugs contract above
            pass
    return tuple(classes)


RECOVERABLE_ERRORS = _recoverable_classes()


def retry_with_backoff(fn: Callable, *, retries: int = 3,
                       base_s: float = 0.05, factor: float = 2.0,
                       max_s: float = 2.0,
                       recoverable=RECOVERABLE_ERRORS,
                       on_retry: Optional[Callable[[int, BaseException],
                                                   None]] = None,
                       sleep: Callable[[float], None] = _time.sleep,
                       jitter_seed: Optional[int] = None,
                       deadline_s: Optional[float] = None,
                       clock: Callable[[], float] = _time.monotonic):
    """Call ``fn()``; on a recoverable error sleep
    ``min(base_s * factor**i, max_s)`` and retry, at most ``retries``
    times, then re-raise the last error.  ``on_retry(attempt, exc)``
    observes each retry (the queue counts them into its metrics).
    ``fn`` must be pure/idempotent -- jitted device launches are.

    ``jitter_seed`` (anti-thundering-herd): scale every sleep by a
    DETERMINISTIC per-seed multiplier in ``[0.5, 1.5)`` (PCG64, stable
    across runs/platforms -- the host-fault-plan convention), so S
    shards relaunching after one shared-tunnel wedge desynchronize by
    seeding with their shard index instead of stampeding the runtime
    in lockstep.  Unseeded behavior is the exact historical schedule.

    ``deadline_s``: an overall wall-clock budget measured by
    ``clock()`` (injectable for tests).  Once spent, the next
    recoverable error re-raises even with retries left, and any final
    sleep is truncated to the remaining budget -- bounded total stall,
    retries or not."""
    rng = None
    if jitter_seed is not None:
        import numpy as _np
        rng = _np.random.Generator(_np.random.PCG64(int(jitter_seed)))
    t0 = clock() if deadline_s is not None else 0.0
    attempt = 0
    while True:
        try:
            return fn()
        except recoverable as e:  # noqa: PERF203 -- the whole point
            if attempt >= retries:
                raise
            if deadline_s is not None and clock() - t0 >= deadline_s:
                raise
            if on_retry is not None:
                on_retry(attempt, e)
            delay = min(base_s * (factor ** attempt), max_s)
            if rng is not None:
                delay *= 0.5 + rng.random()
            if deadline_s is not None:
                delay = min(delay, max(deadline_s - (clock() - t0),
                                       0.0))
            sleep(delay)
            attempt += 1


class GuardedEpoch(NamedTuple):
    """Result of :func:`run_epoch_guarded`."""

    state: object            # EngineState after every committed batch
    count: int               # decisions committed (incl. the resume)
    results: tuple           # the raw epoch result(s), in run order
    rebase_fallbacks: int    # tag32 window trips resumed on int64
    serial_fallbacks: int    # order/cost guard trips resumed serially
    retries: int             # transient device errors retried
    # telemetry accumulators after the LAST scan attempt (pass-through
    # state: a tag32 resume continues accumulating from the first
    # attempt's outputs; the rare serial fallback's decisions are not
    # telemetered -- docs/OBSERVABILITY.md).  None when the caller
    # passed none in.
    hists: object = None
    ledger: object = None
    flight: object = None
    slo: object = None
    prov: object = None


# Module-level jit cache keyed by the static epoch configuration (the
# engine/queue.py _JIT_CACHE convention): a fresh jax.jit(partial(...))
# per call would retrace + recompile the whole epoch program on EVERY
# guarded run, and the compile dwarfs the epoch at bench shapes.
# Entries are compile-plane-instrumented (obs.compile_plane).
_EPOCH_JIT_CACHE: dict = {}


def _jit_epoch(engine: str, m_run: int, kw: dict, tele_sig=()):
    """``tele_sig`` is the tuple of telemetry accumulator names the
    wrapped call threads through as TRACED arguments (they must not be
    closed over -- a partial-bound array would constant-fold into the
    compiled program and break the module-cache reuse)."""
    key = (engine, m_run, tuple(sorted(kw.items())), tele_sig)
    if key not in _EPOCH_JIT_CACHE:
        import functools

        from ..engine import fastpath
        from ..obs import compile_plane as _cplane
        fn = fastpath.epoch_scan_fn(engine)
        if tele_sig:
            def run(st, t, tele):
                return fn(st, t, m=m_run, **kw, **tele)
            _EPOCH_JIT_CACHE[key] = _cplane.instrumented_jit(
                run, cache="guarded.epoch", entry=key)
        else:
            _EPOCH_JIT_CACHE[key] = _cplane.instrumented_jit(
                functools.partial(fn, m=m_run, **kw),
                cache="guarded.epoch", entry=key)
    return _EPOCH_JIT_CACHE[key]


def _jit_serial(steps: int, allow_limit_break: bool,
                anticipation_ns: int):
    key = ("serial", steps, allow_limit_break, anticipation_ns)
    if key not in _EPOCH_JIT_CACHE:
        import functools

        from ..engine import kernels
        from ..obs import compile_plane as _cplane
        _EPOCH_JIT_CACHE[key] = _cplane.instrumented_jit(
            functools.partial(
                kernels.engine_run, steps=steps,
                allow_limit_break=allow_limit_break,
                anticipation_ns=anticipation_ns, advance_now=False),
            cache="guarded.serial", entry=key)
    return _EPOCH_JIT_CACHE[key]


def _epoch_count(engine: str, result) -> int:
    import numpy as np
    return int(np.asarray(result.count).sum())


def _guard_vec(engine: str, result):
    import numpy as np
    ok = result.progress_ok if engine == "calendar" \
        else result.guards_ok
    return np.asarray(ok)


def run_epoch_guarded(state, now, *, engine: str = "prefix",
                      m: int, k: int = 0, chain_depth: int = 4,
                      anticipation_ns: int = 0,
                      allow_limit_break: bool = False,
                      with_metrics: bool = False,
                      select_impl: str = "sort",
                      tag_width: int = 64,
                      window_m: Optional[int] = None,
                      calendar_impl: str = "minstop",
                      ladder_levels: int = 8,
                      wheel_kernel: str = "xla",
                      skew_ns: int = 0,
                      hists=None, ledger=None, flight=None, slo=None,
                      prov=None,
                      retries: int = 3, base_s: float = 0.05,
                      sleep: Callable[[float], None] = _time.sleep,
                      on_retry=None, tracer=None) -> GuardedEpoch:
    """Run one epoch of any of the three epoch engines under the
    guarded-commit contract, host side included.

    The epoch itself enforces commit-nothing-on-trip; this wrapper (a)
    retries transient device failures with bounded backoff, (b) on a
    tag32 window trip resumes the REMAINING batches from the returned
    last-good state on the int64 path, and (c) on an order/cost guard
    trip (64-bit; never observed in practice) resumes on the serial
    engine -- the ``make_prefix_runner`` fallback generalized to all
    three engines.  ``skew_ns`` is the fault-injection hook: the epoch
    sees ``now + skew_ns``.  With ``skew_ns=0`` the first attempt is
    the untouched epoch call -- bit-identical to no wrapper at all
    (chaos differential gate).

    ``hists`` / ``ledger`` / ``flight`` (None = off) are the telemetry
    accumulators of ``fastpath.scan_*_epoch``: pass-through state, so
    a tag32 window trip's int64 resume continues accumulating from
    the first attempt's outputs and the returned accumulators cover
    the whole epoch.  The serial-engine fallback (never observed in
    practice) passes them through untouched -- its decisions are not
    telemetered.

    ``tracer`` (``obs.spans.SpanTracer`` or None) records host spans
    around each launch -- ``guarded.dispatch`` (the jit call) and
    ``guarded.device_wait`` (the ``block_until_ready``) -- plus
    ``retry`` instants for backoff retries and the tag32/serial
    resumes.  Host-side only: decisions are bit-identical with or
    without it (ci.sh tracing smoke).
    """
    import jax
    import jax.numpy as jnp

    from ..engine import fastpath, kernels
    from ..obs import spans as _spans

    assert engine in fastpath.EPOCH_ENGINES, engine
    kw = fastpath.epoch_scan_kwargs(
        engine, k=k, chain_depth=chain_depth, select_impl=select_impl,
        tag_width=tag_width, window_m=window_m,
        calendar_impl=calendar_impl, ladder_levels=ladder_levels,
        wheel_kernel=wheel_kernel,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break,
        with_metrics=with_metrics)
    retry_count = [0]

    def count_retry(attempt, exc):
        retry_count[0] += 1
        _spans.instant(tracer, "guarded.retry", "retry",
                       error=type(exc).__name__)
        if on_retry is not None:
            on_retry(attempt, exc)

    tele = {}
    if hists is not None:
        tele["hists"] = hists
    if ledger is not None:
        tele["ledger"] = ledger
    if flight is not None:
        tele["flight"] = flight
    if slo is not None:
        tele["slo"] = slo
    if prov is not None:
        tele["prov"] = prov
    tele_sig = tuple(sorted(tele))

    def attempt(st, t, m_run, width):
        fn = _jit_epoch(engine, m_run, {**kw, "tag_width": width},
                        tele_sig)
        call = (lambda: fn(st, t, tele)) if tele_sig \
            else (lambda: fn(st, t))

        def one():
            # dispatch (the async jit call) and the device wait are
            # separate spans: their ratio is the dispatch tax
            with _spans.span(tracer, "guarded.dispatch", "dispatch",
                             engine=engine, m=m_run):
                out = call()
            with _spans.span(tracer, "guarded.device_wait",
                             "device_compute"):
                return jax.block_until_ready(out)

        return retry_with_backoff(
            one, retries=retries, base_s=base_s, sleep=sleep,
            on_retry=count_retry)

    def take_tele(ep):
        for name in tele_sig:
            tele[name] = getattr(ep, name)

    t = jnp.asarray(now, dtype=jnp.int64) + jnp.int64(skew_ns)
    results = []
    rebase_fb = serial_fb = 0
    ep = attempt(state, t, m, tag_width)
    results.append(ep)
    take_tele(ep)
    total = _epoch_count(engine, ep)
    state = ep.state
    guards = _guard_vec(engine, ep)
    if not guards.all():
        remaining = int(m - guards.sum())
        if tag_width == 32:
            # tag32 window trip: the batch committed nothing; resume
            # the remaining batches on the int64 path (exactness pinned
            # by tests/test_radix.py)
            rebase_fb = 1
            _spans.instant(tracer, "guarded.rebase_resume", "retry",
                           remaining=remaining)
            ep2 = attempt(state, t, remaining, 64)
            results.append(ep2)
            take_tele(ep2)
            g2 = _guard_vec(engine, ep2)
            total += _epoch_count(engine, ep2)
            state = ep2.state
            guards = g2
            remaining = int(remaining - g2.sum())
        if not guards.all():
            # order/cost guard (or calendar no-progress) on the exact
            # path: fall back to the serial engine for the rest
            serial_fb = 1
            _spans.instant(tracer, "guarded.serial_resume", "retry",
                           remaining=remaining)
            steps = max(remaining, 1) * max(k, 1)
            run = _jit_serial(steps, allow_limit_break,
                              anticipation_ns)

            def serial_one():
                with _spans.span(tracer, "guarded.dispatch",
                                 "dispatch", engine="serial"):
                    out = run(state, t)
                with _spans.span(tracer, "guarded.device_wait",
                                 "device_compute"):
                    return jax.block_until_ready(out)

            st2, _, decs = retry_with_backoff(
                serial_one, retries=retries, base_s=base_s,
                sleep=sleep, on_retry=count_retry)
            import numpy as np
            total += int((np.asarray(decs.type)
                          == kernels.RETURNING).sum())
            state = st2
            results.append(decs)
    return GuardedEpoch(state=state, count=total,
                        results=tuple(results),
                        rebase_fallbacks=rebase_fb,
                        serial_fallbacks=serial_fb,
                        retries=retry_count[0],
                        hists=tele.get("hists"),
                        ledger=tele.get("ledger"),
                        flight=tele.get("flight"),
                        slo=tele.get("slo"),
                        prov=tele.get("prov"))


class StreamGuarded(NamedTuple):
    """Result of :func:`run_stream_chunk_guarded` -- one stream chunk
    of epochs, drained and normalized to per-epoch rows so the caller
    (``robust.supervisor``'s stream loop) runs the exact same chain
    digest / metric-fold / ladder bookkeeping as the round loop."""

    state: object            # EngineState after the whole chunk
    epochs: tuple            # per-epoch tuples of raw result objects
    #                          (digest-ready, run order -- exactly
    #                          what GuardedEpoch.results holds)
    counts: tuple            # per-epoch decisions committed (int)
    guard_trips: tuple       # per-epoch rebase+serial fallback count
    #                          (0 on a clean chunk)
    stream_fallback: int     # 1 when the chunk tripped a guard and
    #                          was discarded + re-run on the round
    #                          path (slower, never divergent)
    retries: int             # transient device errors retried
    hists: object = None     # telemetry accumulators after the chunk
    ledger: object = None
    flight: object = None
    slo: object = None
    prov: object = None


def run_stream_chunk_guarded(state, epoch0: int, counts, *,
                             engine: str, epochs: int, m: int,
                             k: int = 0, chain_depth: int = 4,
                             dt_epoch_ns: int, waves: int,
                             anticipation_ns: int = 0,
                             allow_limit_break: bool = False,
                             with_metrics: bool = True,
                             select_impl: str = "sort",
                             tag_width: int = 64,
                             window_m: Optional[int] = None,
                             calendar_impl: str = "minstop",
                             ladder_levels: int = 8,
                             wheel_kernel: str = "xla",
                             hists=None, ledger=None, flight=None,
                             slo=None, prov=None,
                             retries: int = 3, base_s: float = 0.05,
                             sleep: Callable[[float], None] =
                             _time.sleep,
                             on_retry=None, tracer=None,
                             overlap: Optional[Callable[[], None]]
                             = None) -> StreamGuarded:
    """Run one fused ingest+serve stream chunk (``engine.stream``)
    under the guarded-commit contract, at STREAM-CHUNK granularity:

    - the single chunk launch retries transient device failures with
      bounded backoff exactly like the per-epoch launches do;
    - ``overlap()`` (idempotent; may be None) is invoked after the
      launch is DISPATCHED and before the host blocks on it -- the
      double-buffer seam where the caller pre-generates chunk T+1's
      superwave draws while the device runs chunk T;
    - a guard trip ANYWHERE in the chunk (tag32 window, order/cost
      rebase, calendar no-progress) discards the whole chunk and
      re-runs its epochs one by one on the proven round path
      (``run_epoch_guarded``) from the retained entry state + entry
      telemetry -- bit-identical to the round loop by construction,
      since the round loop IS the fallback.  ``stream_fallback``
      reports it; the entry state/telemetry are therefore never
      donated to the chunk launch.

    ``counts`` is ``int32[epochs, N]`` of RAW (unclamped) Poisson
    draws, or None for a no-ingest stream; the chunk clamps on device
    with the identical integer math the round loop's host clamp uses.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..engine import stream as stream_mod
    from ..obs import spans as _spans

    epochs = int(epochs)
    do_ingest = counts is not None
    fn = stream_mod.jit_stream_chunk(
        engine=engine, epochs=epochs, m=m, k=k,
        chain_depth=chain_depth, dt_epoch_ns=dt_epoch_ns, waves=waves,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break, with_metrics=with_metrics,
        select_impl=select_impl, tag_width=tag_width,
        window_m=window_m, calendar_impl=calendar_impl,
        ladder_levels=ladder_levels, wheel_kernel=wheel_kernel,
        ingest=do_ingest, donate=False)
    retry_count = [0]

    def count_retry(attempt, exc):
        retry_count[0] += 1
        _spans.instant(tracer, "stream.retry", "retry",
                       error=type(exc).__name__)
        if on_retry is not None:
            on_retry(attempt, exc)

    counts_dev = None if counts is None \
        else jnp.asarray(counts, dtype=jnp.int32)

    def one():
        with _spans.span(tracer, "stream.dispatch", "dispatch",
                         engine=engine, epochs=epochs):
            out = fn(state, jnp.int64(epoch0), counts_dev,
                     hists, ledger, flight, slo, prov)
        if overlap is not None:
            overlap()     # host pregen rides the device's chunk time
        with _spans.span(tracer, "stream.device_wait",
                         "device_compute"):
            return jax.block_until_ready(out)

    out = retry_with_backoff(one, retries=retries, base_s=base_s,
                             sleep=sleep, on_retry=count_retry)

    guard_field = stream_mod.STREAM_GUARD_FIELD[engine]
    guards = np.asarray(jax.device_get(out.outs[guard_field]))
    if bool(guards.all()):
        fetched = jax.device_get(out.outs)
        views = tuple(stream_mod.epoch_view(engine, fetched, i)
                      for i in range(epochs))
        return StreamGuarded(
            state=out.state, epochs=tuple((v,) for v in views),
            counts=tuple(stream_mod.epoch_decisions(engine, fetched, i)
                         for i in range(epochs)),
            guard_trips=(0,) * epochs, stream_fallback=0,
            retries=retry_count[0], hists=out.hists,
            ledger=out.ledger, flight=out.flight, slo=out.slo,
            prov=out.prov)

    # a guard tripped somewhere in the chunk: the fused program cannot
    # run the tag32/serial resumes mid-scan, so the whole chunk is
    # discarded (its outputs never reach the digest) and its epochs
    # replay on the round path from the RETAINED entry state -- the
    # epochs before the trip recompute bit-identically (pure integer
    # programs), the tripped one resumes exactly as the round loop
    # would have
    _spans.instant(tracer, "stream.fallback", "retry", engine=engine,
                   epochs=epochs)
    ingest_step = stream_mod.jit_ingest_step(
        dt_epoch_ns=dt_epoch_ns, waves=waves) if do_ingest else None
    st = state
    cur = {"hists": hists, "ledger": ledger, "flight": flight,
           "slo": slo, "prov": prov}
    ep_rows, count_rows, trip_rows = [], [], []
    for i in range(epochs):
        t_base = (int(epoch0) + i) * int(dt_epoch_ns)
        if ingest_step is not None:
            st = ingest_step(st, counts_dev[i], jnp.int64(t_base))
        ep = run_epoch_guarded(
            st, t_base + int(dt_epoch_ns), engine=engine, m=m, k=k,
            chain_depth=chain_depth, anticipation_ns=anticipation_ns,
            allow_limit_break=allow_limit_break,
            with_metrics=with_metrics, select_impl=select_impl,
            tag_width=tag_width, window_m=window_m,
            calendar_impl=calendar_impl, ladder_levels=ladder_levels,
            wheel_kernel=wheel_kernel,
            hists=cur["hists"], ledger=cur["ledger"],
            flight=cur["flight"], slo=cur["slo"], prov=cur["prov"],
            retries=retries, base_s=base_s,
            sleep=sleep, on_retry=on_retry, tracer=tracer)
        st = ep.state
        if cur["hists"] is not None:
            cur["hists"] = ep.hists
        if cur["ledger"] is not None:
            cur["ledger"] = ep.ledger
        if cur["flight"] is not None:
            cur["flight"] = ep.flight
        if cur["slo"] is not None:
            cur["slo"] = ep.slo
        if cur["prov"] is not None:
            cur["prov"] = ep.prov
        retry_count[0] += ep.retries
        ep_rows.append(ep.results)
        count_rows.append(ep.count)
        trip_rows.append(ep.rebase_fallbacks + ep.serial_fallbacks)
    return StreamGuarded(
        state=st, epochs=tuple(ep_rows), counts=tuple(count_rows),
        guard_trips=tuple(trip_rows), stream_fallback=1,
        retries=retry_count[0], hists=cur["hists"],
        ledger=cur["ledger"], flight=cur["flight"], slo=cur["slo"],
        prov=cur["prov"])


class MeshGuarded(NamedTuple):
    """Result of :func:`run_mesh_chunk_guarded` -- one mesh chunk of
    epochs across all shards, drained and normalized to per-epoch rows.
    Each row is a tuple of PER-SHARD result-object tuples in SHARD
    ORDER (flatten a row for the chain digest; the grouping is what
    lets a churn job apply each shard's canonical slot->cid view to
    exactly that shard's results).  At S=1 a flattened row is exactly
    the stream loop's."""

    state: object            # stacked EngineState [S, ...]
    cd: object               # int64[S, N] completion counters
    cr: object
    view_d: object           # int64[S, N] held counter views
    view_r: object
    epochs: tuple            # per-epoch tuples of per-shard tuples
    counts: tuple            # per-epoch AGGREGATE decisions (int)
    guard_trips: tuple       # per-epoch rebase+serial fallback count
    mesh_fallback: int       # 1 when the chunk tripped a guard and
    #                          was discarded + re-run epoch-major on
    #                          the host robust loop (slower, never
    #                          divergent; under a fault plan the
    #                          supervisor counts it as a
    #                          mesh_chaos_fallback)
    retries: int
    hists: object = None     # stacked telemetry accumulators
    ledger: object = None
    slo: object = None       # int64[S, N, W_FIELDS] per-shard blocks
    prov: object = None
    slo_merged: object = None  # int64[N, W_FIELDS] cluster-wide block
    flight: object = None    # stacked per-shard flight rings
    press: object = None     # int64[S, PRESS_FIELDS] per-shard
    #                          mid-epoch pressure PEAKS over the chunk
    #                          (with_pressure chunks; max over epochs
    #                          of the post-ingest pre-serve probe --
    #                          the controller's migrate signal, exact
    #                          across both legs because down epochs
    #                          contribute zeros in each)


# eval_shape'd neutral epoch results for the host chaos replay's DOWN
# epochs, keyed by the static epoch configuration + state shape (the
# module-jit-cache convention; eval_shape traces, so it is not free)
_NEUTRAL_EPOCH_CACHE: dict = {}

# one jitted mid-epoch pressure probe for the host replay leg --
# integer-only reads, so the standalone launch is bit-identical to the
# fused chunk's in-scan probe
_PRESSURE_PROBE_JIT: list = []


def _pressure_probe():
    if not _PRESSURE_PROBE_JIT:
        import jax

        from ..obs import provenance as obsprov

        _PRESSURE_PROBE_JIT.append(jax.jit(obsprov.pressure_vec))
    return _PRESSURE_PROBE_JIT[0]


def neutral_epoch_view(engine: str, state_slice, m: int, kw: dict,
                       fault_met=None):
    """The committed-nothing epoch result of a DOWN shard, host-built:
    guard vectors True, slots -1, every count/cost/class 0, metrics =
    the epoch's fault-event delta -- byte-identical (dtype + shape +
    values) to slicing ``parallel.mesh.mask_epoch_outs``'s device
    masks, which is what makes the host chaos replay digest-equal to
    the fused chaos chunk.  Shapes come from ``jax.eval_shape`` of the
    same epoch program the chunk traces (nothing runs)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..engine import fastpath
    from ..engine import stream as stream_mod

    key = (engine, m, tuple(sorted(kw.items())),
           int(state_slice.capacity), int(state_slice.ring_capacity))
    if key not in _NEUTRAL_EPOCH_CACHE:
        fn = fastpath.epoch_scan_fn(engine)
        shapes = jax.eval_shape(
            lambda st: fn(st, jnp.int64(0), m=m, **kw), state_slice)
        fields = {}
        for name in stream_mod.STREAM_OUT_FIELDS[engine]:
            sd = getattr(shapes, name)
            if name in ("guards_ok", "progress_ok"):
                arr = np.ones(sd.shape, dtype=sd.dtype)
            elif name == "slot":
                arr = np.full(sd.shape, -1, dtype=sd.dtype)
            else:
                arr = np.zeros(sd.shape, dtype=sd.dtype)
            arr.setflags(write=False)
            fields[name] = arr
        msd = shapes.metrics
        _NEUTRAL_EPOCH_CACHE[key] = (fields, msd.shape,
                                     np.dtype(msd.dtype))
    fields, mshape, mdtype = _NEUTRAL_EPOCH_CACHE[key]
    metrics = np.zeros(mshape, dtype=mdtype)
    if fault_met is not None:
        metrics += np.asarray(fault_met, dtype=mdtype)
    cls = {"prefix": fastpath.PrefixEpoch,
           "chain": fastpath.ChainEpoch,
           "calendar": fastpath.CalendarEpoch}[engine]
    return cls(state=None, metrics=metrics, **fields)


def _fault_met_vec(dropout: bool, restart: bool, perturb: int):
    """Host numpy twin of the fused chunk's per-epoch fault metric
    delta (rows 9-11 of the obs vector)."""
    import numpy as np

    from ..obs import device as obsdev

    v = np.zeros(obsdev.NUM_METRICS, dtype=np.int64)
    v[obsdev.MET_SERVER_DROPOUTS] = int(dropout)
    v[obsdev.MET_TRACKER_RESYNCS] = int(restart)
    v[obsdev.MET_FAULTS_INJECTED] = \
        int(dropout) + int(restart) + int(perturb)
    return v


def run_mesh_chunk_guarded(state, cd, cr, view_d, view_r,
                           epoch0: int, counts, *, mesh,
                           engine: str, epochs: int, m: int,
                           k: int = 0, chain_depth: int = 4,
                           dt_epoch_ns: int, waves: int,
                           anticipation_ns: int = 0,
                           allow_limit_break: bool = False,
                           with_metrics: bool = True,
                           select_impl: str = "sort",
                           tag_width: int = 64,
                           window_m: Optional[int] = None,
                           calendar_impl: str = "minstop",
                           ladder_levels: int = 8,
                           wheel_kernel: str = "xla",
                           counter_sync_every: int = 1,
                           collective_skipping: Optional[bool] = None,
                           with_pressure: bool = False,
                           hists=None, ledger=None, slo=None,
                           prov=None, flight=None, faults=None,
                           retries: int = 3, base_s: float = 0.05,
                           sleep: Callable[[float], None] =
                           _time.sleep,
                           on_retry=None, tracer=None) -> MeshGuarded:
    """Run one fused mesh chunk (``parallel.mesh``) under the
    guarded-commit contract at MESH-CHUNK granularity: bounded retry
    around the single launch, and -- on a guard trip ANYWHERE in the
    chunk, on any shard -- the whole chunk is discarded and its epochs
    replay EPOCH-MAJOR, SHARD-MINOR on the proven host robust loop
    (:func:`mesh_chunk_host_replay`: ``run_epoch_guarded`` per shard
    per epoch, with the counter-view psum recomputed on the host at
    each global sync boundary), which reproduces the fused program's
    lockstep sync semantics exactly: epoch e's views on every shard
    read the cluster counters as of the end of epoch e-1.  ``slo``
    must always be a window block (the counter plane diffs it);
    ``counts`` is ``int32[S, E, N]`` raw draws or None for serve-only
    chunks.

    ``faults`` (a ``robust.faults.FaultChunk`` or None) compiles the
    PR-3 fault model into the launch (``parallel.mesh`` documents the
    in-chunk semantics); the guard-trip fallback replays the SAME
    fault schedule on the host robust loop, so a chaos chunk degrades
    to the proven path without ever dropping the plan.  ``flight`` is
    the stacked per-shard flight-ring state (or None).

    ``collective_skipping=None`` resolves PER CHUNK from the host-side
    ``epoch0``: the grouped (collective-free non-sync epochs) program
    is picked only when the chunk is fault-free, ``epochs`` divides by
    ``counter_sync_every`` > 1, AND ``epoch0`` lands on the sync grid
    -- the alignment ``parallel.mesh.build_mesh_chunk`` documents as
    the bit-identity condition.  Off-grid chunks run the flat program
    (bit-identity over raw launch count)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..engine import stream as stream_mod
    from ..obs import slo as obsslo
    from ..obs import spans as _spans
    from ..parallel import mesh as mesh_mod

    epochs = int(epochs)
    do_ingest = counts is not None
    n_shards = int(np.asarray(jax.device_get(cd)).shape[0])
    # normalize EVERY sharded input onto the servers mesh axis before
    # the launch: entry state arrives from three sources (fresh init,
    # checkpoint restore, a previous chunk's host fallback restack)
    # with three different placements, and a compiled mesh executable
    # called with a mismatched input sharding either errors or forces
    # a silent recompile (phantom retraces in the capacity plane)
    from jax.sharding import NamedSharding, PartitionSpec as _P

    sharding = NamedSharding(mesh, _P(mesh_mod.SERVER_AXIS))

    def put(tree):
        return None if tree is None else jax.tree.map(
            lambda a: jax.device_put(a, sharding), tree)

    if slo is None:
        # the counter plane diffs the window block's delivered
        # columns, so a block must ride even when the caller runs the
        # SLO plane off -- build the throwaway here (chunk-local:
        # only cd/cr persist) instead of trapping the caller with a
        # default that crashes mid-trace
        n = int(np.asarray(jax.device_get(cd)).shape[1])
        slo = mesh_mod.stack_shards(obsslo.window_zero(n), n_shards)
    state, cd, cr, view_d, view_r = (put(x) for x in
                                     (state, cd, cr, view_d, view_r))
    hists, ledger, slo, prov, flight = (put(x) for x in
                                        (hists, ledger, slo, prov,
                                         flight))
    faults_dev = None
    if faults is not None:
        faults_dev = tuple(
            jax.device_put(jnp.asarray(a), sharding) for a in faults)
    every = max(int(counter_sync_every), 1)
    if collective_skipping is None:
        collective_skipping = (faults is None and every > 1
                               and epochs % every == 0
                               and int(epoch0) % every == 0)
    fn = mesh_mod.jit_mesh_chunk(
        mesh, engine=engine, epochs=epochs, m=m, k=k,
        chain_depth=chain_depth, dt_epoch_ns=dt_epoch_ns, waves=waves,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break,
        with_metrics=with_metrics, select_impl=select_impl,
        tag_width=tag_width, window_m=window_m,
        calendar_impl=calendar_impl, ladder_levels=ladder_levels,
        wheel_kernel=wheel_kernel,
        counter_sync_every=counter_sync_every,
        collective_skipping=collective_skipping, ingest=do_ingest,
        with_faults=faults is not None,
        with_flight=flight is not None,
        with_pressure=with_pressure)
    retry_count = [0]

    def count_retry(attempt, exc):
        retry_count[0] += 1
        _spans.instant(tracer, "mesh.retry", "retry",
                       error=type(exc).__name__)
        if on_retry is not None:
            on_retry(attempt, exc)

    counts_dev = None if counts is None \
        else jax.device_put(jnp.asarray(counts, dtype=jnp.int32),
                            sharding)

    def one():
        with _spans.span(tracer, "mesh.dispatch", "dispatch",
                         engine=engine, epochs=epochs,
                         shards=n_shards, chaos=faults is not None):
            out = fn(state, cd, cr, view_d, view_r,
                     jnp.int64(epoch0), counts_dev, hists, ledger,
                     slo, prov, flight, faults_dev)
        with _spans.span(tracer, "mesh.device_wait",
                         "device_compute"):
            return jax.block_until_ready(out)

    out = retry_with_backoff(one, retries=retries, base_s=base_s,
                             sleep=sleep, on_retry=count_retry)

    guard_field = stream_mod.STREAM_GUARD_FIELD[engine]
    guards = np.asarray(jax.device_get(out.outs[guard_field]))
    if bool(guards.all()):
        fetched = jax.device_get(out.outs)
        press = None
        if with_pressure:
            # per-shard chunk PEAKS: max over the epoch axis of the
            # mid-epoch probe rows (down epochs read zeros -- a no-op
            # under max on the nonneg fields)
            press = np.asarray(fetched["pressure"],
                               dtype=np.int64).max(axis=1)
        return MeshGuarded(
            state=out.state, cd=out.cd, cr=out.cr,
            view_d=out.view_d, view_r=out.view_r,
            epochs=tuple(
                mesh_mod.mesh_epoch_results(engine, fetched, i)
                for i in range(epochs)),
            counts=tuple(
                mesh_mod.mesh_epoch_decisions(engine, fetched, i)
                for i in range(epochs)),
            guard_trips=(0,) * epochs, mesh_fallback=0,
            retries=retry_count[0], hists=out.hists,
            ledger=out.ledger, slo=out.slo, prov=out.prov,
            slo_merged=out.slo_merged, flight=out.flight,
            press=press)

    # a guard tripped somewhere in the mesh chunk: discard it (the
    # entry state/counters are never donated) and replay epoch-major
    # on the host robust loop -- under a fault plan this is the
    # proven DEGRADED path (the supervisor counts it as a
    # mesh_chaos_fallback), and the replay carries the identical
    # fault schedule
    _spans.instant(tracer, "mesh.fallback", "retry", engine=engine,
                   epochs=epochs, shards=n_shards,
                   chaos=faults is not None)
    return mesh_chunk_host_replay(
        state, cd, cr, view_d, view_r, epoch0, counts_dev,
        engine=engine, epochs=epochs, m=m, k=k,
        chain_depth=chain_depth, dt_epoch_ns=dt_epoch_ns,
        waves=waves, anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break,
        with_metrics=with_metrics, select_impl=select_impl,
        tag_width=tag_width, window_m=window_m,
        calendar_impl=calendar_impl, ladder_levels=ladder_levels,
        wheel_kernel=wheel_kernel,
        counter_sync_every=counter_sync_every,
        with_pressure=with_pressure,
        hists=hists, ledger=ledger, slo=slo, prov=prov,
        flight=flight, faults=faults, retries=retries,
        base_s=base_s, sleep=sleep, on_retry=on_retry,
        tracer=tracer, _retries_so_far=retry_count[0])


def mesh_chunk_host_replay(state, cd, cr, view_d, view_r,
                           epoch0: int, counts, *,
                           engine: str, epochs: int, m: int,
                           k: int = 0, chain_depth: int = 4,
                           dt_epoch_ns: int, waves: int,
                           anticipation_ns: int = 0,
                           allow_limit_break: bool = False,
                           with_metrics: bool = True,
                           select_impl: str = "sort",
                           tag_width: int = 64,
                           window_m: Optional[int] = None,
                           calendar_impl: str = "minstop",
                           ladder_levels: int = 8,
                           wheel_kernel: str = "xla",
                           counter_sync_every: int = 1,
                           with_pressure: bool = False,
                           hists=None, ledger=None, slo=None,
                           prov=None, flight=None, faults=None,
                           retries: int = 3, base_s: float = 0.05,
                           sleep: Callable[[float], None] =
                           _time.sleep,
                           on_retry=None, tracer=None,
                           _retries_so_far: int = 0) -> MeshGuarded:
    """The HOST ROBUST LOOP: drive one mesh chunk's epochs epoch-major
    shard-minor on the proven per-epoch path, with the counter-view
    psum recomputed as a host sum at the same global sync grid and --
    when ``faults`` is given -- the exact in-chunk fault semantics of
    ``parallel.mesh.build_mesh_chunk``: a down shard runs nothing and
    contributes a :func:`neutral_epoch_view` row, its state/telemetry
    /counters frozen; restarts re-sync the held views off-grid; dup
    doubles the completion fold; skew lenses the shard's clock; fault
    events patch the epoch's metrics rows.

    This is both the guard-trip fallback of
    :func:`run_mesh_chunk_guarded` AND the digest reference the chaos
    gates compare the fused chunk against (tests/test_mesh.py,
    scripts/ci.sh mesh chaos smoke): a seeded chaos chunk must be
    decision-for-decision and counter-view-for-counter-view identical
    to this loop under the same plan.  ``slo`` must be a window block
    (stacked [S, N, W_FIELDS]); ``counts`` is ``int32[S, E, N]`` or
    None."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..engine import fastpath
    from ..engine import stream as stream_mod
    from ..obs import slo as obsslo
    from ..parallel.tracker import global_counters_from

    epochs = int(epochs)
    do_ingest = counts is not None
    n_shards = int(np.asarray(jax.device_get(cd)).shape[0])
    if slo is None:
        # the counter plane diffs the window block's delivered
        # columns; when the caller runs the SLO plane off, ride a
        # throwaway zero block (chunk-local -- only cd/cr persist),
        # exactly like run_mesh_chunk_guarded's fused leg
        from ..parallel import mesh as mesh_mod
        n = int(np.asarray(jax.device_get(cd)).shape[1])
        slo = mesh_mod.stack_shards(obsslo.window_zero(n), n_shards)
    every = max(int(counter_sync_every), 1)
    retry_count = [_retries_so_far]
    ingest_step = stream_mod.jit_ingest_step(
        dt_epoch_ns=dt_epoch_ns, waves=waves) if do_ingest else None
    dev0 = jax.devices()[0]

    def slic(tree, s):
        # per-shard slices re-placed on ONE device: the round-path
        # epoch executables are compiled for single-device inputs,
        # and a slice still committed to the mesh would reject them
        return None if tree is None \
            else jax.tree.map(lambda a: jax.device_put(a[s], dev0),
                              tree)

    sts = [slic(state, s) for s in range(n_shards)]
    cur = {name: [slic(acc, s) for s in range(n_shards)]
           for name, acc in (("hists", hists), ("ledger", ledger),
                             ("slo", slo), ("prov", prov),
                             ("flight", flight))}
    cd_np = np.asarray(jax.device_get(cd), dtype=np.int64).copy()
    cr_np = np.asarray(jax.device_get(cr), dtype=np.int64).copy()
    vd_np = np.asarray(jax.device_get(view_d), dtype=np.int64).copy()
    vr_np = np.asarray(jax.device_get(view_r), dtype=np.int64).copy()
    if faults is not None:
        f_up = np.asarray(faults[0], dtype=bool)
        f_skew = np.asarray(faults[1], dtype=np.int64)
        f_delay = np.asarray(faults[2], dtype=bool)
        f_dup = np.asarray(faults[3], dtype=bool)
        up_prev = np.asarray(faults[4], dtype=bool).copy()
    neutral_kw = fastpath.epoch_scan_kwargs(
        engine, k=k, chain_depth=chain_depth, select_impl=select_impl,
        tag_width=tag_width, window_m=window_m,
        calendar_impl=calendar_impl, ladder_levels=ladder_levels,
        wheel_kernel=wheel_kernel,
        anticipation_ns=anticipation_ns,
        allow_limit_break=allow_limit_break,
        with_metrics=with_metrics)
    press_np = None
    if with_pressure:
        from ..obs import provenance as obsprov
        press_np = np.zeros((n_shards, obsprov.PRESS_FIELDS),
                            dtype=np.int64)
    ep_rows, count_rows, trip_rows = [], [], []
    for i in range(epochs):
        t_base = (int(epoch0) + i) * int(dt_epoch_ns)
        sync = (int(epoch0) + i) % every == 0
        # the epoch-entry psum, from the counters as of the end of
        # epoch i-1 (the fused program's lockstep semantics); under a
        # plan each shard refreshes only per its own masks below.
        # Only reduced when some shard CAN refresh this epoch -- a
        # sync epoch, or an off-grid restart -- so a plain fallback
        # replay at K>1 skips the O(S*N) host sum on non-sync epochs
        may_refresh = sync or (
            faults is not None and bool((f_up[:, i] & ~up_prev).any()))
        g_d = g_r = None
        if may_refresh:
            g_d, g_r = global_counters_from(
                cd_np, cr_np, lambda x: x.sum(axis=0))
        row, n_dec, trips = [], 0, 0
        for s in range(n_shards):
            if faults is not None:
                up = bool(f_up[s, i])
                skew = int(f_skew[s, i])
                delay = bool(f_delay[s, i])
                dup = bool(f_dup[s, i])
                restart = up and not up_prev[s]
                dropout = (not up) and up_prev[s]
                refresh = (sync and up and not delay) or restart
                perturb = (int(dup and up) + int(delay and up)
                           + int(skew != 0 and up))
            else:
                up, skew, dup = True, 0, False
                restart = dropout = False
                perturb = 0
                refresh = sync
            if refresh:
                vd_np[s] = g_d
                vr_np[s] = g_r
            if not up:
                # the shard is DOWN this epoch: nothing runs, nothing
                # commits (arrivals posted to it are lost), its row
                # reads the committed-nothing neutrals + fault rows
                row.append((neutral_epoch_view(
                    engine, sts[s], m, neutral_kw,
                    _fault_met_vec(dropout, restart, perturb)),))
                continue
            if ingest_step is not None:
                # the raw-draw slice is still committed to the whole
                # mesh; the single-device round path needs it local
                sts[s] = ingest_step(
                    sts[s],
                    jax.device_put(counts[s, i], dev0),
                    jnp.int64(t_base + skew))
            if press_np is not None:
                # the fused chunk's mid-epoch probe: post-ingest,
                # pre-serve, at the shard's (skew-lensed) serve time
                press_np[s] = np.maximum(press_np[s], np.asarray(
                    jax.device_get(_pressure_probe()(
                        sts[s],
                        jnp.int64(t_base + skew + int(dt_epoch_ns)))),
                    dtype=np.int64))
            w_prev = np.asarray(jax.device_get(cur["slo"][s]),
                                dtype=np.int64)
            ep = run_epoch_guarded(
                sts[s], t_base + int(dt_epoch_ns), engine=engine,
                m=m, k=k, chain_depth=chain_depth,
                anticipation_ns=anticipation_ns,
                allow_limit_break=allow_limit_break,
                with_metrics=with_metrics, select_impl=select_impl,
                tag_width=tag_width, window_m=window_m,
                calendar_impl=calendar_impl,
                ladder_levels=ladder_levels,
                wheel_kernel=wheel_kernel, skew_ns=skew,
                hists=cur["hists"][s], ledger=cur["ledger"][s],
                flight=cur["flight"][s],
                slo=cur["slo"][s], prov=cur["prov"][s],
                retries=retries, base_s=base_s, sleep=sleep,
                on_retry=on_retry, tracer=tracer)
            sts[s] = ep.state
            for name in ("hists", "ledger", "slo", "prov", "flight"):
                if cur[name][s] is not None:
                    cur[name][s] = getattr(ep, name)
            w_now = np.asarray(jax.device_get(ep.slo),
                               dtype=np.int64)
            mult = 2 if dup else 1
            cd_np[s] += (w_now[:, obsslo.W_OPS]
                         - w_prev[:, obsslo.W_OPS]) * mult
            cr_np[s] += (w_now[:, obsslo.W_RESV_OPS]
                         - w_prev[:, obsslo.W_RESV_OPS]) * mult
            retry_count[0] += ep.retries
            results = ep.results
            if restart or perturb:
                # the fused chunk folds the epoch's fault-event delta
                # into its metrics row; patch the first result so the
                # host loop's metric totals match the oracle exactly
                fv = _fault_met_vec(False, restart, perturb)
                r0 = results[0]
                results = (r0._replace(
                    metrics=r0.metrics + jnp.asarray(fv)),) \
                    + results[1:]
            row.append(tuple(results))
            n_dec += ep.count
            trips += ep.rebase_fallbacks + ep.serial_fallbacks
        if faults is not None:
            up_prev = f_up[:, i].copy()
        ep_rows.append(tuple(row))
        count_rows.append(n_dec)
        trip_rows.append(trips)

    def restack(parts):
        if any(p is None for p in parts):
            return None
        return jax.tree.map(lambda *xs: jnp.stack(xs), *parts)

    slo_stacked = restack(cur["slo"])
    return MeshGuarded(
        state=restack(sts), cd=jnp.asarray(cd_np),
        cr=jnp.asarray(cr_np), view_d=jnp.asarray(vd_np),
        view_r=jnp.asarray(vr_np), epochs=tuple(ep_rows),
        counts=tuple(count_rows), guard_trips=tuple(trip_rows),
        mesh_fallback=1, retries=retry_count[0],
        hists=restack(cur["hists"]), ledger=restack(cur["ledger"]),
        slo=slo_stacked, prov=restack(cur["prov"]),
        flight=restack(cur["flight"]),
        slo_merged=jnp.asarray(obsslo.window_combine_np(
            np.zeros_like(np.asarray(slo_stacked[0])),
            *np.asarray(jax.device_get(slo_stacked)))),
        press=press_np)


# ----------------------------------------------------------------------
# escalation / degradation ladder (docs/ROBUSTNESS.md)
# ----------------------------------------------------------------------

# Rung order is cheapest-concession-first: each (knob, fast, safe)
# step trades a fast path for its always-exact twin, and every rung is
# already pinned bit-identical/exact by the differential suites
# (tests/test_calendar_wheel.py, tests/test_calendar_bucketed.py,
# tests/test_radix.py), so a degraded run is SLOWER, never DIVERGENT.
# The two calendar rungs share a knob and CHAIN: wheel steps down to
# bucketed first, and a second concession carries bucketed to minstop
# -- rung engagement is keyed by (knob, fast), not knob alone.
LADDER_RUNGS = (
    ("calendar_impl", "wheel", "bucketed"),
    ("calendar_impl", "bucketed", "minstop"),
    ("select_impl", "radix", "sort"),
    ("tag_width", 32, 64),
)


class LadderStep(NamedTuple):
    """One recorded step-down."""

    knob: str
    from_value: object
    to_value: object
    reason: str     # "guard_trips" | "launch_failures" | "resumed"


class DegradationLadder:
    """Escalation policy over the guarded-commit contract: when an
    epoch loop keeps tripping guards or exhausting launch retries for
    ``threshold`` consecutive epochs, step down ONE rung of
    :data:`LADDER_RUNGS` (the first still engaged in the caller's
    config) and keep serving.  Disabled (``enabled=False``) it is
    inert: ``apply`` is the identity and ``note_epoch`` never steps --
    the zero-cost-when-off gate pins a disabled ladder's obs row at 0.

    The engaged-rung set is tiny host state; :meth:`encode` /
    :meth:`load` round-trip it through an int64 vector so the
    supervisor can carry ladder position inside its rotation
    checkpoints (a resumed run must keep serving at the same degraded
    operating point, or the replay would diverge from the
    uninterrupted run)."""

    def __init__(self, enabled: bool = True, threshold: int = 2,
                 tracer=None):
        self.enabled = bool(enabled)
        self.threshold = max(int(threshold), 1)
        self.steps: list = []       # LadderStep, in engagement order
        self._consecutive = 0
        # optional obs.spans.SpanTracer: step-downs record a "retry"
        # instant so the timeline shows WHEN the run degraded
        self.tracer = tracer

    @property
    def steps_taken(self) -> int:
        return len(self.steps)

    def _engaged(self, knob: str, fast) -> bool:
        # keyed by (knob, fast): the two calendar rungs share a knob,
        # and engaging wheel->bucketed must not imply
        # bucketed->minstop
        return any(s.knob == knob and s.from_value == fast
                   for s in self.steps)

    def apply(self, cfg: dict) -> dict:
        """Map a config through the engaged rungs (a knob already at
        its safe value is untouched).  Rung order chains the shared-
        knob calendar rungs: wheel->bucketed rewrites the value the
        bucketed->minstop rung then reads."""
        out = dict(cfg)
        for knob, fast, safe in LADDER_RUNGS:
            if self._engaged(knob, fast) and out.get(knob) == fast:
                out[knob] = safe
        return out

    def can_step(self, cfg: dict) -> bool:
        """True while a rung is still engageable for ``cfg`` -- the
        retry loops use this to bound re-attempts: a failure with
        nothing left to concede must surface, not spin."""
        return self.enabled and any(
            cfg.get(knob) == fast and not self._engaged(knob, fast)
            for knob, fast, _safe in LADDER_RUNGS)

    def note_epoch(self, cfg: dict, *, guard_trips: int = 0,
                   launch_failures: int = 0) -> int:
        """Observe one epoch's fault counters (POST-``apply`` config).
        Returns the number of step-downs taken (0 or 1); a clean epoch
        resets the consecutive-trip counter."""
        if not self.enabled:
            return 0
        if not (guard_trips or launch_failures):
            self._consecutive = 0
            return 0
        self._consecutive += 1
        if self._consecutive < self.threshold:
            return 0
        self._consecutive = 0
        for knob, fast, safe in LADDER_RUNGS:
            if cfg.get(knob) == fast and not self._engaged(knob, fast):
                reason = "guard_trips" if guard_trips \
                    else "launch_failures"
                self.steps.append(LadderStep(knob, fast, safe, reason))
                if self.tracer is not None:
                    self.tracer.instant(
                        "ladder.step", "retry", knob=knob,
                        to=str(safe), reason=reason)
                return 1
        return 0    # fully degraded already; nothing left to concede

    def describe(self) -> list:
        """JSON-able step list for bench lines / history records."""
        return [{"knob": s.knob, "from": s.from_value,
                 "to": s.to_value, "reason": s.reason}
                for s in self.steps]

    # -- checkpoint round-trip (int64[R + 1]: engaged flags + counter)
    def encode(self):
        import numpy as np
        vec = [1 if self._engaged(knob, fast) else 0
               for knob, fast, _ in LADDER_RUNGS]
        return np.asarray(vec + [self._consecutive], dtype=np.int64)

    def load(self, vec) -> None:
        import numpy as np
        vec = np.asarray(vec, dtype=np.int64)
        assert vec.shape == (len(LADDER_RUNGS) + 1,), vec.shape
        self.steps = [LadderStep(knob, fast, safe, "resumed")
                      for flag, (knob, fast, safe)
                      in zip(vec[:-1], LADDER_RUNGS) if flag]
        self._consecutive = int(vec[-1])
