"""Host-level fault plans: the PR-3 fault vocabulary aimed at the
runner process itself (docs/ROBUSTNESS.md).

``robust.faults`` injects *device/cluster* faults (dropout, stale
counters, skew, duplicated completions); a :class:`HostFaultPlan`
injects the failures that kill the HOST half of a run:

- **kill by decision count** (``kill_at_decisions``): SIGKILL the
  runner the first time the cumulative decision total crosses a
  point -- mid-interval between two rotation checkpoints, the worst
  place to die;
- **kill during a checkpoint save** (``kill_at_save``): die INSIDE
  ``utils.checkpoint.save_pytree`` at a named ``_crash_hook`` stage of
  a given epoch's save -- the torn-snapshot scenarios the atomic save
  path exists for;
- **checkpoint corruption during save** (``corrupt_save_at``): the
  save commits, then payload bytes rot underneath it (flipped via the
  ``_post_commit_hook`` seam) -- resume must fall back past the
  corrupt entry to the newest intact rotation snapshot;
- **scrape-port loss** (``drop_scrape_at``): the metrics HTTP endpoint
  vanishes at an epoch boundary; the runner must rebind soft
  (``obs.registry.start_http_server`` fail-soft + ``SO_REUSEADDR``)
  without perturbing the run.

Plans are host data sampled once from a seed (PCG64, stable across
runs) or built explicitly; an empty plan (:func:`zero_host_plan`) is
pinned bit-identical to running with no supervisor fault plumbing at
all (the zero-host-fault gate, ``tests/test_supervisor.py`` +
``scripts/ci.sh`` crash smoke).

The :class:`HostFaultInjector` arms a plan against a live job loop.
Every point fires **exactly once across restarts**: the injector
appends the point id to a ``host_faults.fired`` write-ahead file
(flush + fsync) *before* acting, so a resumed process -- which replays
the same deterministic decision stream through the same thresholds --
skips already-fired points instead of dying in a loop.
"""

from __future__ import annotations

import os
import signal
from typing import NamedTuple, Optional, Tuple

import numpy as np

from ..utils import checkpoint as ckpt_mod


# The controller's journal-then-apply sequence exposes three named
# kill points per decision (control/journal.py "Replay" contract):
# before the WAL line is durable, after-write-but-before-apply, and
# after the knob vector moved (but before the boundary's checkpoint).
CONTROLLER_STAGES = ("before_journal", "after_journal", "after_apply")


class HostKill(BaseException):
    """In-process stand-in for SIGKILL (a BaseException, so no
    ``except Exception`` inside the job can swallow it) -- what the
    trampoline-mode injector raises at a plan point."""


class HostFaultPlan(NamedTuple):
    """Deterministic host fault schedule.  All fields are tuples of
    plain ints/strs so a plan JSON-round-trips into the spawn-mode
    child process unchanged."""

    kill_at_decisions: Tuple[int, ...] = ()
    # (epoch, stage) pairs; stage from utils.checkpoint.SAVE_STAGES
    kill_at_save: Tuple[Tuple[int, str], ...] = ()
    corrupt_save_at: Tuple[int, ...] = ()     # epochs whose save rots
    drop_scrape_at: Tuple[int, ...] = ()      # epochs losing the port
    # (epoch, stage) pairs; stage from CONTROLLER_STAGES -- die inside
    # the controller's journal-then-apply sequence at that boundary
    kill_at_controller: Tuple[Tuple[int, str], ...] = ()


def zero_host_plan() -> HostFaultPlan:
    """The empty plan: supervisor-wrapped must be bit-identical to the
    bare runner under it."""
    return HostFaultPlan()


def host_plan_events(plan: Optional[HostFaultPlan]) -> dict:
    """Host-side ground truth of what a full run of ``plan`` injects
    (the oracle the supervisor's restart accounting is checked
    against: every kill point is one restart, corruption alone kills
    nothing)."""
    if plan is None:
        return {"kills": 0, "save_kills": 0, "corrupt_saves": 0,
                "scrape_drops": 0, "ctl_kills": 0, "restarts": 0}
    kills = len(plan.kill_at_decisions)
    save_kills = len(plan.kill_at_save)
    ctl_kills = len(getattr(plan, "kill_at_controller", ()))
    return {
        "kills": kills,
        "save_kills": save_kills,
        "corrupt_saves": len(plan.corrupt_save_at),
        "scrape_drops": len(plan.drop_scrape_at),
        "ctl_kills": ctl_kills,
        "restarts": kills + save_kills + ctl_kills,
    }


def describe_host(plan: Optional[HostFaultPlan]) -> str:
    """Compact history tag (the ``robust.faults.describe`` analog):
    ``"none"`` for no/empty plan, else a summary naming the fault mix
    so supervised chaos sessions self-identify in bench history."""
    ev = host_plan_events(plan)
    if sum(ev.values()) == 0:
        return "none"
    tag = (f"host:kill{ev['kills']}+savekill{ev['save_kills']}"
           f"+corrupt{ev['corrupt_saves']}+scrape{ev['scrape_drops']}")
    if ev["ctl_kills"]:
        tag += f"+ctlkill{ev['ctl_kills']}"
    return tag


def sample_host_plan(seed: int, *, epochs: int, est_decisions: int,
                     kills: int = 1, save_kills: int = 0,
                     corrupt_saves: int = 0, scrape_drops: int = 0,
                     ckpt_every: int = 2) -> HostFaultPlan:
    """Sample a deterministic plan from ``seed`` (PCG64; stable across
    runs and platforms).  ``est_decisions`` bounds the kill-point
    draw; kill points land strictly inside the run so the final state
    still differs from the fresh one when a kill fires.  Save-stage
    faults target epochs that actually checkpoint (multiples of
    ``ckpt_every``, matching the supervisor's boundary rule)."""
    rng = np.random.Generator(np.random.PCG64(seed))
    lo = max(est_decisions // 8, 1)
    hi = max(est_decisions - lo, lo + 1)
    kill_pts = tuple(sorted(int(x) for x in
                            rng.integers(lo, hi, size=kills)))
    save_epochs = [e for e in range(epochs)
                   if (e + 1) % max(ckpt_every, 1) == 0]
    stages = [s for s in ckpt_mod.SAVE_STAGES if s != "done"]
    saves = tuple(
        (int(rng.choice(save_epochs)), str(rng.choice(stages)))
        for _ in range(save_kills)) if save_epochs else ()
    corrupt = tuple(int(rng.choice(save_epochs))
                    for _ in range(corrupt_saves)) if save_epochs \
        else ()
    drops = tuple(int(x) for x in
                  rng.integers(0, max(epochs, 1), size=scrape_drops))
    return HostFaultPlan(kill_at_decisions=kill_pts,
                         kill_at_save=saves,
                         corrupt_save_at=corrupt,
                         drop_scrape_at=drops)


def plan_to_json(plan: Optional[HostFaultPlan]) -> dict:
    if plan is None:
        plan = zero_host_plan()
    return {"kill_at_decisions": list(plan.kill_at_decisions),
            "kill_at_save": [[int(e), str(s)]
                             for e, s in plan.kill_at_save],
            "corrupt_save_at": list(plan.corrupt_save_at),
            "drop_scrape_at": list(plan.drop_scrape_at),
            "kill_at_controller": [[int(e), str(s)]
                                   for e, s in plan.kill_at_controller]}


def plan_from_json(obj: dict) -> HostFaultPlan:
    return HostFaultPlan(
        kill_at_decisions=tuple(int(x)
                                for x in obj.get("kill_at_decisions",
                                                 ())),
        kill_at_save=tuple((int(e), str(s))
                           for e, s in obj.get("kill_at_save", ())),
        corrupt_save_at=tuple(int(x)
                              for x in obj.get("corrupt_save_at", ())),
        drop_scrape_at=tuple(int(x)
                             for x in obj.get("drop_scrape_at", ())),
        kill_at_controller=tuple(
            (int(e), str(s))
            for e, s in obj.get("kill_at_controller", ())))


class HostFaultInjector:
    """Arms a :class:`HostFaultPlan` against a running job loop.

    ``kill_mode="raise"`` (the in-process trampoline) raises
    :class:`HostKill`; ``kill_mode="sigkill"`` (the child-process
    supervisor) SIGKILLs the interpreter -- the real thing, nothing
    runs after it.  Either way the point id is durably appended to
    ``<workdir>/host_faults.fired`` BEFORE the kill (write-ahead), so
    the point fires exactly once across however many restarts the
    supervisor grants."""

    FIRED_NAME = "host_faults.fired"

    def __init__(self, plan: Optional[HostFaultPlan], workdir: str,
                 kill_mode: str = "raise"):
        assert kill_mode in ("raise", "sigkill"), kill_mode
        self.plan = plan if plan is not None else zero_host_plan()
        self.kill_mode = kill_mode
        self._fired_path = os.path.join(os.fspath(workdir),
                                        self.FIRED_NAME)
        self._fired = set()
        if os.path.exists(self._fired_path):
            with open(self._fired_path) as fh:
                self._fired = {ln.strip() for ln in fh if ln.strip()}

    @property
    def fired(self) -> frozenset:
        return frozenset(self._fired)

    def _mark(self, point: str) -> bool:
        """Durably record ``point`` as fired; False when it already
        was (the replay-after-resume case)."""
        if point in self._fired:
            return False
        self._fired.add(point)
        with open(self._fired_path, "a") as fh:
            fh.write(point + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        return True

    def _kill(self, label: str) -> None:
        if self.kill_mode == "sigkill":
            os.kill(os.getpid(), signal.SIGKILL)
        raise HostKill(label)

    # -- plan points ---------------------------------------------------
    def after_decisions(self, total: int) -> None:
        """Call with the cumulative decision count after each epoch;
        the first crossing of an unfired kill point dies here."""
        for i, point in enumerate(self.plan.kill_at_decisions):
            if total >= point and self._mark(f"dec:{i}"):
                self._kill(f"kill_at_decisions[{i}]={point} "
                           f"(total {total})")

    def controller_point(self, epoch: int, stage: str) -> None:
        """The controller passes this as its ``fault`` seam: each
        decision fires it at every CONTROLLER_STAGES point.  The first
        unfired matching (epoch, stage) plan entry dies here --
        write-ahead marked, so the resumed incarnation replays the
        boundary instead of dying again."""
        for i, (e, s) in enumerate(self.plan.kill_at_controller):
            if e == epoch and s == stage and self._mark(f"ctl:{i}"):
                self._kill(f"kill_at_controller epoch {epoch} "
                           f"stage {stage}")

    def drop_scrape(self, epoch: int) -> bool:
        """True when this epoch's plan says the scrape port vanishes
        (at most once per planned epoch)."""
        hit = False
        for i, e in enumerate(self.plan.drop_scrape_at):
            if e == epoch and self._mark(f"scrape:{i}"):
                hit = True
        return hit

    def around_save(self, epoch: int, save_fn):
        """Run one checkpoint save under the plan: may die at a named
        ``_crash_hook`` stage, and/or have the committed payload rot
        via ``_post_commit_hook``.  Hooks are module-global, so they
        are always uninstalled on the way out (a HostKill must not
        leak a crash hook into the next save)."""
        kill_stage = None
        for i, (e, stage) in enumerate(self.plan.kill_at_save):
            if e == epoch and f"savekill:{i}" not in self._fired:
                kill_stage, kill_id = stage, f"savekill:{i}"
                break

        def crash_hook(stage):
            if stage == kill_stage and self._mark(kill_id):
                self._kill(f"kill_at_save epoch {epoch} "
                           f"stage {stage}")

        corrupt_id = None
        for i, e in enumerate(self.plan.corrupt_save_at):
            if e == epoch and f"corrupt:{i}" not in self._fired:
                corrupt_id = f"corrupt:{i}"
                break

        def post_commit(path):
            if self._mark(corrupt_id):
                _flip_payload_byte(path)

        if kill_stage is not None:
            ckpt_mod._crash_hook = crash_hook
        if corrupt_id is not None:
            ckpt_mod._post_commit_hook = post_commit
        try:
            return save_fn()
        finally:
            ckpt_mod._crash_hook = None
            ckpt_mod._post_commit_hook = None


def _flip_payload_byte(path: str) -> None:
    """Flip one byte in the middle of a committed snapshot's data file
    (media rot under a just-finished save).  The sidecar is left
    alone, so the pair fails digest verification and restore walks
    back to an older intact rotation entry."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.seek(size // 2)
        b = fh.read(1)
        fh.seek(size // 2)
        fh.write(bytes([b[0] ^ 0xFF]))
        fh.flush()
        os.fsync(fh.fileno())
