"""Fault injection and graceful degradation (docs/ROBUSTNESS.md).

- ``robust.faults``  -- deterministic seeded :class:`FaultPlan` pytrees
  (server dropout/restart, delayed piggyback counters, clock skew,
  duplicated completions) and their host-side event oracles.
- ``robust.cluster`` -- degraded-mode cluster stepping: live-server
  masks gate the tracker psum and per-shard commits, restarted shards
  re-sync from the monotone global counters, every fault lands in the
  device metrics vector.  Imported lazily (it pulls in the engine).
- ``robust.guarded`` -- the guarded-commit contract: device guard
  trips commit nothing and the host retries with bounded exponential
  backoff (``retry_with_backoff``; used by the TPU queue around every
  device launch), plus the :class:`DegradationLadder` escalation
  policy (bucketed->minstop, radix->sort, tag32->int64).
- ``robust.host_faults`` -- :class:`HostFaultPlan`: the fault
  vocabulary aimed at the host process (seeded SIGKILL points by
  decision count or checkpoint save stage, checkpoint corruption
  during save, scrape-port loss).  Imported lazily (it pulls in
  ``utils.checkpoint``).
- ``robust.supervisor`` -- runs bench/sim epoch loops as resumable
  jobs: rotating crash-safe checkpoints at epoch boundaries, bounded
  restarts, exactly-once resume, and the crash-equivalence digest
  gate.  Imported lazily.

This ``__init__`` stays light (``engine.queue`` imports
``robust.guarded`` at module load): ``robust.cluster``,
``robust.host_faults``, and ``robust.supervisor`` resolve on first
attribute access.
"""

from . import faults, guarded
from .faults import (FaultPlan, FaultStep, describe, plan_events,
                     plan_step, sample_plan, single_outage_plan,
                     zero_plan)
from .guarded import (LADDER_RUNGS, RECOVERABLE_ERRORS,
                      DegradationLadder, GuardedEpoch, LadderStep,
                      retry_with_backoff, run_epoch_guarded)

__all__ = [
    "faults", "guarded", "cluster", "host_faults", "supervisor",
    "FaultPlan", "FaultStep", "zero_plan", "sample_plan",
    "single_outage_plan", "plan_step", "plan_events", "describe",
    "retry_with_backoff", "run_epoch_guarded", "GuardedEpoch",
    "RECOVERABLE_ERRORS", "DegradationLadder", "LadderStep",
    "LADDER_RUNGS",
]

_LAZY_MODULES = ("cluster", "host_faults", "supervisor")


def __getattr__(name):
    if name in _LAZY_MODULES:
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError(name)
