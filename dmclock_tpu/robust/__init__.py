"""Fault injection and graceful degradation (docs/ROBUSTNESS.md).

- ``robust.faults``  -- deterministic seeded :class:`FaultPlan` pytrees
  (server dropout/restart, delayed piggyback counters, clock skew,
  duplicated completions) and their host-side event oracles.
- ``robust.cluster`` -- degraded-mode cluster stepping: live-server
  masks gate the tracker psum and per-shard commits, restarted shards
  re-sync from the monotone global counters, every fault lands in the
  device metrics vector.  Imported lazily (it pulls in the engine).
- ``robust.guarded`` -- the guarded-commit contract: device guard
  trips commit nothing and the host retries with bounded exponential
  backoff (``retry_with_backoff``; used by the TPU queue around every
  device launch).

This ``__init__`` stays light (``engine.queue`` imports
``robust.guarded`` at module load): ``robust.cluster`` resolves on
first attribute access.
"""

from . import faults, guarded
from .faults import (FaultPlan, FaultStep, describe, plan_events,
                     plan_step, sample_plan, single_outage_plan,
                     zero_plan)
from .guarded import (RECOVERABLE_ERRORS, GuardedEpoch,
                      retry_with_backoff, run_epoch_guarded)

__all__ = [
    "faults", "guarded", "cluster",
    "FaultPlan", "FaultStep", "zero_plan", "sample_plan",
    "single_outage_plan", "plan_step", "plan_events", "describe",
    "retry_with_backoff", "run_epoch_guarded", "GuardedEpoch",
    "RECOVERABLE_ERRORS",
]


def __getattr__(name):
    if name == "cluster":
        import importlib
        return importlib.import_module(".cluster", __name__)
    raise AttributeError(name)
