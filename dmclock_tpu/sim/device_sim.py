"""Device-resident batch-synchronous QoS simulator.

The SURVEY's "sharded batch sim" (parallelism table, SURVEY.md section
2) as a user-facing model: the ENTIRE closed loop -- client load
generation, the delta/rho piggyback protocol, dmClock scheduling, and
service completion -- lives on device, with servers as a mesh axis and
clients vmapped, so one program advances a whole multi-server cluster
thousands of operations per launch.  The host only drives slice chunks
and reads back aggregate stats.

This is deliberately a DIFFERENT model from the discrete-event host
harness (``sim.harness``), trading event-exact timing for compiled
throughput:

- Time advances in fixed slices of ``q * op_time`` ns; a server with
  backlog serves exactly ``q`` requests per slice (its iops rate), and
  every serve in a slice is stamped at the slice boundary.
- A client's sends for a slice are computed from its rate gap and
  window at the slice start; completions feed back with one-slice
  latency (outstanding decreases at the end of the slice that served
  them).
- Server selection: the harness's deterministic policy
  (``Simulation._make_server_select`` non-random branch), or -- with
  ``server_random_selection`` -- a device-side counter RNG
  (splitmix64 hash of (client, send-sequence), reference random policy
  ``simulate.h:401-444``): stateless, reproducible, identical on every
  shard.
- Multi-thread servers serve ``threads * q`` requests per slice (the
  harness's aggregate-rate model: op_time = threads/iops,
  ``sim_server.h:136-139``).

QoS semantics (tags, phases, AtLimit, idle-reactivation, the tracker
algebra) are exactly the engine's -- inherited from ``kernels.ingest``
/ ``engine_run`` and ``parallel.tracker``, the same kernels pinned by
the oracle differential suites.  Behavioral validation:
``tests/test_device_sim.py`` checks weight-proportional shares,
reservation floors, limit caps, and determinism.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import NS_PER_SEC, ClientInfo
from ..engine import kernels
# module-level on purpose: importing fastpath inside a traced function
# would stage its module-level jnp constants into the caller's trace
# (cached in module globals -> UnexpectedTracerError on reuse)
from ..engine.fastpath import (_window_heads, calendar_batch,
                               calendar_batch_bucketed,
                               calendar_batch_wheel, ring_window,
                               speculate_prefix_batch)
from ..engine.state import EngineState, init_state
from ..parallel.cluster import SERVER_AXIS, make_mesh
from ..utils.compat import shard_map
from ..parallel.tracker import (TrackerState, global_counters,
                                init_tracker, tracker_prepare,
                                tracker_track, tracker_track_counts)
from .config import SimConfig


class ClientLoad(NamedTuple):
    """Replicated ([C]) load-generator state, identical on every shard
    (updates derive from psum'd quantities, keeping shards in step)."""

    gap_ns: jnp.ndarray        # int64[C] inter-send gap
    next_send: jnp.ndarray     # int64[C] next send time (TIME-like ns)
    sent: jnp.ndarray          # int32[C] requests sent so far
    total_ops: jnp.ndarray     # int32[C]
    outstanding: jnp.ndarray   # int32[C]
    window: jnp.ndarray        # int32[C] max outstanding
    cost: jnp.ndarray          # int64[C]
    sel_base: jnp.ndarray      # int32[C] server-select base offset
    sel_range: jnp.ndarray     # int32[C] server-select range


class DeviceSim(NamedTuple):
    engine: EngineState        # [S, ...]
    tracker: TrackerState      # [S, C]
    load: ClientLoad           # [C] replicated
    served_resv: jnp.ndarray   # int64[S, C] completions by phase
    served_prop: jnp.ndarray   # int64[S, C]
    last_served: jnp.ndarray   # int64[S, C] slice-end of last completion
    t: jnp.ndarray             # int64 slice-aligned clock (scalar)
    guard_trips: jnp.ndarray   # int32 scalar: prefix rebase-guard trips
    #                            (must stay 0 -- init_device_sim
    #                            validates the only dynamic inputs;
    #                            run_device_sim raises otherwise)


@dataclass
class DeviceSimSpec:
    """Static launch parameters derived from a SimConfig."""

    n_servers: int
    n_clients: int
    op_time_ns: int            # uniform across servers
    q_per_slice: int           # serves per server per slice
    max_sends: int             # per client per slice (static bound)
    slice_ns: int
    allow_limit_break: bool
    all_weights_positive: bool = True  # Allow-fastpath restriction
    random_select: bool = False
    force_scan: bool = False   # test hook: disable the prefix serve
    select_impl: str = "sort"  # prefix selection backend
    #                            ("sort"|"radix"; bit-identical
    #                            decisions -- fastpath select_impl)
    calendar_impl: Optional[str] = None  # None = prefix/scan serving
    #                            only; "minstop"|"bucketed" front-loads
    #                            each slice with sortless calendar
    #                            batches (whole batches only, budget-
    #                            gated; the capped prefix loop finishes
    #                            the slice), so skewed populations
    #                            serve without the per-batch sort --
    #                            service is EXACTLY the q-step serial
    #                            stream either way
    calendar_steps: int = 8    # per-client serve budget per calendar
    #                            batch (<= ring_capacity)
    ladder_levels: int = 4     # fused ladder levels ("bucketed")


def _make_spec(cfg: SimConfig, q_per_slice: int = 4) -> DeviceSimSpec:
    iops = {g.server_iops for g in cfg.srv_group}
    threads = {g.server_threads for g in cfg.srv_group}
    assert len(iops) == 1 and len(threads) == 1, \
        "device_sim: uniform server groups (iops and threads)"
    n_servers = sum(g.server_count for g in cfg.srv_group)
    n_clients = sum(g.client_count for g in cfg.cli_group)
    n_threads = threads.pop()
    # aggregate service rate stays iops: T threads each at op_time =
    # T/iops (sim_server.h:136-139) -> T*q serves per q*op_time slice
    op_time_ns = int(0.5 + n_threads * 1e6 / iops.pop()) * 1000
    slice_ns = op_time_ns * q_per_slice
    q_per_slice = q_per_slice * n_threads
    # static bound on sends per client per slice; refuse configs whose
    # offered load cannot be expressed (a silent clamp would misreport
    # a simulator artifact as a QoS limit)
    min_gap = min(int(0.5 + 1e6 / g.client_iops_goal) * 1000
                  for g in cfg.cli_group)
    max_sends = max(1, slice_ns // max(min_gap, 1) + 1)
    assert max_sends <= 16, (
        f"client iops goals need {max_sends} sends/client/slice; the "
        "wave unroll caps at 16 -- raise server_iops (shorter slices) "
        "or lower client_iops_goal")
    return DeviceSimSpec(
        n_servers=n_servers, n_clients=n_clients,
        op_time_ns=op_time_ns, q_per_slice=q_per_slice,
        max_sends=max_sends, slice_ns=slice_ns,
        allow_limit_break=cfg.server_soft_limit,
        all_weights_positive=all(g.client_weight > 0
                                 for g in cfg.cli_group),
        random_select=cfg.server_random_selection)


def init_device_sim(cfg: SimConfig, ring_capacity: int = 256,
                    select_impl: str = "sort",
                    calendar_impl: Optional[str] = None,
                    calendar_steps: int = 8,
                    ladder_levels: int = 4
                    ) -> tuple[DeviceSim, DeviceSimSpec]:
    assert calendar_impl in (None, "minstop", "bucketed",
                             "wheel"), calendar_impl
    assert 1 <= calendar_steps <= ring_capacity, \
        "calendar_steps must fit the ring window"
    assert ladder_levels >= 1
    spec = _make_spec(cfg)
    spec.select_impl = select_impl
    spec.calendar_impl = calendar_impl
    spec.calendar_steps = calendar_steps
    spec.ladder_levels = ladder_levels
    s, c = spec.n_servers, spec.n_clients
    max_window = max(g.client_outstanding_ops for g in cfg.cli_group)
    assert max_window <= ring_capacity, (
        f"client_outstanding_ops {max_window} can exceed a per-client "
        f"ring of {ring_capacity}; raise ring_capacity")
    # the prefix serve path's rebase guards depend on request cost and
    # creation-order spread; both are static here (costs from config,
    # order = arange(C) fixed at init), so validating cost once makes a
    # guard failure impossible by construction -- the serve loop relies
    # on this to skip the per-batch guards_ok check
    max_cost = max(g.client_req_cost for g in cfg.cli_group)
    assert 0 < max_cost < (1 << 31), (
        f"client_req_cost {max_cost} overflows the int32 sort payload "
        "of the prefix serve path")

    infos, gaps, waits, totals, windows, costs, ranges = \
        [], [], [], [], [], [], []
    for g in cfg.cli_group:
        for _ in range(g.client_count):
            infos.append(ClientInfo(g.client_reservation,
                                    g.client_weight, g.client_limit))
            gaps.append(int(0.5 + 1e6 / g.client_iops_goal) * 1000)
            waits.append(int(g.client_wait_s * NS_PER_SEC))
            totals.append(g.client_total_ops)
            windows.append(g.client_outstanding_ops)
            costs.append(g.client_req_cost)
            ranges.append(min(g.client_server_select_range, s))

    factor = s / max(1, c)
    sel_base = np.asarray([int(0.5 + i * factor) % s for i in range(c)],
                          dtype=np.int32)

    one = init_state(c, ring_capacity)
    engine = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (s,) + a.shape), one)
    engine = engine._replace(
        active=jnp.ones((s, c), dtype=bool),
        order=jnp.broadcast_to(jnp.arange(c, dtype=jnp.int64), (s, c)),
        resv_inv=jnp.broadcast_to(jnp.asarray(
            [i.reservation_inv_ns for i in infos], jnp.int64), (s, c)),
        weight_inv=jnp.broadcast_to(jnp.asarray(
            [i.weight_inv_ns for i in infos], jnp.int64), (s, c)),
        limit_inv=jnp.broadcast_to(jnp.asarray(
            [i.limit_inv_ns for i in infos], jnp.int64), (s, c)),
    )
    tracker = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (s,) + a.shape), init_tracker(c))
    load = ClientLoad(
        gap_ns=jnp.asarray(gaps, jnp.int64),
        next_send=jnp.asarray(waits, jnp.int64),
        sent=jnp.zeros((c,), jnp.int32),
        total_ops=jnp.asarray(totals, jnp.int32),
        outstanding=jnp.zeros((c,), jnp.int32),
        window=jnp.asarray(windows, jnp.int32),
        cost=jnp.asarray(costs, jnp.int64),
        sel_base=jnp.asarray(sel_base),
        sel_range=jnp.asarray(ranges, jnp.int32),
    )
    sim = DeviceSim(engine=engine, tracker=tracker, load=load,
                    served_resv=jnp.zeros((s, c), jnp.int64),
                    served_prop=jnp.zeros((s, c), jnp.int64),
                    last_served=jnp.zeros((s, c), jnp.int64),
                    t=jnp.int64(0),
                    guard_trips=jnp.int32(0))
    return sim, spec


def shard_device_sim(sim: DeviceSim, mesh: Mesh) -> DeviceSim:
    srv = NamedSharding(mesh, P(SERVER_AXIS))
    rep = NamedSharding(mesh, P())
    return DeviceSim(
        engine=jax.tree.map(lambda a: jax.device_put(a, srv), sim.engine),
        tracker=jax.tree.map(lambda a: jax.device_put(a, srv),
                             sim.tracker),
        load=jax.tree.map(lambda a: jax.device_put(a, rep), sim.load),
        served_resv=jax.device_put(sim.served_resv, srv),
        served_prop=jax.device_put(sim.served_prop, srv),
        last_served=jax.device_put(sim.last_served, srv),
        t=jax.device_put(sim.t, rep),
        guard_trips=jax.device_put(sim.guard_trips, rep),
    )


def _slice_sends(load: ClientLoad, t0, slice_ns: int, max_sends: int):
    """How many sends each client performs this slice (bounded by rate,
    window, and remaining ops), all from slice-start state.

    Model bound: a client catching up after a window stall emits at
    most ``max_sends`` per slice even if its rate debt is larger (the
    wave unroll is static); the debt carries over via ``next_send``, so
    offered load is deferred, never lost.  _make_spec's assert covers
    the steady-state rate; this bound only shapes post-stall bursts."""
    t_end = t0 + slice_ns
    by_rate = jnp.where(
        load.next_send < t_end,
        ((t_end - load.next_send) + load.gap_ns - 1) // load.gap_ns,
        0).astype(jnp.int32)
    n = jnp.minimum(jnp.minimum(by_rate, max_sends),
                    jnp.minimum(load.window - load.outstanding,
                                load.total_ops - load.sent))
    return jnp.maximum(n, 0)


def _splitmix64(x):
    """Stateless counter hash (splitmix64 finalizer): the device-side
    RNG for random server selection -- same value on every shard for a
    given (client, sequence), no carried RNG state."""
    x = (x + jnp.int64(-7046029254386353131))      # 0x9E3779B97F4A7C15
    z = x
    z = (z ^ (z >> 30)) * jnp.int64(-4658895280553007687)
    z = (z ^ (z >> 27)) * jnp.int64(-7723592293110705685)
    return z ^ (z >> 31)


def _sends_to_server(load: ClientLoad, n, wave: int, server_ids,
                     n_servers: int, random_select: bool):
    """Does client c's ``wave``-th send this slice target THIS server?
    Deterministic policy: (sel_base + seq % range) % n_servers; random
    policy: sel_base + hash(client, seq) % range (the reference picks
    uniformly within the client's server window, simulate.h:401-444).
    ``n_servers`` is the static GLOBAL count -- server_ids.shape[0]
    inside shard_map is only the local shard slice."""
    seq = load.sent + wave
    if random_select:
        c = seq.shape[0]
        h = _splitmix64(seq.astype(jnp.int64) * jnp.int64(1 << 20)
                        + jnp.arange(c, dtype=jnp.int64))
        pick = jnp.remainder(jnp.abs(h), load.sel_range.astype(jnp.int64))
        target = (load.sel_base + pick.astype(jnp.int32)) % n_servers
    else:
        target = (load.sel_base
                  + jnp.remainder(seq, load.sel_range)) % n_servers
    return (n > wave) & (target[None, :] == server_ids[:, None])


def device_sim_step(sim: DeviceSim, spec: DeviceSimSpec, mesh: Mesh,
                    slices: int) -> DeviceSim:
    """Advance ``slices`` time slices in one launch (jit this)."""
    s_total = spec.n_servers

    def shard_fn(engine, tracker, load, served_resv, served_prop,
                 last_served, t, trips, server_ids):
        def one_slice(carry, _):
            engine, tracker, load, sresv, sprop, slast, t, trips = carry
            # tracker is [S_local, C] inside the shard: the client-global
            # counters reduce over BOTH the local server slice and the
            # mesh axis
            g_delta, g_rho = global_counters(
                tracker, lambda x: lax.psum(x.sum(axis=0), SERVER_AXIS))

            n = _slice_sends(load, t, spec.slice_ns, spec.max_sends)
            c = n.shape[0]

            def ingest_wave(carry2, wave):
                engine, tracker = carry2
                mine = _sends_to_server(load, n, wave, server_ids,
                                        s_total, spec.random_select)

                def per_server(eng, trk, mine_row):
                    trk, d_out, r_out = tracker_prepare(
                        trk, mine_row, g_delta, g_rho)
                    # one request per client per wave, slots distinct:
                    # the vectorized wave ingest scales to 100k-client
                    # slices where the sequential op scan cannot
                    eng = kernels.ingest_wave(
                        eng, mine_row, t, load.cost,
                        jnp.where(mine_row, r_out, 1),
                        jnp.where(mine_row, d_out, 1),
                        anticipation_ns=0)
                    return eng, trk

                engine, tracker = jax.vmap(per_server)(engine, tracker,
                                                       mine)
                return (engine, tracker), None

            # python-unrolled waves (max_sends is static and small)
            for wave in range(spec.max_sends):
                (engine, tracker), _ = ingest_wave((engine, tracker),
                                                   wave)

            # serve q decisions per server at the slice boundary.
            # Large q (throughput shapes) uses prefix-commit batches:
            # sort-and-commit passes instead of a q-step serial scan.
            # A single batch serves each client at most once, so a
            # server whose eligible population is smaller than q
            # (select-range windows, drained/idle clients) would lose
            # the rest of its slice capacity; batches therefore LOOP --
            # each capped at the remaining slice budget, which keeps
            # the concatenated stream the exact serial prefix -- until
            # the budget is met or a batch commits nothing.
            # AtLimit::Allow rides the prefix path too (limit-break
            # candidates are a third unified class), PROVIDED every
            # client has weight > 0: a ready weight-0 client switches
            # the reference's Allow fallback to reservation order
            # globally, which per-client classification cannot express
            # (fastpath module docstring) -- that shape keeps the scan.
            t_end = t + spec.slice_ns
            # opting into the calendar serve path implies the budgeted
            # batch loop (it is exact at any q; the q >= 256 heuristic
            # only picks the default)
            use_prefix = ((spec.q_per_slice >= 256
                           or spec.calendar_impl is not None)
                          and (not spec.allow_limit_break
                               or spec.all_weights_positive)
                          and not spec.force_scan)
            use_cal = use_prefix and spec.calendar_impl is not None
            if spec.calendar_impl is not None and not use_cal:
                # refuse rather than silently A/B two identical
                # scan-path runs: the Allow-with-weight-0 shape (and
                # the force_scan test hook) cannot serve through the
                # batch loop at all (fastpath module docstring)
                raise ValueError(
                    "calendar_impl requires the batch serve loop: "
                    "incompatible with force_scan, and with "
                    "allow_limit_break unless every client weight "
                    "is positive")

            if use_prefix:
                q = spec.q_per_slice
                # the selection sort yields one row per client, so a
                # batch is at most n_clients wide; the loop covers q
                kb = min(q, spec.n_clients)

                def per_server_run(eng):
                    d0 = kernels.Decision(
                        type=jnp.full((q,), kernels.NONE, jnp.int32),
                        slot=jnp.full((q,), -1, jnp.int32),
                        phase=jnp.zeros((q,), jnp.int32),
                        cost=jnp.zeros((q,), jnp.int64),
                        when=jnp.zeros((q,), jnp.int64),
                        limit_break=jnp.zeros((q,), bool))

                    # --- calendar front-load (spec.calendar_impl):
                    # commit WHOLE sortless calendar batches while they
                    # fit the remaining slice budget -- each batch is an
                    # exact serial prefix, and a batch that would
                    # overshoot q is discarded untaken, so the capped
                    # prefix loop below finishes the slice exactly.
                    # Counts-only emission: the tracker and the stats
                    # fold per-client totals (tracker_track_counts).
                    cal_total = jnp.int32(0)
                    cal_srv = cal_rsv = None
                    if use_cal:
                        steps = min(spec.calendar_steps,
                                    eng.ring_capacity)
                        zc = jnp.zeros((spec.n_clients,), jnp.int32)

                        def cal_cond(carry):
                            return carry[4]

                        def cal_body(carry):
                            eng, srv, rsv, total, _ = carry
                            if spec.calendar_impl == "wheel":
                                b = calendar_batch_wheel(
                                    eng, t_end, steps=steps,
                                    levels=spec.ladder_levels,
                                    anticipation_ns=0,
                                    allow_limit_break=spec
                                    .allow_limit_break,
                                    use_pallas=False)
                            elif spec.calendar_impl == "bucketed":
                                b = calendar_batch_bucketed(
                                    eng, t_end, steps=steps,
                                    levels=spec.ladder_levels,
                                    anticipation_ns=0,
                                    allow_limit_break=spec
                                    .allow_limit_break,
                                    use_pallas=False)
                            else:
                                win = ring_window(eng, steps,
                                                  use_pallas=False)
                                b = calendar_batch(
                                    eng, t_end, steps=steps,
                                    anticipation_ns=0,
                                    allow_limit_break=spec
                                    .allow_limit_break,
                                    heads=(win.arr, win.cost))
                            ok = (b.count > 0) & \
                                (total + b.count <= q)
                            eng = jax.tree.map(
                                lambda new, old:
                                jnp.where(ok, new, old),
                                b.state, eng)
                            srv = srv + jnp.where(ok, b.served, 0)
                            rsv = rsv + jnp.where(ok, b.served_resv,
                                                  0)
                            total = (total
                                     + jnp.where(ok, b.count, 0)
                                     ).astype(jnp.int32)
                            return (eng, srv, rsv, total, ok)

                        eng, cal_srv, cal_rsv, cal_total, _ = \
                            lax.while_loop(
                                cal_cond, cal_body,
                                (eng, zc, zc, jnp.int32(0),
                                 jnp.bool_(True)))

                    def cond(carry):
                        _eng, total, last, _d, _gt = carry
                        return (total < q) & (last > 0)

                    def body(carry):
                        eng, total, _last, dbuf, gt = carry
                        # guards_ok cannot legitimately fail here: its
                        # only dynamic inputs (cost, creation-order
                        # spread) are static in this sim and validated
                        # at init_device_sim.  The trip counter makes
                        # that invariant CHECKED rather than assumed:
                        # run_device_sim raises if it ever goes
                        # nonzero (a future init_device_sim edit that
                        # weakens the validation would surface, not
                        # silently under-serve).
                        # The ring-head read forces the XLA rotate:
                        # this whole body runs under vmap (servers),
                        # which would grid the gridless Pallas kernel
                        # -- ungridded is all the remote Mosaic
                        # compiler accepts.
                        heads = _window_heads(eng, ring_window(
                            eng, 1, use_pallas=False))
                        batch = speculate_prefix_batch(
                            eng, t_end, kb, anticipation_ns=0,
                            max_count=q - total, heads=heads,
                            allow_limit_break=spec.allow_limit_break,
                            select_impl=spec.select_impl)
                        gt = gt + jnp.where(batch.guards_ok, 0,
                                            1).astype(jnp.int32)
                        # pack the committed prefix at the buffer
                        # offset (invalid rows scatter out of range
                        # and drop; the buffer holds only the prefix-
                        # loop decisions -- calendar serves are folded
                        # as counts)
                        j = jnp.arange(kb, dtype=jnp.int32)
                        pos = jnp.where(j < batch.count,
                                        total - cal_total + j, q)
                        dbuf = jax.tree.map(
                            lambda buf, vals:
                            buf.at[pos].set(vals, mode="drop"),
                            dbuf, batch.decisions)
                        return (batch.state, total + batch.count,
                                batch.count, dbuf, gt)

                    eng, _total, _last, dbuf, gt = lax.while_loop(
                        cond, body,
                        (eng, cal_total, jnp.int32(1), d0,
                         jnp.int32(0)))
                    if use_cal:
                        return eng, dbuf, gt, cal_srv, cal_rsv
                    return eng, dbuf, gt

                if use_cal:
                    engine, decs, gts, cal_srv, cal_rsv = \
                        jax.vmap(per_server_run)(engine)
                else:
                    engine, decs, gts = jax.vmap(per_server_run)(
                        engine)
                trips = (trips + lax.psum(gts.sum(), SERVER_AXIS)
                         ).astype(jnp.int32)
            else:
                def per_server_run(eng):
                    eng, _, d = kernels.engine_run(
                        eng, t_end, spec.q_per_slice,
                        allow_limit_break=spec.allow_limit_break,
                        anticipation_ns=0, advance_now=False)
                    return eng, d

                engine, decs = jax.vmap(per_server_run)(engine)
            served = decs.type == kernels.RETURNING

            def per_server_track(trk, d_slot, d_cost, d_phase, d_srv):
                return tracker_track(trk, d_slot, d_cost, d_phase,
                                     d_srv)

            tracker = jax.vmap(per_server_track)(
                tracker, decs.slot, decs.cost, decs.phase, served)
            if use_cal:
                # calendar serves arrive as per-client totals; the
                # counts fold computes the same sums as the decision-
                # stream fold (per-client cost is constant here)
                tracker = jax.vmap(
                    lambda trk, s_, r_: tracker_track_counts(
                        trk, s_, r_, load.cost))(tracker, cal_srv,
                                                 cal_rsv)

            # stats + completion feedback (one [S_local, q] scatter-add
            # per phase; q is small)
            one = jnp.where(served, 1, 0).astype(jnp.int64)
            idx = jnp.where(served, decs.slot, 0)
            sresv = jax.vmap(lambda a, i, v: a.at[i].add(v))(
                sresv, idx, one * (decs.phase == 0))
            sprop = jax.vmap(lambda a, i, v: a.at[i].add(v))(
                sprop, idx, one * (decs.phase == 1))
            t_end_b = t + spec.slice_ns
            slast = jax.vmap(lambda a, i, v: a.at[i].max(v))(
                slast, idx, jnp.where(served, t_end_b, 0))
            done_here = jax.vmap(
                lambda i, v: jnp.zeros((c,), jnp.int32).at[i].add(
                    v.astype(jnp.int32)))(idx, one)
            if use_cal:
                sresv = sresv + cal_rsv.astype(jnp.int64)
                sprop = sprop + (cal_srv - cal_rsv).astype(jnp.int64)
                slast = jnp.maximum(
                    slast, jnp.where(cal_srv > 0, t_end_b,
                                     jnp.int64(0)))
                done_here = done_here + cal_srv
            completions = lax.psum(done_here.sum(axis=0), SERVER_AXIS)

            sends = n  # every shard computed the same [C] send counts
            load = load._replace(
                sent=(load.sent + sends).astype(jnp.int32),
                outstanding=(load.outstanding + sends
                             - completions).astype(jnp.int32),
                next_send=load.next_send
                + sends.astype(jnp.int64) * load.gap_ns,
            )
            return (engine, tracker, load, sresv, sprop, slast,
                    t_end, trips), None

        (engine, tracker, load, served_resv, served_prop, last_served,
         t, trips), _ = lax.scan(
            one_slice,
            (engine, tracker, load, served_resv, served_prop,
             last_served, t, trips), None, length=slices)
        return (engine, tracker, load, served_resv, served_prop,
                last_served, t, trips)

    srv = P(SERVER_AXIS)
    rep = P()
    server_ids = jnp.arange(s_total, dtype=jnp.int32)
    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(srv, srv, rep, srv, srv, srv, rep, rep, srv),
        out_specs=(srv, srv, rep, srv, srv, srv, rep, rep),
        check_vma=False)
    (engine, tracker, load, served_resv, served_prop, last_served, t,
     trips) = fn(sim.engine, sim.tracker, sim.load, sim.served_resv,
                 sim.served_prop, sim.last_served, sim.t,
                 sim.guard_trips, server_ids)
    return DeviceSim(engine=engine, tracker=tracker, load=load,
                     served_resv=served_resv, served_prop=served_prop,
                     last_served=last_served, t=t, guard_trips=trips)


def check_guard_trips(sim: DeviceSim) -> None:
    """Raise if any prefix batch tripped a rebase guard.  The guards'
    only dynamic inputs (request cost, creation-order spread) are
    validated statically by init_device_sim, so a trip means that
    validation no longer covers the workload and committed counts are
    untrustworthy."""
    trips = int(np.asarray(sim.guard_trips))
    if trips:
        raise RuntimeError(
            f"device_sim: {trips} prefix rebase-guard trip(s) -- "
            "init_device_sim's static validation no longer covers the "
            "workload (cost or creation-order spread past the int32 "
            "sort payload); committed counts are untrustworthy")


def run_device_sim(cfg: SimConfig, *, mesh: Optional[Mesh] = None,
                   ring_capacity: int = 256,
                   slices_per_launch: int = 64,
                   max_launches: int = 200,
                   check_guards: bool = True,
                   select_impl: str = "sort",
                   calendar_impl: Optional[str] = None,
                   calendar_steps: int = 8,
                   ladder_levels: int = 4):
    """Run to completion (all clients' ops served) or the launch cap.

    ``check_guards`` (default on) raises after any launch whose prefix
    batches tripped a rebase guard -- the invariant init_device_sim
    validates statically, made CHECKED so future edits that weaken the
    validation surface instead of silently under-serving.

    ``calendar_impl`` (None|"minstop"|"bucketed"|"wheel") front-loads
    each slice with sortless calendar batches
    (DeviceSimSpec.calendar_impl) -- service stays exactly the q-step
    serial stream, pinned by tests/test_calendar_bucketed.py and
    tests/test_calendar_wheel.py.

    Returns (sim, spec, report_str)."""
    if mesh is None:
        mesh = make_mesh()
        n_dev = len(mesh.devices.flat)
        # the servers axis must divide the device count; fall back to a
        # single device otherwise
        total = sum(g.server_count for g in cfg.srv_group)
        if total % n_dev != 0:
            mesh = make_mesh(1)
    sim, spec = init_device_sim(cfg, ring_capacity=ring_capacity,
                                select_impl=select_impl,
                                calendar_impl=calendar_impl,
                                calendar_steps=calendar_steps,
                                ladder_levels=ladder_levels)
    sim = shard_device_sim(sim, mesh)
    step = jax.jit(functools.partial(
        device_sim_step, spec=spec, mesh=mesh,
        slices=slices_per_launch), donate_argnums=(0,))
    total_ops = int(np.asarray(sim.load.total_ops).sum())
    launches = 0
    completed = 0
    for launches in range(1, max_launches + 1):
        sim = step(sim)
        if check_guards:
            check_guard_trips(sim)
        completed = int(np.asarray(sim.served_resv).sum()
                        + np.asarray(sim.served_prop).sum())
        if completed >= total_ops:
            break
    return sim, spec, format_report(cfg, sim, spec, launches,
                                    completed=completed,
                                    total_ops=total_ops)


def format_report(cfg: SimConfig, sim: DeviceSim, spec: DeviceSimSpec,
                  launches: int, *, completed: Optional[int] = None,
                  total_ops: Optional[int] = None) -> str:
    sresv = np.asarray(sim.served_resv).sum(axis=0)   # [C]
    sprop = np.asarray(sim.served_prop).sum(axis=0)
    t_s = int(sim.t) / NS_PER_SEC
    lines = ["=== device sim report ===",
             f"servers: {spec.n_servers}  clients: {spec.n_clients}  "
             f"slice: {spec.slice_ns} ns x {launches} launches",
             f"virtual duration: {t_s:.3f} s",
             f"total ops: {int(sresv.sum() + sprop.sum())} "
             f"(reservation {int(sresv.sum())}, "
             f"priority {int(sprop.sum())})"]
    last = np.asarray(sim.last_served).max(axis=0)  # [C]
    ci = 0
    for gi, g in enumerate(cfg.cli_group):
        sl = slice(ci, ci + g.client_count)
        ops = int(sresv[sl].sum() + sprop[sl].sum())
        finish_s = last[sl].max() / NS_PER_SEC
        rate = ops / finish_s / g.client_count if finish_s else 0.0
        lines.append(
            f"group {gi}: {g.client_count} clients  "
            f"r={g.client_reservation} w={g.client_weight} "
            f"l={g.client_limit} | ops {ops} "
            f"(res {int(sresv[sl].sum())} / prop {int(sprop[sl].sum())})"
            f" | done @ {finish_s:.2f}s | average {rate:.2f} ops/s")
        ci += g.client_count
    if completed is not None and total_ops is not None \
            and completed < total_ops:
        # partial runs must not read as converged QoS shares
        lines.append(f"INCOMPLETE: served {completed}/{total_ops} ops "
                     f"after {launches} launches (raise --max-launches)")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse
    from .config import parse_config_file

    p = argparse.ArgumentParser(
        prog="device_sim", description=__doc__.splitlines()[0])
    p.add_argument("-c", "--conf", required=True)
    p.add_argument("--ring-capacity", type=int, default=256)
    p.add_argument("--slices-per-launch", type=int, default=64)
    p.add_argument("--max-launches", type=int, default=200)
    args = p.parse_args(argv)
    cfg = parse_config_file(args.conf)
    _sim, _spec, report = run_device_sim(
        cfg, ring_capacity=args.ring_capacity,
        slices_per_launch=args.slices_per_launch,
        max_launches=args.max_launches)
    print(report)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
