"""dmc_sim -- dmClock QoS simulation CLI.

Equivalent of the reference simulator binary
(``sim/src/test_dmclock_main.cc:46-342``): reads a reference-format INI
config (``-c/--conf``), runs the closed-loop multi-server multi-client
simulation, and prints per-group / per-server tables.

    python -m dmclock_tpu.sim.dmc_sim -c sim/dmc_sim_example.conf
    python -m dmclock_tpu.sim.dmc_sim -c conf --model dmclock-tpu
"""

from __future__ import annotations

import argparse
import sys

from .. import models
from ..obs import DecisionTrace
from .config import SimConfig, parse_config_file
from .harness import Simulation


def run_sim(cfg: SimConfig, model: str = "dmclock", seed: int = 12345,
            record_trace: bool = False,
            server_mode: str = "pull",
            registry=None, decision_trace=None,
            tracer=None) -> Simulation:
    _pull_factory, tracker_factory = models.get(model)
    if server_mode == "push":
        queue_factory = models.get_push(model)
    else:
        queue_factory = _pull_factory
    sim = Simulation(cfg, queue_factory, tracker_factory, seed=seed,
                     record_trace=record_trace, server_mode=server_mode,
                     registry=registry, decision_trace=decision_trace,
                     tracer=tracer)
    sim.run()
    return sim


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dmc_sim",
                                description=__doc__.splitlines()[0])
    p.add_argument("-c", "--conf", help="INI config file "
                   "(reference sim/dmc_sim_example.conf format)")
    p.add_argument("--model", default="dmclock", choices=models.names(),
                   help="scheduler model to simulate")
    p.add_argument("--seed", type=int, default=12345)
    p.add_argument("--server-mode", default="pull",
                   choices=("pull", "push"),
                   help="drive servers by polling (pull) or let the "
                   "queue push via handle_f (the reference dmc_sim's "
                   "mode)")
    p.add_argument("--intervals", action="store_true",
                   help="print per-client per-second op counts")
    p.add_argument("--trace", metavar="FILE", default=None,
                   help="write a bounded JSONL decision trace "
                   "(schema: docs/OBSERVABILITY.md)")
    p.add_argument("--trace-limit", type=int, default=1_000_000,
                   help="max trace rows before dropping (bounded "
                   "trace; default 1M)")
    p.add_argument("--trace-out", metavar="FILE.json", default=None,
                   help="write a Chrome trace-event / Perfetto "
                   "timeline of host spans (ingest / dispatch wall "
                   "time per server; obs.spans) -- loadable in "
                   "chrome://tracing; decisions are bit-identical "
                   "with or without it")
    p.add_argument("--conformance", action="store_true",
                   help="print the per-client QoS conformance table "
                   "(delivered rate vs reservation/weight/limit), "
                   "plus reservation-tardiness percentiles when the "
                   "backend materializes tags")
    p.add_argument("--slo-check", action="store_true",
                   help="cross-check the queue backends' SLO window "
                   "mirror (obs.slo; open window == cumulative "
                   "ledger on every countable column, contract "
                   "epochs stamped) and exit nonzero on mismatch; "
                   "passes with a note when no backend exposes the "
                   "mirror")
    p.add_argument("--ledger-check", action="store_true",
                   help="cross-check backend conformance ledgers "
                   "(device-truth per-client served/reservation "
                   "counts) against the host-recomputed sim stats; "
                   "exits nonzero on a mismatch")
    p.add_argument("--metrics-out", metavar="FILE", default=None,
                   help="dump the metrics registry at exit: Prometheus "
                   "text (.prom/.txt) or JSON snapshot (.json)")
    p.add_argument("--metrics-port", type=int, metavar="PORT",
                   default=None,
                   help="serve the live metrics registry over HTTP "
                   "(GET /metrics, Prometheus text; /metrics.json) "
                   "for the duration of the run; 0 picks an "
                   "ephemeral port (printed)")
    p.add_argument("--use-prop-heap", action="store_true",
                   help="dmclock-native model: enable the O(1) "
                   "idle-reactivation prop heap (reference "
                   "USE_PROP_HEAP equivalent; same behavior, faster "
                   "adds at scale)")
    args = p.parse_args(argv)
    if args.use_prop_heap and args.model != "dmclock-native":
        p.error("--use-prop-heap applies to --model dmclock-native")
    # unconditional assignment: in-process callers invoking main()
    # repeatedly must not inherit a previous run's flag
    models.USE_PROP_HEAP = bool(args.use_prop_heap)

    if args.server_mode == "push" and \
            args.model not in models.push_names():
        p.error(f"model {args.model!r} has no push-mode queue "
                f"(push models: {', '.join(models.push_names())})")
    try:
        cfg = parse_config_file(args.conf) if args.conf else SimConfig()
    except OSError as e:
        p.error(f"cannot read config file: {e}")
    trace = DecisionTrace(args.trace, limit=args.trace_limit) \
        if args.trace else None
    tracer = None
    if args.trace_out:
        from ..obs import SpanTracer
        tracer = SpanTracer()
    registry = None
    http_srv = None
    if args.metrics_port is not None:
        from ..obs import MetricsRegistry, start_http_server
        registry = MetricsRegistry()
        # fail-soft: a taken port (e.g. a supervisor restarting this
        # sim while the old incarnation drains) logs a warning and
        # runs without a scrape endpoint instead of dying
        http_srv = start_http_server(registry, port=args.metrics_port)
        if http_srv is not None:
            print(f"# metrics: serving {http_srv.url}")
    try:
        sim = run_sim(cfg, model=args.model, seed=args.seed,
                      server_mode=args.server_mode,
                      registry=registry, decision_trace=trace,
                      tracer=tracer)
    finally:
        if trace is not None:
            trace.close()
        if http_srv is not None:
            http_srv.close()
        if tracer is not None:
            # export even on a crashed run (the timeline of a failed
            # sim is exactly when you want it), but FAIL-SOFT: an
            # unwritable path must neither fail a healthy run after
            # all the work nor mask the sim's own exception from
            # inside this finally block
            try:
                from ..obs import export_chrome_trace
                n_ev = export_chrome_trace(tracer, args.trace_out)
                print(f"# trace-out: {n_ev} spans -> "
                      f"{args.trace_out} (chrome://tracing; "
                      f"{tracer.spans_dropped} dropped past the "
                      "ring)")
            except OSError as e:
                print(f"# trace-out failed: {e}", file=sys.stderr)
    report = sim.report()
    print(report.format(show_intervals=args.intervals))
    if args.conformance:
        print(report.format_conformance())
        pct = report.tardiness_percentiles()
        if pct is not None:
            print("-- reservation tardiness (log2-quantized upper "
                  "bounds) --")
            print(f"p50 {pct['p50_ns']:.0f} ns | "
                  f"p90 {pct['p90_ns']:.0f} ns | "
                  f"p99 {pct['p99_ns']:.0f} ns | "
                  f"mean {pct['mean_ns']:.0f} ns "
                  f"({pct['count']} constraint serves)")
    if args.ledger_check:
        chk = report.ledger_check()
        if chk is None:
            print("# ledger-check: no backend exposes a conformance "
                  "ledger (host-recomputed stats are the only "
                  "record); pass")
        elif chk["mismatches"]:
            print(f"# ledger-check: FAILED -- "
                  f"{len(chk['mismatches'])} client(s) diverge "
                  f"between the backend ledger and the host "
                  f"recount: {chk['mismatches'][:5]}")
            return 1
        else:
            print(f"# ledger-check: ok ({chk['clients']} clients, "
                  f"{chk['ops']} ops; backend ledger == host "
                  "recount)")
    if args.ledger_check and args.trace:
        # trace-vs-counters cross-check (schema v2): the JSONL trace's
        # per-phase totals must equal the harness recount (= the
        # device MET_RESV/MET_PROP mirror the ledger-check above
        # already pinned against it) -- a hard error unless rows were
        # deliberately dropped past --trace-limit
        if trace.rows_dropped:
            print(f"# trace-check: skipped ({trace.rows_dropped} "
                  "rows dropped past --trace-limit; totals cannot "
                  "match by construction)")
        else:
            from ..obs.trace import summarize
            try:
                stats = summarize(args.trace,
                                  report.phase_totals())
            except ValueError as e:
                print(f"# trace-check: FAILED -- {e}")
                return 1
            print(f"# trace-check: ok ({stats['rows']} rows; "
                  "per-phase totals == host recount == device "
                  "counters)")
    if args.slo_check:
        chk = report.slo_window_check()
        if chk is None:
            print("# slo-check: no backend exposes the SLO window "
                  "mirror; pass")
        elif chk["mismatches"]:
            print(f"# slo-check: FAILED -- "
                  f"{len(chk['mismatches'])} client(s) diverge "
                  f"between the window mirror and the ledger: "
                  f"{chk['mismatches'][:5]}")
            return 1
        else:
            print(f"# slo-check: ok ({chk['clients']} clients, "
                  f"{chk['windows_ops']} windowed ops == ledger)")
    if trace is not None and trace.rows_dropped:
        print(f"# trace: {trace.rows_written} rows written, "
              f"{trace.rows_dropped} dropped past --trace-limit")
    if args.metrics_out:
        reg = sim.registry
        if args.metrics_out.endswith(".json"):
            text = reg.snapshot_json(indent=1)
        else:
            text = reg.prometheus()
        with open(args.metrics_out, "w") as fh:
            fh.write(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
