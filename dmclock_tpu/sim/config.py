"""Simulation configuration, INI-compatible with the reference.

Parses the same file format as the reference's config subsystem
(``sim/src/config.h:32-155``, ``config.cc:123-184``, Ceph-style
``ConfUtils`` INI underneath): a ``[global]`` section plus numbered
``[client.N]`` / ``[server.N]`` group sections.  Defaults equal the
reference struct-constructor defaults so a bare config behaves
identically.
"""

from __future__ import annotations

import configparser
from dataclasses import dataclass, field
from typing import List


@dataclass
class ClientGroup:
    """One [client.N] section (reference cli_group_t, config.h:32-84)."""

    client_count: int = 100
    client_wait_s: float = 0.0
    client_total_ops: int = 1000
    client_server_select_range: int = 10
    client_iops_goal: float = 50.0
    client_outstanding_ops: int = 100
    client_reservation: float = 20.0
    client_limit: float = 60.0
    client_weight: float = 1.0
    client_req_cost: int = 1


@dataclass
class ServerGroup:
    """One [server.N] section (reference srv_group_t, config.h:87-110)."""

    server_count: int = 100
    server_iops: float = 40.0
    server_threads: int = 1


@dataclass
class SimConfig:
    """Whole-simulation config (reference sim_config_t, config.h:113-149)."""

    server_groups: int = 1
    client_groups: int = 1
    server_random_selection: bool = False
    server_soft_limit: bool = True
    anticipation_timeout_s: float = 0.0
    cli_group: List[ClientGroup] = field(default_factory=list)
    srv_group: List[ServerGroup] = field(default_factory=list)

    def __post_init__(self) -> None:
        while len(self.cli_group) < self.client_groups:
            self.cli_group.append(ClientGroup())
        while len(self.srv_group) < self.server_groups:
            self.srv_group.append(ServerGroup())

    @property
    def total_clients(self) -> int:
        return sum(g.client_count for g in self.cli_group)

    @property
    def total_servers(self) -> int:
        return sum(g.server_count for g in self.srv_group)


def _get_bool(sec, key, default: bool) -> bool:
    raw = sec.get(key, None)
    if raw is None:
        return default
    return str(raw).strip().lower() in ("1", "true", "yes", "on")


def parse_config_file(path: str) -> SimConfig:
    """Parse a reference-format INI sim config
    (reference parse_config_file, config.cc:123-184)."""
    cp = configparser.ConfigParser()
    with open(path) as f:
        cp.read_file(f)

    g = cp["global"] if cp.has_section("global") else {}
    cfg = SimConfig(
        server_groups=int(g.get("server_groups", 1)),
        client_groups=int(g.get("client_groups", 1)),
        server_random_selection=_get_bool(g, "server_random_selection", False),
        server_soft_limit=_get_bool(g, "server_soft_limit", True),
        anticipation_timeout_s=float(g.get("anticipation_timeout", 0.0)),
        cli_group=[], srv_group=[])

    cfg.cli_group = []
    for i in range(cfg.client_groups):
        sec_name = f"client.{i}"
        sec = cp[sec_name] if cp.has_section(sec_name) else {}
        d = ClientGroup()
        cfg.cli_group.append(ClientGroup(
            client_count=int(sec.get("client_count", d.client_count)),
            client_wait_s=float(sec.get("client_wait", d.client_wait_s)),
            client_total_ops=int(sec.get("client_total_ops",
                                         d.client_total_ops)),
            client_server_select_range=int(sec.get(
                "client_server_select_range", d.client_server_select_range)),
            client_iops_goal=float(sec.get("client_iops_goal",
                                           d.client_iops_goal)),
            client_outstanding_ops=int(sec.get("client_outstanding_ops",
                                               d.client_outstanding_ops)),
            client_reservation=float(sec.get("client_reservation",
                                             d.client_reservation)),
            client_limit=float(sec.get("client_limit", d.client_limit)),
            client_weight=float(sec.get("client_weight", d.client_weight)),
            client_req_cost=int(sec.get("client_req_cost",
                                        d.client_req_cost)),
        ))

    cfg.srv_group = []
    for i in range(cfg.server_groups):
        sec_name = f"server.{i}"
        sec = cp[sec_name] if cp.has_section(sec_name) else {}
        d = ServerGroup()
        cfg.srv_group.append(ServerGroup(
            server_count=int(sec.get("server_count", d.server_count)),
            server_iops=float(sec.get("server_iops", d.server_iops)),
            server_threads=int(sec.get("server_threads", d.server_threads)),
        ))

    return cfg
