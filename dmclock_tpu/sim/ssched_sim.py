"""ssched_sim -- FIFO-baseline simulation CLI.

Equivalent of the reference's ``ssched_sim``
(``sim/src/test_ssched_main.cc:49-199``), which runs the same harness
over the simple FIFO queue.  Unlike the reference (hardcoded params),
this accepts the same INI configs as dmc_sim -- and the same
observability flags (``--trace``, ``--conformance``,
``--ledger-check``, ``--metrics-port``, ``--trace-out`` for a
Perfetto span timeline); the FIFO queue materializes no tags and no
ledger, so the tardiness percentiles and ledger cross-check degrade
to their documented no-backend paths.
"""

from __future__ import annotations

import sys

from .dmc_sim import main as _main


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    return _main(argv + ["--model", "ssched"])


if __name__ == "__main__":
    sys.exit(main())
