"""Simple-scheduler (FIFO) baseline.

Equivalent of the reference's ssched comparison scheduler
(``sim/src/ssched/ssched_server.h:35-192`` SimpleQueue FIFO,
``ssched_client.h:25-49`` no-op tracker): same add/pull surface as the
dmclock queues so it drops into the same sim harness as a baseline.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Optional, Tuple

from ..core import NextReqType, Phase, PullReq, ReqParams


class NullServiceTracker:
    """No-op tracker (reference ssched_client.h:26-49)."""

    def get_req_params(self, server: Any) -> ReqParams:
        return ReqParams(0, 0)

    def track_resp(self, server: Any, phase: Phase, cost: int = 1) -> None:
        pass


class SimpleQueue:
    """Strict-FIFO queue with the pull interface
    (reference SimpleQueue, ssched_server.h:36-192)."""

    def __init__(self):
        self._queue: Deque[Tuple[Any, Any, int]] = deque()

    def add_request(self, request: Any, client_id: Any,
                    req_params: ReqParams = ReqParams(),
                    time_ns: Optional[int] = None, cost: int = 1) -> int:
        self._queue.append((client_id, request, cost))
        return 0

    def pull_request(self, now_ns: Optional[int] = None) -> PullReq:
        if not self._queue:
            return PullReq(NextReqType.NONE)
        client, request, cost = self._queue.popleft()
        return PullReq(NextReqType.RETURNING, client=client,
                       request=request, phase=Phase.PRIORITY, cost=cost)

    def request_count(self) -> int:
        return len(self._queue)

    def empty(self) -> bool:
        return not self._queue
