"""Simple-scheduler (FIFO) baseline.

Equivalent of the reference's ssched comparison scheduler
(``sim/src/ssched/ssched_server.h:35-192`` SimpleQueue FIFO,
``ssched_client.h:25-49`` no-op tracker): same add/pull surface as the
dmclock queues so it drops into the same sim harness as a baseline.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Deque, Optional, Tuple

from ..core import NextReqType, Phase, PullReq, ReqParams


class NullServiceTracker:
    """No-op tracker (reference ssched_client.h:26-49)."""

    def get_req_params(self, server: Any) -> ReqParams:
        return ReqParams(0, 0)

    def track_resp(self, server: Any, phase: Phase, cost: int = 1) -> None:
        pass


class SimpleQueue:
    """Strict-FIFO queue with both the pull and push interfaces
    (reference SimpleQueue, ssched_server.h:36-192: pull_request :154
    and the push-mode schedule_request :184 driven by handle_f under a
    can_handle gate -- the same dual surface the dmclock queues have,
    so ssched can A/B either path)."""

    def __init__(self, can_handle_f=None, handle_f=None):
        self._queue: Deque[Tuple[Any, Any, int]] = deque()
        self.can_handle_f = can_handle_f
        self.handle_f = handle_f

    def add_request(self, request: Any, client_id: Any,
                    req_params: ReqParams = ReqParams(),
                    time_ns: Optional[int] = None, cost: int = 1) -> int:
        self._queue.append((client_id, request, cost))
        if self.handle_f is not None:
            self.schedule_request()
        return 0

    # -- push mode (reference ssched_server.h:184-191) -----------------
    def request_completed(self) -> None:
        if self.handle_f is not None:
            self.schedule_request()

    def schedule_request(self) -> None:
        # at most ONE dispatch per call, like the reference: pacing is
        # one request per add/completion event
        if self._queue and \
                (self.can_handle_f is None or self.can_handle_f()):
            client, request, cost = self._queue.popleft()
            self.handle_f(client, request, Phase.PRIORITY, cost)

    def pull_request(self, now_ns: Optional[int] = None) -> PullReq:
        if not self._queue:
            return PullReq(NextReqType.NONE)
        client, request, cost = self._queue.popleft()
        return PullReq(NextReqType.RETURNING, client=client,
                       request=request, phase=Phase.PRIORITY, cost=cost)

    def request_count(self) -> int:
        return len(self._queue)

    def empty(self) -> bool:
        return not self._queue
