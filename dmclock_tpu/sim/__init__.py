from .config import ClientGroup, ServerGroup, SimConfig, parse_config_file
from .harness import EventLoop, SimReport, SimulatedClient, SimulatedServer, Simulation
from .ssched import NullServiceTracker, SimpleQueue

__all__ = [
    "ClientGroup", "ServerGroup", "SimConfig", "parse_config_file",
    "EventLoop", "SimReport", "SimulatedClient", "SimulatedServer",
    "Simulation", "NullServiceTracker", "SimpleQueue",
]
