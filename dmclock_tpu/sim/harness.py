"""Discrete-event QoS simulation harness.

Equivalent of the reference simulation framework
(``sim/src/simulate.h``, ``sim_server.h``, ``sim_client.h``): generic
over the queue/tracker pair, so the dmclock scheduler, the ssched FIFO
baseline, and the TPU batch engine all plug in.

Architectural departure from the reference (deliberate): the reference
models time by *sleeping real threads* (server worker sleeps
``op_time*cost``, sim_server.h:222; clients rate-limit with
``wait_until``, sim_client.h:260-263) so a run takes as long as the
simulated workload.  Here the same client/server state machines advance
a virtual int64-ns clock through an event heap: deterministic
(seq-numbered ties), reproducible, and able to simulate hours of QoS
traffic in milliseconds -- which is also what lets the TPU backend be
driven batch-at-a-time.
"""

from __future__ import annotations

import heapq
import random
import time as _walltime
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import NS_PER_SEC, Phase, ReqParams
from ..obs import spans as _spans
from ..obs.registry import MetricsRegistry
from ..utils.profile import ProfileCombiner, ProfileTimer
from .config import ClientGroup, ServerGroup, SimConfig


# ----------------------------------------------------------------------
# event loop
# ----------------------------------------------------------------------

class EventLoop:
    """Virtual-time event loop; ties broken by schedule order."""

    def __init__(self):
        self.now_ns = 0
        self._heap: List[Tuple[int, int, Callable[[], None]]] = []
        self._seq = 0

    def at(self, time_ns: int, fn: Callable[[], None]) -> None:
        assert time_ns >= self.now_ns, "scheduling into the past"
        heapq.heappush(self._heap, (time_ns, self._seq, fn))
        self._seq += 1

    def after(self, delay_ns: int, fn: Callable[[], None]) -> None:
        self.at(self.now_ns + delay_ns, fn)

    def run(self, until_ns: Optional[int] = None) -> None:
        while self._heap:
            t, _, fn = heapq.heappop(self._heap)
            if until_ns is not None and t > until_ns:
                self.now_ns = until_ns
                return
            self.now_ns = t
            fn()


# ----------------------------------------------------------------------
# server
# ----------------------------------------------------------------------

@dataclass
class ServerStats:
    """Per-server accounting (reference server_data
    test_dmclock_main.cc:285-316 + InternalStats sim_server.h:55-70)."""

    ops_completed: int = 0
    reservation_ops: int = 0
    priority_ops: int = 0
    per_client_phase: Dict[Any, List[int]] = field(default_factory=dict)
    # per-client [tardiness_sum_ns, tardiness_max_ns, resv_tag_ops]:
    # the host half of the device conformance-ledger schema
    # (obs.histograms LED_TARD_*), measurable only when the backend
    # materializes tags (the oracle queues do; the TPU engine's device
    # ledger carries its own)
    per_client_tard: Dict[Any, List[int]] = field(default_factory=dict)
    add_request_timer: ProfileTimer = field(default_factory=ProfileTimer)
    request_complete_timer: ProfileTimer = field(default_factory=ProfileTimer)


def _op_time_ns(threads: int, iops: float) -> int:
    """Per-op service time; the reference rounds to whole microseconds
    (sim_server.h:137-139)."""
    return int(0.5 + threads * 1e6 / iops) * 1000


def _record_service(server, client, phase: Phase, cost: int,
                    tag=None) -> None:
    """Shared serve bookkeeping (trace row + per-phase stats) for both
    server drive modes -- pull/push trace equality depends on the two
    modes recording identically."""
    if server.trace is not None:
        server.trace.append((server.loop.now_ns, server.id, client,
                             int(phase), cost))
    if server.decision_trace is not None:
        server.decision_trace.record(
            server.loop.now_ns, server.id, client, int(phase), cost,
            tag=(tag.reservation, tag.proportion, tag.limit)
            if tag is not None else None)
    phase_idx = server.stats.per_client_phase.setdefault(client, [0, 0])
    phase_idx[int(phase)] += 1
    server.stats.ops_completed += 1
    if phase is Phase.RESERVATION:
        server.stats.reservation_ops += 1
        # reservation tardiness when the backend materializes tags
        # (the device-ledger entry-head semantics, host side): how far
        # past its reservation deadline the serve landed
        if tag is not None:
            tard = max(server.loop.now_ns - tag.reservation, 0)
            row = server.stats.per_client_tard.setdefault(
                client, [0, 0, 0])
            row[0] += tard
            row[1] = max(row[1], tard)
            row[2] += 1
            if server.tard_hist is not None:
                server.tard_hist.observe(tard)
    else:
        server.stats.priority_ops += 1


class SimulatedServer:
    """Service station behind a QoS queue
    (reference SimulatedServer, sim_server.h:31-242).

    ``threads`` service slots each take ``op_time * cost`` of virtual
    time per op, with op_time = threads/iops so aggregate service rate
    is ``iops`` (reference ctor, sim_server.h:136-139).
    """

    def __init__(self, server_id: Any, iops: float, threads: int,
                 queue, loop: EventLoop,
                 client_resp_f: Callable[[Any, Any, Phase, int, Any], None],
                 trace: Optional[list] = None,
                 decision_trace=None, tracer=None):
        self.id = server_id
        self.queue = queue
        self.loop = loop
        self.client_resp_f = client_resp_f
        self.threads = threads
        self.op_time_ns = _op_time_ns(threads, iops)
        self.busy = 0
        self.stats = ServerStats()
        self.trace = trace
        self.decision_trace = decision_trace
        self.tracer = tracer     # obs.spans tracer (None = off)
        self.tard_hist = None    # registry histogram, set by Simulation
        self._wake_at: Optional[int] = None

    # the "network" seam: a client submits a request here
    # (reference SimulatedServer::post, sim_server.h:162-177)
    def post(self, request: Any, client_id: Any, req_params: ReqParams,
             cost: int) -> None:
        t = self.stats.add_request_timer
        t.start()
        with _spans.span(self.tracer, "sim.add", "ingest"):
            self.queue.add_request(request, client_id, req_params,
                                   time_ns=self.loop.now_ns, cost=cost)
        t.stop()
        self._dispatch()

    def _dispatch(self) -> None:
        while self.busy < self.threads:
            free = self.threads - self.busy
            with _spans.span(self.tracer, "sim.pull", "dispatch",
                             server=self.id):
                if free > 1 and hasattr(self.queue, "pull_batch"):
                    # batched consumption: pull_batch(now, n) is
                    # defined as n successive pulls at the SAME now --
                    # exactly this loop -- so the trace is identical
                    # with fewer device launches (reference free-slot
                    # count has_avail_thread, sim_server.h:179)
                    batch = self.queue.pull_batch(self.loop.now_ns,
                                                  free)
                else:
                    batch = [self.queue.pull_request(self.loop.now_ns)]
            done = False
            for pr in batch:
                if pr.is_retn():
                    self.busy += 1
                    self._start_service(pr)
                elif pr.is_future():
                    when = pr.when_ready
                    if self._wake_at is None or when < self._wake_at:
                        self._wake_at = when
                        self.loop.at(max(when, self.loop.now_ns),
                                     self._wake)
                    done = True
                else:
                    done = True
            if done:
                break

    def _wake(self) -> None:
        self._wake_at = None
        self._dispatch()

    def _start_service(self, pr) -> None:
        _record_service(self, pr.client, pr.phase, pr.cost,
                        tag=getattr(pr, "tag", None))

        def complete(client=pr.client, request=pr.request,
                     phase=pr.phase, cost=pr.cost):
            self.busy -= 1
            self.client_resp_f(client, request, phase, cost, self.id)
            t = self.stats.request_complete_timer
            t.start()
            # (push-mode queues would get request_completed() here; the
            # pull driver simply re-polls)
            t.stop()
            self._dispatch()

        self.loop.after(self.op_time_ns * pr.cost, complete)


class PushSimulatedServer:
    """Push-mode service station: the QUEUE drives dispatch through
    ``handle_f`` under a ``can_handle`` gate, with timed wakeups via the
    queue's sched-ahead seam -- the mode the reference's dmc_sim
    actually runs (``test_dmclock.h:38-56`` binds PushPriorityQueue;
    server glue ``sim_server.h:162-241``).

    Dispatch pacing follows the reference: one dispatch per trigger
    (add, completion, sched-ahead wakeup).  With ``threads == 1`` the
    decision stream is identical to the pull server's; with more
    threads a same-instant burst may serve one request per trigger
    instead of greedily draining, exactly like the reference.
    """

    def __init__(self, server_id: Any, iops: float, threads: int,
                 make_queue, loop: EventLoop,
                 client_resp_f: Callable[[Any, Any, Phase, int, Any], None],
                 trace: Optional[list] = None,
                 decision_trace=None, tracer=None):
        self.id = server_id
        self.loop = loop
        self.client_resp_f = client_resp_f
        self.threads = threads
        self.op_time_ns = _op_time_ns(threads, iops)
        self.busy = 0
        self.stats = ServerStats()
        self.trace = trace
        self.decision_trace = decision_trace
        self.tracer = tracer     # obs.spans tracer (None = off)
        self.tard_hist = None    # registry histogram, set by Simulation
        # make_queue(can_handle_f, handle_f, now_ns_f, sched_at_f,
        # capacity_f); capacity_f is the free-slot count (reference
        # has_avail_thread, sim_server.h:179) -- batch-capable queues
        # (TPU) size a dispatch pass by it, host queues ignore it
        self.queue = make_queue(
            can_handle_f=lambda: self.busy < self.threads,
            handle_f=self._handle,
            now_ns_f=lambda: self.loop.now_ns,
            sched_at_f=self._sched_at,
            capacity_f=lambda: self.threads - self.busy)

    def post(self, request: Any, client_id: Any, req_params: ReqParams,
             cost: int) -> None:
        t = self.stats.add_request_timer
        t.start()
        with _spans.span(self.tracer, "sim.add", "ingest"):
            # push-mode adds DISPATCH from inside add_request (the
            # queue drives handle_f); the ingest span covers both --
            # the push sim's per-add cost is the unit of interest
            self.queue.add_request(request, client_id, req_params,
                                   time_ns=self.loop.now_ns, cost=cost)
        t.stop()

    def _sched_at(self, when_ns: int) -> None:
        self.loop.at(max(when_ns, self.loop.now_ns),
                     self.queue.sched_ahead_fire)

    # invoked BY the queue (under its lock) when it dispatches a request
    def _handle(self, client: Any, request: Any, phase: Phase,
                cost: int) -> None:
        self.busy += 1
        _record_service(self, client, phase, cost)

        def complete():
            self.busy -= 1
            self.client_resp_f(client, request, phase, cost, self.id)
            t = self.stats.request_complete_timer
            t.start()
            self.queue.request_completed()
            t.stop()

        self.loop.after(self.op_time_ns * cost, complete)


# ----------------------------------------------------------------------
# client
# ----------------------------------------------------------------------

@dataclass
class ClientStats:
    """Per-client accounting (reference InternalStats sim_client.h:80-95
    + per-interval op counts, simulate.h:214-270)."""

    ops_requested: int = 0
    ops_completed: int = 0
    reservation_ops: int = 0
    priority_ops: int = 0
    completion_times_ns: List[int] = field(default_factory=list)
    finish_time_ns: Optional[int] = None
    get_req_params_timer: ProfileTimer = field(default_factory=ProfileTimer)
    track_resp_timer: ProfileTimer = field(default_factory=ProfileTimer)


class SimulatedClient:
    """Closed-loop load generator
    (reference SimulatedClient, sim_client.h:76-336): rate-limited to
    ``iops_goal`` with at most ``outstanding_ops`` in flight, after an
    initial ``wait``."""

    def __init__(self, client_id: Any, group: ClientGroup, tracker,
                 loop: EventLoop,
                 server_select_f: Callable[[int], Any],
                 submit_f: Callable[[Any, Any, Any, ReqParams, int], None],
                 on_done: Callable[[Any], None]):
        self.id = client_id
        self.group = group
        self.tracker = tracker
        self.loop = loop
        self.server_select_f = server_select_f
        self.submit_f = submit_f
        self.on_done = on_done
        self.stats = ClientStats()
        # reference rounds the inter-request gap to whole microseconds
        # (CliInst ctor, sim_client.h:66-68)
        self.gap_ns = int(0.5 + 1e6 / group.client_iops_goal) * 1000
        self.total_ops = group.client_total_ops
        self.max_outstanding = group.client_outstanding_ops
        self.cost = group.client_req_cost
        self.outstanding = 0
        self.sent = 0
        self._window_blocked = False
        loop.at(int(group.client_wait_s * NS_PER_SEC), self._attempt_send)

    def _attempt_send(self) -> None:
        if self.sent >= self.total_ops:
            return
        if self.outstanding >= self.max_outstanding:
            # window full: the op fires as soon as a response frees it
            # (reference run_req window wait, sim_client.h:234-236)
            self._window_blocked = True
            return
        server = self.server_select_f(self.sent)
        t = self.stats.get_req_params_timer
        t.start()
        rp = self.tracker.get_req_params(server)
        t.stop()
        self.submit_f(server, (self.id, self.sent), self.id, rp, self.cost)
        self.sent += 1
        self.outstanding += 1
        self.stats.ops_requested += 1
        if self.sent < self.total_ops:
            self.loop.after(self.gap_ns, self._attempt_send)

    # response delivery (reference receive_response + run_resp,
    # sim_client.h:204-212, :276-335)
    def receive_response(self, request: Any, phase: Phase, cost: int,
                         server: Any) -> None:
        t = self.stats.track_resp_timer
        t.start()
        self.tracker.track_resp(server, phase, cost)
        t.stop()
        self.outstanding -= 1
        self.stats.ops_completed += 1
        if phase is Phase.RESERVATION:
            self.stats.reservation_ops += 1
        else:
            self.stats.priority_ops += 1
        self.stats.completion_times_ns.append(self.loop.now_ns)
        if self._window_blocked:
            self._window_blocked = False
            self._attempt_send()
        if self.sent >= self.total_ops and self.outstanding == 0:
            self.stats.finish_time_ns = self.loop.now_ns
            self.on_done(self.id)


# ----------------------------------------------------------------------
# simulation orchestrator
# ----------------------------------------------------------------------

class Simulation:
    """Build servers+clients from a SimConfig and run to completion
    (reference Simulation, simulate.h:33-445).

    queue_factory(server_id, client_info_f, anticipation_timeout_ns,
                  soft_limit) -> queue with add_request/pull_request
    tracker_factory() -> tracker with get_req_params/track_resp
    """

    def __init__(self, cfg: SimConfig, queue_factory, tracker_factory,
                 seed: int = 12345, record_trace: bool = False,
                 server_mode: str = "pull",
                 registry: Optional[MetricsRegistry] = None,
                 decision_trace=None, tracer=None):
        assert server_mode in ("pull", "push")
        self.server_mode = server_mode
        self.cfg = cfg
        self.loop = EventLoop()
        self.trace: Optional[list] = [] if record_trace else None
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.decision_trace = decision_trace
        # time-domain tracing (obs.spans.SpanTracer or None): the
        # servers record ingest (add_request) and dispatch (pull)
        # spans, so `dmc_sim --trace-out` yields a Perfetto timeline
        # of where the sim's wall time went; decisions bit-identical
        self.tracer = tracer
        self._rng = random.Random(seed)
        self._done_clients = set()

        # client-id -> group index; ids are dense ints (servers too)
        self.client_group_of: Dict[int, int] = {}
        cid = 0
        for gi, g in enumerate(cfg.cli_group):
            for _ in range(g.client_count):
                self.client_group_of[cid] = gi
                cid += 1
        self.n_clients = cid

        self.server_group_of: Dict[int, int] = {}
        sid = 0
        for gi, g in enumerate(cfg.srv_group):
            for _ in range(g.server_count):
                self.server_group_of[sid] = gi
                sid += 1
        self.n_servers = sid

        from ..core import ClientInfo
        self._infos = [ClientInfo(g.client_reservation, g.client_weight,
                                  g.client_limit,
                                  client=f"client-group-{gi}")
                       for gi, g in enumerate(cfg.cli_group)]

        def client_info_f(c):
            return self._infos[self.client_group_of[c]]

        self.servers: Dict[int, Any] = {}
        anticipation_ns = int(cfg.anticipation_timeout_s * NS_PER_SEC)
        for s in range(self.n_servers):
            g = cfg.srv_group[self.server_group_of[s]]
            if server_mode == "push":
                # queue_factory here has the push signature:
                # (server_id, info_f, ant_ns, soft, *, can_handle_f,
                #  handle_f, now_ns_f, sched_at_f) -> push queue
                def make_queue(s=s, **cb):
                    return queue_factory(s, client_info_f,
                                         anticipation_ns,
                                         cfg.server_soft_limit, **cb)
                self.servers[s] = PushSimulatedServer(
                    s, g.server_iops, g.server_threads, make_queue,
                    self.loop, self._client_resp, trace=self.trace,
                    decision_trace=self.decision_trace,
                    tracer=self.tracer)
            else:
                q = queue_factory(s, client_info_f, anticipation_ns,
                                  cfg.server_soft_limit)
                self.servers[s] = SimulatedServer(
                    s, g.server_iops, g.server_threads, q, self.loop,
                    self._client_resp, trace=self.trace,
                    decision_trace=self.decision_trace,
                    tracer=self.tracer)
            self._register_server_metrics(s)

        self.clients: Dict[int, SimulatedClient] = {}
        for c in range(self.n_clients):
            g = cfg.cli_group[self.client_group_of[c]]
            select = self._make_server_select(c, g)
            self.clients[c] = SimulatedClient(
                c, g, tracker_factory(), self.loop, select,
                self._submit, self._client_done)

        # aggregate callback gauges: read lazily at drain time, so the
        # event loop's hot path never touches the registry
        reg = self.registry
        reg.gauge("sim_ops_completed_total",
                  "client ops completed (all clients)").set_function(
            lambda: sum(c.stats.ops_completed
                        for c in self.clients.values()))
        reg.gauge("sim_reservation_ops_total",
                  "constraint-phase completions").set_function(
            lambda: sum(c.stats.reservation_ops
                        for c in self.clients.values()))
        reg.gauge("sim_priority_ops_total",
                  "weight-phase completions").set_function(
            lambda: sum(c.stats.priority_ops
                        for c in self.clients.values()))
        reg.gauge("sim_virtual_time_ns",
                  "virtual clock").set_function(lambda: self.loop.now_ns)
        reg.timer("sim_client_get_req_params_ns",
                  "tracker get_req_params latency (all clients)")
        reg.timer("sim_client_track_resp_ns",
                  "tracker track_resp latency (all clients)")
        for c in self.clients.values():
            reg.timer("sim_client_get_req_params_ns",
                      source=c.stats.get_req_params_timer)
            reg.timer("sim_client_track_resp_ns",
                      source=c.stats.track_resp_timer)

        self._wall_start = None
        self._wall_elapsed_s = None

    def _register_server_metrics(self, s: int) -> None:
        """Per-server hot-path stats: the host-call timers as merged
        summaries, the queue's scheduling counters via its own
        ``register_metrics`` when the backend offers one."""
        from ..obs.histograms import BUCKET_BOUNDS

        server = self.servers[s]
        labels = {"server": str(s)}
        # one shared log2 tardiness histogram across servers (the
        # device-histogram bucket layout, so sims and bench report
        # the same families -- docs/OBSERVABILITY.md)
        server.tard_hist = self.registry.histogram(
            "sim_resv_tardiness_ns",
            "reservation tardiness of constraint-phase serves "
            "(log2 buckets; backends that materialize tags only)",
            buckets=BUCKET_BOUNDS)
        self.registry.timer("sim_server_add_request_ns",
                            "queue add_request latency", labels=labels,
                            source=server.stats.add_request_timer)
        self.registry.timer("sim_server_request_complete_ns",
                            "completion-path latency", labels=labels,
                            source=server.stats.request_complete_timer)
        self.registry.gauge("sim_server_ops_completed",
                            "decisions served", labels=labels
                            ).set_function(
            lambda st=server.stats: st.ops_completed)
        queue = getattr(server, "queue", None)
        if queue is not None and hasattr(queue, "register_metrics"):
            queue.register_metrics(self.registry, labels=labels)

    # -- server-selection policies (reference simulate.h:398-444) -----
    def _make_server_select(self, client_idx: int, g: ClientGroup):
        servers_per = min(g.client_server_select_range, self.n_servers)
        factor = self.n_servers / max(1, self.n_clients)
        if self.cfg.server_random_selection:
            def select(seed: int) -> int:
                offset = self._rng.randrange(servers_per)
                return (int(0.5 + client_idx * factor) + offset) \
                    % self.n_servers
        else:
            def select(seed: int) -> int:
                offset = seed % servers_per
                return (int(0.5 + client_idx * factor) + offset) \
                    % self.n_servers
        return select

    # -- the callback "network" (reference test_dmclock_main.cc:146-188)
    def _submit(self, server, request, client_id, rp, cost):
        self.servers[server].post(request, client_id, rp, cost)

    def _client_resp(self, client, request, phase, cost, server):
        self.clients[client].receive_response(request, phase, cost, server)

    def _client_done(self, client_id):
        self._done_clients.add(client_id)

    def run(self) -> None:
        """Run to completion (reference Simulation::run, simulate.h:159-178)."""
        self._wall_start = _walltime.perf_counter()
        self.loop.run()
        self._wall_elapsed_s = _walltime.perf_counter() - self._wall_start
        assert len(self._done_clients) == self.n_clients, \
            f"only {len(self._done_clients)}/{self.n_clients} clients finished"

    # -- reporting (reference display_stats, simulate.h:181-395) -------
    def report(self) -> "SimReport":
        return SimReport(self)


class SimReport:
    """Aggregated results with a text table in the spirit of the
    reference's display_stats output."""

    def __init__(self, sim: Simulation):
        self.sim = sim
        self.virtual_duration_s = sim.loop.now_ns / NS_PER_SEC
        self.wall_seconds = sim._wall_elapsed_s
        self.total_ops = sum(c.stats.ops_completed
                             for c in sim.clients.values())
        self.total_reservation_ops = sum(c.stats.reservation_ops
                                         for c in sim.clients.values())
        self.total_priority_ops = sum(c.stats.priority_ops
                                      for c in sim.clients.values())

    # -- per-client QoS conformance (delivered vs contracted) ----------
    def conformance(self, tol: float = 0.05) -> List[dict]:
        """Per-client QoS conformance rows: delivered rate over the
        client's own active window vs its reservation / weight / limit
        contract (the reference sim's per-client breakdown,
        simulate.h:214-270, extended with met/violated verdicts).

        A closed-loop client can deliver under its reservation simply
        by not asking, so ``resv_met`` judges against
        ``min(reservation, demand_rate)``; ``limit_ok`` judges the
        delivered rate against the limit cap.  ``tol`` is the relative
        slack both verdicts allow.
        """
        sim = self.sim
        tard = self._client_tardiness()
        rows = []
        for cid in sorted(sim.clients):
            c = sim.clients[cid]
            g = sim.cfg.cli_group[sim.client_group_of[cid]]
            start_ns = int(g.client_wait_s * NS_PER_SEC)
            end_ns = c.stats.finish_time_ns or sim.loop.now_ns
            window_s = max((end_ns - start_ns) / NS_PER_SEC, 1e-9)
            rate = c.stats.ops_completed / window_s
            demand = c.stats.ops_requested / window_s
            resv_floor = min(g.client_reservation, demand)
            t_sum, t_max, t_n = tard.get(cid, (0, 0, 0))
            rows.append({
                "tardiness_mean_ns": t_sum / max(t_n, 1),
                "tardiness_max_ns": t_max,
                "client": cid,
                "group": sim.client_group_of[cid],
                "reservation": g.client_reservation,
                "weight": g.client_weight,
                "limit": g.client_limit,
                "ops": c.stats.ops_completed,
                "reservation_ops": c.stats.reservation_ops,
                "priority_ops": c.stats.priority_ops,
                "rate": rate,
                "demand_rate": demand,
                "resv_met": (rate >= resv_floor * (1.0 - tol))
                if g.client_reservation > 0 else True,
                "limit_ok": (rate <= g.client_limit * (1.0 + tol))
                if g.client_limit > 0 else True,
            })
        return rows

    def _client_tardiness(self) -> Dict[Any, Tuple[int, int, int]]:
        """Per-client (tardiness_sum, tardiness_max, resv_tag_ops)
        merged across servers -- the host half of the device ledger's
        tardiness columns (zeros for backends without tags)."""
        out: Dict[Any, List[int]] = {}
        for s in self.sim.servers.values():
            for cid, (t_sum, t_max, t_n) in \
                    s.stats.per_client_tard.items():
                row = out.setdefault(cid, [0, 0, 0])
                row[0] += t_sum
                row[1] = max(row[1], t_max)
                row[2] += t_n
        return {cid: tuple(v) for cid, v in out.items()}

    def tardiness_percentiles(self) -> Optional[dict]:
        """p50/p90/p99 reservation tardiness from the shared log2
        histogram the servers observe into -- packed into a device-
        histogram block row so ``obs.histograms.hist_percentile`` is
        THE quantization math (one implementation; sims and bench
        cannot drift).  None when no constraint-phase serve carried a
        tag."""
        import numpy as np

        from ..obs import histograms as obshist

        h = self.sim.registry.histogram("sim_resv_tardiness_ns")
        if h.count == 0:
            return None
        block = np.zeros((obshist.NUM_HISTS, obshist.NUM_BUCKETS + 1),
                         dtype=np.int64)
        fam = obshist.HIST_RESV_TARDINESS
        block[fam, :obshist.NUM_BUCKETS] = h.counts
        block[fam, obshist.HIST_SUM_COL] = int(h.sum)
        return {"count": h.count,
                "mean_ns": obshist.hist_mean(block, fam),
                "p50_ns": obshist.hist_percentile(block, fam, 0.50),
                "p90_ns": obshist.hist_percentile(block, fam, 0.90),
                "p99_ns": obshist.hist_percentile(block, fam, 0.99)}

    def ledger_check(self) -> Optional[dict]:
        """Cross-check backend conformance ledgers against the
        harness's own host-recomputed per-client stats -- the
        device-truth-vs-host-recount gate at sim scale.

        Sums ``ledger_rows()`` over every queue backend that exposes
        one (``engine.queue.TpuPullPriorityQueue``) and compares ops /
        reservation-ops per client against the servers'
        ``per_client_phase`` tables.  Only clients the backend STILL
        tracks are judged: an erased/recycled slot's ledger row is
        deliberately zeroed by the queue (a new tenant must not
        inherit it), so those clients are reported under
        ``recycled_clients`` instead of failing the gate.  Returns
        ``{"clients", "ops", "recycled_clients", "mismatches": [...]}``
        or None when no backend exposes a ledger (the oracle queues
        recompute host-side only)."""
        ledgers: Dict[Any, List[int]] = {}
        found = False
        for s in self.sim.servers.values():
            queue = getattr(s, "queue", None)
            if queue is None or not hasattr(queue, "ledger_rows"):
                continue
            found = True
            for cid, row in queue.ledger_rows().items():
                acc = ledgers.setdefault(cid, [0, 0])
                acc[0] += int(row[0])
                acc[1] += int(row[1])
        if not found:
            return None
        host: Dict[Any, List[int]] = {}
        for s in self.sim.servers.values():
            for cid, (res, prio) in s.stats.per_client_phase.items():
                acc = host.setdefault(cid, [0, 0])
                acc[0] += res + prio
                acc[1] += res
        mismatches = []
        for cid in sorted(ledgers):
            led = ledgers[cid]
            hst = host.get(cid, [0, 0])
            if led != hst:
                mismatches.append({"client": cid,
                                   "ledger_ops": led[0],
                                   "host_ops": hst[0],
                                   "ledger_resv": led[1],
                                   "host_resv": hst[1]})
        # device phase-counter cross-check (trace schema v2
        # satellite, docs/OBSERVABILITY.md): the backends' running
        # reservation/priority counters are the host mirror of the
        # device MET_RESV/MET_PROP rows -- they must equal the
        # harness's own per-phase recount exactly, or decisions were
        # dropped/duplicated/mis-phased somewhere on the way up
        resv_dev = prop_dev = 0
        have_counters = False
        for s in self.sim.servers.values():
            queue = getattr(s, "queue", None)
            if queue is not None and \
                    hasattr(queue, "reserv_sched_count"):
                have_counters = True
                resv_dev += int(queue.reserv_sched_count)
                prop_dev += int(queue.prop_sched_count)
        out = {"clients": len(ledgers),
               "ops": sum(v[0] for v in ledgers.values()),
               "recycled_clients": len(set(host) - set(ledgers)),
               "mismatches": mismatches}
        if have_counters:
            resv_host, prop_host = self.phase_totals()
            out["phase_counters"] = {"reservation": resv_dev,
                                     "priority": prop_dev}
            if (resv_dev, prop_dev) != (resv_host, prop_host):
                mismatches.append({
                    "phase_counters": {"reservation": resv_dev,
                                       "priority": prop_dev},
                    "host": {"reservation": resv_host,
                             "priority": prop_host}})
        return out

    def phase_totals(self) -> Tuple[int, int]:
        """(reservation, priority) decision totals from the host
        per-phase recount -- what the device ``MET_RESV``/``MET_PROP``
        counters (and a decision trace's ``per_phase`` summary) must
        match exactly."""
        resv = sum(s.stats.reservation_ops
                   for s in self.sim.servers.values())
        prop = sum(s.stats.priority_ops
                   for s in self.sim.servers.values())
        return resv, prop

    def slo_window_check(self) -> Optional[dict]:
        """The queue backends' SLO window mirror vs their own ledger
        (docs/OBSERVABILITY.md "SLO plane"): a sim never rolls the
        mirror, so every client's OPEN window must equal its
        cumulative ledger row on the countable columns (ops /
        resv-ops / limit-breaks) and carry a nonzero contract epoch.
        Returns ``{"clients", "windows_ops", "mismatches": [...]}`` or
        None when no backend exposes the mirror."""
        from ..obs import slo as obsslo

        found = False
        mismatches = []
        clients = 0
        ops = 0
        for s in self.sim.servers.values():
            queue = getattr(s, "queue", None)
            if queue is None or not hasattr(queue,
                                            "slo_window_rows"):
                continue
            found = True
            leds = queue.ledger_rows()
            for cid, win in queue.slo_window_rows().items():
                clients += 1
                ops += int(win[obsslo.W_OPS])
                led = leds[cid]
                bad = (int(win[obsslo.W_OPS]) != int(led[0])
                       or int(win[obsslo.W_RESV_OPS]) != int(led[1])
                       or int(win[obsslo.W_LB_OPS]) != int(led[2])
                       or (int(win[obsslo.W_OPS]) > 0
                           and int(win[obsslo.W_CEPOCH]) < 1))
                if bad:
                    mismatches.append({
                        "client": cid,
                        "window": [int(x) for x in win],
                        "ledger": [int(x) for x in led]})
        if not found:
            return None
        return {"clients": clients, "windows_ops": ops,
                "mismatches": mismatches}

    def format_conformance(self, tol: float = 0.05) -> str:
        rows = self.conformance(tol=tol)
        lines = ["-- per-client QoS conformance --",
                 f"{'client':>6} {'grp':>3} {'resv':>8} {'wght':>6} "
                 f"{'limit':>8} {'ops':>8} {'res/prop':>13} "
                 f"{'rate':>9} {'verdict':>10}"]
        for r in rows:
            verdict = ("ok" if r["resv_met"] else "RESV-MISS") + \
                ("" if r["limit_ok"] else "+LIMIT-EXCESS")
            lines.append(
                f"{r['client']:>6} {r['group']:>3} "
                f"{r['reservation']:>8.1f} {r['weight']:>6.1f} "
                f"{r['limit']:>8.1f} {r['ops']:>8} "
                f"{r['reservation_ops']:>6}/{r['priority_ops']:<6} "
                f"{r['rate']:>9.2f} {verdict:>10}")
        total = sum(r["ops"] for r in rows)
        misses = sum(1 for r in rows if not r["resv_met"])
        excess = sum(1 for r in rows if not r["limit_ok"])
        soft = " (allowed: server_soft_limit serves past the limit " \
            "when capacity is spare)" \
            if self.sim.cfg.server_soft_limit and excess else ""
        lines.append(f"total ops {total} | reservation misses {misses} "
                     f"| limit excesses {excess}{soft}")
        return "\n".join(lines)

    def client_interval_ops(self, interval_s: float = 1.0) -> Dict[int, List[int]]:
        out = {}
        step = int(interval_s * NS_PER_SEC)
        for cid, c in self.sim.clients.items():
            if not c.stats.completion_times_ns:
                out[cid] = []
                continue
            hi = max(c.stats.completion_times_ns)
            buckets = [0] * (hi // step + 1)
            for t in c.stats.completion_times_ns:
                buckets[t // step] += 1
            out[cid] = buckets
        return out

    def format(self, show_intervals: bool = False) -> str:
        sim = self.sim
        lines = []
        lines.append(f"=== simulation report ===")
        lines.append(f"clients: {sim.n_clients}  servers: {sim.n_servers}")
        lines.append(f"virtual duration: {self.virtual_duration_s:.3f} s; "
                     f"wall: {self.wall_seconds:.3f} s")
        lines.append(f"total ops: {self.total_ops} "
                     f"(reservation {self.total_reservation_ops}, "
                     f"priority {self.total_priority_ops})")

        # per-client-group summary
        lines.append("-- client groups --")
        for gi, g in enumerate(sim.cfg.cli_group):
            cids = [c for c, gg in sim.client_group_of.items() if gg == gi]
            ops = sum(sim.clients[c].stats.ops_completed for c in cids)
            res = sum(sim.clients[c].stats.reservation_ops for c in cids)
            prop = sum(sim.clients[c].stats.priority_ops for c in cids)
            finish = max((sim.clients[c].stats.finish_time_ns or 0)
                         for c in cids) / NS_PER_SEC
            rate = ops / finish if finish else 0.0
            lines.append(
                f"group {gi}: {len(cids)} clients  r={g.client_reservation}"
                f" w={g.client_weight} l={g.client_limit}"
                f" | ops {ops} (res {res} / prop {prop})"
                f" | done @ {finish:.2f}s | average {rate:.2f} ops/s")

        # host-call latency averages (the numbers the reference
        # benchmark greps, simulate.h:306-395), merged with the
        # reference's ProfileCombiner semantics (profile.h:100-120) so
        # stddev/min/max survive the multi-server merge
        add_t = ProfileCombiner()
        for s in sim.servers.values():
            add_t.combine(s.stats.add_request_timer)
        gr_t = ProfileCombiner()
        tr_t = ProfileCombiner()
        for c in sim.clients.values():
            gr_t.combine(c.stats.get_req_params_timer)
            tr_t.combine(c.stats.track_resp_timer)
        lines.append("-- server internal stats --")
        lines.append(f"average add_request: {add_t.mean_ns():.0f} ns "
                     f"(stddev {add_t.std_dev_ns():.0f})")
        lines.append("-- client internal stats --")
        lines.append(f"average get_req_params: {gr_t.mean_ns():.0f} ns "
                     f"(stddev {gr_t.std_dev_ns():.0f})")
        lines.append(f"average track_resp: {tr_t.mean_ns():.0f} ns "
                     f"(stddev {tr_t.std_dev_ns():.0f})")

        if show_intervals:
            lines.append("-- per-client interval ops/sec --")
            for cid, buckets in self.client_interval_ops().items():
                lines.append(f"client {cid}: " +
                             " ".join(str(b) for b in buckets))
        return "\n".join(lines)
