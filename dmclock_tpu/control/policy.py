"""The deterministic guarded-transition table (docs/CONTROLLER.md).

The policy is a PURE function of ``(pstate, knobs, signals, spec)``:
no clocks, no randomness, no hidden state -- the same triple always
yields the same decisions, which is what lets a resumed run REPLAY its
journal instead of re-deciding (control/journal.py).

Knob vector (``int64[NUM_KNOBS]``, rides the rotation checkpoints as
the ``ctl_knobs`` leaf):

- ``counter_sync_every`` -- the PR-13 mesh staleness knob; read live
  at each chunk launch.
- ``ladder_level`` -- how many :data:`robust.guarded.LADDER_RUNGS`
  the controller has conceded, applied through :func:`overlay` as an
  exact-twin config substitution (the SAME safety order the
  DegradationLadder uses, so every actuation is digest-explainable).
- ``clamp_pct`` -- admission clamp percentage (100 = off).  Applied
  host-side to already-drawn arrival counts, so RNG consumption is
  IDENTICAL with the controller on or off.
- ``compact_trigger`` -- monotone count of compaction/migration-
  eligible triggers fired (the actuation itself is the digest-neutral
  ``LifecyclePlane.force_compact``; on the mesh it marks
  migration-eligible without moving state).
- ``migrate_trigger`` -- monotone count of AUTHORIZED migration slots
  (bumped by ``migrate_max`` each time the ``migrate`` rule fires on
  per-shard pressure skew; the actuation is the supervisor's
  ``_mesh_migrate`` executing digest-neutral EVICT/REGISTER handoffs
  through :mod:`~dmclock_tpu.lifecycle.placement`).

Per-rule hysteresis and cooldown: protective moves (``*_down``) fire
on the FIRST triggering boundary; relaxing moves (``*_up``) and
``compact`` need ``spec["hysteresis"]`` consecutive triggering
boundaries.  Every applied decision starts a per-rule cooldown of
``spec["cooldown"]`` boundaries during which the rule is inert --
that, plus the clean-streak requirement on the ``*_up`` twin of every
``*_down`` rule, is what keeps the loop from flapping.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

RULES = ("staleness_down", "staleness_up", "ladder_down", "ladder_up",
         "clamp_down", "clamp_up", "compact", "migrate")
NUM_RULES = len(RULES)

# fast-first rules: one triggering boundary is enough
_IMMEDIATE = frozenset(("staleness_down", "ladder_down", "clamp_down"))

KNOB_SYNC, KNOB_LADDER, KNOB_CLAMP, KNOB_COMPACT, KNOB_MIGRATE = \
    0, 1, 2, 3, 4
KNOB_NAMES = ("counter_sync_every", "ladder_level", "clamp_pct",
              "compact_trigger", "migrate_trigger")
NUM_KNOBS = 5

# ``0`` means auto: backlog_hi <- n * ring * 3 // 4, occ_floor <- the
# job's initial slot capacity, ladder_max <- len(LADDER_RUNGS);
# migrate_skew_hi == 0 keeps the migrate rule OFF (its trigger is a
# per-shard skew ratio, meaningless off the mesh -- migrate_shards is
# filled in by the Controller ctor from the job's n_shards).
DEFAULT_SPEC = dict(enabled=True, hysteresis=2, cooldown=2,
                    sync_min=1, sync_max=8,
                    clamp_min=25, clamp_step=25,
                    backlog_hi=0, occ_lo=0.5, occ_floor=0,
                    ladder_max=0,
                    migrate_skew_hi=0.0, migrate_max=4,
                    migrate_pick="hot", migrate_shards=1)


def ladder_max_default() -> int:
    from ..robust.guarded import LADDER_RUNGS
    return len(LADDER_RUNGS)


def _hysteresis(rule: str, spec: dict) -> int:
    return 1 if rule in _IMMEDIATE else max(int(spec["hysteresis"]), 1)


def _propose(rule: str, knobs: List[int], sig,
             spec: dict) -> Optional[List[int]]:
    """Proposed knob vector when ``rule`` triggers on ``sig``, else
    None.  Evaluated against the CURRENT (possibly just-updated this
    boundary) knobs, in fixed RULES order."""
    sync, level, clamp, compact, migr = knobs
    burn = sig.resv_miss_d + sig.limit_break_d + sig.share_skew_d
    trips = sig.guard_trips_d
    clean = burn == 0 and trips == 0
    backlog_hi = int(spec["backlog_hi"])
    if rule == "staleness_down":
        # resv-miss burn: counters are too stale to honor reservations
        if sig.resv_miss_d > 0 and sync > spec["sync_min"]:
            return [int(spec["sync_min"]), level, clamp, compact, migr]
    elif rule == "staleness_up":
        # clean streak: widen the sync grid, buy back collective share
        if clean and sync < spec["sync_max"]:
            return [min(sync * 2, int(spec["sync_max"])), level, clamp,
                    compact, migr]
    elif rule == "ladder_down":
        if trips > 0 and level < int(spec["ladder_max"]):
            return [sync, level + 1, clamp, compact, migr]
    elif rule == "ladder_up":
        if clean and level > 0:
            return [sync, level - 1, clamp, compact, migr]
    elif rule == "clamp_down":
        pressured = sig.limit_break_d > 0 or \
            (backlog_hi > 0 and sig.backlog > backlog_hi)
        if pressured and clamp > spec["clamp_min"]:
            return [sync, level,
                    max(clamp - int(spec["clamp_step"]),
                        int(spec["clamp_min"])), compact, migr]
    elif rule == "clamp_up":
        drained = backlog_hi <= 0 or sig.backlog <= backlog_hi // 2
        if clean and drained and clamp < 100:
            return [sync, level,
                    min(clamp + int(spec["clamp_step"]), 100), compact,
                    migr]
    elif rule == "compact":
        # low occupancy after growth: slots fragmented / shard shrunk
        sparse = sig.capacity > int(spec["occ_floor"]) and \
            sig.live > 0 and sig.live < spec["occ_lo"] * sig.capacity
        if sparse:
            return [sync, level, clamp, compact + 1, migr]
    elif rule == "migrate":
        # per-shard pressure skew: the hottest shard's backlog exceeds
        # migrate_skew_hi times the all-shard mean (press_backlog * S
        # > hi * backlog avoids the division).  Two interchangeable
        # reads of the same ratio: the boundary-time depth read
        # (press_backlog / backlog) and the mid-epoch pressure-peak
        # read (press_peak / backlog_peak) -- the peaks are what arms
        # the rule on calendar engines, whose deadline commits drain
        # state.depth within the epoch so the boundary read is
        # structurally zero there.  Hysteresis applies (migrate is NOT
        # in _IMMEDIATE): moving clients is never an emergency action,
        # and cooldown spaces the handoffs out so a move's effect
        # lands before the next decision.
        hi = float(spec.get("migrate_skew_hi", 0.0))
        shards = int(spec.get("migrate_shards", 1))
        if hi > 0 and shards > 1:
            depth_skew = sig.backlog > 0 and \
                sig.press_backlog * shards > hi * sig.backlog
            peak_skew = sig.backlog_peak > 0 and \
                sig.press_peak * shards > hi * sig.backlog_peak
            if depth_skew or peak_skew:
                return [sync, level, clamp, compact,
                        migr + int(spec.get("migrate_max", 4))]
    else:
        raise ValueError(f"unknown controller rule {rule!r}")
    return None


def step(pstate, knobs, sig, spec) -> Tuple[np.ndarray, list]:
    """Evaluate one boundary.  ``pstate`` is ``int64[2*NUM_RULES]``
    ([streak, cooldown] per rule, the ``ctl_policy`` checkpoint leaf);
    returns ``(new_pstate, decisions)`` with ``decisions`` a list of
    ``(rule, new_knob_vector)`` in firing order.  Later rules see
    earlier rules' knob updates (fixed order keeps this
    deterministic)."""
    ps = np.asarray(pstate, dtype=np.int64).reshape(NUM_RULES, 2).copy()
    knobs = [int(k) for k in knobs]
    decisions: list = []
    for ri, rule in enumerate(RULES):
        streak, cool = int(ps[ri, 0]), int(ps[ri, 1])
        if cool > 0:
            ps[ri] = (0, cool - 1)      # cooling: inert, streak resets
            continue
        new = _propose(rule, knobs, sig, spec)
        if new is None:
            ps[ri] = (0, 0)
            continue
        streak += 1
        if streak >= _hysteresis(rule, spec):
            decisions.append((rule, list(new)))
            knobs = list(new)
            ps[ri] = (0, max(int(spec["cooldown"]), 0))
        else:
            ps[ri] = (streak, 0)
    return ps.reshape(-1), decisions


def overlay(cfg: dict, level: int) -> dict:
    """Map an engine config through the first ``level`` engageable
    :data:`robust.guarded.LADDER_RUNGS` -- the controller's ladder
    actuation, and the reason every step is digest-explainable: each
    rung swaps a fast path for its pinned always-exact twin.  Chains
    the shared-knob calendar rungs exactly like
    ``DegradationLadder.apply`` (wheel->bucketed rewrites the value
    bucketed->minstop then reads)."""
    from ..robust.guarded import LADDER_RUNGS
    out = dict(cfg)
    engaged = 0
    for knob, fast, safe in LADDER_RUNGS:
        if engaged >= level:
            break
        if out.get(knob) == fast:
            out[knob] = safe
            engaged += 1
    return out
