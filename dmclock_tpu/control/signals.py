"""ControlSignals: one typed snapshot per checkpoint boundary.

The controller (docs/CONTROLLER.md) decides from ONE immutable
snapshot assembled at each checkpoint boundary.  Fields split into two
tiers, and the split is the whole determinism story:

- **Deterministic fields** (:data:`DETERMINISTIC_FIELDS`) are derived
  exclusively from state that rides the rotation checkpoints or is
  replay-deterministic from it: SLO episode-count deltas
  (``obs.alerts.SloEvaluator`` fired counts restore from the
  ``slo_alert_*`` leaves), device metric-row deltas (``met`` vector,
  RESUME_ROWS excluded), engine backlog (``state.depth``), lifecycle
  slot occupancy, and the provenance starvation watermark.  Rules read
  ONLY these, and the journal's ``digest`` hashes ONLY these -- so a
  resumed incarnation re-deciding a boundary reproduces the
  uninterrupted run's decisions bit-for-bit.
- **Advisory fields** are best-effort host telemetry (capacity-plane
  retraces/compile wall, projected HBM, bound_class, the span
  watchdog's dispatch share, launch/stream fallback counts).  They are
  carried for observability but are EXCLUDED from both the rule table
  and the digest: retrace counts and wall-clock shares restart at zero
  in a resumed process, and a signal that differs across a resume
  would break crash equivalence.
"""

from __future__ import annotations

import hashlib
import json
from typing import NamedTuple


class ControlSignals(NamedTuple):
    """One boundary's snapshot.  Deltas (``*_d``) are since the
    previous boundary of the same run (a resumed incarnation's
    baseline is the restored checkpoint state, which IS the previous
    boundary)."""

    epoch: int                # the boundary epoch this snapshot is for
    # -- deterministic tier (rules + digest) ---------------------------
    backlog: int              # sum of per-slot queue depths
    live: int                 # lifecycle live slots (0: no plane)
    capacity: int             # lifecycle slot capacity (0: no plane)
    resv_miss_d: int          # SLO episodes fired since last boundary
    limit_break_d: int
    share_skew_d: int
    violations_d: int
    guard_trips_d: int        # device metric-row deltas
    ingest_drops_d: int
    ladder_steps_d: int
    starvation_ns: int        # provenance PS_STARVE_MAX watermark
    press_backlog: int        # hottest shard's backlog (== backlog, S=1)
    # mid-epoch pressure PEAKS (deterministic: the chunk's per-shard
    # post-ingest pre-serve probe maxima, replay-exact from the
    # checkpointed RNG + state -- obs.provenance.pressure_vec through
    # engine.stream.make_epoch_step).  The boundary-time depth reads
    # above are structurally zero on calendar engines (deadline
    # commits drain depth within the epoch); these peaks are the
    # migrate rule's calendar-capable twin.  Default 0 = no probe
    # (round/stream loops, controller off), which keeps the peak
    # branch of the migrate rule inert there.
    press_peak: int = 0       # hottest shard's mid-epoch backlog peak
    backlog_peak: int = 0     # sum of per-shard mid-epoch peaks
    # -- advisory tier (observability only; NOT rules, NOT digest) -----
    retraces: int = 0         # capacity plane, this process only
    compile_ms: float = 0.0
    projected_hbm: int = 0
    bound_class: str = ""
    dispatch_share: float = 0.0   # span watchdog, this process only
    fallbacks: int = 0        # stream/mesh launch fallbacks, process


DETERMINISTIC_FIELDS = (
    "epoch", "backlog", "live", "capacity",
    "resv_miss_d", "limit_break_d", "share_skew_d", "violations_d",
    "guard_trips_d", "ingest_drops_d", "ladder_steps_d",
    "starvation_ns", "press_backlog", "press_peak", "backlog_peak",
)


def digest(sig: ControlSignals) -> str:
    """Short stable hash of the deterministic tier -- journaled with
    every decision so a replayed boundary can be audited against the
    signals it originally decided from."""
    blob = json.dumps({k: int(getattr(sig, k))
                       for k in DETERMINISTIC_FIELDS},
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]
