"""dmclock_tpu.control -- the closed-loop serving controller.

A thin host control plane at checkpoint-boundary cadence (the
RackSched two-level shape: a reactive policy layer steering otherwise
unmodified per-server engines).  Per boundary it assembles one
:class:`~dmclock_tpu.control.signals.ControlSignals` snapshot from the
existing observability planes, runs the pure guarded-transition table
(:mod:`~dmclock_tpu.control.policy`), write-ahead-journals every
decision (:mod:`~dmclock_tpu.control.journal`), and only then moves
the knob vector.  Every actuation goes through an existing
exact-twin/digest-neutral mechanism, so ``controller=off`` is
bit-identical to the bare runner and every individual actuation is
digest-explainable.  docs/CONTROLLER.md is the full contract.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from . import journal as journal_mod
from . import policy as policy_mod
from . import signals as signals_mod
from .policy import (KNOB_CLAMP, KNOB_COMPACT, KNOB_LADDER,  # noqa: F401
                     KNOB_MIGRATE, KNOB_NAMES, KNOB_SYNC, NUM_KNOBS,
                     NUM_RULES, RULES)
from .signals import ControlSignals  # noqa: F401

__all__ = ["Controller", "ControllerConfig", "ControlSignals",
           "as_spec", "publish_controller", "RULES", "KNOB_NAMES"]


@dataclasses.dataclass(frozen=True)
class ControllerConfig:
    """Typed spell of the policy spec (``EpochJob(controller=...)``
    accepts this, a plain dict with the same keys, or None).  ``0``
    fields mean auto -- see :data:`policy.DEFAULT_SPEC`."""

    enabled: bool = True
    hysteresis: int = 2
    cooldown: int = 2
    sync_min: int = 1
    sync_max: int = 8
    clamp_min: int = 25
    clamp_step: int = 25
    backlog_hi: int = 0
    occ_lo: float = 0.5
    occ_floor: int = 0
    ladder_max: int = 0
    migrate_skew_hi: float = 0.0
    migrate_max: int = 4
    migrate_pick: str = "hot"


def as_spec(obj) -> Optional[dict]:
    """Normalize ``EpochJob.controller`` (None/False, spec dict, or
    :class:`ControllerConfig`) to a complete spec dict -- or None when
    the controller is off, which the supervisor treats as
    zero-plumbing (the ``controller=off`` == bare-runner gate)."""
    if obj is None or obj is False:
        return None
    if obj is True:
        obj = {}
    if isinstance(obj, ControllerConfig):
        obj = dataclasses.asdict(obj)
    obj = dict(obj)
    unknown = set(obj) - set(policy_mod.DEFAULT_SPEC)
    assert not unknown, f"unknown controller spec keys {sorted(unknown)}"
    spec = dict(policy_mod.DEFAULT_SPEC)
    spec.update(obj)
    if not spec.get("enabled", True):
        return None
    if int(spec["ladder_max"]) <= 0:
        spec["ladder_max"] = policy_mod.ladder_max_default()
    return spec


class Controller:
    """One job loop's controller instance.

    Host state is three checkpoint leaves (``ctl_cursor`` applied-
    decision count, ``ctl_knobs`` knob vector, ``ctl_policy``
    per-rule streak/cooldown) plus the on-disk journal; everything
    else re-derives.  Delta baselines (:meth:`observe_baseline`) pin
    to the restored state at incarnation start, which IS the previous
    boundary's snapshot -- deltas replay identically across a resume.
    """

    def __init__(self, spec: dict, *, n: int, ring: int,
                 counter_sync_every: int = 1, capacity0: int = 0,
                 n_shards: int = 1,
                 workdir: Optional[str] = None, registry=None):
        self.spec = dict(spec)
        if int(self.spec.get("backlog_hi", 0)) <= 0:
            self.spec["backlog_hi"] = max(int(n) * int(ring) * 3 // 4, 1)
        if int(self.spec.get("occ_floor", 0)) <= 0:
            self.spec["occ_floor"] = max(int(capacity0), 0)
        # the migrate rule needs the shard count for its skew ratio
        # (pure policy sees only the spec, so the ctor pins it there)
        self.spec["migrate_shards"] = max(int(n_shards), 1)
        self.knobs = [max(int(counter_sync_every), 1), 0, 100, 0, 0]
        self.pstate = np.zeros(2 * NUM_RULES, dtype=np.int64)
        self.applied = 0            # the ctl_cursor leaf
        self.replays = 0            # journaled decisions replayed
        self.journal = journal_mod.DecisionJournal(workdir)
        self.decisions_by_rule = {r: 0 for r in RULES}
        self._prev = self._zero_snap()
        if registry is not None:
            publish_controller(registry, self)

    # -- checkpoint leaves ---------------------------------------------
    def encode(self) -> dict:
        return {"ctl_cursor": np.asarray(self.applied, dtype=np.int64),
                "ctl_knobs": np.asarray(self.knobs, dtype=np.int64),
                "ctl_policy": np.asarray(self.pstate, dtype=np.int64)}

    @staticmethod
    def empty_leaves() -> dict:
        """Always-present payload leaves for controller-off jobs (the
        every-leaf-always-present checkpoint convention)."""
        return {"ctl_cursor": np.zeros((), dtype=np.int64),
                "ctl_knobs": np.zeros((NUM_KNOBS,), dtype=np.int64),
                "ctl_policy": np.zeros((2 * NUM_RULES,),
                                       dtype=np.int64)}

    def load(self, payload: dict) -> None:
        if "ctl_cursor" not in payload:
            return
        self.applied = int(np.asarray(payload["ctl_cursor"]))
        self.knobs = [int(x) for x in np.asarray(payload["ctl_knobs"])]
        self.pstate = np.asarray(payload["ctl_policy"],
                                 dtype=np.int64).copy()
        self.decisions_by_rule = {r: 0 for r in RULES}
        for ent in self.journal.entries[:self.applied]:
            self.decisions_by_rule[str(ent["rule"])] += 1

    # -- signal assembly -----------------------------------------------
    @staticmethod
    def _zero_snap() -> dict:
        return {"met": np.zeros(3, dtype=np.int64),
                "slo": np.zeros(4, dtype=np.int64)}

    @staticmethod
    def _snap(met=None, slo_eval=None) -> dict:
        s = Controller._zero_snap()
        if met is not None:
            from ..obs import device as obs_device
            m = np.asarray(met, dtype=np.int64)
            if m.ndim > 1:          # stacked per-shard mesh vector
                m = m.sum(axis=0)
            s["met"] = np.asarray(
                [m[obs_device.MET_GUARD_TRIPS],
                 m[obs_device.MET_INGEST_DROPS],
                 m[obs_device.MET_LADDER_STEPS]], dtype=np.int64)
        if slo_eval is not None:
            from ..obs.alerts import RULES as SLO_RULES
            s["slo"] = np.asarray(
                [slo_eval.violations_total]
                + [slo_eval.fired_counts[r] for r in SLO_RULES],
                dtype=np.int64)
        return s

    def observe_baseline(self, *, met=None, slo_eval=None) -> None:
        """Pin the delta baseline at incarnation start (post-restore).
        The restored counters equal their values at the last completed
        boundary, so a resumed run's first delta matches the
        uninterrupted run's."""
        self._prev = self._snap(met=met, slo_eval=slo_eval)

    def collect(self, epoch: int, *, state=None, met=None,
                slo_eval=None, prov=None, planes=None,
                press=None, advisory=None) -> ControlSignals:
        """Assemble one boundary's snapshot and advance the delta
        baseline.  ``planes`` is a list of LifecyclePlane (or None
        entries); ``press`` the chunk's per-shard mid-epoch pressure
        peaks (``int64[S, PRESS_FIELDS]``, ``MeshGuarded.press``) --
        replay-deterministic, so the peak fields stay in the
        deterministic tier; ``advisory`` a dict of best-effort
        extras."""
        import jax
        cur = self._snap(met=met, slo_eval=slo_eval)
        dmet = cur["met"] - self._prev["met"]
        dslo = cur["slo"] - self._prev["slo"]
        self._prev = cur
        backlog = press_bk = 0
        if state is not None:
            depth = np.asarray(jax.device_get(state.depth),
                               dtype=np.int64)
            backlog = int(depth.sum())
            press_bk = int(depth.sum(axis=-1).max()) \
                if depth.ndim > 1 else backlog
        press_peak = backlog_peak = 0
        if press is not None:
            from ..obs import provenance as obs_prov
            peaks = np.asarray(press, dtype=np.int64) \
                .reshape(-1, obs_prov.PRESS_FIELDS)[
                    :, obs_prov.PRESS_BACKLOG]
            press_peak = int(peaks.max())
            backlog_peak = int(peaks.sum())
        live = cap = 0
        for p in (planes or []):
            if p is not None:
                live += int(p.slots.live_count)
                cap += int(p.slots.capacity)
        starve = 0
        if prov is not None:
            from ..obs import provenance as obs_prov
            scal = np.asarray(jax.device_get(prov.scal),
                              dtype=np.int64)
            starve = int(scal[..., obs_prov.PS_STARVE_MAX].max())
        adv = dict(advisory or {})
        return ControlSignals(
            epoch=int(epoch), backlog=backlog, live=live, capacity=cap,
            resv_miss_d=int(dslo[1]), limit_break_d=int(dslo[2]),
            share_skew_d=int(dslo[3]), violations_d=int(dslo[0]),
            guard_trips_d=int(dmet[0]), ingest_drops_d=int(dmet[1]),
            ladder_steps_d=int(dmet[2]), starvation_ns=starve,
            press_backlog=press_bk,
            press_peak=press_peak, backlog_peak=backlog_peak,
            retraces=int(adv.get("retraces", 0)),
            compile_ms=float(adv.get("compile_ms", 0.0)),
            projected_hbm=int(adv.get("projected_hbm", 0)),
            bound_class=str(adv.get("bound_class", "")),
            dispatch_share=float(adv.get("dispatch_share", 0.0)),
            fallbacks=int(adv.get("fallbacks", 0)))

    # -- the boundary step ---------------------------------------------
    def step(self, epoch: int, sig: ControlSignals,
             fault=None) -> list:
        """Run the rule table at boundary ``epoch`` and apply (or
        REPLAY) its decisions under the fsync-before-apply discipline.
        ``fault(epoch, stage)`` -- the HostFaultInjector seam -- fires
        at ``before_journal`` / ``after_journal`` / ``after_apply``
        around each decision.  Returns the rules applied, in order."""
        new_pstate, decisions = policy_mod.step(
            self.pstate, self.knobs, sig, self.spec)
        dig = signals_mod.digest(sig)
        fired = []
        for rule, new in decisions:
            seq = self.applied
            if fault is not None:
                fault(epoch, "before_journal")
            ent = self.journal.entry_at(seq)
            if ent is not None:
                # resumed incarnation: the decision is already durable.
                # Replay it -- and verify the pure policy agreed.
                assert str(ent["rule"]) == rule \
                    and int(ent["epoch"]) == int(epoch), \
                    (ent, rule, epoch)
                self.replays += 1
            else:
                ent = {"seq": seq, "epoch": int(epoch), "rule": rule,
                       "digest": dig,
                       "old": [int(k) for k in self.knobs],
                       "new": [int(k) for k in new]}
                self.journal.append(ent)    # flush+fsync BEFORE apply
            if fault is not None:
                fault(epoch, "after_journal")
            self.knobs = [int(k) for k in ent["new"]]
            self.applied += 1
            self.decisions_by_rule[rule] += 1
            fired.append(rule)
            if fault is not None:
                fault(epoch, "after_apply")
        self.pstate = new_pstate
        return fired

    # -- actuation accessors -------------------------------------------
    def knob_sync(self) -> int:
        return int(self.knobs[KNOB_SYNC])

    def clamp_pct(self) -> int:
        return int(self.knobs[KNOB_CLAMP])

    def migrate_batch(self) -> int:
        """Max clients the ``migrate`` actuation moves per firing."""
        return max(int(self.spec.get("migrate_max", 4)), 0)

    def migrate_pick(self) -> str:
        """Candidate pick policy for the migrate actuation: ``"hot"``
        (largest served-demand first) or ``"cold"`` (never-served
        first -- the digest-gate mode: quiet movers are exactly the
        clients whose move is provably placement-equivalent)."""
        return str(self.spec.get("migrate_pick", "hot"))

    def overlay(self, cfg: dict) -> dict:
        """Engine config through the controller's conceded ladder
        rungs (exact twins only)."""
        return policy_mod.overlay(cfg, int(self.knobs[KNOB_LADDER]))

    def clamp_counts(self, counts, waves: int):
        """Admission clamp on already-drawn arrival counts: cap every
        per-client count at ``clamp_pct`` of the superwave.  Applied
        AFTER the Poisson draw, so RNG consumption never depends on
        the knob."""
        pct = self.clamp_pct()
        if pct >= 100:
            return counts
        arr = np.asarray(counts)
        cap = max(1, (int(waves) * pct) // 100)
        return np.minimum(arr, np.asarray(cap, dtype=arr.dtype))

    # -- reporting -----------------------------------------------------
    def trajectory(self) -> list:
        """Applied decisions as JSON-able rows
        ``[seq, epoch, rule, new_knob...]`` -- the crash-equivalence
        comparand (journal entries are durable across restarts, so a
        resumed run reports the FULL run's trajectory)."""
        return [[int(e["seq"]), int(e["epoch"]), str(e["rule"])]
                + [int(x) for x in e["new"]]
                for e in self.journal.entries[:self.applied]]

    def describe(self) -> dict:
        return {"decisions": int(self.applied),
                "replays": int(self.replays),
                "knobs": [int(k) for k in self.knobs],
                "by_rule": {r: int(c)
                            for r, c in self.decisions_by_rule.items()
                            if c},
                "trajectory": self.trajectory()}


def publish_controller(registry, ctl: Controller) -> None:
    """Mount the ``dmclock_controller_*`` families on ``registry``
    (callback-backed: zero hot-path cost, exact across resume because
    they read the journal-rebuilt controller state)."""
    for rule in RULES:
        registry.gauge(
            "dmclock_controller_decisions_total",
            "controller decisions applied, by rule "
            "(docs/CONTROLLER.md)",
            labels={"rule": rule}) \
            .set_function(lambda r=rule: float(ctl.decisions_by_rule[r]))
    for i, name in enumerate(KNOB_NAMES):
        registry.gauge(
            "dmclock_controller_knob",
            "current actuated knob vector (counter_sync_every / "
            "ladder_level / clamp_pct / compact_trigger / "
            "migrate_trigger)",
            labels={"knob": name}) \
            .set_function(lambda i=i: float(ctl.knobs[i]))
    registry.gauge(
        "dmclock_controller_journal_replays_total",
        "journaled decisions REPLAYED (not re-decided) after a "
        "resume") \
        .set_function(lambda: float(ctl.replays))
