"""Write-ahead decision journal: fsync-before-apply, replay-not-
re-decide (the PR-9 WAL discipline aimed at controller decisions).

One JSON line per decision, strictly sequential ``seq``::

    {"seq": 3, "epoch": 8, "rule": "clamp_down",
     "digest": "9f2c...", "old": [1, 0, 100, 0], "new": [1, 0, 75, 0]}

Contract (docs/CONTROLLER.md "Replay"):

1. The entry is written + ``flush`` + ``fsync`` BEFORE the knob
   vector moves (``append`` is called before apply).
2. The checkpoint payload carries the APPLIED cursor (``ctl_cursor``),
   which can only trail the journal.  A resumed run re-derives each
   boundary's decisions (the policy is pure) and, where the journal
   already has the entry at that seq, REPLAYS the journaled knob
   vector instead of re-deciding -- so a kill at any point
   (before-write / after-write-before-apply / after-apply) yields the
   exact knob trajectory of the uninterrupted run, and the journal
   never holds two entries for one seq.
3. A kill mid-write can tear the last line; on open the torn tail is
   truncated away (the decision was never applied -- the resumed run
   re-decides it identically and rewrites it).

``workdir=None`` (the bare runner / controller smoke without a
supervisor) keeps the journal in memory only: same replay semantics
within the process, nothing durable.
"""

from __future__ import annotations

import json
import os
from typing import Optional

FILENAME = "controller.journal"


class DecisionJournal:

    def __init__(self, workdir: Optional[str] = None):
        self.path = os.path.join(os.fspath(workdir), FILENAME) \
            if workdir is not None else None
        self.entries: list = []
        if self.path is not None and os.path.exists(self.path):
            self._load()

    def _load(self) -> None:
        with open(self.path, "rb") as fh:
            data = fh.read()
        good = 0
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break               # torn tail: kill landed mid-write
            try:
                self.entries.append(json.loads(line))
            except ValueError:      # torn/rotted line: stop trusting
                break
            good += len(line)
        if good != len(data):
            with open(self.path, "r+b") as fh:
                fh.truncate(good)
                fh.flush()
                os.fsync(fh.fileno())

    def __len__(self) -> int:
        return len(self.entries)

    def entry_at(self, seq: int) -> Optional[dict]:
        """The journaled entry for decision ``seq`` (None when the
        journal hasn't reached it -- the fresh-decision case)."""
        if 0 <= seq < len(self.entries):
            return self.entries[seq]
        return None

    def append(self, entry: dict) -> None:
        """Durably journal one decision BEFORE it is applied."""
        assert int(entry["seq"]) == len(self.entries), \
            (entry["seq"], len(self.entries))
        if self.path is not None:
            with open(self.path, "a") as fh:
                fh.write(json.dumps(entry, sort_keys=True) + "\n")
                fh.flush()
                os.fsync(fh.fileno())
        self.entries.append(entry)
