"""The SLO plane: device-resident windowed conformance.

The dmClock contract (reservation floor / limit ceiling / proportional
weight, mClock paper section 3) was only verifiable post-hoc: the PR-6
``int64[N, 5]`` ledger and the sim conformance tables are *cumulative*
artifacts, which smear across contract versions now that the lifecycle
plane (PR-9) replaces QoS triples mid-run.  RackSched's thesis
(PAPERS.md) is that microsecond-scale schedulers need continuously
evaluated, *windowed* policy-compliance signals -- this module supplies
them in three layers:

1. **Device window block** (``int64[N, W_FIELDS]``): per-client
   delivered ops, delivered cost, reservation-phase ops, tardy ops,
   limit-break ops, reservation-tardiness sum, and the window's
   contract-epoch id.  The counter columns accumulate inside all three
   epoch scans exactly like the PR-6 histograms/ledger (riding the scan
   carries, folded per batch gated on tag32 liveness, ``psum``-able
   with a ``pmax`` contract-epoch column); the decision stream is
   bit-identical with the block on or off (tests/test_slo.py).

2. **Window rolls pinned to the epoch grid**: a window is the epochs
   between two PR-5 checkpoint boundaries (= the PR-8 stream-chunk
   grid), so the round loop and the stream loop roll IDENTICALLY and a
   rotation checkpoint never splits a window -- crash equivalence
   extends to the block, the closed-window ring, and the contract-epoch
   counters with no new machinery (``robust.supervisor``).

3. **Host plane** (:class:`SloPlane`): a per-client **contract-epoch
   counter** bumped by every lifecycle REGISTER/UPDATE/EVICT, a bounded
   ring of closed windows per client -- each attributed to exactly one
   ``(client, contract_version)`` pair, read from the block's
   device-stamped contract-epoch column -- and per-window delivered-vs-
   contract conformance (share error against the weight entitlement,
   reservation-floor deficit, limit excess).  ``obs.alerts`` evaluates
   burn-rate rules over the ring; ``scripts/slo_report.py`` renders the
   exported JSONL offline.
"""

from __future__ import annotations

import dataclasses
import json
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

# -- window block columns ----------------------------------------------
W_OPS = 0          # decisions delivered in the window
W_COST = 1         # delivered cost (sum of served request costs)
W_RESV_OPS = 2     # constraint-phase (reservation-eligible) decisions
W_TARDY_OPS = 3    # reservation entries served PAST their deadline
W_LB_OPS = 4       # AtLimit::Allow limit-break entries
W_TARD_SUM = 5     # reservation tardiness sum, ns (entry-head obs)
W_CEPOCH = 6       # contract-epoch id (host-stamped at window open)
W_FIELDS = 7

WINDOW_COL_NAMES = ("ops", "cost", "resv_ops", "tardy_ops", "lb_ops",
                    "tardiness_sum_ns", "contract_epoch")

# the contract-epoch column is metadata, not a counter: deltas carry 0
# there and merges keep the max (same host-constant-mask rule as the
# ledger's tardiness-max column -- a module-level jnp array would leak
# a tracer under a lazy import inside a jit trace)
_W_MAX_MASK = np.zeros((W_FIELDS,), dtype=bool)
_W_MAX_MASK[W_CEPOCH] = True


def window_zero(n: int):
    import jax.numpy as jnp

    return jnp.zeros((n, W_FIELDS), dtype=jnp.int64)


def window_delta(served_pc, cost_pc, resv_pc, tardy_pc, lb_pc,
                 tard_pc):
    """One batch/level's window contribution (``int64[N, W_FIELDS]``):
    pure stacking of per-client reductions the telemetry fold already
    computed, so the decision stream cannot be perturbed.  The
    contract-epoch column rides as zeros (max-merged, so the stamped
    accumulator value survives every fold)."""
    import jax.numpy as jnp

    cols = [jnp.asarray(c, dtype=jnp.int64)
            for c in (served_pc, cost_pc, resv_pc, tardy_pc, lb_pc,
                      tard_pc)]
    cols.append(jnp.zeros_like(cols[0]))
    return jnp.stack(cols, axis=1)


def window_combine(a, b):
    """Merge two window blocks over the SAME client set: counter
    columns add, the contract-epoch column maxes -- associative and
    commutative, the ledger algebra applied per window field."""
    import jax.numpy as jnp

    return jnp.where(_W_MAX_MASK, jnp.maximum(a, b), a + b)


def window_fold(w, delta, live):
    """Fold a batch delta gated on liveness (the tag32 dead-batch rule:
    a tripped batch's window contribution must not land)."""
    import jax.numpy as jnp

    return window_combine(w, jnp.where(live, delta,
                                       jnp.zeros_like(delta)))


def window_mesh_reduce(w, axis_name: str):
    """In-graph mesh merge for REPLICATED client sets: counter columns
    ``psum``, the contract-epoch column ``pmax`` (every shard stamps
    the same epochs) -- the window analog of
    ``obs.histograms.ledger_mesh_reduce``."""
    import jax.numpy as jnp
    from jax import lax

    return jnp.where(_W_MAX_MASK, lax.pmax(w, axis_name),
                     lax.psum(w, axis_name))


def window_combine_axis(mat):
    """Reduce a stacked ``[S, N, W_FIELDS]`` block along its leading
    shard axis (counter columns sum, contract-epoch max) -- the
    local half of a mesh merge (vmapped servers within a shard reduce
    here, then :func:`window_mesh_reduce` crosses the mesh), the
    window analog of ``obs.device.metrics_combine_axis``."""
    import jax.numpy as jnp

    return jnp.where(_W_MAX_MASK, jnp.max(mat, axis=0),
                     jnp.sum(mat, axis=0))


def window_combine_np(acc, *blocks):
    """Host-side mirror of :func:`window_combine` over numpy blocks
    (counters add, contract-epoch max) -- what the mesh merge tests
    compare the in-graph ``window_mesh_reduce`` result against, and
    what the supervisor uses to merge fetched per-shard blocks when
    no mesh program is live.  Derives the max column from the same
    ``_W_MAX_MASK`` as the device merge, so the two cannot drift."""
    acc = np.asarray(acc, dtype=np.int64)
    for b in blocks:
        b = np.asarray(b, dtype=np.int64)
        acc = np.where(_W_MAX_MASK, np.maximum(acc, b), acc + b)
    return acc


def publish_shard_windows(registry, blocks, merged=None,
                          workload: Optional[str] = None) -> None:
    """Publish per-shard window-block totals as ``dmclock_slo_window_*``
    gauges labelled by ``shard`` (the ROADMAP PR-10 fold-in: the
    cluster-wide delivered-vs-contract table keeps its per-shard
    decomposition visible), plus the mesh-merged cluster total under
    ``shard="all"``.  ``blocks`` is ``[S, N, W_FIELDS]`` (stacked) or
    an iterable of per-shard blocks; ``merged`` defaults to the host
    combine of the shards."""
    blocks = [np.asarray(b, dtype=np.int64) for b in blocks]
    if merged is None and blocks:
        merged = window_combine_np(np.zeros_like(blocks[0]), *blocks)

    def emit(block, shard: str) -> None:
        labels = {"shard": shard}
        if workload is not None:
            labels["workload"] = workload
        for name, val in window_totals(block).items():
            registry.gauge(
                f"dmclock_slo_window_{name}",
                "cluster-wide windowed conformance column, per shard "
                "(docs/OBSERVABILITY.md SLO plane; shard=all is the "
                "window_mesh_reduce merge)",
                labels=labels).set(float(val))

    for s, block in enumerate(blocks):
        emit(block, str(s))
    if merged is not None:
        emit(np.asarray(merged, dtype=np.int64), "all")


def stamp_cepoch(block, cepochs):
    """Write the per-slot contract-epoch ids into the block's
    :data:`W_CEPOCH` column (one cheap device launch per boundary --
    lifecycle ops apply only there, so the column is always current
    for the window that follows)."""
    import jax.numpy as jnp

    return block.at[:, W_CEPOCH].set(
        jnp.asarray(cepochs, dtype=jnp.int64))


def window_totals(block) -> dict:
    """Counter-column totals of a fetched block (host side) -- what
    the windowed-vs-cumulative cross-check sums against the ledger."""
    a = np.asarray(block, dtype=np.int64)
    return {name: int(a[:, i].sum())
            for i, name in enumerate(WINDOW_COL_NAMES)
            if i != W_CEPOCH}


# ----------------------------------------------------------------------
# host plane: contract epochs + closed-window ring + conformance
# ----------------------------------------------------------------------

RING_COLS = 12  # seq, cid, cepoch, e0, e1, ops, cost, resv_ops,
#                 tardy_ops, lb_ops, tard_sum_ns, backlog


@dataclasses.dataclass(frozen=True)
class ClosedWindow:
    """One client's closed window, attributed to exactly one
    ``(client, contract_epoch)`` pair.  ``backlog`` is the client's
    queue depth at close -- what separates a reservation-starved
    client (backlogged, undelivered) from an idle one."""

    seq: int          # global roll sequence number
    cid: int          # client id
    cepoch: int       # contract-epoch id (device-stamped)
    e0: int           # first epoch of the window
    e1: int           # one past the last epoch
    ops: int
    cost: int
    resv_ops: int
    tardy_ops: int
    lb_ops: int
    tard_sum_ns: int
    backlog: int

    def row(self) -> list:
        return [self.seq, self.cid, self.cepoch, self.e0, self.e1,
                self.ops, self.cost, self.resv_ops, self.tardy_ops,
                self.lb_ops, self.tard_sum_ns, self.backlog]

    @classmethod
    def from_row(cls, r) -> "ClosedWindow":
        r = [int(x) for x in r]
        return cls(*r)

    def to_json(self) -> dict:
        return {"seq": self.seq, "client": self.cid,
                "contract_epoch": self.cepoch,
                "e0": self.e0, "e1": self.e1, "ops": self.ops,
                "cost": self.cost, "resv_ops": self.resv_ops,
                "tardy_ops": self.tardy_ops, "lb_ops": self.lb_ops,
                "tardiness_sum_ns": self.tard_sum_ns,
                "backlog": self.backlog}


class SloPlane:
    """Host half of the windowed conformance plane for one run.

    Owns the per-client contract-epoch counters (bumped by lifecycle
    REGISTER/UPDATE/EVICT; a re-registered client continues its own
    monotone counter, so versions never repeat), the current + per-
    epoch contract log (reservation, weight, limit as RATES -- what
    delivered-vs-contract is priced against), and a bounded per-client
    ring of closed windows.  All state is plain data and encodes into
    flat ``slo_*`` checkpoint leaves, so a SIGKILLed run resumes with
    the identical attribution state (the crash-equivalence contract).

    Thread contract: single-owner (the epoch loop); the admin API
    reads through :meth:`summary` / :meth:`client_view`, which copy
    under the GIL over plain containers.
    """

    def __init__(self, capacity: int, *, dt_epoch_ns: int,
                 ring_depth: int = 64):
        self.capacity = int(capacity)
        self.dt_epoch_ns = int(dt_epoch_ns)
        self.ring_depth = max(int(ring_depth), 1)
        self.cepoch: Dict[int, int] = {}
        self.contracts: Dict[int, Tuple[float, float, float]] = {}
        # (cid, cepoch) -> (r, w, l): attribution for closed windows
        self.contract_log: Dict[Tuple[int, int],
                                Tuple[float, float, float]] = {}
        self.rings: Dict[int, deque] = {}
        self.window_seq = 0
        self.windows_closed = 0

    # -- contract-epoch bumps (the lifecycle plane calls these) --------
    def register(self, cid: int, r: float, w: float, l: float) -> int:
        """REGISTER bumps the client's contract epoch (a recycled id's
        counter continues from its last value -- a fresh tenancy is a
        fresh contract version) and records the contract."""
        cid = int(cid)
        ce = self.cepoch.get(cid, 0) + 1
        self.cepoch[cid] = ce
        self.contracts[cid] = (float(r), float(w), float(l))
        self.contract_log[(cid, ce)] = self.contracts[cid]
        return ce

    def update(self, cid: int, r: float, w: float, l: float) -> int:
        """Live ClientInfo UPDATE: same bump -- every closed window
        reports against exactly one contract version, never a blend."""
        return self.register(cid, r, w, l)

    def evict(self, cid: int) -> None:
        """EVICT ends the tenancy: the contract goes away, the epoch
        counter stays (monotone across re-registration), the ring
        keeps the departed client's closed windows."""
        self.contracts.pop(int(cid), None)

    def contract_of(self, cid: int, cepoch: int
                    ) -> Optional[Tuple[float, float, float]]:
        return self.contract_log.get((int(cid), int(cepoch)))

    # -- device-column stamping ----------------------------------------
    def cepoch_vector(self, cid_of_slot=None) -> np.ndarray:
        """Per-slot contract-epoch ids (0 for free slots) under the
        current slot layout; ``cid_of_slot=None`` = identity (closed-
        population runs, slot == client id)."""
        if cid_of_slot is None:
            return np.asarray([self.cepoch.get(c, 0)
                               for c in range(self.capacity)],
                              dtype=np.int64)
        cid_of_slot = np.asarray(cid_of_slot)
        return np.asarray(
            [self.cepoch.get(int(c), 0) if c >= 0 else 0
             for c in cid_of_slot], dtype=np.int64)

    def stamp(self, block, cid_of_slot=None):
        """Stamp the block's contract-epoch column from the host
        counters (capacity tracks the block: growth pads the vector)."""
        self.capacity = int(block.shape[0])
        return stamp_cepoch(block, self.cepoch_vector(cid_of_slot))

    # -- the roll ------------------------------------------------------
    def roll(self, block, e0: int, e1: int, *, cid_of_slot=None,
             depth=None, skip_idle: bool = False
             ) -> Tuple[object, List[ClosedWindow]]:
        """Close the window ``[e0, e1)``: fetch the block, append one
        :class:`ClosedWindow` per client with any activity (or a live
        contract -- a backlogged-but-starved client's empty window is
        the signal the reservation rule exists for), and return a
        fresh zeroed block with the contract-epoch column re-stamped.
        ``depth`` (optional ``int[N]``) records per-client backlog at
        close.  ``skip_idle`` drops zero-activity windows even for
        live contracts (large-N bench runs where every client serves
        anyway; keep it OFF when reservation-starvation must be
        detectable -- a starved client's window IS all zeros).
        Deterministic: same block + same counters -> same rows, so a
        resumed run re-rolls identically."""
        import jax

        a = np.asarray(jax.device_get(block), dtype=np.int64)
        self.capacity = a.shape[0]
        d = None if depth is None \
            else np.asarray(jax.device_get(depth), dtype=np.int64)
        closed: List[ClosedWindow] = []
        seq = self.window_seq
        for slot in range(a.shape[0]):
            if cid_of_slot is None:
                cid = slot
            else:
                cid = int(cid_of_slot[slot])
                if cid < 0:
                    continue
            row = a[slot]
            active = bool(row[:W_CEPOCH].any())
            if not active and (skip_idle
                               or cid not in self.contracts):
                continue
            if not active and row[W_CEPOCH] == 0:
                continue     # never registered on device yet
            w = ClosedWindow(
                seq=seq, cid=cid, cepoch=int(row[W_CEPOCH]),
                e0=int(e0), e1=int(e1),
                ops=int(row[W_OPS]), cost=int(row[W_COST]),
                resv_ops=int(row[W_RESV_OPS]),
                tardy_ops=int(row[W_TARDY_OPS]),
                lb_ops=int(row[W_LB_OPS]),
                tard_sum_ns=int(row[W_TARD_SUM]),
                backlog=0 if d is None else int(d[slot]))
            closed.append(w)
            self.rings.setdefault(cid, deque(maxlen=self.ring_depth)) \
                .append(w)
        self.window_seq += 1
        self.windows_closed += len(closed)
        fresh = self.stamp(window_zero(a.shape[0]), cid_of_slot)
        return fresh, closed

    # -- conformance ---------------------------------------------------
    def conformance_rows(self, closed: List[ClosedWindow]
                         ) -> List[dict]:
        """Delivered-vs-contract judgment of one roll's closed windows
        (all share ``[e0, e1)``): per client the delivered rate vs the
        reservation floor, the delivered cost share vs the weight
        entitlement among clients with demand, and the limit excess --
        each against the window's OWN contract version (no smearing
        across a mid-run update)."""
        if not closed:
            return []
        win_s = max((closed[0].e1 - closed[0].e0)
                    * self.dt_epoch_ns / 1e9, 1e-12)
        demand = [w for w in closed if w.ops > 0 or w.backlog > 0]
        total_cost = sum(w.cost for w in demand)
        wsum = 0.0
        for w in demand:
            c = self.contract_of(w.cid, w.cepoch)
            wsum += c[1] if c else 0.0
        rows = []
        for w in closed:
            c = self.contract_of(w.cid, w.cepoch) or (0.0, 0.0, 0.0)
            r, wt, lim = c
            rate = w.ops / win_s
            share = w.cost / total_cost if total_cost else 0.0
            entitled = (wt / wsum) if (wsum > 0 and
                                       (w.ops > 0 or w.backlog > 0)) \
                else 0.0
            share_err = (share - entitled) / max(entitled, 1e-9) \
                if entitled > 0 else 0.0
            resv_deficit = max(r - rate, 0.0) if r > 0 else 0.0
            # a reservation miss needs BACKLOG or tardiness: an idle
            # client under its floor is not a starved one
            resv_miss = bool(r > 0 and resv_deficit > 0.05 * r
                             and (w.backlog > 0 or w.tardy_ops > 0))
            limit_excess = max(rate - lim, 0.0) if lim > 0 else 0.0
            rows.append({
                **w.to_json(),
                "window_s": win_s, "rate": rate,
                "reservation": r, "weight": wt, "limit": lim,
                "share": share, "entitled_share": entitled,
                "share_err": share_err,
                "resv_deficit": resv_deficit, "resv_miss": resv_miss,
                "limit_excess": limit_excess,
                "tardiness_mean_ns": w.tard_sum_ns
                / max(w.resv_ops, 1),
            })
        return rows

    # -- views / reports -----------------------------------------------
    def ring_rows(self, cid: Optional[int] = None
                  ) -> List[ClosedWindow]:
        """Closed windows, oldest first (one client's ring or all,
        interleaved in close order).  Snapshots the containers before
        iterating: the admin HTTP thread reads this while the epoch
        loop's roll() inserts new clients, and iterating the live
        dict would intermittently raise mid-scrape."""
        if cid is not None:
            return list(self.rings.get(int(cid), ()))
        out = [w for ring in list(self.rings.values())
               for w in list(ring)]
        out.sort(key=lambda w: (w.seq, w.cid))
        return out

    def client_view(self, cid: int) -> dict:
        """One client's conformance view (the admin API's
        ``GET /clients/{id}/conformance``).  Each roll group is
        judged ONCE (the client appears in a given seq at most once)
        -- re-judging the full group per ring window would make one
        GET O(ring_depth x live_clients) on the HTTP thread.

        Judgments reflect the SURVIVING ring: once a busier peer's
        window for the same roll has been evicted from its own ring,
        the share denominators here are computed over the remaining
        set and can differ from the at-close judgment (the slo_log
        JSONL is the at-close record; this view is a live ring
        inspection, not an archive)."""
        cid = int(cid)
        want = {w.seq for w in list(self.rings.get(cid, ()))}
        grouped: Dict[int, List[ClosedWindow]] = {}
        for w in self.ring_rows():
            if w.seq in want:
                grouped.setdefault(w.seq, []).append(w)
        rows = []
        for seq in sorted(grouped):
            judged = self.conformance_rows(grouped[seq])
            rows += [r for r in judged if r["client"] == cid]
        return {"id": cid,
                "contract_epoch": self.cepoch.get(cid, 0),
                "contract": self.contracts.get(cid),
                "windows": rows}

    def summary(self) -> dict:
        return {"windows_closed": int(self.windows_closed),
                "rolls": int(self.window_seq),
                "clients_tracked": len(self.rings),
                "live_contracts": len(self.contracts),
                "ring_depth": self.ring_depth}

    def export_jsonl(self, path: str, closed: List[ClosedWindow],
                     judged: bool = True) -> int:
        """Append one roll's closed windows (judged rows when
        ``judged``) as JSONL -- the ``scripts/slo_report.py`` feed.
        Fail-soft is the CALLER's job (telemetry must never kill the
        run, but which exceptions are survivable is loop-specific)."""
        rows = self.conformance_rows(closed) if judged \
            else [w.to_json() for w in closed]
        with open(path, "a") as fh:
            for r in rows:
                fh.write(json.dumps(r) + "\n")
        return len(rows)

    # -- checkpoint round-trip -----------------------------------------
    def encode(self) -> dict:
        """Flat ``slo_*`` leaves for the PR-5 rotation payload."""
        ce = np.asarray(sorted((c, e) for c, e in self.cepoch.items()),
                        dtype=np.int64).reshape(len(self.cepoch), 2)
        con = np.asarray(
            [[c, e, r, w, l]
             for (c, e), (r, w, l) in sorted(self.contract_log.items())],
            dtype=np.float64).reshape(len(self.contract_log), 5)
        live = np.asarray(sorted(self.contracts), dtype=np.int64)
        ring = np.asarray([w.row() for w in self.ring_rows()],
                          dtype=np.int64).reshape(-1, RING_COLS)
        return {"slo_cepoch": ce, "slo_contracts": con,
                "slo_live": live, "slo_ring": ring,
                "slo_scalars": np.asarray(
                    [self.window_seq, self.windows_closed,
                     self.ring_depth], dtype=np.int64)}

    @classmethod
    def load(cls, payload: dict, *, capacity: int,
             dt_epoch_ns: int,
             ring_depth: Optional[int] = None) -> "SloPlane":
        """``ring_depth`` overrides the checkpointed depth BEFORE the
        rings are rebuilt, so every client's deque gets the new
        maxlen (an override applied after load would leave restored
        clients at the old depth and new registrants at the new
        one)."""
        sc = np.asarray(payload["slo_scalars"], dtype=np.int64)
        p = cls(capacity, dt_epoch_ns=dt_epoch_ns,
                ring_depth=int(sc[2]) if ring_depth is None
                else ring_depth)
        p.window_seq = int(sc[0])
        p.windows_closed = int(sc[1])
        for c, e in np.asarray(payload["slo_cepoch"],
                               dtype=np.int64).reshape(-1, 2):
            p.cepoch[int(c)] = int(e)
        for row in np.asarray(payload["slo_contracts"],
                              dtype=np.float64).reshape(-1, 5):
            p.contract_log[(int(row[0]), int(row[1]))] = \
                (float(row[2]), float(row[3]), float(row[4]))
        for c in np.asarray(payload["slo_live"],
                            dtype=np.int64).reshape(-1):
            ce = p.cepoch.get(int(c), 0)
            con = p.contract_log.get((int(c), ce))
            if con is not None:
                p.contracts[int(c)] = con
        for row in np.asarray(payload["slo_ring"],
                              dtype=np.int64).reshape(-1, RING_COLS):
            w = ClosedWindow.from_row(row)
            p.rings.setdefault(w.cid, deque(maxlen=p.ring_depth)) \
                .append(w)
        return p

    @staticmethod
    def empty_leaves() -> dict:
        """Zero-size ``slo_*`` leaves for jobs with the plane off (the
        structure-from-config checkpoint convention)."""
        return {"slo_cepoch": np.zeros((0, 2), dtype=np.int64),
                "slo_contracts": np.zeros((0, 5), dtype=np.float64),
                "slo_live": np.zeros((0,), dtype=np.int64),
                "slo_ring": np.zeros((0, RING_COLS), dtype=np.int64),
                "slo_scalars": np.zeros((3,), dtype=np.int64)}

    # -- convenience constructors --------------------------------------
    def register_from_inv(self, resv_inv, weight_inv,
                          limit_inv) -> None:
        """Register every slot from the engine state's inverse-rate
        arrays (closed-population runs: slot == client id; rates are
        re-derived with the timebase's exact inverse so the contract
        the plane prices against is the device truth, not a parallel
        host copy)."""
        from ..core.timebase import NS_PER_SEC

        def to_rate(inv):
            inv = np.asarray(inv, dtype=np.int64)
            with np.errstate(divide="ignore"):
                return np.where(inv > 0, NS_PER_SEC / np.maximum(
                    inv, 1), 0.0)

        r = to_rate(resv_inv)
        w = to_rate(weight_inv)
        l = to_rate(limit_inv)
        for c in range(len(r)):
            self.register(c, float(r[c]), float(w[c]), float(l[c]))


def load_windows_jsonl(path: str) -> List[dict]:
    """Read a ``SloPlane.export_jsonl`` file back (judged or raw rows;
    malformed lines are skipped with a count in row 0's ``_skipped``
    when any -- the offline tool's fail-soft read)."""
    rows: List[dict] = []
    skipped = 0
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            try:
                obj = json.loads(ln)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if isinstance(obj, dict):
                rows.append(obj)
            else:
                skipped += 1
    if skipped and rows:
        rows[0] = dict(rows[0], _skipped=skipped)
    return rows
