"""On-device QoS telemetry: log2-bucketed histograms + per-client
conformance ledger.

PR-1's metrics vector gives 17 scalar counters; the paper's whole
point is per-client QoS *distributions* -- reservation met, limit
respected, proportional share delivered -- and until now percentiles
only existed as a host-computed sim table after the run.  This module
keeps the distributions IN the data path (RackSched's thesis applied
to our stack): both structures ride the epoch-scan carries next to the
``obs.device`` metrics vector, are accumulated from pure reductions
over arrays the kernels already materialize, and are fetched with the
existing readback -- zero extra round trips, and the decision stream
is bit-identical with telemetry on or off (pinned by
``tests/test_telemetry.py``).

**Histograms** (``int64[NUM_HISTS, NUM_BUCKETS + 1]``): four
families x 48 log2 buckets + one value-sum column (so Prometheus
``_sum``/``_count`` are exact).  Bucket 0 holds values <= 0; bucket i
(1..46) holds ``2^(i-1) <= v < 2^i``; bucket 47 holds ``v >= 2^46``.
Bucketing is exact integer comparison against powers of two -- no
float log2, so the same value lands in the same bucket on every
backend.  Merge is elementwise add (pure counters), so epochs/shards
combine in any order and :func:`hist_mesh_reduce` is a plain ``psum``
-- the same collective path as ``obs.device.metrics_mesh_reduce``.

**Ledger** (``int64[N, LED_COLS]``): per-client served ops,
reservation-phase ops, limit-break serves, reservation-tardiness sum
and max.  Counter columns add, the max column maxes
(:func:`ledger_combine`), so the same fold/merge algebra as the
metrics vector applies.  The ledger is device truth: the sims' and
bench's host-side conformance recomputation cross-checks against it
instead of being the only record.

Observation semantics (documented here because the batch engines emit
sets, not streams -- docs/OBSERVABILITY.md has the full table):

- ``decision_latency_ns``: per committed weight-phase ENTRY,
  ``max(now - effective proportion tag, 0)`` -- how far behind its
  virtual-time tag the serve landed (0 = served at/ahead of tag).
- ``resv_tardiness_ns``: per committed constraint-phase ENTRY,
  ``max(now - reservation tag, 0)`` -- lateness past the reservation
  deadline.  Also folded per client into the ledger's tardiness
  columns.
- ``limit_stall_ns``: per stalled batch/level (committed nothing with
  work queued), time until the earliest queued head becomes eligible:
  ``max(min over queued heads of min(resv, limit) - now, 0)``.
- ``commit_size``: per batch/level, the committed decision count
  (bucket 0 = zero-commit batches).

Granularity: one observation per committed sort unit's entry head
(prefix: every decision; chain: the unit's entry serve -- induced
constraint serves are debt catch-up at the same boundary, not
separately-deadlined decisions), and for the calendar engine one per
client per LEVEL (bucketed ladder level == one minstop batch, so
bucketed-L telemetry equals the composition of L minstop batches
exactly -- the same equality the calendar digest gate pins for
decisions).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# -- histogram families ------------------------------------------------
HIST_DECISION_LATENCY = 0   # weight-phase entry: now - effective prop tag
HIST_RESV_TARDINESS = 1     # constraint-phase entry: now - resv tag
HIST_LIMIT_STALL = 2        # stalled batch: time to next eligibility
HIST_COMMIT_SIZE = 3        # per batch/level committed decisions
NUM_HISTS = 4

HIST_NAMES = ("decision_latency_ns", "resv_tardiness_ns",
              "limit_stall_ns", "commit_size")

NUM_BUCKETS = 48
HIST_SUM_COL = NUM_BUCKETS          # value-sum rides as column 48

# host powers-of-two table (int64); device code folds it in at trace
# time -- a module-level jnp array would leak a tracer when this module
# is imported lazily under a jit trace (the obs.device _HWM_MASK bug)
_POWERS = (np.int64(1) << np.arange(NUM_BUCKETS - 1)).astype(np.int64)

# Prometheus-facing upper bounds: bucket 0 -> le=0; bucket i -> the
# largest value it can hold (2^i - 1); bucket 47 is the clipped open
# bucket and drains as le=+Inf.
BUCKET_BOUNDS = tuple([0.0] + [float((1 << i) - 1)
                               for i in range(1, NUM_BUCKETS - 1)]
                      + [float("inf")])


def hist_zero() -> jnp.ndarray:
    return jnp.zeros((NUM_HISTS, NUM_BUCKETS + 1), dtype=jnp.int64)


def bucket_index(v: jnp.ndarray) -> jnp.ndarray:
    """Exact log2 bucket of int64 values (elementwise): 0 for v <= 0,
    else ``floor(log2(v)) + 1`` clipped to 47.  Computed as a dense
    count of passed power-of-two thresholds -- deterministic on every
    backend, no float rounding at bucket boundaries."""
    v = jnp.asarray(v, dtype=jnp.int64)
    powers = jnp.asarray(_POWERS)
    return jnp.sum(v[..., None] >= powers, axis=-1).astype(jnp.int32)


def hist_observe(h: jnp.ndarray, family: int, values, mask
                 ) -> jnp.ndarray:
    """Fold a dense masked batch of observations into one family:
    one-hot bucket compares + a sum reduction (the radix-histogram
    idiom -- scatters serialize on TPU).  Negative values clamp to
    bucket 0 and contribute 0 to the sum."""
    v = jnp.maximum(jnp.asarray(values, dtype=jnp.int64), 0)
    mask = jnp.asarray(mask, dtype=bool)
    idx = bucket_index(v)
    onehot = (idx[:, None]
              == jnp.arange(NUM_BUCKETS, dtype=jnp.int32)[None, :]) \
        & mask[:, None]
    counts = jnp.sum(onehot, axis=0).astype(jnp.int64)
    total = jnp.sum(jnp.where(mask, v, 0))
    row = jnp.concatenate([counts, total[None]])
    return h.at[family].add(row)


def hist_observe_scalar(h: jnp.ndarray, family: int, value, weight
                        ) -> jnp.ndarray:
    """One (possibly weight-0) scalar observation -- per-batch values
    like the commit size or a stall duration."""
    v = jnp.maximum(jnp.asarray(value, dtype=jnp.int64), 0)
    w = jnp.asarray(weight, dtype=jnp.int64)
    idx = bucket_index(v)
    row = jnp.where(jnp.arange(NUM_BUCKETS, dtype=jnp.int32) == idx,
                    w, jnp.int64(0))
    row = jnp.concatenate([row, (v * w)[None]])
    return h.at[family].add(row)


def hist_combine(a, b):
    """Merge two histogram blocks (pure counters: add).  Associative
    and commutative -- epochs/shards merge in any order."""
    return a + b


def hist_fold(h, delta, live):
    """Fold a batch delta gated on a scalar liveness flag (the tag32
    dead-batch gate: a tripped batch's telemetry must not land)."""
    return h + jnp.where(live, delta, jnp.zeros_like(delta))


def hist_mesh_reduce(h: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """In-graph mesh merge: every cell is a counter, so the collective
    is one ``psum`` -- the histogram analog of
    ``obs.device.metrics_mesh_reduce``."""
    from jax import lax

    return lax.psum(h, axis_name)


def hist_dict(h) -> dict:
    """Name a fetched histogram block (host side): per family the
    bucket counts, count, and sum."""
    a = np.asarray(h, dtype=np.int64)
    out = {}
    for i, name in enumerate(HIST_NAMES):
        counts = a[i, :NUM_BUCKETS]
        out[name] = {"buckets": counts.tolist(),
                     "count": int(counts.sum()),
                     "sum": int(a[i, HIST_SUM_COL])}
    return out


def hist_percentile(h, family: int, q: float) -> float:
    """Host-side percentile estimate from the log2 buckets: the UPPER
    bound of the bucket where the cumulative count crosses ``q`` --
    log2-quantized, so a reported p99 is within one octave of the true
    value (and never under-reports).  Returns 0.0 on an empty family."""
    a = np.asarray(h, dtype=np.int64)
    counts = a[family, :NUM_BUCKETS]
    total = int(counts.sum())
    if total == 0:
        return 0.0
    target = q * total
    cum = np.cumsum(counts)
    i = int(np.searchsorted(cum, target, side="left"))
    i = min(i, NUM_BUCKETS - 1)
    if i == 0:
        return 0.0
    # open top bucket reports its nominal next-octave bound
    return float((1 << (i + 1)) - 1) if i == NUM_BUCKETS - 1 \
        else float((1 << i) - 1)


def hist_mean(h, family: int) -> float:
    a = np.asarray(h, dtype=np.int64)
    n = int(a[family, :NUM_BUCKETS].sum())
    return float(a[family, HIST_SUM_COL]) / n if n else 0.0


def publish_hists(registry, h, prefix: str = "dmclock",
                  labels=None) -> None:
    """Expose a fetched histogram block as proper Prometheus histogram
    families (``_bucket``/``_sum``/``_count``) through the host
    registry: get-or-create a fixed-bucket histogram per family at the
    log2 bounds and overwrite its counts (the device block is itself
    cumulative per run, so set-not-add is the correct drain)."""
    a = np.asarray(h, dtype=np.int64)
    for i, name in enumerate(HIST_NAMES):
        m = registry.histogram(
            f"{prefix}_{name}",
            "on-device log2-bucketed QoS histogram "
            "(docs/OBSERVABILITY.md)",
            labels=labels, buckets=BUCKET_BOUNDS)
        m.set_counts(a[i, :NUM_BUCKETS].tolist(),
                     float(a[i, HIST_SUM_COL]))


# ----------------------------------------------------------------------
# per-client conformance ledger
# ----------------------------------------------------------------------

LED_OPS = 0         # decisions served
LED_RESV_OPS = 1    # constraint-phase decisions
LED_LIMIT_BREAKS = 2  # AtLimit::Allow limit-break entries
LED_TARD_SUM = 3    # reservation tardiness sum, ns (entry-head obs)
LED_TARD_MAX = 4    # reservation tardiness max, ns (merge: max)
LED_COLS = 5

LEDGER_COL_NAMES = ("ops", "resv_ops", "limit_breaks",
                    "tardiness_sum_ns", "tardiness_max_ns")

# max-merged columns, as a host constant (same lazy-import-under-trace
# rule as the histogram powers table)
_LED_MAX_MASK = np.zeros((LED_COLS,), dtype=bool)
_LED_MAX_MASK[LED_TARD_MAX] = True


def ledger_zero(n: int) -> jnp.ndarray:
    return jnp.zeros((n, LED_COLS), dtype=jnp.int64)


def ledger_combine(a, b):
    """Merge two ledgers over the SAME client set (counter columns
    add, the tardiness max maxes) -- associative and commutative, the
    metrics-vector algebra applied per client."""
    return jnp.where(_LED_MAX_MASK, jnp.maximum(a, b), a + b)


def ledger_fold(led, delta, live):
    """Fold a batch delta gated on liveness (all delta entries are
    >= 0, so a zeroed dead-batch delta is the merge identity)."""
    return ledger_combine(led,
                          jnp.where(live, delta, jnp.zeros_like(delta)))


def ledger_mesh_reduce(led: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """In-graph mesh merge for REPLICATED client sets (every shard
    holds rows for the same [N] clients, e.g. per-server ledgers in a
    cluster): counter columns ``psum``, the max column ``pmax``.
    Sharded-client layouts concatenate instead -- do not reduce
    disjoint client rows."""
    from jax import lax

    return jnp.where(_LED_MAX_MASK, lax.pmax(led, axis_name),
                     lax.psum(led, axis_name))


def ledger_combine_np(acc, *ledgers):
    """Host-side mirror of :func:`ledger_combine` (numpy); derives the
    max column from the same mask so the merges cannot diverge."""
    acc = np.asarray(acc, dtype=np.int64)
    for v in ledgers:
        v = np.asarray(v)
        acc = np.where(_LED_MAX_MASK, np.maximum(acc, v), acc + v)
    return acc


def ledger_totals(led) -> dict:
    """Column totals of a fetched ledger (host side): counters sum,
    tardiness max maxes -- the scalar view bench lines carry."""
    a = np.asarray(led, dtype=np.int64)
    out = {}
    for i, name in enumerate(LEDGER_COL_NAMES):
        out[name] = int(a[:, i].max()) if _LED_MAX_MASK[i] \
            else int(a[:, i].sum())
    return out


def ledger_rows(led, limit: int = None) -> list:
    """Per-client dict rows of a fetched ledger (host side), including
    the derived mean tardiness."""
    a = np.asarray(led, dtype=np.int64)
    n = a.shape[0] if limit is None else min(limit, a.shape[0])
    rows = []
    for c in range(n):
        r = {"client": c}
        r.update({name: int(a[c, i])
                  for i, name in enumerate(LEDGER_COL_NAMES)})
        r["tardiness_mean_ns"] = (a[c, LED_TARD_SUM]
                                  / max(int(a[c, LED_RESV_OPS]), 1))
        rows.append(r)
    return rows


def publish_ledger(registry, led, prefix: str = "dmclock_ledger",
                   labels=None) -> None:
    """Fold a fetched ledger's column totals into a host registry as
    gauges (per-client series would explode the scrape; the full table
    drains through the JSON paths instead)."""
    for name, value in ledger_totals(led).items():
        registry.gauge(f"{prefix}_{name}",
                       "device conformance-ledger column total "
                       "(docs/OBSERVABILITY.md)",
                       labels=labels).set(value)
