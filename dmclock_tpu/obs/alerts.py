"""Burn-rate SLO alerting over the windowed conformance plane.

SRE-style multiwindow burn-rate rules (fast window to catch the burn,
slow window to suppress blips) evaluated at every window roll of an
:class:`~.slo.SloPlane`:

- ``resv_miss``: a client with a reservation floor is backlogged (or
  serving tardily) and delivered below its floor -- the mClock
  contract's hard half is being missed;
- ``limit_break``: the AtLimit::Allow break rate exceeds its budget
  (or delivered rate exceeds a configured limit ceiling);
- ``share_skew``: delivered cost share deviates from the weight
  entitlement (among clients with demand) past tolerance -- the
  proportional half drifting.

A rule fires **once per episode** per ``(client, contract_epoch,
rule)``: the warning is emitted on the rising edge (fast AND slow
windows in violation) and re-arms on a clean fast window -- the
watchdog's once-per-episode damping applied to QoS.  Episodes are
per TENANCY/VERSION: an evicted-and-re-registered client (or a live
QoS update) opens a new contract epoch whose burn is a new episode.  Warnings are structured:
one JSON line (prefix ``# slo:``), a ``dmclock_slo_*`` registry bump,
optionally routed through a PR-7 :class:`~.watchdog.Watchdog` (one
warning stream for a whole run), and kept in :attr:`SloEvaluator.fired`
for tests.  Evaluator state encodes into the ``slo_alert_*``
checkpoint leaves so a SIGKILLed run resumes mid-episode without
double-firing (the exactly-once-per-episode contract survives crashes
the same way the decision digest does).
"""

from __future__ import annotations

import json
import sys
from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from .slo import ClosedWindow, SloPlane

RULES = ("resv_miss", "limit_break", "share_skew")

# most-recent per-window tardiness observations kept for the p99
# scalar (bounds host memory AND the slo_alert_tard checkpoint leaf)
TARD_P99_WINDOW = 4096


def _stderr_log(line: str) -> None:
    print(line, file=sys.stderr)


class SloEvaluator:
    """Evaluate burn-rate rules at every roll.

    The FAST horizon is the just-closed roll; the SLOW horizon is the
    last ``slow_windows`` judged rolls (clamped to the plane's ring
    depth: the slow horizon must be reconstructible from the ring on
    a checkpoint resume, or a resumed run could fire episodes the
    uninterrupted run suppresses).  ``slow_frac`` is the fraction of
    slow-horizon windows that must be in violation for the slow
    condition to hold.  Thresholds: ``limit_break_budget`` (allowed
    limit-break fraction of delivered ops), ``share_tol`` (relative
    share error).  The reservation rule's per-window predicate is the
    plane's ``resv_miss`` judgment (floor deficit + backlog)."""

    def __init__(self, plane: SloPlane, *,
                 slow_windows: int = 4,
                 slow_frac: float = 0.5,
                 limit_break_budget: float = 0.05,
                 share_tol: float = 0.5,
                 registry=None, watchdog=None,
                 log: Callable[[str], None] = _stderr_log):
        self.plane = plane
        self.slow_windows = min(max(int(slow_windows), 1),
                                plane.ring_depth)
        self.slow_frac = float(slow_frac)
        self.limit_break_budget = float(limit_break_budget)
        self.share_tol = float(share_tol)
        self._log = log
        self._watchdog = watchdog
        self.fired: List[dict] = []
        self.fired_counts: Dict[str, int] = {r: 0 for r in RULES}
        self.violations_total = 0
        # burn-episode duration accounting: a roll with >=1 rising
        # edge is one BURNING window, and its epoch span adds to the
        # total burn duration -- the bench controller A/B's
        # "how long did the run spend burning" observable.  Rides the
        # checkpoint scalars, so the totals are crash-equivalent.
        self.burn_windows = 0
        self.burn_epochs = 0
        self.worst_share_err = 0.0
        # per-window mean reservation tardiness, for the p99 the bench
        # block reports.  BOUNDED: a long run accumulates one entry
        # per client-window with resv activity, and the whole thing
        # rides every rotation checkpoint -- so keep the most recent
        # window of observations (the p99 is a recent-tail verdict,
        # like the watchdog's windows, not an all-time archive)
        self._tard_means: deque = deque(maxlen=TARD_P99_WINDOW)
        # active episodes keyed by (cid, contract_epoch, rule): the
        # once-per-episode damping is per TENANCY/VERSION -- an
        # evicted-and-re-registered client (or a live QoS update)
        # opens a new contract epoch, whose burn is a new episode
        self.active: Set[Tuple[int, int, str]] = set()
        # bounded judged-roll history for the slow horizon: (seq,
        # {cid: judged row}) for the last slow_windows rolls.  Derived
        # state -- rebuilt from the plane's ring on a checkpoint
        # resume (deterministic, so episode firing replays
        # identically).
        self._judged: deque = deque(maxlen=self.slow_windows)
        self._counter = None
        self._worst_gauge = None
        self._registry = None
        if registry is not None:
            self.attach_registry(registry)

    # -- registry families ---------------------------------------------
    def attach_registry(self, registry) -> None:
        self._counter = registry.counter(
            "dmclock_slo_violations_total",
            "burn-rate SLO episodes fired (resv_miss / limit_break / "
            "share_skew; once per episode -- docs/OBSERVABILITY.md "
            "SLO plane)")
        for rule in RULES:
            registry.counter(
                f"dmclock_slo_{rule}_total",
                f"{rule} burn-rate episodes fired")
        registry.gauge(
            "dmclock_slo_windows_closed_total",
            "closed conformance windows across the run") \
            .set_function(lambda: float(self.plane.windows_closed))
        self._worst_gauge = registry.gauge(
            "dmclock_slo_worst_window_share_err",
            "worst per-window relative share error observed "
            "(delivered cost share vs weight entitlement)")
        self._registry = registry

    # -- per-window predicates -----------------------------------------
    def _violates(self, rule: str, row: dict) -> bool:
        if rule == "resv_miss":
            return bool(row["resv_miss"])
        if rule == "limit_break":
            if row["limit_excess"] > 0:
                return True
            return row["ops"] > 0 and \
                row["lb_ops"] / row["ops"] > self.limit_break_budget
        if rule == "share_skew":
            return row["entitled_share"] > 0 and \
                abs(row["share_err"]) > self.share_tol
        raise ValueError(f"unknown SLO rule {rule!r}")

    def _slow_ok(self, rule: str, cid: int) -> bool:
        """Slow-horizon condition: over the client's windows in the
        last ``slow_windows`` judged rolls, at least ``slow_frac`` are
        in violation.  Each roll was judged against its own contract
        versions, so a mid-run update never smears into the slow
        horizon."""
        mine = [by_cid[cid] for _seq, by_cid in self._judged
                if cid in by_cid]
        if len(mine) < self.slow_windows:
            # ramp-up suppression: with fewer judged windows than the
            # slow horizon, the gate would degenerate to the fast
            # window and a single first-window blip would fire -- the
            # exact flap the two-horizon design exists to prevent
            return False
        bad = sum(1 for r in mine if self._violates(rule, r))
        return bad >= max(1, int(np.ceil(self.slow_frac * len(mine))))

    def _rebuild_judged(self) -> None:
        """Re-derive the judged-roll cache from the plane's ring (the
        checkpoint-resume path): group ring windows by roll seq, keep
        the newest ``slow_windows`` rolls, judge each once."""
        grouped: Dict[int, List[ClosedWindow]] = {}
        for w in self.plane.ring_rows():
            grouped.setdefault(w.seq, []).append(w)
        self._judged.clear()
        for seq in sorted(grouped)[-self.slow_windows:]:
            rows = self.plane.conformance_rows(grouped[seq])
            self._judged.append((seq, {r["client"]: r for r in rows}))

    # -- the roll hook -------------------------------------------------
    def observe_roll(self, closed: List[ClosedWindow]) -> List[dict]:
        """Judge one roll's closed windows; returns the warnings fired
        (rising edges only).  Deterministic: the same window stream
        fires the same episodes, so the counts survive the
        crash-equivalence gate."""
        # drop episodes of DEAD contract versions (evicted tenancies,
        # superseded QoS updates): their keys can never match again
        # and would otherwise accumulate for the run's lifetime
        self.active = {k for k in self.active
                       if self.plane.cepoch.get(k[0]) == k[1]}
        rows = self.plane.conformance_rows(closed)
        if closed:
            # the newest roll joins the slow horizon before judgment:
            # the fast window is this roll, the slow condition reads
            # the last slow_windows rolls INCLUDING it
            self._judged.append(
                (closed[0].seq, {r["client"]: r for r in rows}))
        out: List[dict] = []
        for row in rows:
            cid = row["client"]
            err = abs(row["share_err"]) if row["entitled_share"] > 0 \
                else 0.0
            if err > self.worst_share_err:
                self.worst_share_err = err
            if row["resv_ops"] > 0:
                self._tard_means.append(row["tardiness_mean_ns"])
            for rule in RULES:
                key = (cid, row["contract_epoch"], rule)
                fast_bad = self._violates(rule, row)
                if not fast_bad:
                    self.active.discard(key)   # clean fast window
                    continue                    # re-arms the episode
                if key in self.active:
                    continue                    # once per episode
                if not self._slow_ok(rule, cid):
                    continue                    # blip, not a burn
                self.active.add(key)
                w = {"kind": "slo_" + rule, "client": cid,
                     "contract_epoch": row["contract_epoch"],
                     "window": [row["e0"], row["e1"]],
                     "rate": round(row["rate"], 3),
                     "reservation": row["reservation"],
                     "share": round(row["share"], 4),
                     "entitled_share": round(row["entitled_share"], 4),
                     "share_err": round(row["share_err"], 4),
                     "limit_excess": round(row["limit_excess"], 3)}
                out.append(w)
                self.fired.append(w)
                self.fired_counts[rule] += 1
                self.violations_total += 1
        if out:
            # every row of one roll closes the same [e0, e1) span
            # (windows roll on the checkpoint grid), so the roll
            # contributes its span once no matter how many clients
            # or rules fired inside it
            self.burn_windows += 1
            self.burn_epochs += int(out[0]["window"][1]
                                    - out[0]["window"][0])
        for w in out:
            if self._watchdog is not None:
                # route through the PR-7 watchdog: one structured
                # warning stream (+ its counter) for the whole run
                self._watchdog.external_warning(w)
            else:
                self._log("# slo: " +
                          json.dumps(w, separators=(",", ":")))
            if self._counter is not None:
                self._counter.inc()
                self._registry.counter(
                    "dmclock_slo_" + w["kind"][4:] + "_total").inc()
        if self._worst_gauge is not None:
            self._worst_gauge.set(float(self.worst_share_err))
        return out

    # -- reports -------------------------------------------------------
    def window_tardiness_p99_ns(self) -> float:
        """p99 over closed windows of the per-window mean reservation
        tardiness -- the slo block's tail-QoS scalar (0.0 with no
        reservation activity)."""
        if not self._tard_means:
            return 0.0
        return float(np.percentile(np.asarray(self._tard_means), 99))

    def summary(self) -> dict:
        return {"violations_total": int(self.violations_total),
                **{f"{r}_episodes": int(self.fired_counts[r])
                   for r in RULES},
                "burn_windows": int(self.burn_windows),
                "burn_epochs": int(self.burn_epochs),
                "worst_window_share_err":
                    round(float(self.worst_share_err), 6),
                "window_tardiness_p99_ns":
                    round(self.window_tardiness_p99_ns(), 1),
                "active_episodes": len(self.active),
                **self.plane.summary()}

    # -- checkpoint round-trip (rides the slo_* leaves) ----------------
    def encode(self) -> dict:
        act = np.asarray(
            sorted((cid, ce, RULES.index(rule))
                   for cid, ce, rule in self.active),
            dtype=np.int64).reshape(len(self.active), 3)
        return {"slo_alert_scalars": np.asarray(
                    [self.violations_total]
                    + [self.fired_counts[r] for r in RULES]
                    + [self.burn_windows, self.burn_epochs],
                    dtype=np.int64),
                "slo_alert_active": act,
                "slo_alert_worst": np.float64(self.worst_share_err),
                "slo_alert_tard": np.asarray(self._tard_means,
                                             dtype=np.float64)}

    def load(self, payload: dict) -> None:
        sc = np.asarray(payload["slo_alert_scalars"], dtype=np.int64)
        self.violations_total = int(sc[0])
        for i, r in enumerate(RULES):
            self.fired_counts[r] = int(sc[1 + i])
        if len(sc) > 1 + len(RULES):   # pre-burn-scalar checkpoints
            self.burn_windows = int(sc[1 + len(RULES)])
            self.burn_epochs = int(sc[2 + len(RULES)])
        self.active = {
            (int(c), int(ce), RULES[int(i)])
            for c, ce, i in np.asarray(payload["slo_alert_active"],
                                       dtype=np.int64).reshape(-1, 3)}
        self.worst_share_err = float(payload["slo_alert_worst"])
        self._tard_means = deque(
            np.asarray(payload["slo_alert_tard"], dtype=np.float64),
            maxlen=TARD_P99_WINDOW)
        self._rebuild_judged()

    @staticmethod
    def empty_leaves() -> dict:
        return {"slo_alert_scalars": np.zeros(3 + len(RULES),
                                              dtype=np.int64),
                "slo_alert_active": np.zeros((0, 3), dtype=np.int64),
                "slo_alert_worst": np.float64(0.0),
                "slo_alert_tard": np.zeros((0,), dtype=np.float64)}


# ----------------------------------------------------------------------
# HTTP surface: GET /slo on the scrape/admin endpoint
# ----------------------------------------------------------------------

class SloAPI:
    """``handler(method, path, body)`` for
    ``MetricsHTTPServer.mount("/slo", ...)``: the live SLO summary +
    recent warnings, next to the Prometheus families."""

    def __init__(self, evaluator: SloEvaluator):
        self.evaluator = evaluator

    def handler(self, method: str, path: str, body: bytes):
        if method != "GET":
            return (405, "application/json",
                    json.dumps({"error": f"{method} not allowed"})
                    .encode())
        out = dict(self.evaluator.summary())
        out["recent_warnings"] = self.evaluator.fired[-16:]
        return (200, "application/json", json.dumps(out).encode())


def mount_slo_api(server, evaluator: SloEvaluator
                  ) -> Optional[SloAPI]:
    """Mount ``GET /slo`` on a (possibly None, fail-soft) scrape
    endpoint and register the ``dmclock_slo_*`` families into its
    registry.  Idempotent across rebinds only via re-mounting (the
    ``_ScrapeCtl.on_bind`` convention)."""
    if server is None:
        return None
    api = SloAPI(evaluator)
    server.mount("/slo", api.handler)
    evaluator.attach_registry(server.registry)
    return api
