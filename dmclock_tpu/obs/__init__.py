"""Scheduling observability: metrics registry, device counters, traces.

Three tiers, cheapest first:

1. **On-device counters** (``obs.device``): a small int64 metrics
   vector accumulated inside the kernels that are already running
   (``engine.fastpath`` epoch scans, ``engine.kernels.engine_run``) and
   drained with the existing decision fetch -- zero extra device round
   trips, and gated so the decision stream is bit-identical with
   metrics on or off (pinned by ``tests/test_obs.py``).
2. **Host metrics registry** (``obs.registry``): counters / gauges /
   histograms / timer wrappers with Prometheus text exposition and a
   JSON snapshot.  The sim harness, the host scheduler queues, and the
   distributed tracker register their hot-path stats into it.
3. **Decision trace + QoS conformance** (``obs.trace``,
   ``sim.harness.SimReport.conformance``): a bounded JSONL trace of
   scheduling decisions and an end-of-run per-client conformance table
   (delivered rate vs reservation/weight/limit).

Plus the device telemetry plane (``obs.histograms``, ``obs.flight``):
log2-bucketed latency/tardiness/stall/commit-size histograms and a
per-client conformance ledger accumulated inside the epoch scans, and
an HBM flight recorder of the last R commit records drained only at
epoch/checkpoint boundaries -- distributions in the data path, not the
control path.

See ``docs/OBSERVABILITY.md`` for metric names and schemas.
"""

from .registry import (Counter, Gauge, Histogram, MetricsHTTPServer,
                       MetricsRegistry, TimerMetric, default_registry,
                       start_http_server)
from .trace import DecisionTrace, validate_trace_file
from . import device, flight, histograms

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "TimerMetric",
    "default_registry", "MetricsHTTPServer", "start_http_server",
    "DecisionTrace", "validate_trace_file",
    "device", "flight", "histograms",
]
