"""Scheduling observability: metrics registry, device counters, traces.

Three tiers, cheapest first:

1. **On-device counters** (``obs.device``): a small int64 metrics
   vector accumulated inside the kernels that are already running
   (``engine.fastpath`` epoch scans, ``engine.kernels.engine_run``) and
   drained with the existing decision fetch -- zero extra device round
   trips, and gated so the decision stream is bit-identical with
   metrics on or off (pinned by ``tests/test_obs.py``).
2. **Host metrics registry** (``obs.registry``): counters / gauges /
   histograms / timer wrappers with Prometheus text exposition and a
   JSON snapshot.  The sim harness, the host scheduler queues, and the
   distributed tracker register their hot-path stats into it.
3. **Decision trace + QoS conformance** (``obs.trace``,
   ``sim.harness.SimReport.conformance``): a bounded JSONL trace of
   scheduling decisions and an end-of-run per-client conformance table
   (delivered rate vs reservation/weight/limit).

Plus the device telemetry plane (``obs.histograms``, ``obs.flight``):
log2-bucketed latency/tardiness/stall/commit-size histograms and a
per-client conformance ledger accumulated inside the epoch scans, and
an HBM flight recorder of the last R commit records drained only at
epoch/checkpoint boundaries -- distributions in the data path, not the
control path.

And the time-domain tracing plane (``obs.spans``,
``obs.trace_export``, ``obs.watchdog``): a thread-safe ns-resolution
host span tracer (nested spans, fixed category taxonomy, bounded
ring), Chrome trace-event / Perfetto export so any run produces a
``chrome://tracing``-loadable timeline, and a steady-state watchdog
that warns on launch-cadence stalls and dispatch-share breaches.
Spans are host-side only, never in-graph -- decisions are
bit-identical with tracing on or off.

And the capacity plane (``obs.compile_plane``, ``obs.capacity``): an
instrumented jit-cache wrapper adopted by every module-level jit cache
(per-entry lower+compile wall, retraces with the arg-signature diff
that caused them, ``cost_analysis`` flops/bytes, ``memory_analysis``
HBM breakdown -- exported as ``dmclock_compile_*`` families and as
``compile``-category spans), a static HBM footprint ledger over the
live state pytrees with a ``plan_capacity()`` inverse (max clients
per chip for a budget and knob setting), and a roofline attributor
classifying workloads compute-/memory-/dispatch-bound.

See ``docs/OBSERVABILITY.md`` for metric names and schemas.
"""

from .registry import (Counter, Gauge, Histogram, MetricsHTTPServer,
                       MetricsRegistry, TimerMetric, default_registry,
                       publish_span_gauges, start_http_server)
from .trace import DecisionTrace, validate_trace_file
from .spans import SpanTracer
from .trace_export import export_chrome_trace, validate_chrome_trace
from .watchdog import Watchdog
from .slo import SloPlane
from .alerts import SloEvaluator, mount_slo_api
from .compile_plane import (CompilePlane, instrumented_jit,
                            publish_compile_metrics)
from .compile_plane import plane as compile_plane_singleton
from . import alerts, capacity, compile_plane, device, flight, \
    histograms, provenance, slo, spans, trace_export

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "TimerMetric",
    "default_registry", "MetricsHTTPServer", "start_http_server",
    "publish_span_gauges",
    "DecisionTrace", "validate_trace_file",
    "SpanTracer", "export_chrome_trace", "validate_chrome_trace",
    "Watchdog", "SloPlane", "SloEvaluator", "mount_slo_api",
    "CompilePlane", "instrumented_jit", "publish_compile_metrics",
    "compile_plane_singleton",
    "alerts", "capacity", "compile_plane", "device", "flight",
    "histograms", "provenance", "slo", "spans", "trace_export",
]
