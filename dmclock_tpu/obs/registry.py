"""Host-side metrics registry.

A minimal, dependency-free Prometheus-style registry: counters, gauges
(optionally callback-backed so hot paths pay nothing), fixed-bucket
histograms, and summaries wrapping the repo's ``utils.profile`` timers
(the reference's ``support/src/profile.h`` accumulators).  Two
drains: ``prometheus()`` (text exposition format 0.0.4) and
``snapshot()`` (JSON-able dict, what ``bench.py`` / ``dmc_sim
--metrics-out`` write).

Durations are exposed in nanoseconds with an explicit ``_ns`` unit in
the metric name -- the whole repo's tag algebra is int64 ns, and
converting to float seconds at the edge would be the only lossy step.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.profile import ProfileCombiner, _ProfileBase

_DEFAULT_BUCKETS = (1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9, float("inf"))


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


def _label_str(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Metric:
    """Common name/help/labels plumbing."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help_text
        self.labels = dict(labels or {})

    def sample_rows(self) -> List[Tuple[str, Dict[str, str], float]]:
        """(suffix, extra labels, value) rows for exposition."""
        raise NotImplementedError

    def value_obj(self):
        """JSON-able value for ``snapshot()``."""
        raise NotImplementedError


class Counter(_Metric):
    """Monotonic counter."""

    kind = "counter"

    def __init__(self, name, help_text="", labels=None):
        super().__init__(name, help_text, labels)
        self._value = 0

    def inc(self, n: int = 1) -> None:
        assert n >= 0, "counters only go up"
        self._value += n

    @property
    def value(self):
        return self._value

    def sample_rows(self):
        return [("", {}, self._value)]

    def value_obj(self):
        return self._value


class Gauge(_Metric):
    """Point-in-time value; ``set_function`` makes it callback-backed
    (read lazily at drain time -- zero hot-path cost)."""

    kind = "gauge"

    def __init__(self, name, help_text="", labels=None):
        super().__init__(name, help_text, labels)
        self._value = 0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, v) -> None:
        self._value = v

    def inc(self, n=1) -> None:
        self._value += n

    def set_function(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self):
        return self._fn() if self._fn is not None else self._value

    def sample_rows(self):
        return [("", {}, self.value)]

    def value_obj(self):
        return self.value


class Histogram(_Metric):
    """Fixed upper-bound buckets (cumulative, Prometheus-style)."""

    kind = "histogram"

    def __init__(self, name, help_text="", labels=None,
                 buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_text, labels)
        b = sorted(float(x) for x in buckets)
        if not b or b[-1] != float("inf"):
            b.append(float("inf"))
        self.buckets = tuple(b)
        self.counts = [0] * len(self.buckets)
        self.sum = 0.0
        self.count = 0

    def observe(self, v) -> None:
        self.count += 1
        self.sum += v
        for i, ub in enumerate(self.buckets):
            if v <= ub:
                self.counts[i] += 1
                break

    def set_counts(self, counts, sum_value: float) -> None:
        """Overwrite the per-bucket counts wholesale -- the drain for
        device-accumulated histograms (``obs.histograms``), whose
        blocks are already cumulative per run: re-observing them would
        double-count, so the publisher SETS."""
        assert len(counts) == len(self.buckets), \
            f"{len(counts)} counts for {len(self.buckets)} buckets"
        self.counts = [int(c) for c in counts]
        self.sum = float(sum_value)
        self.count = sum(self.counts)

    def sample_rows(self):
        rows = []
        cum = 0
        for ub, c in zip(self.buckets, self.counts):
            cum += c
            rows.append(("_bucket", {"le": _fmt_value(ub)}, cum))
        rows.append(("_sum", {}, self.sum))
        rows.append(("_count", {}, self.count))
        return rows

    def value_obj(self):
        return {"buckets": {_fmt_value(ub): c for ub, c
                            in zip(self.buckets, self.counts)},
                "sum": self.sum, "count": self.count}


class TimerMetric(_Metric):
    """Summary view over one or more ``utils.profile`` accumulators
    (``ProfileTimer`` / ``ProfileCombiner``).  Multiple sources are
    merged at drain time with ``ProfileCombiner`` -- the reference's
    multi-thread merge semantics (profile.h:100-120) -- so registering
    each server's timer under one name yields the combined stats."""

    kind = "summary"

    def __init__(self, name, help_text="", labels=None):
        super().__init__(name, help_text, labels)
        self._sources: List[_ProfileBase] = []

    def add_source(self, timer: _ProfileBase) -> None:
        self._sources.append(timer)

    def _combined(self) -> ProfileCombiner:
        comb = ProfileCombiner()
        for t in self._sources:
            comb.combine(t)
        return comb

    def _reentries(self) -> int:
        """Reentrant start() calls across the sources (ProfileTimer
        counts them when a running timer is restarted -- the abandoned
        in-flight interval deflates count/sum, so the stat must be
        VISIBLE at the drain or the discard stays silent)."""
        return sum(getattr(t, "reentries", 0) for t in self._sources)

    def sample_rows(self):
        c = self._combined()
        return [("_count", {}, c.count),
                ("_sum", {}, c.sum_ns),
                ("_min", {}, c.low_ns or 0),
                ("_max", {}, c.high_ns or 0),
                ("_mean", {}, c.mean_ns()),
                ("_stddev", {}, c.std_dev_ns()),
                ("_reentries", {}, self._reentries())]

    def value_obj(self):
        c = self._combined()
        return {"count": c.count, "sum_ns": c.sum_ns,
                "min_ns": c.low_ns or 0, "max_ns": c.high_ns or 0,
                "mean_ns": c.mean_ns(), "stddev_ns": c.std_dev_ns(),
                "reentries": self._reentries()}


class MetricsRegistry:
    """Get-or-create registry keyed by (name, labels).

    All factories are idempotent: asking for an existing
    (name, labels) pair returns the live instance, so independent
    modules can share counters without plumbing objects around.
    """

    def __init__(self):
        self._mtx = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple], _Metric] = {}

    def _get_or_create(self, cls, name, help_text, labels, **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._mtx:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help_text, labels, **kw)
                self._metrics[key] = m
            else:
                assert isinstance(m, cls), \
                    f"{name} already registered as {m.kind}"
            return m

    def counter(self, name, help_text="", labels=None) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name, help_text="", labels=None) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(self, name, help_text="", labels=None,
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labels,
                                   buckets=buckets)

    def timer(self, name, help_text="", labels=None,
              source: Optional[_ProfileBase] = None) -> TimerMetric:
        t = self._get_or_create(TimerMetric, name, help_text, labels)
        if source is not None and source not in t._sources:
            t.add_source(source)
        return t

    # -- drains --------------------------------------------------------
    def metrics(self) -> List[_Metric]:
        with self._mtx:
            return list(self._metrics.values())

    def prometheus(self) -> str:
        """Text exposition format 0.0.4.  Label variants of one metric
        name register independently (possibly interleaved with other
        registrations), but a metric family must be one contiguous
        group in the output -- strict parsers reject interleaving -- so
        the drain groups by name first."""
        by_name: Dict[str, List[_Metric]] = {}
        for m in self.metrics():
            by_name.setdefault(m.name, []).append(m)
        lines = []
        for name, group in by_name.items():
            help_text = next((m.help for m in group if m.help), "")
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {group[0].kind}")
            for m in group:
                for suffix, extra, value in m.sample_rows():
                    labels = dict(m.labels)
                    labels.update(extra)
                    lines.append(f"{name}{suffix}{_label_str(labels)} "
                                 f"{_fmt_value(float(value))}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-able {name: [{labels, kind, value}, ...]}."""
        out: Dict[str, list] = {}
        for m in self.metrics():
            out.setdefault(m.name, []).append(
                {"labels": m.labels, "kind": m.kind,
                 "value": m.value_obj()})
        return out

    def snapshot_json(self, **json_kw) -> str:
        return json.dumps(self.snapshot(), **json_kw)


def publish_span_gauges(registry: MetricsRegistry, summary: dict,
                        labels: Optional[Dict[str, str]] = None
                        ) -> None:
    """Expose span-derived dispatch-tax gauges from a bench/sim span
    summary (the dict ``bench.py --spans`` computes from
    ``obs.spans.SpanTracer`` category deltas over its timed chains) so
    the Prometheus endpoint serves them alongside the histogram
    families:

    - ``dmclock_dispatch_ms_per_launch`` -- host dispatch self-time
      per device launch (the ~17 ms tunnel tax, PROFILE.md 17-18);
    - ``dmclock_device_ms_per_launch`` -- device-side time per launch;
    - ``dmclock_host_overhead_frac`` -- host-side (non-device) share
      of the measured wall time.
    """
    rows = (
        ("dmclock_dispatch_ms_per_launch", "dispatch_ms_per_launch",
         "host dispatch self-time per device launch over the timed "
         "region (span tracer; docs/OBSERVABILITY.md tracing plane)"),
        ("dmclock_device_ms_per_launch", "device_ms_per_launch",
         "device-side time per launch over the timed region (span "
         "tracer)"),
        ("dmclock_host_overhead_frac", "host_overhead_frac",
         "host-side (dispatch + prep + fetch + drain) share of the "
         "measured wall time (span tracer)"),
    )
    for name, key, help_text in rows:
        if key in summary:
            registry.gauge(name, help_text,
                           labels=labels).set(float(summary[key]))


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """Process-wide registry (modules that have no natural owner --
    e.g. the bench script -- register here)."""
    return _DEFAULT


# ----------------------------------------------------------------------
# scrape endpoint (stdlib http.server; ROADMAP "registry scrape" item)
# ----------------------------------------------------------------------

class MetricsHTTPServer:
    """Tiny background HTTP server exposing a registry's drains so a
    long-running sim/bench can be scraped LIVE instead of dumped at
    exit:

    - ``GET /metrics`` (or ``/``) -> Prometheus text exposition 0.0.4
    - ``GET /metrics.json``       -> the JSON ``snapshot()``
    - ``GET /healthz``            -> ``{"status": "ok"}`` liveness
      probe that touches NO registry drain -- the supervisor polls it
      after a scrape-port rebind to confirm the new incarnation's
      endpoint is actually serving (docs/ROBUSTNESS.md), and a probe
      must not pay for (or fail on) a metrics drain

    ``mount(prefix, handler)`` adds a path-prefixed sub-API under the
    same endpoint (GET/POST/PUT/DELETE): ``handler(method, path,
    body_bytes) -> (status, content_type, body_bytes)``.  The
    lifecycle plane's admin API (``lifecycle.api``, docs/LIFECYCLE.md)
    mounts ``/clients`` this way, so one port serves scrape + control.
    Mounted prefixes are consulted before the built-in GET routes; a
    handler exception answers 500 without killing the server thread.

    Drains are read lazily per request (callback gauges, timer merges),
    so serving a scrape costs the hot path nothing.  ``port=0`` binds
    an ephemeral port (read it back from ``.port``); ``close()`` shuts
    the daemon thread down.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 port: int = 0, host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry if registry is not None else default_registry()
        self.registry = reg
        # [(prefix, handler)] consulted in mount order; the list object
        # is closed over by the Handler below, so mounts added after
        # the server started are live immediately
        self._mounts: List[Tuple[str, Callable]] = []
        mounts = self._mounts

        def dispatch_mounted(handler, method: str) -> bool:
            """Route one request through the mounted sub-APIs; True
            when a mount claimed the path (response already sent)."""
            path = handler.path.split("?", 1)[0]
            for prefix, fn in mounts:
                if path == prefix or path.startswith(prefix + "/"):
                    n = int(handler.headers.get("Content-Length", 0)
                            or 0)
                    body = handler.rfile.read(n) if n else b""
                    try:
                        status, ctype, out = fn(method, path, body)
                    except Exception as e:   # a control-plane bug must
                        status, ctype = 500, "application/json"
                        out = json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}
                        ).encode()           # not kill the endpoint
                    handler.send_response(status)
                    handler.send_header("Content-Type", ctype)
                    handler.send_header("Content-Length",
                                        str(len(out)))
                    handler.end_headers()
                    handler.wfile.write(out)
                    return True
            return False

        class ReuseServer(ThreadingHTTPServer):
            # SO_REUSEADDR pinned EXPLICITLY (it is also the stdlib
            # HTTPServer default): a supervisor-restarted runner
            # depends on rebinding its scrape port immediately
            # instead of waiting out the dead incarnation's TIME_WAIT
            # sockets (docs/ROBUSTNESS.md scrape-port-loss fault), so
            # the contract must not silently ride on an upstream
            # default
            allow_reuse_address = True
            daemon_threads = True

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib API)
                if dispatch_mounted(self, "GET"):
                    return
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                if path in ("/", "/metrics"):
                    body = reg.prometheus().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = reg.snapshot_json().encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    body = b'{"status": "ok"}'
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                if not dispatch_mounted(self, "POST"):
                    self.send_error(404)

            def do_PUT(self):  # noqa: N802
                if not dispatch_mounted(self, "PUT"):
                    self.send_error(404)

            def do_DELETE(self):  # noqa: N802
                if not dispatch_mounted(self, "DELETE"):
                    self.send_error(404)

            def log_message(self, *_args):  # scrapes are not news
                pass

        self._srv = ReuseServer((host, port), Handler)
        self.host = host
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="metrics-http",
            daemon=True)
        self._thread.start()

    def mount(self, prefix: str, handler: Callable) -> None:
        """Mount ``handler(method, path, body) -> (status, ctype,
        body)`` under ``prefix`` (e.g. ``"/clients"``).  Live
        immediately; later mounts are consulted after earlier ones."""
        if not prefix.startswith("/") or prefix.endswith("/"):
            # ValueError, not assert: under PYTHONOPTIMIZE a stripped
            # check would accept a prefix the dispatcher can never
            # match -- an API that looks mounted but 404s everything
            raise ValueError(
                f"mount prefix must start with '/' and not end with "
                f"one, got {prefix!r}")
        if any(p == prefix for p, _ in self._mounts):
            # first-mount-wins dispatch would silently shadow the
            # second handler forever -- reject the collision instead
            # (re-mount-after-rebind creates a FRESH server, so a
            # legitimate caller never hits this)
            raise ValueError(f"prefix {prefix!r} already mounted")
        self._mounts.append((prefix, handler))

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    @property
    def healthz_url(self) -> str:
        return f"http://{self.host}:{self.port}/healthz"

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_http_server(registry: Optional[MetricsRegistry] = None,
                      port: int = 0, host: str = "127.0.0.1", *,
                      fail_soft: bool = True
                      ) -> Optional[MetricsHTTPServer]:
    """Start a background scrape endpoint over ``registry`` (default:
    the process-wide registry).

    Telemetry must never kill the run it observes: with ``fail_soft``
    (the default) a bind failure -- the port still held by another
    process, a previous incarnation not fully torn down, a privileged
    port -- logs a warning and returns ``None`` instead of raising,
    so repeated calls on the same port degrade to "no scrape
    endpoint" rather than an exception out of the serving layer
    (docs/ROBUSTNESS.md).  The server itself binds with
    ``SO_REUSEADDR``, so a supervisor-restarted runner normally
    rebinds its old port cleanly."""
    try:
        return MetricsHTTPServer(registry, port=port, host=host)
    except (OSError, OverflowError) as e:
        # OverflowError: out-of-range port from CPython's bind()
        if not fail_soft:
            raise
        import sys

        print(f"# metrics: scrape endpoint disabled "
              f"({host}:{port}: {e})", file=sys.stderr)
        return None
