"""``dmclock_rpc_*`` metric families (docs/OBSERVABILITY.md).

The ingest front-end's scrape surface: admission/backpressure/chaos
counters from :class:`net.server.IngestServer`, per-shard routed-ops
attribution (PlacementMap ownership), and the host-side admission-
to-commit latency summary the serving loop measures at each chunk
boundary.  All host-side, all advisory: nothing here participates
in the chain digest.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

_HELP = "RPC ingest front-end (docs/RPC.md; docs/OBSERVABILITY.md)"

#: server counter -> metric suffix (``dmclock_rpc_<suffix>``)
COUNTER_FAMILIES = {
    "requests": "requests_total",
    "admitted_reqs": "admitted_requests_total",
    "admitted_ops": "admitted_ops_total",
    "deduped": "deduped_total",
    "busy": "busy_total",
    "drops_injected": "chaos_drops_total",
    "dup_frames": "chaos_dups_total",
    "reordered": "chaos_reorders_total",
    "proto_errors": "protocol_errors_total",
    "conns_opened": "connections_opened_total",
    "conns_timed_out": "connections_timed_out_total",
    "notify_batches": "notify_batches_total",
    "device_drop_signals": "device_drop_signals_total",
    "datagrams": "datagrams_total",
}


def publish_rpc(registry, status: dict) -> None:
    """Publish one :meth:`IngestServer.status` snapshot.  Fail-soft
    by caller convention (the serving loop wraps this in the same
    best-effort guard every other publisher gets)."""
    if registry is None:
        return
    counters = status.get("counters", {})
    for key, suffix in COUNTER_FAMILIES.items():
        registry.gauge(f"dmclock_rpc_{suffix}", _HELP) \
            .set(float(counters.get(key, 0)))
    registry.gauge("dmclock_rpc_queue_depth", _HELP) \
        .set(float(status.get("queue_depth", 0)))
    registry.gauge("dmclock_rpc_connections_live", _HELP) \
        .set(float(status.get("connections", 0)))
    registry.gauge("dmclock_rpc_backpressure_engaged", _HELP) \
        .set(1.0 if status.get("device_pressure") else 0.0)
    for shard, ops in status.get("shard_rx", {}).items():
        registry.gauge("dmclock_rpc_shard_routed_ops_total", _HELP,
                       labels={"shard": str(shard)}).set(float(ops))


def latency_summary(samples_ns: Sequence[int]) -> Dict[str, float]:
    """p50/p99/max of admission-to-commit latencies in milliseconds
    (empty -> zeros; the bench guard's warn-only series reads the
    p99)."""
    if not samples_ns:
        return {"p50_ms": 0.0, "p99_ms": 0.0, "max_ms": 0.0,
                "samples": 0}
    a = np.asarray(samples_ns, dtype=np.float64) / 1e6
    return {"p50_ms": float(np.percentile(a, 50)),
            "p99_ms": float(np.percentile(a, 99)),
            "max_ms": float(a.max()), "samples": int(a.size)}


def publish_rpc_latency(registry,
                        summary: Optional[Dict[str, float]]) -> None:
    if registry is None or not summary:
        return
    for key in ("p50_ms", "p99_ms", "max_ms"):
        registry.gauge(f"dmclock_rpc_admit_to_commit_{key}", _HELP) \
            .set(float(summary.get(key, 0.0)))
