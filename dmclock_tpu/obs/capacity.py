"""Static HBM footprint ledger, capacity planner, roofline attributor.

The capacity plane's space axis.  Three questions the silicon campaign
and the mesh-sharding item (ROADMAP) cannot currently answer without
burning a TPU session on an OOM:

1. **How many HBM bytes does a configuration pin?**  :func:`hbm_ledger`
   walks the live device-resident pytrees -- the ``EngineState`` client
   block + tail rings, the telemetry histograms/ledger, the flight
   ring, the SLO window block, the lifecycle slot map -- and the epoch
   program's own output blocks (derived with ``jax.eval_shape`` from
   the REAL epoch function, so the ledger cannot rot when a result
   field is added), per subsystem.
2. **How many clients fit a chip?**  Every subsystem is linear in N,
   so :func:`capacity_model` fits the exact (bytes/client, fixed
   bytes) line from two abstract evaluations and
   :func:`plan_capacity` inverts it against an HBM budget
   (:func:`device_hbm_budget` reads the attached device's
   ``memory_stats``; ``DMCLOCK_HBM_BUDGET_BYTES`` overrides, CPU
   boxes report None).  The projection is validated against
   ``Compiled.memory_analysis()`` of the real compiled epoch program
   (ci.sh capacity smoke: within 10% at the cfg4 shape).
3. **Is a measured workload compute-, memory-, or dispatch-bound?**
   :func:`classify` joins ``cost_analysis`` flops/bytes (the compile
   plane records them per cache entry) with the PR-7 span tracer's
   measured dispatch/device self-time: dispatch share past the
   threshold -> ``dispatch_bound``; otherwise arithmetic intensity
   (flops/byte) vs the device's machine balance (peak flops / peak
   HBM bandwidth) decides ``compute_bound`` vs ``memory_bound``.
   Peaks come from a small advisory per-chip table
   (:data:`ROOFLINE_PEAKS`); on XLA:CPU everything here is advisory
   (PROFILE.md) -- the TPU session is the real record.

Everything in this module is host-side arithmetic over abstract
shapes: it launches nothing, allocates nothing device-side, and cannot
perturb a decision.
"""

from __future__ import annotations

import functools
import os
from typing import Dict, Optional, Tuple

import numpy as np

_SUBSYS_STATE = ("client_state", "rings")


def leaf_bytes(leaf) -> int:
    """Logical bytes of one array-like leaf (ShapeDtypeStruct,
    jax.Array, np.ndarray); 0 for None/scalars without dtype.  TPU
    lane tiling can pad small trailing dims -- the planner's
    ``slack_frac`` covers that margin."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def tree_bytes(tree) -> int:
    import jax

    return sum(leaf_bytes(x) for x in jax.tree_util.tree_leaves(tree))


def abstract_state(n: int, ring: int):
    """``EngineState`` shapes/dtypes for (n, ring) without allocating
    a byte (``jax.eval_shape`` over the real ``init_state``)."""
    import jax

    from ..engine.state import init_state

    return jax.eval_shape(functools.partial(init_state, n, ring))


def _abstract_tele(n: int, *, telemetry: bool, slo: bool,
                   flight_records: int) -> dict:
    """Abstract telemetry accumulators for the ledger walk and the
    epoch-output eval_shape -- shaped by the real constructors."""
    import jax

    out = {}
    if telemetry:
        from . import histograms as obshist
        out["hists"] = jax.eval_shape(obshist.hist_zero)
        out["ledger"] = jax.eval_shape(
            functools.partial(obshist.ledger_zero, n))
    if flight_records:
        from . import flight as obsflight
        out["flight"] = jax.eval_shape(
            functools.partial(obsflight.flight_init, flight_records))
    if slo:
        from . import slo as obsslo
        out["slo"] = jax.eval_shape(
            functools.partial(obsslo.window_zero, n))
    return out


def hbm_ledger(n: int, *, ring: int = 64, engine: Optional[str] = None,
               m: int = 0, k: int = 0, chain_depth: int = 4,
               select_impl: str = "sort", tag_width: int = 64,
               window_m: Optional[int] = None,
               calendar_impl: str = "minstop", ladder_levels: int = 8,
               telemetry: bool = False, slo: bool = False,
               flight_records: int = 0, lifecycle: bool = False,
               stream_chunk: int = 0) -> Dict[str, int]:
    """Per-subsystem resident HBM bytes for one configuration.

    Subsystems: ``client_state`` (the [N] SoA minus rings), ``rings``
    (the [N, Q] int64 tail pair -- the dominant term at bench shapes),
    ``telemetry_hists`` / ``telemetry_ledger`` / ``flight`` /
    ``slo_window`` (each only when enabled), ``lifecycle`` (the
    checkpoint-resident slot map), and -- when ``engine``/``m`` are
    given -- ``epoch_outputs``: the epoch program's decision/metric
    output blocks from ``jax.eval_shape`` of the real scan (state and
    accumulator echoes excluded: donated, they alias their inputs).
    ``stream_chunk`` > 1 multiplies the output blocks (the fused chunk
    stacks per-epoch outputs in HBM as scan outputs)."""
    import jax

    st = abstract_state(n, ring)
    rings = leaf_bytes(st.q_arrival) + leaf_bytes(st.q_cost)
    out: Dict[str, int] = {
        "client_state": tree_bytes(st) - rings,
        "rings": rings,
    }
    tele = _abstract_tele(n, telemetry=telemetry, slo=slo,
                          flight_records=flight_records)
    if "hists" in tele:
        out["telemetry_hists"] = tree_bytes(tele["hists"])
        out["telemetry_ledger"] = tree_bytes(tele["ledger"])
    if "flight" in tele:
        out["flight"] = tree_bytes(tele["flight"])
    if "slo" in tele:
        out["slo_window"] = tree_bytes(tele["slo"])
    if lifecycle:
        # the checkpoint-resident slot map (client-id <-> slot); the
        # boundary op vectors are transient launch arguments
        out["lifecycle"] = n * np.dtype(np.int64).itemsize
    if engine and m > 0:
        from ..engine import fastpath

        kw = fastpath.epoch_scan_kwargs(
            engine, k=k, chain_depth=chain_depth,
            select_impl=select_impl, tag_width=tag_width,
            window_m=window_m, calendar_impl=calendar_impl,
            ladder_levels=ladder_levels, with_metrics=True)
        now = jax.ShapeDtypeStruct((), np.dtype(np.int64))
        fn = functools.partial(fastpath.epoch_scan_fn(engine),
                               m=m, **kw, **tele)
        try:
            ep = jax.eval_shape(fn, st, now)
            skip = {"state", "hists", "ledger", "flight", "slo"}
            blocks = sum(
                tree_bytes(getattr(ep, f)) for f in ep._fields
                if f not in skip)
        except Exception:
            # an engine/backend combination eval_shape cannot trace
            # must not kill the planner: fall back to the dominant
            # closed-form term (the [m, k] decision block)
            blocks = m * max(k, 1) * 16
        out["epoch_outputs"] = blocks * max(stream_chunk, 1)
    return out


def projected_total(ledger: Dict[str, int]) -> int:
    return int(sum(ledger.values()))


class CapacityModel:
    """The exact per-subsystem linear model bytes(N) = a*N + b, fitted
    from two abstract ledgers (every subsystem is linear in N by
    construction -- the fit is exact, and it cannot rot because the
    ledgers walk the real pytrees)."""

    def __init__(self, slopes: Dict[str, float],
                 intercepts: Dict[str, float]):
        self.slopes = slopes
        self.intercepts = intercepts

    @property
    def bytes_per_client(self) -> float:
        return float(sum(self.slopes.values()))

    @property
    def fixed_bytes(self) -> float:
        return float(sum(self.intercepts.values()))

    def ledger(self, n: int) -> Dict[str, int]:
        return {s: int(round(self.slopes[s] * n + self.intercepts[s]))
                for s in self.slopes}

    def total(self, n: int) -> int:
        return projected_total(self.ledger(n))


_MODEL_N0, _MODEL_N1 = 256, 512
_MODEL_CACHE: Dict[tuple, CapacityModel] = {}


def capacity_model(**cfg) -> CapacityModel:
    """Fit the linear model for one knob setting (cached per cfg --
    the two eval_shape walks trace the epoch program)."""
    key = tuple(sorted(cfg.items()))
    model = _MODEL_CACHE.get(key)
    if model is None:
        l0 = hbm_ledger(_MODEL_N0, **cfg)
        l1 = hbm_ledger(_MODEL_N1, **cfg)
        dn = _MODEL_N1 - _MODEL_N0
        slopes = {s: (l1[s] - l0[s]) / dn for s in l0}
        inter = {s: l0[s] - slopes[s] * _MODEL_N0 for s in l0}
        model = _MODEL_CACHE[key] = CapacityModel(slopes, inter)
    return model


def projected_hbm(n: int, **cfg) -> int:
    """Projected resident HBM bytes for ``n`` clients at this knob
    setting -- the bench JSON line's ``projected_hbm_bytes``."""
    return capacity_model(**cfg).total(n)


def plan_capacity(budget_bytes: Optional[int] = None, *,
                  slack_frac: float = 0.1, device=None,
                  **cfg) -> dict:
    """Invert the ledger: max clients per chip for an HBM budget and a
    knob setting -- the mesh item's per-shard sizing question in one
    call.  ``budget_bytes`` defaults to the attached device's budget
    (:func:`device_hbm_budget`; raises ``ValueError`` when neither is
    known).  ``slack_frac`` reserves headroom for XLA temps, lane
    padding, and the runtime's own allocations."""
    if budget_bytes is None:
        budget_bytes = device_hbm_budget(device)
        if budget_bytes is None:
            raise ValueError(
                "no HBM budget: pass budget_bytes, set "
                "DMCLOCK_HBM_BUDGET_BYTES, or run where the device "
                "reports memory_stats()")
    model = capacity_model(**cfg)
    usable = int(budget_bytes * (1.0 - slack_frac))
    per = model.bytes_per_client
    n = int(max((usable - model.fixed_bytes) // max(per, 1e-9), 0))
    while n > 0 and model.total(n) > usable:
        n -= 1
    return {
        "max_clients": n,
        "budget_bytes": int(budget_bytes),
        "usable_bytes": usable,
        "slack_frac": slack_frac,
        "bytes_per_client": per,
        "fixed_bytes": model.fixed_bytes,
        "projected_bytes": model.total(n),
        "ledger": model.ledger(n),
        "config": dict(cfg),
    }


def fits(n: int, budget_bytes: int, *, slack_frac: float = 0.1,
         **cfg) -> bool:
    """Does an ``n``-client configuration fit the budget (with the
    planner's slack)?  The round-trip property the ci gate pins:
    ``fits(plan_capacity(b)["max_clients"], b)`` is True and any
    larger N refuses."""
    return projected_hbm(n, **cfg) <= int(budget_bytes
                                          * (1.0 - slack_frac))


def device_hbm_budget(device=None) -> Optional[int]:
    """Detected per-device memory budget in bytes.
    ``DMCLOCK_HBM_BUDGET_BYTES`` overrides (testable, and the escape
    hatch for runtimes that hide ``memory_stats``); CPU boxes report
    None -- host RAM is not the resource this plane manages."""
    env = os.environ.get("DMCLOCK_HBM_BUDGET_BYTES")
    if env:
        try:
            # 0 means "detection disabled" (the DMCLOCK_COMPILE_PLANE
            # =0 convention), not a zero-byte budget that would gate
            # every workload
            return int(env) or None
        except ValueError:
            pass
    import jax

    try:
        d = device if device is not None else jax.local_devices()[0]
        stats = d.memory_stats()
        if stats:
            v = stats.get("bytes_limit") or stats.get(
                "bytes_reservable_limit")
            if v:
                return int(v)
    except Exception:
        pass
    return None


# ----------------------------------------------------------------------
# roofline attribution
# ----------------------------------------------------------------------

# Advisory per-chip peaks: (dense peak flops/s, HBM bytes/s).  These
# gate a CLASSIFICATION (which side of the machine-balance ridge a
# workload sits on), not a utilization claim; the scheduler's integer
# ops count as cost_analysis "flops".  XLA:CPU rows are rough host
# ballparks -- PROFILE.md's advisory caveat applies to everything
# measured there.
ROOFLINE_PEAKS: Dict[str, Tuple[float, float]] = {
    "v6e": (918e12, 1640e9),
    "v5p": (459e12, 2765e9),
    "v5e": (197e12, 819e9),
    "v4": (275e12, 1228e9),
    "v3": (123e12, 900e9),
    "cpu": (2e11, 5e10),
}
_DEFAULT_PEAKS = ("unknown", (1e14, 1e12))


def device_peaks(device=None) -> dict:
    """(peak flops/s, peak HBM bytes/s, label) for the attached
    device, from :data:`ROOFLINE_PEAKS` by device-kind substring."""
    import jax

    try:
        d = device if device is not None else jax.local_devices()[0]
        kind = f"{getattr(d, 'device_kind', '')} " \
               f"{getattr(d, 'platform', '')}".lower()
    except Exception:
        kind = ""
    for key, (pf, pb) in ROOFLINE_PEAKS.items():
        if key in kind:
            return {"label": key, "peak_flops": pf,
                    "peak_bytes_per_s": pb}
    label, (pf, pb) = _DEFAULT_PEAKS
    return {"label": label, "peak_flops": pf, "peak_bytes_per_s": pb}


def classify(*, flops: float, bytes_accessed: float,
             device_time_s: Optional[float] = None,
             dispatch_time_s: Optional[float] = None,
             peak_flops: Optional[float] = None,
             peak_bytes_per_s: Optional[float] = None,
             dispatch_share_warn: float = 0.5) -> dict:
    """The classification rule (docs/OBSERVABILITY.md "Capacity
    plane"):

    1. with measured times, dispatch self-time share of
       (dispatch + device) past ``dispatch_share_warn`` ->
       ``dispatch_bound`` (the tunnel tax dominates; no amount of
       kernel tuning helps before the streaming loop does);
    2. otherwise arithmetic intensity (flops / bytes accessed) vs the
       machine balance (peak flops / peak bandwidth): below the ridge
       -> ``memory_bound``, at/above -> ``compute_bound``;
    3. no flops/bytes at all -> ``unknown``.
    """
    if peak_flops is None or peak_bytes_per_s is None:
        pk = device_peaks()
        peak_flops = peak_flops or pk["peak_flops"]
        peak_bytes_per_s = peak_bytes_per_s or pk["peak_bytes_per_s"]
    out: dict = {"peak_flops": peak_flops,
                 "peak_bytes_per_s": peak_bytes_per_s,
                 "machine_balance": peak_flops / peak_bytes_per_s}
    if device_time_s is not None and dispatch_time_s is not None \
            and (device_time_s + dispatch_time_s) > 0:
        share = dispatch_time_s / (device_time_s + dispatch_time_s)
        out["dispatch_share"] = share
        if share > dispatch_share_warn:
            out["bound_class"] = "dispatch_bound"
            return out
    if not flops and not bytes_accessed:
        out["bound_class"] = "unknown"
        return out
    ai = flops / max(bytes_accessed, 1.0)
    out["arithmetic_intensity"] = ai
    if device_time_s:
        out["achieved_flops_per_s"] = flops / device_time_s
        out["achieved_bytes_per_s"] = bytes_accessed / device_time_s
    out["bound_class"] = "compute_bound" \
        if ai >= out["machine_balance"] else "memory_bound"
    return out


def classify_bench_row(row: dict, *, peaks: Optional[dict] = None,
                       dispatch_share_warn: float = 0.5) -> dict:
    """Roofline verdict for one bench workload row: joins the row's
    ``cost_analysis`` (per-launch flops/bytes) with its ``spans``
    block's measured per-launch dispatch/device self-time when spans
    ran; without spans the verdict is intensity-only (rule 2)."""
    ca = row.get("cost_analysis") or {}
    sp = row.get("spans") or {}
    kw: dict = dict(flops=float(ca.get("flops", 0.0)),
                    bytes_accessed=float(ca.get("bytes_accessed",
                                                0.0)),
                    dispatch_share_warn=dispatch_share_warn)
    if "device_ms_per_launch" in sp and "dispatch_ms_per_launch" in sp:
        kw["device_time_s"] = sp["device_ms_per_launch"] / 1e3
        kw["dispatch_time_s"] = sp["dispatch_ms_per_launch"] / 1e3
    if peaks:
        kw["peak_flops"] = peaks.get("peak_flops")
        kw["peak_bytes_per_s"] = peaks.get("peak_bytes_per_s")
    return classify(**kw)


def publish_capacity_metrics(registry, *, projected_bytes=None,
                             budget_bytes=None, max_clients=None,
                             workload: Optional[str] = None) -> None:
    """``dmclock_capacity_*`` gauges on the scrape endpoint."""
    lbl = {"workload": workload} if workload else None
    if projected_bytes is not None:
        registry.gauge(
            "dmclock_capacity_projected_hbm_bytes",
            "projected resident HBM bytes for the workload's knob "
            "setting (obs.capacity ledger; docs/OBSERVABILITY.md "
            "capacity plane)", labels=lbl).set(float(projected_bytes))
    if budget_bytes is not None:
        registry.gauge(
            "dmclock_capacity_budget_bytes",
            "detected device HBM budget (memory_stats bytes_limit or "
            "DMCLOCK_HBM_BUDGET_BYTES)").set(float(budget_bytes))
    if max_clients is not None:
        registry.gauge(
            "dmclock_capacity_max_clients",
            "plan_capacity() max clients per chip at the current "
            "budget and knob setting", labels=lbl) \
            .set(float(max_clients))
