"""Bounded JSONL decision trace.

One JSON object per scheduling decision, streamed to disk as the sim
runs (no unbounded in-memory list), capped at ``limit`` rows -- past
the cap rows are counted but not written, so a runaway sim cannot fill
the disk.  Schema v2 (``docs/OBSERVABILITY.md``):

    {"t": <virtual ns>, "server": <id>, "client": <id>,
     "phase": "reservation" | "priority", "cost": <int>,
     "tag": [resv, prop, limit] | null,
     "margin": <int ns> | null, "eligible_depth": <int> | null,
     "gate": <int> | null}

``tag`` is the served request's tag triple when the backend exposes it
(the host oracle queues do via ``PullReq.tag``); backends that never
materialize per-decision tags on the host (the TPU batch engine) emit
``null`` -- the field is optional-by-null, never absent.

``margin`` / ``eligible_depth`` / ``gate`` are the decision-provenance
columns (v2; ``obs.provenance``): the winner's margin over the
runner-up candidate (ns), the eligible-set depth, and the limit-gated
client count at the decision's instant -- ``null`` when the backend
does not surface them (the flight ring, ``obs.flight``, is the
always-populated device-side record).  The reader is backward
compatible: v1 rows (no provenance fields) load with nulls.
"""

from __future__ import annotations

import json
from typing import IO, Optional

TRACE_FIELDS_V1 = ("t", "server", "client", "phase", "cost", "tag")
PROVENANCE_FIELDS = ("margin", "eligible_depth", "gate")
TRACE_FIELDS = TRACE_FIELDS_V1 + PROVENANCE_FIELDS
_PHASES = ("reservation", "priority")


class DecisionTrace:
    """Streaming bounded JSONL writer for scheduling decisions."""

    def __init__(self, path: str, limit: int = 1_000_000):
        self.path = path
        self.limit = int(limit)
        self.rows_written = 0
        self.rows_dropped = 0
        self._fh: Optional[IO[str]] = open(path, "w")

    def record(self, t_ns: int, server, client, phase: int, cost: int,
               tag=None, margin=None, eligible_depth=None,
               gate=None) -> None:
        if self._fh is None:
            return
        if self.rows_written >= self.limit:
            self.rows_dropped += 1
            return
        row = {"t": int(t_ns), "server": server, "client": client,
               "phase": _PHASES[int(phase)], "cost": int(cost),
               "tag": [int(x) for x in tag] if tag is not None else None,
               "margin": int(margin) if margin is not None else None,
               "eligible_depth": int(eligible_depth)
               if eligible_depth is not None else None,
               "gate": int(gate) if gate is not None else None}
        self._fh.write(json.dumps(row, separators=(",", ":")) + "\n")
        self.rows_written += 1

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _check_row(path: str, i: int, row: dict) -> None:
    """Schema validation of one row (v1 or v2); raises ValueError."""
    got = set(row)
    if got != set(TRACE_FIELDS) and got != set(TRACE_FIELDS_V1):
        raise ValueError(
            f"{path}:{i+1}: fields {sorted(row)} match neither the "
            f"v2 schema {sorted(TRACE_FIELDS)} nor the v1 schema "
            f"{sorted(TRACE_FIELDS_V1)}")
    if row["phase"] not in _PHASES:
        raise ValueError(f"{path}:{i+1}: bad phase "
                         f"{row['phase']!r}")
    if not isinstance(row["t"], int) or \
            not isinstance(row["cost"], int):
        raise ValueError(f"{path}:{i+1}: t/cost must be ints")
    tag = row["tag"]
    if tag is not None and (
            not isinstance(tag, list) or len(tag) != 3 or
            not all(isinstance(x, int) for x in tag)):
        raise ValueError(f"{path}:{i+1}: tag must be null or "
                         "[resv, prop, limit] ints")
    for field in PROVENANCE_FIELDS:
        v = row.get(field)
        if v is not None and not isinstance(v, int):
            raise ValueError(f"{path}:{i+1}: {field} must be null "
                             "or an int")


def load_trace(path: str) -> list:
    """Read a trace back as dict rows, validating each; v1 rows load
    with ``None`` in the provenance columns (the backward-compatible
    reader)."""
    rows = []
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i+1}: not JSON: {e}")
            _check_row(path, i, row)
            for field in PROVENANCE_FIELDS:
                row.setdefault(field, None)
            rows.append(row)
    return rows


def validate_trace_file(path: str) -> dict:
    """Validate a trace file against the schema (v1 or v2 rows);
    raises ``ValueError`` on the first bad row.  Returns summary stats
    the CI smoke checks against the conformance table:

        {"rows": N, "per_client": {client: count},
         "per_phase": {"reservation": n, "priority": n},
         "v1_rows": n, "v2_rows": n,
         "margin": {"count": n, "max_ns": x},
         "gate": {"count": n, "max": x}}
    """
    per_client: dict = {}
    per_phase = {"reservation": 0, "priority": 0}
    rows = v1_rows = v2_rows = 0
    margin_n = 0
    margin_max = 0
    gate_n = 0
    gate_max = 0
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i+1}: not JSON: {e}")
            _check_row(path, i, row)
            if set(row) == set(TRACE_FIELDS_V1):
                v1_rows += 1
            else:
                v2_rows += 1
            rows += 1
            key = row["client"]
            per_client[key] = per_client.get(key, 0) + 1
            per_phase[row["phase"]] += 1
            m = row.get("margin")
            if m is not None:
                margin_n += 1
                margin_max = max(margin_max, m)
            g = row.get("gate")
            if g is not None:
                gate_n += 1
                gate_max = max(gate_max, g)
    return {"rows": rows, "per_client": per_client,
            "per_phase": per_phase,
            "v1_rows": v1_rows, "v2_rows": v2_rows,
            "margin": {"count": margin_n, "max_ns": margin_max},
            "gate": {"count": gate_n, "max": gate_max}}


def summarize(path: str, device_metrics=None) -> dict:
    """:func:`validate_trace_file` plus the device cross-check: with
    ``device_metrics`` (a fetched ``obs.device`` vector, dict, or
    ``(resv, prop)`` pair) the trace's per-phase totals must equal the
    device ``MET_RESV`` / ``MET_PROP`` counters EXACTLY -- the trace
    is a host-side transcript of the same decisions, so any mismatch
    means rows were dropped, duplicated, or mis-phased.  Raises
    ``ValueError`` on mismatch (``dmc_sim --ledger-check`` turns that
    into a nonzero exit)."""
    stats = validate_trace_file(path)
    if device_metrics is not None:
        if isinstance(device_metrics, dict):
            resv = int(device_metrics["decisions_reservation"])
            prop = int(device_metrics["decisions_priority"])
        elif isinstance(device_metrics, tuple):
            resv, prop = (int(x) for x in device_metrics)
        else:
            from . import device as obsdev
            vec = device_metrics
            resv = int(vec[obsdev.MET_RESV])
            prop = int(vec[obsdev.MET_PROP])
        got = stats["per_phase"]
        if got["reservation"] != resv or got["priority"] != prop:
            raise ValueError(
                f"{path}: per-phase totals diverge from the device "
                f"counters: trace reservation={got['reservation']} "
                f"priority={got['priority']} vs device MET_RESV="
                f"{resv} MET_PROP={prop}")
        stats["device_cross_check"] = {"reservation": resv,
                                       "priority": prop}
    return stats
