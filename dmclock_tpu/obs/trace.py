"""Bounded JSONL decision trace.

One JSON object per scheduling decision, streamed to disk as the sim
runs (no unbounded in-memory list), capped at ``limit`` rows -- past
the cap rows are counted but not written, so a runaway sim cannot fill
the disk.  Schema (``docs/OBSERVABILITY.md``):

    {"t": <virtual ns>, "server": <id>, "client": <id>,
     "phase": "reservation" | "priority", "cost": <int>,
     "tag": [resv, prop, limit] | null}

``tag`` is the served request's tag triple when the backend exposes it
(the host oracle queues do via ``PullReq.tag``); backends that never
materialize per-decision tags on the host (the TPU batch engine) emit
``null`` -- the field is optional-by-null, never absent.
"""

from __future__ import annotations

import json
from typing import IO, Optional

TRACE_FIELDS = ("t", "server", "client", "phase", "cost", "tag")
_PHASES = ("reservation", "priority")


class DecisionTrace:
    """Streaming bounded JSONL writer for scheduling decisions."""

    def __init__(self, path: str, limit: int = 1_000_000):
        self.path = path
        self.limit = int(limit)
        self.rows_written = 0
        self.rows_dropped = 0
        self._fh: Optional[IO[str]] = open(path, "w")

    def record(self, t_ns: int, server, client, phase: int, cost: int,
               tag=None) -> None:
        if self._fh is None:
            return
        if self.rows_written >= self.limit:
            self.rows_dropped += 1
            return
        row = {"t": int(t_ns), "server": server, "client": client,
               "phase": _PHASES[int(phase)], "cost": int(cost),
               "tag": [int(x) for x in tag] if tag is not None else None}
        self._fh.write(json.dumps(row, separators=(",", ":")) + "\n")
        self.rows_written += 1

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def validate_trace_file(path: str) -> dict:
    """Validate a trace file against the schema; raises ``ValueError``
    on the first bad row.  Returns summary stats the CI smoke checks
    against the conformance table:

        {"rows": N, "per_client": {client: count},
         "per_phase": {"reservation": n, "priority": n}}
    """
    per_client: dict = {}
    per_phase = {"reservation": 0, "priority": 0}
    rows = 0
    with open(path) as fh:
        for i, line in enumerate(fh):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i+1}: not JSON: {e}")
            if set(row) != set(TRACE_FIELDS):
                raise ValueError(
                    f"{path}:{i+1}: fields {sorted(row)} != "
                    f"{sorted(TRACE_FIELDS)}")
            if row["phase"] not in _PHASES:
                raise ValueError(f"{path}:{i+1}: bad phase "
                                 f"{row['phase']!r}")
            if not isinstance(row["t"], int) or \
                    not isinstance(row["cost"], int):
                raise ValueError(f"{path}:{i+1}: t/cost must be ints")
            tag = row["tag"]
            if tag is not None and (
                    not isinstance(tag, list) or len(tag) != 3 or
                    not all(isinstance(x, int) for x in tag)):
                raise ValueError(f"{path}:{i+1}: tag must be null or "
                                 "[resv, prop, limit] ints")
            rows += 1
            key = row["client"]
            per_client[key] = per_client.get(key, 0) + 1
            per_phase[row["phase"]] += 1
    return {"rows": rows, "per_client": per_client,
            "per_phase": per_phase}
