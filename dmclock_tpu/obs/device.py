"""On-device scheduling metrics: one small int64 vector, zero extra
round trips.

The epoch scans (``engine.fastpath``) and the serial batch runner
(``engine.kernels.engine_run``) already read back per-batch commit
counts; the metrics vector rides in the same scan carry and the same
fetch.  Accumulation is pure reductions over arrays the kernels
already materialize (decision phases, depths, guard bits), gated on a
STATIC ``with_metrics`` flag so the decision stream -- and, with the
flag off, the compiled program -- is bit-identical to the pre-metrics
kernels (pinned by ``tests/test_obs.py``).

Vector layout (int64[NUM_METRICS]); counters accumulate by addition,
high-water marks by ``maximum``.

The scalar vector is the cheapest tier of the device telemetry plane;
``obs.histograms`` (log2-bucketed QoS distributions + the per-client
conformance ledger) and ``obs.flight`` (the HBM flight recorder) ride
the same scan carries under the same bit-identical-decisions contract
and merge through the same psum/pmax collective path
(``metrics_mesh_reduce`` / ``hist_mesh_reduce`` /
``ledger_mesh_reduce``).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# -- indices -----------------------------------------------------------
MET_DECISIONS = 0       # decisions committed (all phases)
MET_RESV = 1            # constraint-phase (reservation) decisions
MET_PROP = 2            # weight-phase (priority) decisions
MET_LIMIT_BREAK = 3     # AtLimit::Allow limit-break serves
MET_STALLS = 4          # limit-capped stalls: batches/steps that
#                         committed nothing while work was queued
MET_RING_HWM = 5        # ring occupancy high-water mark (max depth)
MET_GUARD_TRIPS = 6     # rebase-guard trips (fastpath fallbacks)
MET_INGEST_DROPS = 7    # arrivals dropped by the admission clamp
MET_REBASE_FALLBACKS = 8  # int32 tag-rebase window trips (epoch ran
#                           out of the +-2^31 ns window; the batch
#                           committed nothing and the caller must rerun
#                           it on the int64 tag path)
MET_SERVER_DROPOUTS = 9   # cluster fault layer: up -> down transitions
#                           (robust.cluster; docs/ROBUSTNESS.md)
MET_TRACKER_RESYNCS = 10  # cluster fault layer: down -> up restarts
#                           that re-synced TrackerState marks from the
#                           monotone global counters
MET_FAULTS_INJECTED = 11  # total injected fault events (dropouts,
#                           restarts, delayed counters, duplicated
#                           completions, nonzero clock skew) -- every
#                           FaultPlan perturbation is visible here
MET_CAL_LADDER_LEVELS = 12  # bucketed calendar: ladder levels that
#                             committed > 0 decisions (summed over
#                             batches; minstop batches count as one
#                             level when they commit)
MET_CAL_LADDER_BASE = 13  # bucketed calendar: decisions the FIRST
#                           ladder level committed -- the minstop-
#                           equivalent share, so (decisions_total -
#                           this) is what the ladder bought per launch
MET_CAL_LADDER_FALLBACKS = 14  # bucketed calendar: batches whose
#                                ladder stalled (a level committed 0
#                                with candidates present -- the
#                                serial-fallback analog; remaining
#                                levels of that batch are wasted)
MET_LADDER_STEPS = 15     # degradation-ladder step-downs taken
#                           (robust.guarded.DegradationLadder:
#                           bucketed->minstop, radix->sort,
#                           tag32->int64; docs/ROBUSTNESS.md).  Reads
#                           zero when the ladder is disabled or never
#                           engaged (the zero-cost-when-off gate).
MET_SUPERVISOR_RESUMES = 16  # supervisor restarts that resumed from a
#                              rotation checkpoint (robust.supervisor).
#                              A resume_* row: crash-equivalence
#                              compares metric totals MODULO this row
#                              (an interrupted run legitimately differs
#                              here and nowhere else).
MET_WHEEL_OCC_HWM = 17    # wheel calendar: bucket-occupancy high-water
#                           mark (max clients sharing one (class,
#                           bucket) cell -- discrimination health of
#                           the wheel geometry; an hwm row)
MET_WHEEL_RESLOTS = 18    # wheel calendar: in-place bucket re-slots
#                           (clients whose (class, key) moved between
#                           ladder levels / API adjust events -- the
#                           O(moved) work the wheel does instead of a
#                           full O(N) re-measure)
MET_PALLAS_FALLBACKS = 19  # batches that requested wheel_kernel=
#                            "pallas" but ran the XLA reference (non-
#                            TPU backend or unsupported shape) -- a
#                            fleet silently off its kernel is visible
NUM_METRICS = 20

METRIC_NAMES = (
    "decisions_total", "decisions_reservation", "decisions_priority",
    "decisions_limit_break", "limit_stalls", "ring_occupancy_hwm",
    "rebase_guard_trips", "ingest_drops", "rebase_fallbacks",
    "server_dropouts", "tracker_resyncs", "faults_injected",
    "calendar_ladder_levels_used", "calendar_ladder_base_decisions",
    "calendar_ladder_fallbacks", "degradation_ladder_steps",
    "supervisor_resumes", "wheel_bucket_occupancy_hwm",
    "wheel_reslots_total", "wheel_pallas_fallbacks",
)

# rows an interrupted-and-resumed run may legitimately grow relative
# to its uninterrupted reference (the "modulo resume_* rows" clause of
# the crash-equivalence digest gate; robust.supervisor)
RESUME_ROWS = (MET_SUPERVISOR_RESUMES,)

# the max-accumulated rows (everything else adds).  The mask is a
# HOST (numpy) constant on purpose: this module is imported lazily
# from inside jitted code paths, and a module-level jnp array built
# under an active trace would leak a tracer into the global --
# jnp.where folds the numpy constant in at trace time either way.
_HWM_ROWS = (MET_RING_HWM, MET_WHEEL_OCC_HWM)
_HWM_MASK = np.zeros((NUM_METRICS,), dtype=bool)
for _i in _HWM_ROWS:
    _HWM_MASK[_i] = True


def metrics_zero() -> jnp.ndarray:
    return jnp.zeros((NUM_METRICS,), dtype=jnp.int64)


def metrics_combine(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Merge two metric vectors (counters add, high-water marks max) --
    the device-side analog of ``ProfileCombiner``.  Associative and
    commutative, so shards/epochs merge in any order (and through a
    psum-of-counters + pmax-of-hwm on a mesh)."""
    return jnp.where(_HWM_MASK, jnp.maximum(a, b), a + b)


def metrics_delta(*, decisions=0, resv=0, prop=0, limit_break=0,
                  stalls=0, ring_hwm=0, guard_trips=0,
                  ingest_drops=0, rebase_fallbacks=0,
                  server_dropouts=0, tracker_resyncs=0,
                  faults_injected=0, cal_ladder_levels_used=0,
                  cal_ladder_base_decisions=0,
                  cal_ladder_fallbacks=0, ladder_steps=0,
                  supervisor_resumes=0, wheel_occ_hwm=0,
                  wheel_reslots=0, pallas_fallbacks=0) -> jnp.ndarray:
    """Build a one-batch delta vector from scalar contributions."""
    rows = [decisions, resv, prop, limit_break, stalls, ring_hwm,
            guard_trips, ingest_drops, rebase_fallbacks,
            server_dropouts, tracker_resyncs, faults_injected,
            cal_ladder_levels_used, cal_ladder_base_decisions,
            cal_ladder_fallbacks, ladder_steps, supervisor_resumes,
            wheel_occ_hwm, wheel_reslots, pallas_fallbacks]
    return jnp.stack([jnp.asarray(r, dtype=jnp.int64) for r in rows])


def metrics_mesh_reduce(vec: jnp.ndarray, axis_name: str) -> jnp.ndarray:
    """In-graph mesh merge of per-shard metric vectors: counter rows
    ``psum``, high-water-mark rows ``pmax`` -- the collective form of
    :func:`metrics_combine` (associative + commutative, so the mesh
    order cannot matter).  Call inside ``shard_map`` on the per-shard
    vector; the result is replicated across the axis, so cluster
    totals need no host-side gather (the ROADMAP healthy-path item)."""
    from jax import lax

    return jnp.where(_HWM_MASK, lax.pmax(vec, axis_name),
                     lax.psum(vec, axis_name))


def metrics_combine_axis(mat: jnp.ndarray) -> jnp.ndarray:
    """Reduce a stacked [S, NUM_METRICS] matrix along its leading axis
    with the vector's merge semantics (counters add, hwm max) -- the
    local-shard half of a mesh merge (vmapped servers within a shard
    reduce here, then :func:`metrics_mesh_reduce` crosses the mesh)."""
    return jnp.where(_HWM_MASK, jnp.max(mat, axis=0),
                     jnp.sum(mat, axis=0))


def admission_clamp(counts: jnp.ndarray, headroom: jnp.ndarray):
    """Clamp per-client arrival counts to ring headroom (the AtLimit
    Reject/EAGAIN analog the sustained bench applies before
    ``ingest_superwave``), returning ``(clamped, dropped_total)`` so
    the drop count feeds MET_INGEST_DROPS instead of vanishing."""
    clamped = jnp.minimum(counts, headroom)
    dropped = jnp.sum((counts - clamped).astype(jnp.int64))
    return clamped, dropped


def metrics_combine_np(acc, *vecs):
    """Host-side mirror of :func:`metrics_combine` over numpy vectors
    (bench.py merges fetched per-chain vectors with this).  Derives the
    max rows from the same ``_HWM_ROWS`` as the device mask, so the two
    merges cannot silently diverge."""
    import numpy as np

    acc = np.asarray(acc, dtype=np.int64)
    hwm = np.isin(np.arange(acc.size), _HWM_ROWS)
    for v in vecs:
        v = np.asarray(v)
        acc = np.where(hwm, np.maximum(acc, v), acc + v)
    return acc


def metrics_dict(vec) -> dict:
    """Name the rows of a fetched metrics vector (host side)."""
    import numpy as np

    v = np.asarray(vec).reshape(-1)
    return {name: int(v[i]) for i, name in enumerate(METRIC_NAMES)}


FAULT_FAMILIES = (
    ("dmclock_fault_server_dropouts_total", MET_SERVER_DROPOUTS,
     "up -> down shard transitions injected by the fault plan "
     "(docs/ROBUSTNESS.md 'Degraded-mode mesh')"),
    ("dmclock_fault_tracker_resyncs_total", MET_TRACKER_RESYNCS,
     "down -> up restarts that re-synced the shard's held counter "
     "view / tracker marks from the monotone global counters"),
    ("dmclock_fault_injected_total", MET_FAULTS_INJECTED,
     "total injected fault events (dropouts, restarts, delayed "
     "counters, duplicated completions, nonzero clock skew)"),
)


def publish_shard_faults(registry, per_shard, labels=None) -> None:
    """Register the ``shard``-labelled ``dmclock_fault_*`` families
    from a ``[S, NUM_METRICS]`` per-shard metric matrix (or a
    ``[S, 3]`` dropouts/resyncs/injected matrix, e.g. the
    ``robust.faults.plan_shard_events`` oracle stacked column-wise):
    one gauge per family per shard plus a ``shard="all"`` total --
    the degraded-mode mesh's scrape surface next to the
    ``dmclock_slo_window_*`` / ``dmclock_shard_pressure_*``
    precedents."""
    import numpy as np

    mat = np.asarray(per_shard, dtype=np.int64)
    assert mat.ndim == 2, mat.shape
    cols = {name: (row if mat.shape[1] == NUM_METRICS else j)
            for j, (name, row, _help) in enumerate(FAULT_FAMILIES)}
    for name, _row, help_text in FAULT_FAMILIES:
        col = cols[name]
        for s in range(mat.shape[0]):
            registry.gauge(
                name, help_text,
                labels={**(labels or {}), "shard": str(s)}
            ).set(int(mat[s, col]))
        registry.gauge(
            name, help_text,
            labels={**(labels or {}), "shard": "all"}
        ).set(int(mat[:, col].sum()))


def publish(registry, vec, prefix: str = "dmclock_engine",
            labels=None) -> None:
    """Fold a fetched metrics vector into a host ``MetricsRegistry``:
    counter rows become counters (the vector is itself cumulative per
    run, so the registry gauge semantics fit better -- publish uses
    gauges for everything, with the hwm documented as a max)."""
    for name, value in metrics_dict(vec).items():
        registry.gauge(f"{prefix}_{name}",
                       "on-device scheduling metric (see "
                       "docs/OBSERVABILITY.md)",
                       labels=labels).set(value)
