"""Chrome trace-event / Perfetto export for the span tracer.

Any run that collected spans (``obs.spans.SpanTracer``) exports a
timeline loadable in ``chrome://tracing`` / https://ui.perfetto.dev:

    from dmclock_tpu.obs import spans, trace_export
    tr = spans.SpanTracer()
    ...
    trace_export.export_chrome_trace(tr, "trace.json")

The format is the Trace Event Format's JSON object form
(``{"traceEvents": [...]}``), one complete ("X") event per span --
``ts``/``dur`` in microseconds (floats, so ns resolution survives),
``pid`` fixed at 0, ``tid`` the recording thread.  An X event IS a
matched begin/end pair by construction; :func:`validate_chrome_trace`
checks the stream the way a B/E validator would -- per-tid events must
nest (every span fully contains its children; partial overlap is a
corrupted begin/end pairing) with monotone, non-negative timestamps
and categories from the fixed taxonomy -- and returns per-category
SELF-time sums so CI can gate "category sums ~= wall time"
(``scripts/ci.sh`` tracing smoke).

:func:`load_rows` reads either format (Chrome JSON or the tracer's
JSONL) back into span rows for ``scripts/trace_report.py``.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Union

from .spans import CATEGORIES, SpanTracer, load_jsonl

# 1 ns expressed in the export's microsecond unit: float-division slop
# for the nesting sweeps
_EPS_US = 1e-3


def chrome_events(rows: List[dict], pid: int = 0) -> List[dict]:
    """Span rows -> trace-event dicts (complete "X" events), sorted by
    (ts, -dur) so a parent precedes the children it contains at the
    same timestamp (the orientation viewers and the validator rely
    on)."""
    events = []
    for r in rows:
        ev = {"name": r["name"], "cat": r["cat"], "ph": "X",
              "ts": r["ts"] / 1000.0, "dur": r["dur"] / 1000.0,
              "pid": pid, "tid": r.get("tid", 0)}
        args = r.get("args")
        if args:
            ev["args"] = dict(args)
        events.append(ev)
    events.sort(key=lambda e: (e["ts"], -e["dur"]))
    return events


def export_chrome_trace(src: Union[SpanTracer, List[dict]],
                        path: str, *,
                        metadata: Optional[dict] = None) -> int:
    """Write ``src`` (a tracer, or raw span rows) as a Chrome
    trace-event JSON file; returns the event count."""
    rows = src.rows() if isinstance(src, SpanTracer) else list(src)
    events = chrome_events(rows)
    obj = {"traceEvents": events, "displayTimeUnit": "ns"}
    if isinstance(src, SpanTracer):
        obj["otherData"] = {"spans_recorded": src.spans_recorded,
                            "spans_dropped": src.spans_dropped}
    if metadata:
        obj.setdefault("otherData", {}).update(metadata)
    with open(path, "w") as fh:
        json.dump(obj, fh, separators=(",", ":"))
    return len(events)


def rows_self_times(rows: List[dict]) -> List[int]:
    """Per-row SELF time (ns).  Tracer JSONL rows carry a recorded
    ``self`` field -- trusted verbatim; otherwise (Chrome exports
    loaded back) a per-tid nesting sweep over (ts, -dur)-ordered rows
    subtracts each span's direct children from it.  This is THE
    canonical sweep -- ``validate_chrome_trace`` and
    ``scripts/trace_report.py`` both use it, so the CI self-time gate
    and the attribution table can never disagree on the same file."""
    if rows and all("self" in r for r in rows):
        return [int(r["self"]) for r in rows]
    order = sorted(range(len(rows)),
                   key=lambda i: (rows[i]["ts"],
                                  -rows[i].get("dur", 0)))
    selfs = [0] * len(rows)
    stacks: Dict[int, list] = {}    # tid -> [[end_ns, row_idx]]
    for i in order:
        r = rows[i]
        ts, dur = r["ts"], r.get("dur", 0)
        st = stacks.setdefault(r.get("tid", 0), [])
        # 1ns slop: a us-float round trip can land a child's end 1ns
        # past its parent's
        while st and ts >= st[-1][0] - 1:
            st.pop()
        if st:
            selfs[st[-1][1]] -= dur
        selfs[i] += dur
        st.append([ts + dur, i])
    return [max(s, 0) for s in selfs]


def _self_time_sweep(events: List[dict]) -> Dict[str, float]:
    """Per-category SELF time (ns) over X events: the canonical
    :func:`rows_self_times` sweep applied to the events' ns-domain
    rows."""
    rows = [{"cat": ev.get("cat", "?"),
             "ts": int(round(ev["ts"] * 1000.0)),
             "dur": int(round(ev.get("dur", 0) * 1000.0)),
             "tid": ev.get("tid", 0)} for ev in events]
    out: Dict[str, float] = {}
    for r, self_ns in zip(rows, rows_self_times(rows)):
        out[r["cat"]] = out.get(r["cat"], 0.0) + self_ns
    return out


def validate_chrome_trace(path: str) -> dict:
    """Validate an exported trace file; raises ``ValueError`` on the
    first violation.  Checks:

    - the envelope is ``{"traceEvents": [...]}`` of "X" events;
    - ``ts``/``dur`` non-negative numbers, ``ts`` monotone
      non-decreasing in file order (the exporter sorts);
    - every ``cat`` is in the fixed taxonomy (``spans.CATEGORIES``);
    - per ``tid``, events NEST: each event either starts at/after the
      enclosing event's end (a sibling) or ends within it (a child) --
      partial overlap means a corrupted begin/end pairing.

    Returns ``{"events", "tids", "cat_self_ns", "cat_count",
    "span_ns"}``: ``cat_self_ns`` sums SELF time per category
    (children subtracted from parents), ``span_ns`` their total -- the
    quantity CI compares against wall time.
    """
    with open(path) as fh:
        obj = json.load(fh)
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        raise ValueError(f"{path}: no traceEvents envelope")
    events = obj["traceEvents"]
    cat_count: Dict[str, int] = {}
    stacks: Dict[int, list] = {}    # tid -> [end_us, ...] open spans
    prev_ts = None
    for i, ev in enumerate(events):
        if ev.get("ph") != "X":
            raise ValueError(f"{path}: event {i}: phase "
                             f"{ev.get('ph')!r} != 'X'")
        ts, dur = ev.get("ts"), ev.get("dur")
        if not isinstance(ts, (int, float)) or ts < 0 or \
                not isinstance(dur, (int, float)) or dur < 0:
            raise ValueError(f"{path}: event {i}: bad ts/dur "
                             f"({ts!r}, {dur!r})")
        if prev_ts is not None and ts < prev_ts:
            raise ValueError(f"{path}: event {i}: ts regressed "
                             f"({ts} < {prev_ts})")
        prev_ts = ts
        cat = ev.get("cat")
        if cat not in CATEGORIES:
            raise ValueError(f"{path}: event {i}: category {cat!r} "
                             f"not in the taxonomy {CATEGORIES}")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            raise ValueError(f"{path}: event {i}: missing name")
        tid = ev.get("tid", 0)
        st = stacks.setdefault(tid, [])
        end = ts + dur
        while st and ts >= st[-1] - _EPS_US:
            st.pop()
        if st and end > st[-1] + _EPS_US:
            raise ValueError(
                f"{path}: event {i} ({ev['name']!r} tid {tid}): ends "
                f"at {end} past its enclosing span's end {st[-1]} "
                "-- begin/end pairs are not properly nested")
        st.append(end)
        cat_count[cat] = cat_count.get(cat, 0) + 1
    cat_self = _self_time_sweep(events)
    return {"events": len(events), "tids": len(stacks),
            "cat_self_ns": cat_self, "cat_count": cat_count,
            "span_ns": sum(cat_self.values())}


def load_rows(path: str) -> List[dict]:
    """Load span rows from either export format: the tracer's JSONL
    (rows pass through) or a Chrome trace-event JSON file (X events
    map back to rows; ``self`` is recomputed by the consumer's nesting
    sweep when absent)."""
    # format sniffing: a Chrome export is ONE json object; the
    # tracer's JSONL is one object per line (both start with "{", so
    # only a whole-file parse distinguishes them)
    try:
        with open(path) as fh:
            obj = json.load(fh)
    except json.JSONDecodeError:
        return load_jsonl(path)
    if isinstance(obj, dict) and "traceEvents" in obj:
        rows = []
        for ev in obj["traceEvents"]:
            if ev.get("ph") != "X":
                continue
            rows.append({"name": ev.get("name", "?"),
                         "cat": ev.get("cat", "?"),
                         "ts": int(round(ev["ts"] * 1000.0)),
                         "dur": int(round(ev.get("dur", 0) * 1000.0)),
                         "tid": ev.get("tid", 0),
                         "args": ev.get("args")})
        return rows
    if isinstance(obj, dict) and "name" in obj and "ts" in obj:
        return [obj]    # a single-row JSONL stream parses whole
    raise ValueError(f"{path}: neither a traceEvents envelope nor "
                     "span JSONL")
