"""Steady-state tracing watchdog.

A background sampler over a :class:`~.spans.SpanTracer` (and
optionally the metrics registry) that turns the span stream into
structured health warnings while a run is still going -- the
"something is wrong NOW" complement to the post-hoc
``scripts/trace_report.py`` attribution:

- **launch-cadence stall**: no ``dispatch``-category span has
  completed for longer than ``stall_after_s`` while at least one had
  before -- the serve loop stopped launching (a wedged tunnel, a host
  deadlock), the failure mode PR-3's guarded retries paper over one
  launch at a time but cannot see across launches.  Streaming-aware:
  an OPEN dispatch/device_compute span (a fused stream chunk
  legitimately runs for seconds per launch) or a recent
  drain-category heartbeat (the stream loop emits one per drain
  point) counts as a live cadence, never a stall;
- **dispatch share**: over the last sampling window, host ``dispatch``
  self-time exceeds ``dispatch_share_warn`` of the
  dispatch+device_compute total -- the run is paying more to LAUNCH
  work than to DO it, the exact pathology the ROADMAP's streaming
  serve loop exists to kill (PROFILE.md findings 17-18);
- **retrace storm** (when a ``compile_plane`` is attached): one jit
  cache entry re-traced >= ``retrace_storm_k`` times inside
  ``retrace_window_s`` -- an argument signature is churning (a shape
  bug, an un-padded dynamic dimension) and every churn pays a full
  XLA compile, the >15-minute-on-Mosaic failure mode PROFILE.md
  records.  First compiles are NOT retraces, so the PR-8 AOT
  pre-compile loop (one fresh entry per chunk length) can never fire
  this.

Warnings are structured: one JSON line on ``log`` (default stderr,
prefixed ``# watchdog:``), a bump of the
``dmclock_watchdog_warnings_total`` registry counter when a registry
is attached, and an entry in :attr:`Watchdog.warnings` for tests.
``poll_once()`` is the deterministic seam -- the thread just calls it
on an interval.  Telemetry must never kill the run it observes: the
sampler catches and counts its own failures.
"""

from __future__ import annotations

import json
import sys
import threading
import time as _walltime
from typing import Callable, List, Optional

from .spans import SpanTracer


def _stderr_log(line: str) -> None:
    print(line, file=sys.stderr)


class Watchdog:
    """Background steady-state monitor over a span tracer.

    ``interval_s`` is the sampling period; ``stall_after_s`` the
    silence (no completed dispatch span) that counts as a stalled
    launch cadence; ``dispatch_share_warn`` the windowed
    dispatch/(dispatch+device_compute) self-time share past which the
    run is dispatch-tax-bound.  ``min_window_ns`` gates the share
    check on enough observed time to be meaningful.  ``clock_ns`` is
    injectable for deterministic tests (must be the same clock domain
    as the tracer's)."""

    def __init__(self, tracer: SpanTracer, *,
                 interval_s: float = 1.0,
                 stall_after_s: float = 5.0,
                 dispatch_share_warn: float = 0.6,
                 min_window_ns: int = 1_000_000,
                 in_flight_max_s: Optional[float] = None,
                 registry=None,
                 compile_plane=None,
                 retrace_storm_k: int = 4,
                 retrace_window_s: float = 120.0,
                 log: Callable[[str], None] = _stderr_log,
                 clock_ns: Callable[[], int] =
                 _walltime.perf_counter_ns):
        self.tracer = tracer
        self.interval_s = float(interval_s)
        self.stall_after_ns = int(stall_after_s * 1e9)
        # how long an OPEN dispatch/device_compute span may suppress
        # the stall warning: a fused stream chunk legitimately runs
        # far past stall_after_s inside one launch, but a launch the
        # runtime wedged INSIDE must still surface -- default 10x the
        # stall threshold
        self.in_flight_max_ns = int(
            (10.0 * stall_after_s if in_flight_max_s is None
             else in_flight_max_s) * 1e9)
        self.dispatch_share_warn = float(dispatch_share_warn)
        self.min_window_ns = int(min_window_ns)
        self._log = log
        self._clock = clock_ns
        self.warnings: List[dict] = []
        self.polls = 0
        self.poll_errors = 0
        self._counter = None
        if registry is not None:
            self._counter = registry.counter(
                "dmclock_watchdog_warnings_total",
                "structured warnings emitted by the tracing watchdog "
                "(launch-cadence stalls, dispatch-share breaches; "
                "docs/OBSERVABILITY.md)")
        self._prev_count = tracer.category_counts()
        # the share check keeps its OWN baseline, advanced only when a
        # window is actually judged: skipped (mid-chain) windows must
        # accumulate their dispatch time into the next judged window,
        # not vanish from it
        self._share_prev = tracer.category_totals()
        self._share_prev_count = dict(self._prev_count)
        # retrace-storm check (obs.compile_plane): the plane's event
        # clock must share this watchdog's clock domain (both default
        # perf_counter_ns; tests inject one fake into both)
        self._cplane = compile_plane
        self.retrace_storm_k = int(retrace_storm_k)
        self.retrace_window_ns = int(retrace_window_s * 1e9)
        self._stall_warned = False
        self._share_warned = False
        self._retrace_warned = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the deterministic seam ---------------------------------------
    def poll_once(self, now_ns: Optional[int] = None) -> List[dict]:
        """One sampling pass; returns the warnings it emitted."""
        self.polls += 1
        if now_ns is None:
            now_ns = self._clock()
        out: List[dict] = []
        totals = self.tracer.category_totals()
        counts = self.tracer.category_counts()

        # launch-cadence stall: dispatch spans have happened before,
        # none since, and the last one ended too long ago.  Two
        # streaming-mode exceptions (docs/OBSERVABILITY.md), or every
        # healthy fused stream chunk would fire this:
        #  - in-flight awareness: an OPEN dispatch/device_compute span
        #    means a launch is dispatched or the host is blocked on
        #    its result -- a chunk running for seconds is work, not
        #    silence.  BOUNDED by in_flight_max_ns: a launch the
        #    runtime wedged INSIDE (the original failure mode this
        #    check exists for) stops suppressing once the open span
        #    outlives the wedge threshold;
        #  - stream heartbeat: the serve loop emits a drain-category
        #    instant at every drain point, so recent drain activity
        #    proves the loop is alive between launches.
        last = self.tracer.last_end_ns("dispatch")
        open_t0 = self.tracer.oldest_open_ns()
        launch_in_flight = open_t0 is not None and \
            now_ns - open_t0 <= self.in_flight_max_ns
        hb = self.tracer.last_end_ns("drain")
        hb_recent = hb is not None and \
            now_ns - hb <= self.stall_after_ns
        if last is not None and \
                not launch_in_flight and not hb_recent and \
                counts.get("dispatch", 0) == \
                self._prev_count.get("dispatch", 0) and \
                now_ns - last > self.stall_after_ns:
            if not self._stall_warned:    # once per stall episode
                out.append({"kind": "launch_stall",
                            "silent_ms": (now_ns - last) / 1e6,
                            "launches": counts.get("dispatch", 0)})
            self._stall_warned = True
        else:
            self._stall_warned = False

        # dispatch share over the window since the LAST JUDGED poll.
        # A window is judged only when it saw at least one device span
        # COMPLETE: the chained-launch wiring records device time once
        # per chain (the digest sync), so a poll landing mid-chain
        # sees dispatch-only deltas that measure span placement, not
        # the dispatch tax.  Skipped windows keep the share baseline
        # where it was -- their dispatch time accumulates into the
        # next judged window instead of vanishing from it (otherwise
        # a long chain's mid-chain dispatch would never be judged at
        # all).  Once per breach episode, like the stall.
        d_disp = totals.get("dispatch", 0) - \
            self._share_prev.get("dispatch", 0)
        d_dev = totals.get("device_compute", 0) - \
            self._share_prev.get("device_compute", 0)
        dev_seen = counts.get("device_compute", 0) > \
            self._share_prev_count.get("device_compute", 0)
        window = d_disp + d_dev
        if dev_seen and window >= self.min_window_ns:
            share = d_disp / window
            if share > self.dispatch_share_warn:
                if not self._share_warned:
                    out.append({"kind": "dispatch_share",
                                "share": round(share, 4),
                                "dispatch_ms": d_disp / 1e6,
                                "device_ms": d_dev / 1e6,
                                "threshold": self.dispatch_share_warn})
                self._share_warned = True
            else:
                self._share_warned = False
            self._share_prev = totals
            self._share_prev_count = counts
        self._prev_count = counts

        # retrace storm: the SAME cache entry re-traced >= K times in
        # the window.  First compiles never count (a retrace is the
        # 2nd+ signature on one entry -- obs.compile_plane), so the
        # legitimate first-compile of each chunk length in an AOT
        # pre-compile loop is invisible here by construction.  Once
        # per episode; a window with no entry at storm level re-arms.
        if self._cplane is not None and self.retrace_storm_k > 0:
            lo = now_ns - self.retrace_window_ns
            per: dict = {}
            for t_ns, entry in self._cplane.retrace_events():
                if t_ns >= lo:
                    per[entry] = per.get(entry, 0) + 1
            worst = max(per.items(), key=lambda kv: kv[1],
                        default=(None, 0))
            if worst[1] >= self.retrace_storm_k:
                if not self._retrace_warned:
                    out.append({"kind": "retrace_storm",
                                "entry": worst[0],
                                "retraces": worst[1],
                                "window_s":
                                    self.retrace_window_ns / 1e9})
                self._retrace_warned = True
            else:
                self._retrace_warned = False

        for w in out:
            self.warnings.append(w)
            if self._counter is not None:
                self._counter.inc()
            self._log("# watchdog: " +
                      json.dumps(w, separators=(",", ":")))
        return out

    def external_warning(self, obj: dict) -> None:
        """Route a structured warning from another monitor (the SLO
        burn-rate evaluator, ``obs.alerts``) through this watchdog's
        stream: appended to :attr:`warnings`, counted in the registry
        counter, logged in the same one-JSON-line format -- one
        warning stream (and one counter) for the whole run."""
        self.warnings.append(obj)
        if self._counter is not None:
            self._counter.inc()
        self._log("# watchdog: " +
                  json.dumps(obj, separators=(",", ":")))

    # -- the thread ----------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:   # never kill the run being observed
                self.poll_errors += 1

    def start(self) -> "Watchdog":
        assert self._thread is None, "watchdog already started"
        self._thread = threading.Thread(target=self._run,
                                        name="span-watchdog",
                                        daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()
        return False
